#!/usr/bin/env python
"""Headline benchmark: Criteo-style sparse logistic regression (async FTRL).

Mirrors the reference's flagship workload (example/linear criteo
online_l1lr: async SGD + FTRL + L1, BASELINE.json) on TPU: the fused SPMD
step in apps/linear/async_sgd.py — pull(gather+psum) → Xw/grad segment-sums
→ push(scatter+psum) → FTRL dense update — driven by a host prefetch thread
doing localization, so device steps and host prep overlap exactly like the
reference's MinibatchReader producer/consumer.

Record protocol (last JSON line wins): the final measurement (or
failure) record is the LAST JSON line on stdout. Non-smoke runs print a
provisional failure record before the device probe and refresh it on
every retry, so a driver that kills the bench at ANY point still parses
a record ({"metric", "value", "unit", "vs_baseline", ...}); a completed
run's final record supersedes the provisionals.

Baseline: BASELINE.json publishes no number for the 8-node ZMQ cluster; we
use 500k examples/sec as the documented estimate for 8-node async FTRL on
Criteo-scale data (order of magnitude from the parameter-server OSDI'14
evaluation: ~65k examples/sec/node with sparse LR at ~100 nnz/example).

MEASUREMENT NOTE (round 2): round 1 reported 5.25M examples/sec. That
number was an artifact — on the tunneled TPU backend,
``jax.block_until_ready`` on shard_map outputs returns before the device
work completes, so the "flushed" windows were measuring dispatch rate, not
throughput. Every flush now fetches a state scalar to the host (a real
device->host dependency). The honest single-chip rate is ~0.6M ex/s at a
2^22 table (~0.5M at 2^26), achieved with scan-fused supersteps
(ELLBitsSuperBatch: T minibatches per launch) — per-launch round trips on
the tunnel cost more than the device math, so batching launches is the
main lever.
"""

import argparse
import contextlib
import json
import os
import sys
import threading
import time
import traceback

import numpy as np

from parameter_server_tpu.telemetry import spans as telemetry_spans
from parameter_server_tpu.utils.concurrent import iter_on_thread

REF_8NODE_EXAMPLES_PER_SEC = 500_000.0


class Watchdog:
    """Emit the best-so-far record instead of hanging when the tunnel
    wedges MID-run.

    ``probe_device`` catches a relay that is already down, but a wedge
    can also strike between two device operations of a healthy run
    (observed 2026-07-31: bench blocked in a device wait for 40 minutes
    — 23s of CPU time over a 22-minute stretch — until the outer
    timeout killed it, losing every number the run had already
    measured). Every phase of the bench calls :meth:`beat`; a daemon
    thread watches the heartbeat and, after ``stall_s`` of silence,
    prints ONE JSON line built from the staged partial fields and
    hard-exits (``os._exit`` — the main thread is unkillably blocked in
    a C-level wait).

    Exit semantics: if the headline phase already landed (``value`` is
    staged), the record is a valid measurement with the wedge disclosed
    in ``wedged`` — exit 0 so the driver keeps it. Otherwise it is a
    failure record (value 0, ``error``) — exit 2.

    The final record goes through :meth:`finish`, which prints under
    the same lock the firing path holds — a run that recovers from a
    near-stall and completes cannot race the watchdog into printing
    two records (whichever takes the lock first wins; the loser either
    sees ``_done`` or the process is already gone)."""

    def __init__(self, metric: str, stall_s: float = 300.0,
                 poll_s: float = 2.0):
        self.metric = metric
        self.stall_s = stall_s
        self.poll_s = poll_s
        self._last = time.monotonic()
        self._phase = "init"
        self._partial: dict = {}
        self._ops: dict = {}  # in-flight bounded ops: token -> deadline
        self._done = False
        # RLock, not Lock: the SIGTERM handler runs ON the main thread,
        # which spends the whole run inside beat()/grace()/finish()
        # critical sections — a plain Lock would deadlock the handler
        # against the very frame it interrupted and the driver's
        # follow-up SIGKILL would reproduce the r4 silent death
        self._lock = threading.RLock()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def beat(self, phase: str | None = None, **fields) -> None:
        """Mark liveness; optionally advance the phase label and stage
        already-measured fields for the partial record."""
        with self._lock:
            self._last = time.monotonic()
            if phase is not None:
                self._phase = phase
            self._partial.update(fields)

    def grace(self, seconds: float) -> None:
        """Push the idle clock ``seconds`` into the future: one legit
        long device operation (a deep-T superbatch upload through a
        throttled tunnel can exceed stall_s on its own) must not read
        as a wedge. The next beat() snaps the clock back to normal.
        Monotone: a later, smaller grace never SHRINKS a pending one —
        a compile grace stacked after a large transfer grace must not
        cut the transfer's budget short."""
        with self._lock:
            self._last = max(
                self._last, time.monotonic() + max(0.0, seconds)
            )

    @contextlib.contextmanager
    def operation(self, budget_s: float):
        """Mark a bounded-duration blocking operation (one tunnel
        transfer) in flight. Unlike :meth:`grace`, this cannot be
        cancelled by a beat from ANOTHER thread: the uploader thread's
        transfer grace used to die the instant the main thread beat on
        an unrelated item, re-arming the false-wedge kill mid-transfer.
        The watchdog holds fire while any operation's budget is
        unexpired; exit removes the marker and refreshes the idle
        clock (without shrinking any LARGER grace() deadline the main
        thread armed — monotone, like grace itself), so an op leaves
        no insensitivity window of its own behind."""
        tok = object()
        with self._lock:
            self._ops[tok] = time.monotonic() + max(0.0, budget_s)
        try:
            yield
        finally:
            with self._lock:
                self._ops.pop(tok, None)
                # max, not assignment: an op exiting on a side thread
                # must never SHRINK a grace() deadline the main thread
                # armed (grace's documented monotone invariant)
                self._last = max(self._last, time.monotonic())

    def cancel(self) -> None:
        with self._lock:
            self._done = True

    def finish(self, rec: dict) -> None:
        """Atomically retire the watchdog and print the final record."""
        with self._lock:
            self._done = True
            print(json.dumps(rec), flush=True)

    def _partial_record(self, wedge: str) -> "tuple[dict, int]":
        """Build the best record the staged fields support (caller holds
        the lock). Returns (record, exit_code): a valid measurement with
        the wedge disclosed when the headline already landed, else a
        failure record carrying whatever diagnostics were staged."""
        partial = dict(self._partial)
        if partial.get("value"):
            rec = {"metric": self.metric, "unit": "examples/sec"}
            rec.update(partial)
            rec["wedged"] = wedge
            rec["note"] = (
                partial.get("note", "")
                + " | RUN CUT SHORT by a mid-run tunnel wedge: "
                "fields after the wedge point are absent; the "
                "headline device-only phase completed before it"
            ).lstrip(" |")
            return rec, 0
        rec = {"metric": self.metric, "unit": "examples/sec"}
        rec.update(partial)
        rec["value"] = 0
        rec["vs_baseline"] = 0
        rec["error"] = f"accelerator wedged: {wedge}"
        return rec, 2

    def sigterm_flush(self, reason: str) -> None:
        """Flush the best-so-far record on a supervisor SIGTERM.

        The round-4 driver killed the bench mid-run and got NOTHING
        (`BENCH_r04.json`: rc 124, parsed null) because the old SIGTERM
        path exited without touching the staged fields. This emits the
        same record the stall branch would — a valid measurement when
        the headline already landed, a failure record otherwise — and
        retires the watchdog so no second record can follow. Always
        emits through :func:`_raw_emit` (the signal-handler path): the
        interrupted main thread may be INSIDE a buffered stdout write,
        where a reentrant print() raises RuntimeError and loses the
        record."""
        with self._lock:
            if self._done:  # a final record already printed; stay silent
                return
            self._done = True
            rec, _ = self._partial_record(reason)
        _raw_emit(rec)

    def abort(self, reason: str) -> int:
        """Synchronous twin of the stall branch, for mid-run EXCEPTIONS:
        a dying backend raises (e.g. ``UNAVAILABLE: TPU backend
        setup/compile error`` from a device_put — observed 2026-07-31
        01:30, which turned 26 minutes of measurement into a bare
        traceback with no JSON). Emits the best-so-far record and
        returns the exit code instead of letting the traceback eat the
        evidence."""
        with self._lock:
            if self._done:  # a final record already printed
                return 0
            self._done = True
            rec, code = self._partial_record(
                f"exception in phase '{self._phase}': {reason}"
            )
            print(json.dumps(rec), flush=True)
            return code

    def _run(self) -> None:
        while True:
            time.sleep(self.poll_s)
            with self._lock:
                if self._done:
                    return
                now = time.monotonic()
                idle = now - self._last
                if idle <= self.stall_s:
                    continue
                if any(dl > now for dl in self._ops.values()):
                    continue  # a bounded op is still inside its budget
                # fire — still under the lock, so finish() cannot
                # interleave a second record
                rec, code = self._partial_record(
                    f"no progress for {idle:.0f}s in phase "
                    f"'{self._phase}' (tunnel wedged mid-run?)"
                )
                print(json.dumps(rec), flush=True)
                os._exit(code)


_WATCHDOG: "Watchdog | None" = None

# Provisional failure record staged by main() during the probe phase:
# printed (flushed) before the first probe attempt, refreshed on every
# retry, flushed one last time by the SIGTERM handler. Cleared the
# moment a better source of truth exists (the watchdog, or a final
# record). Exists because the round-4 driver killed the bench mid-probe
# and parsed NOTHING (`BENCH_r04.json`: rc 124, parsed null).
_PENDING_REC: "dict | None" = None


def _raw_emit(rec: dict) -> None:
    """Signal-safe record write: os.write to fd 1 bypasses Python's
    buffered writer — print() from a signal handler raises
    'RuntimeError: reentrant call' when the signal interrupted a
    main-thread print mid-flush, which would lose the record at the
    exact moment it matters. The leading newline isolates the record
    from any half-written line the interrupt left behind (the driver
    parses the last PARSEABLE line).

    Also used for every PROBE-PHASE record (provisional + retries):
    routing those through the buffered writer would let a SIGTERM land
    between a print's buffer-write and its flush, in which case the
    interpreter's exit flush appends the stale buffered line AFTER the
    handler's raw record — breaking last-line-wins. os.write leaves
    nothing buffered."""
    with contextlib.suppress(Exception):
        os.write(1, b"\n" + json.dumps(rec).encode() + b"\n")


def _sigterm_handler(signum, frame):
    """Flush the best available record BEFORE dying. Mid-run the
    watchdog owns the staged fields (best-so-far measurement); during
    the probe phase the provisional failure record is all we have.
    Then exit via SystemExit — not os._exit — so the tunnel client's
    atexit/GC gets a shot at releasing its device claim (a hard-killed
    client has wedged the relay for hours, see probe_device)."""
    global _PENDING_REC
    if _WATCHDOG is not None:
        _WATCHDOG.sigterm_flush("supervisor SIGTERM (driver timeout?)")
    elif _PENDING_REC is not None:
        rec = dict(_PENDING_REC)
        rec["error"] = (
            str(rec.get("error", ""))
            + " | bench SIGTERM'd by its supervisor mid-probe"
        )
        _raw_emit(rec)
        _PENDING_REC = None
    with contextlib.suppress(Exception):
        # a SIGTERM during the device-lock WAIT dies before the
        # clear_priority finally is even entered, leaving a marker
        # that idles the watcher for the full 30-min freshness window
        # (observed 2026-08-01 23:05-23:16: two killed test benches
        # cost the watcher ~11 idle minutes). We are dying — our
        # device need ends here, whatever phase we were in.
        from parameter_server_tpu.utils.device_lock import clear_priority

        clear_priority()
    sys.exit(143)


def _beat(phase: str | None = None, **fields) -> None:
    if _WATCHDOG is not None:
        _WATCHDOG.beat(phase, **fields)


def _grace_for_compile(seconds: float = 600.0) -> None:
    """Extend the watchdog's patience across a COMPILING launch: the
    fused scan program's remote compile through the tunnel has no
    transfer size to derive a budget from, and a legitimately slow
    compile window (slow link + cold cache) must not read as a wedge —
    the 2026-08-01 08:41 run died in 'warmup' at the 300s default
    while the tunnel was merely crawling. One-time: the next beat()
    snaps the clock back."""
    if _WATCHDOG is not None:
        _WATCHDOG.grace(seconds)


def _grace_for_transfer(nbytes: int) -> None:
    """Extend the watchdog's patience before a large host->device move:
    allow a 1 MB/s worst-case tunnel (observed throttled floor) plus
    the normal stall budget. Single-thread call sites only — from a
    side thread use :func:`_transfer_op`, which a concurrent beat
    cannot cancel."""
    if _WATCHDOG is not None:
        _WATCHDOG.grace(nbytes / 1e6)


@contextlib.contextmanager
def _transfer_op(nbytes: int):
    """Watchdog-aware transfer scope for SIDE threads: budget sized to
    the 1 MB/s worst-case tunnel floor, uncancellable by concurrent
    beats (Watchdog.operation)."""
    if _WATCHDOG is None:
        yield
        return
    with _WATCHDOG.operation(nbytes / 1e6):
        yield


def ensure_trace_sink() -> "str | None":
    """Install a JSONL span sink for the run's timeline when none is
    installed yet (telemetry/timeline.py); returns the trace path, or
    None when an externally installed non-file sink owns the stream.

    MUST run after Postoffice.reset() (reset closes the sink). The
    timeline is the raw material of the record's ``attribution``
    section — every stage span (prep/stack/upload on their threads,
    executor step phases) lands here, flow-correlated per superbatch.

    The flight recorder (telemetry/blackbox.py) arms as a tee over the
    sink, so every bench run also carries the always-on black box —
    an alert firing or a wedged wait mid-run auto-captures a
    diagnostic bundle with the last ring of spans in it (the record's
    ``blackbox.bundles_captured`` discloses how many).
    """
    import tempfile

    from parameter_server_tpu.telemetry import blackbox

    sink = telemetry_spans.get_sink()
    if sink is not None:
        blackbox.arm()
        return getattr(sink, "path", None)
    path = os.path.join(
        tempfile.gettempdir(), f"ps_bench_trace_{os.getpid()}.jsonl"
    )
    with contextlib.suppress(OSError):
        os.remove(path)  # fresh capture: never mix runs
    telemetry_spans.install_sink(telemetry_spans.JsonlSink(path))
    blackbox.arm()
    return path


def attach_attribution(
    rec_or_headline: dict,
    trace_path: "str | None",
    e2e_window: "tuple[float, float] | None" = None,
) -> None:
    """Embed the critical-path attribution section derived from the
    run's span timeline (telemetry/attribution.py) — the trace-derived
    replacement for the hand-computed upload-bound arithmetic of the
    BENCH_r05 era. Never breaks a record.

    Top-level shares/binding come from the SERIALIZED breakdown-phase
    spans (phase="breakdown": the same launches the legacy
    ``breakdown_*`` fields price, so the two must agree — the
    ``agrees_with_hand_breakdown`` cross-check says so explicitly);
    ``e2e`` holds the pipelined phase's resource utilizations and
    queue-wait over its wall window, where overlap and queueing are
    visible. ``trace_jsonl`` points at the raw timeline; export it with
    ``python -m parameter_server_tpu.benchmarks trace`` or
    ``telemetry.timeline.export_chrome_trace`` and open in Perfetto.
    """
    if trace_path is None:
        return
    try:
        from parameter_server_tpu.telemetry import attribution as attr_mod
        from parameter_server_tpu.telemetry import timeline as timeline_mod

        events = timeline_mod.load_events(trace_path)
        # a --profile run's device track rides the same JSONL (emitted
        # by phase_breakdown): stitch it to the submitting executor.step
        # spans so the breakdown summary below grows the per-kernel
        # device_compute_breakdown and flows cross the host/chip line
        dev_events = [e for e in events if attr_mod.is_device_event(e)]
        if dev_events:
            events = timeline_mod.merge_device_track(
                [e for e in events if not attr_mod.is_device_event(e)],
                dev_events,
            )
        section: dict = {"trace_jsonl": trace_path}
        breakdown = [e for e in events if e.get("phase") == "breakdown"]
        if breakdown:
            summary = attr_mod.summarize(breakdown)
            section.update(summary)
        if e2e_window is not None:
            section["e2e"] = attr_mod.summarize(events, window=e2e_window)
        fracs = rec_or_headline.get("breakdown_fracs")
        shares = section.get("shares")
        if fracs and shares:
            # the hand math's categories map 1:1 onto attribution's
            pairs = (
                ("host_prep", "host_prep"), ("upload", "upload"),
                ("device", "device_compute"),
            )
            section["agrees_with_hand_breakdown"] = all(
                abs(fracs.get(hand, 0.0) - shares.get(cat, 0.0)) <= 0.10
                for hand, cat in pairs
            )
        rec_or_headline["attribution"] = section
    except Exception as e:
        rec_or_headline["attribution_error"] = (
            f"{type(e).__name__}: {str(e)[:200]}"
        )


def telemetry_snapshot() -> "dict | None":
    """Best-effort host-side telemetry snapshot for the bench record.

    The process registry (parameter_server_tpu.telemetry) collects
    executor step phases, Van byte counters and push/pull latency during
    the run; persisting the snapshot next to summarize_trace's device
    phases gives every BENCH_*.json host-side counters alongside the
    device trace. Never allowed to break a record."""
    try:
        from parameter_server_tpu.telemetry import default_registry

        snap = default_registry().snapshot()
        return snap or None
    except Exception:
        return None


def kv_dataplane_microbench(mesh, smoke: bool) -> dict:
    """Zero-copy data-plane A/B at the kernel level, on the live backend:
    the seed's copying push (fresh [P, k] table output per call) vs the
    donated in-place push, and the fused single-dispatch push→pull vs
    push-then-pull as two launches (ops/kv_ops). Ticks the PR's
    telemetry counters (ps_kvops_donated_pushes_total, fused-dispatch
    histogram) so they land in the record's telemetry snapshot; the
    returned dict embeds under ``kv_dataplane``. Cheap by construction
    (seconds), guarded at the call site. Deliberately kernel-level
    (raw kv_ops on this worker's live mesh, watchdog-beaten, no
    Postoffice reset); the STORE-level twin — executor round trips
    included — lives in benchmarks/components.py kv_vector_perf; keep
    their A/B shapes in sync when either changes."""
    import jax
    import jax.numpy as jnp

    from parameter_server_tpu.ops import kv_ops
    from parameter_server_tpu.parallel import mesh as meshlib

    n_keys = 1 << (10 if smoke else 16)
    k = 4
    p = 2 * n_keys
    rng = np.random.default_rng(0)
    slots = jax.device_put(rng.integers(0, p, n_keys).astype(np.int32))
    vals = jax.device_put(rng.normal(size=(n_keys, k)).astype(np.float32))
    table0 = jax.device_put(
        jnp.zeros((p, k), jnp.float32), meshlib.table_sharding(mesh)
    )
    jax.block_until_ready(table0)
    reps = 3 if smoke else 20

    def timed(fn):
        fn()  # warm (compile)
        _beat()
        t0 = time.perf_counter()
        for _ in range(reps):
            fn()
        return (time.perf_counter() - t0) / reps

    tbl_nd = jax.block_until_ready(jnp.array(table0, copy=True))

    def push_nodonate():
        jax.block_until_ready(
            kv_ops.push(tbl_nd, slots, vals, mesh=mesh, batch_sharded=False)
        )

    box = [jnp.array(table0, copy=True)]

    def push_donated():
        box[0] = kv_ops.push_donated(
            box[0], slots, vals, mesh=mesh, batch_sharded=False
        )
        jax.block_until_ready(box[0])

    def push_then_pull():
        t = kv_ops.push(tbl_nd, slots, vals, mesh=mesh, batch_sharded=False)
        jax.block_until_ready(
            kv_ops.pull(t, slots, mesh=mesh, batch_sharded=False)
        )

    def push_pull_fused():
        box[0], out = kv_ops.push_pull_donated(
            box[0], slots, vals, mesh=mesh, batch_sharded=False
        )
        jax.block_until_ready(out)

    sec_nd = timed(push_nodonate)
    sec_d = timed(push_donated)
    sec_seq = timed(push_then_pull)
    sec_f = timed(push_pull_fused)
    return {
        "n_keys": n_keys,
        "table_shape": [p, k],
        "push_nodonate_steps_per_sec": round(1.0 / sec_nd, 1),
        "push_donated_steps_per_sec": round(1.0 / sec_d, 1),
        "push_donated_speedup": round(sec_nd / sec_d, 3),
        "push_then_pull_rt_per_sec": round(1.0 / sec_seq, 1),
        "push_pull_fused_rt_per_sec": round(1.0 / sec_f, 1),
        "push_pull_fused_speedup": round(sec_seq / sec_f, 3),
        # structural: the [P, k] output buffer the donated path never
        # materializes — bytes NOT moved per push, by construction
        "table_copy_bytes_avoided_per_push": int(p * k * 4),
    }


def attach_kv_dataplane(rec_or_headline: dict, mesh, smoke: bool) -> None:
    """Guarded embed of the kv data-plane A/B (never breaks a record)."""
    try:
        rec_or_headline["kv_dataplane"] = kv_dataplane_microbench(mesh, smoke)
    except Exception as e:
        rec_or_headline["kv_dataplane_error"] = (
            f"{type(e).__name__}: {str(e)[:200]}"
        )


def attach_host_ingest(rec_or_headline: dict, smoke: bool) -> None:
    """Guarded embed of the serial-vs-pipelined host-ingest A/B
    (benchmarks/components.host_ingest_ab — the PR3 ingest plane) so
    every bench record carries the ingest win under ``host_ingest``,
    next to the ps_ingest_* counters in the telemetry snapshot. Host
    CPU only (no device), seconds of wall time; never breaks a
    record."""
    try:
        from parameter_server_tpu.benchmarks.components import host_ingest_ab

        # parked: the A/B's pipelined arm drives a real IngestPipeline
        # whose per-batch span emits would tax only that arm of the
        # paired ratio and flood the trace with off-window ingest flows
        with telemetry_spans.parked_sink():
            rec_or_headline["host_ingest"] = host_ingest_ab(smoke)
    except Exception as e:
        rec_or_headline["host_ingest_error"] = (
            f"{type(e).__name__}: {str(e)[:200]}"
        )


def attach_wire(rec_or_headline: dict, smoke: bool) -> None:
    """Guarded embed of the compact-wire encoded-vs-raw A/B
    (benchmarks/components.wire_ab) under ``wire`` in every bench
    record: bytes/example per encoding, the multi-pass amortized bytes
    through the upload key cache, exact-mode parity, and encode cost.
    Host CPU only. When the record already carries a measured link rate
    (``host_to_device_mb_s``), also derives the link-bound ceiling each
    encoding implies — the e2e rate that bytes/example CAPS at that
    link speed (ceiling = MB/s × 1e6 ÷ bytes/example), which is the
    motivation for the whole wire: the recorded baseline sat at
    34-69k examples/sec because 107.4 B/example met a 5-27 MB/s link."""
    try:
        from parameter_server_tpu.benchmarks.components import wire_ab

        # parked: encode_exact emits a wire.encode span per call, which
        # would tax the encode arm of the paired encode-over-prep ratio
        # and land off-window noise in the trace
        with telemetry_spans.parked_sink():
            out = wire_ab(smoke)
        mb_s = rec_or_headline.get("host_to_device_mb_s")
        if mb_s:
            per_enc = {}
            for table in ("bytes_per_example", "amortized_bytes_per_example"):
                for k, v in out[table].items():
                    if v:
                        per_enc[k] = round(mb_s * 1e6 / v, 1)
            out["link_bound_examples_per_sec_at_measured_mb_s"] = per_enc
        rec_or_headline["wire"] = out
    except Exception as e:
        rec_or_headline["wire_error"] = f"{type(e).__name__}: {str(e)[:200]}"


def attach_ftrl(rec_or_headline: dict, smoke: bool) -> None:
    """Guarded embed of the sparse-FTRL update A/B
    (benchmarks/components.ftrl_sparse_ab — XLA rows path vs the fused
    Pallas gather→update→scatter kernel, ops/ftrl_sparse.py) under
    ``ftrl_sparse`` in every bench record: per-ministep ms for both
    arms, median-of-paired-reps speedup, the disclosed bytes model with
    ``hbm_gb_s``/``frac_of_peak``, and the on-chip 10x
    ``ftrl_hbm_frac_of_peak`` target the next device capture is judged
    against. On this CPU host the fused arm falls back to the rows path
    (``fused_is_fallback``) — the record is shape truth, not a speedup
    headline; never breaks a record."""
    try:
        from parameter_server_tpu.benchmarks.components import ftrl_sparse_ab

        rec_or_headline["ftrl_sparse"] = ftrl_sparse_ab(smoke)
    except Exception as e:
        rec_or_headline["ftrl_sparse_error"] = (
            f"{type(e).__name__}: {str(e)[:200]}"
        )


def attach_serve(rec_or_headline: dict, smoke: bool) -> None:
    """Guarded embed of the request-path serving bench
    (benchmarks/components.serve_ab — the serving plane, doc/SERVING.md)
    under ``serve`` in every bench record: open-loop p50/p99/p99.9 at
    two offered-load points (below capacity + 3x overload), the
    admission on/off p99 A/B (bounded tail vs queue collapse), the
    coalescer's submits-per-request merge factor, and the speculative
    LM decode lane. Rates self-calibrate to the host, so the record is
    meaningful on CPU and on chip alike; never breaks a record."""
    try:
        from parameter_server_tpu.benchmarks.components import serve_ab

        # parked: the SLO bench fires thousands of requests/s and three
        # timeline events per request (submit/execute/reply + per-line
        # fsync in the JSONL sink) would load the very tail latencies
        # being measured — and flood the trace with off-window noise
        with telemetry_spans.parked_sink():
            rec_or_headline["serve"] = serve_ab(smoke)
    except Exception as e:
        rec_or_headline["serve_error"] = f"{type(e).__name__}: {str(e)[:200]}"


def attach_decode_batching(rec_or_headline: dict, smoke: bool) -> None:
    """Guarded embed of the continuous-batching decode A/B
    (benchmarks/components.decode_batching_ab — serving/batcher.py,
    doc/SERVING.md "Continuous batching") under ``decode_batching`` in
    every bench record: batched-vs-sequential tokens/s at each slot
    count under join/leave churn (median of paired reps, token parity
    asserted in-bench), the ``speedup_at_8`` headline with its
    ``onchip_target``, and the device-resident replica serving a table
    over the host budget with zero degrades; never breaks a record."""
    try:
        from parameter_server_tpu.benchmarks.components import (
            decode_batching_ab,
        )

        # parked: the A/B times back-to-back decode lanes at
        # millisecond granularity — per-line fsync in the span sink
        # would load the very dispatch overhead being measured
        with telemetry_spans.parked_sink():
            rec_or_headline["decode_batching"] = decode_batching_ab(smoke)
    except Exception as e:
        rec_or_headline["decode_batching_error"] = (
            f"{type(e).__name__}: {str(e)[:200]}"
        )


def attach_recovery(rec_or_headline: dict, smoke: bool) -> None:
    """Guarded embed of the kill-one-shard recovery drill
    (benchmarks/components.recovery_drill — the chaos plane,
    doc/ROBUSTNESS.md) under ``recovery`` in every bench record:
    detection/recovery/MTTR wall times for an injected shard death
    under concurrent train+serve load, replayed-update count, the
    degraded/shed/failed serve accounting, the post-recovery
    bit-parity verdict, and the disarmed-overhead paired check. This
    section is DRILL METADATA, not a throughput metric —
    script/bench_diff.py's sentinel explicitly excludes it from
    banding (METADATA_SECTIONS); never breaks a record."""
    try:
        from parameter_server_tpu.benchmarks.components import recovery_drill

        # parked: the drill fires its own serve traffic and three span
        # events per request would load the dead-window latencies —
        # and flood the bench trace with off-window chaos flows
        with telemetry_spans.parked_sink():
            rec_or_headline["recovery"] = recovery_drill(smoke)
    except Exception as e:
        rec_or_headline["recovery_error"] = (
            f"{type(e).__name__}: {str(e)[:200]}"
        )


def attach_blackbox(rec_or_headline: dict, smoke: bool) -> None:
    """Guarded embed of the flight-recorder evidence under ``blackbox``
    in every bench record: the steady-state overhead paired-median A/B
    (armed ring vs no sink on the same span-instrumented work stream —
    the PR 9 disarmed-overhead pattern; the honest claim is the ratio
    straddling this host's noise floor, with the tight-loop absolute
    ns/event that a capacity flap cannot fake), the run's ring
    occupancy, and how many diagnostic bundles the trigger plane
    captured during the run. Run METADATA, not a throughput metric —
    script/bench_diff.py excludes this section from banding
    (METADATA_SECTIONS); never breaks a record."""
    try:
        from parameter_server_tpu.telemetry import blackbox

        # parked: the A/B measures its own private tee — the run's
        # JSONL sink must neither pay for nor record the probe spans
        with telemetry_spans.parked_sink():
            overhead = blackbox.overhead_ab(reps=3 if smoke else 5)
        section: dict = {"overhead": overhead}
        rec = blackbox.installed_recorder()
        if rec is not None:
            d = rec.dump()
            section["ring"] = {
                "node": d["node"],
                "events": len(d["events"]),
                "events_total": d["events_total"],
                "dropped": d["dropped"],
                "capacity": d["capacity"],
                "metrics_samples": len(d["metrics_samples"]),
            }
        section["bundles_captured"] = len(blackbox.bundles())
        rec_or_headline["blackbox"] = section
    except Exception as e:
        rec_or_headline["blackbox_error"] = (
            f"{type(e).__name__}: {str(e)[:200]}"
        )


def attach_history(rec_or_headline: dict, smoke: bool) -> None:
    """Guarded embed of the history plane under ``history`` in every
    bench record (telemetry/history.py, doc/OBSERVABILITY.md "History
    plane"): the fold-hook overhead paired-median A/B (the identical
    metric-churn workload with the ring cascade installed vs absent —
    the honest claim is the ratio straddling this host's noise floor,
    with the tight-loop per-fold cost over the full instrument catalog
    that a capacity flap cannot fake) plus the run's own installed
    store's retention/occupancy snapshot when one is live. Run
    METADATA, not a throughput metric — script/bench_diff.py excludes
    this section from banding (METADATA_SECTIONS); never breaks a
    record."""
    try:
        from parameter_server_tpu.benchmarks.components import history_ab
        from parameter_server_tpu.telemetry import history as history_mod

        # parked: the A/B churns its own private registries — the
        # run's JSONL sink must neither pay for nor record the probe
        with telemetry_spans.parked_sink():
            section: dict = {"overhead": history_ab(smoke)}
        store = history_mod.installed_store()
        if store is not None:
            store.fold(force=True)
            section["store"] = store.snapshot()
        rec_or_headline["history"] = section
    except Exception as e:
        rec_or_headline["history_error"] = (
            f"{type(e).__name__}: {str(e)[:200]}"
        )


def attach_history_drift(rec: dict, samples) -> None:
    """Live steady-state drift verdict over the run's OWN timed
    (elapsed_s, examples/sec) windows, folded into the record's
    ``history`` section after the e2e phase: the tail of the run judged
    against its post-warmup baseline — same host, same run, so no
    cross-run capacity drift can alibi or fake the verdict
    (telemetry/history.drift_check; the online twin of bench_diff's
    cross-run sentinel). Never breaks a record."""
    try:
        from parameter_server_tpu.telemetry.history import drift_check

        rec.setdefault("history", {})["live_drift"] = drift_check(
            list(samples)
        )
    except Exception as e:
        rec["history_drift_error"] = f"{type(e).__name__}: {str(e)[:200]}"


def attach_learning(rec_or_headline: dict, smoke: bool) -> None:
    """Guarded embed of the learning truth plane under ``learning`` in
    every bench record (benchmarks/components.learning_truth +
    telemetry/learning.py): the RUN's own planes first (realized
    staleness of the submissions made so far, with the in-record
    observed<=τ verdict, key-heat shard shares, convergence tail),
    then the self-contained probe — a bounded-delay training run with
    the staleness histogram, sketch-vs-exact heat parity, shard
    balance, loss/grad-norm trajectory, and the seeded LR-blow-up
    divergence drill (shipped ``loss_divergence`` rule to firing, with
    a diagnostic bundle attached). Convergence trajectories are run
    METADATA, never banded as perf — script/bench_diff.py excludes
    this section (METADATA_SECTIONS); never breaks a record. Harvest
    order matters: the probe builds its own mini-cluster
    (Postoffice.reset), which drops the run's registered planes — so
    the run view is read FIRST."""
    try:
        from parameter_server_tpu.benchmarks.components import (
            learning_truth,
        )
        from parameter_server_tpu.telemetry import learning as learning_mod

        section: dict = {}
        run = learning_mod.snapshot_all()
        if run:
            section["run"] = run
        with telemetry_spans.parked_sink():
            section["probe"] = learning_truth(smoke)
        rec_or_headline["learning"] = section
    except Exception as e:
        rec_or_headline["learning_error"] = (
            f"{type(e).__name__}: {str(e)[:200]}"
        )


def attach_consistency(rec_or_headline: dict, smoke: bool) -> None:
    """Guarded embed of the self-driving consistency A/B under
    ``consistency`` (benchmarks/components.consistency_ab): the three
    τ arms (fixed 0 / fixed max / adaptive) with the
    throughput-vs-final-loss frontier verdict, the KKT significance
    filter off/on with its suppression accounting reconciled against
    ``ps_push_keys_total``, and the seeded divergence drill through
    the controller's backoff + rollback reaction. Paired-rep medians
    with the emulated pull-RTT disclosed in-record — run METADATA,
    never banded (script/bench_diff.py METADATA_SECTIONS); never
    breaks a record. Builds its own mini-cluster (Postoffice reset),
    so it must run among the component sections, after the run planes
    are harvested."""
    try:
        from parameter_server_tpu.benchmarks.components import (
            consistency_ab,
        )

        with telemetry_spans.parked_sink():
            rec_or_headline["consistency"] = consistency_ab(smoke)
    except Exception as e:
        rec_or_headline["consistency_error"] = (
            f"{type(e).__name__}: {str(e)[:200]}"
        )


def attach_learning_run(rec: dict, worker) -> None:
    """Fold the MAIN run worker's own learning plane into the record's
    ``learning`` section AFTER the timed windows — the plane object
    rides the worker (module registration does not survive the
    component sections' Postoffice resets), and harvesting here means
    the staleness/trajectory view covers the e2e phase itself. Carries
    the in-record bounded-delay verdict for the run's OWN submissions
    (``run_staleness_within_bound``: observed max <= the configured
    max_delay); the probe asserts its own. Never breaks a record."""
    try:
        plane = getattr(worker, "_learning", None)
        if plane is None:
            return
        section = rec.setdefault("learning", {})
        snap = plane.snapshot()
        section.setdefault("run", {})[plane.worker] = snap
        ok = all(
            s["staleness"]["within_bound"]
            for s in section["run"].values()
        )
        section["run_staleness_within_bound"] = ok
        if not ok:
            section["run_staleness_breaches"] = [
                w for w, s in section["run"].items()
                if not s["staleness"]["within_bound"]
            ]
    except Exception as e:
        rec["learning_run_error"] = f"{type(e).__name__}: {str(e)[:200]}"


def attach_device(rec_or_headline: dict, smoke: bool) -> None:
    """Guarded embed of the device truth plane
    (parameter_server_tpu/telemetry/device.py) under ``device`` in
    every bench record: per-jit cost-analysis FLOPs/bytes and buffer
    sizes from the compiled-function inventory (the kv_ops entry
    points + every step builder wrap into it), recompile counts with
    the post-warmup total (the warmup mark is set right before the
    timed e2e phase, so a healthy record reads zero), the runtime
    donation-fallback count (zero on the data plane — a nonzero means
    XLA silently turned an in-place table update into a copy), HBM /
    live-buffer high-water, and the roofline cross-checks: the
    ``ftrl_sparse`` hand bytes model vs the XLA-derived bytes (ratio
    disclosed in the A/B section itself) and the flash fwd hand-FLOPs
    vs cost-analysis probe. Capture-hardware facts, not trajectory
    points — script/bench_diff.py excludes this section from banding
    (METADATA_SECTIONS); never breaks a record."""
    try:
        from parameter_server_tpu.telemetry import device as device_mod

        section = device_mod.snapshot()
        rooflines: dict = {}
        fs = rec_or_headline.get("ftrl_sparse")
        if isinstance(fs, dict) and isinstance(
            fs.get("bytes_model_cross_check"), dict
        ):
            rooflines["ftrl_sparse"] = dict(fs["bytes_model_cross_check"])
        try:
            from parameter_server_tpu.benchmarks.components import (
                flash_cost_crosscheck,
            )

            rooflines["flash"] = flash_cost_crosscheck(smoke)
        except Exception as e:
            rooflines["flash_error"] = f"{type(e).__name__}: {str(e)[:200]}"
        if rooflines:
            section["rooflines"] = rooflines
        rec_or_headline["device"] = section
    except Exception as e:
        rec_or_headline["device_error"] = (
            f"{type(e).__name__}: {str(e)[:200]}"
        )


_EXPOSITION = None  # live ExpositionServer while --expose-port is up


def _maybe_expose(po, args) -> None:
    """--expose-port: stand the cluster metrics plane up over this run
    (telemetry/exposition.py) — /metrics serves the node-labeled
    aggregate, /healthz the heartbeat+recovery verdict, and the default
    SLO alert rules evaluate live against the run's registry. Port 0
    binds ephemeral; the chosen port is printed to stderr so a scraper
    (or a human with curl) can attach mid-run."""
    global _EXPOSITION
    if getattr(args, "expose_port", None) is None:
        return
    from parameter_server_tpu.telemetry.exposition import expose_cluster

    _EXPOSITION = expose_cluster(
        po, port=args.expose_port, metrics_interval=1.0
    )
    print(f"bench: metrics exposed at {_EXPOSITION.url}/metrics "
          f"(/healthz, /debug/snapshot)", file=sys.stderr)


def _expose_summary(rec: dict) -> None:
    """One self-scrape before teardown: the record carries proof the
    endpoint served node-labeled series while the run was live."""
    if _EXPOSITION is None:
        return
    try:
        import urllib.request

        txt = urllib.request.urlopen(
            f"{_EXPOSITION.url}/metrics", timeout=10
        ).read().decode()
        nodes = sorted({
            line.split('node="', 1)[1].split('"', 1)[0]
            for line in txt.splitlines()
            if line.startswith("ps_cluster_node_up{")
        })
        ok, health = _EXPOSITION.aux.health()
        firing = health.get("alerts_firing", [])
        rec["expose"] = {
            "url": _EXPOSITION.url,
            "nodes": nodes,
            "series_lines": sum(
                1 for l in txt.splitlines() if l and not l.startswith("#")
            ),
            "healthz_ok": ok,
            "alerts_firing": firing,
        }
    except Exception as e:
        rec["expose"] = {"error": f"{type(e).__name__}: {str(e)[:200]}"}


def _close_exposition() -> None:
    global _EXPOSITION
    if _EXPOSITION is not None:
        from parameter_server_tpu.telemetry.exposition import close_cluster

        close_cluster(_EXPOSITION)
        _EXPOSITION = None


def _finish(rec: dict) -> None:
    """Print the final record through the watchdog's lock (single-record
    guarantee); plain print when no watchdog is armed (library use)."""
    _expose_summary(rec)
    _close_exposition()
    if "telemetry" not in rec:
        snap = telemetry_snapshot()
        if snap is not None:
            rec["telemetry"] = snap
    if _WATCHDOG is not None:
        _WATCHDOG.finish(rec)
    else:
        print(json.dumps(rec))


# ---------------------------------------------------------------------------
# --real mode: stream actual criteo-format TEXT from disk through the C++
# parser → localization → fused device step, parsing INSIDE the timed
# pipeline, with a logloss-parity check against a NumPy FTRL oracle
# (BASELINE.json north star: "Criteo-1TB ... at logloss parity").
# ---------------------------------------------------------------------------

def probe_device(timeout_s: float = 150.0, attempts: int = 4,
                 retry_wait_s: float = 60.0, on_retry=None):
    """Fail fast when the accelerator is unreachable: returns None when
    healthy, else a human-readable diagnosis (timeout vs crash, with the
    child's stderr tail).

    On the tunneled backend a wedged relay makes ``jax.devices()`` block
    FOREVER (observed: a killed client left the claim/grant protocol
    stuck for hours). Probe device init in a child process so the bench
    can emit an explicit error JSON line instead of hanging the driver.
    Wedges are often TRANSIENT (the relay times out the dead claim), so
    a failed probe is retried ``attempts`` times with a pause — a bench
    run should not be zeroed by a hiccup that clears in two minutes.

    BUDGET (round 5): 4 attempts x 150s probe + 3 x 60s wait = 13 min,
    deliberately UNDER the round driver's observed ~30-min patience.
    Round 4's 10x~300s budget (~50 min) out-waited the wedge but also
    out-waited the driver, which SIGTERM'd the bench mid-retry and got
    no JSON at all (`BENCH_r04.json`: rc 124, parsed null). Riding out
    a long wedge is the background WATCHER's job (script/onchip.py);
    the bench's job is to always leave a record behind.

    ``on_retry(attempt, diagnosis)`` is called before each wait so the
    caller can refresh its provisional failure record on stdout — the
    record the driver keeps if it kills us mid-probe.
    Each retry refreshes the priority marker so the watcher stays away
    for the whole probing window."""
    import subprocess

    from parameter_server_tpu.utils.device_lock import request_priority

    # child source + graceful-timeout runner shared with the
    # watcher's probe (utils/subproc): device init on a daemon
    # thread so the child stays SIGTERM-deliverable while the
    # wedge blocks the init C call
    from parameter_server_tpu.utils.subproc import (
        PROBE_CHILD_SRC,
        run_graceful,
    )

    diagnosis = "probe never ran"
    for attempt in range(max(1, attempts)):
        if attempt:
            print(
                f"# device probe attempt {attempt} failed ({diagnosis}); "
                f"retrying in {retry_wait_s:.0f}s",
                file=sys.stderr,
            )
            if on_retry is not None:
                with contextlib.suppress(Exception):
                    on_retry(attempt, diagnosis)
            time.sleep(retry_wait_s)
        request_priority("bench-probe")
        try:
            rc, perr, _ = run_graceful(
                [sys.executable, "-c", PROBE_CHILD_SRC], timeout_s
            )
            if rc == 0:
                return None
            tail = perr.decode(errors="replace").strip().splitlines()[-3:]
            # a crash (vs a hang) is deterministic — fail fast, no retry
            return "device init failed: " + " | ".join(tail)
        except subprocess.TimeoutExpired:
            diagnosis = (
                "device init did not complete within the probe timeout "
                "(tunnel relay down?)"
            )
    return diagnosis


def build_device_error(
    diagnosis: str, metric: str = "criteo_sparse_lr_examples_per_sec"
) -> dict:
    """Build (don't print) the explicit failure record — with a POINTER
    to the most recent on-chip capture (BENCH_ONCHIP.md, written by
    script/onchip.py when the tunnel was last up). The cached fields
    are diagnostics for the reader, clearly labeled; ``value`` stays 0
    because no live measurement happened in THIS run.

    Split from :func:`emit_device_error` so main() can stage this as
    the PROVISIONAL record: printed before the first probe attempt and
    refreshed on every retry, it is what the driver parses if it kills
    the bench mid-probe (the exact r4 failure, `BENCH_r04.json`
    rc 124 / parsed null)."""
    rec = {
        "metric": metric,
        "value": 0,
        "unit": "examples/sec",
        "vs_baseline": 0,
        "error": f"accelerator unreachable: {diagnosis}",
    }
    try:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_ONCHIP.md")
        stamp = None
        by_metric = {}
        with open(path) as f:
            for ln in f:
                if ln.startswith("## "):  # any heading resets attribution
                    stamp = (
                        ln[3:].split(" — ")[0].strip()
                        if (" — bench " in ln or " — bench_real " in ln)
                        else None
                    )
                elif stamp and ln.startswith('{"metric"'):
                    try:
                        cached = json.loads(ln)
                    except ValueError:
                        continue  # half-written line: keep earlier finds
                    if cached.get("value") and "metric" in cached:
                        line = {k: cached[k] for k in
                                ("metric", "value", "unit", "vs_baseline")
                                if k in cached}
                        line["captured_at"] = stamp
                        by_metric[cached["metric"]] = line  # latest wins
                        stamp = None  # first VALID capture per section
                    # zero-value lines (the provisional/failure records
                    # every non-smoke run now prints first) must NOT
                    # consume the stamp — a real capture may follow
                    # them inside the same log section
        line = by_metric.get(  # prefer this run's headline metric
            metric
        ) or next(iter(by_metric.values()), None)
        if line is not None:
            rec["last_onchip_capture"] = line
            rec["note"] = (
                "last_onchip_capture is a PRIOR run's on-chip result "
                "(see BENCH_ONCHIP.md), shown for diagnosis only"
            )
    except (OSError, ValueError, KeyError):
        # a half-written log line must never break the failure record
        pass
    try:
        # capture-pipeline status: the reader of a zero record should
        # see that the evidence watcher is armed and what it will run
        # the moment the tunnel returns. NOTHING here may break the
        # failure record — every stage is guarded, and liveness is
        # recorded even if the task-state read fails.
        import subprocess

        rec["watcher"] = {
            "running": subprocess.run(
                ["pgrep", "-f", "onchip.py --watch"], capture_output=True
            ).returncode == 0
        }
        try:
            state_path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "doc", "onchip_state.json",
            )
            with open(state_path) as f:
                st = json.load(f)
            done = sorted(
                n for n, r in st.items()
                if isinstance(r, dict) and r.get("status") == "ok"
            )
            # the task list the watcher ACTUALLY runs (single source
            # of truth — a hardcoded copy here would silently drift)
            sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
            import importlib.util as _ilu

            spec = _ilu.spec_from_file_location(
                "_onchip_tasks",
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "script", "onchip.py"),
            )
            onchip_mod = _ilu.module_from_spec(spec)
            spec.loader.exec_module(onchip_mod)
            all_tasks = [t[0] for t in onchip_mod.TASKS]
            rec["watcher"]["tasks_done"] = done
            rec["watcher"]["tasks_pending"] = [
                t for t in all_tasks if t not in done
            ]
        except Exception:
            pass  # liveness already recorded
        try:
            # tunnel-outage account from the watch log: when the relay
            # was last reachable and how long the current wedge has
            # held — a zero record should tell the whole outage story
            # on its own. Path reused from the loaded onchip module
            # when available so a moved WATCH_LOG can't silently
            # orphan this scraper.
            try:
                wl = onchip_mod.WATCH_LOG
            except NameError:
                wl = os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "doc", "onchip_watch.log",
                )
            last_up = first_wedge_after_up = None
            with open(wl) as f:
                for ln in f:
                    if "probe: device UP" in ln:
                        last_up = ln[1:20]
                        first_wedge_after_up = None
                    elif (
                        first_wedge_after_up is None
                        # POSITIVE match on the wedge diagnosis
                        # (onchip.probe's exact wording): busy/yield
                        # lines are a healthy held device, and a
                        # CRASHED diag's free-text stderr tail must
                        # not be misread either way
                        and "probe:" in ln
                        and ("tunnel wedge" in ln or "init hang >" in ln)
                    ):
                        first_wedge_after_up = ln[1:20]
            if last_up:
                rec["watcher"]["tunnel_last_up"] = last_up
            if first_wedge_after_up:
                rec["watcher"]["tunnel_wedged_since"] = (
                    first_wedge_after_up
                )
        except Exception:
            pass
    except Exception:
        pass
    return rec


# HBM peak bandwidth by device_kind (public spec sheets) for utilization
# reporting; kinds not listed just omit the fraction. ONE table, shared
# with the component benches (ftrl_sparse_ab/ftrl_chain frac-of-peak).
from parameter_server_tpu.benchmarks import HBM_PEAK_GB_S  # noqa: E402


def tree_host_nbytes(prepped) -> int:
    """Wire footprint of one prepped (host-side) batch: what actually
    crosses host->device per launch."""
    import jax

    return int(
        sum(
            getattr(leaf, "nbytes", 0)
            for leaf in jax.tree.leaves(prepped)
        )
    )


def timed_upload(prepped):
    """(staged_tree, seconds): device_put timed until every array has
    really LANDED — fetch one element of EVERY leaf, because device_put
    is async and block_until_ready under-waits on the tunneled
    backend."""
    import jax

    t0 = time.perf_counter()
    dev = jax.device_put(prepped)
    for leaf in jax.tree.leaves(dev):
        np.asarray(leaf.ravel()[:1])
    return dev, time.perf_counter() - t0


class UploadPipeline:
    """Dedicated uploader thread: stacks T host-prepped minibatches
    into a superbatch and stages it to the device, overlapping the
    tunnel's host→device wire time with the producer's parse/localize
    work and the main thread's device waits.

    Why a thread helps even on a ONE-core host (this image): the wire
    transfer is socket I/O inside the PJRT client (GIL-free) and the
    C++ parser releases the GIL too, so parse CPU time and upload wire
    time genuinely overlap; only the numpy stack/localize slices
    compete for the core. Before this, ``jax.device_put`` ran serially
    on the main thread between submits — with the link at ~10-25 MB/s
    the wire time dominated the loop and the breakdown fields read
    upload-bound (r4 verdict item 5: push e2e to the link ceiling).

    Iterating yields ``(device_superbatch, num_examples, nbytes)`` —
    ``nbytes`` is what actually CROSSED the link: with an upload key
    cache attached (``cache=``, learner/wire.UploadCache — the encoded-
    wire default since the wire flip), leaves the device already holds
    ship ~signature bytes, and the yielded count subtracts the cache's
    saved bytes so the e2e bytes/example and the link-ceiling
    reconciliation stay honest. A trailing partial group (< T
    minibatches) is skipped — it would compile a second scan shape
    inside the timed window — and reported via ``skipped_examples``
    after iteration ends. Exceptions on the uploader thread propagate
    to the consuming iterator (the plumbing is :func:`iter_on_thread`;
    this class only adds the staging generator and the accounting).
    The cache is stateful and single-owner by contract — it lives on
    THIS pipeline's one staging thread, satisfying the PR-3
    stateless-or-feeder rule (UploadCache asserts it)."""

    def __init__(self, parts_iter, T: int, queue_depth: int = 2, cache=None):
        self.skipped_examples = 0
        # staging-leg codec accounting (wire_compress): frames decoded
        # on THIS pipeline's one staging thread, right before the
        # stack+device_put — raw vs framed bytes disclosed so the
        # record can quote the staging leg net of compression while
        # ``nbytes`` (what reconcile_link_ceiling divides) stays the
        # REALIZED tunnel traffic
        self.staged_raw_bytes = 0
        self.staged_compressed_bytes = 0
        self._cache = cache
        self._it = iter_on_thread(
            self._stage(parts_iter, T), maxsize=queue_depth
        )

    def _stage(self, parts_iter, T: int):
        # runs on iter_on_thread's daemon thread
        import jax

        from parameter_server_tpu.learner.wire import CompressedBatch

        parts = []
        for item in parts_iter:
            if isinstance(item, CompressedBatch):
                self.staged_raw_bytes += item.raw_nbytes
                self.staged_compressed_bytes += item.wire_nbytes
                from parameter_server_tpu.learner.wire import (
                    decompress_batch,
                )

                item = decompress_batch(item)
            parts.append(item)
            if len(parts) < T:
                continue
            # one timeline flow per superbatch: stack → upload here,
            # then the consumer submits the trainer step under the same
            # id (the 4th yielded element), so the executor.step span
            # joins the flow and the critical path reads end to end
            fid = telemetry_spans.maybe_new_flow()
            with telemetry_spans.flow_scope(fid):
                with telemetry_spans.span("bench.stack", phase="e2e"):
                    sb = stack_supersteps(parts, T)
                parts = []
                nb = tree_host_nbytes(sb)
                _beat()
                # device_put returns promptly with transfer in flight;
                # the bounded queue keeps at most a couple of
                # superbatches staged ahead so host memory stays flat.
                # _transfer_op (not _grace_for_transfer): the main
                # thread beats per consumed item, and a beat would
                # cancel a plain grace mid-transfer
                with _transfer_op(nb):
                    with telemetry_spans.span(
                        "bench.upload", phase="e2e", nbytes=nb
                    ):
                        if self._cache is not None:
                            saved0 = self._cache.saved_bytes
                            staged = self._cache(sb)
                            nb = max(
                                0, nb - (self._cache.saved_bytes - saved0)
                            )
                        else:
                            staged = jax.device_put(sb)
            yield staged, int(sb.num_examples), nb, fid
        self.skipped_examples = sum(int(p.num_examples) for p in parts)

    def __iter__(self):
        return self._it


def measure_upload_mb_s(prepped, reps: int = 3) -> float:
    """Median host->device bandwidth moving a real prepped batch (the
    tunnel drifts several x over minutes; see README)."""
    nbytes = tree_host_nbytes(prepped)
    obs = []
    for _ in range(reps):
        _beat()
        _grace_for_transfer(nbytes)
        _, sec = timed_upload(prepped)
        obs.append(nbytes / sec / 1e6)
    return float(np.median(obs))


def roofline_fields(prepped, num_slots: int, device_step_sec: float,
                    examples_per_launch: int, t_mb: int | None = None) -> dict:
    """The measurement VERDICT r2 asked for: separate the machine from
    the link. Reports wire bytes/example, observed upload MB/s, and the
    FTRL table pass's HBM traffic vs chip peak (the dense update reads+
    writes z and sqrt_n: 16 B/slot/minibatch — the dominant HBM term at
    2^26+; gathers add O(nnz) on top, ignored here as <2%).

    ``prepped`` should be a SMALL representative batch (one minibatch):
    bytes/example, MB/s and the link-bound ceiling are all size-invariant
    ratios, and probing bandwidth with a deep-T superbatch would move GBs
    through a possibly-throttled tunnel for no informational gain. Pass
    ``t_mb`` explicitly when ``device_step_sec`` covers more minibatches
    than ``prepped`` holds (the sweep's winning launch depth)."""
    import jax

    dev = jax.devices()[0]
    wire_bytes = tree_host_nbytes(prepped)
    up_mb_s = measure_upload_mb_s(prepped)
    # device_step_sec covers t_mb minibatches (one launch); the table is
    # touched once per MINIBATCH by the scan superstep
    if t_mb is None:
        t_mb = getattr(prepped, "steps", 1)
    hbm_bytes = 16.0 * num_slots * t_mb
    hbm_gb_s = hbm_bytes / device_step_sec / 1e9 if device_step_sec else None
    out = {
        "bytes_per_example": round(wire_bytes / max(1, examples_per_launch), 1),
        "host_to_device_mb_s": round(up_mb_s, 1),
        "device_kind": dev.device_kind,
        "ftrl_hbm_gb_s": round(hbm_gb_s, 1) if hbm_gb_s else None,
        "num_slots": num_slots,
    }
    peak = HBM_PEAK_GB_S.get(dev.device_kind)
    if peak and hbm_gb_s:
        out["ftrl_hbm_frac_of_peak"] = round(hbm_gb_s / peak, 3)
    # the link-bound ceiling this bytes/example implies, for honesty
    # about what e2e rates are even possible through the tunnel
    if wire_bytes:
        out["link_bound_examples_per_sec_at_measured_mb_s"] = round(
            up_mb_s * 1e6 / (wire_bytes / max(1, examples_per_launch)), 1
        )
    return out


def flush(worker):
    """REAL pipeline drain: fetch a state scalar to the host. On the
    tunneled TPU backend ``jax.block_until_ready`` on shard_map outputs
    returns before the device finishes (the round-1 measurement artifact);
    a value fetch is a true device->host dependency and cannot."""
    import jax

    np.asarray(jax.tree.leaves(worker.state)[0][:1])


def phase_breakdown(worker, make_parts, T: int, launches: int = 3,
                    profile_dir: "str | None" = None) -> dict:
    """Serialized prep -> upload -> device timing for a few launches.

    The pipelined e2e loops overlap these stages (that is the point of
    the pipeline), which also HIDES where a launch's time goes — r3
    verdict: "1.018x with 96% of the roofline unexplained". Outside the
    timed windows, run each stage to completion with a flush between:
    the sum exceeds a pipelined launch (overlap removed) but the RATIO
    answers which stage bounds the pipeline. ``profile_dir`` wraps the
    first launch's device step in a jax.profiler trace
    (utils/profiling.device_trace) for op-level attribution."""
    import jax

    from parameter_server_tpu.telemetry.timeline import device_annotation
    from parameter_server_tpu.utils.profiling import device_trace

    prep_s = up_s = dev_s = 0.0
    bytes_moved = 0
    for i in range(launches):
        _beat()
        # one timeline flow per serialized launch: the three stage
        # spans below (phase="breakdown") are what the record's
        # ``attribution`` section is computed from — the trace-derived
        # twin of the hand accumulators in this loop, kept in lockstep
        # by attach_attribution's agrees_with_hand_breakdown check
        fid = telemetry_spans.maybe_new_flow()
        with telemetry_spans.flow_scope(fid):
            t0 = time.perf_counter()
            with telemetry_spans.span("bench.prep", phase="breakdown"):
                sb = stack_supersteps(make_parts(i), T)
            prep_s += time.perf_counter() - t0
            nb = tree_host_nbytes(sb)
            bytes_moved += nb
            _grace_for_transfer(nb)
            with telemetry_spans.span(
                "bench.upload", phase="breakdown", nbytes=nb
            ):
                staged, sec_up = timed_upload(sb)
            up_s += sec_up
            if profile_dir and i == 0:
                # fresh capture: the watcher reuses a fixed /tmp path,
                # and summarize_trace must not mix this run with stale
                # traces from a previous bench (or code version).
                # Remove ONLY the profiler's own plugins/ subtree — the
                # user may have pointed --profile at a directory
                # holding other files
                import shutil

                shutil.rmtree(
                    os.path.join(profile_dir, "plugins"), ignore_errors=True
                )
            ctx = (
                device_trace(profile_dir) if (profile_dir and i == 0)
                else contextlib.nullcontext()
            )
            if i == 0:
                # wall anchor for the merged device track: the profiler
                # clock has no wall reference, so the capture's ops are
                # shifted to start at this launch's host wall time
                dev_wall0 = time.time()
            t0 = time.perf_counter()
            with ctx:
                # the profiler's device tracks line up with the host
                # timeline through this named annotation (no-op off-TPU)
                with telemetry_spans.span("bench.device", phase="breakdown"):
                    with device_annotation("bench.device"):
                        worker.executor.wait(
                            worker._submit_prepped(staged, with_aux=False)
                        )
                        flush(worker)
            dev_s += time.perf_counter() - t0
    total = prep_s + up_s + dev_s
    out = {
        "breakdown_launches": launches,
        "breakdown_prep_s_per_launch": round(prep_s / launches, 4),
        "breakdown_upload_s_per_launch": round(up_s / launches, 4),
        "breakdown_device_s_per_launch": round(dev_s / launches, 4),
        "breakdown_bound": max(
            (prep_s, "host_prep"), (up_s, "upload"), (dev_s, "device")
        )[1],
        "breakdown_fracs": {
            "host_prep": round(prep_s / total, 3),
            "upload": round(up_s / total, 3),
            "device": round(dev_s / total, 3),
        } if total else None,
    }
    if up_s:
        out["breakdown_upload_mb_s"] = round(bytes_moved / up_s / 1e6, 1)
    if profile_dir:
        out["profile_dir"] = profile_dir
        from parameter_server_tpu.utils.profiling import (
            device_track_events,
            summarize_trace,
        )

        summary = summarize_trace(profile_dir)
        if summary:
            # self-contained phase attribution (ps_pull/ps_compute/
            # ps_push/ps_update named scopes) — the record answers
            # "where does the device step time go" without TensorBoard
            out["profile_device_ms"] = summary["device_ms"]
            out["profile_phases_ms"] = summary["phases"]
            out["profile_top_ops"] = summary["top_ops"][:6]
        # the capture's device ops land in the run's span timeline as a
        # device:<pid> track (anchored at the profiled launch's wall
        # time), so the Chrome export renders them under the host
        # tracks and attach_attribution grows its device_compute
        # sub-breakdown + flow arrows from the submitting step spans
        dev_events = device_track_events(profile_dir, host_anchor=dev_wall0)
        for ev in dev_events:
            ev["phase"] = "breakdown"
            telemetry_spans.emit(dict(ev))
        if dev_events:
            out["profile_device_track_events"] = len(dev_events)
    return out


def reconcile_link_ceiling(rec: dict, bytes_moved: int, done_ex: int,
                           dt: float) -> None:
    """Make the link-bound ceiling consistent with what the e2e phase
    itself observed (r3 verdict: e2e beat its own 'ceiling' by 1.6x —
    the probe-based MB/s was measured at a different moment on a link
    that drifts several x over minutes). The phase's own achieved wire
    rate (bytes actually staged / phase wall time) is a PROVEN lower
    bound on link capacity during the phase; the published ceiling uses
    whichever of probe/achieved is higher, with both disclosed."""
    if not (bytes_moved and done_ex and dt):
        return
    bpe = bytes_moved / done_ex
    achieved_mb_s = bytes_moved / dt / 1e6
    rec["e2e_bytes_per_example"] = round(bpe, 1)
    rec["e2e_achieved_wire_mb_s"] = round(achieved_mb_s, 1)
    probe = rec.get("host_to_device_mb_s")
    used = max(achieved_mb_s, probe or 0.0)
    rec["link_mb_s_used_for_ceiling"] = round(used, 1)
    rec["link_bound_examples_per_sec_at_measured_mb_s"] = round(
        used * 1e6 / bpe, 1
    )
    if probe and achieved_mb_s > probe:
        rec["link_probe_underestimated"] = (
            "in-phase achieved wire rate exceeded the probe's MB/s — "
            "the probe hit a throttled stretch; ceiling uses achieved"
        )


def stack_supersteps(parts, t: int):
    """Cycle ``parts`` to exactly ``t`` minibatches and stack them into
    one scan superbatch — every launch must reuse the ONE compiled
    scan program for its (wire, t) shape; a mid-benchmark shape change
    would put tens of seconds of XLA compile inside a timed window.
    Dispatches on the prepped wire type: ELL-bits batches (the legacy
    headline wire) and compact-encoded exact batches (the default since
    the wire flip — see run_synthetic's config note) stack into their
    respective scan superbatches."""
    from parameter_server_tpu.apps.linear.async_sgd import stack_bits_batches
    from parameter_server_tpu.learner.wire import (
        EncodedEllStreamBatch,
        EncodedExactBatch,
        stack_encoded_batches,
        stack_stream_batches,
    )

    full = [parts[i % len(parts)] for i in range(t)]
    if t == 1:
        return full[0]
    if isinstance(full[0], EncodedExactBatch):
        return stack_encoded_batches(full)
    if isinstance(full[0], EncodedEllStreamBatch):
        return stack_stream_batches(full)
    return stack_bits_batches(full)


def device_only_sweep(worker, prep_parts, base_t: int, minibatch: int,
                      smoke: bool):
    """Device-only rate at increasing scan depths T (minibatches fused
    per launch).

    Each launch's dispatch pays a tunnel round trip whose latency swings
    with link weather, so at small T the "device-only" rate still tracks
    the tunnel (measured: T=8 moves 131k examples/launch against a
    ~0.3s dispatch round trip — the rate IS the round trip). Deeper
    supersteps amortize it toward the true device rate, and the scan
    applies minibatches SEQUENTIALLY on device, so depth does not add
    staleness — convergence semantics match running the minibatches one
    by one (async delay applies across launches, not within). The sweep
    deepens ×4 adaptively while the rate keeps improving ≥10%, capped
    at T=512 (the superbatch upload through a throttled tunnel is the
    cost of each probe). Every swept T is a real streaming
    configuration (the e2e phases run the configured T), and the full
    sweep is disclosed next to the winner.

    Returns ``(best_t, best_rate, best_sec_per_launch, swept)`` where
    swept maps T -> rate. (The staged superbatch is deliberately NOT
    returned: at T=512 it is ~GB-scale, and the roofline probe only
    needs a single-minibatch representative.)"""
    import jax

    best = None
    swept = {}
    t = base_t
    prev_rate = None
    while True:
        try:
            _beat()
            sb = stack_supersteps(prep_parts, t)
            _grace_for_transfer(tree_host_nbytes(sb))
            staged = jax.device_put(sb)
            # untimed: compile this T's scan program + settle the pipeline
            worker.executor.wait(
                worker._submit_prepped(staged, with_aux=False)
            )
            flush(worker)
            _beat()
            launches = max(3, 96 // t)
            pending = []
            t0 = time.perf_counter()
            for _ in range(launches):
                pending.append(
                    worker._submit_prepped(staged, with_aux=False)
                )
                if len(pending) > 2:
                    worker.executor.wait(pending.pop(0))
                    _beat()
            while pending:
                worker.executor.wait(pending.pop(0))
            flush(worker)
            sec = time.perf_counter() - t0
        except Exception as e:  # e.g. RESOURCE_EXHAUSTED at deep T —
            # possibly only once >2 launches are in flight, so the timed
            # loop is inside the guard too. The warmup already ran the
            # user-configured base_t; never let an oversized sweep depth
            # zero the whole run — disclose and stop (larger only gets
            # worse)
            swept[t] = f"failed: {type(e).__name__}"
            break
        rate = t * minibatch * launches / sec
        swept[t] = round(rate, 1)
        if best is None or rate > best[1]:
            best = (t, rate, sec / launches)
        if smoke or t >= 512:
            break
        if prev_rate is not None and rate < prev_rate * 1.1:
            break  # diminishing returns: dispatch is amortized
        prev_rate = rate
        t *= 4
    if best is None:
        # even base_t failed (warmup ran it, so this is in-flight
        # pressure, not shape trouble) — callers catch this and continue
        # with the e2e phase so the run still produces a record
        raise RuntimeError(f"device_only_sweep: no depth succeeded ({swept})")
    return best + (swept,)


def headline_phase(worker, prep_parts, base_t: int, minibatch: int,
                   smoke: bool, num_slots: int, note: str,
                   extra: dict | None = None) -> dict:
    """The device-only headline, measured BEFORE the long e2e phase so a
    mid-run tunnel wedge cannot take it (the watchdog emits whatever is
    staged here). Shared by both bench modes: sweep → headline fields →
    HBM stats → roofline, staging partials at each step. On total sweep
    failure the run continues to the e2e phase with value 0 and the
    failure disclosed."""
    import jax

    _beat("device_only_sweep")
    try:
        best_t, dev_rate, dev_sec, swept = device_only_sweep(
            worker, prep_parts, base_t, minibatch, smoke
        )
    except RuntimeError as e:
        headline = {
            "value": 0,
            "vs_baseline": 0,
            "sweep_error": str(e),
            "note": note,
        }
        headline.update(extra or {})
        _beat("e2e", **headline)
        return headline
    headline = {
        "value": round(dev_rate, 1),
        "vs_baseline": round(dev_rate / REF_8NODE_EXAMPLES_PER_SEC, 3),
        "steps_per_launch_best": best_t,
        "steps_per_launch_swept": swept,
        "note": note,
    }
    headline.update(extra or {})
    _beat("roofline", **headline)
    hbm = jax.devices()[0].memory_stats() or {}
    if hbm.get("bytes_in_use") is not None:
        headline["hbm_bytes_in_use"] = hbm["bytes_in_use"]
        headline["hbm_bytes_limit"] = hbm.get("bytes_limit")
    # bandwidth/bytes ratios are size-invariant: probe with ONE minibatch
    # (a deep-T superbatch would re-move GBs through the tunnel); the HBM
    # accounting still uses the winning launch depth via t_mb
    headline.update(
        roofline_fields(prep_parts[0], num_slots, dev_sec,
                        minibatch, t_mb=best_t)
    )
    _beat("e2e", **headline)
    return headline


_HEXD = np.frombuffer(b"0123456789abcdef", np.uint8)
_ROW_BYTES = 275  # 1 label + 13 2-digit ints + 26 8-hex cats + 39 tabs + \n


def _write_criteo_chunk(f, rng, n: int, w_true: np.ndarray) -> None:
    """Vectorized criteo-format text writer: fixed-width rows assembled as
    one uint8 matrix (no per-row Python formatting — generating multi-GB
    files at memory speed). Token frequencies follow a power law (cube of
    a uniform) like real CTR logs; labels carry signal via w_true."""
    p_cat = w_true.size
    u = rng.random((n, 26))
    cats = (u * u * u * p_cat).astype(np.int64)
    ints = rng.integers(10, 100, size=(n, 13))
    y = w_true[cats].sum(axis=1) > 0
    buf = np.empty((n, _ROW_BYTES), np.uint8)
    buf[:, 0] = ord("0") + y
    buf[:, 1] = 9  # \t
    for j in range(13):
        c = 2 + 3 * j
        buf[:, c] = ord("0") + ints[:, j] // 10
        buf[:, c + 1] = ord("0") + ints[:, j] % 10
        buf[:, c + 2] = 9
    nib = (cats[:, :, None] >> np.arange(28, -4, -4)) & 0xF
    hexs = _HEXD[nib]  # [n, 26, 8] ascii
    for j in range(26):
        c = 41 + 9 * j
        buf[:, c : c + 8] = hexs[:, j]
        buf[:, c + 8] = 9
    buf[:, _ROW_BYTES - 1] = 10  # \n
    buf.tofile(f)


def ensure_criteo_file(path: str, target_mb: int, p_cat: int = 1 << 24) -> str:
    """Generate (once, cached on disk) a criteo-format text file of
    ~target_mb MB. Deterministic: seed 0."""
    want = target_mb << 20
    if os.path.exists(path) and abs(os.path.getsize(path) - want) < (_ROW_BYTES << 12):
        return path
    os.makedirs(os.path.dirname(path), exist_ok=True)
    rng = np.random.default_rng(0)
    w_true = (rng.normal(size=p_cat) * (rng.random(p_cat) < 0.05)).astype(np.float32)
    rows_left = -(-want // _ROW_BYTES)
    t0 = time.perf_counter()
    with open(path + ".tmp", "wb") as f:
        while rows_left > 0:
            _beat()
            n = min(rows_left, 1 << 18)
            _write_criteo_chunk(f, rng, n, w_true)
            rows_left -= n
    os.replace(path + ".tmp", path)
    print(
        f"# generated {os.path.getsize(path) >> 20}MB criteo text in "
        f"{time.perf_counter() - t0:.1f}s -> {path}",
        file=sys.stderr,
    )
    return path


class FtrlOracle:
    """NumPy FTRL on hashed slots — bit-for-bit the device step's math
    (updaters.py FTRLUpdater / ref FTRLEntry::Set) restricted to touched
    slots, using the SAME murmur hash→slot localization. Used to assert
    logloss parity of the real-data device pipeline."""

    def __init__(self, num_slots: int, alpha: float, beta: float, l1: float):
        self.num_slots = num_slots
        self.alpha, self.beta, self.l1 = alpha, beta, l1
        self.z = np.zeros(num_slots, np.float32)
        self.sqrt_n = np.zeros(num_slots, np.float32)

    def step(self, batch) -> float:
        """One minibatch: returns the summed logloss (pre-update weights,
        matching the device metrics' objective)."""
        from parameter_server_tpu.utils.murmur import hash_slots

        n_rows = batch.n
        lanes = batch.nnz // n_rows
        slots = hash_slots(batch.indices, self.num_slots)
        u, inv = np.unique(slots, return_inverse=True)
        eta = self.alpha / (self.sqrt_n[u] + self.beta)
        zt = -self.z[u] * eta
        w_u = np.sign(zt) * np.maximum(np.abs(zt) - self.l1 * eta, 0.0)
        xw = w_u[inv].reshape(n_rows, lanes).sum(axis=1)
        y = batch.y
        ll = float(np.logaddexp(0.0, -y * xw).sum())
        tau = 1.0 / (1.0 + np.exp(np.clip(y * xw, -60, 60)))
        gr = (-y * tau).astype(np.float32)
        g_u = np.bincount(
            inv, weights=np.repeat(gr, lanes), minlength=u.size
        ).astype(np.float32)
        n_new = np.sqrt(self.sqrt_n[u] ** 2 + g_u**2)
        self.z[u] += g_u - (n_new - self.sqrt_n[u]) / self.alpha * w_u
        self.sqrt_n[u] = n_new
        return ll


def run_real(args) -> int:
    """End-to-end real-data bench: criteo TEXT on disk → chunked C++ parse
    (thread pool) → hash/bit-pack localization → device submit, all inside
    the timed loop; then a device-only rate on pre-staged batches; plus a
    logloss-parity phase vs FtrlOracle. One JSON line with all three."""
    import jax

    from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker
    from parameter_server_tpu.apps.linear.config import (
        Config,
        LearningRateConfig,
        PenaltyConfig,
        SGDConfig,
    )
    from parameter_server_tpu.data.stream_reader import StreamReader
    from parameter_server_tpu.system.postoffice import Postoffice

    num_slots = args.num_slots if args.num_slots >= (1 << 26) else (1 << 26)
    if args.smoke:
        num_slots = 1 << 18
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "data",
        "criteo_bench",
        f"part-{args.real_mb}mb.txt",
    )
    ensure_criteo_file(path, args.real_mb)
    file_rows = os.path.getsize(path) // _ROW_BYTES

    Postoffice.reset()
    po = Postoffice.instance().start()
    trace_path = ensure_trace_sink()
    # HBM/live-buffer gauges refresh on every snapshot/scrape from here
    # on (telemetry/device.py collector; feeds the record's device.hbm
    # section and the ps_device_hbm_* families on /metrics)
    from parameter_server_tpu.telemetry.device import install_hbm_monitor

    install_hbm_monitor()
    _maybe_expose(po, args)

    alpha, beta, l1 = 0.1, 1.0, 1.0
    conf = Config()
    conf.penalty = PenaltyConfig(type="l1", lambda_=[l1])
    conf.learning_rate = LearningRateConfig(type="decay", alpha=alpha, beta=beta)
    # THE STREAM-ONCE WIRE FLIP (ROADMAP item 1, --real half): the
    # production-shaped path streams each example ONCE, so the upload
    # key cache never hits and the exact encoding loses to raw bits —
    # the lane-dictionary stream wire is the cache-free encoding built
    # for exactly this regime (~96 B/example vs the recorded 126.9 at
    # 2^26 slots, bit-identical decode on device). Falls back to the
    # bits wire per batch when a batch leaves the pinned lane statics
    # (fallbacks disclosed under e2e_wire).
    conf.async_sgd = SGDConfig(
        algo="ftrl",
        minibatch=args.minibatch,
        num_slots=num_slots,
        max_delay=0,  # parity first; the timed phase relaxes to 4
        ell_lanes=39,
        wire=args.real_wire,
        wire_compress=args.wire_compress,
        pull_filter=(
            [{"type": "fixing_float", "num_bytes": args.pull_bytes}]
            if args.pull_bytes else []
        ),
    )
    worker = AsyncSGDWorker(conf, mesh=po.mesh)

    def stream():
        return StreamReader([path], "criteo").minibatches_bytes(
            args.minibatch, threads=args.parse_threads
        )

    # -- phase 1: logloss parity vs the NumPy oracle (sequential weights:
    # max_delay=0 means the device pulls the latest state every step, so
    # the oracle sees identical math modulo f32 reduction order) --
    # parse-only ceiling: disk -> C++ parse, no localize/upload/device —
    # the host-parse term of the pipeline roofline (the breakdown fields
    # price localize/upload/device). Direct parser-core measurement
    # (reader/prefetch machinery would measure its BUFFER drain rate,
    # not parsing), taken BEFORE the parity stream exists so its
    # thread-pool's in-flight chunk parses can't contend for the core.
    _beat("parse_rate")
    from parameter_server_tpu.data.text_parser import ExampleParser

    with open(path, "rb") as f:
        chunk = f.read(2 << 20 if args.smoke else 16 << 20)
    chunk = chunk[: chunk.rfind(b"\n") + 1]
    pparser = ExampleParser("criteo")
    # warm (C++ lib load, caches) with a LINE-ALIGNED prefix — a
    # mid-row cut is outside parse_text's documented contract
    pparser.parse_text(chunk[: chunk.rfind(b"\n", 0, 1 << 18) + 1])
    t0 = time.perf_counter()
    pb = pparser.parse_text(chunk)
    parse_sec = time.perf_counter() - t0
    parse_only_ex_s = (
        round(pb.n / parse_sec, 1) if parse_sec and pb.n else None
    )
    del chunk, pb

    oracle = FtrlOracle(num_slots, alpha, beta, l1)
    parity_steps = 4 if args.smoke else args.parity_steps
    dev_obj = orc_obj = parity_ex = 0.0
    batches = stream()
    kept = []
    _beat("parity")
    for i in range(parity_steps):
        _beat()
        b = next(batches)
        if b.n < args.minibatch:
            break
        kept.append(b)
        prepped = jax.device_put(worker.prep(b, device_put=False))
        m = worker.executor.wait(worker._submit_prepped(prepped, with_aux=False))
        dev_obj += float(m["objective"])
        orc_obj += oracle.step(b)
        parity_ex += b.n
    assert parity_ex > 0, (
        f"file too small for parity: need >= {args.minibatch} rows, "
        f"have {file_rows}"
    )
    ll_dev = dev_obj / parity_ex
    ll_orc = orc_obj / parity_ex
    # under a quantized pull (--pull-bytes) the oracle stays EXACT while
    # the device trains on stochastically rounded weights; the rounding
    # is unbiased (measured drift ~1e-5 on smoke) but the gate widens
    # 2x to absorb compounding over the full parity window, disclosed
    # in the record
    tol_scale = 2.0 if args.pull_bytes else 1.0
    parity_ok = abs(ll_dev - ll_orc) <= tol_scale * max(0.01, 0.02 * ll_orc)
    assert parity_ok, (
        f"logloss parity FAILED: device {ll_dev:.5f} vs oracle {ll_orc:.5f}"
    )


    # -- phase 2: end-to-end timed stream, parsing inside the pipeline.
    # Three stages on three threads: a producer parses (C++ releases
    # the GIL) + localizes, an UploadPipeline thread stacks supersteps
    # and stages them through the tunnel (socket I/O, GIL-free), and
    # the main thread keeps launches in flight. Even on a SINGLE-core
    # host (this image) the stages overlap: parse CPU runs while the
    # wire moves bytes and the device steps — only the numpy
    # stack/localize slices compete for the core. --
    worker.sgd.max_delay = 4
    worker.executor.max_in_flight = 5
    T = max(1, args.steps_per_launch)

    # untimed warmup: compile BOTH step programs before the clock starts
    # (the donation split jits the snapshot and delayed paths
    # separately, and which one a launch takes depends on the snapshot
    # counter — the timed stream must never pay a compile). One normal
    # launch compiles the snapshot program; a direct call with copied
    # buffers compiles the delayed program (jitted steps are pure — the
    # discarded result mutates nothing, and copies keep donation away
    # from the live table).
    _beat("warmup")
    from parameter_server_tpu.apps.linear.async_sgd import (
        prep_batch_ell_bits,
    )
    from parameter_server_tpu.learner.wire import EncodedEllStreamBatch

    prep_parts = [worker.prep(b, device_put=False) for b in kept]
    # e2e_wire: the --real twin of the synthetic record's section (the
    # stream-once path's wire choice must be visible in the record) —
    # which wire the stream actually rides, the per-encoding
    # bytes/example A/B on THIS run's first real batch, and the pinned
    # lane statics. bench_diff treats it as metadata, never a band.
    stream_mode = isinstance(prep_parts[0], EncodedEllStreamBatch)
    warmup_fallbacks = 0
    if stream_mode:
        # a kept batch past the pinned lane statics fell back to the
        # bits wire — a mixed list cannot stack into the one compiled
        # scan shape (same guard the timed stream applies), so drop
        # fallback parts from the warm pool and disclose
        n0 = len(prep_parts)
        prep_parts = [
            p for p in prep_parts
            if isinstance(p, EncodedEllStreamBatch)
        ]
        warmup_fallbacks = n0 - len(prep_parts)
    rows_pad, _, _ = worker._padding(kept[0])
    bits_part = prep_batch_ell_bits(
        kept[0], worker.directory, worker._num_shards(), rows_pad, 39,
        worker.num_slots,
    )
    e2e_wire = {
        "wire": conf.async_sgd.wire,
        "wire_actual": "stream" if stream_mode else "bits",
        "wire_compress": conf.async_sgd.wire_compress or None,
        "max_delay": 4,  # the timed phase's delay bound (set below)
        "bytes_per_example": {
            "bits": round(
                tree_host_nbytes(bits_part) / args.minibatch, 1
            ),
            **(
                {
                    "stream": round(
                        tree_host_nbytes(prep_parts[0]) / args.minibatch,
                        1,
                    )
                }
                if stream_mode
                else {}
            ),
        },
    }
    if stream_mode:
        e2e_wire["warmup_fallback_parts"] = warmup_fallbacks
        st = worker._stream_statics
        e2e_wire["stream_statics"] = {
            "dict_lanes": len(st.dict_lanes),
            "raw_lanes": st.lanes - len(st.dict_lanes),
            "code_bits": st.code_bits,
            "raw_bits": st.raw_bits,
            "dict_pad": st.dict_pad,
        }
    warm = stack_supersteps(prep_parts, T)
    _grace_for_transfer(tree_host_nbytes(warm))
    warm = jax.device_put(warm)
    _grace_for_compile()  # first wait pays the big scan-program compile
    worker.executor.wait(worker._submit_prepped(warm, with_aux=False))
    flush(worker)
    _beat()
    step_fn = worker._get_step(warm, False)
    live_copy = jax.tree.map(lambda x: x.copy(), worker.state)
    pull_copy = jax.tree.map(lambda x: x.copy(), worker.state)
    _grace_for_compile()  # delayed-path program compiles here
    jax.block_until_ready(
        step_fn(live_copy, pull_copy, warm, np.uint32(0))[1]["num_ex"]
    )
    del live_copy, pull_copy

    headline = headline_phase(
        worker, prep_parts,
        T, args.minibatch, args.smoke, num_slots,
        note="value = device-only rate (pre-staged, no parsing; best "
        "scan depth of the disclosed sweep); "
        "e2e_stream = disk->parse->localize->upload->step",
        extra={
            "logloss_device": round(ll_dev, 5),
            "logloss_oracle": round(ll_orc, 5),
            "parity_ok": parity_ok,
            **({"parity_tol_relaxed_for_quantized_pull": tol_scale}
               if args.pull_bytes else {}),
            "parse_only_examples_per_sec": parse_only_ex_s,
            "e2e_wire": e2e_wire,
        },
    )
    # serialized stage pricing (localize+pack / upload / device) — the
    # --real stream adds PARSE on top, priced by comparing e2e below.
    # Guarded + re-beaten (see run_synthetic's breakdown note).
    try:
        headline.update(phase_breakdown(
            worker,
            lambda i: [
                worker.prep(kept[(i * T + j) % len(kept)], device_put=False)
                for j in range(T)
            ],
            T,
            profile_dir=args.profile,
        ))
    except Exception as e:
        headline["breakdown_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    _beat("kv_dataplane")
    attach_kv_dataplane(headline, worker.mesh, args.smoke)
    _beat("host_ingest")
    attach_host_ingest(headline, args.smoke)
    _beat("wire")
    attach_wire(headline, args.smoke)
    _beat("ftrl_sparse")
    attach_ftrl(headline, args.smoke)
    _beat("serve")
    attach_serve(headline, args.smoke)
    _beat("decode_batching")
    attach_decode_batching(headline, args.smoke)
    _beat("recovery")
    attach_recovery(headline, args.smoke)
    _beat("blackbox")
    attach_blackbox(headline, args.smoke)
    # history-plane fold-hook overhead A/B + the live store snapshot
    # (doc/OBSERVABILITY.md "History plane")
    _beat("history")
    attach_history(headline, args.smoke)
    # learning truth plane (staleness vs τ, heat/shard balance,
    # convergence trajectory, divergence drill). Runs LAST among the
    # component sections: its probe resets the Postoffice, and the run
    # planes it harvests first must still cover the phases above.
    _beat("learning")
    attach_learning(headline, args.smoke)
    # self-driving consistency A/B (adaptive τ + KKT filter + rollback
    # drill) — also Postoffice-resetting, so it rides with learning at
    # the tail of the component sections
    _beat("consistency")
    attach_consistency(headline, args.smoke)
    _beat("e2e", **headline)

    wire_fallback = {"parts": 0, "rows": 0}

    def host_prepped():
        for b in batches:  # rest of the file
            if b.n < args.minibatch:
                break  # keep superstep shapes static
            with telemetry_spans.span("bench.prep", phase="e2e"):
                part = worker.prep(b, device_put=False)
            if stream_mode and not isinstance(part, EncodedEllStreamBatch):
                # a batch left the pinned lane statics and fell back to
                # the bits wire — a mixed group cannot stack into the
                # one compiled scan shape, so the batch is dropped from
                # the timed stream and DISCLOSED (never silently mixed;
                # rows dropped are excluded from the rate's numerator)
                wire_fallback["parts"] += 1
                wire_fallback["rows"] += int(b.n)
                continue
            if args.wire_compress:
                # staging-leg codec on the producer (prep) thread; the
                # UploadPipeline's staging thread decodes before the
                # stack+device_put (the stateless-or-feeder split)
                from parameter_server_tpu.learner.wire import (
                    compress_batch,
                )

                part = compress_batch(
                    part, encoding="stream" if stream_mode else "bits"
                )
            yield part

    def prepped_stream():
        # producer thread even on one core: parse is GIL-free C++, so
        # it overlaps the uploader's socket writes and the device steps
        return iter_on_thread(host_prepped(), maxsize=3 * T)

    # warmup mark for the device inventory: every program the timed
    # stream below will run has compiled by now (warmup + headline +
    # the A/B attaches) — recompiles_post_warmup must read zero
    from parameter_server_tpu.telemetry import device as _device_mod

    _device_mod.mark_warmup()
    e2e_wall0 = time.time()
    t0 = time.perf_counter()
    done_ex = 0
    wire_bytes_moved = 0
    pending = []
    # (elapsed_s, examples/sec) per ~2 s stretch for the live_drift
    # verdict (no flush per sample: submissions are pipelined, so each
    # stretch's rate is approximate — the drift check medians segments)
    drift_samples = []
    win_ex, win_t = 0, t0
    pipe = UploadPipeline(prepped_stream(), T)
    for dev_sb, n_ex, nb, fid in pipe:
        done_ex += n_ex
        win_ex += n_ex
        _now = time.perf_counter()
        if _now - win_t >= 2.0:
            drift_samples.append((_now - t0, win_ex / (_now - win_t)))
            win_ex, win_t = 0, _now
        wire_bytes_moved += nb  # actual staged bytes, not a dtype model
        _beat()
        # device_put returned with the transfer possibly still in
        # flight: the wait below may pay the wire time, so grace it on
        # THIS thread (the beater) like the pre-pipeline code did
        _grace_for_transfer(nb)
        with telemetry_spans.flow_scope(fid):
            pending.append(worker._submit_prepped(dev_sb, with_aux=False))
        if len(pending) > 2:
            worker.executor.wait(pending.pop(0))
    # a trailing partial group would compile a second scan shape inside
    # the timed window; the pipeline skips it — disclose the drop
    skipped_tail = pipe.skipped_examples
    for ts in pending:
        worker.executor.wait(ts)
    flush(worker)
    dt = time.perf_counter() - t0
    e2e_wall1 = time.time()
    e2e_rate = done_ex / dt

    rec = {
        # the ONE metric-name definition lives in main() (the watchdog
        # was armed with it); re-deriving the _qN suffix here could
        # silently diverge from the provisional/partial records
        "metric": _WATCHDOG.metric,
        "unit": "examples/sec",
        "e2e_stream": round(e2e_rate, 1),
        "e2e_vs_baseline": round(e2e_rate / REF_8NODE_EXAMPLES_PER_SEC, 3),
        "file_mb": os.path.getsize(path) >> 20,
        "file_rows": int(file_rows),
        "skipped_tail_rows": int(skipped_tail),
    }
    e2e_wire["fallback_parts"] = wire_fallback["parts"]
    e2e_wire["fallback_rows_dropped"] = wire_fallback["rows"]
    if pipe.staged_raw_bytes:
        # staging leg net of compression (the ps_wire accounting twin);
        # the tunnel bytes in reconcile_link_ceiling stay REALIZED
        e2e_wire["staging_leg"] = {
            "raw_mb": round(pipe.staged_raw_bytes / 1e6, 1),
            "compressed_mb": round(pipe.staged_compressed_bytes / 1e6, 1),
            "ratio": round(
                pipe.staged_raw_bytes
                / max(1, pipe.staged_compressed_bytes),
                3,
            ),
        }
    rec.update(headline)
    reconcile_link_ceiling(rec, wire_bytes_moved, done_ex, dt)
    # the run worker's OWN learning plane, harvested after the timed
    # stream so its staleness/trajectory view covers the e2e phase
    attach_learning_run(rec, worker)
    # live steady-state drift: the run's tail stretches vs its own
    # post-warmup baseline (doc/OBSERVABILITY.md "History plane")
    attach_history_drift(rec, drift_samples)
    # device truth plane AFTER the timed stream: the post-warmup
    # recompile count covers the phase that must not re-specialize
    attach_device(rec, args.smoke)
    attach_attribution(rec, trace_path, (e2e_wall0, e2e_wall1))
    _finish(rec)
    return 0


def main() -> int:
    global _PENDING_REC
    # a supervisor (watcher/driver) stopping the bench sends SIGTERM;
    # flush the best available record, then convert to SystemExit so
    # the tunnel client's atexit/GC gets a shot at releasing its device
    # claim (a hard-killed client has wedged the relay for hours —
    # probe_device docstring). Seed a minimal record BEFORE anything
    # else: argparse + the heavyweight build_device_error take seconds
    # on a loaded host, and a kill inside that window must still leave
    # a parseable artifact.
    _PENDING_REC = {
        "metric": "criteo_sparse_lr_examples_per_sec",
        "value": 0,
        "unit": "examples/sec",
        "vs_baseline": 0,
        "error": "bench killed during startup, before the device probe",
    }
    import signal as _signal

    with contextlib.suppress(ValueError):  # non-main thread: leave it
        _signal.signal(_signal.SIGTERM, _sigterm_handler)
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny quick run (CI)")
    ap.add_argument("--minibatch", type=int, default=16384)
    # criteo shape: 13 numeric + 26 categorical = 39 features/example,
    # categorical dominating (binary). We bench the binary/ELL hot path.
    ap.add_argument("--nnz-per-row", type=int, default=39)
    ap.add_argument("--num-slots", type=int, default=1 << 22)
    ap.add_argument("--steps", type=int, default=240)
    ap.add_argument("--warmup", type=int, default=8)
    ap.add_argument(
        "--real",
        action="store_true",
        help="stream a real criteo-format text file with parsing inside the "
        "timed pipeline + logloss parity vs the numpy oracle (table 2^26)",
    )
    ap.add_argument("--real-mb", type=int, default=2048, help="file size to stream")
    ap.add_argument(
        "--real-wire",
        default="stream",
        choices=("stream", "bits"),
        help="--real path's ELL wire: DEFAULT 'stream' — the stream-once "
        "lane-dictionary encoding (cache-free: small-vocabulary lanes "
        "ship uslot tables + packed ucols, ~96 B/ex vs bits' 126.9 at "
        "2^26; ROADMAP item 1's --real half); 'bits' restores the "
        "legacy raw bit stream. Per-batch fallbacks to bits are "
        "disclosed under e2e_wire",
    )
    ap.add_argument(
        "--wire-compress",
        default="",
        choices=("", "lz"),
        help="staging-leg byte codec for the --real stream: prep "
        "compresses each encoded batch's leaves (native LZ, "
        "incompressible rides raw), the uploader thread decodes before "
        "device_put. Shrinks the modeled feeder→trainer staging leg "
        "(disclosed under e2e_wire.staging_leg), NOT the PJRT tunnel "
        "bytes — default off on the tunnel since the decode costs "
        "serial uploader-thread time for zero tunnel-byte gain",
    )
    ap.add_argument("--parse-threads", type=int, default=4)
    ap.add_argument("--parity-steps", type=int, default=24)
    ap.add_argument(
        "--steps-per-launch",
        type=int,
        default=8,
        help="minibatches scanned per device launch (ELLBitsSuperBatch); "
        "amortizes the tunnel round trip",
    )
    ap.add_argument(
        "--wire-encode",
        default="exact",
        choices=("", "exact", "int8", "u16", "bf16"),
        help="compact host→device wire for the headline e2e path "
        "(learner/wire.py): DEFAULT 'exact' — sparse update + encoded "
        "batches + the upload key cache, so the e2e stream stops "
        "paying the raw 107.4 B/ex the BENCH_r05 breakdown showed "
        "(ROADMAP item 1). '' restores the legacy bits-wire config; "
        "quantized-pull runs (--pull-bytes) keep bits regardless "
        "(sparse composes with unfiltered pulls only)",
    )
    ap.add_argument(
        "--wire-cache-mb",
        type=int,
        default=64,
        help="upload key-cache budget (MB of retained host copies) for "
        "the encoded-wire e2e stream; 0 disables",
    )
    ap.add_argument(
        "--pull-bytes",
        type=int,
        default=0,
        choices=(0, 1, 2),
        help="FIXING_FLOAT pull filter width: servers send n-byte "
        "quantized weights (the reference's production criteo pull, "
        "example/linear/ctr/online_l1lr.conf). The step dequantizes "
        "shard-wide then gathers f32 (pull_gather auto => wide; the "
        "narrow codes+mask gather measured SLOWER on TPU — "
        "BENCH_ONCHIP 08-02). Metric name gains a "
        "_qN suffix so captures pool separately from the exact-pull "
        "headline",
    )
    ap.add_argument(
        "--profile",
        default=None,
        metavar="DIR",
        help="capture a jax.profiler device trace of one serialized "
        "launch into DIR (utils/profiling.device_trace; view in "
        "TensorBoard/Perfetto). DIR/plugins from any previous capture "
        "is removed first so the summary reflects this run only",
    )
    ap.add_argument(
        "--expose-port",
        type=int,
        default=None,
        metavar="PORT",
        help="serve the cluster metrics plane while the bench runs "
        "(telemetry/exposition.py): /metrics = node-labeled Prometheus "
        "aggregate, /healthz = heartbeat+recovery verdict (503 on a "
        "dead/stale shard), /debug/snapshot = registry+alerts+timeline "
        "JSON; default SLO alert rules from configs/alerts/default.json "
        "evaluate live. 0 binds an ephemeral port (printed to stderr); "
        "the record gains an 'expose' section with the scrape summary",
    )
    ap.add_argument(
        "--stall-timeout",
        type=float,
        default=300.0,
        help="seconds of mid-run silence before the watchdog emits the "
        "best-so-far record and exits (tunnel wedge guard)",
    )
    args = ap.parse_args()
    if args.smoke:
        args.minibatch, args.steps, args.warmup = 1024, 10, 2
        args.num_slots = 1 << 16
        args.real_mb = min(args.real_mb, 8)
        # a smoke run is a CPU correctness pass: keep it off the
        # tunnel entirely unless the operator explicitly forced a
        # platform. Before this, a smoke run still PROBED the device
        # below, and the probe's priority marker preempted a live
        # watcher capture task (observed 08-02 07:01) — a toy run
        # must never cost chip time. Unconditional: even an ambient
        # JAX_PLATFORMS=axon (this host's shell default) must not put
        # a toy run on the tunnel — there is no legitimate smoke-on-
        # chip use, and the honor_jax_platforms() hook makes this
        # effective even though jax is already imported
        os.environ["JAX_PLATFORMS"] = "cpu"
    # one tunneled chip, one client at a time: wait for a concurrent
    # holder — e.g. the evidence watcher mid-task — instead of
    # colliding with it. The wait bound exceeds every WATCHER-side
    # hold (task subprocess timeouts, max 5400s), so the watcher is
    # always waited out; only another interactive bench can outlive
    # the bound, and that timeout is disclosed on stderr before
    # proceeding. Smoke runs are CPU-bound and skip the lock
    # entirely; a holder's child skips via PS_DEVICE_LOCK_HELD.
    from parameter_server_tpu.utils.device_lock import (
        clear_priority,
        device_lock,
    )

    # a CPU-platform run (every smoke run — forced above — or an
    # explicit JAX_PLATFORMS=cpu sanity run) never touches the
    # tunnel: no device lock, no priority marker, no probe. A
    # priority marker from a CPU run would preempt the watcher's
    # in-flight on-chip capture for nothing (observed 08-02 07:01).
    cpu_run = os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
    lock = (
        contextlib.nullcontext(True) if cpu_run
        # priority_note announces BEFORE waiting on the flock (and
        # keeps the marker fresh however long the wait runs): the
        # watcher yields — preempting its running task child — within
        # seconds, so the round driver's bench, the artifact of
        # record, never waits out a full watcher task, let alone
        # 5700s. After the bound, keep waiting and ACQUIRE (never run
        # unlocked: the watcher would collide the moment the previous
        # holder exits and frees the flock).
        else device_lock(block_after_timeout=True, priority_note="bench")
    )
    metric = (
        "criteo_real_examples_per_sec"
        if args.real
        else "criteo_sparse_lr_examples_per_sec"
    ) + (f"_q{args.pull_bytes}" if args.pull_bytes else "")
    if not args.smoke:
        # Provisional record: the driver keeps whatever stdout holds
        # when it loses patience, and it parses the LAST JSON line.
        # Print the failure record FIRST (flushed), refresh it on
        # every retry, and let any later record supersede it — a kill
        # at ANY point after this line now leaves a parseable artifact
        # instead of silence. MUST print before the device-lock wait
        # below: the flock can block for minutes behind the watcher's
        # own wedged probe (observed while verifying this change), and
        # a kill during that wait would otherwise find empty stdout.
        _PENDING_REC = build_device_error(
            "provisional record: bench killed before the "
            "device probe loop finished",
            metric=metric,
        )
        _raw_emit(_PENDING_REC)
    with lock:
        try:
            def _refresh(attempt: int, diag: str) -> None:
                if _PENDING_REC is not None:
                    _PENDING_REC["error"] = (
                        f"accelerator unreachable: {diag} (provisional "
                        f"after failed probe attempt {attempt})"
                    )
                    _raw_emit(_PENDING_REC)

            # CPU-platform runs have nothing to probe: probing would
            # touch the tunnel and preempt a live watcher capture
            diagnosis = (
                None if cpu_run else probe_device(on_retry=_refresh)
            )
            if diagnosis is not None:
                # reuse the staged provisional (same heavyweight
                # diagnostics) rather than rebuilding it from scratch.
                # Smoke runs never staged one (their _PENDING_REC is
                # still the minimal startup seed with a hardcoded
                # metric): build the full record for them here
                rec = (
                    _PENDING_REC
                    if _PENDING_REC is not None and not args.smoke
                    else build_device_error(diagnosis, metric=metric)
                )
                rec["error"] = f"accelerator unreachable: {diagnosis}"
                _PENDING_REC = None
                _raw_emit(rec)
                return 1
        finally:
            # unconditional: probe_device writes a marker even on a
            # --smoke run (which skips the request above), and a
            # leaked marker idles the watcher for the full freshness
            # window. The flock itself keeps the watcher off the
            # device from here on; dropping the marker the moment
            # probing ends also means a crashed bench never idles the
            # watcher long.
            clear_priority()
        global _WATCHDOG
        _WATCHDOG = Watchdog(metric, stall_s=args.stall_timeout)
        _PENDING_REC = None  # the watchdog owns flushing from here on
        try:
            if args.real:
                return run_real(args)
            return run_synthetic(args)
        except Exception as e:  # backend death raises instead of stalling
            # full traceback to stderr (the JSON contract owns stdout):
            # a programming error must stay diagnosable from the log
            # even though the record discloses only the truncated
            # message
            traceback.print_exc()
            return _WATCHDOG.abort(f"{type(e).__name__}: {str(e)[:300]}")


def run_synthetic(args) -> int:
    import jax

    from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker
    from parameter_server_tpu.apps.linear.config import (
        Config,
        LearningRateConfig,
        PenaltyConfig,
        SGDConfig,
    )
    from parameter_server_tpu.parallel import mesh as meshlib
    from parameter_server_tpu.system.postoffice import Postoffice
    from parameter_server_tpu.utils.sparse import random_sparse

    Postoffice.reset()
    po = Postoffice.instance().start()  # all local devices, 1 server axis
    trace_path = ensure_trace_sink()
    # HBM/live-buffer gauges refresh on every snapshot/scrape from here
    # on (telemetry/device.py collector; feeds the record's device.hbm
    # section and the ps_device_hbm_* families on /metrics)
    from parameter_server_tpu.telemetry.device import install_hbm_monitor

    install_hbm_monitor()
    _maybe_expose(po, args)
    n_workers = meshlib.num_workers(po.mesh)

    conf = Config()
    conf.penalty = PenaltyConfig(type="l1", lambda_=[1.0])
    conf.learning_rate = LearningRateConfig(type="decay", alpha=0.1, beta=1.0)
    # THE WIRE FLIP (ROADMAP item 1): the headline e2e path rides the
    # compact encoded wire by default — sparse update + wire_encode +
    # the upload key cache — so the record's e2e bytes/example reflects
    # the PR-5 codec instead of the raw 107.4 B/ex bits wire the
    # breakdown kept quoting. Sparse mode is the exact-wire scan-fusion
    # gate (ADVICE r5) and composes with UNFILTERED pulls only, so a
    # quantized-pull run (--pull-bytes, the _qN metric) keeps the
    # legacy bits-wire config — disclosed in the record either way.
    encoded = bool(args.wire_encode) and not args.pull_bytes
    conf.async_sgd = SGDConfig(
        algo="ftrl",
        minibatch=args.minibatch,
        num_slots=args.num_slots,
        # sparse ministeps run on the live state (staleness 0, within
        # any delay bound); the bits path keeps the reference criteo
        # conf's bounded delay
        max_delay=0 if encoded else 4,
        ell_lanes=args.nnz_per_row,
        # legacy minimal wire: 22-bit slot stream + 1-bit labels, fused
        # C++ hash→pack (the --pull-bytes / --no-encoded-wire path)
        wire="" if encoded else "bits",
        update="sparse" if encoded else "auto",
        wire_encode=args.wire_encode if encoded else "",
        wire_cache_mb=args.wire_cache_mb if encoded else 0,
        pull_filter=(
            [{"type": "fixing_float", "num_bytes": args.pull_bytes}]
            if args.pull_bytes else []
        ),
    )
    worker = AsyncSGDWorker(conf, mesh=po.mesh)

    p_space = 1 << 24  # raw key universe (hashed into num_slots)

    def gen(i: int):
        b = random_sparse(
            args.minibatch, p_space, args.nnz_per_row, seed=i, binary=True
        )
        # cheap synthetic labels keyed off low-id features for signal
        b.y = np.where(
            (b.indices.reshape(args.minibatch, -1) % 1024 < 256).mean(1) > 0.24,
            1.0,
            -1.0,
        ).astype(np.float32)
        return b

    # pre-generate raw batches (parsing is benchmarked separately — the
    # --real mode streams actual criteo text with parsing in the loop);
    # LOCALIZATION (hash→slot + bit packing), superbatch stacking and the
    # device upload all run inside the timed loop — the honest host cost.
    T = max(1, args.steps_per_launch)
    raw = [gen(i) for i in range(min(args.steps + args.warmup, 32))]
    worker._padding(raw[0])

    wire_counter = {"bytes": 0}

    def prep_upload_submit(i: int):
        # with_aux=False: skip the per-example AUC outputs in the hot loop
        parts = [
            worker.prep(raw[(i + j) % len(raw)], device_put=False)
            for j in range(T)
        ]
        sb = stack_supersteps(parts, T)
        nb = tree_host_nbytes(sb)
        wire_counter["bytes"] += nb  # actual staged bytes, not a model
        _grace_for_transfer(nb)
        return worker._submit_prepped(jax.device_put(sb), with_aux=False)

    # warmup (compile)
    _beat("warmup")
    pending = []
    for i in range(max(1, args.warmup // T)):
        pending.append(prep_upload_submit(i * T))
    _grace_for_compile()  # first wait pays the big scan-program compile
    for ts in pending:
        worker.executor.wait(ts)
        _beat()
    flush(worker)
    # compile the delayed-step program too (see run_real's warmup note):
    # with T < max_delay the snapshot counter decides mid-stream which
    # jitted variant runs, and the timed windows must never pay a
    # compile. The encoded-wire config needs no second warmup: max_delay
    # is 0 there, so EVERY launch snapshots+donates — the one variant
    # the warmup submits above already compiled.
    prep_parts = [
        worker.prep(raw[j % len(raw)], device_put=False) for j in range(T)
    ]
    if not encoded:
        warm_host = stack_supersteps(prep_parts, T)
        _grace_for_transfer(tree_host_nbytes(warm_host))
        warm_sb = jax.device_put(warm_host)
        del warm_host
        step_fn = worker._get_step(warm_sb, False)
        live_copy = jax.tree.map(lambda x: x.copy(), worker.state)
        pull_copy = jax.tree.map(lambda x: x.copy(), worker.state)
        _grace_for_compile()  # delayed-path program compiles here
        jax.block_until_ready(
            step_fn(live_copy, pull_copy, warm_sb, np.uint32(0))[1]["num_ex"]
        )
        del live_copy, pull_copy, warm_sb

    headline = headline_phase(
        worker, prep_parts,
        T, args.minibatch, args.smoke, args.num_slots,
        note="value = device-only rate (pre-staged batches; best scan "
        "depth of the disclosed sweep); "
        "e2e_median_window = prep+upload+step through the tunnel",
    )
    # serialized stage pricing (+ optional device trace): which of
    # prep/upload/device bounds the pipeline below. Guarded like
    # device_only_sweep: a transient failure in these EXTRA launches
    # must not cost the e2e phase; re-beat so a later wedge's partial
    # record still carries the breakdown.
    try:
        headline.update(phase_breakdown(
            worker,
            lambda i: [
                worker.prep(raw[(i * T + j) % len(raw)], device_put=False)
                for j in range(T)
            ],
            T,
            profile_dir=args.profile,
        ))
    except Exception as e:
        headline["breakdown_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    # zero-copy data-plane A/B rides along in the record (donated vs
    # copying push, fused vs sequenced round trip) + ticks the kvops
    # telemetry counters for the snapshot
    _beat("kv_dataplane")
    attach_kv_dataplane(headline, po.mesh, args.smoke)
    # host-ingest serial-vs-pipelined A/B rides along too (PR3): the
    # ingest plane is the post-zero-copy bottleneck this record tracks
    _beat("host_ingest")
    attach_host_ingest(headline, args.smoke)
    _beat("wire")
    attach_wire(headline, args.smoke)
    # sparse-FTRL update A/B rides along (ROADMAP item 4): XLA rows
    # path vs the fused Pallas kernel, with the on-chip frac-of-peak
    # target stated in the record schema
    _beat("ftrl_sparse")
    attach_ftrl(headline, args.smoke)
    # serving-plane SLO bench rides along (open-loop p50/p99 + the
    # admission/coalescing evidence, doc/SERVING.md)
    _beat("serve")
    attach_serve(headline, args.smoke)
    # continuous-batching decode A/B rides along (batched-vs-sequential
    # tokens/s under churn + the device-replica-over-budget gate,
    # doc/SERVING.md "Continuous batching")
    _beat("decode_batching")
    attach_decode_batching(headline, args.smoke)
    # chaos-plane recovery drill rides along (kill-one-shard MTTR +
    # bit-parity + degraded/shed accounting, doc/ROBUSTNESS.md)
    _beat("recovery")
    attach_recovery(headline, args.smoke)
    # flight-recorder overhead A/B + ring state (doc/OBSERVABILITY.md
    # "Flight recorder & diagnostic bundles")
    _beat("blackbox")
    attach_blackbox(headline, args.smoke)
    # history-plane fold-hook overhead A/B + the live store snapshot
    # (doc/OBSERVABILITY.md "History plane")
    _beat("history")
    attach_history(headline, args.smoke)
    # learning truth plane (staleness vs τ, heat/shard balance,
    # convergence trajectory, divergence drill) — last among the
    # component sections; see attach_learning's harvest-order note
    _beat("learning")
    attach_learning(headline, args.smoke)
    # self-driving consistency A/B (adaptive τ + KKT filter + rollback
    # drill) — Postoffice-resetting, rides with learning at the tail
    _beat("consistency")
    attach_consistency(headline, args.smoke)
    # disclose which wire the e2e stream actually rode (the flip's
    # whole point is that BENCH_r06 stops quoting the raw bits bytes)
    headline["e2e_wire"] = {
        "wire_encode": conf.async_sgd.wire_encode or conf.async_sgd.wire,
        "update": conf.async_sgd.update,
        "wire_cache_mb": conf.async_sgd.wire_cache_mb,
        "max_delay": conf.async_sgd.max_delay,
    }
    _beat("e2e", **headline)

    # The host→device tunnel's bandwidth drifts by several x over minutes
    # (shared link), so a single long average is hostage to one throttled
    # stretch. Time fixed-size windows — each FLUSHED (scalar fetched, so
    # the device really finished) before its clock stops — and report the
    # MEDIAN window rate: robust to transient throttling in either
    # direction and not biased upward the way best-of-K would be. best/avg
    # are disclosed alongside.
    n_launches = max(1, args.steps // T)
    # each window flush pays a tunnel round trip and drains the pipeline;
    # keep windows >= 5 launches so the flush cost stays amortized
    window = max(5, n_launches // 5) if n_launches >= 5 else n_launches
    def host_parts():
        for i in range(n_launches * T):
            with telemetry_spans.span("bench.prep", phase="e2e"):
                part = worker.prep(raw[i % len(raw)], device_put=False)
            yield part

    # upload key cache on the e2e stream (stateful → single-owner: it
    # lives on the UploadPipeline's one staging thread). The synthetic
    # stream CYCLES a fixed batch pool, so repeated key/column arrays
    # re-use their device buffers — the cross-batch half of the wire
    # win, with shipped bytes accounted net of cache hits
    cache = None
    if encoded and conf.async_sgd.wire_cache_mb > 0:
        from parameter_server_tpu.learner.wire import UploadCache

        cache = UploadCache(max_bytes=conf.async_sgd.wire_cache_mb << 20)
    rates = []
    drift_samples = []  # (elapsed_s, window examples/sec) for live_drift
    done = 0
    wire_counter["bytes"] = 0  # count the TIMED phase only (not warmup)
    # warmup mark for the device inventory (see run_real): the timed
    # windows below must trigger zero new compiles
    from parameter_server_tpu.telemetry import device as _device_mod

    _device_mod.mark_warmup()
    e2e_wall0 = time.time()
    t0 = time.perf_counter()
    pending = []
    win_done, win_t0 = 0, t0
    # uploader thread overlaps localize/pack + the tunnel wire with the
    # device steps the main thread is waiting on (see UploadPipeline)
    for dev_sb, _n_ex, nb, fid in UploadPipeline(host_parts(), T, cache=cache):
        wire_counter["bytes"] += nb
        done += 1
        win_done += 1
        _beat()
        # the wait below may pay the staged transfer's wire time
        _grace_for_transfer(nb)
        with telemetry_spans.flow_scope(fid):
            pending.append(worker._submit_prepped(dev_sb, with_aux=False))
        if len(pending) > 2:
            worker.executor.wait(pending.pop(0))
        if win_done >= window:
            while pending:
                worker.executor.wait(pending.pop(0))
            flush(worker)
            now = time.perf_counter()
            rates.append(win_done * T * args.minibatch / (now - win_t0))
            drift_samples.append((now - t0, rates[-1]))
            win_done, win_t0 = 0, now
    for ts in pending:
        worker.executor.wait(ts)
    flush(worker)
    dt = time.perf_counter() - t0
    e2e_wall1 = time.time()
    done *= T

    avg_rate = done * args.minibatch / dt
    e2e_rate = float(np.median(rates)) if rates else avg_rate

    rec = {
        "metric": _WATCHDOG.metric,  # see run_real's note
        "unit": "examples/sec",
        "e2e_median_window": round(e2e_rate, 1),
        "e2e_vs_baseline": round(e2e_rate / REF_8NODE_EXAMPLES_PER_SEC, 3),
        "avg": round(avg_rate, 1),
        "best": round(max(rates), 1) if rates else None,
    }
    rec.update(headline)
    if cache is not None:
        rec["e2e_upload_cache"] = {
            "hits": cache.hits,
            "misses": cache.misses,
            "saved_mb": round(cache.saved_bytes / 1e6, 1),
        }
    reconcile_link_ceiling(
        rec, wire_counter["bytes"], done * args.minibatch, dt
    )
    # the run worker's OWN learning plane, harvested after the timed
    # windows so its staleness/trajectory view covers the e2e phase
    attach_learning_run(rec, worker)
    # live steady-state drift: the run's tail windows vs its own
    # post-warmup baseline (doc/OBSERVABILITY.md "History plane")
    attach_history_drift(rec, drift_samples)
    # device truth plane AFTER the timed windows (post-warmup
    # recompiles cover the phase that must not re-specialize)
    attach_device(rec, args.smoke)
    attach_attribution(rec, trace_path, (e2e_wall0, e2e_wall1))
    _finish(rec)
    return 0


if __name__ == "__main__":
    sys.exit(main())
