#!/usr/bin/env python
"""Headline benchmark: Criteo-style sparse logistic regression (async FTRL).

Mirrors the reference's flagship workload (example/linear criteo
online_l1lr: async SGD + FTRL + L1, BASELINE.json) on TPU: the fused SPMD
step in apps/linear/async_sgd.py — pull(gather+psum) → Xw/grad segment-sums
→ push(scatter+psum) → FTRL dense update — driven by a host prefetch thread
doing localization, so device steps and host prep overlap exactly like the
reference's MinibatchReader producer/consumer.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: BASELINE.json publishes no number for the 8-node ZMQ cluster; we
use 500k examples/sec as the documented estimate for 8-node async FTRL on
Criteo-scale data (order of magnitude from the parameter-server OSDI'14
evaluation: ~65k examples/sec/node with sparse LR at ~100 nnz/example).
"""

import argparse
import json
import sys
import time

import numpy as np

REF_8NODE_EXAMPLES_PER_SEC = 500_000.0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny quick run (CI)")
    ap.add_argument("--minibatch", type=int, default=16384)
    # criteo shape: 13 numeric + 26 categorical = 39 features/example,
    # categorical dominating (binary). We bench the binary/ELL hot path.
    ap.add_argument("--nnz-per-row", type=int, default=39)
    ap.add_argument("--num-slots", type=int, default=1 << 22)
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--warmup", type=int, default=8)
    args = ap.parse_args()
    if args.smoke:
        args.minibatch, args.steps, args.warmup = 1024, 10, 2
        args.num_slots = 1 << 16

    import jax

    from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker
    from parameter_server_tpu.apps.linear.config import (
        Config,
        LearningRateConfig,
        PenaltyConfig,
        SGDConfig,
    )
    from parameter_server_tpu.parallel import mesh as meshlib
    from parameter_server_tpu.system.postoffice import Postoffice
    from parameter_server_tpu.utils.sparse import random_sparse

    Postoffice.reset()
    po = Postoffice.instance().start()  # all local devices, 1 server axis
    n_workers = meshlib.num_workers(po.mesh)

    conf = Config()
    conf.penalty = PenaltyConfig(type="l1", lambda_=[1.0])
    conf.learning_rate = LearningRateConfig(type="decay", alpha=0.1, beta=1.0)
    conf.async_sgd = SGDConfig(
        algo="ftrl",
        minibatch=args.minibatch,
        num_slots=args.num_slots,
        max_delay=4,  # the reference criteo conf's bounded delay
        ell_lanes=args.nnz_per_row,
        # minimal wire: 22-bit slot stream + 1-bit labels, fused C++
        # hash→pack — both bytes and host cycles are the bottleneck here
        wire="bits",
    )
    worker = AsyncSGDWorker(conf, mesh=po.mesh)

    p_space = 1 << 24  # raw key universe (hashed into num_slots)

    def gen(i: int):
        b = random_sparse(
            args.minibatch, p_space, args.nnz_per_row, seed=i, binary=True
        )
        # cheap synthetic labels keyed off low-id features for signal
        b.y = np.where(
            (b.indices.reshape(args.minibatch, -1) % 1024 < 256).mean(1) > 0.24,
            1.0,
            -1.0,
        ).astype(np.float32)
        return b

    # pre-generate raw batches (parsing is benchmarked separately; the
    # reference criteo bench reads pre-tokenized minibatches similarly),
    # but run LOCALIZATION (hash→slot + u24 wire packing) + device upload
    # inside the timed loop — that's the honest host-side cost. The loop is
    # deliberately single-threaded: device_put is async, so transfers
    # overlap the next batch's host prep without helper threads (which
    # contend with the transfer engine for the GIL and *halve* throughput).
    raw = [gen(i) for i in range(min(args.steps + args.warmup, 16))]
    worker._padding(raw[0])

    def prep_upload_submit(i: int):
        # with_aux=False: skip the per-example AUC outputs in the hot loop
        prepped = worker.prep(raw[i % len(raw)], device_put=False)
        return worker._submit_prepped(jax.device_put(prepped), with_aux=False)

    # warmup (compile)
    pending = []
    for i in range(args.warmup):
        pending.append(prep_upload_submit(i))
    for ts in pending:
        worker.executor.wait(ts)

    # The host→device tunnel's bandwidth drifts by several x over minutes
    # (shared link), so a single long average is hostage to one throttled
    # stretch. Time fixed-size windows — each FLUSHED (pipeline drained +
    # state ready) before its clock stops, so a window is only credited
    # work that completed inside it — and report the MEDIAN window rate:
    # robust to transient throttling in either direction and not biased
    # upward the way a best-of-K pick would be. best/avg are disclosed
    # alongside.
    window = max(10, args.steps // 5)
    rates = []
    done = 0
    t0 = time.perf_counter()
    pending = []
    win_done, win_t0 = 0, t0
    while done < args.steps:
        pending.append(prep_upload_submit(done))
        done += 1
        win_done += 1
        if len(pending) > 3:
            worker.executor.wait(pending.pop(0))
        if win_done >= window:
            while pending:
                worker.executor.wait(pending.pop(0))
            jax.block_until_ready(worker.state)
            now = time.perf_counter()
            rates.append(win_done * args.minibatch / (now - win_t0))
            win_done, win_t0 = 0, now
    for ts in pending:
        worker.executor.wait(ts)
    jax.block_until_ready(worker.state)
    dt = time.perf_counter() - t0

    avg_rate = done * args.minibatch / dt
    examples_per_sec = float(np.median(rates)) if rates else avg_rate
    print(
        json.dumps(
            {
                "metric": "criteo_sparse_lr_examples_per_sec",
                "value": round(examples_per_sec, 1),
                "unit": "examples/sec",
                "vs_baseline": round(examples_per_sec / REF_8NODE_EXAMPLES_PER_SEC, 3),
                "avg": round(avg_rate, 1),
                "best": round(max(rates), 1) if rates else None,
                "note": "value = median flushed window; avg = whole run",
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
