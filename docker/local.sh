#!/usr/bin/env bash
# Run an N-container parameter_server_tpu job on ONE machine (ref
# docker/local.sh: num_servers + num_workers containers wired to a
# scheduler container over docker0).
#
# Containers join the jax.distributed rendezvous exactly like processes
# launched by script/local.sh: container 0 is the coordinator (the
# reference's scheduler) and the others dial it over the docker bridge
# network. Roles (server/worker) are mesh axes inside the SPMD program,
# so unlike the reference there is no per-role container — every
# container runs the same command.
#
# usage: docker/local.sh <num_hosts> <command...>
#   e.g. docker/local.sh 2 python -m parameter_server_tpu.apps.linear.main \
#          configs/rcv1.conf --num-servers 2
set -euo pipefail
N=${1:?usage: docker/local.sh <num_hosts> <command...>}; shift
IMAGE=${PS_IMAGE:-parameter-server-tpu}
PORT=${PS_PORT:-29450}
NET=${PS_NET:-psnet}
DEVS=${PS_LOCAL_DEVICES:-2}

docker network inspect "$NET" >/dev/null 2>&1 || docker network create "$NET"

cids=()
cleanup() { docker rm -f "${cids[@]}" >/dev/null 2>&1 || true; }
trap cleanup INT TERM EXIT

for ((i = N - 1; i >= 0; i--)); do
  cids+=("$(docker run -d --network "$NET" --name "ps-node-$i" \
    -e JAX_PLATFORMS=cpu \
    -e XLA_FLAGS="--xla_force_host_platform_device_count=${DEVS}" \
    -e PS_COORDINATOR_ADDRESS="ps-node-0:${PORT}" \
    -e PS_NUM_PROCESSES="$N" \
    -e PS_PROCESS_ID="$i" \
    "$IMAGE" "$@")")
done

# stream the coordinator's output; fail if any container fails
docker logs -f "ps-node-0" &
rc=0
for ((i = 0; i < N; i++)); do
  r=$(docker wait "ps-node-$i")
  if (( r != 0 && rc == 0 )); then rc=$r; docker logs "ps-node-$i" | tail -20; fi
done
exit "$rc"
