# parameter_server_tpu deployment image (ref /root/reference/Dockerfile:
# one container per node, role and cluster wiring injected via env).
#
# Build:   docker build -t parameter-server-tpu .
# One-box: docker run --rm parameter-server-tpu \
#            python -m parameter_server_tpu.apps.linear.main configs/rcv1.conf
# Cluster: run one container per host with the jax.distributed contract
#          (the analog of the reference's -scheduler/-my_node flags):
#            PS_COORDINATOR_ADDRESS=<host0>:<port>
#            PS_NUM_PROCESSES=<N>  PS_PROCESS_ID=<i>
#          On TPU hosts, pass the accelerator through (gcloud/k8s TPU
#          runtime) and leave JAX_PLATFORMS unset; off-TPU smoke runs use
#          JAX_PLATFORMS=cpu. See docker/ for local N-node compose.
FROM python:3.12-slim

# native host runtime (cpp/psnative.so) builds with g++ at image build
# time, like the reference's `RUN make -j8`
RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*

# the compute stack; `jax[tpu]` swaps in the TPU PJRT plugin on TPU VMs
# (kept as the only knob — everything else is pure Python)
ARG JAX_EXTRA=""
RUN pip install --no-cache-dir "jax${JAX_EXTRA}" flax optax orbax-checkpoint chex einops numpy

WORKDIR /home/parameter_server_tpu
COPY parameter_server_tpu parameter_server_tpu
COPY configs configs
COPY script script
COPY bench.py setup.py Makefile ./
RUN make native

ENV PYTHONPATH=/home/parameter_server_tpu
# role dispatch comes from the conf + env, exactly like the reference's
# CMD build/linear -my_node "role:$my_role,..." pattern
CMD ["python", "-m", "parameter_server_tpu.apps.linear.main", "--help"]
