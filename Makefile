# Top-level build (role of the reference's make/ directory)

.PHONY: all native native-test test bench bench-all bench-watch smoke lint pslint metrics-lint donation-lint mesh-test ingest-bench wire-bench stream-prep-bench serve-bench decode-bench ftrl-bench chaos-bench rebalance-bench learning-bench consistency-bench history-bench roofline trace bundle bench-diff metrics-serve clean

all: native

native:
	$(MAKE) -C parameter_server_tpu/cpp

# native-vs-Python parity, REQUIRING the library: the tier-1 suite
# skips the C-parity tests gracefully when libpsnative.so is absent
# (a CPU-only checkout must still pass), but THIS target builds the
# lib and fails LOUDLY if it is missing or the fused-prep / codec
# outputs diverge from the Python paths — run it wherever native is
# expected to exist (the bench container, the on-chip watcher host)
native-test: native
	env JAX_PLATFORMS=cpu PS_REQUIRE_NATIVE=1 python -m pytest \
		tests/test_wire.py -k "stream or native or staging" \
		-q -p no:cacheprovider
	env JAX_PLATFORMS=cpu PS_REQUIRE_NATIVE=1 python -m pytest \
		tests/test_codec.py -q -p no:cacheprovider

test: native
	python -m pytest tests/ -x -q

bench: native
	python bench.py

# one-shot on-chip evidence suite: probe the device; if reachable run
# every pending task (flash-kernel Mosaic validation, bench, bench
# --real, component benches, LM tokens/s+MFU, table-scale probe) and
# append results to BENCH_ONCHIP.md
bench-all: native
	python script/onchip.py --once

# persistent tunnel watcher: retries bench-all whenever the device
# becomes reachable (the tunnel wedges transiently — see README)
bench-watch: native
	python script/onchip.py --watch

smoke: native
	python bench.py --smoke

# the full static-analysis suite (script/pslint/, doc/STATIC_ANALYSIS.md):
# lock-discipline race detector (+ lock-order deadlock cycles),
# thread-lifecycle, jit-purity, donation, metrics, spans, plus the v2
# interprocedural passes — use-after-donate dataflow, thread-affinity,
# determinism, cross-artifact consistency — one engine, one findings
# report (`path:line rule message`, editor-clickable), exit 1 on any
# unsuppressed finding. --timings prints per-pass wall-clock and cache
# hit counts; --budget fails the target (exit 2) if the suite drifts
# past its stated wall-clock (cold run is ~7s; per-file passes cache
# by content hash in .pslint-cache.json, gitignored). Fast, no
# accelerator; also a tier-1 test in tests/test_pslint.py.
pslint:
	python script/pslint/cli.py --timings --budget 60

# the multi-device partitioning suite on a FORCED 8-device CPU
# platform: partitioner spec resolution, mesh auto-shaping (8 -> 4x2,
# never 3x2-with-2-idle), the sharded-table parity tests, and the
# live-rebalance / migration drills — multi-chip paths exercised on
# every dev box, not only when silicon appears (tier-1: the same
# tests run under tests/ via conftest's forced device count)
mesh-test:
	env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m pytest tests/test_partition.py tests/test_rebalance.py \
		-q -p no:cacheprovider

# all static checks + the multi-device partitioning suite (mesh-test
# rides along so layout changes can't pass lint while breaking the
# 8-device paths)
lint: pslint mesh-test

# alias: the telemetry-catalog pass alone (duplicate / non-snake_case
# names, naming drift, unparseable exposition; also a tier-1 test in
# tests/test_telemetry.py)
metrics-lint:
	python script/pslint/cli.py --rules metrics

# alias: the donation pass alone — every data-plane jit site either
# donates its table buffers or justifies not doing so (# no-donate:),
# the defensive-copy trap guard (also a tier-1 test in
# tests/test_donation.py)
donation-lint:
	python script/pslint/cli.py --rules donation

# serial-vs-pipelined host-ingest A/B (components bench): one JSON
# summary line per metric — serial/pipelined examples/sec + the median
# paired speedup (fast, CPU-only, no accelerator; the same A/B is
# embedded in every bench.py record under "host_ingest")
ingest-bench: native
	env JAX_PLATFORMS=cpu python -m parameter_server_tpu.benchmarks host_ingest

# compact-wire encoded-vs-raw A/B (components bench): bytes/example
# per encoding at the headline shape, multi-pass amortized bytes
# through the upload key cache, exact-mode parity, encode cost (fast,
# CPU-only; the same A/B is embedded in every bench.py record under
# "wire" with per-encoding link-bound ceilings)
wire-bench: native
	env JAX_PLATFORMS=cpu python -m parameter_server_tpu.benchmarks wire

# native-vs-Python fused stream-prep A/B (components bench): the one
# C ABI call (hash→per-lane unique→remap→bit-pack) against the NumPy
# passes it replaces — byte-identical output asserted, median paired
# speedup disclosed (also embedded in wire_ab under "fused_prep")
stream-prep-bench: native
	env JAX_PLATFORMS=cpu python -m parameter_server_tpu.benchmarks stream_prep

# FTRL update-path benches (components): the sparse-touched XLA-rows
# vs fused-Pallas-kernel A/B (embedded in every bench.py record under
# "ftrl_sparse", with hbm_gb_s / frac-of-peak and the on-chip 10x
# target), and the dense-formulation 8-update chain A/B whose
# ftrl_dense_*_chain_* captures re-judge ops/ftrl.xla_min_slots.
# CPU-runnable (fused arm falls back — shape truth, not a headline);
# the on-chip watcher runs both via `make bench-all`.
ftrl-bench: native
	env JAX_PLATFORMS=cpu python -m parameter_server_tpu.benchmarks ftrl_sparse_ab
	env JAX_PLATFORMS=cpu python -m parameter_server_tpu.benchmarks ftrl_chain

# request-path serving SLO bench (components bench): open-loop Poisson
# load against the serving frontend — p50/p99/p99.9 at >=2 offered-load
# points, admission on/off A/B (bounded p99 under overload vs queue
# collapse), coalescing merge factor, speculative-decode lane (fast,
# CPU-runnable, self-calibrating rates; the same dict is embedded in
# every bench.py record under "serve")
serve-bench: native
	env JAX_PLATFORMS=cpu python -m parameter_server_tpu.benchmarks serve

# continuous-batching decode A/B (components bench, doc/SERVING.md
# "Continuous batching"): batched vs sequential speculative decode
# tokens/s at each slot count under join/leave churn — wave admission +
# fused round blocks, token parity asserted in-bench, plus the
# device-resident replica serving a table over the host budget with
# zero degrades (the same dict is embedded in every bench.py record
# under "decode_batching")
decode-bench: native
	env JAX_PLATFORMS=cpu python -m parameter_server_tpu.benchmarks decode_batching

# chaos-plane recovery drill (components bench, doc/ROBUSTNESS.md):
# kill a server shard via injected heartbeat silence under concurrent
# train+serve load — detection/recovery/MTTR, requests
# degraded/shed/failed, replayed-update count, and the post-recovery
# trajectory bit-parity verdict vs an undisturbed run (fast,
# CPU-runnable, deterministic under the drill seed; the same dict is
# embedded in every bench.py record under "recovery")
chaos-bench: native
	env JAX_PLATFORMS=cpu python -m parameter_server_tpu.benchmarks recovery_drill

# heat-driven live-repartitioning drill (components bench,
# doc/PERFORMANCE.md "Declarative partitioning"): a heat-skewed
# workload drives the shipped shard_imbalance alert to firing, the
# RebalanceController recomputes slot ownership from the measured
# hot-slot/load-share tables and migrates rows online through the
# consistent-snapshot machinery — serve stream completes every request
# across the move, post-rebalance imbalance re-measured below the
# alert threshold, post-migration table bit-identical to an
# undisturbed run (8 forced CPU devices, deterministic)
rebalance-bench:
	env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m parameter_server_tpu.benchmarks rebalance

# learning truth plane probe (components bench, doc/OBSERVABILITY.md
# "Learning truth plane"): a bounded-delay training run through the
# collect path — realized staleness vs the configured τ (asserted),
# sketch-vs-exact key-heat parity, per-shard load shares + imbalance,
# the loss/grad-norm trajectory from the in-jit side outputs, and the
# seeded LR-blow-up divergence drill (shipped loss_divergence rule to
# firing with a diagnostic bundle attached). Fast, CPU-only; the same
# dict is embedded in every bench.py record under "learning"
learning-bench:
	env JAX_PLATFORMS=cpu python -m parameter_server_tpu.benchmarks learning

# self-driving consistency A/B (components bench, doc/PERFORMANCE.md
# "Consistency–throughput frontier"): fixed τ=0 vs fixed τ=max vs the
# adaptive controller on one planted-regression workload (paired-rep
# medians, emulated pull RTT disclosed in-record), the KKT-style
# significance filter off/on with its suppression accounting
# reconciled against ps_push_keys_total, and the seeded divergence
# drill through the controller's LR-backoff + snapshot-rollback
# reaction (episode captured in one flight-recorder bundle). Full
# record lands at $PS_CONSISTENCY_OUT (default /tmp/ps_consistency.json)
consistency-bench:
	env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m parameter_server_tpu.benchmarks consistency

# history plane overhead probe (components bench, doc/OBSERVABILITY.md
# "History plane"): the multi-resolution ring-cascade fold hook priced
# against the identical metric-churn workload without it — paired
# back-to-back reps (on, off, off, on), MEDIAN ratio quoted, plus the
# tight-loop per-fold cost over the full instrument catalog. The same
# dict is embedded in every bench.py record under "history"
history-bench:
	env JAX_PLATFORMS=cpu python -m parameter_server_tpu.benchmarks history_ab

# device truth plane probe (components bench, doc/OBSERVABILITY.md
# "Device truth plane"): an HBM-bound FTRL chain + a FLOPs-bound flash
# fwd through instrumented wrappers with per-dispatch roofline
# sampling — achieved GB/s / GFLOP/s per kernel against the XLA cost
# analysis, frac-of-peak where the peak tables know the chip, and the
# zero-steady-state-recompile sanity (fast, CPU-runnable; the full
# per-jit inventory is embedded in every bench.py record under
# "device")
roofline:
	env JAX_PLATFORMS=cpu python -m parameter_server_tpu.benchmarks roofline

# capture a short synthetic run's flow-correlated timeline and export
# it as Chrome trace / Perfetto JSON (open at https://ui.perfetto.dev;
# doc/OBSERVABILITY.md "Reading a timeline"). Override the output with
# PS_TRACE_OUT=/path.json; the raw JSONL span stream lands next to it
trace:
	env JAX_PLATFORMS=cpu PS_TRACE_OUT=$${PS_TRACE_OUT:-/tmp/ps_timeline_trace.json} \
		python -m parameter_server_tpu.benchmarks trace
	@echo "timeline: $${PS_TRACE_OUT:-/tmp/ps_timeline_trace.json} (open at https://ui.perfetto.dev)"

# capture a diagnostic bundle from a live mini-cluster
# (doc/OBSERVABILITY.md "Flight recorder & diagnostic bundles"): the
# flight-recorder rings of every node (one deliberately silent ->
# marked stale), metrics snapshot, alert states, executor state, and a
# Perfetto-ready trace — the same artifact an alert firing, a
# DegradedError, a shard death, or a wedged executor wait auto-captures,
# and what /debug/bundle serves live. Override the output with
# PS_BUNDLE_OUT=/path.json
bundle:
	env JAX_PLATFORMS=cpu PS_BUNDLE_OUT=$${PS_BUNDLE_OUT:-/tmp/ps_bundle.json} \
		python -m parameter_server_tpu.benchmarks bundle
	@echo "bundle: $${PS_BUNDLE_OUT:-/tmp/ps_bundle.json} (open its 'trace' member at https://ui.perfetto.dev)"

# cluster metrics plane demo (doc/OBSERVABILITY.md "Cluster metrics
# plane"): a tiny live system on the CPU mesh with the full plane up —
# scrape http://127.0.0.1:$(METRICS_PORT)/metrics (also /healthz,
# /debug/snapshot) while it trains; default SLO alert rules from
# configs/alerts/default.json evaluate live. Ctrl-C stops it cleanly.
# The same endpoint rides any real run via `python bench.py
# --expose-port 9100` or `apps/serve ... --expose-port 9100`.
METRICS_PORT ?= 9100
metrics-serve:
	env JAX_PLATFORMS=cpu python -m parameter_server_tpu.telemetry.exposition --port $(METRICS_PORT)

# bench regression sentinel: compare the newest valid BENCH_r*.json
# against the prior trajectory (median-of-priors baseline, tolerance
# band from the trajectory's own spread — ROADMAP bench discipline);
# exit 1 on an out-of-band throughput regression (tier-1 tested
# against fixture records in tests/data/bench_diff/)
bench-diff:
	python script/bench_diff.py

clean:
	$(MAKE) -C parameter_server_tpu/cpp clean
	find . -name __pycache__ -type d -exec rm -rf {} +
