# Top-level build (role of the reference's make/ directory)

.PHONY: all native test bench bench-all bench-watch smoke metrics-lint donation-lint ingest-bench clean

all: native

native:
	$(MAKE) -C parameter_server_tpu/cpp

test: native
	python -m pytest tests/ -x -q

bench: native
	python bench.py

# one-shot on-chip evidence suite: probe the device; if reachable run
# every pending task (flash-kernel Mosaic validation, bench, bench
# --real, component benches, LM tokens/s+MFU, table-scale probe) and
# append results to BENCH_ONCHIP.md
bench-all: native
	python script/onchip.py --once

# persistent tunnel watcher: retries bench-all whenever the device
# becomes reachable (the tunnel wedges transiently — see README)
bench-watch: native
	python script/onchip.py --watch

smoke: native
	python bench.py --smoke

# validate the telemetry metric catalog: duplicate / non-snake_case
# names, naming-convention drift, unparseable exposition (fast, no
# accelerator; also runs as a tier-1 test in tests/test_telemetry.py)
metrics-lint:
	python script/metrics_lint.py

# statically verify every data-plane jit site either donates its table
# buffers or justifies not doing so (# no-donate:) — the defensive-copy
# trap guard (fast, no accelerator; also a tier-1 test in
# tests/test_donation.py)
donation-lint:
	python script/donation_lint.py

# serial-vs-pipelined host-ingest A/B (components bench): one JSON
# summary line per metric — serial/pipelined examples/sec + the median
# paired speedup (fast, CPU-only, no accelerator; the same A/B is
# embedded in every bench.py record under "host_ingest")
ingest-bench: native
	env JAX_PLATFORMS=cpu python -m parameter_server_tpu.benchmarks host_ingest

clean:
	$(MAKE) -C parameter_server_tpu/cpp clean
	find . -name __pycache__ -type d -exec rm -rf {} +
