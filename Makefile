# Top-level build (role of the reference's make/ directory)

.PHONY: all native test bench smoke clean

all: native

native:
	$(MAKE) -C parameter_server_tpu/cpp

test: native
	python -m pytest tests/ -x -q

bench: native
	python bench.py

smoke: native
	python bench.py --smoke

clean:
	$(MAKE) -C parameter_server_tpu/cpp clean
	find . -name __pycache__ -type d -exec rm -rf {} +
