"""parameter_server_tpu build (role of the reference's make/ build system).

Builds the C++ host library (crc32c, hashing, text parsers) as part of the
package; pure-stdlib build so no pip installs are needed.

    python setup.py build_native   # or: make -C parameter_server_tpu/cpp
    pip install -e .               # optional editable install
"""

import subprocess
from pathlib import Path

from setuptools import Command, find_packages, setup


class BuildNative(Command):
    description = "build the C++ host library (libpsnative.so)"
    user_options = []

    def initialize_options(self):
        pass

    def finalize_options(self):
        pass

    def run(self):
        cpp = Path(__file__).parent / "parameter_server_tpu" / "cpp"
        subprocess.run(["make", "-C", str(cpp)], check=True)


setup(
    name="parameter_server_tpu",
    version="0.1.0",
    description=(
        "TPU-native parameter server framework: sparse linear learners "
        "(async FTRL, darlin block proximal gradient), KV containers over "
        "jax device meshes, NN training through KVLayer, ring attention"
    ),
    packages=find_packages(exclude=("tests",)),
    package_data={"parameter_server_tpu.cpp": ["*.cc", "Makefile"]},
    python_requires=">=3.10",
    # jax/flax/optax/orbax are environment-provided (TPU image); no pins here
    cmdclass={"build_native": BuildNative},
)
