#!/bin/bash
# Regenerate doc/API_REFERENCE.md (ref doc/gendoc.sh runs doxygen).
dir=$(dirname "$0")
exec python "$dir/gendoc.py"
