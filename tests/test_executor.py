"""Executor DAG semantics (ref src/system/executor.{h,cc} +
task_tracker.h): logical clocks, wait_time dependencies, bounded-delay
throttling, and the race-detection asserts of SURVEY §5 (a step may not
depend on a timestamp at/after its own; timestamps cannot be reused)."""

import numpy as np
import pytest

from parameter_server_tpu.system.executor import Executor, TaskTracker
from parameter_server_tpu.system.message import Task


class TestTaskTracker:
    def test_start_finish_cycle(self):
        t = TaskTracker()
        assert not t.was_started(3) and not t.is_finished(3)
        t.start(3)
        assert t.was_started(3) and not t.is_finished(3)
        t.finish(3)
        assert t.is_finished(3)


class TestExecutor:
    def test_timestamps_monotonic(self):
        ex = Executor()
        ts = [ex.submit(lambda: None) for _ in range(3)]
        assert ts == [0, 1, 2]

    def test_wait_returns_value_once(self):
        ex = Executor()
        ts = ex.submit(lambda: 42)
        assert ex.wait(ts) == 42
        assert ex.wait(ts) is None  # evicted after first wait

    def test_dependencies_run_first(self):
        ex = Executor()
        order = []
        t0 = ex.submit(lambda: order.append("a"))
        t1 = ex.submit(lambda: order.append("b"), Task(wait_time=[t0]))
        ex.wait(t1)
        assert order == ["a", "b"]
        assert ex.tracker.is_finished(t0)  # dep was waited, not just queued

    def test_forward_dependency_rejected(self):
        """Race-detection: a step cannot read a snapshot newer than itself
        (dep >= own timestamp is a program error, not a silent reorder)."""
        ex = Executor()
        ex.submit(lambda: None)
        with pytest.raises(ValueError, match="not before"):
            ex.submit(lambda: None, Task(time=5, wait_time=[7]))

    def test_timestamp_reuse_rejected(self):
        ex = Executor()
        ts = ex.submit(lambda: 1, Task(time=4))
        with pytest.raises(ValueError, match="already used"):
            ex.submit(lambda: 2, Task(time=4))
        assert ex.wait(ts) == 1

    def test_explicit_timestamp_advances_clock(self):
        ex = Executor()
        ex.submit(lambda: None, Task(time=10))
        assert ex.submit(lambda: None) == 11

    def test_bounded_delay_throttles(self):
        """max_in_flight=2: submitting step t blocks until t-2 finished —
        the reference's bounded-delay message-clock window."""
        ex = Executor(max_in_flight=2)
        done = []
        for i in range(5):
            ex.submit(lambda i=i: done.append(i))
        # with the sliding window, step 4's submit waited on step 2;
        # everything up to 2 must be finished already
        assert ex.tracker.is_finished(2)
        ex.wait_all()
        assert done == list(range(5))

    def test_callback_fires_on_wait(self):
        ex = Executor()
        fired = []
        ts = ex.submit(lambda: 7, callback=lambda: fired.append(True))
        assert not fired
        ex.wait(ts)
        assert fired == [True]

    def test_wait_all_drains(self):
        ex = Executor()
        for i in range(4):
            ex.submit(lambda i=i: np.zeros(2) + i)
        ex.wait_all()
        assert all(ex.tracker.is_finished(t) for t in range(4))

    def test_step_exception_propagates_to_waiter(self):
        ex = Executor()
        ts = ex.submit(lambda: 1 / 0)
        with pytest.raises(ZeroDivisionError):
            ex.wait(ts)


class TestOutOfOrderDispatch:
    """ref executor.cc PickActiveMsg: a received message whose wait_time
    deps are unmet must NOT block later messages that are ready — the
    engine picks any active message out of order."""

    def test_ready_step_overtakes_blocked_one(self):
        import threading as th

        ex = Executor()
        gate = th.Event()
        independent_ran = th.Event()
        order = []

        t0 = ex.submit(lambda: (gate.wait(5), order.append("slow"))[1])
        t1 = ex.submit(lambda: order.append("dependent"), Task(wait_time=[t0]))
        t2 = ex.submit(
            lambda: (order.append("independent"), independent_ran.set())[0]
        )
        # t0 occupies the dispatch thread until the gate opens; t1 waits
        # on t0; t2 has no deps. Once t0's step returns, the dispatcher
        # must pick the ready t2 before it resolves t1's dependency.
        # Synchronize on that EVENT rather than racing wait_all()
        # against the dispatch thread: a wait_all() entered early can
        # itself finish t0 (materialize + promote) and push t1 into the
        # ready heap before t2 was ever picked — the load flake this
        # test used to have (ROADMAP).
        gate.set()
        assert independent_ran.wait(5), "independent step never dispatched"
        ex.wait_all()
        assert order.index("independent") < order.index("dependent")
        assert order[-1] == "dependent"

    def test_interleaved_customers_make_progress(self):
        """Two logical task chains through one executor: chain A's steps
        depend on each other; chain B is independent and must interleave
        without waiting for A's chain to drain."""
        ex = Executor()
        log = []
        a_prev = ex.submit(lambda: log.append("A0"))
        for i in range(1, 3):
            a_prev = ex.submit(
                lambda i=i: log.append(f"A{i}"), Task(wait_time=[a_prev])
            )
        b_ts = [ex.submit(lambda i=i: log.append(f"B{i}")) for i in range(3)]
        ex.wait_all()
        assert sorted(log) == ["A0", "A1", "A2", "B0", "B1", "B2"]
        # A-chain order respected
        ia = [log.index(f"A{i}") for i in range(3)]
        assert ia == sorted(ia)

    def test_submit_does_not_block_on_deps(self):
        import time as _time

        ex = Executor()
        t0 = ex.submit(lambda: _time.sleep(0.2))
        start = _time.monotonic()
        ex.submit(lambda: None, Task(wait_time=[t0]))
        elapsed = _time.monotonic() - start
        assert elapsed < 0.1, "submit must enqueue, not wait for deps"
        ex.wait_all()

    def test_dispatched_in_flight_telemetry(self):
        ex = Executor()
        for i in range(4):
            ex.submit(lambda: None)
        ex.wait_all()
        assert ex.max_dispatched_in_flight >= 1

    def test_wait_all_drains_currently_executing_step(self):
        import threading as th

        ex = Executor()
        entered = th.Event()
        done = []

        def slow():
            entered.set()
            import time as _t

            _t.sleep(0.15)
            done.append(1)

        ex.submit(slow)
        entered.wait(5)  # the step is mid-execution on the dispatch thread
        ex.wait_all()
        assert done == [1], "wait_all must include the running step"

    def test_wait_all_pop_false_preserves_results(self):
        ex = Executor()
        ts = ex.submit(lambda: 41)
        ex.wait_all(pop=False)
        assert ex.tracker.is_finished(ts)
        assert ex.wait(ts) == 41  # still claimable after the drain

    def test_stop_cancels_pending_and_joins(self):
        import threading as th

        ex = Executor()
        gate = th.Event()
        entered = th.Event()
        ran = []

        def first():
            entered.set()
            gate.wait(5)
            ran.append("first")

        ex.submit(first)
        ex.submit(lambda: ran.append("second"))
        entered.wait(5)  # ensure the first step is executing before stop
        gate.set()
        ex.stop()  # joins; the executing step completes, pending is dropped
        assert "first" in ran
        assert ex._thread is None or not ex._thread.is_alive()


class TestReadyQueueDispatch:
    """Round-5 dependency-counted dispatch: promotion and cancellation
    seams of the ready heap (the burst-scaling win itself is measured
    by `benchmarks executor`: 2.7k -> 114k steps/s at a 5000-burst)."""

    def test_dependent_promoted_when_dep_finishes_via_wait(self):
        import threading

        ex = Executor("promote")
        gate = threading.Event()
        t1 = ex.submit(lambda: gate.wait(10))
        done = []
        t2 = ex.submit(lambda: done.append(1), task=Task(wait_time=[t1]))
        # t2 must not run while t1 blocks
        import time

        time.sleep(0.2)
        assert not done
        gate.set()
        ex.wait(t2)
        assert done == [1]
        ex.stop()

    def test_cancelled_steps_leave_no_stale_dispatch(self):
        ex = Executor("cancel")
        import threading

        gate = threading.Event()
        t1 = ex.submit(lambda: gate.wait(10))
        ran = []
        ex.submit(lambda: ran.append("dependent"),
                  task=Task(wait_time=[t1]))
        ex.submit(lambda: ran.append("free"))
        ex.stop(cancel_pending=True)  # drops both pending steps
        gate.set()
        # a fresh submit restarts the thread; cancelled entries in the
        # heap/dependents maps must not resurrect or crash dispatch
        t4 = ex.submit(lambda: ran.append("after"))
        ex.wait(t4)
        assert "after" in ran and "dependent" not in ran
        ex.stop()


def test_external_tracker_finish_still_dispatches_dependent():
    """Customer.reply finishes timestamps via tracker.finish directly,
    bypassing _finish's heap promotion — the dispatch loop must
    self-heal instead of spinning forever on the blocked step."""
    import threading
    import time

    ex = Executor("ext-finish")
    gate = threading.Event()
    t1 = ex.submit(lambda: gate.wait(10))
    # wait for t1 to be RUNNING so t2 registers as its dependent
    deadline = time.time() + 5
    while not ex.tracker.was_started(t1) and time.time() < deadline:
        time.sleep(0.01)
    done = []
    t2 = ex.submit(lambda: done.append(1), task=Task(wait_time=[t1]))
    gate.set()
    ex.wait(t1)  # normal path finishes t1 (promotes t2)
    ex.wait(t2)
    assert done == [1]

    # now the external path: a dep finished ONLY through tracker.finish
    ex2 = Executor("ext-finish-2")
    gate2 = threading.Event()
    d1 = ex2.submit(lambda: gate2.wait(10))
    while not ex2.tracker.was_started(d1) and time.time() < deadline + 10:
        time.sleep(0.01)
    done2 = []
    d2 = ex2.submit(lambda: done2.append(1), task=Task(wait_time=[d1]))
    gate2.set()
    # drain d1's future WITHOUT ex2.wait: external finish like
    # customer.reply
    while ex2.result(d1) is None:
        time.sleep(0.01)
    ex2.tracker.finish(d1)
    with ex2._cv:
        ex2._futures.pop(d1, None)
        ex2._cv.notify_all()
    ex2.wait(d2)  # must not hang
    assert done2 == [1]
    ex.stop()
    ex2.stop()


def test_reused_timestamp_after_cancel_respects_fresh_deps():
    """A stale ready-heap entry for a cancelled explicit timestamp must
    not dispatch that timestamp's REINCARNATION past its fresh deps."""
    import threading
    import time

    ex = Executor("reuse")
    # ts 7 must be cancelled BEFORE dispatch, or its reincarnation is
    # (correctly) rejected as "already used" — which used to flake this
    # test ~40% of runs: the dispatch thread raced the stop() and ran
    # the instant lambda first. Pin the dispatch thread inside an
    # earlier step for the whole cancel window instead.
    hold = threading.Event()
    running = threading.Event()
    ex.submit(lambda: (running.set(), hold.wait(10)), task=Task(time=3))
    running.wait(10)  # dispatch thread is now INSIDE step 3
    ex.submit(lambda: None, task=Task(time=7))  # ready, never dispatched
    threading.Timer(0.05, hold.set).start()  # unblocks stop()'s join
    ex.stop(cancel_pending=True)
    # reincarnate ts 7, now blocked on a slow dep 6
    gate = threading.Event()
    order = []
    ex.submit(lambda: (gate.wait(10), order.append(6)), task=Task(time=6))
    ex.submit(lambda: order.append(7), task=Task(time=7, wait_time=[6]))
    time.sleep(0.3)
    assert order == []  # 7 must NOT have run ahead of its dep
    gate.set()
    ex.wait(7)
    assert order == [6, 7]
    ex.stop()
