"""Executor DAG semantics (ref src/system/executor.{h,cc} +
task_tracker.h): logical clocks, wait_time dependencies, bounded-delay
throttling, and the race-detection asserts of SURVEY §5 (a step may not
depend on a timestamp at/after its own; timestamps cannot be reused)."""

import numpy as np
import pytest

from parameter_server_tpu.system.executor import Executor, TaskTracker
from parameter_server_tpu.system.message import Task


class TestTaskTracker:
    def test_start_finish_cycle(self):
        t = TaskTracker()
        assert not t.was_started(3) and not t.is_finished(3)
        t.start(3)
        assert t.was_started(3) and not t.is_finished(3)
        t.finish(3)
        assert t.is_finished(3)


class TestExecutor:
    def test_timestamps_monotonic(self):
        ex = Executor()
        ts = [ex.submit(lambda: None) for _ in range(3)]
        assert ts == [0, 1, 2]

    def test_wait_returns_value_once(self):
        ex = Executor()
        ts = ex.submit(lambda: 42)
        assert ex.wait(ts) == 42
        assert ex.wait(ts) is None  # evicted after first wait

    def test_dependencies_run_first(self):
        ex = Executor()
        order = []
        t0 = ex.submit(lambda: order.append("a"))
        t1 = ex.submit(lambda: order.append("b"), Task(wait_time=[t0]))
        ex.wait(t1)
        assert order == ["a", "b"]
        assert ex.tracker.is_finished(t0)  # dep was waited, not just queued

    def test_forward_dependency_rejected(self):
        """Race-detection: a step cannot read a snapshot newer than itself
        (dep >= own timestamp is a program error, not a silent reorder)."""
        ex = Executor()
        ex.submit(lambda: None)
        with pytest.raises(ValueError, match="not before"):
            ex.submit(lambda: None, Task(time=5, wait_time=[7]))

    def test_timestamp_reuse_rejected(self):
        ex = Executor()
        ts = ex.submit(lambda: 1, Task(time=4))
        with pytest.raises(ValueError, match="already used"):
            ex.submit(lambda: 2, Task(time=4))
        assert ex.wait(ts) == 1

    def test_explicit_timestamp_advances_clock(self):
        ex = Executor()
        ex.submit(lambda: None, Task(time=10))
        assert ex.submit(lambda: None) == 11

    def test_bounded_delay_throttles(self):
        """max_in_flight=2: submitting step t blocks until t-2 finished —
        the reference's bounded-delay message-clock window."""
        ex = Executor(max_in_flight=2)
        done = []
        for i in range(5):
            ex.submit(lambda i=i: done.append(i))
        # with the sliding window, step 4's submit waited on step 2;
        # everything up to 2 must be finished already
        assert ex.tracker.is_finished(2)
        ex.wait_all()
        assert done == list(range(5))

    def test_callback_fires_on_wait(self):
        ex = Executor()
        fired = []
        ts = ex.submit(lambda: 7, callback=lambda: fired.append(True))
        assert not fired
        ex.wait(ts)
        assert fired == [True]

    def test_wait_all_drains(self):
        ex = Executor()
        for i in range(4):
            ex.submit(lambda i=i: np.zeros(2) + i)
        ex.wait_all()
        assert all(ex.tracker.is_finished(t) for t in range(4))
