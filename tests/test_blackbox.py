"""Cross-node tracing + flight recorder + diagnostic bundles (PR 14).

Covers the three tentpole pieces and their satellites:

- trace context over the Van: ``Task.trace`` stamped from the sending
  thread's flow, re-activated on the receiving side, validated against
  hostile blobs, tolerant of legacy headers (rolling upgrades);
- per-peer clock-offset estimation from report round trips;
- the multi-node timeline merge (node-tagged threads, flow namespacing
  by origin, per-node Perfetto processes, cross-node flow arrows) and
  the ``network`` attribution category cross-checked against a hand
  breakdown on a transfer-bound synthetic trace;
- the flight recorder ring (bounded, lock-annotated, zero file IO) and
  its metrics-delta samples;
- diagnostic bundles: capture contents, Van-fetched rings with
  staleness for silent nodes, the trigger plane (rate limit, wedged
  executor wait, degraded serving), the /debug/bundle endpoint, and
  the concurrent-scrape floor (no message-plane re-drives).
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from parameter_server_tpu.system import faults
from parameter_server_tpu.system.heartbeat import ClockSync
from parameter_server_tpu.system.message import Message, Task
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.system.remote_node import RemoteNode
from parameter_server_tpu.telemetry import attribution as attribution_mod
from parameter_server_tpu.telemetry import blackbox
from parameter_server_tpu.telemetry import spans as telemetry_spans
from parameter_server_tpu.telemetry import timeline as timeline_mod


@pytest.fixture(autouse=True)
def hermetic():
    Postoffice.reset()
    faults.reset()
    blackbox.reset()
    before = set(threading.enumerate())
    yield
    faults.reset()
    blackbox.reset()
    Postoffice.reset()
    deadline = time.time() + 5
    while time.time() < deadline:
        leaked = [
            t for t in set(threading.enumerate()) - before if t.is_alive()
        ]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, f"leaked threads: {leaked}"


def _get(url, timeout=10):
    return urllib.request.urlopen(url, timeout=timeout)


# ---------------------------------------------------------------------------
# trace context over the Van
# ---------------------------------------------------------------------------


class TestTraceContext:
    def test_van_stamps_flow_and_span(self, tmp_path):
        po = Postoffice.instance().start()
        path = str(tmp_path / "trace.jsonl")
        prev = telemetry_spans.install_sink(telemetry_spans.JsonlSink(path))
        try:
            fid = telemetry_spans.new_flow()
            with telemetry_spans.flow_scope(fid):
                out = po.van.transfer(
                    RemoteNode("W0"), RemoteNode("H0"),
                    Message(task=Task(), sender="W0", recver="H0"),
                )
        finally:
            mine = telemetry_spans.install_sink(prev)
            if mine is not None:
                mine.close()
        # the decoded message carries the context (validated on decode)
        assert out.task.trace["flow"] == fid
        assert out.task.trace["node"] == telemetry_spans.node_id()
        assert out.task.trace["t_send"] == pytest.approx(time.time(), abs=60)
        # the wire leg is a span on the same flow, with its frame bytes
        evs = timeline_mod.load_events(path)
        van = [e for e in evs if e["name"] == "van.transfer"]
        assert len(van) == 1
        assert van[0]["flow"] == fid
        assert van[0]["bytes"] > 0
        po.stop()

    def test_presets_respected(self):
        po = Postoffice.instance().start()
        preset = {"flow": 7, "node": "W3", "t_send": 1.0}
        out = po.van.transfer(
            RemoteNode("W3"), RemoteNode("H0"),
            Message(task=Task(trace=dict(preset)), sender="W3", recver="H0"),
        )
        assert out.task.trace == preset
        po.stop()

    @pytest.mark.parametrize(
        "trace",
        [
            ["flow", 1],                        # not a dict
            {"flow": "evil"},                   # non-int flow
            {"flow": 1, "extra": "x"},          # unknown key
            {"flow": -3},                       # out of range
            {"node": "x" * 65},                 # oversized node id
            {"t_send": float("inf")},           # non-finite time
            {"flow": True},                     # bool is not an int here
            {"node": 7},                        # non-str node
        ],
    )
    def test_hostile_trace_blob_rejected_loudly(self, trace):
        msg = Message(task=Task(), sender="A", recver="B")
        msg.task.trace = trace
        blob = msg.to_bytes()
        with pytest.raises(ValueError, match="trace context"):
            Message.from_bytes(blob)

    def test_numpy_scalar_flow_rejected(self):
        msg = Message(task=Task(), sender="A", recver="B")
        msg.task.trace = {"flow": np.int64(4)}
        with pytest.raises(ValueError, match="trace context"):
            Message.from_bytes(msg.to_bytes())

    def test_legacy_header_without_field_decodes(self):
        """Rolling-upgrade tolerance: a peer running the previous
        release pickles a Task with NO trace attribute at all —
        dataclass unpickling restores __dict__ verbatim, so the
        receiver must normalize, not crash."""
        t = Task()
        del t.__dict__["trace"]  # the pre-field wire shape
        blob = Message(task=t, sender="A", recver="B").to_bytes()
        out = Message.from_bytes(blob)
        assert out.task.trace is None

    def test_activate_trace_reenters_flow_with_origin(self):
        with telemetry_spans.activate_trace(
            {"flow": 41, "node": "W9", "t_send": 0.0}
        ):
            assert telemetry_spans.current_flow() == 41
            assert telemetry_spans.current_flow_node() == "W9"
        assert telemetry_spans.current_flow() is None
        # local origin needs no namespacing
        with telemetry_spans.activate_trace(
            {"flow": 5, "node": telemetry_spans.node_id()}
        ):
            assert telemetry_spans.current_flow_node() is None
        # no flow / legacy None: passthrough
        with telemetry_spans.activate_trace(None):
            assert telemetry_spans.current_flow() is None

    def test_rpc_flow_end_to_end(self, tmp_path):
        """The acceptance shape: ONE flow covers the submitting step,
        the Van leg, and work the receiver does — without any stage
        passing ids by hand."""
        import parameter_server_tpu.ps as ps

        path = str(tmp_path / "rpc.jsonl")
        prev = telemetry_spans.install_sink(telemetry_spans.JsonlSink(path))
        flows = []

        class Server(ps.App):
            def process_request(self, req):
                flows.append(telemetry_spans.current_flow())
                with telemetry_spans.span("server.handle"):
                    pass

        class Worker(ps.App):
            def run(self):
                fid = telemetry_spans.new_flow()
                flows.append(fid)
                with telemetry_spans.flow_scope(fid):
                    self.wait(ps.submit(self, Task()))

        def create_app():
            if ps.is_worker():
                return Worker()
            if ps.is_server():
                return Server()
            return ps.App()

        try:
            ps.run_system(create_app, num_workers=1, num_servers=1)
        finally:
            mine = telemetry_spans.install_sink(prev)
            if mine is not None:
                mine.close()
        # the handler observed the worker's flow id (re-activated
        # through the wire context + executor flow hand-off)
        worker_fid = flows[0]
        assert worker_fid in flows[1:]
        evs = timeline_mod.load_events(path)
        by_name = {}
        for e in evs:
            by_name.setdefault(e["name"], []).append(e)
        van_flows = {e.get("flow") for e in by_name.get("van.transfer", [])}
        handle_flows = {e.get("flow") for e in by_name.get("server.handle", [])}
        step_flows = {e.get("flow") for e in by_name.get("executor.step", [])}
        assert worker_fid in van_flows, "flow died at the Van"
        assert worker_fid in handle_flows, "flow died at the receiver"
        assert worker_fid in step_flows, "flow died at the executor"
        # and the Perfetto export draws arrows for that flow across the
        # threads it visited (worker thread -> dispatch thread)
        trace = timeline_mod.to_chrome_trace(evs)["traceEvents"]
        arrow_ids = {e["id"] for e in trace if e.get("ph") in ("s", "f")}
        assert worker_fid in arrow_ids, "no flow arrows drawn for the RPC"


# ---------------------------------------------------------------------------
# clock offsets
# ---------------------------------------------------------------------------


class TestClockSync:
    def test_offset_math_and_min_delay_retention(self):
        cs = ClockSync()
        cs.observe("W0", t_send=100.0, t_recv=102.0, delay_s=1.0)
        # offset = 102 - 1.0 - 100 = 1.0 (delay_s is the ONE-WAY
        # delivery estimate, subtracted whole — not halved)
        assert cs.offset("W0") == pytest.approx(1.0)
        # a noisier (bigger-delay) sample must NOT replace the estimate
        cs.observe("W0", t_send=100.0, t_recv=110.0, delay_s=4.0)
        assert cs.offset("W0") == pytest.approx(1.0)
        # a tighter exchange does
        cs.observe("W0", t_send=100.0, t_recv=101.2, delay_s=0.2)
        assert cs.offset("W0") == pytest.approx(1.0)
        snap = cs.snapshot()["W0"]
        assert snap["samples"] == 3
        assert snap["error_bound_s"] == pytest.approx(0.2)
        # nonsense (negative delay: a clock step mid-exchange) dropped
        cs.observe("W0", t_send=0.0, t_recv=0.0, delay_s=-1.0)
        assert cs.snapshot()["W0"]["samples"] == 3

    def test_measured_delay_cancels_out_of_the_offset(self):
        """The finding this contract encodes: a slow delivery (an
        injected van delay fault during a report) must NOT read as
        clock skew — the delay is measured and subtracted whole, so
        two synchronized clocks estimate ~0 regardless of how long the
        frame sat on the wire."""
        for delay in (0.001, 1.0, 5.0):  # same clock, slower wire
            cs = ClockSync()
            cs.observe("N", t_send=50.0, t_recv=50.0 + delay,
                       delay_s=delay)
            assert cs.offset("N") == pytest.approx(0.0, abs=1e-9)

    def test_aux_report_path_feeds_clock(self):
        po = Postoffice.instance().start()
        aux = po.start_aux(heartbeat_timeout=10.0)
        try:
            aux.register("W0")
            assert aux.report_node("W0")  # wire auto-detects the started po
            off = aux.clock.offset("W0")
            assert off is not None
            # single process: one clock — the offset must read ~zero
            assert abs(off) < 1.0
        finally:
            aux.stop()
            po.stop()


# ---------------------------------------------------------------------------
# multi-node timeline merge + network attribution
# ---------------------------------------------------------------------------


def _ev(name, t, dur, thread, flow=None, flow_node=None, **kw):
    ev = {"kind": "span", "name": name, "t_wall": t, "dur_s": dur,
          "thread": thread}
    if flow is not None:
        ev["flow"] = flow
    if flow_node is not None:
        ev["flow_node"] = flow_node
    ev.update(kw)
    return ev


class TestNodeMerge:
    def test_merge_tags_aligns_and_namespaces(self):
        # W0's clock runs 10s behind the scheduler's; both nodes used
        # local flow id 1 for DIFFERENT units, and W0's flow 1 also
        # appears on H0 (it crossed the Van, keeping flow_node="W0")
        events = {
            "H0": [
                _ev("a", 100.0, 0.1, "MainThread", flow=1),
                _ev("recv", 100.5, 0.1, "executor:x", flow=1,
                    flow_node="W0"),
            ],
            "W0": [_ev("send", 90.2, 0.1, "MainThread", flow=1)],
        }
        merged = timeline_mod.merge_node_events(events, {"W0": 10.0})
        by_name = {e["name"]: e for e in merged}
        # clock alignment: W0's 90.2 + 10.0 lands between H0's events
        assert by_name["send"]["t_wall"] == pytest.approx(100.2)
        # node-tagged threads + node field
        assert by_name["send"]["thread"] == "W0/MainThread"
        assert by_name["a"]["node"] == "H0"
        # flow namespacing: H0-local flow 1 != W0-origin flow 1, and
        # the Van-crossing pair shares ONE merged id
        assert by_name["send"]["flow"] == by_name["recv"]["flow"]
        assert by_name["a"]["flow"] != by_name["send"]["flow"]
        # time-sorted output
        times = [e["t_wall"] for e in merged]
        assert times == sorted(times)

    def test_chrome_export_one_process_per_node_arrows_cross(self):
        events = {
            "H0": [_ev("recv", 100.5, 0.2, "executor:x", flow=3,
                       flow_node="W0")],
            "W0": [_ev("send", 100.0, 0.2, "MainThread", flow=3)],
        }
        merged = timeline_mod.merge_node_events(events)
        trace = timeline_mod.to_chrome_trace(merged)["traceEvents"]
        procs = {
            m["args"]["name"]: m["pid"]
            for m in trace
            if m.get("ph") == "M" and m["name"] == "process_name"
        }
        assert len(procs) == 2  # one Perfetto process per node
        assert any(":W0" in n for n in procs)
        # the flow arrow's s/f pair crosses the two node processes
        starts = [e for e in trace if e.get("ph") == "s"]
        finishes = [e for e in trace if e.get("ph") == "f"]
        assert starts and finishes
        assert starts[0]["pid"] != finishes[0]["pid"]

    def test_single_node_export_shape_unchanged(self):
        # no node tags: the legacy single-pid schema, exactly
        evs = [_ev("x", 1.0, 0.1, "T1"), _ev("y", 1.2, 0.1, "T2")]
        trace = timeline_mod.to_chrome_trace(evs)["traceEvents"]
        pids = {e["pid"] for e in trace}
        assert pids == {1}
        assert trace[0]["name"] == "process_name"


class TestNetworkAttribution:
    def test_transfer_bound_trace_agrees_with_hand_breakdown(self):
        """The acceptance cross-check: on a synthetic transfer-bound
        trace the ``network`` share from the analyzer must equal the
        hand-computed busy fraction."""
        events = []
        t = 1000.0
        prep_s, wire_s = 0.01, 0.09
        for i in range(8):
            fid = 100 + i
            events.append(_ev("ingest.prep", t, prep_s, "prep", flow=fid))
            events.append(
                _ev("van.transfer", t + prep_s, wire_s, "sender", flow=fid)
            )
            t += prep_s + wire_s
        summary = attribution_mod.summarize(events)
        assert summary["binding_resource"] == "network"
        hand = (8 * wire_s) / (8 * (prep_s + wire_s))
        assert summary["shares"]["network"] == pytest.approx(hand, abs=0.01)
        # the flow view sees the same dominance
        assert summary["flows"]["dominant"] == "network"

    def test_transfer_nested_in_step_not_double_billed(self):
        """A ps.py RPC's van.transfer runs INSIDE the executor step
        body — its seconds belong to the network resource alone, carved
        out of the step's run (device_compute) phase on that thread."""
        # executor.step: finish at t=101.0, total 1.0s, all run time
        step = {
            "kind": "span", "name": "executor.step", "t_wall": 101.0,
            "total_s": 1.0, "queue_wait_s": 0.0, "run_s": 1.0,
            "materialize_s": 0.0, "thread": "executor:rpc", "flow": 1,
        }
        wire = _ev("van.transfer", 100.2, 0.6, "executor:rpc", flow=1)
        busy = attribution_mod.busy_by_category([step, wire])
        assert busy["network"] == pytest.approx(0.6)
        assert busy["device_compute"] == pytest.approx(0.4)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_bounded_and_dump_shape(self):
        rec = blackbox.FlightRecorder(capacity=4, node_id="T0")
        for i in range(10):
            rec.emit({"name": f"e{i}", "t_wall": float(i), "dur_s": 0.0})
        d = rec.dump()
        assert d["node"] == "T0"
        assert d["capacity"] == 4
        assert len(d["events"]) == 4
        assert d["events_total"] == 10
        assert d["dropped"] == 6
        # oldest evicted, newest kept
        assert d["events"][0]["name"] == "e6"
        assert d["events"][-1]["name"] == "e9"

    def test_tee_records_and_forwards(self, tmp_path):
        path = str(tmp_path / "tee.jsonl")
        prev = telemetry_spans.install_sink(telemetry_spans.JsonlSink(path))
        try:
            rec = blackbox.arm()
            assert blackbox.installed_recorder() is rec
            with telemetry_spans.span("tee.demo"):
                pass
            # both destinations got the event; path proxies the inner
            assert getattr(telemetry_spans.get_sink(), "path") == path
            assert any(
                e["name"] == "tee.demo"
                for e in timeline_mod.load_events(path)
            )
            assert any(
                e["name"] == "tee.demo" for e in rec.dump()["events"]
            )
            blackbox.disarm()
            assert telemetry_spans.get_sink().path == path
        finally:
            mine = telemetry_spans.install_sink(prev)
            if mine is not None:
                mine.close()

    def test_armed_without_inner_sink_no_file_io(self):
        rec = blackbox.arm()
        assert telemetry_spans.get_sink().path is None  # nothing to write
        with telemetry_spans.span("bb.idle"):
            pass
        assert any(
            e["name"] == "bb.idle" for e in rec.dump()["events"]
        )
        assert telemetry_spans.sink_state() == "active"

    def test_metrics_delta_samples(self):
        from parameter_server_tpu.telemetry.registry import MetricsRegistry

        reg = MetricsRegistry()
        c = reg.counter("bb_test_total", "t")
        rec = blackbox.FlightRecorder(node_id="T0")
        c.inc(3)
        rec.sample_metrics(reg=reg)
        c.inc(2)
        s = rec.sample_metrics(reg=reg)
        assert s["delta"]["bb_test_total"] == pytest.approx(2.0)
        d = rec.dump()
        assert len(d["metrics_samples"]) == 2
        # first sample's delta is the from-zero baseline
        assert d["metrics_samples"][0]["delta"]["bb_test_total"] == 3.0

    def test_overhead_ab_shape(self):
        out = blackbox.overhead_ab(reps=2, n=100)
        assert out["file_io"] is False
        assert out["ratio_median"] > 0
        assert out["armed_ns_per_event"] > out["added_ns_per_event"] > 0
        assert out["reps"] == 2


# ---------------------------------------------------------------------------
# diagnostic bundles + the trigger plane
# ---------------------------------------------------------------------------


class TestBundles:
    def test_capture_contents_and_perfetto_trace(self):
        rec = blackbox.arm()
        with telemetry_spans.flow_scope(telemetry_spans.new_flow()):
            with telemetry_spans.span("incident.work"):
                pass
        rec.sample_metrics()
        b = blackbox.capture_bundle(trigger="manual", detail="unit")
        assert b["kind"] == "ps_diagnostic_bundle"
        assert b["trigger"]["kind"] == "manual"
        nid = telemetry_spans.node_id()
        assert nid in b["rings"]
        names = [e["name"] for e in b["rings"][nid]["events"]]
        assert "incident.work" in names
        # Perfetto-ready: a traceEvents list with X events in it
        xs = [e for e in b["trace"]["traceEvents"] if e.get("ph") == "X"]
        assert xs
        # JSON-serializable end to end (self-contained artifact)
        json.dumps(b, default=str)
        s = blackbox.summarize_bundle(b)
        assert s["nodes"][nid]["events"] >= 1
        assert not s["section_errors"]

    def test_trigger_rate_limit(self):
        blackbox.set_min_interval(3600.0)
        b1 = blackbox.trigger_bundle("manual", detail="first")
        assert b1 is not None
        assert blackbox.trigger_bundle("manual", detail="second") is None
        assert blackbox.last_bundle() is b1
        blackbox.set_min_interval(0.0)
        assert blackbox.trigger_bundle("manual", detail="third") is not None
        assert len(blackbox.bundles()) == 2

    def test_wedged_executor_wait_triggers_bundle(self):
        from parameter_server_tpu.system.executor import Executor
        from parameter_server_tpu.utils.retry import DeadlineExceeded

        blackbox.set_min_interval(0.0)
        blackbox.arm()
        ex = Executor("wedge-test")
        gate = threading.Event()
        try:
            ts = ex.submit(gate.wait)
            with pytest.raises(DeadlineExceeded):
                ex.wait(ts, timeout=0.05)
            b = blackbox.last_bundle()
            assert b is not None
            assert b["trigger"]["kind"] == "executor_wait_timeout"
            assert "wedge-test" in b["trigger"]["detail"]
            # the executor section pins the wedged state at capture time
            mine = [
                e for e in b["executors"] if e["name"] == "wedge-test"
            ]
            assert mine and (
                mine[0]["running"] is not None or mine[0]["pending"] > 0
            )
        finally:
            gate.set()
            ex.wait_all()
            ex.stop()

    def test_degraded_serving_triggers_bundle(self, mesh8):
        from parameter_server_tpu.parameter.kv_vector import KVVector
        from parameter_server_tpu.serving import (
            DegradedError,
            PullRequest,
            ServeConfig,
            ServeFrontend,
        )

        blackbox.set_min_interval(0.0)
        blackbox.arm()
        kv = KVVector(mesh=mesh8, k=4, num_slots=1 << 10, hashed=True,
                      name="bb_degraded")
        fe = ServeFrontend(
            kv, ServeConfig(replica="off", workers=1,
                            live_pull_deadline_s=2.0)
        ).start()
        try:
            keys = np.arange(8, dtype=np.int64)
            fe.submit(PullRequest(keys=keys)).result(30)  # healthy warm
            faults.arm("serve.pull", kind="raise")
            with pytest.raises(DegradedError):
                fe.submit(PullRequest(keys=keys)).result(30)
            b = blackbox.last_bundle()
            assert b is not None
            assert b["trigger"]["kind"] == "degraded"
            assert "no-replica" in b["trigger"]["detail"]
        finally:
            faults.reset()
            fe.close()
            kv.executor.stop()

    def test_aux_owned_coordinator_death_captures_with_cluster_context(self):
        """A node death detected through an AuxRuntime's coordinator
        captures the FULL-context bundle (cluster metrics snapshot,
        clock offsets, staleness-aware rings) — not the process-local
        fallback a standalone coordinator gets."""
        from parameter_server_tpu.system.aux_runtime import AuxRuntime

        blackbox.set_min_interval(0.0)
        blackbox.arm()
        aux = AuxRuntime(heartbeat_timeout=0.05)
        try:
            assert aux.coordinator.bundle_context is aux
            aux.register("S0")
            time.sleep(0.12)  # past the heartbeat timeout: S0 is dead
            handled = aux.coordinator.check()
            assert handled == ["S0"]
            b = blackbox.last_bundle()
            assert b is not None
            assert b["trigger"]["kind"] == "node_death"
            # cluster-context sections only an aux capture carries
            assert "nodes" in b["metrics"]  # ClusterAggregator.snapshot
            assert b["clock_offsets"] is not None
            assert b["rings"]["S0"]["stale"]
        finally:
            aux.stop()

    def test_fetch_rings_own_node_dumps_even_when_marked_stale(self):
        """A stalled aux loop marks the capturing process's OWN node
        stale — exactly the wedged-process incident a bundle exists to
        diagnose. Its in-memory ring needs no wire and is provably
        alive, so the capture must dump it, not record staleness for
        the node executing the capture."""
        from parameter_server_tpu.system.aux_runtime import AuxRuntime

        aux = AuxRuntime(heartbeat_timeout=30.0, stale_after_s=0.01)
        try:
            rec = blackbox.arm()
            rec.emit({"name": "self.evidence", "t_wall": 1.0,
                      "dur_s": 0.0})
            aux.cluster.update(aux.node_id, {})
            time.sleep(0.03)  # past stale_after_s: self reads stale
            assert aux.node_id in aux.cluster.stale_nodes()
            rings = aux.fetch_rings(wire=False)
            own = rings[aux.node_id]
            assert not own.get("stale"), own
            assert [e["name"] for e in own["events"]] == ["self.evidence"]
        finally:
            aux.stop()

    def test_fetch_rings_over_van_with_staleness(self):
        """Ring dumps ride the real wire; a node whose fetch is lost on
        the wire (injected drop) shows staleness, not a fabricated
        ring — and a node with stale metric reports is not fetched at
        all."""
        po = Postoffice.instance().start()
        aux = po.start_aux(heartbeat_timeout=30.0)
        aux.cluster.stale_after_s = 30.0
        try:
            aux.register("W0")
            aux.register("S0")
            blackbox.recorder("W0").emit({"name": "w0.e", "t_wall": 1.0,
                                          "dur_s": 0.0})
            blackbox.recorder("S0").emit({"name": "s0.e", "t_wall": 1.0,
                                          "dur_s": 0.0})
            sent_before = po.van.wire_sent_bytes
            faults.arm("van.transfer", kind="drop", match="S0->")
            rings = aux.fetch_rings()
            faults.disarm("van.transfer")
            # W0's ring crossed the wire intact
            assert [e["name"] for e in rings["W0"]["events"]] == ["w0.e"]
            assert po.van.wire_sent_bytes > sent_before
            # S0's fetch was lost: staleness, with the loss named
            assert rings["S0"]["stale"]
            assert "lost" in rings["S0"]["reason"]
            # this process's own node dumps locally
            assert aux.node_id in rings
        finally:
            aux.stop()
            po.stop()


# ---------------------------------------------------------------------------
# exposition: /debug/bundle, sink disclosure, concurrent-scrape floor
# ---------------------------------------------------------------------------


class TestExposition:
    def test_snapshot_discloses_sink_state(self, tmp_path):
        from parameter_server_tpu.telemetry.exposition import _timeline_tail

        # absent: no sink was ever installed
        tail = _timeline_tail()
        assert tail["sink"] == "absent"
        assert tail["events"] == []
        sink = telemetry_spans.JsonlSink(str(tmp_path / "t.jsonl"))
        prev = telemetry_spans.install_sink(sink)
        try:
            with telemetry_spans.span("disclose.me"):
                pass
            tail = _timeline_tail()
            assert tail["sink"] == "active"
            assert [e["name"] for e in tail["events"]] == ["disclose.me"]
            # parked: a sink exists but an embedded A/B uninstalled it —
            # "no trace captured" is now distinguishable from "nothing
            # happened"
            with telemetry_spans.parked_sink():
                tail = _timeline_tail()
                assert tail["sink"] == "parked"
                assert tail["events"] == []
        finally:
            telemetry_spans.install_sink(prev)
            sink.close()

    def test_bundle_endpoint_and_concurrent_scrape_floor(self):
        """Satellite: N threads hammering /metrics + /debug/bundle must
        ride the scrape-refresh floor — the message plane is driven at
        the floor rate, not the request rate, fault-point call counters
        tick accordingly, and every response is 200 (the hermetic
        fixture asserts no thread leaks)."""
        from parameter_server_tpu.telemetry.exposition import (
            close_cluster,
            expose_cluster,
        )

        po = Postoffice.instance().start()
        blackbox.arm()
        srv = expose_cluster(
            po, metrics_interval=0.0, check_interval=5.0,
            heartbeat_timeout=30.0,
        )
        try:
            aux = srv.aux
            aux.register("W0")
            # warm the floor: one scrape + one bundle so the hammer
            # below measures steady-state behavior, then count fault-
            # point calls without ever firing (a threshold the hammer
            # can never reach makes the spec a pure call counter)
            with _get(srv.url + "/metrics") as r:
                assert r.status == 200
            with _get(srv.url + "/debug/bundle") as r:
                assert r.status == 200
            n_nodes = len(aux.cluster.node_ages()) + 1
            spec_hb = faults.arm(
                "heartbeat.report", kind="raise", after_n_calls=1 << 30
            )
            spec_van = faults.arm(
                "van.transfer", kind="raise", after_n_calls=1 << 30
            )
            n_threads, n_reqs = 6, 10
            codes = []
            codes_lock = threading.Lock()

            def hammer(i):
                for j in range(n_reqs):
                    path = "/metrics" if (i + j) % 2 else "/debug/bundle"
                    with _get(srv.url + path) as r:
                        with codes_lock:
                            codes.append(r.status)

            t0 = time.monotonic()
            threads = [
                threading.Thread(target=hammer, args=(i,))
                for i in range(n_threads)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dur = time.monotonic() - t0
            assert codes and all(c == 200 for c in codes)
            # the floor: at most one metrics sweep / bundle capture per
            # scrape_refresh_min_s window (+ straddle slack) — NOT one
            # per request. Each sweep/capture ticks each point at most
            # once per known node (every manager node is a registered
            # sampler), so the bound scales with cluster size, never
            # with the request count.
            floor = aux.scrape_refresh_min_s
            max_sweeps = dur / floor + 2
            assert spec_hb.calls <= max_sweeps * n_nodes, (
                f"{spec_hb.calls} heartbeat fault-point ticks for "
                f"{len(codes)} requests in {dur:.2f}s over {n_nodes} "
                "nodes — the scrape floor is not holding"
            )
            assert spec_van.calls <= 2 * max_sweeps * n_nodes, (
                f"{spec_van.calls} van fault-point ticks — the message "
                "plane is being re-driven per scrape"
            )
            # far below the request count (the actual re-drive signal)
            assert spec_hb.calls + spec_van.calls < len(codes)
        finally:
            faults.reset()
            close_cluster(srv)
            po.stop()
