"""Aux subsystem tests: assigner, heartbeat/failure detection, dashboard,
remote-node filter state, workload pool, monitor, slot reader, example info,
text2record roundtrip, checkpoint/restore + replica recovery."""

import time

import numpy as np
import pytest

from parameter_server_tpu.data.info import info_from_batch
from parameter_server_tpu.data.slot_reader import SlotReader
from parameter_server_tpu.data.text2record import convert
from parameter_server_tpu.data.stream_reader import StreamReader
from parameter_server_tpu.data.text_parser import SLOT_SPACE
from parameter_server_tpu.learner.workload_pool import Workload, WorkloadPool
from parameter_server_tpu.parameter.replica import CheckpointManager, ReplicaManager
from parameter_server_tpu.system.assigner import DataAssigner, NodeAssigner
from parameter_server_tpu.system.dashboard import Dashboard
from parameter_server_tpu.system.heartbeat import HeartbeatCollector, HeartbeatInfo
from parameter_server_tpu.system.manager import Node
from parameter_server_tpu.system.message import FilterSpec, Message, Task
from parameter_server_tpu.system.monitor import MonitorMaster, MonitorSlaver
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.system.remote_node import RemoteNodeTable
from parameter_server_tpu.utils.range import Range
from parameter_server_tpu.utils.sparse import random_sparse


class TestAssigner:
    def test_node_assigner_key_ranges(self):
        na = NodeAssigner(num_servers=3, key_range=Range(0, 90))
        servers = [na.assign(Node(Node.SERVER, 0)) for _ in range(3)]
        assert [s.key_range for s in servers] == [
            Range(0, 30), Range(30, 60), Range(60, 90),
        ]
        assert [s.rank for s in servers] == [0, 1, 2]
        w = na.assign(Node(Node.WORKER, 0))
        assert w.rank == 0

    def test_data_assigner_more_files_than_workers(self, tmp_path):
        files = []
        for i in range(6):
            p = tmp_path / f"part{i}"
            p.write_text("x")
            files.append(str(p))
        da = DataAssigner(files, num=3)
        parts = [da.next() for _ in range(3)]
        assert da.next() is None
        assert sum(len(p.files) for p in parts) == 6

    def test_data_assigner_fewer_files(self, tmp_path):
        p = tmp_path / "single"
        p.write_text("x")
        da = DataAssigner([str(p)], num=4)
        parts = [da.next() for _ in range(4)]
        assert all(pt.files == [str(p)] for pt in parts)
        assert len({pt.range_begin for pt in parts}) == 4


class TestHeartbeat:
    def test_report_fields(self):
        hb = HeartbeatInfo(hostname="testhost")
        hb.start_timer()
        time.sleep(0.01)
        hb.stop_timer()
        hb.increase_in_bytes(1_000_000)
        rep = hb.get()
        assert rep.hostname == "testhost"
        assert rep.busy_time_milli >= 10
        assert rep.net_in_mb == pytest.approx(1.0)
        assert rep.process_rss_mb > 0

    def test_failure_detection(self):
        col = HeartbeatCollector(timeout=0.05)
        col.report("W0", HeartbeatInfo().get())
        col.report("W1", HeartbeatInfo().get())
        assert col.dead_nodes() == []
        time.sleep(0.06)
        col.report("W1", HeartbeatInfo().get())  # W1 stays alive
        assert col.dead_nodes() == ["W0"]

    def test_concurrent_get_windows_tile_exactly(self, monkeypatch):
        """Regression (pslint guarded-access): ``get()`` used to read
        and replace ``_last`` OUTSIDE the lock, so concurrent reporter
        threads could rate the same sample window twice — or clobber a
        newer sample with an older one, driving dt negative. With the
        whole sample-and-diff under the lock, N concurrent gets consume
        the synthetic sample stream in non-overlapping windows: the
        cpu-rate multiset must be exactly {2i-1}."""
        import sys
        import threading

        from parameter_server_tpu.system import heartbeat as hb_mod
        from parameter_server_tpu.utils.resource_usage import Usage

        state = {"n": 0}
        state_lock = threading.Lock()

        def fake_sample():
            with state_lock:
                state["n"] += 1
                n = float(state["n"])
            # timestamp advances by 1 per sample; cpu_seconds = n^2, so
            # the true rate over the window (n-1, n) is exactly 2n - 1
            return Usage(
                timestamp=n,
                rss_mb=1.0,
                vm_mb=1.0,
                cpu_seconds=n * n,
                host_total_cpu_seconds=0.0,
                load1=0.0,
            )

        monkeypatch.setattr(hb_mod.resource_usage, "sample", fake_sample)
        info = HeartbeatInfo(hostname="h")  # consumes sample #1
        rates = []
        rates_lock = threading.Lock()
        start = threading.Barrier(4)

        def reporter():
            start.wait()
            for _ in range(50):
                rep = info.get()
                with rates_lock:
                    rates.append(round(rep.process_cpu_usage))

        threads = [threading.Thread(target=reporter) for _ in range(4)]
        # the pre-fix window is a few bytecodes wide — preempt often
        # enough that the racy interleaving actually happens
        old_interval = sys.getswitchinterval()
        sys.setswitchinterval(1e-5)
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            sys.setswitchinterval(old_interval)
        # 200 gets consume samples #2..#201: rates 2n-1 for n in 2..201,
        # each window exactly once — duplicates or misses mean the
        # unlocked read-modify-write of _last came back
        assert sorted(rates) == [2 * n - 1 for n in range(2, 202)]


class TestDashboard:
    def test_table_render_and_order(self):
        dash = Dashboard()
        hb = HeartbeatInfo(hostname="h")
        for nid in ("S1", "W0", "H0", "S0"):
            dash.add_report(nid, hb.get())
        out = dash.report().splitlines()
        assert out[0].startswith("node")
        order = [line.split()[0] for line in out[1:]]
        assert order == ["H0", "W0", "S0", "S1"]

    def test_report_never_sees_torn_event_window(self):
        """Regression (pslint guarded-access): Dashboard had NO lock —
        AuxRuntime.beat() feeds it from every node's reporter thread
        while the aux poller renders report(). ``add_event`` appends
        and THEN trims to the last ``keep`` entries; without the lock a
        concurrent report() can observe the list between those two
        steps and render more events than the window allows (and, on
        free-threaded builds, corrupt the dict outright). With
        add_event/report atomic under the new lock, the rendered event
        count can never exceed the window."""
        import sys
        import threading

        dash = Dashboard()
        stop = threading.Event()

        def writer(prefix):
            i = 0
            while not stop.is_set():
                dash.add_event(f"{prefix}{i}")  # keep=8 window
                i += 1

        threads = [
            threading.Thread(target=writer, args=(p,), daemon=True)
            for p in ("W", "S")
        ]
        old_interval = sys.getswitchinterval()
        sys.setswitchinterval(1e-5)
        max_seen = 0
        try:
            for t in threads:
                t.start()
            for _ in range(4000):
                n_events = dash.report().count("event: ")
                max_seen = max(max_seen, n_events)
                if max_seen > 8:
                    break
        finally:
            stop.set()
            sys.setswitchinterval(old_interval)
            for t in threads:
                t.join(timeout=5)
        assert max_seen <= 8, (
            f"report() observed a torn event window ({max_seen} > 8): "
            "add_event/report are not atomic"
        )


class TestRemoteNode:
    def test_per_peer_filter_state_isolated(self):
        table = RemoteNodeTable()
        keys = np.arange(10, dtype=np.int64)

        def msg():
            m = Message(task=Task(key_range=Range(0, 100)))
            m.key = keys.copy()
            m.values = [np.ones(10, np.float32)]
            m.task.filters = [FilterSpec(type="key_caching")]
            return m

        a, b = table.get("S0"), table.get("S1")
        m1 = a.encode(msg())
        assert m1.key is not None  # first send to S0 carries keys
        m2 = a.encode(msg())
        assert m2.key is None  # cache hit on S0
        m3 = b.encode(msg())
        assert m3.key is not None  # S1 has its own cache
        assert len(table) == 2


class TestWorkloadPool:
    def test_assign_finish_restore(self):
        pool = WorkloadPool(Workload(files=["a", "b", "c"]))
        l1 = pool.assign("W0")
        l2 = pool.assign("W1")
        pool.finish(l1.id)
        pool.restore("W1")  # W1 died: its piece goes back
        l2b = pool.assign("W2")
        assert l2b.files == l2.files
        pool.finish(l2b.id)
        l3 = pool.assign("W2")
        pool.finish(l3.id)
        assert pool.wait_until_done(timeout=1)

    def test_replica_and_shuffle(self):
        pool = WorkloadPool(Workload(files=["a", "b"], replica=3, shuffle=True))
        assert pool.num_pending() == 6


class TestMonitor:
    def test_merge_and_print(self):
        master: MonitorMaster[list] = MonitorMaster()
        master.set_data_merger(lambda src, dst: dst.extend(src))
        s1 = MonitorSlaver(master, "W0")
        s2 = MonitorSlaver(master, "W1")
        s1.report([1])
        s1.report([2])
        s2.report([3])
        prog = master.progress()
        assert prog["W0"] == [1, 2] and prog["W1"] == [3]


class TestSlotReaderInfo:
    def _write_criteo(self, tmp_path, n=50):
        path = tmp_path / "part.criteo"
        rng = np.random.default_rng(0)
        with open(path, "w") as f:
            for i in range(n):
                ints = "\t".join(str(rng.integers(0, 100)) for _ in range(13))
                cats = "\t".join(f"{rng.integers(0, 1 << 32):08x}" for _ in range(26))
                f.write(f"{i % 2}\t{ints}\t{cats}\n")
        return str(path)

    def test_slot_reader_splits_criteo_slots(self, tmp_path):
        path = self._write_criteo(tmp_path)
        sr = SlotReader([path], "criteo", cache_dir=str(tmp_path / "cache"))
        info = sr.read()
        assert info.num_ex == 50
        assert len(info.slot) == 39  # 13 numeric + 26 categorical
        s1 = sr.slot(1)
        assert s1 is not None and s1.nnz == 50  # slot 1 present in every row
        # cache round trip
        sr.clear(1)
        s1b = sr.slot(1)
        np.testing.assert_array_equal(s1.indices, s1b.indices)

    def test_info_from_batch(self):
        b = random_sparse(20, 100, 5, seed=0)
        info = info_from_batch(b, split_slots=False)
        assert info.num_ex == 20
        assert info.slot[0].nnz_ele == b.nnz

    def test_info_merge(self):
        b1 = info_from_batch(random_sparse(10, 50, 3, seed=1), split_slots=False)
        b2 = info_from_batch(random_sparse(15, 50, 3, seed=2), split_slots=False)
        b1.merge(b2)
        assert b1.num_ex == 25
        assert b1.slot[0].nnz_ele == 30 + 45


class TestText2Record:
    def test_roundtrip(self, tmp_path):
        svm = tmp_path / "in.svm"
        b = random_sparse(100, 50, 4, seed=5)
        exp_indices = []
        with open(svm, "w") as f:
            for r in range(b.n):
                lo, hi = b.indptr[r], b.indptr[r + 1]
                # rows must be written id-sorted: the parser is
                # reference-strict and drops out-of-order lines
                order = np.argsort(b.indices[lo:hi], kind="stable")
                exp_indices.append(b.indices[lo:hi][order])
                feats = " ".join(
                    f"{int(k)}:{v:.5f}"
                    for k, v in zip(b.indices[lo:hi][order], b.values[lo:hi][order])
                )
                f.write(f"{int(b.y[r])} {feats}\n")
        out = tmp_path / "out.rec"
        n = convert([str(svm)], "libsvm", str(out), batch_size=32)
        assert n == 100
        back = StreamReader([str(out)], "record").read_all()
        assert back.n == 100
        np.testing.assert_array_equal(back.y, b.y)
        np.testing.assert_array_equal(back.indices, np.concatenate(exp_indices))


class TestCheckpointReplica:
    def test_checkpoint_roundtrip(self, tmp_path, mesh8):
        import jax
        import jax.numpy as jnp

        from parameter_server_tpu.parallel import mesh as meshlib

        cm = CheckpointManager(str(tmp_path / "ckpt"))
        tree = {
            "z": jax.device_put(
                jnp.arange(16.0).reshape(16, 1), meshlib.table_sharding(mesh8)
            ),
            "step": jnp.asarray(7),
        }
        cm.save(3, tree)
        assert cm.latest_step() == 3
        restored = cm.restore(3, like=tree)
        np.testing.assert_allclose(np.asarray(restored["z"]), np.asarray(tree["z"]))
        assert restored["z"].sharding == tree["z"].sharding

    def test_async_save_roundtrip(self, tmp_path, mesh8):
        """save_async overlaps the disk write with the caller; restore/
        latest_step drain the in-flight write first, and back-to-back
        async saves serialize (no interleaved step dirs)."""
        import jax
        import jax.numpy as jnp

        from parameter_server_tpu.parallel import mesh as meshlib

        cm = CheckpointManager(str(tmp_path / "ckpt"))
        tree = {
            "z": jax.device_put(
                jnp.arange(16.0).reshape(16, 1), meshlib.table_sharding(mesh8)
            ),
            "step": jnp.asarray(7),
        }
        for s in (1, 2, 3):  # serialize: each drains the previous
            cm.save_async(s, tree)
        assert cm.latest_step() == 3  # drains the in-flight write
        restored = cm.restore(3, like=tree)
        np.testing.assert_allclose(
            np.asarray(restored["z"]), np.asarray(tree["z"])
        )
        assert restored["z"].sharding == tree["z"].sharding
        cm.wait()  # idempotent with nothing in flight

    def test_async_save_snapshot_precedes_mutation(self, tmp_path):
        """The device→host snapshot happens IN save_async, not in the
        background thread: mutating the caller's numpy tree right after
        the call must not corrupt the written checkpoint (the donation-
        safety contract)."""
        cm = CheckpointManager(str(tmp_path / "ckpt"), use_orbax=False)
        arr = np.arange(8.0)
        cm.save_async(1, {"w": arr})
        arr += 100.0  # simulates the next step consuming the buffer
        got = cm.restore(1, like={"w": np.empty(8)})
        np.testing.assert_array_equal(got["w"], np.arange(8.0))

    def test_async_save_error_surfaces(self, tmp_path):
        """A failed background write raises from the next wait()/save,
        not silently."""
        cm = CheckpointManager(str(tmp_path / "ckpt"), use_orbax=False)
        cm._write = lambda path, tree: (_ for _ in ()).throw(
            OSError("disk full")
        )
        cm.save_async(1, {"w": np.zeros(4)})
        with pytest.raises(RuntimeError, match="async checkpoint save"):
            cm.wait()
        cm.wait()  # error is consumed, not re-raised forever

    def test_replica_recovery(self, mesh8):
        from parameter_server_tpu.parameter.kv_vector import KVVector

        Postoffice.reset()
        kv = KVVector(mesh=mesh8, k=1, num_slots=16, hashed=False, name="kv_rep")
        keys = np.array([2, 9], dtype=np.int64)
        kv.set_keys(0, keys)
        kv.wait(kv.push(kv.request(0), keys=keys, values=np.ones((2, 1), np.float32)))
        rm = ReplicaManager()
        rm.backup(kv)
        # "server dies": wipe state, then recover from replica
        kv.set_replica({0: np.zeros((16, 1), np.float32)})
        assert kv.values(0, keys).sum() == 0
        assert rm.recover(kv)
        np.testing.assert_allclose(kv.values(0, keys), np.ones((2, 1)))
        Postoffice.reset()


class TestWireFrameSafety:
    """Message.from_bytes on untrusted/corrupt frames (ref van.cc recv)."""

    def _msg(self):
        return Message(
            task=Task(filters=[FilterSpec(type="compressing")]),
            sender="W0",
            recver="S0",
            key=np.arange(4, dtype=np.int64),
            values=[np.ones(3, np.float32)],
        )

    def test_roundtrip(self):
        m = Message.from_bytes(self._msg().to_bytes())
        assert m.sender == "W0" and m.task.filters[0].type == "compressing"
        np.testing.assert_array_equal(m.key, np.arange(4))

    def test_truncated_frame_is_value_error(self):
        blob = self._msg().to_bytes()
        for cut in (0, 2, len(blob) // 2, len(blob) - 1):
            with pytest.raises(ValueError):
                Message.from_bytes(blob[:cut])

    def test_flipped_length_is_value_error(self):
        blob = bytearray(self._msg().to_bytes())
        blob[0] = 0xFF  # header length now exceeds the frame
        with pytest.raises(ValueError):
            Message.from_bytes(bytes(blob))

    def test_forbidden_global_rejected(self):
        import pickle
        import struct

        # a classic __reduce__ payload: pickle naming os.system
        evil = pickle.dumps((__import__("os").system, ("true",)))
        frame = struct.pack("<I", len(evil)) + evil
        with pytest.raises(ValueError, match="forbidden global|malformed"):
            Message.from_bytes(frame)

    def test_task_payload_roundtrip(self):
        # app payloads built from package types + numpy survive the
        # restricted unpickler
        m = Message(task=Task(payload={"r": Range(3, 9), "x": np.float64(2.5)}))
        out = Message.from_bytes(m.to_bytes())
        assert out.task.payload["r"] == Range(3, 9)
        assert out.task.payload["x"] == 2.5

    def test_fresh_copy_isolates_filter_extra(self):
        t = Task(filters=[FilterSpec(type="compressing")])
        c = t.fresh_copy()
        c.filters[0].extra["meta"] = ["poison"]
        assert "meta" not in t.filters[0].extra

    @pytest.mark.parametrize(
        "module,name",
        [
            ("os", "system"),
            # STACK_GLOBAL dotted traversal through an allowed module
            ("parameter_server_tpu.cpp", "subprocess.run"),
            # function (not class) re-exported by an allowed module
            ("parameter_server_tpu.cpp", "native"),
            # numpy escapes: file write, dlopen, side-effectful ctor
            ("numpy", "save"),
            ("numpy.ctypeslib", "load_library"),
            ("numpy", "memmap"),
            # package class OUTSIDE the closed wire set: constructing it
            # would register a phantom customer with the postoffice
            ("parameter_server_tpu.system.customer", "Customer"),
        ],
    )
    def test_unpickler_bypasses_rejected(self, module, name):
        import pickle
        import struct

        # hand-build a protocol-4 STACK_GLOBAL pickle naming module.name
        frame = (
            pickle.PROTO + bytes([4])
            + pickle.SHORT_BINUNICODE + bytes([len(module)]) + module.encode()
            + pickle.SHORT_BINUNICODE + bytes([len(name)]) + name.encode()
            + pickle.STACK_GLOBAL
            + pickle.STOP
        )
        blob = struct.pack("<I", len(frame)) + frame
        with pytest.raises(ValueError, match="forbidden|malformed"):
            Message.from_bytes(blob)
