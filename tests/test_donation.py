"""Zero-copy data plane: donation semantics, fused push_pull parity,
slot-directory caching, and the donation lint.

The contract under test (doc/PERFORMANCE.md "Donation rules"):

- owners update tables IN PLACE (donated buffers) — stale references
  raise instead of silently reading old data;
- checkpoint/replica paths copy BEFORE donation can land, so snapshots
  are immune to later pushes;
- the fused ``push_pull`` kernel is bit-identical to push-then-pull;
- ``KeyDirectory`` caches slot mappings by key-array signature and can
  never serve wrong slots on a signature collision.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_tpu.ops import kv_ops
from parameter_server_tpu.parameter.kv_layer import KVLayer, SGDUpdater
from parameter_server_tpu.parameter.kv_map import AddEntry, KVMap
from parameter_server_tpu.parameter.kv_vector import KVVector
from parameter_server_tpu.parameter.parameter import KeyDirectory
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.telemetry import registry as telemetry_registry


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    yield
    Postoffice.reset()


def _counter(name: str) -> float:
    inst = telemetry_registry.default_registry().get(name)
    return 0.0 if inst is None else inst.value()


class TestDonatedPush:
    def test_read_after_donate_raises(self, mesh8):
        """Pushing twice through the donated path must not alias stale
        buffers: the consumed input raises, it never serves old data."""
        from parameter_server_tpu.parallel import mesh as meshlib

        t0 = jax.device_put(
            jnp.zeros((16, 1), jnp.float32), meshlib.table_sharding(mesh8)
        )
        idx = jnp.array([1, 9], jnp.int32)
        vals = jnp.ones((2, 1), jnp.float32)
        t1 = kv_ops.push_donated(t0, idx, vals, mesh=mesh8, batch_sharded=False)
        t2 = kv_ops.push_donated(t1, idx, vals, mesh=mesh8, batch_sharded=False)
        expect = np.zeros((16, 1))
        expect[[1, 9]] = 2.0
        np.testing.assert_allclose(np.asarray(t2), expect)
        for stale in (t0, t1):
            with pytest.raises(RuntimeError, match="deleted|donated"):
                np.asarray(stale)

    def test_kv_vector_updates_table_in_place(self, mesh8):
        """The live table buffer is consumed per push (zero-copy), and
        the store's values stay correct across repeated pushes."""
        kv = KVVector(mesh=mesh8, k=1, num_slots=32, hashed=False)
        keys = np.array([2, 7], dtype=np.int64)
        kv.set_keys(0, keys)
        before = kv.table(0)  # live view
        kv.wait(kv.push(kv.request(channel=0), keys=keys,
                        values=np.ones((2, 1), np.float32)))
        with pytest.raises(RuntimeError, match="deleted|donated"):
            np.asarray(before)  # the old buffer was donated
        kv.wait(kv.push(kv.request(channel=0), keys=keys,
                        values=np.ones((2, 1), np.float32)))
        np.testing.assert_allclose(kv.values(0, keys), 2 * np.ones((2, 1)))

    def test_table_copy_survives_pushes(self, mesh8):
        kv = KVVector(mesh=mesh8, k=1, num_slots=32, hashed=False)
        keys = np.array([3], dtype=np.int64)
        kv.set_keys(0, keys)
        snap = kv.table(0, copy=True)
        kv.wait(kv.push(kv.request(channel=0), keys=keys,
                        values=np.ones((1, 1), np.float32)))
        np.testing.assert_allclose(np.asarray(snap), np.zeros((32, 1)))

    def test_replica_snapshot_unaffected_by_later_push(self, mesh8):
        """get_replica taken BEFORE a push must capture the pre-push
        state and stay readable after the donated update."""
        kv = KVVector(mesh=mesh8, k=1, num_slots=32, hashed=False)
        keys = np.array([4, 8], dtype=np.int64)
        kv.set_keys(0, keys)
        kv.wait(kv.push(kv.request(channel=0), keys=keys,
                        values=np.ones((2, 1), np.float32)))
        snap = kv.get_replica()
        kv.wait(kv.push(kv.request(channel=0), keys=keys,
                        values=np.full((2, 1), 5.0, np.float32)))
        slots = kv.channel(0).directory.slots(keys)
        np.testing.assert_allclose(snap[0][slots], np.ones((2, 1)))
        # and restoring it really rolls back
        kv.set_replica(snap)
        np.testing.assert_allclose(kv.values(0, keys), np.ones((2, 1)))

    def test_kv_map_replica_unaffected_and_push_correct(self, mesh8):
        m = KVMap(AddEntry(), mesh=mesh8, k=1, num_slots=32,
                  keys=np.array([1, 2]))
        m.wait(m.push(m.request(), np.array([1, 2]),
                      np.ones((2, 1), np.float32)))
        snap = m.get_replica()
        m.wait(m.push(m.request(), np.array([1, 2]),
                      np.ones((2, 1), np.float32)))
        np.testing.assert_allclose(m.values(np.array([1, 2])),
                                   2 * np.ones((2, 1)))
        # the snapshot captured the one-push state and is still live
        assert float(snap["value"][0, 0]) == 1.0

    def test_kv_layer_donated_pull_view_dies_with_next_push(self, mesh8):
        layer = KVLayer(partition_thr=4, updater=SGDUpdater(lr=0.5),
                        mesh=mesh8)
        layer.init_layer("w", (8,))
        grad = jnp.ones(8)
        layer.wait(layer.push(layer.request(), "w", grad))
        view = layer.wait_pull(layer.pull(layer.request(), "w"))
        np.testing.assert_allclose(np.asarray(view), -0.5 * np.ones(8))
        snap = layer.get_replica()  # host copy, pre-second-push
        layer.wait(layer.push(layer.request(), "w", grad))
        with pytest.raises(RuntimeError, match="deleted|donated"):
            np.asarray(view)
        np.testing.assert_allclose(snap["w"], -0.5 * np.ones(8))

    def test_kv_layer_donate_false_keeps_pull_views(self, mesh8):
        layer = KVLayer(partition_thr=4, updater=SGDUpdater(lr=0.5),
                        mesh=mesh8, donate=False)
        layer.init_layer("w", (8,))
        grad = jnp.ones(8)
        layer.wait(layer.push(layer.request(), "w", grad))
        view = layer.wait_pull(layer.pull(layer.request(), "w"))
        layer.wait(layer.push(layer.request(), "w", grad))
        # copying mode: the earlier pull view stays valid
        np.testing.assert_allclose(np.asarray(view), -0.5 * np.ones(8))

    def test_fire_and_forget_pushes_then_snapshot(self, mesh8):
        """Regression (review finding): push steps store the live table
        as their executor future; a later push donates that buffer.
        wait()/wait_all() on the superseded future must treat the
        donated buffer as materialized — not raise, not wedge — so a
        snapshot under training load works."""
        kv = KVVector(mesh=mesh8, k=1, num_slots=32, hashed=False)
        keys = np.array([2, 7], dtype=np.int64)
        kv.set_keys(0, keys)
        ones = np.ones((2, 1), np.float32)
        tss = [
            kv.push(kv.request(channel=0), keys=keys, values=ones)
            for _ in range(3)
        ]
        snap = kv.get_replica()  # wait_all over superseded futures
        assert float(snap[0].sum()) == 6.0
        kv.wait(tss[0])  # explicit wait on a donated future: no error
        np.testing.assert_allclose(kv.values(0, keys), 3 * ones)

    def test_push_pull_rejects_buffered_staging(self, mesh8):
        """Regression (review finding): the fused round trip applies to
        the LIVE table; on a buffer_value store with a staging timestamp
        it must raise, not silently bypass the staging buffer."""
        kv = KVVector(mesh=mesh8, k=1, num_slots=32, hashed=False,
                      buffer_value=True)
        keys = np.array([4], dtype=np.int64)
        kv.set_keys(0, keys)
        with pytest.raises(ValueError, match="buffer_value"):
            kv.push_pull(
                kv.request(channel=0, ts=5), keys=keys,
                values=np.ones((1, 1), np.float32),
            )

    def test_donated_push_counter_ticks(self, mesh8):
        before = _counter("ps_kvops_donated_pushes_total")
        kv = KVVector(mesh=mesh8, k=1, num_slots=32, hashed=False)
        keys = np.array([5], dtype=np.int64)
        kv.set_keys(0, keys)
        kv.wait(kv.push(kv.request(channel=0), keys=keys,
                        values=np.ones((1, 1), np.float32)))
        assert _counter("ps_kvops_donated_pushes_total") >= before + 1


class TestFusedPushPull:
    def test_kernel_bit_identical_to_push_then_pull(self, mesh8):
        """push_pull == push; pull — exactly, including duplicate
        indices (scatter-add order) and sentinel drops."""
        from parameter_server_tpu.parallel import mesh as meshlib

        p, k = 32, 3
        rng = np.random.default_rng(0)
        base = rng.normal(size=(p, k)).astype(np.float32)
        idx = jnp.array([2, 2, 31, 30, 9, 32], jnp.int32)  # dup + sentinel
        vals = jnp.asarray(rng.normal(size=(6, k)).astype(np.float32))
        pull_idx = jnp.array([2, 9, 32, 0], jnp.int32)

        t_seq = jax.device_put(jnp.asarray(base),
                               meshlib.table_sharding(mesh8))
        t_seq = kv_ops.push(t_seq, idx, vals, mesh=mesh8, batch_sharded=False)
        want = kv_ops.pull(t_seq, pull_idx, mesh=mesh8, batch_sharded=False)

        t_f = jax.device_put(jnp.asarray(base),
                             meshlib.table_sharding(mesh8))
        t_f, got = kv_ops.push_pull(
            t_f, idx, vals, pull_idx, mesh=mesh8, batch_sharded=False
        )
        assert np.array_equal(np.asarray(got), np.asarray(want))
        assert np.array_equal(np.asarray(t_f), np.asarray(t_seq))

    def test_kv_vector_push_pull_matches_sequenced(self, mesh8):
        keys = np.array([3, 17, 40, 99], dtype=np.int64)
        vals = np.arange(8, dtype=np.float32).reshape(4, 2)

        kv_a = KVVector(mesh=mesh8, k=2, num_slots=64, hashed=False)
        kv_a.set_keys(0, keys)
        kv_a.wait(kv_a.push(kv_a.request(channel=0), keys=keys, values=vals))
        want = kv_a.values(0, keys)

        kv_b = KVVector(mesh=mesh8, k=2, num_slots=64, hashed=False)
        kv_b.set_keys(0, keys)
        got = np.asarray(kv_b.wait_pull(
            kv_b.push_pull(kv_b.request(channel=0), keys=keys, values=vals)
        ))
        assert np.array_equal(got, want)
        # fused result aggregates on REPEAT too (push adds)
        got2 = np.asarray(kv_b.wait_pull(
            kv_b.push_pull(kv_b.request(channel=0), keys=keys, values=vals)
        ))
        np.testing.assert_allclose(got2, 2 * vals)

    def test_kv_vector_push_pull_distinct_pull_keys(self, mesh8):
        keys = np.array([1, 5], dtype=np.int64)
        all_keys = np.array([1, 5, 9], dtype=np.int64)
        kv = KVVector(mesh=mesh8, k=1, num_slots=32, hashed=False)
        kv.set_keys(0, all_keys)
        got = np.asarray(kv.wait_pull(kv.push_pull(
            kv.request(channel=0), keys=keys,
            values=np.ones((2, 1), np.float32), pull_keys=all_keys,
        )))
        np.testing.assert_allclose(got, [[1.0], [1.0], [0.0]])

    def test_kv_layer_push_pull_matches_sequenced(self, mesh8):
        a = KVLayer(partition_thr=4, updater=SGDUpdater(lr=0.5), mesh=mesh8)
        a.init_layer("w", (8, 2))
        a.wait(a.push(a.request(), "w", jnp.ones((8, 2))))
        want = np.asarray(a.wait_pull(a.pull(a.request(), "w")))

        b = KVLayer(partition_thr=4, updater=SGDUpdater(lr=0.5), mesh=mesh8)
        b.init_layer("w", (8, 2))
        got = np.asarray(b.wait_pull(
            b.push_pull(b.request(), "w", jnp.ones((8, 2)))
        ))
        assert np.array_equal(got, want)

    def test_fused_dispatch_histogram_observes(self, mesh8):
        kv = KVVector(mesh=mesh8, k=1, num_slots=32, hashed=False)
        kv.set_keys(0, np.array([2], dtype=np.int64))
        hist = telemetry_registry.default_registry().get(
            "ps_kvops_fused_dispatch_seconds"
        )
        before = hist.count() if hist is not None else 0
        kv.wait_pull(kv.push_pull(
            kv.request(channel=0), keys=np.array([2], dtype=np.int64),
            values=np.ones((1, 1), np.float32),
        ))
        hist = telemetry_registry.default_registry().get(
            "ps_kvops_fused_dispatch_seconds"
        )
        assert hist is not None and hist.count() >= before + 1


class TestSlotDirectoryCache:
    def test_repeat_key_set_hits_and_reuses_device_upload(self, mesh8):
        kv = KVVector(mesh=mesh8, k=1, num_slots=64, hashed=True)
        keys = np.random.default_rng(0).integers(0, 1 << 30, 256)
        h0 = _counter("ps_directory_slot_cache_hits_total")
        m0 = _counter("ps_directory_slot_cache_misses_total")
        s1 = kv.slots(0, keys)
        s2 = kv.slots(0, keys)
        assert s2 is s1  # same cached device array — no re-upload
        assert _counter("ps_directory_slot_cache_hits_total") == h0 + 1
        assert _counter("ps_directory_slot_cache_misses_total") == m0 + 1

    def test_signature_collision_cannot_serve_wrong_slots(self):
        """Two key arrays identical in the signed PREFIX but different
        beyond it must not alias cache entries: hits verify the full
        array, so the second lookup recomputes."""
        d = KeyDirectory(1 << 20, hashed=True)
        n = (d.MAX_SIG_LEN // 8) + 64  # int64 keys: prefix covers 256
        a = np.arange(n, dtype=np.int64)
        b = a.copy()
        b[-1] = 1 << 40  # differs past the signature prefix only
        sa = d.slots(a)
        sb = d.slots(b)
        assert sa[-1] != sb[-1] or not np.array_equal(a, b)
        np.testing.assert_array_equal(sb, d._compute_slots(b))

    def test_exact_directory_cache_correct(self):
        d = KeyDirectory(16, keys=np.array([2, 5, 9]))
        q = np.array([5, 9, 7])
        np.testing.assert_array_equal(d.slots(q), [1, 2, 16])
        np.testing.assert_array_equal(d.slots(q), [1, 2, 16])  # cached

    def test_lru_eviction_bounded(self):
        d = KeyDirectory(1 << 16, hashed=True)
        for i in range(3 * d.CACHE_SLOTS):
            d.slots(np.arange(i, i + 4, dtype=np.int64))
        assert len(d._slot_cache) <= d.CACHE_SLOTS


class TestSetKeysValidation:
    def test_set_keys_canonicalizes_unsorted_duplicates(self, mesh8):
        """Regression: exact directories require sorted unique keys for
        searchsorted; raw caller order used to corrupt lookups silently."""
        kv = KVVector(mesh=mesh8, k=1, num_slots=32, hashed=False)
        kv.set_keys(0, np.array([40, 3, 99, 3, 17], dtype=np.int64))
        np.testing.assert_array_equal(
            kv.channel(0).key, [3, 17, 40, 99]
        )
        keys = np.array([3, 17, 40, 99], dtype=np.int64)
        vals = np.arange(4, dtype=np.float32).reshape(4, 1)
        kv.wait(kv.push(kv.request(channel=0), keys=keys, values=vals))
        np.testing.assert_allclose(kv.values(0, keys), vals)
        # a key NOT in the set maps to the sentinel and is dropped
        np.testing.assert_allclose(kv.values(0, np.array([7])), [[0.0]])

    def test_key_directory_rejects_unsorted(self):
        with pytest.raises(ValueError, match="unsorted"):
            KeyDirectory(16, keys=np.array([5, 2, 9]))

    def test_key_directory_rejects_duplicates(self):
        with pytest.raises(ValueError, match="duplicate"):
            KeyDirectory(16, keys=np.array([2, 2, 9]))


def test_donation_lint_passes():
    """Tier-1 guard: every data-plane jit site either donates or carries
    an explicit '# no-donate:' justification (script/donation_lint.py —
    same pattern as metrics-lint)."""
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "script",
        "donation_lint.py",
    )
    spec = importlib.util.spec_from_file_location("_donation_lint", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    problems = mod.lint()
    assert problems == [], "\n".join(problems)
