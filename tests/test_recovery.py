"""Failure detection → recovery orchestration (system/recovery.py,
mirroring the reference manager's dead-node flow): a dead worker's
workloads return to the pool, a dead server's shard recovers from its
replica, each exactly once."""

import numpy as np

from parameter_server_tpu.learner.workload_pool import Workload, WorkloadPool
from parameter_server_tpu.parameter.replica import ReplicaManager
from parameter_server_tpu.system.heartbeat import HeartbeatCollector, HeartbeatReport
from parameter_server_tpu.system.recovery import RecoveryCoordinator


def _collector(timeout=5.0):
    c = HeartbeatCollector(timeout=timeout)
    for nid in ("W0", "W1", "S0"):
        c.report(nid, HeartbeatReport(hostname=nid))
    return c


def test_dead_worker_workload_restored():
    c = _collector()
    pool = WorkloadPool(Workload(files=["a", "b", "c"]))
    got_w0 = pool.assign("W0")
    pool.assign("W1")
    assert got_w0 is not None

    rc = RecoveryCoordinator(c)
    rc.on_worker_dead(pool.restore)

    # nothing dead yet
    assert rc.check(now=c._last_seen["W0"] + 1) == []
    # W0 goes silent past the timeout; W1 keeps reporting
    late = c._last_seen["W0"] + 6
    c.report("W1", HeartbeatReport())
    c.report("S0", HeartbeatReport())
    c._last_seen["W1"] = late
    c._last_seen["S0"] = late
    assert rc.check(now=late) == ["W0"]
    # W0's files are assignable again — a live worker picks them up
    again = pool.assign("W1")
    assert again is not None
    assert set(again.files) & set(got_w0.files)
    # exactly-once: a second pass does not re-fire
    assert rc.check(now=late + 1) == []


def test_dead_server_recovers_from_replica(mesh8):
    from parameter_server_tpu.parameter.kv_vector import KVVector

    c = _collector()
    kv = KVVector(mesh=mesh8, k=1, num_slots=32, hashed=False, name="table")
    keys = np.array([1, 5, 9], dtype=np.int64)
    kv.set_keys(0, keys)
    kv.wait(kv.push(kv.request(channel=0), keys=keys, values=np.ones((3, 1), np.float32)))

    rm = ReplicaManager()
    rm.backup(kv)

    # "S0 dies": wipe the table, as a replacement shard would start empty
    kv.set_table(0, kv._zeros())
    recovered = []

    def recover_server(nid):
        assert rm.recover(kv)
        recovered.append(nid)

    rc = RecoveryCoordinator(c)
    rc.on_server_dead(recover_server)
    assert rc.check(now=c._last_seen["S0"] + 6) != []
    assert "S0" in recovered
    np.testing.assert_allclose(kv.values(0, keys), np.ones((3, 1)))


def test_revive_allows_redetection():
    c = _collector()
    rc = RecoveryCoordinator(c)
    seen = []
    rc.on_worker_dead(seen.append)
    t0 = c._last_seen["W0"]
    rc.check(now=t0 + 6)
    rc.revive("W0")
    rc.check(now=t0 + 12)
    assert seen.count("W0") == 2


def test_handler_exception_does_not_block_others():
    c = _collector()
    rc = RecoveryCoordinator(c)
    calls = []
    rc.on_worker_dead(lambda nid: (_ for _ in ()).throw(RuntimeError("boom")))
    rc.on_worker_dead(calls.append)
    t0 = c._last_seen["W0"]
    handled = rc.check(now=t0 + 6)
    assert "W0" in handled and "W0" in calls
