"""Sharded big-table correctness on the virtual 8-mesh.

BASELINE.json's north star is Criteo-1TB (~800M keys ≈ 2^29.6). One v5e
chip holds a 2^28-2^29-slot FTRL table (2 f32/slot; measured on-chip by
script/onchip.py's `scale` task); this file proves the SHARDED paths are
correct at that slot count — key routing, push aggregation, pull
assembly, and a real training step — on the 8-device CPU mesh, where
round-2 coverage stopped at 2^26.

The 2^29 case allocates ~4.3 GB of table state; it is skipped unless
PS_BIG_TABLE=1 so CI stays light (run manually / by the onchip watcher's
host; results recorded in doc/ROUND3_NOTES.md). A 2^24 case runs always
to keep the code path exercised.
"""

import os

import numpy as np
import pytest

from parameter_server_tpu.parameter.kv_vector import KVVector
from parameter_server_tpu.system.postoffice import Postoffice


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    yield
    Postoffice.reset()


def _roundtrip(mesh8, num_slots: int) -> None:
    kv = KVVector(mesh=mesh8, k=1, num_slots=num_slots, hashed=True)
    rng = np.random.default_rng(0)
    keys = np.unique(rng.integers(0, 1 << 62, 1 << 14).astype(np.int64))
    # distinct keys may share a hashed slot (expected ~(n^2/2)/num_slots
    # of them); exact roundtrip only holds for collision-free keys, so
    # assert on those — slot ROUTING correctness is what's under test
    slots = kv.slots(0, keys)
    _, first_idx, counts = np.unique(
        np.asarray(slots), return_index=True, return_counts=True
    )
    keys = keys[np.sort(first_idx[counts == 1])]
    assert len(keys) > (1 << 13)  # collisions must stay rare
    vals = rng.normal(size=(len(keys), 1)).astype(np.float32)
    kv.wait(kv.push(kv.request(channel=0), keys=keys, values=vals))
    got = kv.values(0, keys)
    np.testing.assert_allclose(got, vals, rtol=1e-6)
    # second push aggregates (PLUS semantics, ref aggregation_ps.cc)
    kv.wait(kv.push(kv.request(channel=0), keys=keys, values=vals))
    np.testing.assert_allclose(kv.values(0, keys), 2 * vals, rtol=1e-6)


def test_sharded_table_2e24(mesh8):
    _roundtrip(mesh8, 1 << 24)


@pytest.mark.skipif(
    not os.environ.get("PS_BIG_TABLE"),
    reason="~4.3 GB table state; set PS_BIG_TABLE=1 to run",
)
def test_sharded_table_2e29(mesh8):
    _roundtrip(mesh8, 1 << 29)


@pytest.mark.skipif(
    not os.environ.get("PS_BIG_TABLE"),
    reason="~6.4 GB table state; set PS_BIG_TABLE=1 to run",
)
def test_sharded_table_800m(mesh8):
    """The north-star key count itself (BASELINE.json: Criteo-1TB ~800M
    keys), sharded over the 8-mesh: one chip tops out at 2^29 slots
    under the tunnel's compile helper (BENCH_ONCHIP.md scale task), so
    800M is precisely the table that NEEDS the server axis — the same
    argument as the reference's multi-server sharding."""
    _roundtrip(mesh8, 800_000_000)


@pytest.mark.skipif(
    not os.environ.get("PS_BIG_TABLE"),
    reason="~2+ GB FTRL state; set PS_BIG_TABLE=1 to run",
)
def test_training_step_2e28(mesh8):
    """One fused async-SGD step against a 2^28-slot sharded FTRL table:
    the full pull->grad->push->update wire at north-star slot counts."""
    from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker
    from parameter_server_tpu.apps.linear.config import (
        Config,
        LearningRateConfig,
        PenaltyConfig,
        SGDConfig,
    )
    from parameter_server_tpu.utils.sparse import random_sparse

    conf = Config()
    conf.penalty = PenaltyConfig(type="l1", lambda_=[0.01])
    conf.learning_rate = LearningRateConfig(type="decay", alpha=0.5, beta=1.0)
    conf.async_sgd = SGDConfig(
        algo="ftrl", ada_grad=True, minibatch=256, num_slots=1 << 28,
        max_delay=0,
    )
    worker = AsyncSGDWorker(conf, mesh=mesh8)
    rng = np.random.default_rng(1)
    w_true = (rng.normal(size=512) * (rng.random(512) < 0.2)).astype(np.float32)
    prog = worker.train(
        random_sparse(256, 512, 8, seed=i, w_true=w_true) for i in range(8)
    )
    ev = worker.evaluate(random_sparse(1000, 512, 8, seed=99, w_true=w_true))
    assert np.isfinite(ev["logloss"])
    assert ev["auc"] > 0.6  # it actually learns against the 2^28 table


class TestInt32Boundary:
    """2^31-slot addressing: slot ids occupy the full non-negative int32
    lattice, so every Python-int operand derived from ``num_slots`` (the
    ``axis_index * shard`` localization, the one-past-the-end sentinel,
    the ``slots < num_slots`` valid mask) overflows jnp/np int32 parsing
    at exactly this size. These tests pin the int32-safe forms without
    allocating any table (the 2^31 SPEED capture is script/onchip.py's
    ``2e31_bf16n_sparse`` on-chip task)."""

    def test_localize_one_shard_2e31(self):
        import jax
        import jax.numpy as jnp

        from parameter_server_tpu.ops.kv_ops import localize

        ids = jnp.array([0, 5, (1 << 31) - 1, -1], jnp.int32)
        rel, ok = jax.jit(lambda i: localize(i, 1 << 31))(ids)
        np.testing.assert_array_equal(
            np.asarray(rel), [0, 5, (1 << 31) - 1, 0]
        )
        np.testing.assert_array_equal(
            np.asarray(ok), [True, True, True, False]
        )

    def test_localize_rejects_beyond_int32(self):
        import jax.numpy as jnp
        import pytest as _pytest

        from parameter_server_tpu.ops.kv_ops import localize

        with _pytest.raises(ValueError, match="int32"):
            localize(jnp.array([0], jnp.int32), 1 << 32)

    def test_localize_matches_reference_formula_sharded(self, mesh8):
        """On real shards (< 2^31) localize must equal the original
        ``clip(idx - lo)`` arithmetic, per server shard."""
        import jax
        import jax.numpy as jnp
        from parameter_server_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from parameter_server_tpu.ops.kv_ops import localize
        from parameter_server_tpu.parallel.mesh import SERVER_AXIS

        shard = 16

        def local(ix):
            rel, ok = localize(ix, shard)
            lo = jax.lax.axis_index(SERVER_AXIS) * shard
            rel_ref = jnp.clip(ix - lo, 0, shard - 1)
            ok_ref = ((ix - lo) >= 0) & ((ix - lo) < shard)
            return (
                (rel == rel_ref).all() & (ok == ok_ref).all()
            ).astype(jnp.int32)[None]

        ids = jnp.array([0, 3, 15, 16, 31, 32, -1], jnp.int32)
        out = shard_map(
            local, mesh=mesh8, in_specs=P(), out_specs=P(SERVER_AXIS),
        )(ids)
        assert np.asarray(out).all()

    def test_sentinel_and_valid_mask(self):
        import jax.numpy as jnp

        from parameter_server_tpu.ops.kv_ops import slot_sentinel, valid_slots

        assert slot_sentinel(1 << 24) == 1 << 24
        assert slot_sentinel((1 << 31) - 8) == (1 << 31) - 8
        assert slot_sentinel(1 << 31) == -1
        np.testing.assert_array_equal(
            np.asarray(
                valid_slots(jnp.array([0, 7, -1], jnp.int32), 1 << 31)
            ),
            [True, True, False],
        )
        np.testing.assert_array_equal(
            np.asarray(valid_slots(jnp.array([0, 8], jnp.int32), 8)),
            [True, False],
        )

    def test_prep_batch_2e31_host_side(self):
        """Host prep at num_slots = 2^31 must produce int32 slot arrays
        with the -1 sentinel (np.full with 2^31 would raise)."""
        from parameter_server_tpu.apps.linear.async_sgd import prep_batch
        from parameter_server_tpu.parameter.parameter import KeyDirectory
        from parameter_server_tpu.utils.sparse import random_sparse

        d = KeyDirectory(1 << 31, hashed=True)
        batch = random_sparse(64, 1 << 20, 8, seed=0, binary=True)
        out = prep_batch(batch, d, 1, 64, 1024, 1024, 1 << 31)
        assert out.uslots.dtype == np.int32
        assert (out.uslots[out.umask == 0] == -1).all()
        valid = out.uslots[out.umask > 0]
        assert (valid >= 0).all()
