"""Sharded big-table correctness on the virtual 8-mesh.

BASELINE.json's north star is Criteo-1TB (~800M keys ≈ 2^29.6). One v5e
chip holds a 2^28-2^29-slot FTRL table (2 f32/slot; measured on-chip by
script/onchip.py's `scale` task); this file proves the SHARDED paths are
correct at that slot count — key routing, push aggregation, pull
assembly, and a real training step — on the 8-device CPU mesh, where
round-2 coverage stopped at 2^26.

The 2^29 case allocates ~4.3 GB of table state; it is skipped unless
PS_BIG_TABLE=1 so CI stays light (run manually / by the onchip watcher's
host; results recorded in doc/ROUND3_NOTES.md). A 2^24 case runs always
to keep the code path exercised.
"""

import os

import numpy as np
import pytest

from parameter_server_tpu.parameter.kv_vector import KVVector
from parameter_server_tpu.system.postoffice import Postoffice


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    yield
    Postoffice.reset()


def _roundtrip(mesh8, num_slots: int) -> None:
    kv = KVVector(mesh=mesh8, k=1, num_slots=num_slots, hashed=True)
    rng = np.random.default_rng(0)
    keys = np.unique(rng.integers(0, 1 << 62, 1 << 14).astype(np.int64))
    # distinct keys may share a hashed slot (expected ~(n^2/2)/num_slots
    # of them); exact roundtrip only holds for collision-free keys, so
    # assert on those — slot ROUTING correctness is what's under test
    slots = kv.slots(0, keys)
    _, first_idx, counts = np.unique(
        np.asarray(slots), return_index=True, return_counts=True
    )
    keys = keys[np.sort(first_idx[counts == 1])]
    assert len(keys) > (1 << 13)  # collisions must stay rare
    vals = rng.normal(size=(len(keys), 1)).astype(np.float32)
    kv.wait(kv.push(kv.request(channel=0), keys=keys, values=vals))
    got = kv.values(0, keys)
    np.testing.assert_allclose(got, vals, rtol=1e-6)
    # second push aggregates (PLUS semantics, ref aggregation_ps.cc)
    kv.wait(kv.push(kv.request(channel=0), keys=keys, values=vals))
    np.testing.assert_allclose(kv.values(0, keys), 2 * vals, rtol=1e-6)


def test_sharded_table_2e24(mesh8):
    _roundtrip(mesh8, 1 << 24)


@pytest.mark.skipif(
    not os.environ.get("PS_BIG_TABLE"),
    reason="~4.3 GB table state; set PS_BIG_TABLE=1 to run",
)
def test_sharded_table_2e29(mesh8):
    _roundtrip(mesh8, 1 << 29)


@pytest.mark.skipif(
    not os.environ.get("PS_BIG_TABLE"),
    reason="~6.4 GB table state; set PS_BIG_TABLE=1 to run",
)
def test_sharded_table_800m(mesh8):
    """The north-star key count itself (BASELINE.json: Criteo-1TB ~800M
    keys), sharded over the 8-mesh: one chip tops out at 2^29 slots
    under the tunnel's compile helper (BENCH_ONCHIP.md scale task), so
    800M is precisely the table that NEEDS the server axis — the same
    argument as the reference's multi-server sharding."""
    _roundtrip(mesh8, 800_000_000)


@pytest.mark.skipif(
    not os.environ.get("PS_BIG_TABLE"),
    reason="~2+ GB FTRL state; set PS_BIG_TABLE=1 to run",
)
def test_training_step_2e28(mesh8):
    """One fused async-SGD step against a 2^28-slot sharded FTRL table:
    the full pull->grad->push->update wire at north-star slot counts."""
    from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker
    from parameter_server_tpu.apps.linear.config import (
        Config,
        LearningRateConfig,
        PenaltyConfig,
        SGDConfig,
    )
    from parameter_server_tpu.utils.sparse import random_sparse

    conf = Config()
    conf.penalty = PenaltyConfig(type="l1", lambda_=[0.01])
    conf.learning_rate = LearningRateConfig(type="decay", alpha=0.5, beta=1.0)
    conf.async_sgd = SGDConfig(
        algo="ftrl", ada_grad=True, minibatch=256, num_slots=1 << 28,
        max_delay=0,
    )
    worker = AsyncSGDWorker(conf, mesh=mesh8)
    rng = np.random.default_rng(1)
    w_true = (rng.normal(size=512) * (rng.random(512) < 0.2)).astype(np.float32)
    prog = worker.train(
        random_sparse(256, 512, 8, seed=i, w_true=w_true) for i in range(8)
    )
    ev = worker.evaluate(random_sparse(1000, 512, 8, seed=99, w_true=w_true))
    assert np.isfinite(ev["logloss"])
    assert ev["auc"] > 0.6  # it actually learns against the 2^28 table
