"""Binary matrix container + MATLAB-toolbox parity (ref src/data/matlab:
bin2mat/save_bin/load_bin/saveas_pserver/filter_fea, and the
writeToBinFile layout in src/util/sparse_matrix.h)."""

import numpy as np
import pytest

from parameter_server_tpu.data import binmat
from parameter_server_tpu.data.text_parser import (
    parse_ps_sparse,
    parse_ps_sparse_binary,
)
from parameter_server_tpu.utils.sparse import random_sparse


def test_save_load_bin_roundtrip(tmp_path):
    p = str(tmp_path / "v.bin")
    x = np.arange(17, dtype=np.float64)
    binmat.save_bin(p, x)
    np.testing.assert_array_equal(binmat.load_bin(p), x)
    # offset/count slicing like load_bin.m
    np.testing.assert_array_equal(binmat.load_bin(p, "float64", 5, 3), x[5:8])
    # dtype override
    binmat.save_bin(p, x, np.uint32)
    assert binmat.load_bin(p, np.uint32).dtype == np.uint32


def test_dense_mat2bin_roundtrip(tmp_path):
    name = str(tmp_path / "D")
    m = np.arange(12, dtype=np.float64).reshape(3, 4)
    binmat.mat2bin(name, m)
    np.testing.assert_array_equal(binmat.bin2mat(name), m)


def test_sparse_mat2bin_roundtrip(tmp_path):
    name = str(tmp_path / "S")
    b = random_sparse(16, 64, 4, seed=0)
    keys = np.arange(64, dtype=np.uint64)
    binmat.mat2bin(name, b, keys=keys)
    b2, keys2 = binmat.bin2mat(name)
    np.testing.assert_array_equal(b2.indptr, b.indptr)
    np.testing.assert_array_equal(b2.indices, b.indices)
    np.testing.assert_allclose(b2.values, b.values, rtol=1e-6)
    np.testing.assert_array_equal(keys2, keys)


def test_sparse_mat2bin_wide_indices_roundtrip(tmp_path):
    # non-localized global 64-bit hash keys (criteo) must not be wrapped
    # into uint32 — mat2bin widens sizeof_index to 8 (ADVICE r1)
    name = str(tmp_path / "W")
    idx = np.array(
        [5, 2**32 + 7, np.int64(np.uint64(2**63 + 11).view(np.int64))],
        dtype=np.int64,
    )
    b = random_sparse(3, 8, 1, seed=0)
    b.indices = idx
    b.num_cols = None
    binmat.mat2bin(name, b)
    b2, _ = binmat.bin2mat(name)
    np.testing.assert_array_equal(b2.indices, idx)


def test_sparse_binary_mat2bin_roundtrip(tmp_path):
    name = str(tmp_path / "B")
    b = random_sparse(8, 32, 3, seed=1, binary=True)
    binmat.mat2bin(name, b)
    b2, keys = binmat.bin2mat(name)
    assert b2.binary and keys is None
    np.testing.assert_array_equal(b2.indices, b.indices)


@pytest.mark.parametrize("binary", [False, True])
def test_saveas_pserver_parses_back(tmp_path, binary):
    b = random_sparse(10, 40, 5, seed=2, binary=binary)
    p = str(tmp_path / "ps.txt")
    binmat.saveas_pserver(p, np.where(b.y > 0, 1, -1), b)
    lines = open(p).read().splitlines()
    parsed = (parse_ps_sparse_binary if binary else parse_ps_sparse)(lines)
    assert parsed.n == b.n and parsed.nnz == b.nnz


def test_saveas_pserver_rejects_unsorted_groups(tmp_path):
    b = random_sparse(4, 8, 2, seed=3)
    gid = np.array([1, 0] + [2] * 6)
    with pytest.raises(ValueError):
        binmat.saveas_pserver(str(tmp_path / "x"), b.y, b, group_id=gid)


def test_filter_fea_drops_rare():
    b = random_sparse(64, 32, 4, seed=4)
    fb, keep = binmat.filter_fea(b, 2)
    _, counts = np.unique(b.indices, return_counts=True)
    assert len(keep) == (counts > 2).sum()
    assert fb.cols == len(keep)
    assert fb.nnz <= b.nnz
