"""Direct unit coverage for modules previously tested only transitively:
kv_store façade, model_evaluation, utils.concurrent, utils.resource_usage."""

import numpy as np
import pytest

from parameter_server_tpu.system.postoffice import Postoffice


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    yield
    Postoffice.reset()


class TestKVStoreFacade:
    def test_factory_returns_each_kind(self, mesh8):
        from parameter_server_tpu.parameter.kv_layer import KVLayer
        from parameter_server_tpu.parameter.kv_map import AddEntry, KVMap
        from parameter_server_tpu.parameter.kv_store import kv_store
        from parameter_server_tpu.parameter.kv_vector import KVVector

        v = kv_store("vector", mesh=mesh8, k=2, num_slots=64, hashed=True)
        assert isinstance(v, KVVector)
        m = kv_store(
            "map", entry=AddEntry(), mesh=mesh8, k=1, num_slots=32,
            keys=np.array([1, 2]),
        )
        assert isinstance(m, KVMap)
        l = kv_store("layer", mesh=mesh8)
        assert isinstance(l, KVLayer)
        with pytest.raises(ValueError, match="unknown"):
            kv_store("tree")

    def test_factory_vector_works_end_to_end(self, mesh8):
        from parameter_server_tpu.parameter.kv_store import kv_store

        kv = kv_store("vector", mesh=mesh8, k=2, num_slots=64, hashed=False)
        keys = np.array([3, 17], dtype=np.int64)
        kv.set_keys(0, keys)
        vals = np.arange(4, dtype=np.float32).reshape(2, 2)
        kv.wait(kv.push(kv.request(channel=0), keys=keys, values=vals))
        np.testing.assert_allclose(kv.values(0, keys), vals)


class TestModelEvaluation:
    def _libsvm(self, path, rows):
        with open(path, "w") as f:
            for y, feats in rows:
                s = " ".join(f"{k}:{v}" for k, v in feats)
                f.write(f"{y} {s}\n")

    def test_manual_model_auc(self, tmp_path):
        """Hand-built model + validation file: xw and AUC computed by the
        same rules the reference's Run() uses."""
        from parameter_server_tpu.apps.linear.config import Config, DataConfig
        from parameter_server_tpu.apps.linear.model_evaluation import (
            ModelEvaluation,
        )

        (tmp_path / "model_S0").write_text("1\t2.0\n3\t-1.5\n")
        val = tmp_path / "val.libsvm"
        # margins: row0 = 2.0 (key1), row1 = -1.5 (key3), row2 = 0.5
        self._libsvm(
            val,
            [
                (1, [(1, 1.0)]),
                (-1, [(3, 1.0)]),
                (1, [(1, 1.0), (3, 1.0)]),
            ],
        )
        conf = Config()
        conf.model_input = DataConfig(file=[str(tmp_path / "model_S*")])
        conf.validation_data = DataConfig(
            format="text", text="libsvm", file=[str(val)]
        )
        ev = ModelEvaluation(conf)
        metrics = ev.run()
        assert metrics["auc"] == 1.0  # positives strictly above the negative
        assert metrics["accuracy"] == 1.0

    def test_roundtrip_with_async_sgd_save_model(self, mesh8, tmp_path):
        """Train -> save_model (hashed header, per-shard files) ->
        ModelEvaluation must agree with the worker's own evaluate()."""
        from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker
        from parameter_server_tpu.apps.linear.config import (
            Config,
            DataConfig,
            LearningRateConfig,
            PenaltyConfig,
            SGDConfig,
        )
        from parameter_server_tpu.apps.linear.model_evaluation import (
            ModelEvaluation,
        )
        from parameter_server_tpu.utils.sparse import random_sparse

        conf = Config()
        conf.penalty = PenaltyConfig(type="l1", lambda_=[0.01])
        conf.learning_rate = LearningRateConfig(
            type="decay", alpha=0.5, beta=1.0
        )
        conf.async_sgd = SGDConfig(
            algo="ftrl", minibatch=128, num_slots=256, max_delay=0
        )
        w = AsyncSGDWorker(conf, mesh=mesh8)
        rng = np.random.default_rng(0)
        for i in range(5):
            b = random_sparse(128, 512, 4, seed=i, binary=True)
            b.y = np.where(
                (b.indices.reshape(128, -1) % 7 < 3).mean(1) > 0.4, 1.0, -1.0
            ).astype(np.float32)
            w.collect(w.process_minibatch(b))
        val = random_sparse(200, 512, 4, seed=99, binary=True)
        val.y = np.where(
            (val.indices.reshape(200, -1) % 7 < 3).mean(1) > 0.4, 1.0, -1.0
        ).astype(np.float32)
        want = w.evaluate(val)

        model = str(tmp_path / "model")
        w.save_model(model)
        vpath = tmp_path / "val.libsvm"
        rows = []
        for r in range(val.n):
            ks = np.sort(val.indices[val.indptr[r] : val.indptr[r + 1]])
            rows.append((int(val.y[r]), [(int(k), 1) for k in ks]))
        self._libsvm(vpath, rows)
        conf2 = Config()
        conf2.model_input = DataConfig(file=[model + "_S*"])
        conf2.validation_data = DataConfig(
            format="text", text="libsvm", file=[str(vpath)]
        )
        metrics = ModelEvaluation(conf2).run()
        np.testing.assert_allclose(metrics["auc"], want["auc"], atol=1e-6)


class TestConcurrent:
    def test_threadsafe_queue(self):
        from parameter_server_tpu.utils.concurrent import ThreadsafeQueue

        q = ThreadsafeQueue()
        q.push(1)
        q.push(2)
        assert q.wait_and_pop() == 1
        assert q.try_pop() == 2
        assert q.try_pop() is None
        assert q.empty()

    def test_producer_consumer_streams_in_order(self):
        from parameter_server_tpu.utils.concurrent import ProducerConsumer

        pc = ProducerConsumer(capacity=4)
        it = iter(range(100))
        pc.start_producer(lambda: next(it, None))
        assert list(pc) == list(range(100))
        # end-of-stream is sticky: later pops keep returning None
        assert pc.pop() is None

    def test_thread_pool_runs_everything(self):
        import threading

        from parameter_server_tpu.utils.concurrent import ThreadPool

        done = []
        lock = threading.Lock()

        def work(i):
            def run():
                with lock:
                    done.append(i)

            return run

        pool = ThreadPool(4)
        for i in range(32):
            pool.add(work(i))
        pool.start_workers()  # blocks until all queued tasks ran
        assert sorted(done) == list(range(32))


class TestResourceUsage:
    def test_sample_reads_proc(self):
        from parameter_server_tpu.utils import resource_usage

        u = resource_usage.sample()
        assert u.rss_mb > 0
        assert u.vm_mb >= u.rss_mb
        # cpu percent needs a delta; a second sample must not crash
        u2 = resource_usage.sample()
        assert u2.rss_mb > 0
