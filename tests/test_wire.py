"""Compact host→device wire (learner/wire.py + ops/wire_codec.py).

Contract under test — the PR's wire counterpart of PR 3's ingest
determinism contract:

1. the default ``exact`` mode is BIT-IDENTICAL: every decoded array
   equals the raw wire's, dtype included, and whole training
   trajectories match bit-for-bit (raw vs encoded, serial vs
   pipelined-with-cache);
2. quantized modes stay within the configured logloss-parity bound;
3. encode never guesses: a batch outside a verified encoding domain
   falls back to the raw wire (None), never to wrong bytes;
4. stateful wire stages stay OFF the trainer thread (the
   stateless-or-feeder rule): encode runs on the prep pool,
   UploadCache on the uploader thread, and the cache is single-owner
   by assertion.
"""

import dataclasses
import os
import threading

import numpy as np
import pytest

from parameter_server_tpu.apps.linear.async_sgd import (
    AsyncSGDWorker,
    PreppedBatch,
    prep_batch,
    prep_batch_shared,
)
from parameter_server_tpu.apps.linear.config import (
    Config,
    LearningRateConfig,
    PenaltyConfig,
    SGDConfig,
)
from parameter_server_tpu.learner import wire
from parameter_server_tpu.ops import wire_codec as wc
from parameter_server_tpu.parameter.parameter import KeyDirectory
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.utils.sparse import SparseBatch, random_sparse

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "wire_parity.libsvm")

PREPPED_FIELDS = [f.name for f in dataclasses.fields(PreppedBatch)]


def fixture_batches(binary: bool = False, minibatch: int = 32):
    from parameter_server_tpu.data.stream_reader import StreamReader

    out = []
    for b in StreamReader([FIXTURE], "libsvm").minibatches(minibatch):
        if binary:
            b = SparseBatch(y=b.y, indptr=b.indptr, indices=b.indices)
        out.append(b)
    return out


def synth_batch(n=64, lanes=8, seed=0, binary=True):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 1 << 31, (n, lanes)).astype(np.int64)
    indptr = np.arange(0, n * lanes + 1, lanes)
    y = rng.choice((-1.0, 1.0), n).astype(np.float32)
    vals = (
        None if binary else (rng.random(n * lanes) + 0.5).astype(np.float32)
    )
    return SparseBatch(y=y, indptr=indptr, indices=keys.ravel(), values=vals)


def assert_batches_identical(raw: PreppedBatch, dec: tuple, skip=()):
    for name, arr in zip(PREPPED_FIELDS, dec):
        if name in skip:
            continue
        want = np.asarray(getattr(raw, name))
        got = np.asarray(arr)
        assert want.dtype == got.dtype, (name, want.dtype, got.dtype)
        np.testing.assert_array_equal(want, got, err_msg=name)


class TestDecodeOps:
    """Each decode op against its numpy ground truth."""

    def test_row_ids_general(self):
        counts = np.array([3, 0, 2, 0, 0, 4, 1, 0], np.uint8)
        nnz = int(counts.sum())
        nnz_pad = 16
        want = np.zeros(nnz_pad, np.int32)
        want[:nnz] = np.repeat(np.arange(8), counts)
        got = np.asarray(wc.decode_row_ids(counts, nnz, nnz_pad))
        np.testing.assert_array_equal(got, want)

    def test_row_ids_trailing_empty_and_full(self):
        # trailing all-empty rows drop their start markers at exactly
        # nnz == nnz_pad — mode='drop' must not wrap them around
        counts = np.array([4, 4, 0, 0], np.uint8)
        got = np.asarray(wc.decode_row_ids(counts, 8, 8))
        np.testing.assert_array_equal(
            got, np.repeat(np.arange(2), 4).astype(np.int32)
        )

    def test_row_ids_empty_batch(self):
        got = np.asarray(
            wc.decode_row_ids(np.zeros(4, np.uint8), 0, 8)
        )
        np.testing.assert_array_equal(got, np.zeros(8, np.int32))

    def test_sorted_deltas(self):
        uslots = np.array([5, 9, 40, 41, 1000], np.int64)
        deltas = np.diff(uslots, prepend=0).astype(np.uint16)
        padded = np.concatenate([deltas, np.zeros(3, np.uint16)])
        got = np.asarray(wc.decode_sorted_deltas(padded, 5, 4096))
        np.testing.assert_array_equal(
            got, np.concatenate([uslots, [4096] * 3]).astype(np.int32)
        )

    def test_sign_labels_pad_is_zero(self):
        y = np.array([1, -1, -1, 1, 0, 0], np.float32)
        bits = np.packbits(y > 0, bitorder="little")
        got = np.asarray(wc.decode_sign_labels(bits, 4, 6))
        np.testing.assert_array_equal(got, np.array(
            [1, -1, -1, 1, 0, 0], np.float32))

    def test_mask_and_binary_vals(self):
        np.testing.assert_array_equal(
            np.asarray(wc.decode_mask(3, 5)),
            np.array([1, 1, 1, 0, 0], np.float32),
        )
        np.testing.assert_array_equal(
            np.asarray(wc.decode_binary_vals(2, 4)),
            np.array([1, 1, 0, 0], np.float32),
        )

    def test_u24(self):
        import jax.numpy as jnp

        from parameter_server_tpu.apps.linear.async_sgd import pack_u24

        v = np.array([0, 1, 255, 256, (1 << 24) - 1], np.int32)
        np.testing.assert_array_equal(
            np.asarray(wc.decode_u24(jnp.asarray(pack_u24(v)))), v
        )


class TestEncodeExactParity:
    NUM_SLOTS = 1 << 18

    def _directory(self):
        return KeyDirectory(self.NUM_SLOTS, hashed=True)

    def _prep(self, b, shared=False):
        d = self._directory()
        rows_pad = 64
        nnz_pad = rows_pad * 16
        if shared:
            return prep_batch_shared(
                b, d, 2, rows_pad, nnz_pad, 1024, self.NUM_SLOTS
            )
        return prep_batch(
            b, d, 2, rows_pad, nnz_pad, nnz_pad, self.NUM_SLOTS
        )

    @pytest.mark.parametrize("shared", [False, True])
    def test_binary_bit_identical(self, shared):
        for b in fixture_batches(binary=True):
            raw = self._prep(b, shared)
            enc = wire.encode_exact(raw, self.NUM_SLOTS)
            assert enc is not None
            assert enc.vals_mode == "binary"  # value stream elided
            # prep_batch_shared's uslots are np.unique output → the
            # delta wire; prep_batch hashes sorted KEYS → bit-packed
            assert enc.uslots_delta == shared
            dec = wire.decode_exact_host(enc, self.NUM_SLOTS)
            assert_batches_identical(raw, dec)

    @pytest.mark.parametrize("shared", [False, True])
    def test_valued_exact_bit_identical(self, shared):
        for b in fixture_batches(binary=False):
            raw = self._prep(b, shared)
            enc = wire.encode_exact(raw, self.NUM_SLOTS, mode="exact")
            assert enc is not None
            dec = wire.decode_exact_host(enc, self.NUM_SLOTS)
            assert_batches_identical(raw, dec)

    def test_ragged_rows_bit_identical(self):
        # the fixture is ragged (3-10 features/row): row_counts +
        # decode_row_ids must reproduce the repeat structure exactly —
        # covered above; here also a batch with EMPTY rows
        rng = np.random.default_rng(3)
        counts = rng.integers(0, 5, 40)
        counts[[3, 7, 39]] = 0
        indptr = np.concatenate([[0], np.cumsum(counts)])
        idx = rng.integers(0, 1 << 30, indptr[-1]).astype(np.int64)
        b = SparseBatch(
            y=rng.choice((-1.0, 1.0), 40).astype(np.float32),
            indptr=indptr, indices=idx,
        )
        raw = self._prep(b)
        enc = wire.encode_exact(raw, self.NUM_SLOTS)
        dec = wire.decode_exact_host(enc, self.NUM_SLOTS)
        assert_batches_identical(raw, dec)

    def test_regression_labels_keep_f32(self):
        b = synth_batch(binary=False, seed=5)
        b.y[:] = np.linspace(-2, 2, b.n).astype(np.float32)
        raw = self._prep(b)
        enc = wire.encode_exact(raw, self.NUM_SLOTS)
        assert enc is not None and not enc.y_sign  # no silent sign collapse
        dec = wire.decode_exact_host(enc, self.NUM_SLOTS)
        assert_batches_identical(raw, dec)

    @pytest.mark.parametrize("mode,tol", [
        ("int8", 1.0 / 254), ("u16", 1.0 / 65534), ("bf16", 1.0 / 128),
    ])
    def test_quantized_value_error_bound(self, mode, tol):
        b = synth_batch(binary=False, seed=6)
        raw = self._prep(b)
        enc = wire.encode_exact(raw, self.NUM_SLOTS, mode=mode)
        assert enc.vals_mode == mode
        dec = wire.decode_exact_host(enc, self.NUM_SLOTS)
        assert_batches_identical(raw, dec, skip=("vals",))
        v_raw = np.asarray(raw.vals)
        v_dec = np.asarray(dec[PREPPED_FIELDS.index("vals")])
        span = v_raw.max() - v_raw.min()
        rel = np.abs(v_dec - v_raw).max() / max(span, 1e-9)
        assert rel <= 2 * tol, (mode, rel)

    def test_quantized_padding_decodes_to_exact_zero(self):
        # regression: every padding entry carries rows=0/ucols=0, so a
        # dequantized-zero code (0±step noise, and with lo<0 never
        # exactly 0) would scatter-add a padding-sized bias into
        # example 0 and uslots[0] — decode must mask past nnz
        b = synth_batch(n=40, binary=False, seed=21)
        b.values[:] = b.values - 1.0  # span negatives: lo < 0
        raw = self._prep(b)  # rows_pad 64 ⇒ plenty of padding
        enc = wire.encode_exact(raw, self.NUM_SLOTS, mode="int8")
        dec = wire.decode_exact_host(enc, self.NUM_SLOTS)
        v_dec = np.asarray(dec[PREPPED_FIELDS.index("vals")])
        nnz = np.asarray(enc.nnz)
        for d in range(v_dec.shape[0]):
            assert (v_dec[d, nnz[d]:] == 0.0).all()

    def test_quantized_scale_from_live_entries_only(self):
        # all-positive values: [lo, hi] must come from the live slice,
        # not be dragged to 0 by the zero padding (wasted resolution)
        b = synth_batch(n=40, binary=False, seed=22)  # vals in [0.5, 1.5)
        raw = self._prep(b)
        enc = wire.encode_exact(raw, self.NUM_SLOTS, mode="int8")
        assert np.asarray(enc.vals_lo).min() >= 0.5

    def test_quantized_encode_deterministic(self):
        # stochastic rounding must be content-keyed (pool workers may
        # encode in any order): same batch → same bytes, always
        b = synth_batch(binary=False, seed=7)
        raw = self._prep(b)
        e1 = wire.encode_exact(raw, self.NUM_SLOTS, mode="int8")
        e2 = wire.encode_exact(raw, self.NUM_SLOTS, mode="int8")
        np.testing.assert_array_equal(e1.vals, e2.vals)

    def test_domain_violation_falls_back(self):
        raw = self._prep(synth_batch())
        # a hole in the mask is outside the count-coded domain
        bad_mask = np.asarray(raw.mask).copy()
        bad_mask[0, 1] = 0.0
        bad = dataclasses.replace(raw, mask=bad_mask)
        assert wire.encode_exact(bad, self.NUM_SLOTS) is None
        # non-sentinel tail in uslots likewise
        bad_us = np.asarray(raw.uslots).copy()
        bad_us[0, -1] = 7
        bad2 = dataclasses.replace(raw, uslots=bad_us)
        assert wire.encode_exact(bad2, self.NUM_SLOTS) is None

    def test_unknown_mode_raises(self):
        raw = self._prep(synth_batch())
        with pytest.raises(ValueError):
            wire.encode_exact(raw, self.NUM_SLOTS, mode="fp4")

    def test_wire_shrinks(self):
        raw = self._prep(synth_batch(seed=8))
        enc = wire.encode_exact(raw, self.NUM_SLOTS)
        assert wire.tree_nbytes(enc) * 3 < wire.tree_nbytes(raw)

    def test_superbatch_stack_and_static_mismatch(self):
        raws = [self._prep(synth_batch(seed=i)) for i in range(3)]
        encs = [wire.encode_exact(r, self.NUM_SLOTS) for r in raws]
        sb = wire.stack_encoded_batches(encs)
        assert sb.steps == 3
        assert sb.num_examples == sum(e.num_examples for e in encs)
        other = dataclasses.replace(encs[0], ucols_bits=encs[0].ucols_bits + 1)
        with pytest.raises(AssertionError):
            wire.stack_encoded_batches([encs[0], other])


def _conf(update="sparse", wire_encode="", cache_mb=0, spl=1,
          minibatch=256, pull_gather="auto"):
    conf = Config()
    conf.penalty = PenaltyConfig(type="l1", lambda_=[0.05])
    conf.learning_rate = LearningRateConfig(type="decay", alpha=0.5, beta=1.0)
    conf.async_sgd = SGDConfig(
        algo="ftrl", minibatch=minibatch, num_slots=1 << 14, max_delay=0,
        update=update, wire_encode=wire_encode, wire_cache_mb=cache_mb,
        steps_per_launch=spl, pull_gather=pull_gather,
    )
    return conf


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    yield
    Postoffice.reset()


def _train_state(mesh8, batches, conf, pipelined=None):
    worker = AsyncSGDWorker(conf, mesh=mesh8)
    worker.train(iter(list(batches)), pipelined=pipelined)
    return worker, {k: np.asarray(v) for k, v in worker.state.items()}


class TestTrainParity:
    def _batches(self, n=6, binary=False):
        rng = np.random.default_rng(1)
        w_true = (rng.normal(size=512) * (rng.random(512) < 0.3)).astype(
            np.float32
        )
        return [
            random_sparse(256, 512, 8, seed=i, w_true=w_true, binary=binary)
            for i in range(n)
        ], w_true

    def test_exact_mode_trajectory_bit_identical(self, mesh8):
        batches, _ = self._batches()
        _, raw = _train_state(mesh8, batches, _conf(wire_encode=""))
        Postoffice.reset()
        worker, enc = _train_state(mesh8, batches, _conf(wire_encode="exact"))
        # the encoded path really ran (sparse mode → PreppedBatch → enc)
        assert any(k[0].startswith("exact_enc") for k in worker._steps)
        for k in raw:
            np.testing.assert_array_equal(raw[k], enc[k], err_msg=k)

    def test_pipelined_scan_cache_bit_identical(self, mesh8):
        # two passes over the same data exercise the upload key cache;
        # the trajectory must still match the serial raw wire exactly
        batches, _ = self._batches(4)
        stream = batches + batches
        _, raw = _train_state(
            mesh8, stream, _conf(wire_encode="", spl=2), pipelined=False
        )
        Postoffice.reset()
        worker, enc = _train_state(
            mesh8, stream,
            _conf(wire_encode="exact", cache_mb=32, spl=2), pipelined=True,
        )
        assert any(k[0] == "exact_enc_scan" for k in worker._steps)
        for k in raw:
            np.testing.assert_array_equal(raw[k], enc[k], err_msg=k)

    def test_quantized_mode_logloss_bound(self, mesh8):
        batches, w_true = self._batches(6)
        test = random_sparse(1000, 512, 8, seed=99, w_true=w_true)
        w_exact, _ = _train_state(mesh8, batches, _conf(wire_encode="exact"))
        ll_exact = w_exact.evaluate(test)["logloss"]
        for mode in ("int8", "bf16"):
            Postoffice.reset()
            w_q, _ = _train_state(mesh8, batches, _conf(wire_encode=mode))
            ll_q = w_q.evaluate(test)["logloss"]
            # the configured parity bound for lossy value wires: the
            # same 2% envelope bench.py grants the quantized pull
            assert abs(ll_q - ll_exact) <= max(0.01, 0.02 * ll_exact), (
                mode, ll_q, ll_exact,
            )

    def test_sparse_rejects_narrow_pull(self, mesh8):
        # ADVICE round 5: an explicit narrow gather must fail loudly in
        # sparse mode instead of silently no-op'ing
        batches, _ = self._batches(1)
        worker = AsyncSGDWorker(
            _conf(pull_gather="narrow"), mesh=mesh8
        )
        with pytest.raises(ValueError, match="narrow"):
            worker.process_minibatch(batches[0])
        Postoffice.reset()
        # auto/wide stay fine
        worker = AsyncSGDWorker(_conf(pull_gather="wide"), mesh=mesh8)
        worker.executor.wait(worker.process_minibatch(batches[0]))

    def test_bad_config_rejected(self, mesh8):
        with pytest.raises(ValueError, match="wire_encode"):
            AsyncSGDWorker(_conf(wire_encode="zstd"), mesh=mesh8)


class TestDenseGroupGate:
    """ADVICE round 5: exact-wire scan fusion is sparse-mode only —
    dense groups must stay per-minibatch (snapshot/filter semantics)."""

    def test_sparse_mode_scan_fuses(self, mesh8):
        rng = np.random.default_rng(2)
        w_true = rng.normal(size=512).astype(np.float32)
        batches = [
            random_sparse(64, 512, 8, seed=i, w_true=w_true)
            for i in range(3)
        ]
        worker = AsyncSGDWorker(_conf(update="sparse", spl=3), mesh=mesh8)
        parts = worker._prep_group(batches)
        assert len(parts) == 1 and parts[0][1] == 3

    def test_dense_mode_superbatch_raises(self, mesh8):
        # submit_superbatch carries the same gate as _prep_group: a
        # dense-mode exact group must not silently scan-fuse (the scan
        # bypasses snapshot/filter semantics) — the explicit API raises
        rng = np.random.default_rng(2)
        w_true = rng.normal(size=512).astype(np.float32)
        batches = [
            random_sparse(64, 512, 8, seed=i, w_true=w_true)
            for i in range(3)
        ]
        worker = AsyncSGDWorker(_conf(update="dense", spl=3), mesh=mesh8)
        d = KeyDirectory(1 << 14, hashed=True)
        worker.prep = lambda b, device_put=False: prep_batch(
            b, d, 4, 64, 64 * 8, 64 * 8, 1 << 14
        )
        with pytest.raises(ValueError, match="sparse-update"):
            worker.submit_superbatch(batches)

    def test_dense_mode_stays_per_minibatch(self, mesh8):
        # dense + hashed directory yields HashedBatches — not scan
        # fusible either way; emulate a dense exact-wire group directly
        rng = np.random.default_rng(2)
        w_true = rng.normal(size=512).astype(np.float32)
        batches = [
            random_sparse(64, 512, 8, seed=i, w_true=w_true)
            for i in range(3)
        ]
        worker = AsyncSGDWorker(_conf(update="dense", spl=3), mesh=mesh8)
        d = KeyDirectory(1 << 14, hashed=True)

        def exact_prep(b, device_put=False):
            return prep_batch(b, d, 4, 64, 64 * 8, 64 * 8, 1 << 14)

        worker.prep = exact_prep
        parts = worker._prep_group(batches)
        assert len(parts) == 3 and all(n == 1 for _, n in parts)
        assert all(isinstance(p, PreppedBatch) for p, _ in parts)


class TestUploadCache:
    def test_hit_miss_and_saved_bytes(self):
        uploads = []
        cache = wire.UploadCache(
            upload_leaf=lambda x: (uploads.append(x) or np.asarray(x)),
            min_leaf_bytes=1,
        )
        a = np.arange(4096, dtype=np.int32)
        t1 = cache({"slots": a, "y": np.ones(16, np.float32)})
        n1 = len(uploads)
        t2 = cache({"slots": a.copy(), "y": np.ones(16, np.float32)})
        assert cache.hits == 2 and cache.misses == 2
        assert len(uploads) == n1  # nothing re-uploaded on the repeat
        assert cache.saved_bytes == a.nbytes + 16 * 4
        np.testing.assert_array_equal(t2["slots"], t1["slots"])

    def test_signature_collision_never_serves_wrong_bytes(self):
        # array_signature hashes a 2048-byte prefix: two arrays equal in
        # the prefix but different past it COLLIDE by construction — the
        # exact verify must treat that as a miss
        cache = wire.UploadCache(upload_leaf=np.asarray, min_leaf_bytes=1)
        a = np.zeros(4096, np.uint8)
        b = a.copy()
        b[-1] = 7
        cache({"x": a})
        out = cache({"x": b})
        assert cache.hits == 0 and cache.misses == 2
        np.testing.assert_array_equal(out["x"], b)

    def test_collision_overwrite_releases_accounting(self):
        # regression: overwriting a signature-colliding entry must
        # release the displaced bytes, or phantom accounting grows
        # until the eviction loop permanently thrashes the cache
        cache = wire.UploadCache(
            upload_leaf=np.asarray, max_bytes=1 << 20, min_leaf_bytes=1
        )
        a = np.zeros(4096, np.uint8)
        b = a.copy()
        b[-1] = 7  # same 2048-byte prefix signature, different tail
        for _ in range(10):
            cache({"x": a})
            cache({"x": b})
        assert cache._bytes == 4096  # one retained entry, not phantom 80KB
        assert len(cache._cache) == 1

    def test_eviction_bounds_retained_bytes(self):
        cache = wire.UploadCache(
            upload_leaf=np.asarray, max_bytes=3 * 4096, min_leaf_bytes=1
        )
        for i in range(8):
            cache({"x": np.full(4096, i, np.uint8)})
        assert cache._bytes <= 3 * 4096
        # evicted entries miss again
        cache({"x": np.full(4096, 0, np.uint8)})
        assert cache.hits == 0

    def test_small_leaves_bypass(self):
        cache = wire.UploadCache(upload_leaf=np.asarray, min_leaf_bytes=1024)
        small = np.ones(4, np.float32)
        cache({"x": small})
        cache({"x": small})
        assert cache.hits == 0 and cache.misses == 0

    def test_single_owner_thread_asserted(self):
        cache = wire.UploadCache(upload_leaf=np.asarray, min_leaf_bytes=1)
        cache({"x": np.ones(8, np.float32)})
        err = []

        def other():
            try:
                cache({"x": np.ones(8, np.float32)})
            except RuntimeError as e:
                err.append(e)

        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert err, "cross-thread use must raise (stateful uploader stage)"


class TestOffTrainerThread:
    """The PR-3 ingest rule, wire edition (tier-1 twin of the pslint
    thread checks): encode is a stateless pool stage, the cache a
    serial uploader stage — neither may run on the trainer thread."""

    def test_encode_and_cache_stay_off_trainer_thread(
        self, mesh8, monkeypatch
    ):
        rng = np.random.default_rng(4)
        w_true = rng.normal(size=512).astype(np.float32)
        batches = [
            random_sparse(64, 512, 8, seed=i, w_true=w_true)
            for i in range(6)
        ]
        encode_threads = set()
        real_encode = wire.encode_exact

        def spy_encode(*a, **kw):
            encode_threads.add(threading.get_ident())
            return real_encode(*a, **kw)

        monkeypatch.setattr(wire, "encode_exact", spy_encode)
        caches = []
        real_cache = wire.UploadCache

        def spy_cache(*a, **kw):
            c = real_cache(*a, **kw)
            caches.append(c)
            return c

        monkeypatch.setattr(wire, "UploadCache", spy_cache)
        worker = AsyncSGDWorker(
            _conf(wire_encode="exact", cache_mb=16, spl=2, minibatch=64),
            mesh=mesh8,
        )
        worker.train(iter(batches), pipelined=True)
        me = threading.get_ident()
        assert encode_threads and me not in encode_threads, (
            "wire encode ran on the trainer thread"
        )
        assert caches and all(
            c._owner is not None and c._owner != me for c in caches
        ), "UploadCache ran on the trainer thread"


class TestUploadedBytesWithCache:
    def test_cache_hits_do_not_count_as_link_traffic(self):
        # ps_ingest_uploaded_bytes_total documents REALIZED link
        # traffic: a cache-hit batch re-uses its device buffer, so its
        # bytes must not inflate the counter (regression)
        from parameter_server_tpu.apps.linear.async_sgd import (
            DeviceUploader,
        )
        from parameter_server_tpu.telemetry import registry as treg

        if not treg.enabled():
            pytest.skip("telemetry disabled")
        from parameter_server_tpu.telemetry.instruments import (
            ingest_instruments,
        )

        tel = ingest_instruments(treg.default_registry())
        b0 = tel["uploaded_bytes"].value()
        d = KeyDirectory(1 << 18, hashed=True)
        prepped = prep_batch(
            synth_batch(seed=31), d, 2, 64, 64 * 16, 64 * 16, 1 << 18
        )
        repeat = dataclasses.replace(prepped)  # same bytes, new tree
        # expected first-pass link traffic: the cache also dedups
        # byte-identical leaves WITHIN a batch, so probe that offline
        probe = wire.UploadCache(upload_leaf=np.asarray, min_leaf_bytes=1)
        probe(prepped)
        expected = wire.tree_nbytes(prepped) - probe.saved_bytes
        cache = wire.UploadCache(upload_leaf=np.asarray, min_leaf_bytes=1)
        up = DeviceUploader(iter([(prepped, 1), (repeat, 1)]), cache, depth=2)
        list(up)
        up.close()
        # first pass ships the miss bytes, the repeat ships ~nothing
        shipped = tel["uploaded_bytes"].value() - b0
        assert shipped == expected, (shipped, expected)


class TestWireTelemetry:
    def test_instruments_advance(self):
        from parameter_server_tpu.telemetry import registry as treg

        if not treg.enabled():
            pytest.skip("telemetry disabled")
        reg = treg.default_registry()
        from parameter_server_tpu.telemetry.instruments import (
            wire_instruments,
        )

        tel = wire_instruments(reg)
        b0 = tel["bytes"].labels(encoding="exact").value
        d = KeyDirectory(1 << 18, hashed=True)
        raw = prep_batch(
            synth_batch(seed=11), d, 2, 64, 64 * 16, 64 * 16, 1 << 18
        )
        enc = wire.encode_exact(raw, 1 << 18)
        assert tel["bytes"].labels(encoding="exact").value == (
            b0 + wire.tree_nbytes(enc)
        )
        h0 = tel["cache_hits"].value()
        cache = wire.UploadCache(upload_leaf=np.asarray, min_leaf_bytes=1)
        cache({"x": np.ones(64, np.float32)})
        cache({"x": np.ones(64, np.float32)})
        assert tel["cache_hits"].value() == h0 + 1


class TestMessageWireCodec:
    def test_chain_roundtrip_and_key_cache(self):
        rng = np.random.default_rng(5)
        sender = wire.MessageWireCodec()
        receiver = wire.MessageWireCodec()
        keys = np.sort(rng.choice(1 << 30, 256, replace=False)).astype(
            np.int64
        )
        vals = (rng.random(256) < 0.1).astype(np.float32)
        m1 = sender.encode(keys.copy(), [vals.copy()])
        assert m1.key is not None
        k1, v1 = receiver.decode(m1)
        np.testing.assert_array_equal(k1, keys)
        np.testing.assert_array_equal(v1[0], vals)
        # repeat: keys ride as signature only, receiver restores them
        m2 = sender.encode(keys.copy(), [vals.copy()])
        assert m2.key is None
        k2, v2 = receiver.decode(m2)
        np.testing.assert_array_equal(k2, keys)
        np.testing.assert_array_equal(v2[0], vals)

    def test_quantized_chain_bounded(self):
        rng = np.random.default_rng(6)
        sender = wire.MessageWireCodec(num_bytes=2)
        receiver = wire.MessageWireCodec(num_bytes=2)
        vals = rng.normal(size=512).astype(np.float32)
        k, v = receiver.decode(sender.encode(None, [vals.copy()]))
        assert k is None
        step = (vals.max() - vals.min()) / 65535
        assert np.abs(v[0] - vals).max() <= step + 1e-6


# ---------------------------------------------------------------------------
# Stream-once lane-dictionary wire (wire='stream') — the cache-free
# encoding for single-epoch data, plus its native fused prep and the
# staging-leg codec. Same contract as the exact wire above: decode is
# BIT-IDENTICAL, encode never guesses (domain verify → raw fallback),
# stateless stages pool.
# ---------------------------------------------------------------------------


def _criteo_like_batches(n_batches, rows=256, lanes=8, vocab_small=60,
                         seed=7):
    """Uniform-lane binary batches with the criteo-law lane split:
    half the lanes draw from a tiny per-lane vocabulary (the integer
    count fields), half from a ~2^40 space (hashed categoricals)."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        small = rng.integers(0, vocab_small, (rows, lanes // 2))
        wide = rng.integers(0, 1 << 40, (rows, lanes - lanes // 2))
        keys = np.concatenate(
            [small + (np.arange(lanes // 2) << 50), wide], axis=1
        ).astype(np.int64)
        y = rng.choice((-1.0, 1.0), rows).astype(np.float32)
        out.append(SparseBatch(
            y=y,
            indptr=np.arange(0, rows * lanes + 1, lanes),
            indices=keys.ravel(),
        ))
    return out


class TestStreamStatics:
    NUM_SLOTS = 1 << 18

    def test_lane_split_derivation(self):
        b = _criteo_like_batches(1)[0]
        st = wire.derive_stream_statics(
            b.indices, 8, self.NUM_SLOTS, self.NUM_SLOTS
        )
        assert st is not None
        # the tiny-vocab lanes (0-3) take the dictionary, wide stay raw
        assert st.dict_lanes == (0, 1, 2, 3)
        assert 2 * st.code_bits <= st.raw_bits

    def test_no_win_returns_none(self):
        # every lane wide-vocab: no dictionary split can win
        rng = np.random.default_rng(1)
        keys = rng.integers(0, 1 << 40, 256 * 8).astype(np.int64)
        assert wire.derive_stream_statics(
            keys, 8, self.NUM_SLOTS, self.NUM_SLOTS
        ) is None

    def test_table_cost_guard(self):
        # tiny batch: per-row savings cannot amortize the table → None
        b = _criteo_like_batches(1, rows=4)[0]
        assert wire.derive_stream_statics(
            b.indices, 8, self.NUM_SLOTS, self.NUM_SLOTS
        ) is None


class TestStreamWireParity:
    NUM_SLOTS = 1 << 18

    def _prep(self, b, st, rows_pad=None, shards=2, lanes=8):
        from parameter_server_tpu.apps.linear.async_sgd import (
            prep_batch_ell_stream,
        )

        d = KeyDirectory(self.NUM_SLOTS, hashed=True)
        rows_pad = rows_pad or -(-b.n // shards)
        return prep_batch_ell_stream(
            b, d, shards, rows_pad, lanes, self.NUM_SLOTS, st
        )

    def _statics(self, b, lanes=8):
        return wire.derive_stream_statics(
            b.indices, lanes, self.NUM_SLOTS, self.NUM_SLOTS
        )

    def test_decode_bit_identical(self):
        from parameter_server_tpu.utils.murmur import hash_slots

        for b in _criteo_like_batches(3):
            st = self._statics(b)
            enc = self._prep(b, st)
            assert enc is not None
            per = -(-b.n // 2)
            for d in range(2):
                lo, hi = min(d * per, b.n), min((d + 1) * per, b.n)
                seg = slice(b.indptr[lo], b.indptr[hi])
                want = hash_slots(
                    np.ascontiguousarray(b.indices[seg], np.uint64),
                    self.NUM_SLOTS,
                ).reshape(hi - lo, 8)
                y, mask, slots = wire.decode_stream_shard(enc, d)
                got = np.asarray(slots)
                assert got.dtype == np.int32
                np.testing.assert_array_equal(got[: hi - lo], want)
                np.testing.assert_array_equal(
                    np.asarray(y)[: hi - lo], b.y[lo:hi]
                )
                np.testing.assert_array_equal(
                    np.asarray(mask),
                    (np.arange(enc.rows) < (hi - lo)).astype(np.float32),
                )

    def test_fixture_refuses_ragged(self):
        # the committed wire_parity.libsvm fixture is ragged (3-10
        # features/row) — outside the uniform-lane stream domain: the
        # encoder must REFUSE (raw fallback), never mis-encode; the
        # exact wire stays the fixture's encoded path (tested above)
        for b in fixture_batches(binary=True):
            st = wire.StreamStatics(
                lanes=8, dict_lanes=(0,), code_bits=4, dict_pad=64,
                raw_bits=18,
            )
            assert self._prep(b, st) is None

    def test_valued_and_regression_refused(self):
        b = _criteo_like_batches(1)[0]
        st = self._statics(b)
        valued = SparseBatch(
            y=b.y, indptr=b.indptr, indices=b.indices,
            values=np.ones(b.nnz, np.float32) * 2.0,
        )
        assert self._prep(valued, st) is None
        regress = SparseBatch(
            y=np.linspace(-2, 2, b.n).astype(np.float32),
            indptr=b.indptr, indices=b.indices,
        )
        assert self._prep(regress, st) is None

    def test_statics_overflow_falls_back(self):
        # pinned statics from a tiny-vocab batch; a batch whose lane
        # vocabulary blows past the padded code space must fall back
        b0 = _criteo_like_batches(1, vocab_small=16)[0]
        st = self._statics(b0)
        big = _criteo_like_batches(1, vocab_small=250, seed=9)[0]
        assert self._prep(big, st) is None
        assert self._prep(b0, st) is not None

    def test_superbatch_stack_and_static_mismatch(self):
        batches = _criteo_like_batches(3)
        st = self._statics(batches[0])
        encs = [self._prep(b, st) for b in batches]
        sb = wire.stack_stream_batches(encs)
        assert sb.steps == 3
        assert sb.num_examples == sum(e.num_examples for e in encs)
        other = dataclasses.replace(encs[0], code_bits=encs[0].code_bits + 1)
        with pytest.raises(AssertionError):
            wire.stack_stream_batches([encs[0], other])

    def test_wire_shrinks_vs_bits(self):
        from parameter_server_tpu.apps.linear.async_sgd import (
            prep_batch_ell_bits,
        )

        b = _criteo_like_batches(1, rows=1024)[0]
        st = self._statics(b)
        enc = self._prep(b, st, rows_pad=512)
        d = KeyDirectory(self.NUM_SLOTS, hashed=True)
        bits = prep_batch_ell_bits(b, d, 2, 512, 8, self.NUM_SLOTS)
        assert wire.tree_nbytes(enc) < wire.tree_nbytes(bits)


def _native_or_skip():
    from conftest import require_native

    return require_native("ps_stream_encode")


class TestNativeFusedPrep:
    """C-vs-Python fused unique+remap+encode parity: the native one-
    pass ps_stream_encode must be BYTE-IDENTICAL to the NumPy path on
    the committed ingest fixture's key stream. Skips gracefully when
    the library is absent (tier-1 on a bare checkout); `make
    native-test` sets PS_REQUIRE_NATIVE=1 to fail loudly instead."""

    NUM_SLOTS = 1 << 18
    LANES = 8

    def _fixture_keys(self):
        # the committed ingest fixture's real key bytes, reshaped to
        # uniform lanes (the stream wire's domain): same keys the PR-3
        # ingest parity contract pins
        import os as _os

        from parameter_server_tpu.data.stream_reader import StreamReader

        fx = _os.path.join(
            _os.path.dirname(__file__), "data", "ingest_parity.libsvm"
        )
        idx = np.concatenate(
            [b.indices for b in StreamReader([fx], "libsvm").minibatches(64)]
        )
        n = (idx.size // self.LANES) * self.LANES
        # fold some keys into a small per-lane vocabulary so the lane
        # dictionary engages (fixture keys are near-unique)
        keys = idx[:n].copy()
        rows = n // self.LANES
        km = keys.reshape(rows, self.LANES)
        km[:, : self.LANES // 2] = (km[:, : self.LANES // 2] % 48) + (
            np.arange(self.LANES // 2) << 50
        )
        return keys, rows

    def test_byte_identical_on_ingest_fixture(self):
        from parameter_server_tpu.utils.murmur import hash_slots

        _native_or_skip()
        keys, rows = self._fixture_keys()
        st = wire.derive_stream_statics(
            keys, self.LANES, self.NUM_SLOTS, self.NUM_SLOTS
        )
        assert st is not None and st.dict_lanes
        rows_pad = rows + 13  # exercise the zero tail too
        nat = wire.encode_stream_shard(
            keys, rows, rows_pad, self.NUM_SLOTS, st
        )
        py = wire._encode_stream_shard_py(
            hash_slots(np.ascontiguousarray(keys, np.uint64),
                       self.NUM_SLOTS),
            rows, rows_pad, st,
        )
        assert nat is not None and py is not None
        for name, a, c in zip(
            ("raw_words", "code_words", "table_words", "lane_starts",
             "n_uniq"), nat, py,
        ):
            a, c = np.asarray(a), np.asarray(c)
            assert a.dtype == c.dtype and a.shape == c.shape, name
            np.testing.assert_array_equal(a, c, err_msg=name)

    def test_overflow_agreement(self):
        # both paths must refuse the SAME batches (the fallback is part
        # of the wire format): shrink the pinned table/code space and
        # check C and Python agree on rejection
        from parameter_server_tpu.utils.murmur import hash_slots

        _native_or_skip()
        keys, rows = self._fixture_keys()
        st = wire.derive_stream_statics(
            keys, self.LANES, self.NUM_SLOTS, self.NUM_SLOTS
        )
        tight = dataclasses.replace(st, dict_pad=8, code_bits=2)
        nat = wire.encode_stream_shard(
            keys, rows, rows, self.NUM_SLOTS, tight
        )
        py = wire._encode_stream_shard_py(
            hash_slots(np.ascontiguousarray(keys, np.uint64),
                       self.NUM_SLOTS),
            rows, rows, tight,
        )
        assert nat is None and py is None


class TestStreamTrainParity:
    """The PR-5 whole-trajectory invariant, extended to the stream
    encoder: training on the stream wire (per-minibatch AND scan-fused
    AND staging-leg-compressed, pipelined) is bit-identical to the raw
    bits wire."""

    def _conf(self, wire_fmt, spl=1, compress=""):
        conf = Config()
        conf.penalty = PenaltyConfig(type="l1", lambda_=[0.05])
        conf.learning_rate = LearningRateConfig(
            type="decay", alpha=0.5, beta=1.0
        )
        conf.async_sgd = SGDConfig(
            algo="ftrl", minibatch=256, num_slots=1 << 16, max_delay=0,
            ell_lanes=8, wire=wire_fmt, steps_per_launch=spl,
            wire_compress=compress,
        )
        return conf

    def _run(self, mesh8, wire_fmt, spl=1, compress="", pipelined=None):
        Postoffice.reset()
        worker = AsyncSGDWorker(self._conf(wire_fmt, spl, compress),
                                mesh=mesh8)
        worker.train(iter(_criteo_like_batches(6)), pipelined=pipelined)
        return worker, {k: np.asarray(v) for k, v in worker.state.items()}

    def test_trajectory_bit_identical(self, mesh8):
        _, raw = self._run(mesh8, "bits")
        worker, enc = self._run(mesh8, "stream")
        assert any(k[0] == "ell_stream" for k in worker._steps), (
            "the stream path did not run"
        )
        for k in raw:
            np.testing.assert_array_equal(raw[k], enc[k], err_msg=k)

    def test_scan_compressed_pipelined_bit_identical(self, mesh8):
        _, raw = self._run(mesh8, "bits")
        worker, enc = self._run(
            mesh8, "stream", spl=2, compress="lz", pipelined=True
        )
        assert any(k[0] == "ell_stream_scan" for k in worker._steps)
        for k in raw:
            np.testing.assert_array_equal(raw[k], enc[k], err_msg=k)

    def test_bad_compress_config_rejected(self, mesh8):
        with pytest.raises(ValueError, match="wire_compress"):
            AsyncSGDWorker(self._conf("bits", compress="zstd"), mesh=mesh8)


class TestStagingLegCodec:
    def test_roundtrip_bit_identical(self):
        b = _criteo_like_batches(1)[0]
        st = wire.derive_stream_statics(b.indices, 8, 1 << 18, 1 << 18)
        from parameter_server_tpu.apps.linear.async_sgd import (
            prep_batch_ell_stream,
        )

        d = KeyDirectory(1 << 18, hashed=True)
        enc = prep_batch_ell_stream(b, d, 2, 128, 8, 1 << 18, st)
        cb = wire.compress_batch(enc, encoding="stream")
        assert cb.num_examples == enc.num_examples
        assert cb.wire_nbytes <= cb.raw_nbytes + len(cb.frames)
        dec = wire.decompress_batch(cb)
        assert type(dec) is type(enc)
        for f in dataclasses.fields(type(enc)):
            want = getattr(enc, f.name)
            got = getattr(dec, f.name)
            if isinstance(want, np.ndarray):
                assert want.dtype == got.dtype, f.name
                np.testing.assert_array_equal(want, got, err_msg=f.name)
            else:
                assert want == got, f.name

    def test_maybe_decompress_identity(self):
        x = {"a": np.arange(4)}
        assert wire.maybe_decompress(x) is x

    def test_incompressible_leaves_ride_raw(self):
        rng = np.random.default_rng(3)
        noise = {"x": rng.integers(0, 256, 1 << 15).astype(np.uint8)}
        cb = wire.compress_batch(noise)
        # raw frame: one header byte of overhead, nothing more
        assert cb.wire_nbytes <= cb.raw_nbytes + len(cb.frames)
        got = wire.decompress_batch(cb)
        np.testing.assert_array_equal(got["x"], noise["x"])
