"""Alert-driven autoscaling (system/autoscale.py): the alert→action
edge. The overload drill is the acceptance criterion made executable:
an induced decode-latency burn fires ``serve_p99_burn`` (the REAL
multi-window quantile rule shape from configs/alerts/default.json),
the listener grows the fleet, latency recovers, the alert resolves —
no human in the loop — and the flight-recorder bundles show the whole
overload → resize → resolve arc."""

import numpy as np
import pytest

from parameter_server_tpu.system.autoscale import AlertDrivenScaler
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.telemetry import blackbox
from parameter_server_tpu.telemetry.alerts import (
    AlertEvent,
    AlertManager,
    AlertRule,
)
from parameter_server_tpu.telemetry.history import HistoryStore
from parameter_server_tpu.telemetry.registry import MetricsRegistry


def _event(rule="serve_p99_burn", to="firing", frm="inactive", value=0.2):
    return AlertEvent(
        rule=rule, frm=frm, to=to, value=value, threshold=0.05,
        op=">", t=0.0, severity="page",
    )


class _Manager:
    """Stub AlertManager surface: just the listener registry."""

    def __init__(self):
        self.listeners = []

    def add_listener(self, fn):
        self.listeners.append(fn)

    def deliver(self, ev):
        for fn in self.listeners:
            fn(ev)


class _Fleet:
    def __init__(self, size=2):
        self.size = size

    def add_worker(self):
        self.size += 1
        return self.size


@pytest.fixture(autouse=True)
def fresh_blackbox():
    blackbox.reset()
    yield
    blackbox.reset()


class TestAlertDrivenScaler:
    def test_firing_grows_and_captures_bundle(self):
        mgr, fleet = _Manager(), _Fleet(size=2)
        sc = AlertDrivenScaler(mgr, fleet, cooldown_s=0.0)
        blackbox.set_min_interval(0.0)
        mgr.deliver(_event(to="firing"))
        assert fleet.size == 3
        assert sc.grown() == 1
        assert [a["outcome"] for a in sc.actions()] == ["grew"]
        b = blackbox.last_bundle()
        assert b is not None and b["trigger"]["kind"] == "alert"
        assert "serve_p99_burn firing -> grew" in b["trigger"]["detail"]

    def test_other_rules_ignored(self):
        mgr, fleet = _Manager(), _Fleet()
        sc = AlertDrivenScaler(mgr, fleet, cooldown_s=0.0)
        mgr.deliver(_event(rule="train_stale_exceeded", to="firing"))
        mgr.deliver(_event(to="pending", frm="inactive"))
        assert fleet.size == 2 and not sc.actions()

    def test_cooldown_spaces_actions(self):
        mgr, fleet = _Manager(), _Fleet()
        t = [0.0]
        sc = AlertDrivenScaler(
            mgr, fleet, cooldown_s=60.0, clock=lambda: t[0]
        )
        mgr.deliver(_event(to="firing"))
        t[0] = 30.0  # inside cooldown: a flapping alert must not saw
        mgr.deliver(_event(to="firing", frm="resolved"))
        assert fleet.size == 3
        assert [a["outcome"] for a in sc.actions()] == [
            "grew", "skipped-cooldown",
        ]
        t[0] = 90.0  # past it: acts again
        mgr.deliver(_event(to="firing", frm="resolved"))
        assert fleet.size == 4

    def test_max_workers_bounds_growth(self):
        mgr, fleet = _Manager(), _Fleet()
        sc = AlertDrivenScaler(mgr, fleet, cooldown_s=0.0, max_workers=1)
        mgr.deliver(_event(to="firing"))
        mgr.deliver(_event(to="firing", frm="resolved"))
        assert fleet.size == 3 and sc.grown() == 1
        assert sc.actions()[-1]["outcome"] == "skipped-max-workers"

    def test_grow_errors_are_fenced(self):
        """An actuator failure must not raise into evaluate() and must
        refund the grown count so capacity accounting stays truthful."""
        mgr = _Manager()

        def boom():
            raise RuntimeError("resize wedged")

        sc = AlertDrivenScaler(mgr, _Fleet(), cooldown_s=0.0, grow=boom)
        mgr.deliver(_event(to="firing"))
        assert sc.grown() == 0
        act = sc.actions()[-1]
        assert act["outcome"] == "error"
        assert "resize wedged" in act["result"]

    def test_resolved_without_action_stays_quiet(self):
        mgr = _Manager()
        AlertDrivenScaler(mgr, _Fleet())
        blackbox.set_min_interval(0.0)
        mgr.deliver(_event(to="resolved", frm="firing"))
        assert blackbox.last_bundle() is None


class TestRealCoordinatorEdge:
    def test_default_action_is_add_worker(self, mesh8):
        """The default actuator really is ElasticCoordinator.add_worker:
        a firing event grows the data-worker count by one and rebuilds
        the worker on the new mesh (the resize itself is tier-1-proven
        in test_elastic.py; this pins the scaler→coordinator edge)."""
        from tests.test_elastic import make_worker
        from parameter_server_tpu.system.elastic import ElasticCoordinator

        Postoffice.reset()
        try:
            co = ElasticCoordinator(make_worker, num_data=2, num_server=2)
            co.start()
            mgr = _Manager()
            AlertDrivenScaler(mgr, co, cooldown_s=0.0)
            before = co.num_data
            mgr.deliver(_event(to="firing"))
            assert co.num_data == before + 1
            assert co.worker is not None
        finally:
            Postoffice.reset()


class TestOverloadDrill:
    def test_overload_resize_resolve_arc(self):
        """The acceptance drill, on the fake clock: sustained decode
        p99 burn → ``serve_p99_burn`` fires (real rule shape: 15s AND
        120s windows over ``ps_serve_latency_seconds``) → the listener
        grows the fleet → latency recovers → the alert resolves with no
        human action — and ``blackbox.bundles()`` holds the captured
        overload → resize → resolve arc."""
        reg = MetricsRegistry()
        lat = reg.histogram(
            "ps_serve_latency_seconds", "decode latency",
            buckets=(0.001, 0.01, 0.05, 0.25, 1.0),
        )
        t = [0.0]
        st = HistoryStore(
            reg, resolutions=((1.0, 600), (10.0, 720)), clock=lambda: t[0]
        )
        rule = AlertRule(
            name="serve_p99_burn", kind="quantile",
            metric="ps_serve_latency_seconds", q=0.99, op=">",
            threshold=0.05, window_s=15.0, slow_window_s=120.0,
            for_s=0.0, severity="page",
        )
        mgr = AlertManager(
            [rule], registry=reg, clock=lambda: t[0], history=st
        )
        fleet = _Fleet(size=2)
        scaler = AlertDrivenScaler(
            mgr, fleet, cooldown_s=30.0, clock=lambda: t[0]
        )
        blackbox.set_min_interval(0.0)

        transitions = []
        mgr.add_listener(lambda ev: transitions.append(ev.to))

        overload_from = 130.0  # healthy baseline first, then the burn
        fired_at = resolved_at = None
        for i in range(1, 60):  # 10s ticks, ~600s of cluster time
            t[0] = 10.0 * i
            # the simulated truth: an underprovisioned fleet serves
            # decode at ~200ms p99, a grown one at ~5ms — the metric
            # the rule watches is a pure function of fleet size once
            # the induced overload begins
            hot = t[0] >= overload_from and fleet.size < 3
            per_req = 0.2 if hot else 0.005
            for _ in range(20):
                lat.observe(per_req)
            mgr.evaluate()
            name = mgr.states()["serve_p99_burn"].state_name
            if name == "firing" and fired_at is None:
                fired_at = t[0]
            if t[0] < overload_from:
                assert name == "inactive"  # quiet while healthy
            if name == "resolved":
                resolved_at = t[0]
                break

        # the arc happened, end to end, without a human:
        assert fired_at is not None and fired_at >= overload_from
        assert resolved_at is not None and resolved_at > fired_at
        assert fleet.size == 3  # grew exactly once (cooldown held)
        assert [a["outcome"] for a in scaler.actions()][:1] == ["grew"]

        # and the flight recorder holds the evidence pair
        details = [
            b["trigger"]["detail"] for b in blackbox.bundles()
            if b["trigger"]["kind"] == "alert"
        ]
        assert any("firing -> grew" in d for d in details), details
        assert any("resolved after autoscale" in d for d in details), details
