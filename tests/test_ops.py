"""Ops tests: the XLA segment-sum spmv formulation vs dense, FTRL kernel
fallback parity, quantize roundtrip error bounds (CPU fallback paths; the
Pallas variants are exercised on TPU by bench/verify runs).

The spmv helpers below are the canonical formulations the fused app steps
inline (darlin/async_sgd); a Pallas spmv kernel was probed on v5e and
rejected — Mosaic has no 1-D table gather — see SURVEY §3."""

import jax
import jax.numpy as jnp
import numpy as np

from parameter_server_tpu.ops.ftrl import ftrl_update, ftrl_update_ref
from parameter_server_tpu.ops.quantize import dequantize, quantize
from parameter_server_tpu.utils.sparse import random_sparse


def spmv(vals, cols, rows, w, n):
    """Xw over localized COO (loss.h::compute's Eigen matvec)."""
    return jax.ops.segment_sum(vals * w[cols], rows, num_segments=n)


def spmv_t(vals, cols, rows, g, u):
    """X^T g (loss.h transTimes)."""
    return jax.ops.segment_sum(vals * g[rows], cols, num_segments=u)


def spmv_t_sq(vals, cols, rows, h, u):
    """(X.^2)^T h (loss.h dotTimes path)."""
    return jax.ops.segment_sum(vals * vals * h[rows], cols, num_segments=u)


class TestSpmv:
    def setup_method(self, _):
        # duplicate-free CSR (spmv_t_sq squares per entry; dup (row,col)
        # pairs would differ from the dense-merged oracle)
        from parameter_server_tpu.utils.sparse import from_dense

        rng = np.random.default_rng(0)
        dense = (rng.random((40, 60)) < 0.1) * rng.normal(size=(40, 60))
        self.b = from_dense(dense.astype(np.float32), np.sign(rng.normal(size=40)))
        loc_rows = self.b.row_ids()
        # localized: treat raw indices as unique-index space directly
        self.rows = jnp.asarray(loc_rows, jnp.int32)
        self.cols = jnp.asarray(self.b.indices, jnp.int32)
        self.vals = jnp.asarray(self.b.value_array())
        self.dense = self.b.to_dense()

    def test_spmv_matches_dense(self):
        w = np.random.default_rng(1).normal(size=60).astype(np.float32)
        out = spmv(self.vals, self.cols, self.rows, jnp.asarray(w), 40)
        np.testing.assert_allclose(np.asarray(out), self.dense @ w, rtol=2e-5, atol=1e-5)

    def test_spmv_t_matches_dense(self):
        g = np.random.default_rng(2).normal(size=40).astype(np.float32)
        out = spmv_t(self.vals, self.cols, self.rows, jnp.asarray(g), 60)
        np.testing.assert_allclose(np.asarray(out), self.dense.T @ g, rtol=2e-5, atol=1e-5)

    def test_spmv_t_sq_matches_dense(self):
        h = np.abs(np.random.default_rng(3).normal(size=40)).astype(np.float32)
        out = spmv_t_sq(self.vals, self.cols, self.rows, jnp.asarray(h), 60)
        np.testing.assert_allclose(
            np.asarray(out), (self.dense**2).T @ h, rtol=2e-5, atol=1e-5
        )


class TestFtrlOp:
    def test_fallback_matches_reference(self):
        rng = np.random.default_rng(0)
        p = 2048
        z = jnp.asarray(rng.normal(size=p), jnp.float32)
        n = jnp.abs(jnp.asarray(rng.normal(size=p), jnp.float32))
        g = jnp.asarray(rng.normal(size=p) * (rng.random(p) < 0.2), jnp.float32)
        t = g != 0
        z1, n1 = ftrl_update(z, n, g, t, alpha=0.5, beta=1.0, l1=0.1, l2=0.01)
        z2, n2 = ftrl_update_ref(z, n, g, t, alpha=0.5, beta=1.0, l1=0.1, l2=0.01)
        np.testing.assert_allclose(np.asarray(z1), np.asarray(z2), atol=1e-6)
        np.testing.assert_allclose(np.asarray(n1), np.asarray(n2), atol=1e-6)

    def test_untouched_slots_frozen(self):
        p = 1024
        z = jnp.ones(p)
        n = jnp.ones(p)
        g = jnp.ones(p)
        t = jnp.zeros(p, bool)
        z1, n1 = ftrl_update(z, n, g, t, alpha=0.5, beta=1.0, l1=0.1)
        np.testing.assert_allclose(np.asarray(z1), 1.0)
        np.testing.assert_allclose(np.asarray(n1), 1.0)

    def test_bf16_kernel_matches_reference(self):
        """_kernel_bf16 numerics (interpret mode — the same kernel body
        Mosaic compiles): z must EQUAL the f32 reference (z math is
        deterministic); stored sqrt_n must be one of the two bf16
        neighbors of the f32 value (stochastic rounding never moves
        more than one ulp); untouched slots must be bit-frozen."""
        rng = np.random.default_rng(1)
        p = 2048
        z = jnp.asarray(rng.normal(size=p), jnp.float32)
        n_f32 = jnp.abs(jnp.asarray(rng.normal(size=p), jnp.float32))
        n = n_f32.astype(jnp.bfloat16)
        g = jnp.asarray(rng.normal(size=p) * (rng.random(p) < 0.5),
                        jnp.float32)
        t = g != 0
        kw = dict(alpha=0.5, beta=1.0, l1=0.1, l2=0.01)
        zk, nk = ftrl_update(z, n, g, t, seed=jnp.uint32(9),
                             force_pallas=True, interpret=True, **kw)
        assert nk.dtype == jnp.bfloat16
        # reference on the SAME widened operands, f32 result
        zr, nr = ftrl_update_ref(z, n.astype(jnp.float32), g, t, **kw)
        np.testing.assert_allclose(np.asarray(zk), np.asarray(zr),
                                   atol=1e-6)
        # each stored value is a bf16 neighbor of the exact f32 value
        nk32 = np.asarray(nk.astype(jnp.float32))
        nr32 = np.asarray(nr)
        down = np.asarray(jnp.asarray(nr32).astype(jnp.bfloat16)
                          .astype(jnp.float32))
        ulp = np.maximum(np.abs(nr32) * 2.0**-7, 1e-30)
        assert np.all(np.abs(nk32 - nr32) <= ulp), (
            np.abs(nk32 - nr32).max(), ulp.min()
        )
        # untouched slots: exact round-trip of the stored bf16 value
        frozen = ~np.asarray(t)
        np.testing.assert_array_equal(
            nk32[frozen], np.asarray(n.astype(jnp.float32))[frozen]
        )
        del down

    def test_touched_none_equals_support_mask(self):
        """touched=None (the unquantized-push contract: membership IS
        grad's support, derived in-kernel so no table-sized mask
        operand exists — the 2^30 single-chip fit depends on it) must
        be BIT-identical to passing touched=(g != 0) explicitly, on
        the ref path, the f32 kernel, and the bf16 kernel."""
        rng = np.random.default_rng(3)
        p = 2048
        z = jnp.asarray(rng.normal(size=p), jnp.float32)
        n = jnp.abs(jnp.asarray(rng.normal(size=p), jnp.float32))
        g = jnp.asarray(
            rng.normal(size=p) * (rng.random(p) < 0.2), jnp.float32
        )
        kw = dict(alpha=0.5, beta=1.0, l1=0.1, l2=0.01)
        for extra in (
            {},  # ref fallback (cpu)
            {"force_pallas": True, "interpret": True},  # f32 kernel
        ):
            za, na = ftrl_update(z, n, g, g != 0, **kw, **extra)
            zb, nb = ftrl_update(z, n, g, None, **kw, **extra)
            np.testing.assert_array_equal(np.asarray(za), np.asarray(zb))
            np.testing.assert_array_equal(np.asarray(na), np.asarray(nb))
        nb16 = n.astype(jnp.bfloat16)
        za, na = ftrl_update(z, nb16, g, g != 0, seed=jnp.uint32(7),
                             force_pallas=True, interpret=True, **kw)
        zb, nb = ftrl_update(z, nb16, g, None, seed=jnp.uint32(7),
                             force_pallas=True, interpret=True, **kw)
        np.testing.assert_array_equal(np.asarray(za), np.asarray(zb))
        np.testing.assert_array_equal(
            np.asarray(na.astype(jnp.float32)),
            np.asarray(nb.astype(jnp.float32)),
        )

    def test_kernel_in_place_aliasing_keeps_results(self):
        """input_output_aliases={z,sqrt_n} makes the kernel update in
        place (the alias is why one chip holds a 2^30 table: no fresh
        8 GB z'/n' next to the live table). Two halves: (a) interpret
        mode reproduces the reference numerics under the donation
        contract, (b) the alias ACTUALLY SURVIVES into the lowered TPU
        program — asserted on the exported StableHLO, because the
        numeric half alone would still pass if the alias were dropped
        (and 2^30 would quietly OOM again)."""
        rng = np.random.default_rng(5)
        p = 4096
        z = jnp.asarray(rng.normal(size=p), jnp.float32)
        n = jnp.abs(jnp.asarray(rng.normal(size=p), jnp.float32))
        g = jnp.asarray(
            rng.normal(size=p) * (rng.random(p) < 0.3), jnp.float32
        )
        kw = dict(alpha=0.5, beta=1.0, l1=0.1, l2=0.01)
        zr, nr = ftrl_update_ref(z, n, g, g != 0, **kw)
        zk, nk = ftrl_update(z, n, g, None, force_pallas=True,
                             interpret=True, **kw)
        np.testing.assert_allclose(np.asarray(zk), np.asarray(zr),
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(nk), np.asarray(nr),
                                   atol=1e-6)
        # (b) lowering contract, f32 and bf16-state variants
        import re

        # jax 0.4.x only materializes jax.export on explicit submodule
        # import (lazy attr access raises AttributeError)
        import jax.export  # noqa: F401

        for n_in, seed in ((n, None), (n.astype(jnp.bfloat16), 7)):
            exp = jax.export.export(
                jax.jit(lambda z, n, g: ftrl_update(
                    z, n, g, None, seed=(None if seed is None
                                         else jnp.uint32(seed)),
                    force_pallas=True, **kw)),
                platforms=["tpu"],
            )(z, n_in, g)
            aliases = re.findall(
                r"output_operand_alias<output_tuple_indices = \[(\d)\], "
                r"operand_index = (\d)", exp.mlir_module()
            )
            assert ("0", "0") in aliases and ("1", "1") in aliases, (
                f"z/sqrt_n not aliased in lowered TPU program: {aliases}"
            )

    def test_bf16_stochastic_rounding_unbiased(self):
        """Across many seeds the bf16 narrow must average to the exact
        f32 value (unbiased walk) — deterministic truncation would
        bias low and stall accumulators (absorption)."""
        from parameter_server_tpu.ops.ftrl import stochastic_round_bf16

        x = jnp.full(256, 1.0 + 1.0 / 512.0, jnp.float32)  # mid-ulp
        acc = np.zeros(256, np.float64)
        k = 200
        for s in range(k):
            acc += np.asarray(
                stochastic_round_bf16(x, np.uint32(s)).astype(jnp.float32)
            )
        mean = acc / k
        np.testing.assert_allclose(mean, np.asarray(x), rtol=2e-3)


class TestQuantizeOp:
    def test_error_within_one_step(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=5000), jnp.float32)
        for nbytes in (1, 2):
            q, lo, hi = quantize(x, seed=3, num_bytes=nbytes)
            back = dequantize(q, lo, hi, nbytes)
            step = float(hi - lo) / ((1 << (8 * nbytes)) - 1)
            assert float(jnp.abs(back - x).max()) <= step + 1e-6

    def test_unbiased(self):
        x = jnp.full(20000, 0.37, jnp.float32).at[0].set(0.0).at[1].set(1.0)
        q, lo, hi = quantize(x, seed=11, num_bytes=1)
        back = dequantize(q, lo, hi, 1)
        assert abs(float(back[2:].mean()) - 0.37) < 2e-3


def test_ftrl_block_rows_knob_is_math_invariant(monkeypatch):
    """block_rows (arg or PS_FTRL_BLOCK_ROWS) only retiles the grid —
    results must match the reference bit-for-bit at every block size
    (the on-chip sweep relies on this being a pure perf knob)."""
    import numpy as np

    rng = np.random.default_rng(7)
    p = 64 * 1024  # rows = 512: small enough for interpret mode, big
    # enough that the sweep below genuinely retiles (grids 64 and 8)
    z = jnp.asarray(rng.normal(size=p), jnp.float32)
    n = jnp.abs(jnp.asarray(rng.normal(size=p), jnp.float32))
    g = jnp.asarray(rng.normal(size=p), jnp.float32)
    t = jnp.asarray(rng.random(p) < 0.5, jnp.float32)
    kw = dict(alpha=0.5, beta=1.0, l1=0.1, l2=0.01)
    zr, nr = ftrl_update_ref(z, n, g, t > 0, **kw)
    # retiling must be bit-invariant KERNEL-vs-KERNEL (the math per
    # element is identical; only the grid changes) and track the jnp
    # reference to normal fp tolerance
    z0, n0 = ftrl_update(z, n, g, t, force_pallas=True, interpret=True,
                         block_rows=512, **kw)
    for br in (8, 64):
        zk, nk = ftrl_update(z, n, g, t, force_pallas=True,
                             interpret=True, block_rows=br, **kw)
        np.testing.assert_array_equal(np.asarray(zk), np.asarray(z0))
        np.testing.assert_array_equal(np.asarray(nk), np.asarray(n0))
    np.testing.assert_allclose(np.asarray(z0), np.asarray(zr), rtol=2e-5,
                               atol=2e-6)
    # the selection helper is the observable seam for the env knob
    # (bit-equality across block sizes makes an end-to-end env assert
    # vacuous by construction)
    from parameter_server_tpu.ops.ftrl import _choose_block_rows

    assert _choose_block_rows(4096, 1536) == 1024  # pow2 round-down
    assert _choose_block_rows(4096, 4096) == 4096
    assert _choose_block_rows(24, 2048) == 8       # halves to a divisor
    import pytest as _pytest

    with _pytest.raises(ValueError):
        _choose_block_rows(12, 2048)  # untileable rows fail loud
    monkeypatch.setenv("PS_FTRL_BLOCK_ROWS", "512")
    assert _choose_block_rows(4096) == 512         # env honored
    monkeypatch.setenv("PS_FTRL_BLOCK_ROWS", "bogus")
    assert _choose_block_rows(4096) == 2048        # bad env falls back


def test_ftrl_path_selection_predicate(monkeypatch):
    """Path selection is a pure predicate: Pallas everywhere by default
    (the corrected chained A/B has the kernel ahead at every size —
    see ops.ftrl.xla_min_slots), the XLA path only via the env sweep
    knob, and force_pallas pinning the kernel except where it cannot
    run (misaligned tile, unseeded bf16 narrow)."""
    from parameter_server_tpu.ops import ftrl

    monkeypatch.setattr(ftrl, "_use_pallas", lambda: True)
    assert not ftrl.use_ref_path(1 << 20, False, False, False)
    assert not ftrl.use_ref_path(1 << 28, False, False, False)
    assert not ftrl.use_ref_path(1 << 30, True, True, False)
    # correctness gates hold regardless of force_pallas
    assert ftrl.use_ref_path((1 << 20) + 8, False, False, True)  # tile
    assert ftrl.use_ref_path(1 << 20, True, False, True)  # unseeded bf16
    # off-TPU always ref unless forced
    monkeypatch.setattr(ftrl, "_use_pallas", lambda: False)
    assert ftrl.use_ref_path(1 << 20, False, False, False)
    # env override enables the flip for crossover sweeps
    monkeypatch.setattr(ftrl, "_use_pallas", lambda: True)
    monkeypatch.setenv("PS_FTRL_XLA_MIN_SLOTS", str(1 << 16))
    assert ftrl.use_ref_path(1 << 16, False, False, False)
    assert not ftrl.use_ref_path(1 << 15, False, False, False)
    assert not ftrl.use_ref_path(1 << 16, False, False, True)  # forced
