"""Beam search (lm_beam_search): exact-logprob bookkeeping over the
KV-cached decode path.

The strongest pins: (1) returned scores EQUAL teacher-forcing the
returned sequences through the training forward; (2) the top beam is
never worse than greedy decoding under the model's own logprob."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_tpu.models.transformer import (
    LMConfig,
    init_lm,
    lm_beam_search,
    lm_forward,
    lm_generate,
    shard_tokens,
)

# Promoted to the slow tier (PR 2, per the PR-1 ROADMAP note): the
# shard_map-shim unlock made the full 'not slow' suite overrun the
# 870s tier-1 budget on a 2-core host. Run via `pytest -m slow`.
pytestmark = pytest.mark.slow

CFG = LMConfig(vocab=37, d_model=32, n_heads=4, n_layers=2, d_ff=64)


@pytest.fixture()
def params():
    return init_lm(jax.random.PRNGKey(0), CFG)


def _seq_logprob(params, seqs, p_len):
    """Teacher-forced logprob of the generated part of each sequence
    [.., total] under the training forward."""
    from parameter_server_tpu.parallel import mesh as meshlib

    mesh1 = meshlib.make_mesh(num_data=1, num_server=1)
    flat = seqs.reshape(-1, seqs.shape[-1])
    logits = np.asarray(
        lm_forward(params, shard_tokens(flat, mesh1), CFG, mesh1, "data")
    )
    logp = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
    out = []
    for r in range(flat.shape[0]):
        tot = 0.0
        for t in range(p_len - 1, flat.shape[1] - 1):
            tot += float(logp[r, t, flat[r, t + 1]])
        out.append(tot)
    return np.asarray(out).reshape(seqs.shape[:-1])


def test_scores_match_teacher_forcing(params):
    rng = np.random.default_rng(1)
    prompt = jnp.asarray(rng.integers(0, 37, (2, 6)), np.int32)
    toks, scores = lm_beam_search(params, prompt, CFG, steps=5, beam_width=3)
    toks, scores = np.asarray(toks), np.asarray(scores)
    assert toks.shape == (2, 3, 11) and scores.shape == (2, 3)
    # best-first ordering
    assert (np.diff(scores, axis=1) <= 1e-6).all(), scores
    want = _seq_logprob(params, toks, p_len=6)
    np.testing.assert_allclose(scores, want, atol=2e-4, rtol=1e-4)


def test_top_beam_at_least_greedy(params):
    rng = np.random.default_rng(2)
    prompt = jnp.asarray(rng.integers(0, 37, (3, 5)), np.int32)
    toks, scores = lm_beam_search(params, prompt, CFG, steps=6, beam_width=4)
    greedy = np.asarray(lm_generate(params, prompt, CFG, steps=6))
    g_score = _seq_logprob(params, greedy[:, None, :], p_len=5)[:, 0]
    assert (np.asarray(scores)[:, 0] >= g_score - 1e-4).all(), (
        scores[:, 0], g_score
    )


def test_beam_width_one_is_greedy(params):
    rng = np.random.default_rng(3)
    prompt = jnp.asarray(rng.integers(0, 37, (2, 7)), np.int32)
    toks, _ = lm_beam_search(params, prompt, CFG, steps=5, beam_width=1)
    greedy = np.asarray(lm_generate(params, prompt, CFG, steps=5))
    np.testing.assert_array_equal(np.asarray(toks)[:, 0], greedy)


def test_eos_freezes_beam_and_score(params):
    rng = np.random.default_rng(4)
    prompt = jnp.asarray(rng.integers(1, 37, (1, 5)), np.int32)
    # find a token the top beam emits, use it as eos; t=0 always
    # qualifies if nonzero, so the fallback keeps the test robust to
    # numerics shifting which tokens get emitted
    base, _ = lm_beam_search(params, prompt, CFG, steps=6, beam_width=2)
    gen = np.asarray(base)[0, 0, 5:]
    cands = [t for t in range(6) if gen[t] != 0
             and (gen[:t] != gen[t]).all()]
    if not cands:
        pytest.skip("degenerate model emitted only pads")
    eos = int(gen[cands[-1]])
    toks, scores = lm_beam_search(
        params, prompt, CFG, steps=6, beam_width=2, eos_id=eos
    )
    toks, scores = np.asarray(toks), np.asarray(scores)
    froze_any = False
    for w in range(2):
        row = toks[0, w, 5:]
        hits = np.flatnonzero(row == eos)
        if hits.size:
            froze_any = True
            assert (row[hits[0] + 1:] == 0).all(), row
            # SCORE FREEZE: the returned score must equal the teacher-
            # forced logprob of the sequence truncated at eos — pads
            # after the freeze contribute nothing
            upto = 5 + hits[0] + 1
            want = _seq_logprob(
                params, toks[0, w][None, None, :upto], p_len=5
            )[0, 0]
            np.testing.assert_allclose(scores[0, w], want, atol=2e-4,
                                       rtol=1e-4)
    assert froze_any, toks


@pytest.mark.parametrize(
    "variant",
    [
        dict(n_kv_heads=2, rope=True, kv_cache_dtype="int8"),
        dict(compute_dtype="bfloat16", window=8),
    ],
    ids=["gqa_rope_int8", "bf16_window"],
)
def test_beam_variants_score_parity(variant):
    """The beam tile/reorder runs over the (data, scale) cache tuples —
    exactly where GQA/int8/bf16/window could break; pin the
    teacher-forcing score equality per variant (bf16 at loose
    tolerance)."""
    import dataclasses

    cfg = dataclasses.replace(CFG, **variant)
    p = init_lm(jax.random.PRNGKey(8), cfg)
    rng = np.random.default_rng(9)
    prompt = jnp.asarray(rng.integers(0, 37, (2, 6)), np.int32)
    toks, scores = lm_beam_search(p, prompt, cfg, steps=5, beam_width=3)
    toks, scores = np.asarray(toks), np.asarray(scores)
    from parameter_server_tpu.parallel import mesh as meshlib

    mesh1 = meshlib.make_mesh(num_data=1, num_server=1)
    flat = toks.reshape(-1, toks.shape[-1])
    logits = np.asarray(
        lm_forward(p, shard_tokens(flat, mesh1), cfg, mesh1, "data")
    )
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))
    want = np.asarray([
        sum(logp[r, t, flat[r, t + 1]] for t in range(5, 10))
        for r in range(flat.shape[0])
    ]).reshape(2, 3)
    tol = 0.05 if cfg.compute_dtype == "bfloat16" or cfg.kv_cache_dtype         else 2e-4
    np.testing.assert_allclose(scores, want, atol=tol, rtol=0.02)


def test_moe_beam_runs(params):
    cfg = LMConfig(
        vocab=37, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        moe_every=2, n_experts=4, capacity_factor=8.0,
    )
    p_m = init_lm(jax.random.PRNGKey(5), cfg)
    rng = np.random.default_rng(6)
    prompt = jnp.asarray(rng.integers(0, 37, (2, 5)), np.int32)
    toks, scores = lm_beam_search(p_m, prompt, cfg, steps=4, beam_width=3)
    assert np.asarray(toks).shape == (2, 3, 9)


def test_length_penalty_reranks_only(params):
    rng = np.random.default_rng(7)
    prompt = jnp.asarray(rng.integers(0, 37, (2, 5)), np.int32)
    a, sa = lm_beam_search(params, prompt, CFG, steps=5, beam_width=3)
    b, sb = lm_beam_search(
        params, prompt, CFG, steps=5, beam_width=3, length_penalty=1.0
    )
    # without eos every beam has the same length: the penalty divides
    # all scores equally, so the SET of sequences (and raw scores) match
    np.testing.assert_allclose(
        np.sort(np.asarray(sa), axis=1), np.sort(np.asarray(sb), axis=1),
        atol=1e-6,
    )


def test_ragged_beams_equal_single_prompt_calls(params):
    """Each prompt's beam set (tokens AND scores) must equal a
    single-prompt call on the unpadded prompt — the ragged beam path's
    exactness contract."""
    rng = np.random.default_rng(10)
    widths = [4, 9, 6]
    rows = [rng.integers(1, 37, w).astype(np.int32) for w in widths]
    padded = np.zeros((3, 9), np.int32)
    for i, r in enumerate(rows):
        padded[i, : r.size] = r
    toks, scores = lm_beam_search(
        params, jnp.asarray(padded), CFG, steps=5, beam_width=3,
        prompt_lengths=np.asarray(widths, np.int32),
    )
    toks, scores = np.asarray(toks), np.asarray(scores)
    for i, r in enumerate(rows):
        solo_t, solo_s = lm_beam_search(
            params, jnp.asarray(r[None, :]), CFG, steps=5, beam_width=3
        )
        np.testing.assert_allclose(
            scores[i], np.asarray(solo_s)[0], atol=1e-5, rtol=1e-5,
            err_msg=f"row {i}",
        )
        np.testing.assert_array_equal(
            toks[i, :, : r.size + 5], np.asarray(solo_t)[0],
            err_msg=f"row {i}",
        )
        assert (toks[i, :, r.size + 5:] == 0).all()


def test_ragged_beam_with_eos_matches_single_prompt(params):
    """ragged x eos: the riskiest composition (per-row pad writes,
    frozen done-beams, gen_len clocks) — each prompt's beams must still
    equal its single-prompt eos run exactly."""
    rng = np.random.default_rng(12)
    widths = [3, 8]
    rows = [rng.integers(1, 37, w).astype(np.int32) for w in widths]
    padded = np.zeros((2, 8), np.int32)
    for i, r in enumerate(rows):
        padded[i, : r.size] = r
    # choose an eos from what the eos-free top beams actually emit
    base, _ = lm_beam_search(
        params, jnp.asarray(padded), CFG, steps=6, beam_width=2,
        prompt_lengths=np.asarray(widths, np.int32),
    )
    emitted = [
        t for i in range(2)
        for t in np.asarray(base)[i, 0, widths[i]: widths[i] + 6].tolist()
        if t != 0
    ]
    if not emitted:
        pytest.skip("degenerate model emitted only pads")
    eos = int(emitted[-1])
    toks, scores = lm_beam_search(
        params, jnp.asarray(padded), CFG, steps=6, beam_width=2,
        eos_id=eos, prompt_lengths=np.asarray(widths, np.int32),
        length_penalty=0.6,
    )
    toks, scores = np.asarray(toks), np.asarray(scores)
    for i, r in enumerate(rows):
        solo_t, solo_s = lm_beam_search(
            params, jnp.asarray(r[None, :]), CFG, steps=6, beam_width=2,
            eos_id=eos, length_penalty=0.6,
        )
        np.testing.assert_array_equal(
            toks[i, :, : r.size + 6], np.asarray(solo_t)[0],
            err_msg=f"row {i}",
        )
        np.testing.assert_allclose(
            scores[i], np.asarray(solo_s)[0], atol=1e-5, rtol=1e-5
        )


def test_ragged_beam_uniform_equals_dense(params):
    rng = np.random.default_rng(11)
    prompt = jnp.asarray(rng.integers(1, 37, (2, 7)), np.int32)
    a_t, a_s = lm_beam_search(params, prompt, CFG, steps=4, beam_width=2)
    b_t, b_s = lm_beam_search(
        params, prompt, CFG, steps=4, beam_width=2,
        prompt_lengths=np.full(2, 7, np.int32),
    )
    np.testing.assert_array_equal(np.asarray(a_t), np.asarray(b_t))
    np.testing.assert_allclose(np.asarray(a_s), np.asarray(b_s),
                               atol=1e-5)


def test_beam_under_tensor_parallelism(params, mesh8):
    """Beam search with Megatron-placed weights: the per-step cache
    parent-gather and top-k run over TP-sharded compute — tokens must
    match the replicated run exactly (scores to f32 psum tolerance)."""
    from parameter_server_tpu.models.transformer import shard_lm_params

    rng = np.random.default_rng(13)
    prompt = jnp.asarray(rng.integers(0, 37, (2, 6)), np.int32)
    rep_t, rep_s = lm_beam_search(params, prompt, CFG, steps=5,
                                  beam_width=3)
    tp = shard_lm_params(params, mesh8)
    tp_t, tp_s = lm_beam_search(tp, prompt, CFG, steps=5, beam_width=3)
    np.testing.assert_array_equal(np.asarray(rep_t), np.asarray(tp_t))
    np.testing.assert_allclose(np.asarray(rep_s), np.asarray(tp_s),
                               atol=1e-4)


def test_validation(params):
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="beam_width"):
        lm_beam_search(params, prompt, CFG, steps=2, beam_width=0)
    with pytest.raises(ValueError, match="beam_width"):
        lm_beam_search(params, prompt, CFG, steps=2, beam_width=38)
    with pytest.raises(ValueError, match="eos_id"):
        lm_beam_search(params, prompt, CFG, steps=2, eos_id=99)
    with pytest.raises(ValueError, match="steps"):
        lm_beam_search(params, prompt, CFG, steps=0)
