"""The history plane (PR 16): multi-resolution telemetry rings,
multi-window burn alerts, trend/drift detection, and the cluster-wide
range-query surface.

The contracts pinned here are the ones doc/OBSERVABILITY.md "History
plane" sells:

- typed downsampling is EXACT per kind: counters fold to per-cell rate
  deltas (reset-aware), gauges keep a last/min/max envelope, histograms
  merge bucket-count deltas so windowed percentiles come out of cells;
- fold attribution is midpoint-clamped, so a fold landing exactly on a
  cell boundary never writes a second's accrual into a ~zero-width
  open cell (the rate-explosion bug class);
- retention is BOUNDED: ring laps forget, series caps drop NEW series
  one-shot-counted under ps_history_dropped_series_total, and
  export_ring truncation is disclosed, never silent;
- the alert evaluator reads history on the STORE's clock: multi-window
  burn rules fire on sustained overload and stay quiet on a brief
  spike, trend rules gate Theil-Sen slope on monotonic concordance,
  and the meta-monitoring lag gauge walks the starvation rule through
  its states;
- the seeded leak drill: a ramping gauge drives the shipped hbm_leak
  trend rule inactive→pending→firing, and the auto-captured bundle's
  embedded history CONTAINS the ramp (asserted on bundle contents);
- per-node rings ride the metric-report frame: a silenced node's ring
  goes stale by age (disclosed, never merged into any cluster rollup)
  and a torn frame drops one shipment without poisoning the stored
  ring;
- /metrics/history answers range queries as JSON and 400s on malformed
  params instead of guessing.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from urllib.parse import quote

import pytest

from parameter_server_tpu.system import faults
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.telemetry import alerts as alerts_mod
from parameter_server_tpu.telemetry import blackbox
from parameter_server_tpu.telemetry import history as history_mod
from parameter_server_tpu.telemetry import registry as telemetry_registry
from parameter_server_tpu.telemetry.aggregate import (
    CLUSTER_NODE,
    ClusterAggregator,
)
from parameter_server_tpu.telemetry.alerts import AlertManager, AlertRule
from parameter_server_tpu.telemetry.exposition import (
    ExpositionServer,
    _parse_history_query,
)
from parameter_server_tpu.telemetry.history import (
    HistoryStore,
    drift_check,
    monotonic_fractions,
    percentile_from_buckets,
    theil_sen,
)
from parameter_server_tpu.telemetry.registry import MetricsRegistry


@pytest.fixture(autouse=True)
def hermetic():
    Postoffice.reset()
    faults.reset()
    blackbox.reset()
    history_mod.reset_default_store()
    before = set(threading.enumerate())
    yield
    faults.reset()
    blackbox.reset()
    history_mod.reset_default_store()
    Postoffice.reset()
    deadline = time.time() + 5
    while time.time() < deadline:
        leaked = [
            t for t in set(threading.enumerate()) - before if t.is_alive()
        ]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, f"leaked threads: {leaked}"


def _get(url, timeout=10):
    return urllib.request.urlopen(url, timeout=timeout)


def _store(reg, t, resolutions=((1.0, 600), (10.0, 720), (60.0, 720))):
    return HistoryStore(reg, resolutions=resolutions, clock=lambda: t[0])


# ---------------------------------------------------------------------------
# estimators: Theil-Sen, concordance, bucket percentiles, drift verdicts
# ---------------------------------------------------------------------------


class TestEstimators:
    def test_theil_sen_exact_on_linear(self):
        pts = [(float(i), 2.0 + 0.5 * i) for i in range(10)]
        assert theil_sen(pts) == pytest.approx(0.5)

    def test_theil_sen_robust_to_outlier(self):
        # one wild point must not drag the median slope (the property
        # that makes a trend rule usable on jittery gauges)
        pts = [(float(i), 1.0 + 0.1 * i) for i in range(11)]
        pts[5] = (5.0, 1e6)
        assert theil_sen(pts) == pytest.approx(0.1, rel=0.05)

    def test_theil_sen_degenerate(self):
        assert theil_sen([(0.0, 1.0)]) is None
        assert theil_sen([(1.0, 1.0), (1.0, 2.0)]) is None  # zero dt

    def test_monotonic_fractions(self):
        up, down = monotonic_fractions([1, 2, 3, 4])
        assert (up, down) == (1.0, 0.0)
        up, down = monotonic_fractions([4, 3, 2, 1])
        assert (up, down) == (0.0, 1.0)
        up, down = monotonic_fractions([1, 2, 1, 2, 1])
        assert up == pytest.approx(0.5)
        assert down == pytest.approx(0.5)

    def test_percentile_from_buckets_interpolates_and_clamps(self):
        bounds = [0.1, 1.0, 10.0]
        # 10 obs in (0, 0.1], 10 in (1, 10]
        dcounts = [10, 0, 10]
        assert percentile_from_buckets(bounds, dcounts, 20, 0.5) == (
            pytest.approx(0.1)
        )
        assert percentile_from_buckets(bounds, dcounts, 20, 0.9) == (
            pytest.approx(8.2)
        )
        # rank past every bucket clamps to the top bound, never raises
        assert percentile_from_buckets(bounds, [0, 0, 0], 0, 0.5) is None

    def test_drift_check_verdicts(self):
        ramp_down = [(float(i), 100.0 - 0.5 * i) for i in range(60)]
        d = drift_check(ramp_down)
        assert d["verdict"] == "drift-down" and d["drifting"]
        assert d["ratio"] < 0.85
        flat = [(float(i), 100.0) for i in range(60)]
        d = drift_check(flat)
        assert d["verdict"] == "ok" and not d["drifting"]
        assert d["ratio"] == pytest.approx(1.0)
        d = drift_check([(0.0, 1.0), (1.0, 1.0)])
        assert d["verdict"] == "insufficient-data" and not d["drifting"]


# ---------------------------------------------------------------------------
# the store: typed downsampling, bounded retention, queries
# ---------------------------------------------------------------------------


class TestHistoryStore:
    def test_counter_rate_cells_and_midpoint_attribution(self):
        """Folds landing EXACTLY on cell boundaries — the worst case
        for open-cell width math — must yield the true rate at every
        level, not an exploded rate in a ~zero-width cell."""
        reg = MetricsRegistry()
        c = reg.counter("h_req_total", "r")
        t = [0.0]
        st = _store(reg, t)
        st.fold()  # first sight: baseline, no attribution window
        for i in range(1, 31):
            t[0] = float(i)
            c.inc(5)
            st.fold()
        r = st.query("h_req_total", window_s=20.0, resolution=1.0)
        rates = [p["rate"] for p in r["series"][0]["points"]]
        assert rates and all(x == pytest.approx(5.0) for x in rates)
        assert st.window_rate("h_req_total", None, 20.0) == (
            pytest.approx(5.0)
        )
        # the 10s level saw the same traffic, just coarser
        coarse = st.query("h_req_total", window_s=20.0, resolution=10.0)
        closed = [
            p for p in coarse["series"][0]["points"] if p["t"] + 10 <= t[0]
        ]
        assert closed and all(
            p["delta"] == pytest.approx(50.0) for p in closed
        )

    def test_counter_reset_contributes_post_reset_total(self):
        """A registry swap (process restart mid-run) must contribute
        the post-reset total as the delta — never a negative delta."""
        reg = MetricsRegistry()
        c = reg.counter("h_reset_total", "r")
        t = [0.0]
        st = _store(reg, t)
        st.fold()
        t[0] = 1.0
        c.inc(100)
        st.fold()
        reg2 = MetricsRegistry()
        c2 = reg2.counter("h_reset_total", "r")
        st.registry = reg2  # the restarted process's registry
        t[0] = 2.0
        c2.inc(3)
        st.fold()
        pts = st.query("h_reset_total", window_s=5.0, resolution=1.0)
        deltas = [p["delta"] for p in pts["series"][0]["points"]]
        assert min(deltas) >= 0.0
        assert 3.0 in [pytest.approx(d) for d in deltas]

    def test_gauge_envelope_last_min_max(self):
        reg = MetricsRegistry()
        g = reg.gauge("h_depth", "d")
        t = [2.0]
        st = _store(reg, t, resolutions=((1.0, 60), (10.0, 60)))
        g.set(9)
        st.fold()
        for tt, v in ((11.0, 3.0), (14.0, 1.0), (17.0, 5.0)):
            t[0] = tt
            g.set(v)
            st.fold()
        t[0] = 19.0
        r = st.query("h_depth", window_s=20.0, resolution=10.0)
        by_t = {p["t"]: p for p in r["series"][0]["points"]}
        cell = by_t[10.0]  # all three later folds land in [10, 20)
        assert cell["last"] == pytest.approx(5.0)
        assert cell["min"] == pytest.approx(1.0)
        assert cell["max"] == pytest.approx(5.0)

    def test_histogram_cells_and_window_quantile(self):
        reg = MetricsRegistry()
        h = reg.histogram("h_lat_seconds", "l", buckets=(0.1, 1.0, 10.0))
        t = [1.0]
        st = _store(reg, t)
        h.observe(0.05)
        st.fold()  # first sight: baseline only, no attribution window
        for _ in range(10):
            h.observe(0.05)
        for _ in range(10):
            h.observe(5.0)
        t[0] = 2.0
        st.fold()
        assert st.window_quantile(
            "h_lat_seconds", None, 10.0, 0.5
        ) == pytest.approx(0.1)
        assert st.window_quantile(
            "h_lat_seconds", None, 10.0, 0.9
        ) == pytest.approx(8.2)
        r = st.query("h_lat_seconds", window_s=10.0, q=0.9)
        pts = [p for p in r["series"][0]["points"] if p["count"] > 0]
        assert pts and pts[-1]["q"] == pytest.approx(8.2)
        assert pts[-1]["count"] == pytest.approx(20.0)

    def test_fold_floor_and_force(self):
        reg = MetricsRegistry()
        reg.counter("h_floor_total", "r")
        t = [0.0]
        st = _store(reg, t)
        assert st.fold()
        t[0] = 0.2  # inside half the 1s base resolution
        assert not st.fold()
        assert st.fold(force=True)
        assert st.snapshot()["folds"] == 2

    def test_series_caps_drop_one_shot_counted(self):
        reg = MetricsRegistry()
        c = reg.counter("h_capped_total", "r", labelnames=("k",))
        t = [0.0]
        st = HistoryStore(
            reg, resolutions=((1.0, 60),), max_series_per_metric=2,
            clock=lambda: t[0],
        )
        for k in "abcd":
            c.labels(k=k).inc()
        st.fold()
        snap = st.snapshot()
        assert snap["series_dropped"] == 2
        # re-folding the same overflow must not re-count the drops
        t[0] = 1.0
        for k in "abcd":
            c.labels(k=k).inc()
        st.fold()
        assert st.snapshot()["series_dropped"] == 2
        ex = reg.export_state()["ps_history_dropped_series_total"]
        assert [s["value"] for s in ex["series"]] == [2.0]

    def test_ring_laps_forget_beyond_span(self):
        reg = MetricsRegistry()
        g = reg.gauge("h_lap", "g")
        t = [0.0]
        st = _store(reg, t, resolutions=((1.0, 4), (10.0, 6)))
        g.set(1.0)
        st.fold()
        t[0] = 100.0
        g.set(2.0)
        st.fold()
        # the t=0 cells are lapped out of every level's live window
        pts = st.value_points("h_lap", None, window_s=200.0)
        assert pts and all(tc >= 50.0 for tc, _ in pts)
        assert pts[-1][1] == pytest.approx(2.0)

    def test_value_points_max_points_coarsens_level(self):
        reg = MetricsRegistry()
        g = reg.gauge("h_trendy", "g")
        t = [0.0]
        st = _store(reg, t)
        for i in range(200):
            t[0] = float(i)
            g.set(float(i))
            st.fold()
        fine = st.value_points("h_trendy", None, window_s=150.0)
        coarse = st.value_points(
            "h_trendy", None, window_s=150.0, max_points=16
        )
        assert len(fine) > 64
        assert 0 < len(coarse) <= 16
        tr = st.trend("h_trendy", None, window_s=150.0, max_points=16)
        assert tr["n"] <= 16
        assert tr["slope_per_s"] == pytest.approx(1.0, rel=0.05)
        assert tr["frac_up"] == 1.0

    def test_trend_needs_min_points(self):
        reg = MetricsRegistry()
        g = reg.gauge("h_thin", "g")
        t = [0.0]
        st = _store(reg, t)
        for i in range(3):
            t[0] = float(i)
            g.set(float(i))
            st.fold()
        assert st.trend("h_thin", None, window_s=60.0, min_points=4) is None

    def test_export_ring_shape_and_truncation_disclosed(self):
        reg = MetricsRegistry()
        c = reg.counter("h_ship_total", "r", labelnames=("k",))
        g = reg.gauge("h_ship_depth", "d")
        t = [0.0]
        st = _store(reg, t)
        for i in range(5):
            t[0] = float(i)
            for k in "abc":
                c.labels(k=k).inc()
            g.set(float(i))
            st.fold()
        ring = st.export_ring(window_s=60.0)
        assert ring["series"] >= 4 and ring["series_truncated"] == 0
        assert ring["t"] == t[0]
        assert set(ring["metrics"]) >= {"h_ship_total", "h_ship_depth"}
        decl = ring["metrics"]["h_ship_total"]
        assert decl["kind"] == "counter" and decl["series"]
        # a max_series smaller than one metric's fan-out truncates that
        # metric WHOLE and discloses the count — never half a metric
        tight = st.export_ring(window_s=60.0, max_series=2)
        assert tight["series_truncated"] > 0
        assert "h_ship_total" not in tight["metrics"]

    def test_default_store_identity_and_installed(self):
        assert history_mod.installed_store() is None
        s = history_mod.default_store()
        assert history_mod.installed_store() is s
        assert history_mod.default_store() is s
        history_mod.reset_default_store()
        assert history_mod.installed_store() is None

    def test_set_default_store_swaps_and_restores(self):
        reg = telemetry_registry.default_registry()
        mine = HistoryStore(reg, clock=lambda: 123.0).install()
        prev = history_mod.set_default_store(mine)
        try:
            assert prev is None
            assert history_mod.installed_store() is mine
            assert history_mod.default_store() is mine
        finally:
            history_mod.set_default_store(prev)


# ---------------------------------------------------------------------------
# history-backed alerting: multi-window burn, trend rules, meta-monitoring
# ---------------------------------------------------------------------------


def _transitions(events):
    return [(e.frm, e.to) for e in events]


class TestMultiWindowBurn:
    def _manager(self, rules):
        reg = MetricsRegistry()
        c = reg.counter("mw_req_total", "r")
        t = [0.0]
        st = _store(reg, t)
        mgr = AlertManager(
            rules, registry=reg, clock=lambda: t[0], history=st
        )
        return reg, c, t, mgr

    def test_sustained_overload_fires(self):
        rule = AlertRule(
            name="burn", kind="counter_rate", metric="mw_req_total",
            threshold=5.0, window_s=30, slow_window_s=300, for_s=0,
        )
        _, c, t, mgr = self._manager([rule])
        for i in range(37):  # 0..360s: 10/s the whole way
            t[0] = 10.0 * i
            if i:
                c.inc(100)
            mgr.evaluate()
        st = mgr.states()["burn"]
        assert st.state_name == "firing"
        # the conjunction reports the less-violating window's value —
        # both windows sit at the true 10/s here
        assert st.value == pytest.approx(10.0, rel=0.05)

    def test_brief_spike_stays_quiet_while_single_window_flaps(self):
        """A burst shorter than the slow window: the single-window
        rule goes pending (detection speed), the multi-window burn
        stays INACTIVE throughout (sustain proof) — the page-noise
        contract multi-window burn exists for."""
        burn = AlertRule(
            name="burn", kind="counter_rate", metric="mw_req_total",
            threshold=5.0, window_s=30, slow_window_s=300, for_s=0,
        )
        fast = AlertRule(
            name="fast", kind="counter_rate", metric="mw_req_total",
            threshold=5.0, window_s=30, for_s=40,
        )
        _, c, t, mgr = self._manager([burn, fast])
        burn_transitions = []
        mgr.add_listener(
            lambda ev: burn_transitions.append(ev) if ev.rule == "burn"
            else None
        )
        for i in range(31):  # 0..300s quiet
            t[0] = 10.0 * i
            mgr.evaluate()
        t[0] = 310.0
        c.inc(400)  # one hot 10s stretch: 13.3/s fast, 1.3/s slow
        mgr.evaluate()
        assert mgr.states()["fast"].state_name == "pending"
        assert mgr.states()["burn"].state_name == "inactive"
        for i in range(32, 36):  # quiet again: the flap clears
            t[0] = 10.0 * i
            mgr.evaluate()
        assert mgr.states()["fast"].state_name == "inactive"
        assert mgr.states()["burn"].state_name == "inactive"
        assert not burn_transitions  # never even went pending


class TestTrendRules:
    def test_monotonic_gate_keeps_noise_quiet(self):
        """Jitter around a level has nonzero Theil-Sen slope samples —
        the concordance gate is what separates noise from a leak."""
        reg = MetricsRegistry()
        g = reg.gauge("tr_level", "g")
        t = [0.0]
        st = _store(reg, t)
        rule = AlertRule(
            name="leak", kind="trend", metric="tr_level",
            threshold=1e-4, window_s=300, for_s=0, min_points=6,
            monotonic_frac=0.7,
        )
        mgr = AlertManager(
            [rule], registry=reg, clock=lambda: t[0], history=st
        )
        for i in range(20):  # saw-tooth with a slight upward bias
            t[0] = 10.0 * i
            g.set(1.0 + 0.002 * i + (0.5 if i % 2 else -0.5))
            mgr.evaluate()
        stt = mgr.states()["leak"]
        assert stt.state_name == "inactive"
        assert stt.value == pytest.approx(0.0)  # gated, not thresholded

    def test_ramp_walks_pending_then_firing(self):
        reg = MetricsRegistry()
        g = reg.gauge("tr_ramp", "g")
        t = [1000.0]
        st = _store(reg, t)
        rule = AlertRule(
            name="leak", kind="trend", metric="tr_ramp",
            threshold=1e-4, window_s=600, for_s=60, min_points=6,
            monotonic_frac=0.7,
        )
        mgr = AlertManager(
            [rule], registry=reg, clock=lambda: t[0], history=st
        )
        events = []
        mgr.add_listener(events.append)
        for i in range(12):
            t[0] = 1000.0 + 30.0 * i
            g.set(0.5 + 0.01 * i)  # +3.3e-4/s, strictly monotone
            mgr.evaluate()
        assert mgr.states()["leak"].state_name == "firing"
        walk = _transitions(events)
        assert ("inactive", "pending") in walk
        assert ("pending", "firing") in walk
        assert walk.index(("inactive", "pending")) < walk.index(
            ("pending", "firing")
        )


class TestEvaluatorStarvation:
    def test_lag_gauge_walks_starvation_rule(self):
        """Meta-monitoring: a starved evaluator tick reports its OWN
        lag (the gauge is set BEFORE sampling), so the rule fires on
        the very tick that was late — then resolves once the cadence
        recovers."""
        rule = AlertRule(
            name="starved", kind="gauge",
            metric="ps_alert_eval_lag_seconds", threshold=2.0,
            window_s=10, for_s=0, resolve_hold_s=20, severity="page",
        )
        t = [0.0]
        mgr = AlertManager([rule], clock=lambda: t[0])  # default registry
        assert mgr.period_s == pytest.approx(1.0)
        mgr.evaluate()  # first tick: no previous tick, no lag sample
        t[0] = 1.0
        mgr.evaluate()  # on-cadence: lag 0
        assert mgr.states()["starved"].state_name == "inactive"
        t[0] = 50.0  # a 49s gap on a 1s period: 48s of pure lag
        mgr.evaluate()
        st = mgr.states()["starved"]
        assert st.state_name == "firing"
        assert st.value == pytest.approx(48.0)
        t[0] = 51.0
        mgr.evaluate()  # cadence recovered
        assert mgr.states()["starved"].state_name == "resolved"
        # the jump past resolve_hold_s is ITSELF a 28s gap — the meta
        # rule re-fires on it (for_s=0: pending→firing in one tick)
        t[0] = 80.0
        mgr.evaluate()
        assert mgr.states()["starved"].state_name == "firing"
        # back on cadence: resolved again, then quiet ticks inside the
        # hold window keep it resolved until the hold elapses
        for tt in (81.0, 82.0, 83.0):
            t[0] = tt
            mgr.evaluate()
        assert mgr.states()["starved"].state_name == "resolved"
        t[0] = 83.5  # half-tick cadence: faster than the period, 0 lag
        mgr.evaluate()
        t[0] = 84.0
        mgr.evaluate()
        assert mgr.states()["starved"].state_name == "resolved"

    def test_shipped_starvation_rule_matches_catalog(self):
        rules = {r.name: r for r in alerts_mod.default_rules()}
        r = rules["alert_evaluator_starved"]
        assert r.metric == "ps_alert_eval_lag_seconds"
        assert r.kind == "gauge" and r.severity == "page"


# ---------------------------------------------------------------------------
# the seeded leak drill: ramp → trend rule fires → bundle embeds the ramp
# ---------------------------------------------------------------------------


class TestLeakDrillBundle:
    def test_hbm_ramp_fires_shipped_rule_and_bundle_contains_ramp(self):
        """End-to-end acceptance: a seeded HBM-fraction ramp drives the
        SHIPPED hbm_leak trend rule inactive→pending→firing through a
        real AuxRuntime listener, and the auto-captured diagnostic
        bundle's embedded history visibly contains the ramp — the
        evidence a human needs is IN the bundle, not in a dashboard
        that has already scrolled past."""
        from parameter_server_tpu.system.aux_runtime import AuxRuntime

        t = [1000.0]
        reg = telemetry_registry.default_registry()
        g = reg.ensure_gauge("ps_device_hbm_frac_used", "hbm frac")
        store = HistoryStore(reg, clock=lambda: t[0]).install()
        prev_store = history_mod.set_default_store(store)
        blackbox.set_min_interval(0.0)
        rule = next(
            r for r in alerts_mod.default_rules() if r.name == "hbm_leak"
        )
        mgr = AlertManager([rule], clock=lambda: t[0])
        events = []
        mgr.add_listener(events.append)
        aux = AuxRuntime(heartbeat_timeout=30.0)
        try:
            aux.set_alerts(mgr)
            for i in range(12):
                t[0] = 1000.0 + 30.0 * i
                g.set(0.50 + 0.01 * i)  # +3.3e-4/s >> the 1e-4 threshold
                mgr.evaluate()
            walk = _transitions(events)
            assert ("inactive", "pending") in walk
            assert ("pending", "firing") in walk
            assert mgr.states()["hbm_leak"].state_name == "firing"

            b = blackbox.last_bundle()
            assert b is not None, "firing transition captured no bundle"
            assert b["trigger"]["kind"] == "alert"
            assert b["trigger"]["detail"] == "hbm_leak"
            hist = b["history"]
            assert hist is not None and "history" not in (
                b.get("section_errors") or {}
            )
            decl = hist["metrics"]["ps_device_hbm_frac_used"]
            assert decl["kind"] == "gauge"
            lasts = [p["last"] for p in decl["series"][0]["points"]]
            # the ramp is IN the bundle: monotone and spanning the seed
            assert len(lasts) >= 6
            assert lasts == sorted(lasts)
            assert lasts[-1] - lasts[0] >= 0.05
            # the bundle's alert section caught the breach state too
            assert b["alerts"]["states"]["hbm_leak"]["state_name"] == (
                "firing"
            )
            summary = blackbox.summarize_bundle(b)
            assert summary["history_series"] >= 1
            assert summary["history_window_s"] == pytest.approx(3600.0)
        finally:
            aux.stop()
            history_mod.set_default_store(prev_store)


# ---------------------------------------------------------------------------
# cluster history: staleness, no rollup, torn frames
# ---------------------------------------------------------------------------


def _mini_ring(value=1.0, t0=100.0):
    reg = MetricsRegistry()
    g = reg.gauge("ring_gauge", "g")
    t = [t0]
    st = _store(reg, t)
    g.set(value)
    st.fold()
    return st.export_ring(window_s=60.0)


class TestClusterHistory:
    def test_ages_staleness_and_no_cluster_rollup(self):
        tq = [0.0]
        agg = ClusterAggregator(stale_after_s=5.0, clock=lambda: tq[0])
        agg.update_history("S0", _mini_ring(1.0))
        tq[0] = 7.0
        agg.update_history("S1", _mini_ring(2.0))
        tq[0] = 10.0
        ages = agg.history_ages()
        assert ages["S0"] == pytest.approx(10.0)
        assert ages["S1"] == pytest.approx(3.0)
        hq = agg.history_query("ring_gauge")
        assert hq["nodes"]["S0"]["stale"] is True
        assert hq["nodes"]["S1"]["stale"] is False
        # the stale ring is still DISCLOSED — it is evidence
        assert hq["nodes"]["S0"]["series"]
        # histories never merge into any cluster rollup
        assert CLUSTER_NODE not in hq["nodes"]
        snap = agg.history_snapshot()
        assert snap["nodes"]["S0"]["stale"] is True
        assert snap["stale_after_s"] == pytest.approx(5.0)

    def test_window_filter_trims_points(self):
        tq = [0.0]
        agg = ClusterAggregator(stale_after_s=5.0, clock=lambda: tq[0])
        reg = MetricsRegistry()
        g = reg.gauge("ring_gauge", "g")
        t = [100.0]
        st = _store(reg, t)
        for i in range(5):
            t[0] = 100.0 + 30.0 * i
            g.set(float(i))
            st.fold()
        agg.update_history("S0", st.export_ring(window_s=600.0))
        hq = agg.history_query("ring_gauge", window_s=60.0)
        pts = hq["nodes"]["S0"]["series"][0]["points"]
        assert pts and all(p["t"] >= 220.0 - 60.0 for p in pts)

    def test_torn_frame_keeps_previous_ring(self):
        """A report frame without a well-formed ring loses THAT
        shipment only: the stored ring is never replaced with garbage
        — it ages into staleness instead."""
        from parameter_server_tpu.system.aux_runtime import AuxRuntime

        aux = AuxRuntime(heartbeat_timeout=30.0)
        try:
            good = _mini_ring(3.0)
            aux.handle_metrics_message(
                {"node": "S9", "metrics": {}, "history": good}
            )
            before_t = dict(aux.cluster._history_t)
            # torn frames: history missing, not a dict, missing metrics
            for bad in (None, "garbage", {"t": 1.0, "series": 0}):
                payload = {"node": "S9", "metrics": {}}
                if bad is not None:
                    payload["history"] = bad
                aux.handle_metrics_message(payload)
            hq = aux.cluster.history_query("ring_gauge")
            assert hq["nodes"]["S9"]["series"]  # the good ring survived
            assert dict(aux.cluster._history_t) == before_t
        finally:
            aux.stop()

    def test_silenced_node_history_goes_stale(self):
        """The heartbeat.report silence fault: the silenced node ships
        NO history (a crashed node reports nothing), so its ring age
        grows past stale_after_s while live nodes keep refreshing."""
        from parameter_server_tpu.system.aux_runtime import AuxRuntime

        aux = AuxRuntime(heartbeat_timeout=30.0, stale_after_s=0.2)
        try:
            aux.register("S0")
            aux.register("S1")
            assert aux.report_all(wire=False) >= 2
            snap = aux.cluster.history_snapshot()
            assert {"S0", "S1"} <= set(snap["nodes"])
            faults.arm("heartbeat.report", kind="silence", match="S0")
            time.sleep(0.3)
            aux.report_all(wire=False)
            ages = aux.cluster.history_ages()
            assert ages["S0"] > 0.2 > ages["S1"]
            hq = aux.cluster.history_query("ps_node_rss_mb")
            assert hq["nodes"]["S0"]["stale"] is True
            assert hq["nodes"]["S1"]["stale"] is False
            assert CLUSTER_NODE not in hq["nodes"]
        finally:
            aux.stop()


# ---------------------------------------------------------------------------
# /metrics/history: the range-query endpoint
# ---------------------------------------------------------------------------


class TestHistoryEndpoint:
    def test_parse_history_query(self):
        p, err = _parse_history_query(
            "/metrics/history?name=m&window=60&resolution=10&q=0.5"
            "&labels=" + quote('{"k": "v"}')
        )
        assert err is None
        assert p == {
            "name": "m", "window_s": 60.0, "resolution": 10.0,
            "q": 0.5, "labels": {"k": "v"},
        }
        for path, frag in (
            ("/metrics/history", "missing required"),
            ("/metrics/history?name=m&window=abc", "numeric"),
            ("/metrics/history?name=m&window=-5", "window must be > 0"),
            ("/metrics/history?name=m&labels=notjson", "JSON object"),
            ("/metrics/history?name=m&labels=" + quote("[1]"),
             "JSON object"),
        ):
            p, err = _parse_history_query(path)
            assert p is None and frag in err, (path, err)

    def test_route_answers_echoes_and_400s(self):
        seen = []

        def history_fn(params):
            seen.append(params)
            return {"query": params, "local": {"series": []}}

        srv = ExpositionServer(
            lambda: "# empty\n", history_fn=history_fn
        ).start()
        try:
            body = json.load(
                _get(f"{srv.url}/metrics/history?name=ps_x&window=60")
            )
            assert body["query"]["name"] == "ps_x"
            assert body["query"]["window_s"] == 60.0
            assert seen and seen[-1]["name"] == "ps_x"
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{srv.url}/metrics/history?window=60")
            assert ei.value.code == 400
            assert "name" in ei.value.read().decode()
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{srv.url}/metrics/history?name=m&window=bogus")
            assert ei.value.code == 400
            # the root index advertises the route
            root = _get(srv.url).read().decode()
            assert "/metrics/history" in root
        finally:
            srv.close()

    def test_404_without_history_source(self):
        srv = ExpositionServer(lambda: "# empty\n").start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{srv.url}/metrics/history?name=m")
            assert ei.value.code == 404
        finally:
            srv.close()
