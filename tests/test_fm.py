"""Factorization machine (apps/linear/fm.py): one-step parity vs a NumPy
oracle of the fused FM step, and the capability test that motivates FM —
learning a pure feature-interaction target that a linear model cannot."""

import numpy as np
import pytest

from parameter_server_tpu.apps.linear.config import (
    Config,
    LearningRateConfig,
    LossConfig,
    PenaltyConfig,
    SGDConfig,
)
from parameter_server_tpu.apps.linear.fm import FMWorker
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.utils.sparse import SparseBatch


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    yield
    Postoffice.reset()


def make_conf(num_slots=64, lanes=2, alpha=0.5, lambda1=0.01):
    conf = Config()
    conf.loss = LossConfig(type="logit")
    conf.penalty = PenaltyConfig(type="l1", lambda_=[lambda1])
    conf.learning_rate = LearningRateConfig(type="decay", alpha=alpha, beta=1.0)
    conf.async_sgd = SGDConfig(
        algo="standard", minibatch=256, num_slots=num_slots, ell_lanes=lanes
    )
    return conf


def batch_of(rows, y):
    """Uniform 2-lane binary batch from explicit key pairs."""
    rows = np.asarray(rows, np.int64)
    n = len(rows)
    return SparseBatch(
        y=np.asarray(y, np.float32),
        indptr=np.arange(0, 2 * n + 1, 2, dtype=np.int64),
        indices=rows.reshape(-1),
        values=None,
    )


def interaction_batches(n_batches, rows_per=256, seed0=0):
    """Pure-interaction labels: y = +1 iff both features come from the
    same group — zero linear signal by construction."""
    out = []
    for i in range(n_batches):
        rng = np.random.default_rng(seed0 + i)
        a = rng.integers(0, 2, rows_per)  # feature from {0,1}
        b = rng.integers(0, 2, rows_per)  # feature from {2,3}
        keys = np.stack([a, 2 + b], axis=1)
        y = np.where(a == b, 1.0, -1.0)
        out.append(batch_of(keys, y))
    return out


class TestOracleParity:
    def test_single_step_matches_numpy(self, mesh8):
        alpha, beta, lam = 0.5, 1.0, 0.01
        conf = make_conf(num_slots=32, alpha=alpha, lambda1=lam)
        w = FMWorker(conf, k=4, mesh=mesh8, v_init_std=0.1, seed=3)
        S, k = w.num_slots, w.k
        v0 = np.asarray(w.state["v"]).copy()

        rng = np.random.default_rng(0)
        keys = rng.integers(0, 1 << 40, (8, 2))
        y = np.where(rng.random(8) < 0.5, 1.0, -1.0)
        batch = batch_of(keys, y)
        slots = w.directory.slots(batch.indices).reshape(8, 2)

        w.collect(w.process_minibatch(batch))

        # numpy oracle of the same step
        wv = np.zeros(S, np.float64)
        vv = v0.astype(np.float64)
        xw = np.zeros(8)
        for r in range(8):
            vr = vv[slots[r]]
            s = vr.sum(0)
            xw[r] = wv[slots[r]].sum() + 0.5 * (s @ s - (vr * vr).sum())
        gr = -y / (1.0 + np.exp(y * xw))
        g_w = np.zeros(S)
        g_v = np.zeros((S, k))
        for r in range(8):
            vr = vv[slots[r]]
            s = vr.sum(0)
            for j in range(2):
                g_w[slots[r, j]] += gr[r]
                g_v[slots[r, j]] += gr[r] * (s - vr[j])
        touched = g_w != 0
        w_ss = g_w * g_w
        eta_w = alpha / (np.sqrt(w_ss) + beta)
        w_new = np.sign(-eta_w * g_w) * np.maximum(
            np.abs(-eta_w * g_w) - lam * eta_w, 0.0
        )
        v_ss = g_v * g_v
        eta_v = alpha / (np.sqrt(v_ss) + beta)
        v_new = vv - eta_v * g_v
        np.testing.assert_allclose(
            np.asarray(w.state["w"]), np.where(touched, w_new, 0.0), atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(w.state["v"]),
            np.where(touched[:, None], v_new, vv),
            atol=1e-5,
        )

    def test_predict_margin_matches_device_forward(self, mesh8):
        conf = make_conf(num_slots=64)
        w = FMWorker(conf, k=4, mesh=mesh8, v_init_std=0.1, seed=1)
        batches = interaction_batches(3)
        w.train(iter(batches))
        # device aux xw for a batch == host predict_margin
        prog = w.collect(w.process_minibatch(batches[0]))
        host = w.predict_margin(batches[0])
        assert np.isfinite(host).all()
        assert prog.num_examples_processed == 256


class TestInteractionLearning:
    def test_fm_learns_what_linear_cannot(self, mesh8):
        from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker

        train = interaction_batches(60)
        test = interaction_batches(1, rows_per=1000, seed0=999)[0]

        fm = FMWorker(make_conf(alpha=0.3, lambda1=0.001), k=4, mesh=mesh8,
                      v_init_std=0.3, seed=2)
        fm.train(iter(train))
        fm_auc = fm.evaluate(test)["auc"]

        lconf = make_conf(alpha=0.3, lambda1=0.001)
        linear = AsyncSGDWorker(lconf, mesh=mesh8)
        linear.train(iter(train))
        lin_auc = linear.evaluate(test)["auc"]

        assert fm_auc > 0.9, f"FM failed the interaction task: {fm_auc}"
        assert lin_auc < 0.6, f"linear should NOT solve it: {lin_auc}"


class TestFMCheckpoint:
    def test_fm_checkpoint_restore(self, mesh8, tmp_path):
        from parameter_server_tpu.parameter.replica import CheckpointManager

        fm = FMWorker(make_conf(alpha=0.3, lambda1=0.001), k=4, mesh=mesh8,
                      v_init_std=0.3, seed=2)
        fm.train(iter(interaction_batches(20)))
        test = interaction_batches(1, rows_per=500, seed0=999)[0]
        want = fm.predict_margin(test)
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        fm.checkpoint(mgr, step=3)
        fm2 = FMWorker(make_conf(alpha=0.3, lambda1=0.001), k=4, mesh=mesh8,
                       v_init_std=0.3, seed=42)
        assert fm2.restore(mgr) == 3
        np.testing.assert_allclose(fm2.predict_margin(test), want, atol=1e-6)
        fm2.collect(fm2.process_minibatch(interaction_batches(1, seed0=55)[0]))


class TestFMElastic:
    def test_fm_resizes_live(self, mesh8):
        from parameter_server_tpu.system.elastic import ElasticCoordinator

        def mk(mesh):
            return FMWorker(make_conf(num_slots=100, alpha=0.3,
                                      lambda1=0.001),
                            k=4, mesh=mesh, v_init_std=0.3, seed=2)

        co = ElasticCoordinator(mk, num_data=2, num_server=2)
        fm = co.start()
        fm.train(iter(interaction_batches(40)))
        test = interaction_batches(1, rows_per=500, seed0=999)[0]
        auc_before = fm.evaluate(test)["auc"]
        fm2 = co.add_server()  # 2x2 -> 2x3, non-divisible table padding
        auc_after = fm2.evaluate(test)["auc"]
        assert auc_after == auc_before > 0.9
        fm2.collect(fm2.process_minibatch(interaction_batches(1, seed0=77)[0]))

    def test_fm_crash_path_shrinks(self, mesh8):
        """FM has no ongoing replica: a server death shrinks the cluster
        around the dead range (recover_server_shard -> False contract)."""
        from parameter_server_tpu.system.elastic import ElasticCoordinator

        def mk(mesh):
            return FMWorker(make_conf(num_slots=100), k=4, mesh=mesh, seed=2)

        co = ElasticCoordinator(mk, num_data=2, num_server=2)
        fm = co.start()
        fm.collect(fm.process_minibatch(interaction_batches(1)[0]))
        assert co.handle_server_death(1) == "resharded"
        assert co.num_server == 1
        co.worker.collect(
            co.worker.process_minibatch(interaction_batches(1, seed0=9)[0])
        )

    def test_predict_margin_handles_ragged_and_empty_rows(self, mesh8):
        w = FMWorker(make_conf(num_slots=64, lanes=4), k=3, mesh=mesh8,
                     v_init_std=0.2, seed=5)
        # ragged CSR incl. an EMPTY row (bias-only prediction)
        batch = SparseBatch(
            y=np.array([1.0, -1.0, 1.0], np.float32),
            indptr=np.array([0, 3, 3, 7], np.int64),
            indices=np.array([5, 9, 11, 2, 5, 30, 31], np.int64),
            values=None,
        )
        out = w.predict_margin(batch)
        # oracle: per-row loop
        v = np.asarray(w.state["v"]); wl = np.asarray(w.state["w"])
        b = float(w.state["b"])
        slots = w.directory.slots(batch.indices)
        for r in range(3):
            sl = slots[batch.indptr[r]: batch.indptr[r + 1]]
            vr = v[sl]; s = vr.sum(0)
            want = b + wl[sl].sum() + 0.5 * (s @ s - (vr * vr).sum())
            np.testing.assert_allclose(out[r], want, atol=1e-5)
