"""Sequence-parallel transformer LM: forward parity across mesh layouts,
training signal, and cross-shard loss shift (models/transformer.py)."""

import dataclasses

import jax
import numpy as np
import pytest

from parameter_server_tpu.models.transformer import (
    LMConfig,
    init_lm,
    lm_forward,
    lm_loss,
    make_lm_train_step,
    shard_lm_params,
    shard_tokens,
)

# Promoted to the slow tier (PR 2, per the PR-1 ROADMAP note): the
# shard_map-shim unlock made the full 'not slow' suite overrun the
# 870s tier-1 budget on a 2-core host. Run via `pytest -m slow`.
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def cfg():
    return LMConfig(vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64)


@pytest.fixture(scope="module")
def params(cfg):
    return init_lm(jax.random.PRNGKey(0), cfg)


def periodic_tokens(rng, b, s, vocab, period=4):
    """Sequences where token t repeats every `period` — learnable only by
    attending `period` steps back, which crosses shard boundaries."""
    base = rng.integers(0, vocab, (b, period))
    reps = -(-s // period)
    return np.tile(base, (1, reps))[:, :s].astype(np.int32)


def run_copy_training(mesh, params, cfg, steps, zigzag=False):
    """Shared copy-task training loop (adam, jitted step): constant-token
    sequences, loss history returned. ``zigzag=True`` routes through
    zigzag_lm_arrays + lm_loss_with_targets in the permuted layout."""
    import optax

    from parameter_server_tpu.models.transformer import (
        lm_loss_with_targets,
        zigzag_lm_arrays,
    )

    rng = np.random.default_rng(1)
    tx = optax.adam(1e-2)
    p = params
    opt = tx.init(p)

    if zigzag:

        @jax.jit
        def step(p, opt, toks, tgts, wts):
            loss, g = jax.value_and_grad(lm_loss_with_targets)(
                p, toks, tgts, wts, cfg, mesh, "data"
            )
            up, opt = tx.update(g, opt, p)
            return optax.apply_updates(p, up), opt, loss

    else:

        @jax.jit
        def step(p, opt, toks):
            loss, g = jax.value_and_grad(lm_loss)(p, toks, cfg, mesh, "data")
            up, opt = tx.update(g, opt, p)
            return optax.apply_updates(p, up), opt, loss

    losses = []
    for i in range(steps):
        const = rng.integers(0, cfg.vocab, (4, 1)).astype(np.int32)
        tokens = np.broadcast_to(const, (4, 64)).copy()
        if zigzag:
            tz, gz, wz = zigzag_lm_arrays(tokens, mesh.shape["data"])
            p, opt, loss = step(
                p, opt, shard_tokens(tz, mesh), shard_tokens(gz, mesh),
                shard_tokens(wz, mesh),
            )
        else:
            p, opt, loss = step(p, opt, shard_tokens(tokens, mesh))
        losses.append(float(loss))
    return losses, p


class TestSeqParallelLM:
    def test_forward_matches_single_shard(self, mesh8, cfg, params):
        """Sharding the sequence 4 ways must not change the math."""
        from parameter_server_tpu.parallel import mesh as meshlib

        rng = np.random.default_rng(0)
        tokens = rng.integers(0, cfg.vocab, (2, 64)).astype(np.int32)
        sharded = lm_forward(
            params, shard_tokens(tokens, mesh8), cfg, mesh8, "data"
        )
        mesh1 = meshlib.make_mesh(num_data=1, num_server=1)
        ref = lm_forward(
            params, shard_tokens(tokens, mesh1), cfg, mesh1, "data"
        )
        np.testing.assert_allclose(
            np.asarray(sharded), np.asarray(ref), atol=2e-4
        )

    def test_lm_learns_copy_task(self, mesh8, cfg, params):
        """End-to-end training over the seq-sharded mesh: constant-token
        sequences (predict next = current) drive loss well below the
        uniform baseline. (Exactness of the sharded attention itself is
        covered by the parity and gradient tests.)"""
        losses, _ = run_copy_training(mesh8, params, cfg, steps=60)
        baseline = np.log(cfg.vocab)
        assert losses[-1] < 0.3 * baseline, (losses[0], losses[-1], baseline)

    def test_lm_trains_with_ring_flash(self, mesh8, params):
        """The flash-kernel attention path carries training gradients:
        a few copy-task steps reduce the loss (parity of the kernel
        itself is covered in tests/test_flash_attention.py)."""
        cfg_f = LMConfig(
            vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            attention="ring_flash",
        )
        losses, _ = run_copy_training(mesh8, params, cfg_f, steps=30)
        assert losses[-1] < 0.6 * losses[0], (losses[0], losses[-1])

    def test_scanned_supersteps_match_sequential(self, mesh8, cfg, params):
        """steps_per_launch=T fuses T sequential SGD steps into one
        program (lax.scan carries the params): identical training
        trajectory to T separate step() calls."""
        rng = np.random.default_rng(3)
        stack = rng.integers(0, cfg.vocab, (3, 2, 64)).astype(np.int32)

        seq_step = make_lm_train_step(cfg, mesh8, "data", lr=0.2)
        p_seq = params
        seq_losses = []
        for i in range(3):
            p_seq, loss = seq_step(p_seq, shard_tokens(stack[i], mesh8))
            seq_losses.append(float(loss))

        fused = make_lm_train_step(
            cfg, mesh8, "data", lr=0.2, steps_per_launch=3
        )
        p_fused, losses = fused(params, shard_tokens(stack, mesh8))
        np.testing.assert_allclose(
            np.asarray(losses), seq_losses, rtol=1e-5
        )
        for k in params:
            np.testing.assert_allclose(
                np.asarray(p_fused[k]), np.asarray(p_seq[k]), atol=1e-5,
                err_msg=k,
            )

    def test_lm_zigzag_forward_matches_ring_permuted(self, mesh8, cfg, params):
        """No positional encoding + per-position layers: the zigzag-layout
        logits must equal the natural-layout logits permuted."""
        from parameter_server_tpu.models.attention import zigzag_permutation
        from parameter_server_tpu.models.transformer import lm_forward as fwd

        cfg_z = LMConfig(
            vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            attention="ring_zigzag",
        )
        rng = np.random.default_rng(3)
        tokens = rng.integers(0, 32, (2, 64)).astype(np.int32)
        n = mesh8.shape["data"]
        perm = zigzag_permutation(64, n)
        base = np.asarray(
            fwd(params, shard_tokens(tokens, mesh8), cfg, mesh8, "data")
        )
        zig = np.asarray(
            fwd(
                params, shard_tokens(tokens[:, perm], mesh8), cfg_z, mesh8,
                "data",
            )
        )
        np.testing.assert_allclose(zig, base[:, perm], atol=2e-4, rtol=1e-4)

    def test_lm_trains_in_zigzag_layout(self, mesh8, params):
        """End-to-end training in the zigzag layout with carried targets
        (zigzag_lm_arrays + lm_loss_with_targets): loss drops on the
        copy task; lm_loss itself must refuse the zigzag config."""
        cfg_z = LMConfig(
            vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            attention="ring_zigzag",
        )
        with pytest.raises(ValueError, match="NATURAL token order"):
            lm_loss(params, np.zeros((1, 64), np.int32), cfg_z, mesh8, "data")

        losses, _ = run_copy_training(mesh8, params, cfg_z, steps=30, zigzag=True)
        assert losses[-1] < 0.6 * losses[0], (losses[0], losses[-1])

    def test_zigzag_train_step_factory(self, mesh8, params):
        """make_lm_train_step refuses zigzag; the with-targets factory
        trains it."""
        from parameter_server_tpu.models.transformer import (
            make_lm_train_step_with_targets,
            zigzag_lm_arrays,
        )

        cfg_z = LMConfig(
            vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            attention="ring_zigzag",
        )
        with pytest.raises(ValueError, match="with_targets"):
            make_lm_train_step(cfg_z, mesh8)
        step = make_lm_train_step_with_targets(cfg_z, mesh8, lr=0.5)
        rng = np.random.default_rng(0)
        p = params
        first = last = None
        for i in range(10):
            const = rng.integers(0, 32, (4, 1)).astype(np.int32)
            tz, gz, wz = zigzag_lm_arrays(
                np.broadcast_to(const, (4, 64)).copy(), mesh8.shape["data"]
            )
            p, loss = step(
                p, shard_tokens(tz, mesh8), shard_tokens(gz, mesh8),
                shard_tokens(wz, mesh8),
            )
            first = float(loss) if first is None else first
            last = float(loss)
        assert last < first, (first, last)

    def test_loss_shift_crosses_shards(self, mesh8, cfg, params):
        """The next-token shift must see across shard boundaries: loss of a
        perfectly periodic stream differs from a shuffled one."""
        rng = np.random.default_rng(2)
        t1 = periodic_tokens(rng, 2, 64, cfg.vocab)
        l_seq = float(lm_loss(params, shard_tokens(t1, mesh8), cfg, mesh8))
        assert np.isfinite(l_seq) and l_seq > 0


class TestMemoryAndPrecision:
    def test_remat_gradients_match_exactly(self, mesh8, cfg, params):
        """jax.checkpoint trades recompute for memory; the gradients must
        be numerically identical (same program, re-run)."""
        cfg_r = dataclasses.replace(cfg, remat=True)
        rng = np.random.default_rng(7)
        tokens = shard_tokens(
            rng.integers(0, cfg.vocab, (2, 64)).astype(np.int32), mesh8
        )
        g0 = jax.grad(lm_loss)(params, tokens, cfg, mesh8, "data")
        g1 = jax.grad(lm_loss)(params, tokens, cfg_r, mesh8, "data")
        for k in g0:
            np.testing.assert_allclose(
                np.asarray(g0[k]), np.asarray(g1[k]), atol=1e-6, rtol=1e-6,
                err_msg=k,
            )

    def test_bf16_forward_close_and_trains(self, mesh8, cfg, params):
        cfg_b = dataclasses.replace(cfg, compute_dtype="bfloat16")
        rng = np.random.default_rng(8)
        tokens = rng.integers(0, cfg.vocab, (2, 64)).astype(np.int32)
        f32 = np.asarray(
            lm_forward(params, shard_tokens(tokens, mesh8), cfg, mesh8, "data")
        )
        bf16 = np.asarray(
            lm_forward(
                params, shard_tokens(tokens, mesh8), cfg_b, mesh8, "data"
            )
        )
        assert bf16.dtype == np.float32  # logits always f32
        # bf16 mantissa is 8 bits: loose but bounded agreement
        assert np.max(np.abs(f32 - bf16)) < 0.05, np.max(np.abs(f32 - bf16))
        losses, _ = run_copy_training(mesh8, params, cfg_b, steps=30)
        assert losses[-1] < 0.6 * losses[0], (losses[0], losses[-1])

    def test_remat_composes_with_flash_and_bf16(self, mesh8, params):
        cfg_all = LMConfig(
            vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            attention="ring_flash", remat=True, compute_dtype="bfloat16",
        )
        losses, _ = run_copy_training(mesh8, params, cfg_all, steps=30)
        assert losses[-1] < 0.6 * losses[0], (losses[0], losses[-1])

    def test_bad_compute_dtype_rejected(self):
        with pytest.raises(ValueError, match="compute_dtype"):
            LMConfig(compute_dtype="float16")


class TestGenerate:
    def test_decode_logits_match_full_forward(self, mesh8, cfg, params):
        """KV-cached decode must produce the SAME next-token logits as
        the full (training) forward pass, position by position."""
        from parameter_server_tpu.models.transformer import lm_generate
        from parameter_server_tpu.parallel import mesh as meshlib

        rng = np.random.default_rng(5)
        tokens = rng.integers(0, cfg.vocab, (2, 16)).astype(np.int32)
        _, dec_logits = lm_generate(
            params, tokens, cfg, steps=0, return_logits=True
        )
        mesh1 = meshlib.make_mesh(num_data=1, num_server=1)
        full = lm_forward(
            params, shard_tokens(tokens, mesh1), cfg, mesh1, "data"
        )
        np.testing.assert_allclose(
            np.asarray(dec_logits), np.asarray(full)[:, :-1], atol=2e-4,
            rtol=1e-4,
        )

    def test_greedy_decode_continues_copy_task(self, mesh8, cfg, params):
        """After copy-task training, greedy decoding from a constant
        prompt must emit the same constant."""
        from parameter_server_tpu.models.transformer import lm_generate

        losses, p = run_copy_training(mesh8, params, cfg, steps=60)
        assert losses[-1] < 0.5, losses[-1]
        prompt = np.full((2, 8), 7, np.int32)
        out = np.asarray(lm_generate(p, prompt, cfg, steps=12))
        assert out.shape == (2, 20)
        assert (out[:, 8:] == 7).all(), out

    def test_decode_honors_bf16(self, mesh8, cfg, params):
        """Decode runs in cfg.compute_dtype too: bf16 decode logits must
        track the bf16 training forward within bf16 tolerance."""
        from parameter_server_tpu.models.transformer import lm_generate
        from parameter_server_tpu.parallel import mesh as meshlib

        cfg_b = dataclasses.replace(cfg, compute_dtype="bfloat16")
        rng = np.random.default_rng(6)
        tokens = rng.integers(0, cfg.vocab, (2, 16)).astype(np.int32)
        _, dec = lm_generate(params, tokens, cfg_b, steps=0, return_logits=True)
        mesh1 = meshlib.make_mesh(num_data=1, num_server=1)
        full = lm_forward(
            params, shard_tokens(tokens, mesh1), cfg_b, mesh1, "data"
        )
        assert np.max(
            np.abs(np.asarray(dec) - np.asarray(full)[:, :-1])
        ) < 0.05

    def test_sliding_window_lm_decode_matches_forward(self, mesh8, params):
        """LMConfig.window: the windowed forward and the windowed decode
        must agree logit-for-logit (each masks its own way)."""
        from parameter_server_tpu.models.transformer import lm_generate
        from parameter_server_tpu.parallel import mesh as meshlib

        cfg_w = LMConfig(
            vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            attention="ring_flash", window=5,
        )
        rng = np.random.default_rng(9)
        tokens = rng.integers(0, 32, (2, 16)).astype(np.int32)
        _, dec = lm_generate(params, tokens, cfg_w, steps=0, return_logits=True)
        mesh1 = meshlib.make_mesh(num_data=1, num_server=1)
        full = lm_forward(
            params, shard_tokens(tokens, mesh1), cfg_w, mesh1, "data"
        )
        np.testing.assert_allclose(
            np.asarray(dec), np.asarray(full)[:, :-1], atol=2e-4, rtol=1e-4
        )
        # and the window genuinely changes the function vs full causal
        cfg_f = dataclasses.replace(cfg_w, window=None)
        full_nc = lm_forward(
            params, shard_tokens(tokens, mesh1), cfg_f, mesh1, "data"
        )
        assert np.max(np.abs(np.asarray(full) - np.asarray(full_nc))) > 1e-3

    def test_window_requires_flash_mode(self):
        # the default attention is now ring_flash (measured), so the
        # non-flash mode must be named explicitly to trip the guard
        with pytest.raises(ValueError, match="flash"):
            LMConfig(window=8, attention="ring")
        LMConfig(window=8)  # flash default: valid

    def test_sampling_modes(self, cfg, params):
        from parameter_server_tpu.models.transformer import lm_generate

        prompt = np.asarray([[3, 1, 4, 1]], np.int32)
        greedy = np.asarray(lm_generate(params, prompt, cfg, steps=6))
        # top_k=1 sampling == greedy regardless of temperature/seed
        topk1 = np.asarray(
            lm_generate(
                params, prompt, cfg, steps=6, temperature=2.0, top_k=1,
                key=jax.random.PRNGKey(42),
            )
        )
        np.testing.assert_array_equal(topk1, greedy)
        # sampling: valid tokens, deterministic per seed
        s1 = np.asarray(
            lm_generate(
                params, prompt, cfg, steps=6, temperature=1.0,
                key=jax.random.PRNGKey(7),
            )
        )
        s2 = np.asarray(
            lm_generate(
                params, prompt, cfg, steps=6, temperature=1.0,
                key=jax.random.PRNGKey(7),
            )
        )
        np.testing.assert_array_equal(s1, s2)
        assert ((s1 >= 0) & (s1 < cfg.vocab)).all()
        with pytest.raises(ValueError, match="PRNG key"):
            lm_generate(params, prompt, cfg, steps=2, temperature=1.0)
        with pytest.raises(ValueError, match="top_k"):
            lm_generate(
                params, prompt, cfg, steps=2, temperature=1.0, top_k=0,
                key=jax.random.PRNGKey(0),
            )

    def test_top_k_truncation_restricts_support(self, cfg, params):
        """top_k=3 samples must land in each step's 3 most likely tokens
        (high temperature flattens the kept mass so an off-by-one in the
        threshold would escape the set almost surely over many seeds)."""
        from parameter_server_tpu.models.transformer import lm_generate

        prompt = np.asarray([[3, 1, 4, 1]], np.int32)
        k = 3
        for seed in range(8):
            out, logits = lm_generate(
                params, prompt, cfg, steps=8, temperature=50.0, top_k=k,
                key=jax.random.PRNGKey(seed), return_logits=True,
            )
            out, logits = np.asarray(out), np.asarray(logits)
            p_len = prompt.shape[1]
            for t in range(p_len - 1, out.shape[1] - 1):
                allowed = np.argsort(logits[0, t])[-k:]
                assert out[0, t + 1] in allowed, (t, out[0, t + 1], allowed)

    def test_generate_supports_moe(self):
        """Round 4 lifted the dense-FFN-only restriction: MoE models
        generate (dropless per-token routing; exactness suite in
        tests/test_moe_serving.py — this pins mere reachability)."""
        from parameter_server_tpu.models.transformer import (
            init_lm,
            lm_generate,
        )

        cfg_m = LMConfig(
            vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            moe_every=2, n_experts=4,
        )
        p_m = init_lm(jax.random.PRNGKey(0), cfg_m)
        out = lm_generate(p_m, np.zeros((1, 4), np.int32), cfg_m, steps=2)
        assert np.asarray(out).shape == (1, 6)


class TestDecodeStepChunkParity:
    """_decode_step is the specialized C=1/scalar-pos fast path of
    _chunk_decode (dynamic-update-slice writes instead of per-row
    scatters — measured ~2x per decode token). They are separate code
    for speed, so this pin is what stops their math drifting apart."""

    @pytest.mark.parametrize(
        "kw",
        [
            {},
            {"rope": True},
            {"n_heads": 4, "n_kv_heads": 2, "compute_dtype": "bfloat16"},
            {"kv_cache_dtype": "int8"},
        ],
    )
    def test_equal_logits_and_caches(self, kw):
        import jax.numpy as jnp

        from parameter_server_tpu.models.transformer import (
            _alloc_kv_caches,
            _chunk_decode,
            _decode_step,
            _prefill,
        )

        base = dict(vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64)
        cfg = LMConfig(**{**base, **kw})
        params = init_lm(jax.random.PRNGKey(0), cfg)
        b, p = 2, 6
        rng = np.random.default_rng(0)
        prompt = jnp.asarray(rng.integers(0, 32, (b, p)), jnp.int32)
        k1, v1 = _alloc_kv_caches(cfg, b, p + 2)
        _, k1, v1 = _prefill(params, cfg, prompt, k1, v1)
        k2, v2 = jax.tree.map(lambda x: x, (k1, v1))
        tok = jnp.asarray(rng.integers(0, 32, (b,)), jnp.int32)
        la, k1, v1 = _decode_step(params, cfg, tok, k1, v1, p)
        lb, k2, v2 = _chunk_decode(
            params, cfg, tok[:, None], k2, v2, jnp.full((b,), p, jnp.int32)
        )
        tol = 2e-2 if cfg.compute_dtype == "bfloat16" else 1e-5
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb[:, 0]), atol=tol, err_msg=str(kw)
        )
        for a, c in zip(jax.tree.leaves((k1, v1)), jax.tree.leaves((k2, v2))):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(c, np.float32),
                atol=tol, err_msg=str(kw),
            )


class TestGenerateContinue:
    """Multi-turn serving: lm_generate(..., return_state=True) +
    lm_generate_continue must reproduce single-shot generation — the
    state carries the caches, so no history is re-prefetched."""

    def test_split_equals_single_shot(self, cfg, params):
        from parameter_server_tpu.models.transformer import (
            lm_generate,
            lm_generate_continue,
        )

        rng = np.random.default_rng(20)
        prompt = rng.integers(0, cfg.vocab, (2, 10)).astype(np.int32)
        full = np.asarray(lm_generate(params, prompt, cfg, steps=12))
        part, state = lm_generate(
            params, prompt, cfg, steps=5, return_state=True,
            max_len=prompt.shape[1] + 12,
        )
        gen2, state2 = lm_generate_continue(params, state, cfg, steps=7)
        got = np.concatenate([np.asarray(part), np.asarray(gen2)], axis=1)
        np.testing.assert_array_equal(got, full)
        assert state2.length == prompt.shape[1] + 12

    def test_new_turn_matches_fresh_generation(self, cfg, params):
        """Ingesting a second 'user turn' through the state must equal
        generating from the full concatenated history."""
        from parameter_server_tpu.models.transformer import (
            lm_generate,
            lm_generate_continue,
        )

        rng = np.random.default_rng(21)
        p1 = rng.integers(0, cfg.vocab, (2, 8)).astype(np.int32)
        p2 = rng.integers(0, cfg.vocab, (2, 5)).astype(np.int32)
        out1, state = lm_generate(
            params, p1, cfg, steps=4, return_state=True, max_len=40
        )
        gen2, _ = lm_generate_continue(
            params, state, cfg, steps=6, new_tokens=p2
        )
        # fresh run over the concatenated history (p1 + generated + p2)
        history = np.concatenate([np.asarray(out1), p2], axis=1)
        want = np.asarray(
            lm_generate(params, history, cfg, steps=6)
        )[:, history.shape[1]:]
        np.testing.assert_array_equal(np.asarray(gen2), want)

    def test_continue_composes_with_features(self):
        """rope + GQA + bf16 + int8 cache through the state handoff."""
        from parameter_server_tpu.models.transformer import (
            lm_generate,
            lm_generate_continue,
        )

        cfg = LMConfig(
            vocab=32, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            n_kv_heads=2, rope=True, compute_dtype="bfloat16",
            kv_cache_dtype="int8",
        )
        p = init_lm(jax.random.PRNGKey(6), cfg)
        prompt = np.random.default_rng(22).integers(0, 32, (2, 8)).astype(
            np.int32
        )
        full = np.asarray(lm_generate(p, prompt, cfg, steps=10))
        part, state = lm_generate(
            p, prompt, cfg, steps=4, return_state=True, max_len=18
        )
        gen2, _ = lm_generate_continue(p, state, cfg, steps=6)
        got = np.concatenate([np.asarray(part), np.asarray(gen2)], axis=1)
        np.testing.assert_array_equal(got, full)

    def test_capacity_validation(self, cfg, params):
        from parameter_server_tpu.models.transformer import (
            lm_generate,
            lm_generate_continue,
        )

        prompt = np.zeros((1, 4), np.int32)
        with pytest.raises(ValueError, match="max_len"):
            lm_generate(params, prompt, cfg, steps=8, max_len=10)
        _, state = lm_generate(
            params, prompt, cfg, steps=2, return_state=True
        )  # capacity exactly 6: no headroom
        with pytest.raises(ValueError, match="cache slots"):
            lm_generate_continue(params, state, cfg, steps=1)

    def test_ingest_only_then_generate(self, cfg, params):
        """steps=0 + new_tokens is the 'absorb the turn now, generate
        later' call; the later generation must equal single-shot over
        the concatenated history (the boundary slot's re-write is an
        identical deterministic recompute)."""
        from parameter_server_tpu.models.transformer import (
            lm_generate,
            lm_generate_continue,
        )

        rng = np.random.default_rng(24)
        p1 = rng.integers(0, cfg.vocab, (2, 7)).astype(np.int32)
        p2 = rng.integers(0, cfg.vocab, (2, 4)).astype(np.int32)
        out1, state = lm_generate(
            params, p1, cfg, steps=3, return_state=True, max_len=30
        )
        empty, state = lm_generate_continue(
            params, state, cfg, steps=0, new_tokens=p2
        )
        assert empty.shape == (2, 0)
        gen, _ = lm_generate_continue(params, state, cfg, steps=5)
        history = np.concatenate([np.asarray(out1), p2], axis=1)
        want = np.asarray(
            lm_generate(params, history, cfg, steps=5)
        )[:, history.shape[1]:]
        np.testing.assert_array_equal(np.asarray(gen), want)
        # steps=0 with no tokens is a no-op
        noop, st2 = lm_generate_continue(params, state, cfg, steps=0)
        assert noop.shape == (2, 0) and st2.length == state.length

    def test_growing_length_does_not_recompile(self, cfg, params):
        """state.length is a traced operand: same-(m, steps) turns at
        different conversation lengths share one compiled program."""
        from parameter_server_tpu.models.transformer import (
            _lm_continue_jit,
            lm_generate,
            lm_generate_continue,
        )

        prompt = np.zeros((1, 4), np.int32)
        _, state = lm_generate(
            params, prompt, cfg, steps=2, return_state=True, max_len=64
        )
        before = None
        for _ in range(3):  # three turns, three different lengths
            _, state = lm_generate_continue(params, state, cfg, steps=3)
            size = _lm_continue_jit._cache_size()
            if before is not None:
                assert size == before, "continuation recompiled per turn"
            before = size

    def test_prefill_only_state_is_exact(self, cfg, params):
        """steps=0 generate: prefill wrote EVERY slot, so the state is
        boundary_cached and the continuation starts from the carried
        logits — exactly equal to single-shot, no slot recomputed."""
        from parameter_server_tpu.models.transformer import (
            lm_generate,
            lm_generate_continue,
        )

        rng = np.random.default_rng(25)
        prompt = rng.integers(0, cfg.vocab, (2, 9)).astype(np.int32)
        _, state = lm_generate(
            params, prompt, cfg, steps=0, return_state=True, max_len=25
        )
        assert state.boundary_cached and state.last_logits is not None
        gen, _ = lm_generate_continue(params, state, cfg, steps=8)
        want = np.asarray(
            lm_generate(params, prompt, cfg, steps=8)
        )[:, prompt.shape[1]:]
        np.testing.assert_array_equal(np.asarray(gen), want)

    def test_sampled_continuation_reproducible(self, cfg, params):
        from parameter_server_tpu.models.transformer import (
            lm_generate,
            lm_generate_continue,
        )

        prompt = np.random.default_rng(23).integers(
            0, cfg.vocab, (2, 6)
        ).astype(np.int32)
        _, state = lm_generate(
            params, prompt, cfg, steps=3, return_state=True, max_len=20,
        )
        a, _ = lm_generate_continue(
            params, state, cfg, steps=5, temperature=0.9,
            key=jax.random.PRNGKey(1),
        )
        b, _ = lm_generate_continue(
            params, state, cfg, steps=5, temperature=0.9,
            key=jax.random.PRNGKey(1),
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestInt8KVCache:
    """kv_cache_dtype="int8": per-token symmetric int8 cache storage.
    The quant error budget: scale = rowmax/127, so |dequant - x| <=
    scale/2 per element — attention scores shift by well under 1%
    relative, which must not change a trained model's decisions and
    must keep logits close on a random one."""

    def test_quant_roundtrip_bound(self):
        import jax.numpy as jnp

        from parameter_server_tpu.models.transformer import _quant_kv_i8

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(4, 3, 64)).astype(np.float32))
        q, s = _quant_kv_i8(x)
        assert q.dtype == jnp.int8 and s.shape == (4, 3)
        deq = np.asarray(q, np.float32) * np.asarray(s)[..., None]
        bound = np.asarray(s)[..., None] * 0.5 + 1e-7
        assert (np.abs(deq - np.asarray(x)) <= bound).all()
        # all-zero row: scale 0, exact zeros back
        qz, sz = _quant_kv_i8(jnp.zeros((1, 2, 8)))
        assert float(np.abs(np.asarray(qz)).max()) == 0.0
        assert float(np.asarray(sz).max()) == 0.0

    def test_int8_decode_logits_track_unquantized(self, cfg, params):
        """Same prompt, steps>0 (the generated rows READ the quantized
        cache): int8-cache logits must track the plain-cache run within
        the quant error budget, for MHA and for GQA+rope+window+bf16."""
        from parameter_server_tpu.models.transformer import lm_generate

        variants = [
            cfg,
            LMConfig(
                vocab=32, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                n_kv_heads=2, rope=True, window=8,
                attention="ring_flash", compute_dtype="bfloat16",
            ),
        ]
        rng = np.random.default_rng(11)
        for base in variants:
            pv = (
                params if base is cfg
                else init_lm(jax.random.PRNGKey(1), base)
            )
            prompt = rng.integers(0, 32, (2, 12)).astype(np.int32)
            _, ref = lm_generate(
                pv, prompt, base, steps=6, return_logits=True
            )
            cfg_i8 = dataclasses.replace(base, kv_cache_dtype="int8")
            _, got = lm_generate(
                pv, prompt, cfg_i8, steps=6, return_logits=True
            )
            err = np.max(np.abs(np.asarray(got) - np.asarray(ref)))
            assert err < 0.08, (base.compute_dtype, err)

    def test_int8_cache_greedy_output_survives_training(self, mesh8, cfg,
                                                        params):
        """On a trained copy task the quantized cache must not flip a
        single greedy decision."""
        from parameter_server_tpu.models.transformer import lm_generate

        losses, p = run_copy_training(mesh8, params, cfg, steps=60)
        assert losses[-1] < 0.5, losses[-1]
        cfg_i8 = dataclasses.replace(cfg, kv_cache_dtype="int8")
        prompt = np.full((2, 8), 7, np.int32)
        out = np.asarray(lm_generate(p, prompt, cfg_i8, steps=12))
        assert (out[:, 8:] == 7).all(), out

    def test_bad_cache_dtype_rejected(self):
        with pytest.raises(ValueError, match="kv_cache_dtype"):
            LMConfig(kv_cache_dtype="int4")


class TestAttentionModes:
    def test_a2a_equals_ring(self, mesh8, params):
        """Both sp schedules compute EXACT attention — the same model
        must produce the same logits under either."""
        from parameter_server_tpu.models.transformer import (
            LMConfig,
            lm_forward,
            shard_tokens,
        )

        cfg_r = LMConfig(vocab=32, d_model=32, n_heads=4, n_layers=2, d_ff=64)
        cfg_a = LMConfig(vocab=32, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                         attention="a2a")
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 32, (2, 64)).astype(np.int32)
        td = shard_tokens(tokens, mesh8)
        out_r = lm_forward(params, td, cfg_r, mesh8, "data")
        out_a = lm_forward(params, td, cfg_a, mesh8, "data")
        np.testing.assert_allclose(
            np.asarray(out_r), np.asarray(out_a), atol=2e-4
        )


class TestMoELM:
    def test_moe_lm_trains_on_copy_task(self, mesh8):
        """A seq-parallel LM with expert-parallel MoE FFNs must train:
        loss on constant-token sequences drops well below uniform."""
        from parameter_server_tpu.models.transformer import (
            LMConfig,
            init_lm,
            make_lm_train_step,
            shard_tokens,
        )

        cfg = LMConfig(vocab=16, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                       moe_every=1, n_experts=8, capacity_factor=4.0)
        params = init_lm(jax.random.PRNGKey(0), cfg)
        assert "l0/moe_router" in params and "l1/moe_router" in params
        step = make_lm_train_step(cfg, mesh8, "data", lr=0.1)
        rng = np.random.default_rng(0)
        losses = []
        for i in range(80):
            tok = np.repeat(
                rng.integers(0, 16, (4, 1)), 32, axis=1
            ).astype(np.int32)
            params, loss = step(params, shard_tokens(tok, mesh8))
            losses.append(float(loss))
        tail = float(np.median(losses[-10:]))
        assert np.isfinite(losses[-1])
        assert tail < 0.5 * losses[0], losses[-10:]
        assert tail < np.log(16) * 0.5, losses[-10:]


class TestTensorParallel:
    def test_tp_sharded_params_match_replicated(self, mesh8):
        """sp x tp on the same 2-D mesh: sequence sharded over 'data',
        weights Megatron-split over 'server' — logits must not change."""
        from parameter_server_tpu.models.transformer import (
            LMConfig,
            init_lm,
            lm_forward,
            shard_lm_params,
            shard_tokens,
        )

        cfg = LMConfig(vocab=32, d_model=32, n_heads=4, n_layers=2, d_ff=64)
        params = init_lm(jax.random.PRNGKey(1), cfg)
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 32, (2, 64)).astype(np.int32)
        td = shard_tokens(tokens, mesh8)
        base = lm_forward(params, td, cfg, mesh8, "data")
        tp_params = shard_lm_params(params, mesh8, "server")
        tp = lm_forward(tp_params, td, cfg, mesh8, "data")
        np.testing.assert_allclose(
            np.asarray(tp), np.asarray(base), atol=2e-4
        )
        # placement really is Megatron-split (spec, not just the mesh)
        assert "server" in str(tp_params["l0/wq"].sharding.spec)

    def test_tp_training_step_runs(self, mesh8):
        from parameter_server_tpu.models.transformer import (
            LMConfig,
            init_lm,
            make_lm_train_step,
            shard_lm_params,
            shard_tokens,
        )

        cfg = LMConfig(vocab=16, d_model=32, n_heads=4, n_layers=2, d_ff=64)
        params = shard_lm_params(init_lm(jax.random.PRNGKey(0), cfg), mesh8)
        step = make_lm_train_step(cfg, mesh8, "data", lr=0.2)
        rng = np.random.default_rng(0)
        first = last = None
        for i in range(30):
            tok = np.repeat(
                rng.integers(0, 16, (4, 1)), 32, axis=1
            ).astype(np.int32)
            params, loss = step(params, shard_tokens(tok, mesh8))
            first = first if first is not None else float(loss)
            last = float(loss)
        assert np.isfinite(last) and last < first
        # weights kept their tp sharding (the SPEC, not just the mesh)
        # through the jitted update steps
        assert "server" in str(params["l0/wq"].sharding.spec)


class TestGQA:
    """Grouped-query attention through the LM stack (LMConfig.n_kv_heads):
    narrow K/V params, group-broadcast training forward, grouped decode
    cache. Extension row 56g (flash_mha n_kv_heads is the kernel-level
    half; this is the LM/decode half)."""

    def _cfg(self, kvh):
        return LMConfig(
            vocab=32, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            n_kv_heads=kvh,
        )

    def test_validation(self):
        with pytest.raises(ValueError, match="multiple"):
            self._cfg(3)
        with pytest.raises(ValueError, match="n_kv_heads"):
            self._cfg(8)
        with pytest.raises(ValueError, match="n_kv_heads"):
            self._cfg(0)
        assert self._cfg(2).kv_heads == 2
        assert self._cfg(None).kv_heads == 4

    def test_param_shapes(self):
        from parameter_server_tpu.models.transformer import init_lm

        params = init_lm(jax.random.PRNGKey(0), self._cfg(1))  # MQA
        assert params["l0/wk"].shape == (32, 8)  # kvh * hd = 1 * 8
        assert params["l0/wv"].shape == (32, 8)
        assert params["l0/wq"].shape == (32, 32)

    @pytest.mark.parametrize("kvh", [1, 2])
    def test_decode_matches_forward(self, kvh):
        """The grouped decode cache and the group-broadcast training
        forward must agree logit-for-logit."""
        from parameter_server_tpu.models.transformer import (
            init_lm,
            lm_forward,
            lm_generate,
            shard_tokens,
        )
        from parameter_server_tpu.parallel import mesh as meshlib

        cfg = self._cfg(kvh)
        params = init_lm(jax.random.PRNGKey(1), cfg)
        rng = np.random.default_rng(3)
        tokens = rng.integers(0, 32, (2, 16)).astype(np.int32)
        _, dec = lm_generate(params, tokens, cfg, steps=4, return_logits=True)
        mesh1 = meshlib.make_mesh(num_data=1, num_server=1)
        full = lm_forward(
            params, shard_tokens(tokens, mesh1), cfg, mesh1, "data"
        )
        # prompt positions: decode rows [0, 15) vs forward rows [0, 15)
        np.testing.assert_allclose(
            np.asarray(dec)[:, : tokens.shape[1] - 1],
            np.asarray(full)[:, :-1],
            atol=2e-4, rtol=1e-4,
        )

    def test_cache_shrinks_by_group_factor(self):
        from parameter_server_tpu.models.transformer import (
            _prefill,
            init_lm,
        )

        cfg = self._cfg(2)
        params = init_lm(jax.random.PRNGKey(0), cfg)
        import jax.numpy as jnp

        b, p = 2, 8
        hd = cfg.d_model // cfg.n_heads
        # caches are (data, scale) pytrees; scale None = plain dtype
        kcache = (jnp.zeros((cfg.n_layers, b, cfg.kv_heads, p, hd)), None)
        logits, kcache, _ = _prefill(
            params, cfg, jnp.zeros((b, p), jnp.int32), kcache,
            jax.tree.map(jnp.zeros_like, kcache),
        )
        assert kcache[0].shape[2] == 2  # kv heads, not 4 query heads
        assert logits.shape == (b, p, cfg.vocab)

    def test_gqa_trains(self, mesh8):
        from parameter_server_tpu.models.transformer import (
            init_lm,
            make_lm_train_step,
            shard_tokens,
        )

        cfg = self._cfg(2)
        params = init_lm(jax.random.PRNGKey(0), cfg)
        step = make_lm_train_step(cfg, mesh8, lr=0.5)
        rng = np.random.default_rng(0)
        toks = shard_tokens(
            rng.integers(0, 32, (2, 32)).astype(np.int32), mesh8
        )
        losses = []
        for _ in range(6):
            params, loss = step(params, toks)
            losses.append(float(loss))
        assert losses[-1] < losses[0]  # learns with narrow K/V


class TestPrefillAttention:
    """The prefill dispatch: chunked XLA path vs the flash-kernel path
    (interpret mode off-TPU) must agree, including GQA and window."""

    @pytest.mark.parametrize("kvh,window", [(4, None), (2, None), (1, 7)])
    def test_flash_matches_chunked(self, kvh, window):
        from parameter_server_tpu.models.transformer import (
            _prefill_attention,
        )

        import jax.numpy as jnp

        b, p, nh, hd = 2, 24, 4, 8
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(size=(b, p, nh, hd)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(b, p, kvh, hd)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(b, p, kvh, hd)).astype(np.float32))
        chunked = _prefill_attention(q, k, v, window, use_flash=False)
        flash = _prefill_attention(
            q, k, v, window, use_flash=True, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(flash), np.asarray(chunked), atol=2e-5, rtol=1e-5
        )


class TestTopP:
    """Nucleus sampling: composes with top_k; a vanishing nucleus
    degenerates to greedy; validation mirrors top_k's."""

    def _setup(self):
        from parameter_server_tpu.models.transformer import (
            init_lm,
            lm_generate,
        )

        import jax.numpy as jnp

        cfg = LMConfig(vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64)
        params = init_lm(jax.random.PRNGKey(0), cfg)
        prompt = jnp.asarray(
            np.random.default_rng(0).integers(0, 64, (2, 16), np.int32)
        )
        return cfg, params, prompt, lm_generate

    def test_tiny_nucleus_is_greedy(self):
        cfg, params, prompt, gen = self._setup()
        got = gen(params, prompt, cfg, steps=8, temperature=0.9,
                  top_p=1e-9, key=jax.random.PRNGKey(1))
        greedy = gen(params, prompt, cfg, steps=8)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(greedy))

    def test_full_nucleus_matches_plain_sampling(self):
        cfg, params, prompt, gen = self._setup()
        # top_p=1.0 keeps everything: identical to plain temperature
        # sampling under the same key
        a = gen(params, prompt, cfg, steps=8, temperature=0.8,
                top_p=1.0, key=jax.random.PRNGKey(2))
        b = gen(params, prompt, cfg, steps=8, temperature=0.8,
                key=jax.random.PRNGKey(2))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_composes_with_top_k(self):
        cfg, params, prompt, gen = self._setup()
        out = gen(params, prompt, cfg, steps=8, temperature=0.9,
                  top_k=8, top_p=0.9, key=jax.random.PRNGKey(3))
        assert out.shape == (2, 24)
        assert (np.asarray(out) < 64).all()

    def test_validation(self):
        cfg, params, prompt, gen = self._setup()
        with pytest.raises(ValueError, match="sampling"):
            gen(params, prompt, cfg, steps=2, top_p=0.5)
        with pytest.raises(ValueError, match="top_p"):
            gen(params, prompt, cfg, steps=2, temperature=0.9, top_p=1.5,
                key=jax.random.PRNGKey(0))


def test_tp_composes_with_gqa(mesh8):
    """Megatron placement of GQA-narrow wk/wv (kvh*hd columns over the
    server axis) must reproduce the replicated logits exactly."""
    cfg = LMConfig(
        vocab=32, d_model=32, n_heads=4, n_layers=2, d_ff=64, n_kv_heads=2
    )
    params = init_lm(jax.random.PRNGKey(1), cfg)
    assert params["l0/wk"].shape == (32, 16)  # narrow K/V
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 32, (2, 64)).astype(np.int32)
    td = shard_tokens(tokens, mesh8)
    base = lm_forward(params, td, cfg, mesh8, "data")
    tp_params = shard_lm_params(params, mesh8, "server")
    tp = lm_forward(tp_params, td, cfg, mesh8, "data")
    np.testing.assert_allclose(np.asarray(tp), np.asarray(base), atol=2e-4)
    assert "server" in str(tp_params["l0/wk"].sharding.spec)
