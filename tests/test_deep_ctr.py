"""Wide & Deep CTR app (apps/linear/deep_ctr.py): device/host forward
parity, sparse-update semantics (untouched slots, wide-only L1), the
interaction capability test, and the elastic live-resize contract."""

import numpy as np
import pytest

from parameter_server_tpu.apps.linear.config import (
    Config,
    LearningRateConfig,
    LossConfig,
    PenaltyConfig,
    SGDConfig,
)
from parameter_server_tpu.apps.linear.deep_ctr import DeepCTRWorker
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.utils.sparse import SparseBatch


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    yield
    Postoffice.reset()


def make_conf(num_slots=64, lanes=2, alpha=0.1, lambda1=0.0):
    conf = Config()
    conf.loss = LossConfig(type="logit")
    conf.penalty = PenaltyConfig(type="l1", lambda_=[lambda1])
    conf.learning_rate = LearningRateConfig(type="decay", alpha=alpha, beta=1.0)
    conf.async_sgd = SGDConfig(
        algo="standard", minibatch=256, num_slots=num_slots, ell_lanes=lanes
    )
    return conf


def batch_of(rows, y):
    rows = np.asarray(rows, np.int64)
    n, lanes = rows.shape
    return SparseBatch(
        y=np.asarray(y, np.float32),
        indptr=np.arange(0, lanes * n + 1, lanes, dtype=np.int64),
        indices=rows.reshape(-1),
        values=None,
    )


def interaction_batches(n_batches, rows_per=256, seed0=0):
    """y = +1 iff both features come from the same group — zero linear
    signal by construction (same task the FM test uses)."""
    out = []
    for i in range(n_batches):
        rng = np.random.default_rng(seed0 + i)
        a = rng.integers(0, 2, rows_per)
        b = rng.integers(0, 2, rows_per)
        keys = np.stack([a, 2 + b], axis=1)
        y = np.where(a == b, 1.0, -1.0)
        out.append(batch_of(keys, y))
    return out


def test_device_forward_matches_host_predict(mesh8):
    w = DeepCTRWorker(
        make_conf(num_slots=64), k=4, hidden=(8,), mesh=mesh8,
        v_init_std=0.3, seed=1,
    )
    rng = np.random.default_rng(0)
    n = 16
    keys = rng.integers(0, 1 << 40, (n, 2))
    y = np.where(rng.random(n) < 0.5, 1.0, -1.0)
    batch = batch_of(keys, y)
    host = w.predict_margin(batch)  # BEFORE any update
    prepped = w._prep_ell(batch)
    _, metrics = w._step(w.state, prepped.y, prepped.mask, prepped.slots)
    xw = np.asarray(metrics["xw"]).ravel()
    mask = np.asarray(metrics["mask"]).ravel() > 0
    np.testing.assert_allclose(xw[mask], host, atol=1e-4, rtol=1e-4)


def test_untouched_slots_stay_fixed_and_mlp_updates(mesh8):
    w = DeepCTRWorker(
        make_conf(num_slots=64), k=4, hidden=(8,), mesh=mesh8,
        v_init_std=0.3, seed=2,
    )
    v0 = np.asarray(w.state["table"]["v"]).copy()
    mlp0 = [np.asarray(p).copy() for p in w.state["mlp"]]
    batch = batch_of([[1, 3], [0, 2]], [1.0, -1.0])
    touched = set(w.directory.slots(batch.indices).tolist())
    w.collect(w.process_minibatch(batch))
    v1 = np.asarray(w.state["table"]["v"])
    for s in range(w.num_slots):
        if s in touched:
            assert np.abs(v1[s] - v0[s]).max() > 0, f"slot {s} should move"
        else:
            np.testing.assert_array_equal(v1[s], v0[s])
    # the replicated MLP must move too (deep path carries gradient)
    assert any(
        np.abs(np.asarray(p1) - p0).max() > 0
        for p1, p0 in zip(w.state["mlp"], mlp0)
    )


def test_l1_pins_wide_but_deep_still_learns(mesh8):
    # heavy L1 on the wide table: w stays at 0, yet the model still
    # separates the interaction task through the (unpenalized) deep path
    w = DeepCTRWorker(
        make_conf(alpha=0.3, lambda1=10.0), k=4, hidden=(16,), mesh=mesh8,
        v_init_std=0.3, seed=3,
    )
    w.train(iter(interaction_batches(40)))
    assert float(np.abs(np.asarray(w.state["table"]["w"])).max()) == 0.0
    test = interaction_batches(1, rows_per=1000, seed0=999)[0]
    assert w.evaluate(test)["auc"] > 0.9


def test_wide_deep_learns_interaction_linear_cannot(mesh8):
    from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker

    train = interaction_batches(60)
    test = interaction_batches(1, rows_per=1000, seed0=999)[0]

    deep = DeepCTRWorker(
        make_conf(alpha=0.3, lambda1=0.001), k=4, hidden=(16,), mesh=mesh8,
        v_init_std=0.3, seed=2,
    )
    deep.train(iter(train))
    deep_auc = deep.evaluate(test)["auc"]

    linear = AsyncSGDWorker(make_conf(alpha=0.3, lambda1=0.001), mesh=mesh8)
    linear.train(iter(train))
    lin_auc = linear.evaluate(test)["auc"]

    assert deep_auc > 0.9, f"wide&deep failed the interaction task: {deep_auc}"
    assert lin_auc < 0.6, f"linear should NOT solve it: {lin_auc}"


def test_checkpoint_mid_flight_keeps_metrics(mesh8, tmp_path):
    # a checkpoint between submit and collect must not swallow the
    # in-flight step's metrics (state_host drains with pop=False)
    from parameter_server_tpu.parameter.replica import CheckpointManager

    w = DeepCTRWorker(
        make_conf(alpha=0.3, lambda1=0.001), k=4, hidden=(8,), mesh=mesh8,
        v_init_std=0.3, seed=2,
    )
    b = interaction_batches(1)[0]
    ts = w.process_minibatch(b)
    w.checkpoint(CheckpointManager(str(tmp_path / "ck")), step=1)
    prog = w.collect(ts)
    assert prog.num_examples_processed == 256


def test_predict_margin_ragged_and_overflow(mesh8):
    w = DeepCTRWorker(
        make_conf(num_slots=64, lanes=4), k=3, hidden=(8,), mesh=mesh8,
        v_init_std=0.2, seed=5,
    )
    # ragged CSR incl. an EMPTY row: short rows pad with zero embeddings
    batch = SparseBatch(
        y=np.array([1.0, -1.0, 1.0], np.float32),
        indptr=np.array([0, 3, 3, 7], np.int64),
        indices=np.array([5, 9, 11, 2, 5, 30, 31], np.int64),
        values=None,
    )
    out = w.predict_margin(batch)
    # oracle: per-row loop with explicit lane padding
    v = np.asarray(w.state["table"]["v"]).astype(np.float64)
    wl = np.asarray(w.state["table"]["w"]).astype(np.float64)
    mlp = [np.asarray(p).astype(np.float64) for p in w.state["mlp"]]
    b = float(w.state["b"])
    slots = w.directory.slots(batch.indices)
    for r in range(3):
        sl = slots[batch.indptr[r] : batch.indptr[r + 1]]
        e = np.zeros((4, 3))
        e[: len(sl)] = v[sl]
        h = e.reshape(1, -1)
        for i in range(len(mlp) // 2 - 1):
            h = np.maximum(h @ mlp[2 * i] + mlp[2 * i + 1], 0.0)
        want = b + wl[sl].sum() + (h @ mlp[-2] + mlp[-1])[0, 0]
        np.testing.assert_allclose(out[r], want, atol=1e-5)
    # a row wider than the lane budget must be REJECTED, not truncated
    wide_batch = SparseBatch(
        y=np.array([1.0], np.float32),
        indptr=np.array([0, 5], np.int64),
        indices=np.array([1, 2, 3, 4, 5], np.int64),
        values=None,
    )
    with pytest.raises(ValueError, match="lane budget"):
        w.predict_margin(wide_batch)


def test_deep_ctr_checkpoint_restore(mesh8, tmp_path):
    from parameter_server_tpu.parameter.replica import CheckpointManager

    w = DeepCTRWorker(
        make_conf(alpha=0.3, lambda1=0.001), k=4, hidden=(16,), mesh=mesh8,
        v_init_std=0.3, seed=2,
    )
    w.train(iter(interaction_batches(20)))
    test = interaction_batches(1, rows_per=500, seed0=999)[0]
    want = w.predict_margin(test)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    w.checkpoint(mgr, step=7)
    # a FRESH worker (different seed -> different init) restores exactly
    w2 = DeepCTRWorker(
        make_conf(alpha=0.3, lambda1=0.001), k=4, hidden=(16,), mesh=mesh8,
        v_init_std=0.3, seed=99,
    )
    assert w2.restore(mgr) == 7
    np.testing.assert_allclose(w2.predict_margin(test), want, atol=1e-6)
    # training continues after restore
    w2.collect(w2.process_minibatch(interaction_batches(1, seed0=55)[0]))


def test_deep_ctr_resizes_live(mesh8):
    from parameter_server_tpu.system.elastic import ElasticCoordinator

    def mk(mesh):
        return DeepCTRWorker(
            make_conf(num_slots=100, alpha=0.3, lambda1=0.001), k=4,
            hidden=(16,), mesh=mesh, v_init_std=0.3, seed=2,
        )

    co = ElasticCoordinator(mk, num_data=2, num_server=2)
    w = co.start()
    w.train(iter(interaction_batches(40)))
    test = interaction_batches(1, rows_per=500, seed0=999)[0]
    auc_before = w.evaluate(test)["auc"]
    w2 = co.add_server()  # 2x2 -> 2x3, non-divisible table padding
    auc_after = w2.evaluate(test)["auc"]
    assert auc_after == auc_before > 0.9
    w2.collect(w2.process_minibatch(interaction_batches(1, seed0=77)[0]))
