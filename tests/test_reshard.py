"""Server key-range reassignment (ref src/test/
reassign_server_key_range_ps.cc): state saved under one server split must
restore — values intact — onto a mesh with a DIFFERENT number of server
shards, and training must continue. On TPU the key ranges are the table
sharding, so reassignment = restore with the new mesh's NamedSharding."""

import numpy as np
import pytest

from parameter_server_tpu.parallel import mesh as meshlib
from parameter_server_tpu.system.postoffice import Postoffice


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    yield
    Postoffice.reset()


def test_kv_vector_reshards_2_to_4_servers(mesh8):
    from parameter_server_tpu.parameter.kv_vector import KVVector

    mesh_a = meshlib.make_mesh(num_data=4, num_server=2)
    mesh_b = meshlib.make_mesh(num_data=2, num_server=4)
    keys = np.array([3, 17, 40, 99, 512, 1000], dtype=np.int64)
    vals = np.arange(12, dtype=np.float32).reshape(6, 2)

    kv_a = KVVector(mesh=mesh_a, k=2, num_slots=1024, hashed=False)
    kv_a.set_keys(0, keys)
    kv_a.wait(kv_a.push(kv_a.request(channel=0), keys=keys, values=vals))
    snap = kv_a.get_replica()

    kv_b = KVVector(mesh=mesh_b, k=2, num_slots=1024, hashed=False)
    kv_b.set_keys(0, keys)
    kv_b.set_replica(snap)
    np.testing.assert_allclose(kv_b.values(0, keys), vals)
    # the restored table is really sharded 4 ways now
    table = kv_b.table(0)
    assert dict(table.sharding.mesh.shape)["server"] == 4
    # and stays writable: pushes land on the new shards
    kv_b.wait(kv_b.push(kv_b.request(channel=0), keys=keys, values=vals))
    np.testing.assert_allclose(kv_b.values(0, keys), 2 * vals)


def test_restore_matches_namedtuple_fields_by_name(tmp_path, mesh8):
    """Orbax returns namedtuples as field-name dicts; the restore walk
    must pair them BY NAME. optax's MultiStepsState is the regression:
    its field order (mini_step, gradient_step, inner_opt_state,
    acc_grads, skip_state) differs from sorted order, so the old
    sorted-leaf reorder cross-wired adam moments with accumulator
    slots (caught as a shape error mid-update after a CLI resume)."""
    import jax
    import optax

    from parameter_server_tpu.parameter.replica import CheckpointManager

    params = {
        "emb": np.arange(12, dtype=np.float32).reshape(4, 3),
        "w1": np.ones((3, 5), np.float32),
    }
    tx = optax.MultiSteps(
        optax.chain(optax.clip_by_global_norm(1.0), optax.adam(1e-2)),
        every_k_schedule=2,
    )
    opt = tx.init(params)
    # advance one microbatch so every counter/accumulator is nonzero
    grads = jax.tree.map(lambda x: 0.5 * np.ones_like(x), params)
    _, opt = tx.update(grads, opt, params)

    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(3, {"params": params, "opt": opt})
    got = mgr.restore(3, like={"params": params, "opt": tx.init(params)})
    for a, b in zip(
        jax.tree.leaves(got["opt"], is_leaf=lambda x: x is None),
        jax.tree.leaves(opt, is_leaf=lambda x: x is None),
    ):
        if b is None:
            assert a is None
        else:
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    # structure (not just leaves) survives: same namedtuple types
    assert jax.tree.structure(got["opt"]) == jax.tree.structure(opt)
    # a SMALLER template must refuse the checkpoint (extra keys are a
    # config mismatch, not something to silently drop)
    with pytest.raises(ValueError, match="unexpected"):
        mgr.restore(3, like={"params": {"emb": params["emb"]},
                             "opt": tx.init(params)})


def test_worker_checkpoint_restores_across_server_counts(tmp_path, mesh8):
    from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker
    from parameter_server_tpu.apps.linear.config import (
        Config,
        LearningRateConfig,
        PenaltyConfig,
        SGDConfig,
    )
    from parameter_server_tpu.parameter.replica import CheckpointManager
    from parameter_server_tpu.utils.sparse import random_sparse

    rng = np.random.default_rng(0)
    w_true = (rng.normal(size=512) * (rng.random(512) < 0.2)).astype(np.float32)

    def make_worker(mesh):
        conf = Config()
        conf.penalty = PenaltyConfig(type="l1", lambda_=[0.01])
        conf.learning_rate = LearningRateConfig(type="decay", alpha=0.5, beta=1.0)
        conf.async_sgd = SGDConfig(
            algo="ftrl", minibatch=256, num_slots=4096, ell_lanes=8
        )
        return AsyncSGDWorker(conf, mesh=mesh)

    def batches(n, seed0=0):
        for i in range(n):
            yield random_sparse(
                256, 512, 8, seed=seed0 + i, w_true=w_true, binary=True
            )

    mgr = CheckpointManager(str(tmp_path))
    w_a = make_worker(meshlib.make_mesh(num_data=4, num_server=2))
    w_a.train(batches(5))
    w_a.checkpoint(mgr, step=5)
    w_a.train(batches(3, seed0=50))
    want = w_a.weights_dense()

    # "cluster resize": 4 servers now; same checkpoint, same replay
    w_b = make_worker(meshlib.make_mesh(num_data=2, num_server=4))
    assert w_b.restore(mgr) == 5
    w_b.train(batches(3, seed0=50))
    np.testing.assert_allclose(w_b.weights_dense(), want, atol=1e-6)
