"""Device truth plane (telemetry/device.py + the merged device
timeline): the contracts doc/OBSERVABILITY.md "Device truth plane"
sells.

- the compiled-function inventory is a DROP-IN wrapper: identical
  outputs, donation semantics preserved, tracer-stage calls pass
  through, unreadable signatures fall back to the plain jit path —
  and two builders sharing a name with different closures NEVER get
  each other's executable (the aval-only-key bug this module's cache
  key regression-tests);
- recompiles are counted per name (new avals or statics), zero on a
  steady-shape stream after the warmup mark — including through the
  real kv_ops data plane;
- the runtime donation verifier counts a deliberately non-donatable
  jit (shape-mismatched alias) and stays silent on a healthy one;
- roofline sampling turns measured dispatch wall time + cost analysis
  into achieved GB/s (+ frac-of-peak only when the peak tables know
  the chip — a CPU host reports rates, never a faked frac);
- the HBM monitor collects live-buffer totals with a monotone
  high-water mark on every backend;
- the recompile-storm alert rule (configs/alerts/default.json) walks
  inactive→pending→firing on a shape-churning jit and resolves when
  shapes steady;
- synthetic device tracks merge into the host timeline (flows
  inherited from the submitting executor.step), attribute correctly
  (kernel-dominated vs gap-dominated), and records without a device
  trace are byte-for-byte unchanged.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.telemetry import device as device_mod
from parameter_server_tpu.telemetry import registry as telemetry_registry


@pytest.fixture()
def fresh_plane():
    """Hermetic inventory + registry per test (the process-global
    inventory is shared with every other module's wrap points)."""
    Postoffice.reset()
    device_mod.reset()
    yield device_mod.inventory()
    device_mod.reset()
    Postoffice.reset()


def _recompiles_total(name: str) -> float:
    reg = telemetry_registry.default_registry()
    decl = reg.export_state().get("ps_device_recompiles_total")
    if decl is None:
        return 0.0
    return sum(
        s["value"] for s in decl["series"] if s["labels"].get("fn") == name
    )


class TestInventory:
    def test_wrapper_parity_and_compile_accounting(self, fresh_plane):
        f = jax.jit(lambda x, y: x * 2.0 + y)
        w = device_mod.instrument("t_parity", f)
        x = jnp.arange(32, dtype=jnp.float32)
        y = jnp.ones(32, jnp.float32)
        np.testing.assert_array_equal(np.asarray(w(x, y)), np.asarray(f(x, y)))
        w(x, y)  # same avals: no new compile
        rec = fresh_plane.snapshot()["functions"]["t_parity"]
        assert rec["compiles"] == 1
        assert rec["recompiles"] == 0
        assert rec["calls"] == 2
        # the XLA analyses landed with the compile
        assert rec["cost"]["flops"] > 0
        assert rec["cost"]["bytes_accessed"] > 0
        assert rec["memory"]["output_bytes"] > 0

    def test_recompile_on_new_avals_counted_and_metered(self, fresh_plane):
        w = device_mod.instrument("t_recompile", jax.jit(lambda x: x + 1))
        w(jnp.ones(8))
        assert _recompiles_total("t_recompile") == 0
        w(jnp.ones(16))  # new shape → re-specialization
        w(jnp.ones(16))  # cached: no growth
        rec = fresh_plane.snapshot()["functions"]["t_recompile"]
        assert rec["compiles"] == 2
        assert rec["recompiles"] == 1
        assert _recompiles_total("t_recompile") == 1

    def test_static_change_is_a_recompile(self, fresh_plane):
        import functools

        f = functools.partial(jax.jit, static_argnames=("k",))(
            lambda x, k: x * k
        )
        w = device_mod.instrument("t_static", f, static_argnames=("k",))
        x = jnp.ones(8)
        assert float(np.asarray(w(x, k=3))[0]) == 3.0
        assert float(np.asarray(w(x, k=5))[0]) == 5.0
        rec = fresh_plane.snapshot()["functions"]["t_static"]
        assert rec["compiles"] == 2 and rec["recompiles"] == 1

    def test_tracer_stage_calls_pass_through(self, fresh_plane):
        w = device_mod.instrument("t_traced", jax.jit(lambda x: x * 3.0))

        @jax.jit
        def outer(a):
            return w(a) + 1.0

        assert float(np.asarray(outer(jnp.ones(4)))[0]) == 4.0
        # the enclosing jit owned the compile: no inventory entry
        assert "t_traced" not in fresh_plane.snapshot()["functions"]

    def test_unlowerable_callable_falls_back(self, fresh_plane):
        # a plain python callable has no .lower: the wrapper must
        # route to it untouched and count the dispatch fallback
        w = device_mod.instrument("t_fallback", lambda x: x + 1)
        assert w(1) == 2
        rec = fresh_plane.snapshot()["functions"]["t_fallback"]
        assert rec["dispatch_fallbacks"] == 1

    def test_same_name_different_closures_not_cross_served(self, fresh_plane):
        """REGRESSION (caught live by test_async_sgd's noise tests):
        two builders share an inventory name and avals but close over
        different constants — any SHARED aval-keyed executable cache
        hands the second the FIRST one's compiled program (the cache
        must be per-wrapper)."""
        def build(c):
            return device_mod.instrument(
                "t_closure", jax.jit(lambda x: x + c)
            )

        a, b = build(1.0), build(100.0)
        x = jnp.zeros(8)
        assert float(np.asarray(a(x))[0]) == 1.0
        assert float(np.asarray(b(x))[0]) == 100.0  # not 1.0
        # and the second build's compile is visible as a recompile
        rec = fresh_plane.snapshot()["functions"]["t_closure"]
        assert rec["compiles"] == 2

    def test_default_spelling_variants_are_one_compile(self, fresh_plane):
        """jit's own cache treats f(x), f(x, seed_default) and
        f(x, k=<declared default>) as ONE entry; the wrapper must
        normalize the same way or an omitted-vs-explicit default
        double-compiles and ticks a spurious recompile — breaking the
        zero-post-warmup contract (and the storm page rule) on a
        healthy run."""
        import functools

        f = functools.partial(jax.jit, static_argnames=("k",))(
            lambda x, seed=0, *, k=2: x * k + seed
        )
        w = device_mod.instrument("t_spelling", f, static_argnames=("k",))
        x = jnp.ones(8)
        w(x)                # all defaults omitted
        w(x, 0, k=2)        # same call, spelled out
        w(x, seed=0, k=2)   # same call, keyword spelling
        rec = fresh_plane.snapshot()["functions"]["t_spelling"]
        assert rec["compiles"] == 1 and rec["recompiles"] == 0
        assert rec.get("dispatch_fallbacks", 0) == 0

    def test_distinct_shardings_get_distinct_entries(
        self, fresh_plane, mesh8
    ):
        """Sharding is part of the cache key: a Compiled is specialized
        to the shardings it was lowered with, so two same-aval call
        patterns with different shardings need their own entries — a
        shared entry would make the second pattern raise-and-fall-back
        on EVERY dispatch (per-call exception on the hot data plane,
        chip accounting silently skipped)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        w = device_mod.instrument("t_shard", jax.jit(lambda t: t * 2.0))
        base = np.arange(8 * 128, dtype=np.float32).reshape(8, 128)
        x_sh = jax.device_put(base, NamedSharding(mesh8, P("server")))
        x_rep = jax.device_put(base, NamedSharding(mesh8, P()))
        a = np.asarray(w(x_sh))
        b = np.asarray(w(x_rep))
        np.testing.assert_array_equal(a, b)
        rec = fresh_plane.snapshot()["functions"]["t_shard"]
        assert rec["compiles"] == 2
        assert rec.get("dispatch_fallbacks", 0) == 0

    def test_donated_input_consumed_like_plain_jit(self, fresh_plane):
        w = device_mod.instrument(
            "t_donate", jax.jit(lambda x: x + 1, donate_argnums=(0,)),
            donate_argnums=(0,),
        )
        x = jnp.ones(128)
        out = w(x)
        assert float(np.asarray(out)[0]) == 2.0
        assert x.is_deleted()  # the buffer was really donated


class TestDonationVerifier:
    def test_shape_mismatched_alias_counted(self, fresh_plane):
        """Satellite: the runtime verifier's discriminating case — a
        deliberately non-donatable jit (the donated [N] input cannot
        alias the scalar output) must count a fallback; the static
        donation lint cannot see this, only the compiled program
        can."""
        w = device_mod.instrument(
            "t_bad_donate",
            jax.jit(lambda x: x.sum(), donate_argnums=(0,)),
            donate_argnums=(0,),
        )
        w(jnp.ones((8, 128)))
        snap = fresh_plane.snapshot()
        assert snap["functions"]["t_bad_donate"]["donation_fallbacks"] == 1
        assert snap["donation_fallbacks_total"] == 1
        reg = telemetry_registry.default_registry()
        decl = reg.export_state()["ps_device_donation_fallbacks_total"]
        assert sum(
            s["value"] for s in decl["series"]
            if s["labels"].get("fn") == "t_bad_donate"
        ) == 1

    def test_healthy_donation_silent(self, fresh_plane):
        w = device_mod.instrument(
            "t_good_donate",
            jax.jit(lambda x: x * 2.0, donate_argnums=(0,)),
            donate_argnums=(0,),
        )
        w(jnp.ones((8, 128)))
        rec = fresh_plane.snapshot()["functions"]["t_good_donate"]
        assert rec["donation_fallbacks"] == 0
        # and the analysis shows the aliased bytes
        assert rec["memory"]["alias_bytes"] == 8 * 128 * 4
        assert rec["donated_bytes"] == 8 * 128 * 4


class TestRoofline:
    def test_sampling_sets_gauges_no_faked_frac_on_cpu(self, fresh_plane):
        prev = device_mod.set_sampling(1)
        try:
            w = device_mod.instrument("t_roof", jax.jit(lambda x: x @ x))
            w(jnp.ones((64, 64)))
        finally:
            device_mod.set_sampling(prev)
        rec = fresh_plane.snapshot()["functions"]["t_roof"]
        tl = rec["roofline"]
        assert tl["wall_ms"] > 0
        assert tl["achieved_gb_s"] > 0
        assert tl["achieved_tflops"] >= 0
        # CPU host: the peak tables do not know this kind — no frac
        assert "frac_of_hbm_peak" not in tl
        assert "mfu" not in tl
        reg = telemetry_registry.default_registry()
        export = reg.export_state()
        gb = export["ps_device_kernel_gb_s"]
        assert any(
            s["labels"].get("fn") == "t_roof" and s["value"] > 0
            for s in gb["series"]
        )
        assert not export.get("ps_device_roofline_frac", {}).get("series")

    def test_sampling_off_by_default(self, fresh_plane):
        w = device_mod.instrument("t_unsampled", jax.jit(lambda x: x + 1))
        w(jnp.ones(8))
        assert "roofline" not in fresh_plane.snapshot()["functions"][
            "t_unsampled"
        ]


class TestHbmMonitor:
    def test_live_buffer_accounting_and_high_water(self, fresh_plane):
        mon = device_mod.install_hbm_monitor()
        assert mon is not None
        big = jax.device_put(np.zeros(1 << 16, np.float32))
        snap1 = mon.snapshot()
        assert snap1["live_buffer_bytes"] >= big.nbytes
        hw1 = snap1["live_buffer_high_water_bytes"]
        del big
        snap2 = mon.snapshot()
        # high water is monotone even after buffers die
        assert snap2["live_buffer_high_water_bytes"] >= hw1
        reg = telemetry_registry.default_registry()
        export = reg.export_state()
        assert export["ps_device_live_buffer_bytes"]["series"]
        assert export["ps_device_live_buffer_high_water_bytes"]["series"]

    def test_bench_snapshot_shape(self, fresh_plane):
        device_mod.install_hbm_monitor()
        snap = device_mod.snapshot()
        assert "functions" in snap
        assert "hbm" in snap and "live_buffer_bytes" in snap["hbm"]
        assert snap["backend"] == "cpu"
        # the no-faked-peak rule rides into the record
        assert snap["hbm_peak_gb_s"] is None
        assert snap["flops_peak_tflops"] is None


class TestSteadyState:
    def test_zero_recompiles_post_warmup_through_kv_data_plane(
        self, fresh_plane, mesh8
    ):
        """Satellite: the steady-state contract on the REAL data plane
        — after warmup, a fixed-shape push/pull stream through the
        instrumented kv_ops entry points must re-specialize nothing."""
        from parameter_server_tpu.ops import kv_ops
        from parameter_server_tpu.parallel import mesh as meshlib

        rng = np.random.default_rng(0)
        p, n, k = 1 << 10, 1 << 7, 4
        tbl = jax.device_put(
            jnp.zeros((p, k), jnp.float32), meshlib.table_sharding(mesh8)
        )
        idx = jax.device_put(rng.integers(0, p, n).astype(np.int32))
        vals = jax.device_put(rng.normal(size=(n, k)).astype(np.float32))
        # warmup: compile both programs
        tbl2 = kv_ops.push(tbl, idx, vals, mesh=mesh8, batch_sharded=False)
        kv_ops.pull(tbl2, idx, mesh=mesh8, batch_sharded=False)
        device_mod.mark_warmup()
        for _ in range(4):
            tbl2 = kv_ops.push(
                tbl, idx, vals, mesh=mesh8, batch_sharded=False
            )
            kv_ops.pull(tbl2, idx, mesh=mesh8, batch_sharded=False)
        snap = fresh_plane.snapshot()
        assert snap["recompiles_post_warmup"] == 0
        assert snap["functions"]["kv_push"]["compiles"] == 1
        assert snap["functions"]["kv_pull"]["compiles"] == 1

    def test_post_warmup_counts_churn(self, fresh_plane):
        w = device_mod.instrument("t_churn", jax.jit(lambda x: x + 1))
        w(jnp.ones(8))
        device_mod.mark_warmup()
        assert fresh_plane.snapshot()["recompiles_post_warmup"] == 0
        w(jnp.ones(9))
        w(jnp.ones(10))
        assert fresh_plane.snapshot()["recompiles_post_warmup"] == 2


class TestRecompileStormAlert:
    def test_storm_rule_fires_and_resolves(self, fresh_plane):
        """Satellite: the shipped device_recompile_storm rule
        (configs/alerts/default.json) driven by a real shape-churning
        jit against the live registry: inactive → pending → firing
        while shapes churn, resolved once they steady."""
        from parameter_server_tpu.telemetry.alerts import (
            AlertManager,
            default_rules,
        )

        rule = next(
            r for r in default_rules()
            if r.name == "device_recompile_storm"
        )
        assert rule.kind == "counter_rate"
        assert rule.metric == "ps_device_recompiles_total"
        clock = [0.0]
        mgr = AlertManager([rule], clock=lambda: clock[0])
        w = device_mod.instrument("t_storm", jax.jit(lambda x: x + 1))
        w(jnp.ones(4))  # first compile: not a recompile
        mgr.evaluate()
        assert mgr.states()["device_recompile_storm"].state_name == "inactive"
        # churn: 8 new shapes in 10s → 0.8/s > the 0.2/s threshold
        for i in range(8):
            w(jnp.ones(5 + i))
        clock[0] = 10.0
        mgr.evaluate()
        assert mgr.states()["device_recompile_storm"].state_name == "pending"
        clock[0] = 10.0 + rule.for_s + 1.0
        mgr.evaluate()
        assert mgr.states()["device_recompile_storm"].state_name == "firing"
        # steady shapes: the windowed rate decays to zero → resolved
        clock[0] += rule.window_s + 5.0
        for _ in range(4):
            w(jnp.ones(4))
        mgr.evaluate()
        assert mgr.states()["device_recompile_storm"].state_name == "resolved"

    def test_hbm_rule_parses(self):
        from parameter_server_tpu.telemetry.alerts import default_rules

        rule = next(
            r for r in default_rules() if r.name == "device_hbm_high_water"
        )
        assert rule.kind == "gauge"
        assert rule.metric == "ps_device_hbm_frac_used"


# -- merged device timeline + attribution ---------------------------------


def _host_step(flow, t0, total, run_s, name="executor.step"):
    """An executor.step event as the executor emits it (t_wall stamped
    at FINISH, total_s spanning submit→finish)."""
    return {
        "kind": "span", "name": name, "t_wall": t0 + total,
        "total_s": total, "queue_wait_s": total - run_s, "run_s": run_s,
        "materialize_s": 0.0, "flow": flow, "thread": "executor",
    }


def _dev_span(name, t0, dur, thread="device:1"):
    return {
        "kind": "span", "name": f"device.{name}", "thread": thread,
        "t_wall": t0, "dur_s": dur,
    }


class TestDeviceTimelineMerge:
    def test_device_track_events_parse_and_anchor(self, tmp_path):
        run = tmp_path / "plugins" / "profile" / "run1"
        run.mkdir(parents=True)
        trace = {
            "traceEvents": [
                {"ph": "M", "pid": 7, "tid": 0, "name": "process_name",
                 "args": {"name": "/device:TPU:0"}},
                {"ph": "M", "pid": 7, "tid": 2, "name": "thread_name",
                 "args": {"name": "XLA Ops"}},
                {"ph": "M", "pid": 7, "tid": 3, "name": "thread_name",
                 "args": {"name": "XLA Modules"}},
                {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
                 "args": {"name": "host threads"}},
                # op track events (kept), module aggregate (dropped),
                # host event (dropped)
                {"ph": "X", "pid": 7, "tid": 2, "name": "fusion.3",
                 "ts": 1000.0, "dur": 500.0},
                {"ph": "X", "pid": 7, "tid": 2, "name": "copy.1",
                 "ts": 1600.0, "dur": 100.0},
                {"ph": "X", "pid": 7, "tid": 3, "name": "jit_step",
                 "ts": 1000.0, "dur": 700.0},
                {"ph": "X", "pid": 1, "tid": 5, "name": "hostwork",
                 "ts": 0.0, "dur": 99.0},
            ]
        }
        (run / "host.trace.json").write_text(json.dumps(trace))
        from parameter_server_tpu.utils.profiling import device_track_events

        evs = device_track_events(str(tmp_path), host_anchor=100.0)
        assert [e["name"] for e in evs] == ["device.fusion.3", "device.copy.1"]
        assert all(e["thread"] == "device:7" for e in evs)
        # anchored: first op starts at the host window start; the
        # 600us relative offset and durations survive exactly
        assert evs[0]["t_wall"] == pytest.approx(100.0)
        assert evs[1]["t_wall"] == pytest.approx(100.0006)
        assert evs[0]["dur_s"] == pytest.approx(500e-6)

    def test_merge_attaches_submitting_step_flow(self):
        from parameter_server_tpu.telemetry.timeline import merge_device_track

        host = [_host_step(flow=7, t0=100.0, total=1.0, run_s=0.8)]
        dev_in = _dev_span("fusion.3", 100.5, 0.2)
        dev_out = _dev_span("fusion.9", 200.0, 0.1)
        merged = merge_device_track(host, [dev_in, dev_out])
        by_name = {e["name"]: e for e in merged}
        assert by_name["device.fusion.3"]["flow"] == 7
        assert "flow" not in by_name["device.fusion.9"]
        # inputs were not mutated
        assert "flow" not in dev_in

    def test_chrome_export_renders_device_track_with_arrows(self, tmp_path):
        from parameter_server_tpu.telemetry import timeline as tl

        events = [
            _host_step(flow=7, t0=100.0, total=1.0, run_s=0.8),
            _dev_span("fusion.3", 100.5, 0.2),
        ]
        jsonl = tmp_path / "t.jsonl"
        with open(jsonl, "w") as f:
            for ev in events:
                f.write(json.dumps(ev) + "\n")
        out = tmp_path / "t.json"
        trace = tl.export_chrome_trace(str(jsonl), str(out))
        evs = trace["traceEvents"]
        threads = {
            (e.get("args") or {}).get("name")
            for e in evs if e.get("name") == "thread_name"
        }
        assert "device:1" in threads
        arrows = [e for e in evs if e.get("ph") in ("s", "f")]
        assert any(a.get("id") == 7 for a in arrows)
        assert os.path.exists(out)


class TestDeviceAttribution:
    def _summarize(self, events):
        from parameter_server_tpu.telemetry.attribution import summarize

        return summarize(events)

    def test_kernel_dominated_track(self):
        host = [_host_step(flow=1, t0=0.0, total=1.0, run_s=0.9)]
        dev = [
            _dev_span("matmul.1", 0.10, 0.50),
            _dev_span("matmul.1", 0.62, 0.30),
            _dev_span("copy.2", 0.93, 0.05),
        ]
        out = self._summarize(host + dev)
        db = out["device_compute_breakdown"]
        assert db["busy_frac"] > 0.9
        assert db["gap_s"] < 0.1
        kernels = {k["name"]: k for k in db["kernels"]}
        assert kernels["matmul.1"]["share"] > 0.9
        assert kernels["matmul.1"]["calls"] == 2

    def test_gap_dominated_track(self):
        host = [_host_step(flow=1, t0=0.0, total=1.0, run_s=0.9)]
        dev = [
            _dev_span("matmul.1", 0.0, 0.02),
            _dev_span("matmul.1", 0.98, 0.02),
        ]
        db = self._summarize(host + dev)["device_compute_breakdown"]
        assert db["busy_frac"] < 0.1
        assert db["gap_s"] > 0.9
        # the resource view is untouched: device events are not
        # double-billed into device_compute busy time
        assert self._summarize(host + dev)["busy_s"].get(
            "device_compute", 0.0
        ) == pytest.approx(0.9)

    def test_nested_device_spans_credit_self_time(self):
        dev = [
            _dev_span("while.body", 0.0, 1.0),
            _dev_span("mul.1", 0.1, 0.8),
        ]
        from parameter_server_tpu.telemetry.attribution import (
            device_breakdown,
        )

        db = device_breakdown(dev)
        kernels = {k["name"]: k for k in db["kernels"]}
        assert kernels["mul.1"]["ms"] == pytest.approx(800.0)
        # the wrapper is credited only what its body leaves
        assert kernels["while.body"]["ms"] == pytest.approx(200.0)
        # and union coverage counts the interval once
        assert db["gap_s"] == pytest.approx(0.0)

    def test_no_device_trace_record_unchanged(self):
        host = [_host_step(flow=1, t0=0.0, total=1.0, run_s=0.9)]
        out = self._summarize(host)
        assert "device_compute_breakdown" not in out

    def test_scrape_shows_device_families_node_labeled_and_storm_rule(
        self, fresh_plane, mesh8
    ):
        """ACCEPTANCE: one live /metrics scrape shows the
        ``ps_device_*`` families node-labeled through the PR 10
        aggregator, and the recompile-storm rule is evaluating (its
        ``ps_alert_state`` series exists on the same scrape)."""
        import time
        import urllib.request

        from parameter_server_tpu.telemetry.exposition import (
            close_cluster,
            expose_cluster,
        )

        po = Postoffice.instance().start(num_data=4, num_server=2)
        srv = expose_cluster(po, port=0, metrics_interval=0.05)
        try:
            w = device_mod.instrument("t_scrape", jax.jit(lambda x: x + 1))
            w(jnp.ones(4))
            w(jnp.ones(5))  # one recompile on the wire
            def storm_lines(text):
                return [
                    ln for ln in text.splitlines()
                    if ln.startswith("ps_device_recompiles_total{")
                    and 'fn="t_scrape"' in ln
                ]

            def rule_live(text):
                return any(
                    ln.startswith("ps_alert_state{")
                    and 'rule="device_recompile_storm"' in ln
                    for ln in text.splitlines()
                )

            deadline = time.time() + 10
            txt = ""
            while time.time() < deadline:
                time.sleep(0.1)
                txt = urllib.request.urlopen(
                    f"{srv.url}/metrics", timeout=10
                ).read().decode()
                if storm_lines(txt) and rule_live(txt):
                    break
            lines = storm_lines(txt)
            assert lines, "ps_device_recompiles_total never reached /metrics"
            assert any('node="' in ln for ln in lines)  # node-labeled
            assert any(ln.rstrip().endswith(" 1") for ln in lines)
            assert rule_live(txt), "recompile-storm rule not evaluating live"
        finally:
            close_cluster(srv)
            Postoffice.reset()

    def test_flash_crosscheck_reconciles_hand_model(self):
        """The flash half of the record's roofline cross-check: XLA's
        counted FLOPs must be within 2x of the hand 4·bh·s²·d
        convention (it was 0.96x on this container) — and a CPU host
        must report no MFU rather than a faked one."""
        from parameter_server_tpu.benchmarks.components import (
            flash_cost_crosscheck,
        )

        out = flash_cost_crosscheck(smoke=True)
        assert out["hand_flops"] > 0
        assert 0.5 < out["hand_over_xla_ratio"] < 2.0
        assert out["mfu_hand"] is None
