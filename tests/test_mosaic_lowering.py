"""Real-Mosaic lowering regression tests (no chip needed).

Round 2 shipped flash kernels validated only in Pallas interpret mode; on
first contact with the chip they failed Mosaic's (8, 128) block-tiling
check — exactly the class of bug the interpreter cannot catch.
``jax.export`` with ``platforms=['tpu']`` runs the full Pallas->Mosaic
lowering pipeline on the CPU host, so every kernel variant is lowered for
TPU in CI. This does not execute anything on a TPU (backend compile/run
is covered by script/onchip.py); it pins the lowering contract.
"""

import jax
import jax.numpy as jnp
import pytest

from parameter_server_tpu.ops.flash_attention import flash_attention, flash_mha
from parameter_server_tpu.ops.ftrl import ftrl_update
from parameter_server_tpu.ops.ftrl_sparse import ftrl_sparse_update
from parameter_server_tpu.ops.quantize import quantize


def lower_tpu(fn, *args):
    # jax 0.4.x only materializes jax.export on explicit submodule
    # import (same shim as test_ops)
    import jax.export  # noqa: F401

    jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)


def _fa(**kw):
    def fn(q, k, v):
        return flash_attention(q, k, v, use_pallas=True, interpret=False, **kw)

    return fn


def _fa_grad(**kw):
    def fn(q, k, v):
        return jax.grad(
            lambda *a: _fa(**kw)(*a).astype(jnp.float32).sum(), argnums=(0, 1, 2)
        )(q, k, v)

    return fn


Z = jnp.zeros


@pytest.mark.parametrize(
    "shape,dtype,kw",
    [
        ((4, 1024, 64), jnp.float32, dict(causal=True)),
        ((4, 1024, 64), jnp.float32, dict(causal=False)),
        ((4, 1024, 64), jnp.bfloat16, dict(causal=True)),
        ((4, 1024, 64), jnp.float32, dict(causal=True, window=256)),
        ((2, 96, 40), jnp.float32, dict(causal=True)),  # sub-block, odd D
        ((1, 384, 128), jnp.float32, dict(causal=True)),  # S % block != 0
        # sub-SUBLANE decode shapes (the BENCH_ONCHIP small-shape
        # block-spec crash class): a speculative gamma+1 verify chunk
        # and a single-row serving query — block specs must stay
        # (8, 128)-tileable even when S < 8
        ((4, 5, 64), jnp.float32, dict(causal=True)),  # spec verify chunk
        ((4, 1, 64), jnp.float32, dict(causal=False)),  # 1-row query

        # the 512x512 default blocking with a wide head dim: the largest
        # VMEM tile shape the model paths can request
        ((2, 1024, 128), jnp.bfloat16, dict(causal=True)),
    ],
    ids=["causal", "full", "bf16", "window", "small", "s384",
         "spec_chunk", "one_row", "d128"],
)
def test_flash_fwd_and_bwd_lower(shape, dtype, kw):
    q = Z(shape, dtype)
    lower_tpu(_fa(**kw), q, q, q)
    lower_tpu(_fa_grad(**kw), q, q, q)


def test_flash_short_query_long_keys_lowers():
    """The serving decode shape: a sub-sublane query block against a
    long key axis (speculative verify reads the whole cache with a
    gamma+1-row chunk). Fwd and bwd must lower with sq < 8 < sk."""
    q = Z((4, 5, 64), jnp.float32)
    k = Z((4, 1024, 64), jnp.float32)

    def fn(q, k, v):
        return flash_attention(
            q, k, v, causal=True, q_offset=1019, use_pallas=True,
            interpret=False, with_lse=True,
        )

    lower_tpu(fn, q, k, k)

    def g(q, k, v):
        return jax.grad(
            lambda *a: fn(*a)[0].astype(jnp.float32).sum(), argnums=(0, 1, 2)
        )(q, k, v)

    lower_tpu(g, q, k, k)


def test_flash_traced_offsets_lower():
    q = Z((4, 512, 64), jnp.float32)

    def fn(q, k, v, off):
        return flash_attention(
            q, k, v, causal=True, q_offset=off, k_offset=off,
            use_pallas=True, interpret=False, with_lse=True,
        )

    lower_tpu(fn, q, q, q, jnp.int32(512))


def test_flash_gqa_lowers():
    x = Z((2, 512, 256), jnp.float32)
    kv = Z((2, 512, 64), jnp.float32)

    def fn(a, b, c):
        return flash_mha(
            a, b, c, 8, n_kv_heads=2, causal=True,
            use_pallas=True, interpret=False,
        )

    lower_tpu(fn, x, kv, kv)


def test_ftrl_kernel_lowers():
    p = 1 << 14

    def fn(z, n, g, t):
        return ftrl_update(
            z, n, g, t, alpha=0.1, beta=1.0, l1=1.0, l2=0.1, force_pallas=True
        )

    lower_tpu(fn, Z(p), Z(p), Z(p), Z(p, jnp.bool_))


def test_ftrl_bf16_kernel_lowers():
    """The bf16-sqrt_n variant (on-core PRNG stochastic narrow) must
    lower under real Mosaic rules — bitcasts, prng_seed/random_bits,
    and the bf16 VMEM output ref."""
    p = 1 << 14

    def fn(z, n, g, t, seed):
        return ftrl_update(
            z, n, g, t, alpha=0.1, beta=1.0, l1=1.0, l2=0.1,
            seed=seed, force_pallas=True,
        )

    lower_tpu(
        fn, Z(p), Z(p, jnp.bfloat16), Z(p), Z(p, jnp.bool_),
        jnp.uint32(3),
    )


def test_ftrl_sparse_kernel_lowers():
    """The fused sparse gather→update→scatter kernel: scalar-prefetched
    row ids, manual double-buffered row DMAs from/to ANY-space refs,
    aliased in-place outputs — all must survive real Mosaic rules."""
    p, u = 1 << 14, 1024

    def fn(z, n, rel, ok, g):
        return ftrl_sparse_update(
            z, n, rel, ok, g, alpha=0.1, beta=1.0, l1=1.0, l2=0.1,
            force_pallas=True,
        )

    lower_tpu(fn, Z(p), Z(p), Z(u, jnp.int32), Z(u, jnp.bool_), Z(u))


def test_ftrl_sparse_bf16_kernel_lowers():
    """bf16-sqrt_n sparse variant: on-core PRNG stochastic narrow +
    bf16 row DMAs (256 B) next to the f32 z rows."""
    p, u = 1 << 14, 1024

    def fn(z, n, rel, ok, g, seed):
        return ftrl_sparse_update(
            z, n, rel, ok, g, alpha=0.1, beta=1.0, l1=1.0, l2=0.1,
            seed=seed, force_pallas=True,
        )

    lower_tpu(
        fn, Z(p), Z(p, jnp.bfloat16), Z(u, jnp.int32), Z(u, jnp.bool_),
        Z(u), jnp.uint32(3),
    )


def test_ftrl_sparse_donated_step_lowers():
    """The production form: an enclosing donated jit around the aliased
    kernel (what the fused train step compiles to)."""
    p, u = 1 << 14, 1024

    def fn(z, n, rel, ok, g):
        return ftrl_sparse_update(
            z, n, rel, ok, g, alpha=0.1, beta=1.0, l1=1.0, l2=0.1,
            force_pallas=True,
        )

    import jax.export  # noqa: F401

    jax.export.export(jax.jit(fn, donate_argnums=(0, 1)), platforms=["tpu"])(
        Z(p), Z(p), Z(u, jnp.int32), Z(u, jnp.bool_), Z(u)
    )


def test_quantize_kernel_lowers():
    def fn(x, seed):
        return quantize(x, seed, num_bytes=1, force_pallas=True)

    lower_tpu(fn, Z((512, 256), jnp.float32), jnp.uint32(7))


def test_prefill_flash_attention_lowers():
    # the generate path's prefill uses the flash kernel on TPU backends,
    # folded/broadcast from GQA-narrow K/V — lower that exact plumbing
    from parameter_server_tpu.models.transformer import _prefill_attention

    q = Z((2, 256, 4, 64), jnp.float32)
    kv = Z((2, 256, 2, 64), jnp.float32)

    def fn(q, k, v):
        return _prefill_attention(
            q, k, v, None, use_flash=True, interpret=False
        )

    lower_tpu(fn, q, kv, kv)
