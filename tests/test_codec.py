"""Wire codec (utils/codec.py + cpp ps_lz_*): roundtrip fuzz, malformed-
frame safety, and cross-codec interop (role of the reference's snappy
CompressTo/UncompressFrom, shared_array_inl.h)."""

import numpy as np
import pytest

from parameter_server_tpu.cpp import native
from parameter_server_tpu.utils import codec


def _payloads(rng):
    yield b""
    yield b"x"
    yield b"abcd" * 3  # 12 bytes: below the n>12 match threshold
    yield b"\x00" * 100000  # RLE (offset-1 overlap copies)
    yield bytes(rng.integers(0, 256, 1 << 16, dtype=np.uint8))  # noise
    yield (b"the quick brown fox " * 4000)  # highly repetitive
    g = rng.normal(size=1 << 16).astype(np.float32)
    g[rng.random(g.size) < 0.9] = 0.0
    yield g.tobytes()  # sparse float gradients
    yield np.arange(1 << 14, dtype=np.int64).tobytes()  # sorted keys
    # periodic patterns around the 8-byte overlap-copy boundary
    for period in (1, 2, 3, 5, 7, 8, 9, 15, 16, 17):
        yield bytes(range(period)) * (3000 // period)


class TestRoundtrip:
    def test_representative_payloads(self):
        rng = np.random.default_rng(0)
        for data in _payloads(rng):
            frame = codec.compress(data)
            assert codec.decompress(frame) == data

    def test_random_mutation_fuzz(self):
        """500 random payloads roundtrip; mutated FRAMES must either
        decode to something or raise ValueError — never crash, hang, or
        over-allocate (malformed input is distinguished from
        small-output, so garbage can't trigger buffer growth)."""
        rng = np.random.default_rng(1)
        for _ in range(500):
            n = int(rng.integers(0, 5000))
            if rng.random() < 0.5:
                data = bytes(rng.integers(0, 256, n, dtype=np.uint8))
            else:  # compressible: few symbols + runs
                data = bytes(
                    rng.choice([0, 1, 65], p=[0.7, 0.2, 0.1], size=n)
                    .astype(np.uint8)
                )
            frame = codec.compress(data)
            assert codec.decompress(frame) == data
            # mutate the frame
            fb = bytearray(frame)
            for _ in range(int(rng.integers(1, 4))):
                op = rng.integers(0, 3)
                if op == 0 and len(fb) > 1:
                    fb[int(rng.integers(0, len(fb)))] = int(
                        rng.integers(0, 256)
                    )
                elif op == 1 and len(fb) > 2:
                    del fb[int(rng.integers(1, len(fb))):]
                else:
                    fb.insert(
                        int(rng.integers(0, len(fb) + 1)),
                        int(rng.integers(0, 256)),
                    )
            try:
                codec.decompress(bytes(fb), max_size=1 << 24)
            except ValueError:
                pass  # rejection is the expected failure mode

    def test_zlib_fallback_interop(self, monkeypatch):
        """A zlib frame (native-less sender) decodes on a native host,
        and RAW frames decode everywhere."""
        import zlib

        data = b"payload " * 1000
        zframe = bytes([2]) + zlib.compress(data, 1)
        assert codec.decompress(zframe) == data
        assert codec.decompress(bytes([0]) + data) == data

    def test_malformed_rejections(self):
        with pytest.raises(ValueError):
            codec.decompress(b"")
        with pytest.raises(ValueError):
            codec.decompress(bytes([9]) + b"zz")  # unknown tag
        with pytest.raises(ValueError):
            codec.decompress(bytes([2]) + b"notzlib")
        if native() is not None:
            # truncated LZ: token promises literals that aren't there
            with pytest.raises(ValueError):
                codec.decompress(bytes([1, 0xF0, 255, 255]))


@pytest.mark.skipif(native() is None, reason="native lib unavailable")
class TestNativeEdges:
    def test_incompressible_stays_raw(self):
        rng = np.random.default_rng(2)
        data = bytes(rng.integers(0, 256, 4096, dtype=np.uint8))
        frame = codec.compress(data)
        assert frame[0] == 0 and len(frame) == len(data) + 1

    def test_compression_wins_on_sparse_values(self):
        g = np.zeros(1 << 16, np.float32)
        g[::97] = 1.5
        frame = codec.compress(g.tobytes())
        assert frame[0] == 1
        assert len(frame) < g.nbytes // 10


def _require_or_skip_native():
    from conftest import require_native

    return require_native()


class TestHostileBuffers:
    """The staging-leg codec's untrusted-input contract, exercised
    through BOTH the native LZ path and the zlib fallback: empty
    frames, incompressible noise, and length-extension headers claiming
    multi-GB output (the >4GB-frame-header edge) must round-trip or
    reject cleanly — never crash, hang, or allocate the claimed size."""

    HOSTILE = (
        b"",  # empty
        b"\x00",  # single byte
        bytes(np.random.default_rng(7).integers(0, 256, 1 << 15,
                                                dtype=np.uint8)),
        b"\xff" * 70000,  # long RLE run (length extensions on encode)
    )

    def _roundtrip_all(self):
        for data in self.HOSTILE:
            frame = codec.compress(data)
            assert codec.decompress(frame) == data
            # incompressible noise must ride raw, not expand
            assert len(frame) <= len(data) + 1 + len(data) // 255 + 16

    def test_native_path(self):
        _require_or_skip_native()
        self._roundtrip_all()

    def test_zlib_fallback_path(self, monkeypatch):
        monkeypatch.setattr(codec, "native", lambda: None)
        self._roundtrip_all()
        # and a zlib frame produced here still decodes with native back
        monkeypatch.undo()
        data = self.HOSTILE[2]
        import zlib

        assert codec.decompress(bytes([2]) + zlib.compress(data, 1)) == data

    def test_lz_giant_claim_rejected_without_allocation(self):
        """An LZ frame whose 255-run match-length extensions claim far
        more output than max_size must raise, not allocate the claim:
        the grow loop is capped at max_size (the >4GB header edge,
        scaled down — the code path is the same -2/grow/cap one)."""
        _require_or_skip_native()
        # token: 4 literals + match-len 15 (extensions follow); then
        # literals, offset=1, and a run of 255-extensions claiming ~2MB
        frame = bytes([1, (4 << 4) | 15]) + b"abcd" + bytes([1, 0]) + (
            b"\xff" * 8000
        ) + bytes([7])
        with pytest.raises(ValueError):
            codec.decompress(frame, max_size=1 << 16)

    def test_zlib_bomb_bounded_by_max_size(self, monkeypatch):
        """The zlib fallback must bound output BEFORE the bytes exist
        (decompressobj max_length, not the one-shot API): a tiny frame
        claiming 64MB of zeros stops at max_size."""
        import zlib

        bomb = bytes([2]) + zlib.compress(b"\x00" * (64 << 20), 1)
        assert len(bomb) < 1 << 20
        with pytest.raises(ValueError):
            codec.decompress(bomb, max_size=1 << 16)

    def test_expected_size_oversized_clamped(self):
        data = b"q" * 4096
        frame = codec.compress(data)
        # a wildly wrong expected_size must not pre-allocate past
        # max_size, and a CORRECT decode still comes back
        assert codec.decompress(frame, expected_size=1 << 62) == data
