"""Multi-process (multi-host) integration: the DCN story.

Counterpart of the reference's local.sh-driven ``*_ps.cc`` runs with
separate server/worker OS processes. Here N processes join via
jax.distributed (gloo collectives on CPU standing in for DCN), form one
global mesh, and run real training steps where each process feeds its own
data partition — see tests/multihost_child.py.
"""

import os
import socket
import subprocess
import sys

import pytest

# Promoted to the slow tier (PR 2, per the PR-1 ROADMAP note): the
# shard_map-shim unlock made the full 'not slow' suite overrun the
# 870s tier-1 budget on a 2-core host. Run via `pytest -m slow`.
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("nproc", [2, 4])
def test_local_sh_n_hosts(nproc):
    """script/local.sh launches N federated processes; every one trains
    the same global model and reports the psum'd example count. nproc=4
    exercises cross-host server sharding seams (2x2 data x server per
    host pair) that 2 processes cannot; processes 0/1 additionally
    exchange filter-chained control frames over the DCN transport and
    assert the compression + key-cache byte reductions."""
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env["PS_PORT"] = str(_free_port())
    env["PS_LOCAL_DEVICES"] = "2"
    # local.sh overrides JAX_PLATFORMS/XLA_FLAGS itself
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "script", "local.sh"), str(nproc),
         sys.executable, os.path.join(REPO, "tests", "multihost_child.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
        cwd=REPO,
    )
    # processes share the pipe, so two PS_OK prints can interleave on one
    # line — parse occurrences, not lines
    import re

    oks = re.findall(r"PS_OK (\d+)", proc.stdout)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    assert len(oks) == nproc, proc.stdout[-2000:]
    # all processes agree on the global example count
    assert len(set(oks)) == 1
    # the filtered control-plane exchange ran and its byte reductions
    # held (asserted in the child; the marker proves it executed)
    assert "PS_FILTER_OK" in proc.stdout, proc.stdout[-2000:]
    # the LM segment ran on every process (seq-sharded + FSDP over the
    # same multi-process mesh) and all processes agree on the
    # replicated loss to the printed precision
    lm = re.findall(r"PS_LM_OK ([0-9.]+)", proc.stdout)
    assert len(lm) == nproc, proc.stdout[-2000:]
    assert len(set(lm)) == 1, lm


def test_mpi_root_sh_4_ranks():
    """script/mpi_root.sh (the reference's mpi_root.sh/mpi_node.sh
    twins): ranks reach the SAME multihost training path through the
    mpi_node.sh env adapter — with no MPI runtime installed the
    launcher emulates local ranks, and mpi_node.sh still performs the
    rank->PS_* translation (the part a real mpirun would exercise
    per-host)."""
    import re

    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env["PS_PORT"] = str(_free_port())
    env["PS_LOCAL_DEVICES"] = "2"
    # force the emulation branch even on machines WITH an MPI runtime —
    # this test pins the adapter/emulation path, not mpirun itself
    env["PS_MPIRUN"] = "/nonexistent/mpirun-for-test"
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "script", "mpi_root.sh"), "4",
         sys.executable, os.path.join(REPO, "tests", "multihost_child.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
        cwd=REPO,
    )
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    assert "emulating 4 local ranks" in proc.stderr
    oks = re.findall(r"PS_OK (\d+)", proc.stdout)
    assert len(oks) == 4 and len(set(oks)) == 1, proc.stdout[-2000:]


@pytest.mark.skipif(
    not os.environ.get("PS_MULTIHOST_8"),
    reason="8 federated jax processes on one core takes minutes; "
    "set PS_MULTIHOST_8=1 to run (verified live 2026-08-02, r5)",
)
def test_local_sh_8_hosts():
    """The launcher path at 8 ranks (r4 verdict item 8): 8 federated
    processes × 2 virtual devices = a 16-device global mesh with
    cross-host server shards 4 deep — seams that 4 ranks cannot
    reach. Same contract as test_local_sh_n_hosts."""
    import re

    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env["PS_PORT"] = str(_free_port())
    env["PS_LOCAL_DEVICES"] = "2"
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "script", "local.sh"), "8",
         sys.executable, os.path.join(REPO, "tests", "multihost_child.py")],
        env=env, capture_output=True, text=True, timeout=900, cwd=REPO,
    )
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    oks = re.findall(r"PS_OK (\d+)", proc.stdout)
    assert len(oks) == 8 and len(set(oks)) == 1, proc.stdout[-2000:]
    lm = re.findall(r"PS_LM_OK ([0-9.]+)", proc.stdout)
    assert len(lm) == 8 and len(set(lm)) == 1, lm
