"""Filter tests — mirrors src/test/fixing_float_test.cc plus roundtrip
coverage for each filter in the chain."""

import numpy as np
import pytest

from parameter_server_tpu.filter import sparse as sparse_filter
from parameter_server_tpu.filter.base import FilterChain, create
from parameter_server_tpu.filter.fixing_float import dequantize, quantize
from parameter_server_tpu.filter.frequency import FrequencyFilter
from parameter_server_tpu.system.message import FilterSpec, Message, Task
from parameter_server_tpu.utils.range import Range


def msg_with(values, key=None, channel=0):
    m = Message(task=Task(key_channel=channel, key_range=Range(0, 100)))
    m.values = values
    m.key = key
    return m


class TestFixingFloat:
    def test_quantize_error_bound(self, rng):
        # ref fixing_float_test.cc: error within one quantization step
        v = rng.normal(size=10000).astype(np.float32)
        for nbytes in (1, 2):
            q, lo, hi = quantize(v, nbytes, rng)
            back = dequantize(q, lo, hi, nbytes)
            step = (hi - lo) / ((1 << (8 * nbytes)) - 1)
            assert np.abs(back - v).max() <= step + 1e-6

    def test_stochastic_rounding_unbiased(self, rng):
        v = np.full(20000, 0.3, dtype=np.float32)
        v[0], v[1] = 0.0, 1.0  # pin the range
        q, lo, hi = quantize(v, 1, rng)
        back = dequantize(q, lo, hi, 1)
        assert abs(back[2:].mean() - 0.3) < 1e-3

    def test_chain_roundtrip(self, rng):
        chain = FilterChain()
        spec = FilterSpec(type="fixing_float", num_bytes=2)
        v = rng.normal(size=100).astype(np.float32)
        m = msg_with([v.copy()])
        m.task.filters = [spec]
        enc = chain.encode(m)
        assert enc.values[0].dtype == np.uint16
        dec = chain.decode(enc)
        assert np.abs(dec.values[0] - v).max() < 1e-3


class TestKeyCaching:
    def test_second_send_drops_keys(self):
        chain_s, chain_r = FilterChain(), FilterChain()
        keys = np.arange(50, dtype=np.int64)
        for i in range(2):
            spec = FilterSpec(type="key_caching")
            m = msg_with([np.ones(50, np.float32)], key=keys.copy())
            m.task.filters = [spec]
            enc = chain_s.encode(m)
            if i == 0:
                assert enc.key is not None
            else:
                assert enc.key is None  # cache hit: keys omitted
            dec = chain_r.decode(enc)
            np.testing.assert_array_equal(dec.key, keys)

    def test_miss_raises(self):
        chain_r = FilterChain()
        spec = FilterSpec(type="key_caching")
        spec.extra["signature"] = 12345
        m = msg_with([np.ones(3, np.float32)])
        m.task.filters = [spec]
        with pytest.raises(KeyError):
            chain_r.decode(m)


class TestCompressing:
    def test_roundtrip(self, rng):
        chain = FilterChain()
        spec = FilterSpec(type="compressing")
        v = (rng.random(1000) < 0.05).astype(np.float32)  # compressible
        m = msg_with([v.copy()])
        m.task.filters = [spec]
        enc = chain.encode(m)
        assert enc.values[0].nbytes < v.nbytes  # actually smaller
        dec = chain.decode(enc)
        np.testing.assert_array_equal(dec.values[0], v)


class TestSparse:
    def test_zeros_dropped_nans_survive(self):
        chain = FilterChain()
        spec = FilterSpec(type="sparse")
        v = np.array([0, 1.5, 0, 0, 2.5, 0], dtype=np.float32)
        sparse_filter.mark(v, 2)  # kkt-style mark
        m = msg_with([v.copy()])
        m.task.filters = [spec]
        enc = chain.encode(m)
        assert len(enc.values[0]) == 3  # 1.5, nan, 2.5
        dec = chain.decode(enc)
        assert sparse_filter.marked(dec.values[0])[2]
        np.testing.assert_array_equal(np.nan_to_num(dec.values[0]), np.nan_to_num(v))


class TestAddNoise:
    def test_noise_added(self, rng):
        chain = FilterChain()
        spec = FilterSpec(type="add_noise", std=0.1)
        v = np.zeros(1000, dtype=np.float32)
        m = msg_with([v.copy()])
        m.task.filters = [spec]
        enc = chain.encode(m)
        assert 0.05 < enc.values[0].std() < 0.2


class TestFrequency:
    def test_tail_keys_dropped(self, rng):
        f = FrequencyFilter(1 << 16, 2)
        hot = rng.integers(0, 1 << 40, 100).astype(np.uint64)
        cold = rng.integers(1 << 41, 1 << 42, 100).astype(np.uint64)
        f.insert_keys(hot, 10)
        f.insert_keys(cold, 1)
        kept = f.query_keys(np.concatenate([hot, cold]), 5)
        assert set(hot.tolist()) <= set(kept.tolist())
        assert len(kept) < 150  # most cold keys dropped

    def test_freq_zero_keeps_all(self):
        f = FrequencyFilter()
        keys = np.arange(10, dtype=np.uint64)
        np.testing.assert_array_equal(f.query_keys(keys, 0), keys)


class TestFullWireChain:
    """Full upload-wire chain round trips (learner/wire.wire_filter_specs):
    key_caching + fixing_float + compressing together, decode in reverse,
    with the stateful per-peer caches exercised across repeats."""

    def _roundtrip(self, specs_fn, keys, vals, sender, receiver):
        """Returns (key_crossed_wire, decoded message). The chain
        mutates the Message in place (decode RESTORES msg.key), so
        whether keys crossed must be sampled between encode and
        decode."""
        m = msg_with([v.copy() for v in vals],
                     key=None if keys is None else keys.copy())
        m.task.filters = specs_fn()
        enc = sender.encode(m)
        key_crossed = enc.key is not None
        return key_crossed, receiver.decode(enc)

    def test_reference_order_quantizes_then_compresses(self, rng):
        # the WORKING order: fixing_float must run before the byte
        # codec, else it sees uint8 frames and quantizes nothing
        from parameter_server_tpu.learner.wire import wire_filter_specs

        sender, receiver = FilterChain(), FilterChain()
        keys = np.sort(rng.choice(1 << 30, 300, replace=False)).astype(np.int64)
        vals = [rng.normal(size=300).astype(np.float32)]
        crossed, dec = self._roundtrip(
            lambda: wire_filter_specs(num_bytes=2), keys, vals,
            sender, receiver,
        )
        assert crossed  # first send carries keys
        np.testing.assert_array_equal(dec.key, keys)
        step = (vals[0].max() - vals[0].min()) / 65535
        assert np.abs(dec.values[0] - vals[0]).max() <= step + 1e-6
        # repeat: the stateful per-peer key cache drops the keys from
        # the wire; the receiver's cache restores them on decode
        crossed2, dec2 = self._roundtrip(
            lambda: wire_filter_specs(num_bytes=2), keys, vals,
            sender, receiver,
        )
        assert not crossed2
        np.testing.assert_array_equal(dec2.key, keys)

    def test_swapped_order_still_roundtrips(self, rng):
        # chain mechanics are order-agnostic (decode reverses encode):
        # compressing → key_caching → fixing_float also round-trips —
        # fixing_float just sees byte frames and passes them through
        def specs():
            return [
                FilterSpec(type="compressing"),
                FilterSpec(type="key_caching"),
                FilterSpec(type="fixing_float", num_bytes=1),
            ]

        sender, receiver = FilterChain(), FilterChain()
        keys = np.arange(64, dtype=np.int64)
        vals = [np.zeros(512, np.float32)]
        vals[0][::7] = 1.0
        crossed, dec = self._roundtrip(specs, keys, vals, sender, receiver)
        assert crossed
        np.testing.assert_array_equal(dec.key, keys)
        # lossless: the quantizer never touched the compressed bytes
        np.testing.assert_array_equal(dec.values[0], vals[0])

    def test_per_peer_caches_are_independent(self, rng):
        # ref RemoteNode: one stateful chain PER PEER — a second
        # receiver that never saw the keys must miss, not inherit the
        # first receiver's cache
        from parameter_server_tpu.learner.wire import wire_filter_specs

        sender = FilterChain()
        recv_a, recv_b = FilterChain(), FilterChain()
        keys = np.arange(128, dtype=np.int64)
        vals = [np.ones(128, np.float32)]
        _, _ = self._roundtrip(
            wire_filter_specs, keys, vals, sender, recv_a
        )
        crossed2, dec_a = self._roundtrip(
            wire_filter_specs, keys, vals, sender, recv_a
        )
        assert not crossed2
        np.testing.assert_array_equal(dec_a.key, keys)  # peer A: hit
        # peer B never cached: replay the keyless wire form to it
        m = msg_with([vals[0].copy()], key=keys.copy())
        m.task.filters = wire_filter_specs()
        wire_form = sender.encode(m)
        assert wire_form.key is None  # sender cache still hot
        with pytest.raises(KeyError):
            recv_b.decode(wire_form)  # loud miss, not silent garbage

    def test_mixed_dtype_values_pass_through(self, rng):
        from parameter_server_tpu.learner.wire import wire_filter_specs

        sender, receiver = FilterChain(), FilterChain()
        ints = np.arange(100, dtype=np.int32)
        floats = rng.normal(size=100).astype(np.float32)
        _, dec = self._roundtrip(
            lambda: wire_filter_specs(num_bytes=1), None,
            [ints, floats], sender, receiver,
        )
        np.testing.assert_array_equal(dec.values[0], ints)  # untouched
        step = (floats.max() - floats.min()) / 255
        assert np.abs(dec.values[1] - floats).max() <= step + 1e-6


class TestChainOrder:
    def test_stacked_filters_reverse_decode(self, rng):
        chain = FilterChain()
        specs = [
            FilterSpec(type="sparse"),
            FilterSpec(type="compressing"),
        ]
        v = np.zeros(500, dtype=np.float32)
        v[::50] = rng.normal(size=10)
        m = msg_with([v.copy()])
        m.task.filters = specs
        dec = chain.decode(chain.encode(m))
        np.testing.assert_allclose(dec.values[0], v)

    def test_unknown_filter_raises(self):
        with pytest.raises(ValueError):
            create("nope")
