"""Ragged-batch + stop-token serving: right-padded variable-length
prompts decode in ONE batch, each row exactly equal to a single-row
call on its unpadded prompt — across rope, GQA, int8 cache, and
sliding-window configs, under tensor parallelism, for speculative
decoding, and composed with eos_id (plain and speculative)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_tpu.models.transformer import (
    LMConfig,
    init_lm,
    lm_generate,
    shard_lm_params,
)

# Promoted to the slow tier (PR 2, per the PR-1 ROADMAP note): the
# shard_map-shim unlock made the full 'not slow' suite overrun the
# 870s tier-1 budget on a 2-core host. Run via `pytest -m slow`.
pytestmark = pytest.mark.slow

BASE = LMConfig(vocab=61, d_model=32, n_heads=4, n_layers=2, d_ff=64)


def _ragged_prompts(rng, widths, pad_to):
    rows = [rng.integers(1, 61, w).astype(np.int32) for w in widths]
    padded = np.zeros((len(rows), pad_to), np.int32)
    for i, r in enumerate(rows):
        padded[i, : r.size] = r
    return rows, padded, np.asarray(widths, np.int32)


@pytest.mark.parametrize(
    "cfg",
    [
        BASE,
        dataclasses.replace(BASE, rope=True),
        dataclasses.replace(BASE, n_kv_heads=2),
        dataclasses.replace(
            BASE, n_kv_heads=2, kv_cache_dtype="int8", rope=True
        ),
        dataclasses.replace(BASE, window=8),
    ],
    ids=["base", "rope", "gqa", "gqa_int8_rope", "window"],
)
def test_ragged_rows_equal_single_row_calls(cfg):
    rng = np.random.default_rng(0)
    steps = 7
    rows, padded, lengths = _ragged_prompts(rng, [5, 12, 9], pad_to=12)
    params = init_lm(jax.random.PRNGKey(1), cfg)
    out = np.asarray(
        lm_generate(
            params, jnp.asarray(padded), cfg, steps=steps,
            prompt_lengths=lengths,
        )
    )
    for i, r in enumerate(rows):
        solo = np.asarray(
            lm_generate(params, jnp.asarray(r[None, :]), cfg, steps=steps)
        )[0]
        got = out[i, : r.size + steps]
        np.testing.assert_array_equal(got, solo, err_msg=f"row {i}")
        # positions past the row's content are zeroed
        assert (out[i, r.size + steps:] == 0).all()


def test_uniform_lengths_match_dense_path():
    """prompt_lengths all equal to the padded width must reproduce the
    dense path bit for bit."""
    rng = np.random.default_rng(2)
    prompt = jnp.asarray(rng.integers(1, 61, (3, 10)), np.int32)
    params = init_lm(jax.random.PRNGKey(3), BASE)
    dense = np.asarray(lm_generate(params, prompt, BASE, steps=6))
    ragged = np.asarray(
        lm_generate(
            params, prompt, BASE, steps=6,
            prompt_lengths=np.full(3, 10, np.int32),
        )
    )
    np.testing.assert_array_equal(dense, ragged)


def test_ragged_sampling_runs_and_respects_lengths():
    rng = np.random.default_rng(4)
    rows, padded, lengths = _ragged_prompts(rng, [3, 8], pad_to=8)
    params = init_lm(jax.random.PRNGKey(5), BASE)
    out = np.asarray(
        lm_generate(
            params, jnp.asarray(padded), BASE, steps=5,
            prompt_lengths=lengths, temperature=0.8, top_k=10,
            key=jax.random.PRNGKey(6),
        )
    )
    assert out.shape == (2, 13)
    # generated region is fully populated (vocab excludes 0 in prompts;
    # sampled tokens may be 0, so only check prompt echo + shape)
    np.testing.assert_array_equal(out[0, :3], rows[0])
    np.testing.assert_array_equal(out[1, :8], rows[1])


def test_ragged_under_tensor_parallelism(mesh8):
    """The multi-chip serving composition: ragged decode with
    Megatron-placed weights equals the replicated ragged run."""
    rng = np.random.default_rng(7)
    rows, padded, lengths = _ragged_prompts(rng, [4, 11, 7], pad_to=11)
    params = init_lm(jax.random.PRNGKey(8), BASE)
    rep = np.asarray(
        lm_generate(
            params, jnp.asarray(padded), BASE, steps=6,
            prompt_lengths=lengths,
        )
    )
    tp = np.asarray(
        lm_generate(
            shard_lm_params(params, mesh8), jnp.asarray(padded), BASE,
            steps=6, prompt_lengths=lengths,
        )
    )
    np.testing.assert_array_equal(rep, tp)


class TestEos:
    """eos_id freeze semantics: 'eos then pads' in both modes."""

    def _params_cfg(self):
        return init_lm(jax.random.PRNGKey(1), BASE), BASE

    def test_dense_rows_freeze_after_eos(self):
        params, cfg = self._params_cfg()
        rng = np.random.default_rng(9)
        prompt = jnp.asarray(rng.integers(1, 61, (3, 6)), np.int32)
        base = np.asarray(lm_generate(params, prompt, cfg, steps=12))
        gen = base[:, 6:]
        # choose an eos that actually occurs mid-stream in some row
        cands = [
            (r, t) for r in range(3) for t in range(8)
            if gen[r, t] != 0 and (gen[r, :t] != gen[r, t]).all()
        ]
        assert cands, gen
        row, t_hit = max(cands, key=lambda c: c[1])
        eos = int(gen[row, t_hit])
        out = np.asarray(
            lm_generate(params, prompt, cfg, steps=12, eos_id=eos)
        )[:, 6:]
        for r in range(3):
            hits = np.flatnonzero(out[r] == eos)
            if hits.size:
                h = hits[0]
                # greedy prefix up to and including eos matches plain
                np.testing.assert_array_equal(out[r, : h + 1],
                                              gen[r, : h + 1])
                assert (out[r, h + 1:] == 0).all(), out[r]
            else:
                np.testing.assert_array_equal(out[r], gen[r])

    def test_ragged_eos(self):
        params, cfg = self._params_cfg()
        rng = np.random.default_rng(10)
        rows, padded, lengths = _ragged_prompts(rng, [4, 9], pad_to=9)
        base = np.asarray(
            lm_generate(
                params, jnp.asarray(padded), cfg, steps=10,
                prompt_lengths=lengths,
            )
        )
        # pick an eos appearing in row 0's continuation
        cont0 = base[0, 4:14]
        # any position whose token has no earlier occurrence works as
        # the eos probe; t=0 always qualifies (degenerate random-weight
        # models can emit one repeated token — h=0 still checks the
        # freeze)
        nz = [t for t in range(0, 8) if cont0[t] != 0
              and (cont0[:t] != cont0[t]).all()]
        assert nz, cont0
        eos = int(cont0[nz[-1]])
        out = np.asarray(
            lm_generate(
                params, jnp.asarray(padded), cfg, steps=10,
                prompt_lengths=lengths, eos_id=eos,
            )
        )
        h = np.flatnonzero(out[0, 4:14] == eos)[0]
        np.testing.assert_array_equal(out[0, 4:4 + h + 1],
                                      cont0[: h + 1])
        assert (out[0, 4 + h + 1: 14] == 0).all()

    def test_eos_id_validated(self):
        params, cfg = self._params_cfg()
        with pytest.raises(ValueError, match="eos_id"):
            lm_generate(
                params, jnp.zeros((1, 4), jnp.int32), cfg, steps=2,
                eos_id=61,
            )
        # frozen rows cache pad tokens: GenState/logits contracts break,
        # so the compositions are rejected rather than silently wrong
        with pytest.raises(ValueError, match="does not compose"):
            lm_generate(
                params, jnp.zeros((1, 4), jnp.int32), cfg, steps=2,
                eos_id=3, return_state=True,
            )
        with pytest.raises(ValueError, match="does not compose"):
            lm_generate(
                params, jnp.zeros((1, 4), jnp.int32), cfg, steps=2,
                eos_id=3, return_logits=True,
            )


class TestRaggedSpeculative:
    """spec decode x ragged batches: the exactness contract holds per
    row against plain greedy decode of the unpadded prompt."""

    def test_ragged_spec_equals_plain_greedy(self):
        from parameter_server_tpu.models.speculative import (
            speculative_generate,
        )

        rng = np.random.default_rng(11)
        tcfg = dataclasses.replace(BASE, n_kv_heads=2, rope=True)
        dcfg = LMConfig(vocab=61, d_model=16, n_heads=2, n_layers=1,
                        d_ff=32)
        tparams = init_lm(jax.random.PRNGKey(12), tcfg)
        dparams = init_lm(jax.random.PRNGKey(13), dcfg)
        rows, padded, lengths = _ragged_prompts(rng, [5, 11, 8], pad_to=11)
        steps = 9
        out, st = speculative_generate(
            tparams, tcfg, dparams, dcfg, jnp.asarray(padded), steps,
            gamma=3, prompt_lengths=lengths, return_stats=True,
        )
        out = np.asarray(out)
        for i, r in enumerate(rows):
            plain = np.asarray(
                lm_generate(tparams, jnp.asarray(r[None, :]), tcfg,
                            steps=steps)
            )[0]
            np.testing.assert_array_equal(
                out[i, : r.size + steps], plain, err_msg=f"row {i}"
            )
            assert (out[i, r.size + steps:] == 0).all()
        assert int(st["rounds"]) >= 1

    def test_dense_batches_unchanged(self):
        """lengths=None must reproduce the pre-ragged dense behavior
        (exactness vs plain greedy — the existing contract)."""
        from parameter_server_tpu.models.speculative import (
            speculative_generate,
        )

        rng = np.random.default_rng(14)
        prompt = jnp.asarray(rng.integers(1, 61, (2, 7)), np.int32)
        params = init_lm(jax.random.PRNGKey(15), BASE)
        dcfg = LMConfig(vocab=61, d_model=16, n_heads=2, n_layers=1,
                        d_ff=32)
        dparams = init_lm(jax.random.PRNGKey(16), dcfg)
        plain = np.asarray(lm_generate(params, prompt, BASE, steps=6))
        spec = np.asarray(
            speculative_generate(
                params, BASE, dparams, dcfg, prompt, 6, gamma=2
            )
        )
        np.testing.assert_array_equal(plain, spec)

    def test_ragged_spec_validation(self):
        from parameter_server_tpu.models.speculative import (
            speculative_generate,
        )

        params = init_lm(jax.random.PRNGKey(0), BASE)
        with pytest.raises(ValueError, match="lie in|range"):
            speculative_generate(
                params, BASE, params, BASE,
                jnp.zeros((2, 4), jnp.int32), 2,
                prompt_lengths=np.asarray([0, 4], np.int32),
            )


def test_ragged_rejects_unsupported_composition():
    params = init_lm(jax.random.PRNGKey(0), BASE)
    prompt = jnp.zeros((2, 4), jnp.int32)
    lens = np.asarray([2, 4], np.int32)
    with pytest.raises(ValueError, match="ragged"):
        lm_generate(
            params, prompt, BASE, steps=2, prompt_lengths=lens,
            return_state=True,
        )
    with pytest.raises(ValueError, match="steps"):
        lm_generate(params, prompt, BASE, steps=0, prompt_lengths=lens)
    with pytest.raises(ValueError, match="range|lie in"):
        lm_generate(
            params, prompt, BASE, steps=2,
            prompt_lengths=np.asarray([0, 4], np.int32),
        )
    with pytest.raises(ValueError, match="range|lie in"):
        lm_generate(
            params, prompt, BASE, steps=2,
            prompt_lengths=np.asarray([2, 5], np.int32),
        )


class TestSpeculativeEos:
    """spec decode x eos: clamped chunk commits must reproduce
    lm_generate's 'eos then pads' exactly (greedy), dense and ragged."""

    def _models(self):
        tcfg = dataclasses.replace(BASE, n_kv_heads=2)
        dcfg = LMConfig(vocab=61, d_model=16, n_heads=2, n_layers=1,
                        d_ff=32)
        return (
            tcfg, init_lm(jax.random.PRNGKey(20), tcfg),
            dcfg, init_lm(jax.random.PRNGKey(21), dcfg),
        )

    def test_dense_spec_eos_equals_plain_eos(self):
        from parameter_server_tpu.models.speculative import (
            speculative_generate,
        )

        tcfg, tp, dcfg, dp = self._models()
        rng = np.random.default_rng(22)
        prompt = jnp.asarray(rng.integers(1, 61, (2, 6)), np.int32)
        plain = np.asarray(lm_generate(tp, prompt, tcfg, steps=8))
        emitted = [t for t in plain[:, 6:].ravel().tolist() if t != 0]
        if not emitted:
            pytest.skip("degenerate model emitted only pads")
        eos = int(emitted[len(emitted) // 2])
        want = np.asarray(
            lm_generate(tp, prompt, tcfg, steps=8, eos_id=eos)
        )
        got = np.asarray(
            speculative_generate(
                tp, tcfg, dp, dcfg, prompt, 8, gamma=3, eos_id=eos
            )
        )
        np.testing.assert_array_equal(got, want)

    def test_ragged_spec_eos_equals_plain_eos(self):
        from parameter_server_tpu.models.speculative import (
            speculative_generate,
        )

        tcfg, tp, dcfg, dp = self._models()
        rng = np.random.default_rng(23)
        rows, padded, lengths = _ragged_prompts(rng, [4, 9], pad_to=9)
        plain = np.asarray(
            lm_generate(
                tp, jnp.asarray(padded), tcfg, steps=7,
                prompt_lengths=lengths,
            )
        )
        emitted = [
            t
            for i in range(2)
            for t in plain[i, lengths[i]: lengths[i] + 7].tolist()
            if t != 0
        ]
        if not emitted:
            pytest.skip("degenerate model emitted only pads")
        eos = int(emitted[-1])
        want = np.asarray(
            lm_generate(
                tp, jnp.asarray(padded), tcfg, steps=7,
                prompt_lengths=lengths, eos_id=eos,
            )
        )
        got = np.asarray(
            speculative_generate(
                tp, tcfg, dp, dcfg, jnp.asarray(padded), 7, gamma=2,
                prompt_lengths=lengths, eos_id=eos,
            )
        )
        np.testing.assert_array_equal(got, want)

    def test_spec_eos_validation(self):
        from parameter_server_tpu.models.speculative import (
            speculative_generate,
        )

        tcfg, tp, dcfg, dp = self._models()
        with pytest.raises(ValueError, match="eos_id"):
            speculative_generate(
                tp, tcfg, dp, dcfg, jnp.zeros((1, 4), jnp.int32), 2,
                eos_id=61,
            )
