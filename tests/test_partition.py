"""Declarative partitioning (parallel/partition.py) + mesh auto-shaping.

Runs on the conftest-forced 8-device CPU platform (`make mesh-test`
re-runs this file standalone under the same XLA_FLAGS) — every
multi-device layout path is exercised without silicon.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from parameter_server_tpu.parallel import mesh as meshlib
from parameter_server_tpu.parallel import partition as partlib
from parameter_server_tpu.parallel.mesh import DATA_AXIS, SERVER_AXIS


@pytest.fixture(autouse=True)
def fresh_po():
    from parameter_server_tpu.system.postoffice import Postoffice

    Postoffice.reset()
    yield
    Postoffice.reset()


class TestAutoShape:
    def test_auto_shape_factors_full_device_count(self):
        """8 devices with num_server=3 must become 4x2 (largest divisor
        <= requested), never 2x3 with 2 chips idle."""
        m = meshlib.make_mesh(num_server=3)
        assert m.devices.size == 8
        assert dict(m.shape) == {DATA_AXIS: 4, SERVER_AXIS: 2}

    @pytest.mark.parametrize("num_server,want", [
        (1, (8, 1)), (2, (4, 2)), (4, (2, 4)), (8, (1, 8)),
        (5, (2, 4)), (6, (2, 4)), (7, (2, 4)), (100, (1, 8)),
    ])
    def test_auto_shape_never_idles_a_device(self, num_server, want):
        m = meshlib.make_mesh(num_server=num_server)
        assert m.devices.size == 8, (num_server, m.shape)
        assert (m.shape[DATA_AXIS], m.shape[SERVER_AXIS]) == want

    def test_auto_shape_logs_chosen_shape(self, caplog):
        with caplog.at_level(logging.INFO, logger=meshlib.__name__):
            meshlib.make_mesh(num_server=3)
        text = caplog.text
        assert "auto-shape" in text and "0 idle" in text

    def test_explicit_shape_keeps_existing_contract(self):
        # an explicit num_data is the caller's decision: undersubscribing
        # still warns-and-proceeds, oversubscribing still raises
        m = meshlib.make_mesh(num_data=3, num_server=2)
        assert m.devices.size == 6
        with pytest.raises(ValueError):
            meshlib.make_mesh(num_data=5, num_server=2)


class TestRules:
    def test_tree_path_to_string_and_named_tree_map(self):
        tree = {"a": {"b": np.zeros(2)}, "c": [np.zeros(3)]}
        names = []
        partlib.named_tree_map(
            lambda name, leaf: names.append(name) or leaf, tree
        )
        assert set(names) == {"a/b", "c/0"}

    def test_match_partition_rules_first_match_wins_and_fits_rank(self):
        tree = {
            "table": np.zeros((8, 4)),
            "z": np.zeros(8),
            "lr": np.float32(0.1),
            "batch": np.zeros((16, 3)),
        }
        specs = partlib.match_partition_rules(partlib.DEFAULT_RULES, tree)
        assert specs["table"] == P(SERVER_AXIS, None)
        assert specs["z"] == P(SERVER_AXIS)
        assert specs["lr"] == P()  # scalar: replicated regardless of rule
        assert specs["batch"] == P(DATA_AXIS, None)

    def test_no_matching_rule_raises(self):
        with pytest.raises(ValueError, match="no partition rule"):
            partlib.match_partition_rules(
                ((r"^only_this$", partlib.TABLE_SPEC),),
                {"other": np.zeros(4)},
            )

    def test_state_partition_spec_matches_the_inline_rule_it_replaced(self):
        # the exact spec async_sgd/KVMap used to build by hand
        state = {"w": np.zeros((16, 2)), "n": np.zeros(16), "step": np.int32(0)}
        specs = partlib.state_partition_spec(state)
        want = jax.tree.map(
            lambda leaf: P(SERVER_AXIS) if np.ndim(leaf) >= 1 else P(),
            state,
        )
        flat_got = jax.tree.leaves(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
        flat_want = jax.tree.leaves(
            want, is_leaf=lambda x: isinstance(x, P)
        )
        for g, w in zip(flat_got, flat_want):
            # fitted specs may carry explicit trailing None — same layout
            assert tuple(g)[: len(tuple(w))] == tuple(w) or g == w

    def test_fit_spec(self):
        assert partlib.fit_spec(partlib.TABLE_SPEC, 0) == P()
        assert partlib.fit_spec(partlib.TABLE_SPEC, 1) == P(SERVER_AXIS)
        assert partlib.fit_spec(P(SERVER_AXIS), 3) == P(SERVER_AXIS, None, None)


class TestMeshPartitioner:
    def test_for_mesh_caches_one_partitioner_per_mesh(self, mesh8):
        assert partlib.for_mesh(mesh8) is partlib.for_mesh(mesh8)

    def test_canonical_shardings_resolve_once_and_delegate(self, mesh8):
        p = partlib.for_mesh(mesh8)
        assert p.table_sharding() is p.table_sharding()  # resolved once
        assert p.table_sharding() == NamedSharding(mesh8, P(SERVER_AXIS, None))
        # the mesh helpers now delegate to the same resolved objects
        assert meshlib.table_sharding(mesh8) is p.table_sharding()
        assert meshlib.batch_sharding(mesh8) is p.batch_sharding()
        assert meshlib.replicated(mesh8) is p.replicated()

    def test_shard_and_gather_roundtrip(self, mesh8):
        p = partlib.for_mesh(mesh8)
        tree = {"table": np.arange(32, dtype=np.float32).reshape(16, 2)}
        sharded = p.shard(tree)
        assert sharded["table"].sharding == p.table_sharding()
        back = p.gather(sharded)
        np.testing.assert_array_equal(back["table"], tree["table"])

    def test_layer_sharding_policy(self, mesh8):
        p = partlib.for_mesh(mesh8)
        # big + divisible first dim: server-sharded on that dim
        s = p.layer_sharding((16, 10), partition_thr=100)
        assert s == NamedSharding(mesh8, P(SERVER_AXIS, None))
        # big but no divisible dim: replicated
        assert p.layer_sharding((7, 5), 30) == p.replicated()
        # small: replicated
        assert p.layer_sharding((2, 2), 1000) == p.replicated()

    def test_init_sharded_lands_rows_per_shard(self, mesh8):
        """The table-over-HBM path: a [P, k] init materializes directly
        into its server-sharded layout — each server shard holds
        P / n_server rows (the sizing math in PERFORMANCE.md)."""
        p = partlib.for_mesh(mesh8)
        out = p.init_sharded(lambda: {"table": jnp.ones((16, 4))})
        arr = out["table"]
        assert arr.sharding == p.table_sharding()
        n_server = mesh8.shape[SERVER_AXIS]
        for shard in arr.addressable_shards:
            assert shard.data.shape == (16 // n_server, 4)


class TestShardedTableParity:
    def test_multi_shard_training_bit_identical_to_single_shard(self):
        """A table spanning >1 server shard trains bit-identically to
        the single-shard path: same device count on the data axis (psum
        order fixed), only the server sharding differs — each shard
        contributes its owned rows plus exact zeros."""
        from parameter_server_tpu.parameter.kv_vector import KVVector
        from parameter_server_tpu.system.postoffice import Postoffice

        devs = jax.devices()[:4]
        rng = np.random.default_rng(7)
        batches = [
            (
                np.sort(rng.choice(997, size=48, replace=False)).astype(
                    np.int64
                ),
                rng.normal(size=(48, 2)).astype(np.float32),
            )
            for _ in range(5)
        ]

        # 4x1 (single server shard) vs 4x2 (table spans 2 shards):
        # num_data identical, so the data-axis combine is identical
        Postoffice.reset()
        mesh1 = meshlib.make_mesh(num_data=4, num_server=1, devices=devs)
        kv1 = KVVector(mesh=mesh1, k=2, num_slots=128, hashed=True, name="one")
        for keys, vals in batches:
            kv1.push(kv1.request(channel=0), keys=keys, values=vals)
        kv1.executor.wait_all(pop=False)
        single = kv1.get_replica()[0]

        Postoffice.reset()
        mesh2 = meshlib.make_mesh(num_data=4, num_server=2)
        assert mesh2.devices.size == 8
        kv2 = KVVector(mesh=mesh2, k=2, num_slots=128, hashed=True, name="two")
        assert kv2.table(0).sharding.spec == P(SERVER_AXIS, None)
        for keys, vals in batches:
            kv2.push(kv2.request(channel=0), keys=keys, values=vals)
        kv2.executor.wait_all(pop=False)
        multi = kv2.get_replica()[0]

        assert single.tobytes() == multi.tobytes()


class TestSpecDelegation:
    def test_kv_ops_index_spec(self):
        from parameter_server_tpu.ops import kv_ops

        assert kv_ops.index_spec(True) == P(DATA_AXIS)
        assert kv_ops.index_spec(False) == P()
        assert kv_ops.TABLE_SPEC == P(SERVER_AXIS, None)

    def test_kv_vector_resolves_table_spec_through_partitioner(self, mesh8):
        from parameter_server_tpu.parameter.kv_vector import KVVector
        from parameter_server_tpu.system.postoffice import Postoffice

        Postoffice.reset()
        po = Postoffice.instance()
        po.start(num_data=4, num_server=2)
        kv = KVVector(k=2, num_slots=32, name="spec")
        assert kv.partitioner is partlib.for_mesh(po.mesh)
        assert kv._table_sharding is kv.partitioner.table_sharding()
        assert kv.table(0).sharding == kv._table_sharding

    def test_kv_layer_uses_partitioner_policy(self, mesh8):
        from parameter_server_tpu.parameter.kv_layer import KVLayer
        from parameter_server_tpu.system.postoffice import Postoffice

        Postoffice.reset()
        po = Postoffice.instance()
        po.start(num_data=4, num_server=2)
        layer = KVLayer(partition_thr=100, name="layers")
        big = layer.init_layer("w", (16, 10))
        assert big.sharding == NamedSharding(po.mesh, P(SERVER_AXIS, None))
        small = layer.init_layer("b", (3,))
        assert small.sharding == partlib.for_mesh(po.mesh).replicated()
