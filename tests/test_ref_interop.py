"""Reference-format interop (data/ref_interop.py): the reference's
protobuf Example recordio files (ref src/util/recordio.h framing +
src/data/proto/example.proto schema) decode into SparseBatch and
re-encode byte-compatibly.

Two independent oracles:
1. a checked-in golden file (tests/data/ref_example.recordio) generated
   ONCE with the real protobuf toolchain (protoc + google.protobuf) —
   authentic reference-format bytes, not our own encoder's output;
2. when google.protobuf is importable, randomized cross-validation:
   our encoder's bytes parse back identically through a dynamically
   compiled real protobuf module, and vice versa.
"""

import os
import shutil
import struct
import subprocess
import sys

import numpy as np
import pytest

from parameter_server_tpu.data.ref_interop import (
    REF_MAGIC,
    decode_example,
    encode_example,
    format_info_ascii,
    iter_ref_records,
    parse_info_ascii,
    read_ref_batch,
    write_ref_batch,
    write_ref_records,
)

GOLDEN = os.path.join(os.path.dirname(__file__), "data", "ref_example.recordio")


class TestGoldenFile:
    """The checked-in reference-produced file decodes exactly."""

    def test_framing(self):
        payloads = list(iter_ref_records(GOLDEN))
        assert len(payloads) == 3
        with open(GOLDEN, "rb") as f:
            assert struct.unpack("<i", f.read(4))[0] == REF_MAGIC

    def test_decode_examples(self):
        ex1, ex2, ex3 = (decode_example(p) for p in iter_ref_records(GOLDEN))
        # ex1: libsvm-style (label + slot 1 keys/vals)
        assert [s[0] for s in ex1] == [0, 1]
        np.testing.assert_array_equal(
            ex1[1][1], np.asarray([3, 17, 2**40 + 5], np.uint64)
        )
        np.testing.assert_allclose(ex1[1][2], [0.5, -2.25, 3.0])
        # ex2: criteo-style (binary slots, no vals, >63-bit key)
        assert [s[0] for s in ex2] == [0, 2, 5]
        assert ex2[1][2] is None and ex2[2][2] is None
        np.testing.assert_array_equal(
            ex2[2][1], np.asarray([2**63 + 9], np.uint64)
        )
        # ex3: label-only
        assert [s[0] for s in ex3] == [0]

    def test_read_batch(self):
        b = read_ref_batch(GOLDEN)
        np.testing.assert_array_equal(b.y, [1.0, -1.0, 1.0])
        np.testing.assert_array_equal(b.indptr, [0, 3, 6, 6])
        np.testing.assert_array_equal(
            b.indices.view(np.uint64),
            np.asarray([3, 17, 2**40 + 5, 11, 13, 2**63 + 9], np.uint64),
        )
        np.testing.assert_array_equal(b.slot_ids, [1, 1, 1, 2, 2, 5])
        # mixed: slot 1 has vals, binary slots default to 1.0
        np.testing.assert_allclose(
            b.values, [0.5, -2.25, 3.0, 1.0, 1.0, 1.0]
        )

    def test_reencode_roundtrip(self):
        """decode -> encode -> decode is identity (byte equality is NOT
        required by proto — field order is — but our encoder uses the
        canonical order, so bytes match here too)."""
        for payload in iter_ref_records(GOLDEN):
            slots = decode_example(payload)
            again = encode_example(slots)
            assert again == payload


class TestBatchRoundTrip:
    def _random_batch(self, rng, binary):
        n = 17
        counts = rng.integers(0, 6, n)
        indptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        nnz = int(indptr[-1])
        from parameter_server_tpu.utils.sparse import SparseBatch

        return SparseBatch(
            y=rng.choice([-1.0, 1.0], n).astype(np.float32),
            indptr=indptr,
            indices=rng.integers(0, 2**63, nnz).astype(np.int64),
            values=(
                None if binary
                else rng.normal(size=nnz).astype(np.float32)
            ),
            slot_ids=rng.integers(1, 5, nnz).astype(np.int32),
        )

    @pytest.mark.parametrize("binary", [True, False])
    def test_write_read(self, tmp_path, binary):
        rng = np.random.default_rng(3)
        b = self._random_batch(rng, binary)
        path = str(tmp_path / "b.recordio")
        assert write_ref_batch(path, b) == b.n
        back = read_ref_batch(path)
        np.testing.assert_array_equal(back.y, b.y)
        np.testing.assert_array_equal(back.indptr, b.indptr)
        assert (back.values is None) == binary
        # writer groups a row's entries by slot id; compare as sets per
        # row with slot attribution
        for r in range(b.n):
            lo, hi = b.indptr[r], b.indptr[r + 1]
            lo2, hi2 = back.indptr[r], back.indptr[r + 1]
            want = sorted(
                zip(b.slot_ids[lo:hi].tolist(),
                    b.indices[lo:hi].tolist(),
                    (b.values[lo:hi].tolist() if not binary
                     else [1.0] * (hi - lo)))
            )
            got = sorted(
                zip(back.slot_ids[lo2:hi2].tolist(),
                    back.indices[lo2:hi2].tolist(),
                    (back.values[lo2:hi2].tolist() if not binary
                     else [1.0] * (hi2 - lo2)))
            )
            assert got == want

    def test_max_examples(self, tmp_path):
        rng = np.random.default_rng(4)
        b = self._random_batch(rng, True)
        path = str(tmp_path / "b.recordio")
        write_ref_batch(path, b)
        head = read_ref_batch(path, max_examples=5)
        assert head.n == 5
        np.testing.assert_array_equal(head.y, b.y[:5])

    def test_bad_magic_rejected(self, tmp_path):
        path = str(tmp_path / "junk.recordio")
        with open(path, "wb") as f:
            f.write(b"\x00" * 16)
        with pytest.raises(ValueError, match="bad magic"):
            list(iter_ref_records(path))

    def test_truncated_payload_rejected(self, tmp_path):
        path = str(tmp_path / "trunc.recordio")
        write_ref_records(path, [b"\x0a\x02\x08\x00"])
        with open(path, "r+b") as f:
            f.truncate(10)  # cut into the payload
        with pytest.raises(ValueError, match="truncated"):
            list(iter_ref_records(path))


_PROTO_SRC = """
syntax = "proto2";
package PSX;
message Slot {
  optional int32 id = 1;
  repeated uint64 key = 2 [packed=true];
  repeated float val = 3 [packed=true];
}
message Example {
  repeated Slot slot = 1;
}
"""


@pytest.fixture(scope="module")
def real_pb(tmp_path_factory):
    """Compile the Example schema with the REAL protobuf toolchain."""
    if shutil.which("protoc") is None:
        pytest.skip("protoc not available")
    pytest.importorskip("google.protobuf")
    d = tmp_path_factory.mktemp("pb")
    (d / "psx.proto").write_text(_PROTO_SRC)
    subprocess.run(
        ["protoc", f"--python_out={d}", "psx.proto"],
        cwd=d, check=True, capture_output=True,
    )
    sys.path.insert(0, str(d))
    try:
        import psx_pb2  # noqa: F401

        yield psx_pb2
    finally:
        sys.path.remove(str(d))


class TestAgainstRealProtobuf:
    """Cross-validation with google.protobuf on randomized messages."""

    def test_our_bytes_parse_in_protobuf(self, real_pb):
        rng = np.random.default_rng(11)
        for _ in range(25):
            nk = int(rng.integers(0, 9))
            slot_id = int(rng.integers(0, 100))
            keys = rng.integers(0, 2**64, nk, dtype=np.uint64)
            vals = (
                rng.normal(size=nk).astype(np.float32)
                if rng.random() < 0.5 else None
            )
            ours = encode_example([(slot_id, keys, vals)])
            ex = real_pb.Example()
            ex.ParseFromString(ours)
            assert len(ex.slot) == 1
            assert ex.slot[0].id == slot_id
            np.testing.assert_array_equal(
                np.asarray(ex.slot[0].key, np.uint64), keys
            )
            if vals is None:
                assert len(ex.slot[0].val) == 0
            else:
                np.testing.assert_allclose(ex.slot[0].val, vals, rtol=1e-6)

    def test_protobuf_bytes_parse_in_ours(self, real_pb):
        rng = np.random.default_rng(12)
        for _ in range(25):
            ex = real_pb.Example()
            for _ in range(int(rng.integers(1, 4))):
                s = ex.slot.add()
                s.id = int(rng.integers(0, 50))
                s.key.extend(
                    rng.integers(0, 2**64, int(rng.integers(0, 7)),
                                 dtype=np.uint64).tolist()
                )
                if rng.random() < 0.5:
                    s.val.extend(
                        rng.normal(size=len(s.key)).astype(np.float32)
                        .tolist()
                    )
            blob = ex.SerializeToString()
            slots = decode_example(blob)
            assert len(slots) == len(ex.slot)
            for (sid, keys, vals), ps in zip(slots, ex.slot):
                assert sid == ps.id
                np.testing.assert_array_equal(
                    keys, np.asarray(ps.key, np.uint64)
                )
                if vals is None:
                    assert len(ps.val) == 0
                else:
                    np.testing.assert_allclose(
                        vals, np.asarray(ps.val, np.float32), rtol=1e-6
                    )

    def test_unpacked_encoding_accepted(self, real_pb):
        """A writer that ignores [packed=true] is still legal proto —
        hand-build an unpacked Slot and decode it."""
        from parameter_server_tpu.data.ref_interop import decode_slot

        buf = bytearray()
        buf += bytes([0x08, 0x07])            # id = 7 (varint)
        buf += bytes([0x10, 0x03])            # key = 3 (UNPACKED varint)
        buf += bytes([0x10, 0x80, 0x01])      # key = 128
        buf += bytes([0x1D]) + struct.pack("<f", 1.5)  # val fixed32
        sid, keys, vals = decode_slot(bytes(buf))
        assert sid == 7
        np.testing.assert_array_equal(keys, np.asarray([3, 128], np.uint64))
        np.testing.assert_allclose(vals, [1.5])


class TestToolingRoundTrip:
    """text2record --ref-format + StreamReader(format='ref_record'):
    the user-facing path for reference-dataset interop."""

    def test_libsvm_to_ref_format_and_back(self, tmp_path, capsys):
        from parameter_server_tpu.data.stream_reader import StreamReader
        from parameter_server_tpu.data.text2record import main as t2r_main

        src = tmp_path / "train.libsvm"
        src.write_text(
            "1 3:0.5 17:2.0\n"
            "-1 2:1.0 900:0.25\n"
            "1 1:1.5\n"
        )
        out = str(tmp_path / "train.ref.recordio")
        rc = t2r_main([
            "--input", str(src), "--format", "libsvm",
            "--output", out, "--ref-format",
        ])
        assert rc == 0
        assert "wrote 3 examples" in capsys.readouterr().out
        # the file is genuine reference framing
        assert list(iter_ref_records(out))
        batches = list(
            StreamReader([out], "ref_record").minibatches(2)
        )
        assert [b.n for b in batches] == [2, 1]
        np.testing.assert_array_equal(batches[0].y, [1.0, -1.0])
        np.testing.assert_array_equal(
            batches[0].indices, [3, 17, 2, 900]
        )
        np.testing.assert_allclose(
            batches[0].values, [0.5, 2.0, 1.0, 0.25]
        )

    def test_golden_through_stream_reader(self):
        from parameter_server_tpu.data.stream_reader import StreamReader

        (b,) = list(StreamReader([GOLDEN], "ref_record").minibatches(10))
        assert b.n == 3
        np.testing.assert_array_equal(b.slot_ids, [1, 1, 1, 2, 2, 5])

    def test_conf_proto_format_maps_to_ref_record(self):
        """A reference .conf declaring `format: PROTO` must route to the
        reference-format reader (that IS DataConfig.PROTO's on-disk
        format), not this repo's own crc-framed batches."""
        from parameter_server_tpu.apps.linear.config import parse_conf

        conf = parse_conf(
            'training_data {\nformat: PROTO\nfile: "x.recordio"\n}\n'
        )
        assert conf.training_data.format == "ref_record"

    def test_gzipped_ref_file(self, tmp_path):
        """ref recordio behind .gz works like every other reader path
        (utils.file.open_read owns decompression)."""
        import gzip

        gz = tmp_path / "g.recordio.gz"
        gz.write_bytes(gzip.compress(open(GOLDEN, "rb").read()))
        b = read_ref_batch(str(gz))
        assert b.n == 3


class TestDecoderFuzz:
    """The wire decoder must never hang/crash on arbitrary bytes —
    malformed input raises ValueError (or decodes, for bytes that
    happen to be valid proto), nothing else."""

    def test_random_bytes(self):
        rng = np.random.default_rng(99)
        for _ in range(300):
            blob = rng.integers(0, 256, rng.integers(0, 64),
                                dtype=np.uint8).tobytes()
            try:
                decode_example(blob)
            except ValueError:
                pass  # the only acceptable failure mode

    def test_mutated_golden(self):
        """Bit-flipped versions of REAL payloads — closer to the
        corruption a torn write produces than uniform noise."""
        rng = np.random.default_rng(100)
        payloads = list(iter_ref_records(GOLDEN))
        for _ in range(200):
            p = bytearray(payloads[rng.integers(len(payloads))])
            for _ in range(rng.integers(1, 4)):
                p[rng.integers(len(p))] ^= 1 << rng.integers(8)
            try:
                decode_example(bytes(p))
            except ValueError:
                pass


class TestInfoAscii:
    def test_roundtrip(self):
        from parameter_server_tpu.data.example import ExampleInfo, SlotInfo

        info = ExampleInfo(
            slot=[
                SlotInfo(id=0, format="dense", min_key=0, max_key=0,
                         nnz_ele=100, nnz_ex=100),
                SlotInfo(id=1, format="sparse_binary", min_key=5,
                         max_key=2**63, nnz_ele=321, nnz_ex=99),
            ],
            num_ex=100,
        )
        text = format_info_ascii(info)
        back = parse_info_ascii(text)
        assert back == info

    def test_parses_enum_numbers(self):
        info = parse_info_ascii(
            "slot {\n format: 3\n id: 2\n min_key: 1\n max_key: 9\n"
            " nnz_ele: 4\n nnz_ex: 2\n}\nnum_ex: 7\n"
        )
        assert info.slot[0].format == "sparse_binary"
        assert info.num_ex == 7
