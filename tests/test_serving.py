"""Serving plane (serving/): admission control, request coalescing,
read replicas, the composed frontend, the open-loop load generator,
and serve traffic across an elastic resize.

The contracts pinned here are the ones doc/SERVING.md sells:
rejections are explicit and cheap (never a hang, never a corrupt
response), coalesced pulls are value-identical to direct pulls with
FEWER executor submits, replica reads are snapshot-consistent and
immune to concurrent donated training pushes, speculative decode
served through the frontend equals plain greedy decoding token for
token, and an elastic resize mid-traffic queues or sheds — never
errors."""

import threading
import time

import numpy as np
import pytest

from parameter_server_tpu.parameter.kv_vector import KVVector
from parameter_server_tpu.serving import (
    AdmissionController,
    DecodeRequest,
    PredictRequest,
    PullCoalescer,
    PullRequest,
    ReadReplica,
    RejectedError,
    ServeConfig,
    ServeFrontend,
    TokenBucket,
    open_loop_bench,
)
from parameter_server_tpu.system.postoffice import Postoffice


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    yield
    Postoffice.reset()


def _store(mesh, num_slots=1 << 12, k=1, seed=0, n_keys=512,
           key_space=1 << 20):
    kv = KVVector(mesh=mesh, k=k, num_slots=num_slots, hashed=True,
                  name="serve_test")
    rng = np.random.default_rng(seed)
    keys = np.unique(rng.integers(0, key_space, n_keys))
    vals = rng.normal(size=(len(keys), k)).astype(np.float32)
    kv.wait(kv.push(kv.request(channel=0), keys=keys, values=vals))
    return kv, keys


class TestTokenBucket:
    def test_burst_then_rate(self):
        now = [0.0]
        tb = TokenBucket(rate=10.0, burst=5.0, clock=lambda: now[0])
        for _ in range(5):
            assert tb.try_acquire() is None  # burst drains
        retry = tb.try_acquire()
        assert retry == pytest.approx(0.1)  # 1 token at 10/s
        now[0] = 0.35  # 3.5 tokens refilled
        assert tb.try_acquire(3) is None
        assert tb.try_acquire(1) is not None

    def test_refill_caps_at_burst(self):
        now = [0.0]
        tb = TokenBucket(rate=100.0, burst=4.0, clock=lambda: now[0])
        now[0] = 100.0
        assert tb.available() == pytest.approx(4.0)

    def test_validation(self):
        with pytest.raises(ValueError):
            TokenBucket(rate=0, burst=1)
        with pytest.raises(ValueError):
            TokenBucket(rate=1, burst=0)


class TestAdmission:
    def test_rate_shed_carries_retry_after(self):
        now = [0.0]
        adm = AdmissionController(rate=10, burst=2, clock=lambda: now[0])
        adm.admit()
        adm.admit()
        with pytest.raises(RejectedError) as ei:
            adm.admit()
        assert ei.value.reason == "rate"
        assert ei.value.retry_after_s == pytest.approx(0.1)

    def test_queue_shed(self):
        depth = [0]
        adm = AdmissionController(
            max_queue_depth=3, depth_fn=lambda: depth[0]
        )
        adm.admit()  # no rate gate, depth below bound
        depth[0] = 3
        with pytest.raises(RejectedError) as ei:
            adm.admit()
        assert ei.value.reason == "queue"
        assert ei.value.retry_after_s > 0

    def test_disabled_gates_admit_everything(self):
        adm = AdmissionController()
        for _ in range(1000):
            adm.admit()


class TestCoalescer:
    def test_concurrent_pulls_match_direct_with_fewer_submits(self, mesh8):
        kv, keys = _store(mesh8)
        co = PullCoalescer(kv, window_s=0.005, max_requests=64)
        rng = np.random.default_rng(1)
        reqs = [rng.choice(keys, 24, replace=True) for _ in range(24)]
        results = [None] * len(reqs)
        errors = []

        def client(j):
            try:
                results[j] = co.pull(reqs[j]).result(timeout=30)
            except BaseException as e:  # surfaced below
                errors.append(e)

        threads = [
            threading.Thread(target=client, args=(j,))
            for j in range(len(reqs))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors
        for j, req in enumerate(reqs):
            np.testing.assert_allclose(results[j], kv.values(0, req))
        stats = co.stats()
        assert stats["requests"] == len(reqs)
        assert stats["submits"] < stats["requests"]  # the coalescing win
        assert stats["key_dedup_factor"] > 1.0  # overlap fetched once
        co.close()

    def test_duplicate_keys_within_one_request(self, mesh8):
        kv, keys = _store(mesh8)
        co = PullCoalescer(kv, window_s=0.001)
        req = np.array([keys[3], keys[3], keys[5], keys[3]])
        got = co.pull(req).result(timeout=30)
        np.testing.assert_allclose(got, kv.values(0, req))
        co.close()

    def test_store_failure_propagates_to_every_waiter(self, mesh8):
        kv, keys = _store(mesh8)

        class Boom(Exception):
            pass

        def bad_pull(task, keys=None, **kw):
            raise Boom("table on fire")

        kv.pull = bad_pull
        co = PullCoalescer(kv, window_s=0.001)
        t1 = co.pull(keys[:4])
        t2 = co.pull(keys[4:8])
        for t in (t1, t2):
            with pytest.raises(RuntimeError, match="coalesced pull failed"):
                t.result(timeout=30)
        co.close()

    def test_close_rejects_new_and_flushes_staged(self, mesh8):
        kv, keys = _store(mesh8)
        co = PullCoalescer(kv, window_s=30.0)  # would wait forever
        ticket = co.pull(keys[:8])
        co.close()  # must flush the staged window, not strand it
        np.testing.assert_allclose(
            ticket.result(timeout=30), kv.values(0, keys[:8])
        )
        with pytest.raises(RuntimeError, match="closed"):
            co.pull(keys[:4])


class TestReadReplica:
    def test_snapshot_consistency_across_pushes(self, mesh8):
        kv, keys = _store(mesh8)
        rep = ReadReplica(kv)
        before, hit = rep.pull(keys[:16])
        assert hit.all()
        np.testing.assert_allclose(before, kv.values(0, keys[:16]))
        # training pushes donate the live table; the replica must not move
        kv.wait(kv.push(
            kv.request(channel=0), keys=keys[:16],
            values=np.ones((16, 1), np.float32),
        ))
        again, _ = rep.pull(keys[:16])
        np.testing.assert_array_equal(before, again)  # snapshot held
        v1 = rep.refresh()
        assert v1 == 2
        after, _ = rep.pull(keys[:16])
        np.testing.assert_allclose(after, before + 1.0)

    def test_reads_survive_concurrent_donated_push_stream(self, mesh8):
        """The zero-copy hazard this class exists for: with pushes
        donating the live table in flight, replica reads (and
        refreshes) must never hit read-after-donate."""
        kv, keys = _store(mesh8)
        rep = ReadReplica(kv)
        stop = threading.Event()
        push_err = []

        def pusher():
            try:
                while not stop.is_set():
                    kv.wait(kv.push(
                        kv.request(channel=0), keys=keys[:64],
                        values=np.ones((64, 1), np.float32),
                    ))
            except BaseException as e:
                push_err.append(e)

        t = threading.Thread(target=pusher)
        t.start()
        deadline = time.monotonic() + 1.0
        while time.monotonic() < deadline:
            vals, hit = rep.pull(keys[:32])
            assert hit.all() and vals.shape == (32, 1)
            rep.refresh()
        stop.set()
        t.join(timeout=60)
        assert not push_err

    def test_hot_key_replica_reports_misses(self, mesh8):
        kv, keys = _store(mesh8)
        hot = keys[:32]
        rep = ReadReplica(kv, hot_keys=hot)
        assert rep.nbytes() < ReadReplica(kv).nbytes()  # compact
        mixed = np.concatenate([hot[:4], keys[-4:]])
        vals, hit = rep.pull(mixed)
        assert hit[:4].all() and not hit[4:].any()
        np.testing.assert_allclose(vals[:4], kv.values(0, hot[:4]))

    def test_miss_counter_counts_keys_not_requests(self, mesh8):
        """Regression pin for the hot-replica accounting contract:
        ``ps_serve_replica_misses_total`` advances by the number of
        missed KEYS, not by 1 per request that had any miss — the miss
        RATE (misses/keys) is what sizes the hot set, and a per-request
        count would understate it by the batch width."""
        kv, keys = _store(mesh8)
        hot = keys[:32]
        rep = ReadReplica(kv, hot_keys=hot)

        def count(name):
            snap = Postoffice.instance().metrics.snapshot()
            return sum(snap.get(name, {}).get("values", {}).values())

        misses0 = count("ps_serve_replica_misses_total")
        hits0 = count("ps_serve_replica_hits_total")
        mixed = np.concatenate([hot[:3], keys[-5:]])  # ONE request
        _, hit = rep.pull(mixed)
        assert hit[:3].all() and not hit[3:].any()
        assert count("ps_serve_replica_misses_total") - misses0 == 5
        assert count("ps_serve_replica_hits_total") - hits0 == 3

    def test_live_pull_receives_exactly_the_missed_keys(self, mesh8):
        """The fall-through contract: a mixed hot/cold pull live-pulls
        ONLY the missed rows (pulling the hits again would double the
        live-store read load the hot replica exists to absorb)."""
        kv, keys = _store(mesh8)
        hot, cold = keys[:32], keys[-6:]
        fe = ServeFrontend(
            kv, ServeConfig(replica="hot", hot_keys=hot,
                            coalesce_window_s=0.001, workers=1),
        ).start()
        try:
            seen = []
            orig = fe._live_pull

            def spy(ks):
                seen.append(np.asarray(ks).copy())
                return orig(ks)

            fe._live_pull = spy
            mixed = np.concatenate([hot[:4], cold])
            got = fe.submit(PullRequest(keys=mixed)).result(30)
            np.testing.assert_allclose(got, kv.values(0, mixed))
            assert len(seen) == 1
            np.testing.assert_array_equal(
                np.sort(seen[0]), np.sort(cold)
            )
        finally:
            fe.close()

    def test_snapshot_step_serializes_with_pushes(self, mesh8):
        """KVVector.snapshot is a SUBMITTED step: a snapshot requested
        after a push observes that push (timestamp order), unlike a
        racy host copy."""
        kv, keys = _store(mesh8)
        kv.push(kv.request(channel=0), keys=keys[:8],
                values=np.full((8, 1), 7.0, np.float32))
        snap = np.asarray(kv.executor.wait(kv.snapshot(0)))
        slots = kv.channel(0).directory.slots(keys[:8])
        got = snap[slots]
        want = kv.values(0, keys[:8])
        np.testing.assert_allclose(got, want)


class TestDeviceReplica:
    def test_device_matches_host_full_and_hot(self, mesh8):
        """device=True serves byte-identical values to the host-mode
        replica, across request widths (the pow2-padded gather) and in
        both full and hot-key modes — and the snapshot really stays a
        device array."""
        import jax

        kv, keys = _store(mesh8)
        host = ReadReplica(kv)
        dev = ReadReplica(kv, device=True)
        assert isinstance(dev._table, jax.Array)
        assert isinstance(host._table, np.ndarray)
        for n in (1, 3, 8, 17, 100):
            vh, _ = host.pull(keys[:n])
            vd, hit = dev.pull(keys[:n])
            assert hit.all()
            np.testing.assert_array_equal(vh, vd)
        hot = keys[:32]
        hh = ReadReplica(kv, hot_keys=hot)
        hd = ReadReplica(kv, hot_keys=hot, device=True)
        mixed = np.concatenate([hot[:5], keys[-3:]])
        vh, mh = hh.pull(mixed)
        vd, md = hd.pull(mixed)
        np.testing.assert_array_equal(mh, md)
        np.testing.assert_array_equal(vh, vd)

    def test_device_reads_survive_concurrent_donated_push_stream(
        self, mesh8
    ):
        """The zero-copy hazard, device edition: the device snapshot is
        the executor's submitted copy, so reads and refreshes stay
        consistent while training pushes donate the live table."""
        kv, keys = _store(mesh8)
        rep = ReadReplica(kv, device=True)
        stop = threading.Event()
        push_err = []

        def pusher():
            try:
                while not stop.is_set():
                    kv.wait(kv.push(
                        kv.request(channel=0), keys=keys[:64],
                        values=np.ones((64, 1), np.float32),
                    ))
            except BaseException as e:
                push_err.append(e)

        t = threading.Thread(target=pusher)
        t.start()
        deadline = time.monotonic() + 1.0
        while time.monotonic() < deadline:
            vals, hit = rep.pull(keys[:32])
            assert hit.all() and vals.shape == (32, 1)
            rep.refresh()
        stop.set()
        t.join(timeout=60)
        assert not push_err

    def test_host_budget_fails_loudly_device_ignores_it(self, mesh8):
        """``host_budget_bytes`` below the table size: host mode
        refuses the snapshot with MemoryError (pointing at device
        mode); device mode serves the same table under the same budget
        — capacity scales with HBM, not host RAM."""
        kv, keys = _store(mesh8)
        table_bytes = ReadReplica(kv).nbytes()
        budget = table_bytes // 2
        with pytest.raises(MemoryError, match="device=True"):
            ReadReplica(kv, host_budget_bytes=budget)
        dev = ReadReplica(kv, device=True, host_budget_bytes=budget)
        vals, hit = dev.pull(keys[:8])
        assert hit.all()
        np.testing.assert_allclose(vals, kv.values(0, keys[:8]))

    def test_device_frontend_over_host_budget_zero_degraded(self, mesh8):
        """The acceptance arc: a frontend in device-replica mode serves
        a table LARGER than the configured host-replica budget, with
        background refreshes live, and zero DegradedErrors (and zero
        degraded fallbacks) across the run."""
        kv, keys = _store(mesh8)
        budget = ReadReplica(kv).nbytes() // 2
        fe = ServeFrontend(
            kv,
            ServeConfig(replica="full", workers=2,
                        replica_device=True,
                        replica_host_budget_bytes=budget,
                        replica_refresh_s=0.02),
        ).start()
        try:
            assert fe.replica.device
            deadline = time.monotonic() + 0.5
            served = 0
            while time.monotonic() < deadline:
                got = fe.submit(PullRequest(keys=keys[:16])).result(30)
                np.testing.assert_allclose(got, kv.values(0, keys[:16]))
                served += 1
            assert served > 0
            assert fe.degraded_served == 0
            snap = Postoffice.instance().metrics.snapshot()
            degraded = sum(
                snap.get("ps_serve_degraded_total", {})
                .get("values", {}).values()
            )
            assert degraded == 0
            assert fe.stats()["replica"]["device"] is True
        finally:
            fe.close()


class TestFrontend:
    def test_pull_predict_decode_and_telemetry(self, mesh8):
        kv, keys = _store(mesh8)
        fe = ServeFrontend(
            kv, ServeConfig(replica="full", workers=2)
        ).start()
        try:
            got = fe.submit(PullRequest(keys=keys[:12])).result(30)
            np.testing.assert_allclose(got, kv.values(0, keys[:12]))
            # predict: sigmoid of per-row weight sums
            pr = PredictRequest(
                indices=keys[:6], indptr=np.array([0, 2, 6])
            )
            scores = fe.submit(pr).result(30)
            w = kv.values(0, keys[:6]).ravel()
            want = 1 / (1 + np.exp(-np.array([w[:2].sum(), w[2:6].sum()])))
            np.testing.assert_allclose(scores, want, rtol=1e-6)
            snap = Postoffice.instance().metrics.snapshot()
            assert snap["ps_serve_requests_total"]["values"]
            assert snap["ps_serve_latency_seconds"]["values"]
        finally:
            fe.close()
        with pytest.raises(RuntimeError, match="closed"):
            fe.submit(PullRequest(keys=keys[:2]))

    def test_hot_replica_miss_falls_through_to_live_pull(self, mesh8):
        kv, keys = _store(mesh8)
        fe = ServeFrontend(
            kv,
            ServeConfig(replica="hot", hot_keys=keys[:32],
                        coalesce_window_s=0.001, workers=2),
        ).start()
        try:
            mixed = np.concatenate([keys[:8], keys[-8:]])
            got = fe.submit(PullRequest(keys=mixed)).result(30)
            np.testing.assert_allclose(got, kv.values(0, mixed))
            assert fe.coalescer.stats()["requests"] >= 1  # misses pulled live
        finally:
            fe.close()

    def test_shed_is_explicit_and_counted(self, mesh8):
        kv, keys = _store(mesh8)
        fe = ServeFrontend(
            kv,
            ServeConfig(replica="full", workers=1,
                        admission_rate=20, admission_burst=2,
                        max_queue_depth=4),
        ).start()
        try:
            shed = ok = 0
            for _ in range(100):
                try:
                    fe.submit(PullRequest(keys=keys[:4]))
                    ok += 1
                except RejectedError as e:
                    assert e.reason in ("rate", "queue")
                    assert e.retry_after_s >= 0
                    shed += 1
            assert shed > 0 and ok > 0
            snap = Postoffice.instance().metrics.snapshot()
            total_shed = sum(
                snap["ps_serve_shed_total"]["values"].values()
            )
            assert total_shed >= shed  # counted at the door
        finally:
            fe.close()

    def test_decode_equals_plain_greedy(self, mesh8):
        """The serving guarantee for the LM lane: speculative decode
        served through the frontend is token-for-token plain greedy
        decoding of the target model."""
        import jax

        from parameter_server_tpu.models.speculative import (
            speculative_generate,
        )
        from parameter_server_tpu.models.transformer import (
            LMConfig,
            init_lm,
            lm_generate,
        )

        tcfg = LMConfig(vocab=64, d_model=32, n_heads=2, n_layers=2,
                        d_ff=64)
        dcfg = LMConfig(vocab=64, d_model=16, n_heads=2, n_layers=1,
                        d_ff=32)
        tparams = init_lm(jax.random.PRNGKey(0), tcfg)
        dparams = init_lm(jax.random.PRNGKey(1), dcfg)

        def decode_fn(req):
            return speculative_generate(
                tparams, tcfg, dparams, dcfg,
                jax.numpy.asarray(req.prompt), req.steps, gamma=2,
            )

        kv, keys = _store(mesh8)
        fe = ServeFrontend(
            kv, ServeConfig(replica="full", workers=1),
            decode_fn=decode_fn,
        ).start()
        try:
            prompt = np.asarray(
                jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, 64),
                np.int32,
            )
            served = fe.submit(DecodeRequest(prompt=prompt, steps=8)).result(
                300
            )
            plain = np.asarray(lm_generate(tparams, prompt, tcfg, steps=8))
            np.testing.assert_array_equal(served, plain)
        finally:
            fe.close()

    def test_decode_backlog_sheds_decode_not_pulls(self, mesh8):
        """Lane isolation at the door: a decode pileup fills the decode
        lane's own bound (shedding further DECODES with the explicit
        429) while microsecond pulls stay admitted and served — the
        no-head-of-line contract, admission edition."""
        kv, keys = _store(mesh8)
        gate = threading.Event()

        def slow_decode(req):
            gate.wait(30)
            return req.prompt

        fe = ServeFrontend(
            kv,
            ServeConfig(replica="full", workers=1, max_queue_depth=2),
            decode_fn=slow_decode,
        ).start()
        try:
            prompt = np.zeros((1, 4), np.int32)
            dts = [
                fe.submit(DecodeRequest(prompt=prompt, steps=4))
                for _ in range(2)
            ]
            with pytest.raises(RejectedError) as ei:
                fe.submit(DecodeRequest(prompt=prompt, steps=4))
            assert ei.value.reason == "queue"
            # the pull lane is untouched by the decode backlog
            got = fe.submit(PullRequest(keys=keys[:4])).result(30)
            np.testing.assert_allclose(got, kv.values(0, keys[:4]))
            gate.set()
            for t in dts:
                t.result(60)
        finally:
            gate.set()
            fe.close()

    def test_pull_backlog_sheds_pulls_not_decode(self, mesh8):
        """Lane isolation, the other direction: with the pull lane
        pinned at the depth bound, further PULLS shed with the explicit
        429 but a decode submit still passes the door — each lane
        carries its own same-sized bound against its own backlog."""
        kv, keys = _store(mesh8)
        fe = ServeFrontend(
            kv,
            ServeConfig(replica="full", workers=1, max_queue_depth=2),
            decode_fn=lambda req: req.prompt,
        ).start()
        try:
            fe.pause()  # workers gated: admitted pulls pile up queued
            pts = [fe.submit(PullRequest(keys=keys[:4])) for _ in range(2)]
            with pytest.raises(RejectedError) as ei:
                fe.submit(PullRequest(keys=keys[:4]))
            assert ei.value.reason == "queue"
            # the decode lane is untouched by the pull backlog
            dt = fe.submit(DecodeRequest(
                prompt=np.zeros((1, 4), np.int32), steps=4
            ))
            fe.resume()
            np.testing.assert_array_equal(
                dt.result(60), np.zeros((1, 4), np.int32)
            )
            for t in pts:
                np.testing.assert_allclose(
                    t.result(60), kv.values(0, keys[:4])
                )
        finally:
            fe.resume()
            fe.close()

    def test_bad_replica_config_leaks_no_threads(self, mesh8):
        """A config error in __init__ must not leak the coalescer's
        flusher thread: replica validation runs BEFORE the coalescer
        (whose constructor starts a thread) is built."""
        kv, _ = _store(mesh8)

        def flushers():
            return sum(
                t.name == "serve-coalescer" for t in threading.enumerate()
            )

        before = flushers()
        with pytest.raises(ValueError, match="hot_keys"):
            ServeFrontend(kv, ServeConfig(replica="hot"))
        with pytest.raises(ValueError, match="'off'"):
            ServeFrontend(kv, ServeConfig(replica="bogus"))
        assert flushers() == before

    def test_concurrent_submits_never_exceed_depth_bound(self, mesh8):
        """The depth gate checks AND reserves in one critical section:
        N racing submitters against a paused frontend admit at most
        max_queue_depth pulls total, never bound + N - 1."""
        kv, keys = _store(mesh8)
        bound = 16
        fe = ServeFrontend(
            kv, ServeConfig(replica="full", workers=1,
                            max_queue_depth=bound),
        ).start()
        accepted = []
        try:
            fe.pause()  # nothing drains: accepted == in-flight

            def hammer():
                n = 0
                for _ in range(50):
                    try:
                        fe.submit(PullRequest(keys=keys[:4]))
                        n += 1
                    except RejectedError:
                        pass
                accepted.append(n)

            threads = [
                threading.Thread(target=hammer) for _ in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30)
            assert sum(accepted) == fe.depth() == bound
        finally:
            fe.resume()
            fe.close()

    def test_wrong_channel_rejected_at_door(self, mesh8):
        """A frontend is bound to ONE channel (replica + coalescer);
        answering another channel's request with this channel's rows
        would be silent wrong data — submit must reject loudly."""
        kv, keys = _store(mesh8)
        fe = ServeFrontend(kv, ServeConfig(replica="full")).start()
        try:
            with pytest.raises(ValueError, match="channel"):
                fe.submit(PullRequest(keys=keys[:4], channel=1))
            with pytest.raises(ValueError, match="channel"):
                fe.submit(PredictRequest(
                    indices=keys[:4], indptr=np.array([0, 4]), channel=2
                ))
        finally:
            fe.close()

    def test_store_level_admission_gates_on_executor_backlog(self):
        """The bare-store admission wiring: Executor.pending_count as
        the depth signal (a store serving direct pulls has no frontend
        in-flight count to gate on)."""
        from parameter_server_tpu.system.executor import Executor

        ex = Executor("adm-test")
        gate = threading.Event()
        adm = AdmissionController(
            max_queue_depth=3, depth_fn=ex.pending_count
        )
        ts = [ex.submit(gate.wait) for _ in range(4)]  # 1 runs, 3 pend
        # pending reads 4 until the dispatch thread picks the first
        # step (which then blocks on the gate) — wait for the settled
        # backlog, not merely >=3, or the assert races the pickup
        deadline = time.monotonic() + 5
        while ex.pending_count() != 3 and time.monotonic() < deadline:
            time.sleep(0.001)
        assert ex.pending_count() == 3
        with pytest.raises(RejectedError) as ei:
            adm.admit()
        assert ei.value.reason == "queue"
        gate.set()
        for t in ts:
            ex.wait(t)
        adm.admit()  # backlog drained: the door reopens
        ex.stop()

    def test_decode_without_decode_fn_rejected(self, mesh8):
        kv, keys = _store(mesh8)
        fe = ServeFrontend(kv, ServeConfig(replica="full")).start()
        try:
            with pytest.raises(ValueError, match="decode_fn"):
                fe.submit(DecodeRequest(prompt=np.zeros((1, 4), np.int32),
                                        steps=4))
        finally:
            fe.close()


class TestLoadgen:
    def test_open_loop_point_shape_and_rates(self, mesh8):
        kv, keys = _store(mesh8)
        fe = ServeFrontend(
            kv, ServeConfig(replica="full", workers=2)
        ).start()
        try:
            rec = open_loop_bench(
                fe, lambda i: PullRequest(keys=keys[i % 32: i % 32 + 8]),
                rate=200, duration_s=0.5, seed=3, warmup_requests=3,
            )
        finally:
            fe.close()
        # Poisson(100) arrivals in 0.5s: within wide deterministic-seed
        # bounds (the seed fixes the draw, the bound documents intent)
        assert 60 <= rec["offered"] <= 140
        assert rec["n_errors"] == 0
        assert rec["completed"] == rec["accepted"]
        lat = rec["latency_ms"]
        assert lat["p50_ms"] <= lat["p99_ms"] <= lat["max_ms"] + 1e-9
        assert rec["goodput_per_sec"] > 0

    def test_collector_reports_server_errors_instead_of_raising(self,
                                                                mesh8):
        kv, keys = _store(mesh8)
        fe = ServeFrontend(kv, ServeConfig(replica="off")).start()

        def bad_pull(task, keys=None, **kw):
            raise RuntimeError("shard gone")

        kv.pull = bad_pull
        try:
            rec = open_loop_bench(
                fe, lambda i: PullRequest(keys=keys[:4]),
                rate=50, duration_s=0.3, seed=4,
            )
        finally:
            fe.close()
        assert rec["n_errors"] == rec["accepted"] > 0
        assert rec["errors"]  # first few disclosed


class _ServeWorker:
    """Minimal elastic worker: a KVVector + the state_host hooks the
    ElasticCoordinator drives (hashed slots are modulus-stable, so the
    snapshot re-installs exactly across server counts)."""

    def __init__(self, mesh, num_slots):
        self.kv = KVVector(mesh=mesh, k=1, num_slots=num_slots,
                           hashed=True, name="elastic_serve")
        self.executor = self.kv.executor

    def state_host(self):
        self.kv.executor.wait_all(pop=False)
        return {"table": np.asarray(self.kv.table(0))}

    def load_state_host(self, snap):
        # re-fit rows to the new server count's padded capacity (the
        # configured modulus keeps every real slot stable; only the
        # zero padding tail changes — same contract as AsyncSGDWorker)
        t = snap["table"]
        cap = self.kv.num_slots
        if len(t) < cap:
            t = np.pad(t, ((0, cap - len(t)), (0, 0)))
        self.kv.set_replica({0: t[:cap]})

    def recover_server_shard(self, rank):
        return False


class TestServeAcrossElasticResize:
    NUM_SLOTS = 1000  # non-pow2: padding varies per server count

    def test_traffic_queues_or_sheds_never_errors(self, mesh8):
        """Requests in flight across the elastic stop-the-world must
        queue (completing with correct values after the resize) or
        shed with the explicit 429 — never surface an error."""
        from parameter_server_tpu.system.elastic import ElasticCoordinator

        co = ElasticCoordinator(
            lambda mesh: _ServeWorker(mesh, self.NUM_SLOTS),
            num_data=2, num_server=2,
        )
        w = co.start()
        rng = np.random.default_rng(7)
        keys = np.unique(rng.integers(0, 1 << 16, 256))
        vals = rng.normal(size=(len(keys), 1)).astype(np.float32)
        w.kv.wait(w.kv.push(w.kv.request(channel=0), keys=keys,
                            values=vals))
        expect = w.kv.values(0, keys)

        fe = ServeFrontend(
            w.kv,
            # background refresher ON: quiesce() must hold the resize
            # back while a refresh is mid-flight against the old store
            # (the refresher counts in _executing like a worker)
            ServeConfig(replica="full", workers=2, max_queue_depth=64,
                        replica_refresh_s=0.01),
        ).start()
        stop = threading.Event()
        outcomes = {"ok": 0, "shed": 0, "wrong": 0}
        errors = []

        def traffic():
            i = 0
            while not stop.is_set():
                lo = i % (len(keys) - 16)
                try:
                    got = fe.submit(
                        PullRequest(keys=keys[lo:lo + 16])
                    ).result(timeout=60)
                    if np.allclose(got, expect[lo:lo + 16]):
                        outcomes["ok"] += 1
                    else:
                        outcomes["wrong"] += 1
                except RejectedError:
                    outcomes["shed"] += 1  # explicit 429: allowed
                except BaseException as e:  # anything else: the bug
                    errors.append(e)
                    return
                i += 1

        threads = [threading.Thread(target=traffic) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.15)
        before_resize = outcomes["ok"]
        # the elastic stop-the-world, with traffic in flight
        fe.pause()
        fe.quiesce()
        w = co.resize(num_server=3)
        fe.rebind(w.kv)
        fe.resume()
        time.sleep(0.25)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        fe.close()
        assert not errors, errors
        assert outcomes["wrong"] == 0
        assert before_resize > 0, "no traffic completed before the resize"
        assert outcomes["ok"] > before_resize, (
            "no traffic completed after the resize", outcomes
        )
        # post-resize reads still serve the migrated table
        np.testing.assert_allclose(w.kv.values(0, keys), expect)
