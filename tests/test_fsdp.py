"""FSDP / ZeRO-3 parameter sharding (fsdp_shard_lm_params): placement,
per-device memory reduction, trajectory identity vs replicated params,
the full ZeRO-3 stack via optax-state inheritance, and composition with
Megatron tensor parallelism / remat / RoPE. Extension beyond the
reference (its analogue is kv_layer.h's partition-threshold server
sharding of NN layers; here the data axis carries the shards and GSPMD
inserts the gather/reduce-scatter pair)."""

import jax
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from parameter_server_tpu.models.transformer import (
    LMConfig,
    fsdp_shard_lm_params,
    init_lm,
    lm_loss,
    shard_lm_params,
    shard_tokens,
)


@pytest.fixture(scope="module")
def cfg():
    return LMConfig(vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64)


class TestFsdpPlacement:
    def test_params_shard_over_data_axis(self, mesh8, cfg):
        params = fsdp_shard_lm_params(
            init_lm(jax.random.PRNGKey(0), cfg), mesh8, "data"
        )
        n = mesh8.shape["data"]
        emb = params["emb"]  # [32, 32]: 32 % 4 == 0 -> sharded
        spec = list(emb.sharding.spec) + [None] * (
            emb.ndim - len(emb.sharding.spec)
        )
        assert "data" in spec, emb.sharding
        # per-device bytes shrink by the axis size
        assert emb.addressable_shards[0].data.nbytes == emb.nbytes // n
        # every leaf is mesh-committed
        for k, v in params.items():
            assert isinstance(v.sharding, NamedSharding), k

    def test_optax_state_inherits_sharding(self, mesh8, cfg):
        """tx.init(zeros_like) inherits each param's placement — FSDP
        params alone give sharded moments, i.e. the full ZeRO-3 stack
        with no separate zero1 call."""
        params = fsdp_shard_lm_params(
            init_lm(jax.random.PRNGKey(0), cfg), mesh8, "data"
        )
        opt = optax.adam(1e-2).init(params)
        mu = opt[0].mu["emb"]
        assert not mu.sharding.is_fully_replicated
        spec = list(mu.sharding.spec) + [None] * (
            mu.ndim - len(mu.sharding.spec)
        )
        assert "data" in spec, mu.sharding

    def test_composes_with_tensor_parallel(self, mesh8, cfg):
        """A Megatron-split leaf keeps its server dim and gains the data
        axis on another dimension."""
        params = fsdp_shard_lm_params(
            shard_lm_params(
                init_lm(jax.random.PRNGKey(0), cfg), mesh8, "server"
            ),
            mesh8,
            "data",
        )
        wq = params["l0/wq"]
        spec = list(wq.sharding.spec) + [None] * (
            wq.ndim - len(wq.sharding.spec)
        )
        assert "server" in spec and "data" in spec, spec

    def test_indivisible_leaves_stay_replicated(self, mesh8):
        # 3x5: no dim divides the 4-way data axis -> replicated, committed
        x = jax.device_put(
            np.zeros((3, 5), np.float32), NamedSharding(mesh8, P())
        )
        out = fsdp_shard_lm_params({"w": x}, mesh8, "data")
        assert out["w"].sharding.is_fully_replicated
        assert isinstance(out["w"].sharding, NamedSharding)


class TestFsdpTraining:
    def test_trajectory_matches_replicated(self, mesh8, cfg):
        """Sharded params must train to the same values as replicated
        params — FSDP is placement, not math. Unlike ZeRO-1 (bit-exact:
        only the moment update is partitioned), FSDP changes the
        GRADIENT reduction from all-reduce to reduce-scatter, whose
        summation order differs — and adam amplifies those few-ulp grad
        differences early in training (g/(sqrt(v)+eps) with small v), so
        params agree to ~1e-4 and the per-step losses to 1e-5."""
        init = init_lm(jax.random.PRNGKey(1), cfg)
        tx = optax.adam(1e-2)

        @jax.jit
        def step(p, opt, toks):
            loss, g = jax.value_and_grad(lm_loss)(p, toks, cfg, mesh8, "data")
            up, opt = tx.update(g, opt, p)
            return optax.apply_updates(p, up), opt, loss

        rng = np.random.default_rng(0)
        toks = [
            shard_tokens(
                rng.integers(0, cfg.vocab, (2, 64)).astype(np.int32), mesh8
            )
            for _ in range(4)
        ]
        p_a = jax.device_put(init, NamedSharding(mesh8, P()))
        opt_a = tx.init(p_a)
        p_b = fsdp_shard_lm_params(init, mesh8, "data")
        opt_b = tx.init(p_b)
        for t in toks:
            p_a, opt_a, la = step(p_a, opt_a, t)
            p_b, opt_b, lb = step(p_b, opt_b, t)
            np.testing.assert_allclose(float(la), float(lb), atol=1e-5)
        for k in p_a:
            np.testing.assert_allclose(
                np.asarray(p_a[k]), np.asarray(p_b[k]), atol=1e-4,
                err_msg=k,
            )
        # params AND moments stayed sharded through the jitted updates
        assert not p_b["emb"].sharding.is_fully_replicated
        assert not opt_b[0].mu["emb"].sharding.is_fully_replicated

    def test_remat_rope_ring_config_trains(self, mesh8):
        """FSDP under the production config surface: remat + RoPE +
        ring attention, loss finite and params stay sharded."""
        cfg = LMConfig(
            vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            remat=True, rope=True, attention="ring",
        )
        params = fsdp_shard_lm_params(
            init_lm(jax.random.PRNGKey(2), cfg), mesh8, "data"
        )

        @jax.jit
        def step(p, toks):
            loss, g = jax.value_and_grad(lm_loss)(p, toks, cfg, mesh8, "data")
            return jax.tree.map(lambda a, b: a - 0.1 * b, p, g), loss

        toks = shard_tokens(
            np.random.default_rng(3)
            .integers(0, cfg.vocab, (2, 64))
            .astype(np.int32),
            mesh8,
        )
        params, l0 = step(params, toks)
        params, l1 = step(params, toks)
        assert np.isfinite(float(l0)) and np.isfinite(float(l1))
        assert float(l1) < float(l0)  # second step on the same batch improves
        assert not params["emb"].sharding.is_fully_replicated
