"""Live elasticity (system/elastic.py): node join/leave with key-range
migration on the virtual 8-device mesh. Mirrors the reference's live
membership flows (manager.cc AddNode / dead-node): grow and shrink the
server set mid-training without files, keep every key's slot stable, and
recover a crashed server from the live replica."""

import numpy as np
import pytest

from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker
from parameter_server_tpu.apps.linear.config import (
    Config,
    LearningRateConfig,
    PenaltyConfig,
    SGDConfig,
)
from parameter_server_tpu.system.elastic import ElasticCoordinator
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.utils.sparse import random_sparse


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    yield
    Postoffice.reset()


NUM_SLOTS = 1000  # deliberately NOT a power of two: padding varies per
# server count (1000 -> 1000@2, 1002@3), so these tests prove hashing
# stays on the configured modulus across resizes


def make_worker(mesh):
    conf = Config()
    conf.penalty = PenaltyConfig(type="l1", lambda_=[0.01])
    conf.learning_rate = LearningRateConfig(type="decay", alpha=0.5, beta=1.0)
    conf.async_sgd = SGDConfig(
        algo="ftrl", minibatch=256, num_slots=NUM_SLOTS, num_replicas=1,
        replica_every=1,
    )
    return AsyncSGDWorker(conf, mesh=mesh)


def batches(n, seed0=0):
    rng = np.random.default_rng(42)
    w_true = (rng.normal(size=512) * (rng.random(512) < 0.2)).astype(np.float32)
    return [
        random_sparse(256, 512, 8, seed=seed0 + i, w_true=w_true)
        for i in range(n)
    ]


class TestGracefulResize:
    def test_server_join_migrates_key_ranges(self, mesh8):
        events = []
        co = ElasticCoordinator(make_worker, num_data=2, num_server=2)
        co.subscribe_nodes(lambda ev, n: events.append((ev, n.id)))
        w = co.start()
        for b in batches(3):
            w.collect(w.process_minibatch(b))
        before = w.weights_dense()[:NUM_SLOTS]

        w2 = co.add_server()  # 2x2 -> 2x3: key ranges re-divide 3 ways
        assert co.num_server == 3
        table = w2.state["z"]
        assert dict(table.sharding.mesh.shape)["server"] == 3
        np.testing.assert_allclose(
            w2.weights_dense()[:NUM_SLOTS], before, atol=1e-6
        )
        assert ("add", "S2") in events
        # training continues on the new split
        w2.collect(w2.process_minibatch(batches(1, seed0=50)[0]))

    def test_server_leave_keeps_model(self, mesh8):
        co = ElasticCoordinator(make_worker, num_data=2, num_server=2)
        w = co.start()
        for b in batches(3):
            w.collect(w.process_minibatch(b))
        before = w.weights_dense()[:NUM_SLOTS]
        w2 = co.remove_server()  # graceful decommission: state migrates
        np.testing.assert_allclose(
            w2.weights_dense()[:NUM_SLOTS], before, atol=1e-6
        )
        w2.collect(w2.process_minibatch(batches(1, seed0=50)[0]))

    def test_worker_join_grows_data_axis(self, mesh8):
        co = ElasticCoordinator(make_worker, num_data=2, num_server=2)
        w = co.start()
        w.collect(w.process_minibatch(batches(1)[0]))
        before = w.weights_dense()[:NUM_SLOTS]
        w2 = co.add_worker()  # 2x2 -> 3x2
        assert dict(w2.state["z"].sharding.mesh.shape)["data"] == 3
        np.testing.assert_allclose(
            w2.weights_dense()[:NUM_SLOTS], before, atol=1e-6
        )
        w2.collect(w2.process_minibatch(batches(1, seed0=60)[0]))

    def test_hash_slots_stable_across_resize(self, mesh8):
        co = ElasticCoordinator(make_worker, num_data=2, num_server=2)
        w = co.start()
        keys = np.array([3, 1 << 40, -5, 999999], dtype=np.int64)
        slots_before = w.directory.slots(keys)
        w2 = co.add_server()
        np.testing.assert_array_equal(w2.directory.slots(keys), slots_before)


class TestCrashPath:
    def test_death_with_replica_recovers_in_place(self, mesh8):
        co = ElasticCoordinator(make_worker, num_data=2, num_server=2)
        w = co.start()
        for b in batches(3):
            w.collect(w.process_minibatch(b))
        want = w.weights_dense()
        w.wipe_server_shard(0)
        assert co.handle_server_death(0) == "recovered"
        np.testing.assert_allclose(co.worker.weights_dense(), want, atol=1e-6)
        assert co.num_server == 2  # no shrink needed

    def test_death_without_replica_resharding_loses_only_dead_range(
        self, mesh8
    ):
        def make_worker_noreplica(mesh):
            conf = Config()
            conf.penalty = PenaltyConfig(type="l1", lambda_=[0.01])
            conf.learning_rate = LearningRateConfig(
                type="decay", alpha=0.5, beta=1.0
            )
            conf.async_sgd = SGDConfig(
                algo="ftrl", minibatch=256, num_slots=NUM_SLOTS
            )
            return AsyncSGDWorker(conf, mesh=mesh)

        events = []
        co = ElasticCoordinator(make_worker_noreplica, num_data=2, num_server=2)
        co.subscribe_nodes(lambda ev, n: events.append((ev, n.id)))
        w = co.start()
        for b in batches(3):
            w.collect(w.process_minibatch(b))
        before = w.weights_dense()
        per = w.num_slots // 2
        assert co.handle_server_death(1) == "resharded"
        assert co.num_server == 1
        after = co.worker.weights_dense()
        # surviving range intact; the dead server's range is lost (zeros)
        np.testing.assert_allclose(after[:per], before[:per], atol=1e-6)
        assert np.abs(after[per : 2 * per]).sum() == 0
        assert ("remove", "S1") in events
        co.worker.collect(co.worker.process_minibatch(batches(1, seed0=70)[0]))

    def test_heartbeat_timeout_drives_elastic_death_flow(self, mesh8):
        from parameter_server_tpu.system.heartbeat import (
            HeartbeatCollector,
            HeartbeatReport,
        )
        from parameter_server_tpu.system.recovery import RecoveryCoordinator

        co = ElasticCoordinator(make_worker, num_data=2, num_server=2)
        w = co.start()
        for b in batches(3):
            w.collect(w.process_minibatch(b))
        want = w.weights_dense()
        w.wipe_server_shard(1)

        c = HeartbeatCollector(timeout=5.0)
        c.report("S1", HeartbeatReport())
        rc = RecoveryCoordinator(c)
        co.attach_recovery(rc)
        assert rc.check(now=c._last_seen["S1"] + 6) == ["S1"]
        np.testing.assert_allclose(co.worker.weights_dense(), want, atol=1e-6)

    def test_middle_rank_death_emits_only_the_dead_node(self, mesh8):
        """Regression: killing rank 0 of 2 must broadcast exactly one
        remove for S0 — not an inverted stream claiming the SURVIVOR
        left (the positional renumbering inside the shrink is not a
        membership change)."""
        def mk(mesh):
            conf = Config()
            conf.penalty = PenaltyConfig(type="l1", lambda_=[0.01])
            conf.learning_rate = LearningRateConfig(
                type="decay", alpha=0.5, beta=1.0
            )
            conf.async_sgd = SGDConfig(
                algo="ftrl", minibatch=256, num_slots=NUM_SLOTS
            )
            return AsyncSGDWorker(conf, mesh=mesh)

        events = []
        co = ElasticCoordinator(mk, num_data=2, num_server=2)
        co.subscribe_nodes(lambda ev, n: events.append((ev, n.id)))
        w = co.start()
        w.collect(w.process_minibatch(batches(1)[0]))
        assert co.handle_server_death(0) == "resharded"
        assert events == [("remove", "S0")]

    def test_recovery_in_place_emits_no_events(self, mesh8):
        events = []
        co = ElasticCoordinator(make_worker, num_data=2, num_server=2)
        co.subscribe_nodes(lambda ev, n: events.append((ev, n.id)))
        w = co.start()
        w.collect(w.process_minibatch(batches(1)[0]))
        w.wipe_server_shard(0)
        assert co.handle_server_death(0) == "recovered"
        assert events == []

    def test_saved_model_header_uses_configured_modulus(self, mesh8, tmp_path):
        """Regression: the '#hashed <n>' header must carry the hashing
        modulus (configured count), not the padded table size — model
        evaluation rebuilds the key->slot map from it."""
        co = ElasticCoordinator(make_worker, num_data=2, num_server=2)
        w = co.start()
        w.collect(w.process_minibatch(batches(1)[0]))
        paths = w.save_model(str(tmp_path / "m"))
        header = open(paths[0]).readline().split()
        assert header == ["#hashed", str(NUM_SLOTS)]

    def test_aux_runtime_survives_resize(self, mesh8):
        """Regression: heartbeat/recovery must not go deaf after a
        membership change — resize carries the LIVE aux runtime over:
        same collector state, registered samplers, recovery handlers and
        poller; decommissioned slots are forgotten (no false deaths)."""
        co = ElasticCoordinator(make_worker, num_data=2, num_server=2)
        w = co.start()
        po = Postoffice.instance()
        aux = po.start_aux(heartbeat_timeout=7.5, print_fn=lambda s: None)
        aux.register("W0")
        deaths = []
        aux.coordinator.on_server_dead(deaths.append)
        w.collect(w.process_minibatch(batches(1)[0]))

        co.remove_server()  # 2x2 -> 2x1: S1 decommissioned
        po2 = Postoffice.instance()
        assert po2.aux is aux  # the same live object, not a blank copy
        assert aux.coordinator._handlers["server"] == [deaths.append]
        assert po2.aux.info("W0") is not None  # samplers carried over
        po2.beat("W0")  # still a live no-op-free path
        # the decommissioned S1 must NOT be declared dead later...
        aux.collector.report("S0", __import__(
            "parameter_server_tpu.system.heartbeat", fromlist=["HeartbeatReport"]
        ).HeartbeatReport())
        late = aux.collector._last_seen["S0"] + 100
        handled = aux.coordinator.check(now=late)
        assert "S0" in handled and "S1" not in handled  # ...but S0 can
        assert deaths == ["S0"]

    def test_single_server_death_rebuilds_slot_with_add_event(self, mesh8):
        """Regression: a 1-server cluster cannot shrink — the dead slot is
        rebuilt empty and subscribers must see remove THEN add for S0."""
        def mk(mesh):
            conf = Config()
            conf.penalty = PenaltyConfig(type="l1", lambda_=[0.01])
            conf.learning_rate = LearningRateConfig(
                type="decay", alpha=0.5, beta=1.0
            )
            conf.async_sgd = SGDConfig(
                algo="ftrl", minibatch=256, num_slots=NUM_SLOTS
            )
            return AsyncSGDWorker(conf, mesh=mesh)

        events = []
        co = ElasticCoordinator(mk, num_data=2, num_server=1)
        co.subscribe_nodes(lambda ev, n: events.append((ev, n.id)))
        w = co.start()
        w.collect(w.process_minibatch(batches(1)[0]))
        assert co.handle_server_death(0) == "resharded"
        assert events == [("remove", "S0"), ("add", "S0")]
        assert co.num_server == 1
        co.worker.collect(co.worker.process_minibatch(batches(1, seed0=5)[0]))


class TestResizeUnderLoad:
    def test_streaming_minibatches_across_resizes_loses_no_step(self, mesh8):
        """VERDICT r2 #6: a resize happens while minibatches are
        actively streaming — every step before, between and after the
        two resizes (2x2 -> 2x1 -> 3x2) must land, the example count
        must be exact, learning must survive (loss improves end to
        end), and the measured stop-the-world pause must be recorded
        and rendered on the dashboard."""
        co = ElasticCoordinator(make_worker, num_data=2, num_server=2)
        w = co.start()
        po = Postoffice.instance()
        aux = po.start_aux(heartbeat_timeout=60.0, print_fn=lambda s: None)

        stream = iter(batches(9))
        losses = []
        phase_examples = []  # per-phase counts (a new worker object's
        # progress restarts at 0 after each resize; the TABLE state is
        # what migrates)

        def drive(n):
            nonlocal w
            start = w.progress.num_examples_processed
            for _ in range(n):
                prog = w.collect(w.process_minibatch(next(stream)))
                losses.append(prog.objective[-1] / 256)
            phase_examples.append(w.progress.num_examples_processed - start)

        drive(3)
        before1 = w.weights_dense()[:NUM_SLOTS]
        w = co.resize(num_data=2, num_server=1)   # shrink mid-stream
        np.testing.assert_allclose(
            w.weights_dense()[:NUM_SLOTS], before1, atol=1e-6
        )
        drive(3)
        before2 = w.weights_dense()[:NUM_SLOTS]
        w = co.resize(num_data=3, num_server=2)   # grow mid-stream
        np.testing.assert_allclose(
            w.weights_dense()[:NUM_SLOTS], before2, atol=1e-6
        )
        drive(3)

        # every step landed: 3 per phase, none dropped by the resizes;
        # the learned table migrated intact through both resizes (the
        # allclose checks above), so no training was lost
        assert phase_examples == [3 * 256] * 3
        assert len(losses) == 9
        assert len(co.resize_history) == 2
        for rec in co.resize_history:
            assert rec["pause_s"] > 0
        assert co.resize_history[0]["old"] == (2, 2)
        assert co.resize_history[0]["new"] == (2, 1)
        report = aux.dashboard.report()
        assert "elastic resize 2x2 -> 2x1: stop-the-world" in report
        assert "elastic resize 2x1 -> 3x2" in report
