"""Flash attention kernels (ops/flash_attention.py).

The Pallas kernels run in interpret mode on the CPU test mesh (identical
program, no Mosaic compile), compared against the XLA reference path and
dense attention — forward values, logsumexp, and all three gradients —
including unaligned shapes (block padding) and nonzero global offsets
(the ring-attention chunk case). Ring integration: impl="flash" must
match dense attention through the chunk-merge on the 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_tpu.models.attention import (
    dense_attention,
    dense_mha,
    ring_attention,
)
from parameter_server_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_ref,
    flash_mha,
)
from parameter_server_tpu.parallel.mesh import make_mesh

# Promoted to the slow tier (PR 2, per the PR-1 ROADMAP note): the
# shard_map-shim unlock made the full 'not slow' suite overrun the
# 870s tier-1 budget on a 2-core host. Run via `pytest -m slow`.
pytestmark = pytest.mark.slow


def _rand(shape, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=shape), jnp.float32
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("qo,ko", [(0, 0), (64, 0), (0, 128)])
def test_flash_kernel_matches_ref(causal, qo, ko):
    # deliberately unaligned: exercises block and lane padding
    bh, sq, sk, d = 3, 200, 264, 48
    q, k, v = _rand((bh, sq, d), 1), _rand((bh, sk, d), 2), _rand((bh, sk, d), 3)
    o_ref, lse_ref = flash_attention(
        q, k, v, causal=causal, q_offset=qo, k_offset=ko,
        use_pallas=False, with_lse=True,
    )
    o_pal, lse_pal = flash_attention(
        q, k, v, causal=causal, q_offset=qo, k_offset=ko,
        use_pallas=True, interpret=True, with_lse=True,
    )
    np.testing.assert_allclose(o_ref, o_pal, atol=2e-5, rtol=1e-5)
    np.testing.assert_allclose(lse_ref, lse_pal, atol=2e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize(
    "sq,sk,d",
    [
        (200, 136, 48),  # unaligned: block/lane padding path
        # d_head 128 — every MFU-push LM config's head size
        # (mfu_d1024/mfu_d2048/h4 run d_model/n_heads = 128); a d=128
        # regression must not surface only on-chip mid-capture-window
        (160, 192, 128),
    ],
)
def test_flash_kernel_gradients(causal, sq, sk, d):
    bh = 2
    q, k, v = _rand((bh, sq, d), 1), _rand((bh, sk, d), 2), _rand((bh, sk, d), 3)
    w = _rand((bh, sq, d), 4)

    def make_loss(use_pallas):
        def loss(q, k, v):
            out = flash_attention(
                q, k, v, causal=causal, q_offset=8, k_offset=0,
                use_pallas=use_pallas, interpret=use_pallas,
            )
            return jnp.sum(out * w)

        return jax.grad(loss, argnums=(0, 1, 2))

    for a, b in zip(make_loss(False)(q, k, v), make_loss(True)(q, k, v)):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4)


def test_flash_kernel_d128_fwd():
    """d=128 forward parity (grad coverage lives in the parametrized
    test_flash_kernel_gradients shape (160, 192, 128))."""
    bh, sq, sk, d = 2, 160, 192, 128
    q, k, v = _rand((bh, sq, d), 1), _rand((bh, sk, d), 2), _rand((bh, sk, d), 3)
    o_ref = flash_attention(q, k, v, causal=True, use_pallas=False)
    o_pal = flash_attention(
        q, k, v, causal=True, use_pallas=True, interpret=True
    )
    np.testing.assert_allclose(o_ref, o_pal, atol=2e-5, rtol=1e-5)


def test_flash_ref_matches_dense():
    bh, s, d = 2, 96, 32
    q, k, v = _rand((bh, s, d), 1), _rand((bh, s, d), 2), _rand((bh, s, d), 3)
    for causal in (False, True):
        o, _ = flash_attention_ref(
            q, k, v, jnp.int32(0), jnp.int32(0), causal=causal
        )
        np.testing.assert_allclose(
            o, dense_attention(q, k, v, causal=causal), atol=2e-5, rtol=1e-5
        )


def test_flash_fully_masked_chunk_is_zero_with_neg_lse():
    # a kv chunk entirely AFTER the queries (ring hop k_offset > q rows):
    # every row is masked — out must be exactly 0 and lse ~ -inf so the
    # chunk-merge weight underflows to zero
    bh, s, d = 1, 64, 32
    q, k, v = _rand((bh, s, d), 1), _rand((bh, s, d), 2), _rand((bh, s, d), 3)
    out, lse = flash_attention(
        q, k, v, causal=True, q_offset=0, k_offset=1024,
        use_pallas=True, interpret=True, with_lse=True,
    )
    assert float(jnp.max(jnp.abs(out))) == 0.0
    assert float(jnp.max(lse)) < -1e29


def test_flash_mha_matches_dense_mha():
    b, s, h, nh = 2, 80, 64, 4
    q, k, v = _rand((b, s, h), 1), _rand((b, s, h), 2), _rand((b, s, h), 3)
    for causal in (False, True):
        got = flash_mha(
            q, k, v, nh, causal=causal, use_pallas=True, interpret=True
        )
        np.testing.assert_allclose(
            got, dense_mha(q, k, v, nh, causal=causal), atol=2e-5, rtol=1e-5
        )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_dense(causal):
    mesh = make_mesh(num_data=8, num_server=1)
    b, s, h = 2, 128, 32
    q, k, v = _rand((b, s, h), 1), _rand((b, s, h), 2), _rand((b, s, h), 3)
    got = ring_attention(
        q, k, v, mesh=mesh, axis="data", causal=causal, impl="flash"
    )
    np.testing.assert_allclose(
        got, dense_attention(q, k, v, causal=causal), atol=2e-5, rtol=1e-5
    )


def test_ring_flash_gradients_match_dense():
    mesh = make_mesh(num_data=4, num_server=1)
    b, s, h = 1, 64, 16
    q, k, v = _rand((b, s, h), 1), _rand((b, s, h), 2), _rand((b, s, h), 3)
    w = _rand((b, s, h), 4)

    def loss_ring(q, k, v):
        out = ring_attention(
            q, k, v, mesh=mesh, axis="data", causal=True, impl="flash"
        )
        return jnp.sum(out * w)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) * w)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gr, gd):
        np.testing.assert_allclose(a, b_, atol=5e-5, rtol=1e-4)


def test_flash_kernel_gradients_through_lse():
    # exercises the dlse cotangent path IN THE PALLAS KERNELS (the ring
    # merge differentiates through lse; the c = delta - dlse folding in
    # the backward kernels must carry it)
    bh, s, d = 2, 136, 32
    q, k, v = _rand((bh, s, d), 1), _rand((bh, s, d), 2), _rand((bh, s, d), 3)
    w = _rand((bh, s, d), 4)
    wl = _rand((bh, s), 5)

    def make_loss(use_pallas):
        def loss(q, k, v):
            out, lse = flash_attention(
                q, k, v, causal=True, use_pallas=use_pallas,
                interpret=use_pallas, with_lse=True,
            )
            return jnp.sum(out * w) + jnp.sum(lse * wl)

        return jax.grad(loss, argnums=(0, 1, 2))

    for a, b in zip(make_loss(False)(q, k, v), make_loss(True)(q, k, v)):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4)


def test_ring_flash_with_interpret_kernel_on_mesh():
    # the pallas kernel itself (interpret mode) under shard_map: one hop
    # per device with nonzero traced offsets
    mesh = make_mesh(num_data=2, num_server=1)
    b, s, h = 1, 256, 32
    q, k, v = _rand((b, s, h), 1), _rand((b, s, h), 2), _rand((b, s, h), 3)
    got = ring_attention(
        q, k, v, mesh=mesh, axis="data", causal=True, impl="flash",
        use_pallas=True, interpret=True,
    )
    np.testing.assert_allclose(
        got, dense_attention(q, k, v, causal=True), atol=2e-5, rtol=1e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_zigzag_ring_matches_dense(causal):
    from parameter_server_tpu.models.attention import zigzag_permutation

    mesh = make_mesh(num_data=4, num_server=1)
    n = 4
    b, s, h = 2, 128, 32
    q, k, v = _rand((b, s, h), 1), _rand((b, s, h), 2), _rand((b, s, h), 3)
    perm = zigzag_permutation(s, n)
    inv = np.argsort(perm)
    got_z = ring_attention(
        q[:, perm], k[:, perm], v[:, perm], mesh=mesh, axis="data",
        causal=causal, impl="zigzag",
    )
    got = np.asarray(got_z)[:, inv]
    np.testing.assert_allclose(
        got, dense_attention(q, k, v, causal=causal), atol=2e-5, rtol=1e-5
    )


def test_zigzag_gradients_match_dense():
    from parameter_server_tpu.models.attention import zigzag_permutation

    mesh = make_mesh(num_data=2, num_server=1)
    b, s, h = 1, 64, 16
    q, k, v = _rand((b, s, h), 1), _rand((b, s, h), 2), _rand((b, s, h), 3)
    w = _rand((b, s, h), 4)
    perm = zigzag_permutation(s, 2)
    inv = np.argsort(perm)

    def loss_z(q, k, v):
        out = ring_attention(
            q[:, perm], k[:, perm], v[:, perm], mesh=mesh, axis="data",
            causal=True, impl="zigzag",
        )
        return jnp.sum(out[:, inv] * w)

    def loss_d(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) * w)

    gz = jax.grad(loss_z, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gz, gd):
        np.testing.assert_allclose(a, b_, atol=5e-5, rtol=1e-4)


def test_zigzag_permutation_roundtrip_and_validation():
    from parameter_server_tpu.models.attention import zigzag_permutation

    perm = zigzag_permutation(48, 3)
    assert sorted(perm.tolist()) == list(range(48))
    # device 0 must hold half-blocks 0 and 2n-1 (here 0 and 5)
    assert perm[:16].tolist() == list(range(0, 8)) + list(range(40, 48))
    with pytest.raises(ValueError, match="divide"):
        zigzag_permutation(50, 3)


def dense_swa(q, k, v, window):
    """Dense sliding-window reference: causal + (q_pos - k_pos) < window."""
    s = jnp.einsum("bqh,bkh->bqk", q, k) / jnp.sqrt(q.shape[-1])
    n = q.shape[1]
    pos = jnp.arange(n)
    keep = (pos[:, None] >= pos[None, :]) & (
        pos[:, None] - pos[None, :] < window
    )
    s = jnp.where(keep[None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkh->bqh", p, v)


@pytest.mark.parametrize("window", [1, 16, 100])
def test_sliding_window_kernel_matches_dense(window):
    bh, s, d = 2, 200, 48  # unaligned: exercises padding + block skip
    q, k, v = _rand((bh, s, d), 1), _rand((bh, s, d), 2), _rand((bh, s, d), 3)
    want = dense_swa(q, k, v, window)
    for up in (False, True):
        got = flash_attention(
            q, k, v, causal=True, window=window, use_pallas=up, interpret=up
        )
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-5)


def test_sliding_window_gradients():
    bh, s, d = 2, 136, 32
    q, k, v = _rand((bh, s, d), 1), _rand((bh, s, d), 2), _rand((bh, s, d), 3)
    w = _rand((bh, s, d), 4)

    def make_loss(up):
        def loss(q, k, v):
            out = flash_attention(
                q, k, v, causal=True, window=24, use_pallas=up, interpret=up
            )
            return jnp.sum(out * w)

        return jax.grad(loss, argnums=(0, 1, 2))

    def loss_dense(q, k, v):
        return jnp.sum(dense_swa(q, k, v, 24) * w)

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for up in (False, True):
        for a, b in zip(make_loss(up)(q, k, v), gd):
            np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4)


@pytest.mark.parametrize("impl", ["flash", "zigzag"])
def test_sliding_window_on_ring(impl):
    from parameter_server_tpu.models.attention import zigzag_permutation

    mesh = make_mesh(num_data=4, num_server=1)
    b, s, h, window = 2, 128, 32, 40
    q, k, v = _rand((b, s, h), 1), _rand((b, s, h), 2), _rand((b, s, h), 3)
    want = np.asarray(dense_swa(q, k, v, window))
    if impl == "zigzag":
        perm = zigzag_permutation(s, 4)
        got = np.asarray(
            ring_attention(
                q[:, perm], k[:, perm], v[:, perm], mesh=mesh, axis="data",
                causal=True, impl="zigzag", window=window,
            )
        )[:, np.argsort(perm)]
    else:
        got = np.asarray(
            ring_attention(
                q, k, v, mesh=mesh, axis="data", causal=True, impl="flash",
                window=window,
            )
        )
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-5)


def test_window_validation():
    x = _rand((1, 16, 8), 0)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(x, x, x, causal=False, window=4)
    with pytest.raises(ValueError, match=">= 1"):
        flash_attention(x, x, x, causal=True, window=0)
    mesh = make_mesh(num_data=2, num_server=1)
    with pytest.raises(ValueError, match="flash"):
        ring_attention(
            x, x, x, mesh=mesh, axis="data", causal=True, window=4
        )


@pytest.mark.parametrize("n_kv_heads", [1, 2])
def test_gqa_matches_expanded_dense(n_kv_heads):
    # grouped-query attention == dense MHA with the K/V heads repeated
    b, s, nh, dh = 2, 64, 4, 16
    q = _rand((b, s, nh * dh), 1)
    k = _rand((b, s, n_kv_heads * dh), 2)
    v = _rand((b, s, n_kv_heads * dh), 3)
    got = flash_mha(
        q, k, v, nh, causal=True, n_kv_heads=n_kv_heads,
        use_pallas=True, interpret=True,
    )
    # expand kv to full heads for the dense reference
    rep = nh // n_kv_heads

    def expand(x):
        x = x.reshape(b, s, n_kv_heads, dh)
        return np.repeat(np.asarray(x), rep, axis=2).reshape(b, s, nh * dh)

    want = dense_mha(q, jnp.asarray(expand(k)), jnp.asarray(expand(v)),
                     nh, causal=True)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-5)


def test_gqa_rejects_nondivisible():
    x = _rand((1, 16, 12), 0)
    with pytest.raises(ValueError, match="divide"):
        flash_mha(x, x, x, 4, n_kv_heads=3)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_matches_dense(causal):
    from parameter_server_tpu.models.attention import ulysses_attention

    mesh = make_mesh(num_data=4, num_server=1)
    b, s, nh, h = 2, 64, 4, 32
    q, k, v = _rand((b, s, h), 1), _rand((b, s, h), 2), _rand((b, s, h), 3)
    got = ulysses_attention(
        q, k, v, mesh=mesh, axis="data", n_heads=nh, causal=causal,
        impl="flash", use_pallas=True, interpret=True,
    )
    np.testing.assert_allclose(
        got, dense_mha(q, k, v, nh, causal=causal), atol=2e-5, rtol=1e-5
    )


def test_ulysses_flash_gradients_match_dense():
    from parameter_server_tpu.models.attention import ulysses_attention

    mesh = make_mesh(num_data=2, num_server=1)
    b, s, nh, h = 1, 32, 2, 16
    q, k, v = _rand((b, s, h), 1), _rand((b, s, h), 2), _rand((b, s, h), 3)
    w = _rand((b, s, h), 4)

    def loss_u(q, k, v):
        out = ulysses_attention(
            q, k, v, mesh=mesh, axis="data", n_heads=nh, causal=True,
            impl="flash",
        )
        return jnp.sum(out * w)

    def loss_d(q, k, v):
        return jnp.sum(dense_mha(q, k, v, nh, causal=True) * w)

    gu = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gu, gd):
        np.testing.assert_allclose(a, b_, atol=5e-5, rtol=1e-4)


def test_ulysses_flash_sliding_window():
    from parameter_server_tpu.models.attention import ulysses_attention

    mesh = make_mesh(num_data=2, num_server=1)
    b, s, nh, h, window = 1, 64, 2, 16, 12
    q, k, v = _rand((b, s, h), 1), _rand((b, s, h), 2), _rand((b, s, h), 3)
    got = ulysses_attention(
        q, k, v, mesh=mesh, axis="data", n_heads=nh, causal=True,
        impl="flash", window=window,
    )
    # dense SWA per head
    dh = h // nh
    qh = np.asarray(q).reshape(b, s, nh, dh)
    kh = np.asarray(k).reshape(b, s, nh, dh)
    vh = np.asarray(v).reshape(b, s, nh, dh)
    want = np.zeros_like(qh)
    for hh in range(nh):
        want[:, :, hh] = np.asarray(
            dense_swa(
                jnp.asarray(qh[:, :, hh]), jnp.asarray(kh[:, :, hh]),
                jnp.asarray(vh[:, :, hh]), window,
            )
        )
    np.testing.assert_allclose(
        got, want.reshape(b, s, h), atol=2e-5, rtol=1e-5
    )
    with pytest.raises(ValueError, match="flash"):
        ulysses_attention(
            q, k, v, mesh=mesh, axis="data", n_heads=nh, causal=True,
            window=window,
        )


def test_ulysses_rejects_bad_impl_and_stray_flags():
    from parameter_server_tpu.models.attention import ulysses_attention

    mesh = make_mesh(num_data=2, num_server=1)
    x = _rand((1, 16, 8), 0)
    with pytest.raises(ValueError, match="impl"):
        ulysses_attention(
            x, x, x, mesh=mesh, axis="data", n_heads=2, impl="dense"
        )
    with pytest.raises(ValueError, match="use_pallas"):
        ulysses_attention(
            x, x, x, mesh=mesh, axis="data", n_heads=2, interpret=True
        )


def test_lm_ring_flash_mode_matches_ring():
    from parameter_server_tpu.models.transformer import (
        LMConfig,
        init_lm,
        lm_forward,
    )

    mesh = make_mesh(num_data=4, num_server=1)
    cfg_r = LMConfig(vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64)
    cfg_f = LMConfig(
        vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        attention="ring_flash",
    )
    params = init_lm(jax.random.PRNGKey(0), cfg_r)
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(2, 64)), jnp.int32
    )
    lr = lm_forward(params, toks, cfg_r, mesh)
    lf = lm_forward(params, toks, cfg_f, mesh)
    np.testing.assert_allclose(lr, lf, atol=2e-5, rtol=1e-5)
