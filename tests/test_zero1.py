"""ZeRO-1 optimizer-state sharding (zero1_shard_opt_state): placement,
per-device memory reduction, trajectory identity vs replicated state,
and composition with Megatron tensor parallelism. Extension beyond the
reference (its optimizer state lives sharded on the servers by design;
this brings the same property to the replicated-model LM path)."""

import jax
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from parameter_server_tpu.models.transformer import (
    LMConfig,
    init_lm,
    lm_loss,
    shard_lm_params,
    shard_tokens,
    zero1_shard_opt_state,
)


@pytest.fixture(scope="module")
def cfg():
    return LMConfig(vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64)


def _adam_state(params, mesh):
    tx = optax.adam(1e-2)
    opt = tx.init(jax.device_put(params, NamedSharding(mesh, P())))
    return tx, opt


class TestZero1Placement:
    def test_moments_shard_over_data_axis(self, mesh8, cfg):
        params = init_lm(jax.random.PRNGKey(0), cfg)
        tx, opt = _adam_state(params, mesh8)
        z = zero1_shard_opt_state(opt, mesh8, "data")
        n = mesh8.shape["data"]
        mu = z[0].mu["emb"]  # [32, 32]: 32 % 4 == 0 -> sharded
        assert "data" in jax.tree.leaves(
            [list(mu.sharding.spec)]
        ), mu.sharding
        # per-device bytes shrink by the axis size
        assert mu.addressable_shards[0].data.nbytes == mu.nbytes // n
        # scalar count stays replicated but mesh-committed
        count = z[0].count
        assert count.sharding.is_fully_replicated
        assert isinstance(count.sharding, NamedSharding)

    def test_composes_with_tensor_parallel(self, mesh8, cfg):
        params = shard_lm_params(
            init_lm(jax.random.PRNGKey(0), cfg), mesh8, "server"
        )
        tx = optax.adam(1e-2)
        opt = tx.init(params)  # moments inherit the Megatron placement
        z = zero1_shard_opt_state(opt, mesh8, "data")
        mu = z[0].mu["l0/wq"]  # param sharded P(None, "server")
        spec = list(mu.sharding.spec) + [None] * (
            mu.ndim - len(mu.sharding.spec)
        )
        assert "server" in spec and "data" in spec, spec

    def test_trivial_data_axis_preserves_tp_placement(self, cfg):
        """num_data=1 (all-TP mesh) + --zero1 must NOT gather the
        Megatron-sharded moments back to replicated — that would
        multiply optimizer memory by the server-axis size exactly when
        the user asked to shard it."""
        from parameter_server_tpu.parallel import mesh as meshlib
        from parameter_server_tpu.system.postoffice import Postoffice

        Postoffice.reset()
        m = meshlib.make_mesh(num_data=1, num_server=8)
        params = shard_lm_params(init_lm(jax.random.PRNGKey(0), cfg), m,
                                 "server")
        tx = optax.adam(1e-2)
        z = zero1_shard_opt_state(tx.init(params), m, "data")
        mu = z[0].mu["l0/wq"]
        assert "server" in list(mu.sharding.spec), mu.sharding
        assert not mu.sharding.is_fully_replicated
        # scalars still come back committed
        assert isinstance(z[0].count.sharding, NamedSharding)
        Postoffice.reset()

    def test_indivisible_leaves_stay_replicated(self, mesh8):
        # 3x5: no dim divides the 4-way data axis -> replicated, committed
        x = jax.device_put(
            np.zeros((3, 5), np.float32), NamedSharding(mesh8, P())
        )
        z = zero1_shard_opt_state({"w": x}, mesh8, "data")
        assert z["w"].sharding.is_fully_replicated


class TestZero1Training:
    def test_trajectory_matches_replicated(self, mesh8, cfg):
        """The sharded-moment step must produce the same params as the
        replicated-moment step — placement, not math."""
        params = jax.device_put(
            init_lm(jax.random.PRNGKey(1), cfg),
            NamedSharding(mesh8, P()),
        )
        tx = optax.adam(1e-2)

        @jax.jit
        def step(p, opt, toks):
            loss, g = jax.value_and_grad(lm_loss)(p, toks, cfg, mesh8, "data")
            up, opt = tx.update(g, opt, p)
            return optax.apply_updates(p, up), opt, loss

        rng = np.random.default_rng(0)
        toks = [
            shard_tokens(
                rng.integers(0, cfg.vocab, (2, 64)).astype(np.int32), mesh8
            )
            for _ in range(4)
        ]
        p_a, opt_a = params, tx.init(params)
        p_b = params
        opt_b = zero1_shard_opt_state(tx.init(params), mesh8, "data")
        for t in toks:
            p_a, opt_a, _ = step(p_a, opt_a, t)
            p_b, opt_b, _ = step(p_b, opt_b, t)
        for k in p_a:
            np.testing.assert_allclose(
                np.asarray(p_a[k]), np.asarray(p_b[k]), atol=1e-6,
                err_msg=k,
            )
        # the moments stayed sharded through the jitted updates
        mu = opt_b[0].mu["emb"]
        assert not mu.sharding.is_fully_replicated
