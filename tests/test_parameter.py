"""Parameter-layer integration tests on the virtual 8-device mesh.

Mirrors the reference's multi-node binaries: kv_vector_ps.cc (push/pull with
channels), kv_vector_buffer_ps.cc (buffered merges), kv_map_ps.cc (entry
updaters), kv_layer_ps.cc (layer push/pull + updater), aggregation_ps.cc
(additive aggregation across pushes).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_tpu.ops import kv_ops
from parameter_server_tpu.parameter.kv_layer import KVLayer, SGDUpdater
from parameter_server_tpu.parameter.kv_map import AddEntry, AssignEntry, KVMap
from parameter_server_tpu.parameter.kv_vector import KVVector
from parameter_server_tpu.parameter.parameter import KeyDirectory, pad_slots
from parameter_server_tpu.system.postoffice import Postoffice


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    yield
    Postoffice.reset()


class TestKeyDirectory:
    def test_exact_hits_and_misses(self):
        d = KeyDirectory(8, keys=np.array([2, 5, 9, 100]))
        slots = d.slots(np.array([5, 2, 7, 100]))
        np.testing.assert_array_equal(slots, [1, 0, 8, 3])  # 7 -> sentinel 8

    def test_hashed_stable_in_range(self):
        d = KeyDirectory(16, hashed=True)
        keys = np.arange(1000, dtype=np.int64)
        s1, s2 = d.slots(keys), d.slots(keys)
        np.testing.assert_array_equal(s1, s2)
        assert s1.min() >= 0 and s1.max() < 16

    def test_pad_slots(self):
        assert pad_slots(10, 4) == 12
        assert pad_slots(8, 4) == 8


class TestKvOps:
    def test_pull_matches_numpy(self, mesh8):
        from parameter_server_tpu.parallel import mesh as meshlib

        p, k = 32, 3
        table = jnp.arange(p * k, dtype=jnp.float32).reshape(p, k)
        table = kv_ops.jax.device_put(table, meshlib.table_sharding(mesh8))
        idx = jnp.array([0, 5, 31, 16, 5], dtype=jnp.int32)
        out = kv_ops.pull(table, idx, mesh=mesh8, batch_sharded=False)
        np.testing.assert_allclose(
            np.asarray(out), np.arange(p * k).reshape(p, k)[np.asarray(idx)]
        )

    def test_pull_sentinel_is_zero(self, mesh8):
        from parameter_server_tpu.parallel import mesh as meshlib

        table = kv_ops.jax.device_put(
            jnp.ones((16, 2), jnp.float32), meshlib.table_sharding(mesh8)
        )
        out = kv_ops.pull(
            table, jnp.array([16, 3], dtype=jnp.int32), mesh=mesh8, batch_sharded=False
        )
        np.testing.assert_allclose(np.asarray(out), [[0, 0], [1, 1]])

    def test_push_scatter_add_with_duplicates(self, mesh8):
        from parameter_server_tpu.parallel import mesh as meshlib

        table = kv_ops.jax.device_put(
            jnp.zeros((16, 1), jnp.float32), meshlib.table_sharding(mesh8)
        )
        idx = jnp.array([2, 2, 9, 15], dtype=jnp.int32)
        vals = jnp.array([[1.0], [2.0], [3.0], [4.0]])
        out = kv_ops.push(table, idx, vals, mesh=mesh8, batch_sharded=False)
        expect = np.zeros((16, 1))
        expect[2] = 3.0
        expect[9] = 3.0
        expect[15] = 4.0
        np.testing.assert_allclose(np.asarray(out), expect)


class TestKVVector:
    def test_push_pull_roundtrip(self, mesh8):
        kv = KVVector(mesh=mesh8, k=2, num_slots=64, hashed=False)
        keys = np.array([3, 17, 40, 99], dtype=np.int64)
        kv.set_keys(0, keys)
        vals = np.arange(8, dtype=np.float32).reshape(4, 2)
        ts = kv.push(kv.request(channel=0), keys=keys, values=vals)
        kv.wait(ts)
        out = kv.values(0, keys)
        np.testing.assert_allclose(out, vals)

    def test_push_aggregates(self, mesh8):
        # aggregation_ps.cc: repeated pushes sum
        kv = KVVector(mesh=mesh8, k=1, num_slots=32, hashed=False)
        keys = np.array([1, 5, 9], dtype=np.int64)
        kv.set_keys(0, keys)
        for _ in range(3):
            ts = kv.push(kv.request(channel=0), keys=keys, values=np.ones((3, 1), np.float32))
            kv.wait(ts)
        np.testing.assert_allclose(kv.values(0, keys), 3 * np.ones((3, 1)))

    def test_channels_isolated(self, mesh8):
        kv = KVVector(mesh=mesh8, k=1, num_slots=32, hashed=False)
        k0 = np.array([1, 2], dtype=np.int64)
        k1 = np.array([1, 2], dtype=np.int64)
        kv.set_keys(0, k0)
        kv.set_keys(1, k1)
        kv.wait(kv.push(kv.request(channel=0), keys=k0, values=np.full((2, 1), 7.0, np.float32)))
        np.testing.assert_allclose(kv.values(1, k1), np.zeros((2, 1)))
        np.testing.assert_allclose(kv.values(0, k0), np.full((2, 1), 7.0))

    def test_buffered_push(self, mesh8):
        # kv_vector_buffer_ps.cc: buffer_value stages instead of merging
        kv = KVVector(mesh=mesh8, k=1, num_slots=32, hashed=False, buffer_value=True)
        keys = np.array([4, 8], dtype=np.int64)
        kv.set_keys(0, keys)
        task = kv.request(channel=0, ts=5)
        ts = kv.push(task, keys=keys, values=np.ones((2, 1), np.float32))
        kv.wait(ts)
        # live table untouched, buffer holds the push
        np.testing.assert_allclose(kv.values(0, keys), np.zeros((2, 1)))
        buf = np.asarray(kv.buffer(0, 5))
        assert buf[kv.channel(0).directory.slots(keys)].sum() == 2.0
        kv.clear_buffer(0, 5)
        assert kv.buffer(0, 5) is None

    def test_write_to_file(self, mesh8, tmp_path):
        kv = KVVector(mesh=mesh8, k=1, num_slots=16, hashed=False)
        keys = np.array([2, 11], dtype=np.int64)
        kv.set_keys(0, keys)
        kv.wait(kv.push(kv.request(0), keys=keys, values=np.array([[1.5], [0.0]], np.float32)))
        path = tmp_path / "model.txt"
        kv.write_to_file(str(path), ch=0)
        lines = path.read_text().strip().splitlines()
        assert len(lines) == 1 and lines[0].startswith("2\t")


class TestKVMap:
    def test_assign_entry(self, mesh8):
        m = KVMap(AssignEntry(), mesh=mesh8, k=1, num_slots=32, keys=np.array([5, 10, 20]))
        ts = m.push(m.request(), np.array([5, 20]), np.array([[1.0], [2.0]]))
        m.wait(ts)
        np.testing.assert_allclose(m.values(np.array([5, 10, 20])), [[1.0], [0.0], [2.0]])

    def test_add_entry_accumulates(self, mesh8):
        m = KVMap(AddEntry(), mesh=mesh8, k=2, num_slots=32, keys=np.array([1, 2]))
        for _ in range(2):
            m.wait(m.push(m.request(), np.array([1, 2]), np.ones((2, 2), np.float32)))
        np.testing.assert_allclose(m.values(np.array([1, 2])), 2 * np.ones((2, 2)))

    def test_replica_roundtrip(self, mesh8):
        m = KVMap(AssignEntry(), mesh=mesh8, k=1, num_slots=16, keys=np.array([3]))
        m.wait(m.push(m.request(), np.array([3]), np.array([[9.0]])))
        snap = m.get_replica()
        m2 = KVMap(AssignEntry(), mesh=mesh8, k=1, num_slots=16, keys=np.array([3]))
        m2.set_replica(snap)
        np.testing.assert_allclose(m2.values(np.array([3])), [[9.0]])


class TestKVLayer:
    def test_sgd_updater_push_pull(self, mesh8):
        layer = KVLayer(partition_thr=4, updater=SGDUpdater(lr=0.5), mesh=mesh8)
        layer.init_layer("w1", (8, 2))
        grad = jnp.ones((8, 2))
        layer.wait(layer.push(layer.request(), "w1", grad))
        out = np.asarray(layer.wait_pull(layer.pull(layer.request(), "w1")))
        np.testing.assert_allclose(out, -0.5 * np.ones((8, 2)))

    def test_small_layer_replicated_large_sharded(self, mesh8):
        layer = KVLayer(partition_thr=100, mesh=mesh8)
        small = layer.init_layer("b", (3,))
        big = layer.init_layer("w", (128, 4))
        assert small.sharding.is_fully_replicated
        assert not big.sharding.is_fully_replicated

    def test_replica(self, mesh8):
        layer = KVLayer(mesh=mesh8)
        layer.init_layer("w", (4,))
        layer.wait(layer.push(layer.request(), "w", jnp.ones(4)))
        snap = layer.get_replica()
        l2 = KVLayer(mesh=mesh8)
        l2.set_replica(snap)
        np.testing.assert_allclose(np.asarray(l2["w"]), -0.01 * np.ones(4))


class TestPaddedSentinel:
    def test_exact_kvmap_drops_unknown_keys_when_padded(self, mesh8):
        """Regression: with num_slots not divisible by the server count
        (33 -> padded 34), a directory miss must map OUTSIDE every
        shard's range — unknown keys are dropped, never scattered into a
        padding slot."""
        m = KVMap(
            AssignEntry(), mesh=mesh8, k=1, num_slots=33,
            keys=np.array([5, 10]),
        )
        assert m.num_slots == 34
        m.wait(m.push(m.request(), np.array([5, 999]), np.array([[1.0], [7.0]])))
        np.testing.assert_allclose(m.values(np.array([5, 10])), [[1.0], [0.0]])
        np.testing.assert_allclose(np.asarray(m.values(np.array([999]))), [[0.0]])
