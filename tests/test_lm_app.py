"""LM app CLI (apps/lm/main.py): end-to-end train + generate in-process
on the virtual mesh, across attention modes."""

import numpy as np
import pytest

from parameter_server_tpu.apps.lm.main import main

# Promoted to the slow tier (PR 2, per the PR-1 ROADMAP note): the
# shard_map-shim unlock made the full 'not slow' suite overrun the
# 870s tier-1 budget on a 2-core host. Run via `pytest -m slow`.
pytestmark = pytest.mark.slow


def run_cli(capsys, *extra):
    rc = main(
        [
            "--steps", "30", "--seq-len", "64", "--batch", "4",
            "--d-model", "32", "--n-heads", "2", "--d-ff", "64",
            "--report-every", "10", "--prompt", "ab", "--gen-tokens", "8",
            *extra,
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    rows = [
        line.split() for line in out.splitlines()
        if line and line.split()[0].isdigit()
    ]
    losses = [float(r[1]) for r in rows]
    return out, losses


def resume_cli(capsys, ck, *extra):
    """Resume from a run_cli checkpoint (same base hyperparameters —
    repeated flags in ``extra`` override, argparse last-wins) and train
    to step 40."""
    rc = main(
        [
            "--steps", "40", "--seq-len", "64", "--batch", "4",
            "--d-model", "32", "--n-heads", "2", "--d-ff", "64",
            "--report-every", "5", "--ckpt-dir", ck, "--resume", *extra,
        ]
    )
    assert rc == 0
    return capsys.readouterr().out


def _write_corpus(tmp_path):
    """8-periodic corpus shared by the corpus-consuming CLI tests: the
    model should get well under 1 bit/byte on it fast."""
    f = tmp_path / "corpus.txt"
    f.write_bytes(b"abcdefgh" * 4096)
    return f


def test_lm_cli_trains_and_generates(mesh8, capsys):
    out, losses = run_cli(capsys)
    assert losses[-1] < losses[0], losses
    assert "--- generation" in out


def test_lm_cli_beam_and_eos(mesh8, capsys):
    out, losses = run_cli(capsys, "--beam", "3", "--eos-byte", "10")
    assert losses[-1] < losses[0], losses
    assert "beam 3, logprob" in out


def test_lm_cli_moe_generates(mesh8, capsys):
    """Round 4: MoE models generate from the CLI (the old path printed
    'generation skipped' and exited)."""
    out, _ = run_cli(capsys, "--moe-every", "2")
    assert "--- generation" in out
    assert "generation skipped" not in out


def test_lm_cli_zigzag_mode(mesh8, capsys):
    out, losses = run_cli(capsys, "--attention", "ring_zigzag")
    assert losses[-1] < losses[0], losses
    assert "--- generation" in out


def test_lm_cli_flash_window_remat(mesh8, capsys):
    out, losses = run_cli(
        capsys, "--attention", "ring_flash", "--window", "16", "--remat",
    )
    assert losses[-1] < losses[0], losses


def test_lm_cli_corpus_file(mesh8, capsys, tmp_path):
    out, losses = run_cli(capsys, "--data", str(_write_corpus(tmp_path)))
    assert losses[-1] < 0.7 * losses[0], losses


@pytest.mark.parametrize("extra", [(), ("--num-servers", "2")])
def test_lm_cli_checkpoint_resume(mesh8, capsys, tmp_path, extra):
    """Save, resume, and TRAIN ON (restored leaves must land on the
    template's training placement — replicated, or Megatron-split under
    --num-servers; ref save_model_every_n_iter parity)."""
    ck = str(tmp_path / "ck")
    run_cli(capsys, "--ckpt-dir", ck, *extra)  # saves the final step (30)
    out = resume_cli(capsys, ck, *extra)
    assert "resumed from step 30" in out
    rows = [
        line.split() for line in out.splitlines()
        if line and line.split()[0].isdigit()
    ]
    # trains exactly the REMAINING steps (35, 40 reported)
    assert [int(r[0]) for r in rows] == [35, 40], rows


def test_lm_cli_async_save_failure_fails_clean_run(mesh8, tmp_path,
                                                   monkeypatch):
    """An async checkpoint-save failure on a CLEAN run must propagate
    (the '--ckpt-dir always saves the final step' resume contract) —
    r3 advisor: sys.exc_info() read INSIDE the except handler always
    saw the drain's own RuntimeError, so the CLI swallowed the failure
    and exited 0 with the final checkpoint missing."""
    from parameter_server_tpu.parameter import replica

    def boom(self, path, host_tree):
        raise OSError("disk full (simulated)")

    monkeypatch.setattr(replica.CheckpointManager, "_write", boom)
    with pytest.raises(RuntimeError, match="async checkpoint save failed"):
        main(
            [
                "--steps", "4", "--seq-len", "64", "--batch", "2",
                "--d-model", "32", "--n-heads", "2", "--d-ff", "64",
                "--report-every", "4",
                "--ckpt-dir", str(tmp_path / "ck"),
            ]
        )


def test_lm_cli_tensor_parallel(mesh8, capsys):
    # sp x tp on one 2-D mesh: 4 data x 2 server, flash attention
    out, losses = run_cli(
        capsys, "--num-servers", "2", "--attention", "ring_flash"
    )
    assert losses[-1] < losses[0], losses
    assert "data=4 x server=2" in out
    with pytest.raises(SystemExit):  # 3 does not divide 8
        main(["--steps", "2", "--seq-len", "64", "--num-servers", "3"])


def test_lm_cli_fsdp(mesh8, capsys, tmp_path):
    """--fsdp through the CLI surface: trains, composes with --zero1 and
    --num-servers (the sharded params serve as the checkpoint restore
    template), and resume trains on from FSDP-placed leaves."""
    out, losses = run_cli(capsys, "--fsdp", "--zero1")
    assert losses[-1] < losses[0], losses
    ck = str(tmp_path / "ck")
    run_cli(capsys, "--fsdp", "--num-servers", "2", "--ckpt-dir", ck)
    out = resume_cli(capsys, ck, "--fsdp", "--num-servers", "2")
    assert "resumed from step 30" in out


def test_lm_cli_log_file(mesh8, capsys, tmp_path):
    """--log-file appends one JSON line per report interval (full
    telemetry), plus a line for every eval measured OFF the report
    grid — no eval-curve point is ever dropped from the log."""
    import json

    log = tmp_path / "train.jsonl"
    run_cli(  # report grid 10/20/30; eval grid 6/12/18/24/30
        capsys, "--log-file", str(log), "--eval-every", "6",
        "--data", str(_write_corpus(tmp_path)),
    )
    recs = [json.loads(ln) for ln in log.read_text().splitlines()]
    assert recs, "no telemetry written"
    assert [r["step"] for r in recs] == sorted(r["step"] for r in recs)
    full = [r for r in recs if "tokens_per_sec" in r]
    assert [r["step"] for r in full] == [10, 20, 30], full
    for r in full:
        assert {"step", "loss", "bits_per_byte", "wall_s"} <= set(r)
        assert r["tokens_per_sec"] > 0
    evals = [r["step"] for r in recs if "eval_loss" in r]
    assert evals == [6, 12, 18, 24, 30], evals  # off-grid ones kept


def test_lm_cli_profile_trace(mesh8, capsys, tmp_path):
    """--profile captures a device trace of the training loop (works on
    the CPU backend too — the capture machinery is backend-agnostic)."""
    prof = tmp_path / "trace"
    out, losses = run_cli(capsys, "--profile", str(prof))
    assert losses[-1] < losses[0], losses
    captured = [
        p for p in prof.rglob("*") if p.is_file()
    ]
    assert captured, "no trace artifacts written"


@pytest.mark.parametrize(
    "opt,extra",
    [
        # d-model 128: optax.adafactor only factors dims >= its
        # min_dim_size_to_factor (128), so the emb [256, 128] creates
        # the real v_row/v_col factored state — the point of the flag —
        # and resume round-trips it
        ("adafactor", ("--d-model", "128")),
        ("lion", ()),
    ],
)
def test_lm_cli_optimizer_choice(mesh8, capsys, tmp_path, opt, extra):
    """--optimizer variants train AND resume (their state trees differ
    from adam's — the checkpoint template walk must rebuild each)."""
    ck = str(tmp_path / "ck")
    out, losses = run_cli(
        capsys, "--optimizer", opt, "--ckpt-dir", ck, *extra
    )
    assert losses[-1] < losses[0], (opt, losses)
    out = resume_cli(capsys, ck, "--optimizer", opt, *extra)
    assert "resumed from step 30" in out


def test_lm_cli_a2a_mode(mesh8, capsys):
    # a2a needs n_heads divisible by the 8-device axis
    out, losses = run_cli(capsys, "--attention", "a2a", "--n-heads", "8")
    assert losses[-1] < losses[0], losses


def test_lm_cli_training_hygiene_flags(mesh8, capsys):
    """Warmup-cosine LR, global-norm clipping, and microbatch gradient
    accumulation run together and still train."""
    out, losses = run_cli(
        capsys, "--warmup", "5", "--clip-norm", "1.0", "--grad-accum", "2",
    )
    assert losses[-1] < losses[0], losses
    assert "--- generation" in out


def test_lm_cli_eval_holdout(mesh8, capsys, tmp_path):
    """--eval-every scores fixed held-out batches the model never
    trains on, printed alongside the train rows."""
    out, losses = run_cli(
        capsys, "--data", str(_write_corpus(tmp_path)), "--eval-every",
        "10",
    )
    assert "held out" in out
    evals = [
        float(line.split()[1])
        for line in out.splitlines()
        if line.strip().startswith("eval@")
    ]
    assert len(evals) >= 3, out
    assert all(np.isfinite(e) for e in evals)
    # periodic text: held-out loss must drop along with train loss
    assert evals[-1] < evals[0], evals


def test_lm_cli_resume_with_schedule_and_accum(mesh8, capsys, tmp_path):
    """The LR-schedule and accumulation counters live in the optimizer
    state: a resumed run must rebuild the same tx and restore onto it."""
    ck = str(tmp_path / "ck")
    hygiene = ["--warmup", "5", "--clip-norm", "1.0", "--grad-accum", "2"]
    run_cli(capsys, "--ckpt-dir", ck, *hygiene)
    rc = main(
        [
            "--steps", "40", "--seq-len", "64", "--batch", "4",
            "--d-model", "32", "--n-heads", "2", "--d-ff", "64",
            "--report-every", "5", "--ckpt-dir", ck, "--resume", *hygiene,
        ]
    )
    assert rc == 0
    assert "resumed from step 30" in capsys.readouterr().out


def test_lm_cli_flag_mistakes_fail_fast(mesh8):
    base = ["--steps", "5", "--seq-len", "64", "--batch", "2"]
    with pytest.raises(SystemExit):  # a2a heads not divisible by devices
        main([*base, "--attention", "a2a", "--n-heads", "2"])
    with pytest.raises(SystemExit):  # top_k without sampling
        main([*base, "--top-k", "3"])
    with pytest.raises(SystemExit):  # negative temperature
        main([*base, "--temperature", "-1"])
    with pytest.raises(SystemExit):  # launch must divide the step budget
        main([*base, "--steps-per-launch", "3"])
    with pytest.raises(SystemExit):  # warmup must fit inside the run
        main([*base, "--warmup", "5"])
    with pytest.raises(SystemExit):  # accumulation must be positive
        main([*base, "--grad-accum", "0"])
    with pytest.raises(SystemExit):  # ...and fit inside the run
        main([*base, "--grad-accum", "10"])
    with pytest.raises(SystemExit):  # ...and divide it (no partial window)
        main([*base, "--grad-accum", "2"])
    with pytest.raises(SystemExit):  # negative clip flips gradients
        main([*base, "--clip-norm", "-1"])
    with pytest.raises(SystemExit):  # eval fraction out of range
        main([*base, "--eval-every", "2", "--eval-frac", "1.5"])
    with pytest.raises(SystemExit):  # negative eval cadence
        main([*base, "--eval-every", "-10"])
    with pytest.raises(SystemExit):  # ...and the checkpoint cadence
        main(
            [*base, "--steps", "6", "--steps-per-launch", "3",
             "--save-every", "4", "--ckpt-dir", "/tmp/unused-lm-ckpt"]
        )


@pytest.mark.parametrize("extra", [(), ("--attention", "ring_zigzag")])
def test_lm_cli_scanned_supersteps(mesh8, capsys, extra):
    """--steps-per-launch fuses optimizer steps into scanned launches
    (plain and zigzag three-array layouts): training still converges
    and reports land on launch boundaries."""
    out, losses = run_cli(capsys, "--steps-per-launch", "5", *extra)
    assert losses[-1] < losses[0], losses
    assert "--- generation" in out


def test_lm_cli_tiny_corpus_rejected(mesh8, tmp_path):
    f = tmp_path / "tiny.txt"
    f.write_bytes(b"x" * 32)
    with pytest.raises(SystemExit):
        main(["--steps", "2", "--seq-len", "64", "--data", str(f)])


def test_lm_cli_save_needs_dir(mesh8):
    with pytest.raises(SystemExit):
        main(["--save-every", "5"])


def test_lm_cli_rejects_bad_seq_len(mesh8):
    with pytest.raises(SystemExit):
        main(["--seq-len", "65"])  # not divisible by the 8-device axis


@pytest.mark.parametrize("argv", [
    ["--attention", "a2a", "--window", "8"],   # window needs a flash mode
    ["--window", "0"],                         # window must be >= 1
])
def test_lm_cli_invalid_config_is_a_flag_error(mesh8, argv):
    """LMConfig-rejected combinations surface as argparse errors
    (SystemExit 2), not raw ValueError tracebacks."""
    with pytest.raises(SystemExit) as e:
        main(["--steps", "1", *argv])
    assert e.value.code == 2


def test_mfu_queue_configs_trace_and_lower():
    """The queued MFU-push configs (script/onchip.py _mfu_modes — the
    ONE definition the on-chip task also consumes) must build and
    lower at their REAL shapes on a SINGLE-device mesh, exactly as
    task_lm will run them: they have never executed anywhere (smoke
    shrinks shapes), and a latent shape bug would burn a scarce
    tunnel window. Abstract tracing only — no 151M/403M-param
    allocation."""
    import importlib.util
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    from parameter_server_tpu.models.transformer import (
        LMConfig,
        init_lm,
        make_lm_train_step,
    )
    from parameter_server_tpu.system.postoffice import Postoffice

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "onchip_for_mfu", os.path.join(repo, "script", "onchip.py")
    )
    onchip = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(onchip)
    base = dict(vocab=256, d_model=512, n_heads=8, n_layers=8,
                d_ff=2048, remat=True, compute_dtype="bfloat16")
    modes = onchip._mfu_modes(base)
    assert len(modes) == 6
    # single-device mesh: the queued task runs on ONE chip, and the
    # per-device chunk shapes (where shape bugs live) must match it
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
    Postoffice.reset()
    try:
        for _name, kw, ov in modes:
            cfg = LMConfig(**kw)
            spl = ov.get("spl", 8)
            params = jax.eval_shape(
                lambda k, c=cfg: init_lm(k, c), jax.random.PRNGKey(0)
            )
            step = make_lm_train_step(
                cfg, mesh, donate=True, steps_per_launch=spl
            )
            toks = jax.ShapeDtypeStruct(
                (spl, ov["batch"], ov["seq"]), jnp.int32
            )
            step.lower(params, toks)  # raises on any shape bug
    finally:
        Postoffice.reset()
