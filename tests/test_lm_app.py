"""LM app CLI (apps/lm/main.py): end-to-end train + generate in-process
on the virtual mesh, across attention modes."""

import numpy as np
import pytest

from parameter_server_tpu.apps.lm.main import main


def run_cli(capsys, *extra):
    rc = main(
        [
            "--steps", "30", "--seq-len", "64", "--batch", "4",
            "--d-model", "32", "--n-heads", "2", "--d-ff", "64",
            "--report-every", "10", "--prompt", "ab", "--gen-tokens", "8",
            *extra,
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    rows = [
        line.split() for line in out.splitlines()
        if line and line.split()[0].isdigit()
    ]
    losses = [float(r[1]) for r in rows]
    return out, losses


def test_lm_cli_trains_and_generates(mesh8, capsys):
    out, losses = run_cli(capsys)
    assert losses[-1] < losses[0], losses
    assert "--- generation" in out


def test_lm_cli_zigzag_mode(mesh8, capsys):
    out, losses = run_cli(capsys, "--attention", "ring_zigzag")
    assert losses[-1] < losses[0], losses
    assert "--- generation" in out


def test_lm_cli_flash_window_remat(mesh8, capsys):
    out, losses = run_cli(
        capsys, "--attention", "ring_flash", "--window", "16", "--remat",
    )
    assert losses[-1] < losses[0], losses


def test_lm_cli_corpus_file(mesh8, capsys, tmp_path):
    f = tmp_path / "corpus.txt"
    f.write_bytes(b"abcdefgh" * 4096)
    out, losses = run_cli(capsys, "--data", str(f))
    # 8-periodic text: the model should get well under 1 bit/byte fast
    assert losses[-1] < 0.7 * losses[0], losses


def test_lm_cli_rejects_bad_seq_len(mesh8):
    with pytest.raises(SystemExit):
        main(["--seq-len", "65"])  # not divisible by the 8-device axis
