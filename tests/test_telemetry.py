"""Telemetry spine tests: registry semantics, span tracing, per-layer
instrumentation, the metrics-lint gate, and the acceptance run — one
linear-app training on the CPU mesh producing a populated registry
snapshot, a valid JSONL span trace, Prometheus exposition, and a
dashboard telemetry section (ISSUE 1 acceptance criteria)."""

from __future__ import annotations

import json
import math
import re
import statistics
import threading
import time

import numpy as np
import pytest

from parameter_server_tpu.system.executor import Executor
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.telemetry import (
    DuplicateMetricError,
    JsonlSink,
    MetricsRegistry,
    close_sink,
    default_registry,
    get_sink,
    install_sink,
    set_enabled,
    span,
)
from parameter_server_tpu.telemetry.instruments import install_all


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    Postoffice.reset()  # fresh registry + closed sink
    yield
    Postoffice.reset()


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_counter_thread_safety(self):
        reg = MetricsRegistry()
        c = reg.counter("hits_total")
        lc = reg.counter("labeled_total", labelnames=("who",))

        def worker(i):
            child = lc.labels(who=f"t{i % 2}")
            for _ in range(5000):
                c.inc()
                child.inc()

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value() == 8 * 5000
        assert lc.value(who="t0") + lc.value(who="t1") == 8 * 5000

    def test_histogram_concurrent_observe(self):
        reg = MetricsRegistry()
        h = reg.histogram("obs_seconds", buckets=[1, 10])

        def worker():
            for _ in range(2000):
                h.observe(0.5)

        threads = [threading.Thread(target=worker) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert h.count() == 12000
        assert h.sum() == pytest.approx(6000.0)

    def test_histogram_percentile_math(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", buckets=list(range(1, 11)))
        for v in range(1, 11):  # one observation per bucket bound
            h.observe(v)
        # ranks land exactly on bucket bounds -> interpolation is exact
        assert h.percentile(0.5) == pytest.approx(5.0)
        assert h.percentile(0.9) == pytest.approx(9.0)
        assert h.percentile(1.0) == pytest.approx(10.0)
        # above the last finite bound clamps to the observed max
        h.observe(500.0)
        assert h.percentile(1.0) == pytest.approx(500.0)
        # empty series
        assert math.isnan(reg.histogram("empty_seconds").percentile(0.5))

    def test_duplicate_name_rejected(self):
        reg = MetricsRegistry()
        reg.counter("dup_total")
        with pytest.raises(DuplicateMetricError):
            reg.counter("dup_total")
        with pytest.raises(DuplicateMetricError):
            reg.gauge("dup_total")  # other kind, same name
        # ensure_* is idempotent on an identical declaration...
        g = reg.ensure_gauge("depth", labelnames=("executor",))
        assert reg.ensure_gauge("depth", labelnames=("executor",)) is g
        # ...but a mismatched re-declaration is still an error
        with pytest.raises(DuplicateMetricError):
            reg.ensure_gauge("depth", labelnames=("other",))
        with pytest.raises(DuplicateMetricError):
            reg.ensure_counter("depth")
        # histogram exposition suffixes are reserved
        reg.histogram("rt_seconds")
        with pytest.raises(DuplicateMetricError):
            reg.counter("rt_seconds_count")

    def test_non_snake_case_rejected(self):
        reg = MetricsRegistry()
        for bad in ("CamelCase", "has-dash", "has.dot", "9leading", ""):
            with pytest.raises(ValueError):
                reg.counter(bad)

    def test_counter_is_monotone(self):
        reg = MetricsRegistry()
        c = reg.counter("mono_total")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_render_text_prometheus_parseable(self):
        reg = MetricsRegistry()
        install_all(reg)
        reg.counter("plain_total", "with help").inc(3)
        reg.gauge("g_val", labelnames=("node",)).labels(node="W0").set(1.5)
        h = reg.histogram("h_seconds", 'esc"aped\nhelp', labelnames=("ch",))
        h.labels(ch="0").observe(0.02)
        sample = re.compile(
            r"^[a-z_][a-z0-9_]*(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""
            r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})? [^ ]+$"
        )
        text = reg.render_text()
        assert text.endswith("\n")
        for line in text.splitlines():
            assert line.startswith("# ") or sample.match(line), line
        # histogram exposition: cumulative buckets + sum/count present
        assert 'h_seconds_bucket{ch="0",le="+Inf"} 1' in text
        assert 'h_seconds_count{ch="0"} 1' in text

    def test_snapshot_shape(self):
        reg = MetricsRegistry()
        reg.counter("a_total").inc(2)
        h = reg.histogram("b_seconds", buckets=[1, 2])
        h.observe(1.5)
        snap = reg.snapshot()
        assert snap["a_total"]["type"] == "counter"
        assert snap["a_total"]["values"][""] == 2
        hv = snap["b_seconds"]["values"][""]
        assert hv["count"] == 1 and hv["sum"] == pytest.approx(1.5)
        json.dumps(snap)  # JSON-friendly end to end


# ---------------------------------------------------------------------------
# spans + executor emission
# ---------------------------------------------------------------------------


class TestSpans:
    def test_span_records_into_histogram_and_sink(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        install_sink(JsonlSink(path))
        reg = MetricsRegistry()
        h = reg.histogram("blk_seconds")
        with span("unit.block", ts=7, histogram=h, phase="test"):
            time.sleep(0.002)
        close_sink()
        assert h.count() == 1 and h.sum() >= 0.002
        (event,) = [json.loads(l) for l in open(path)]
        assert event["name"] == "unit.block" and event["ts"] == 7
        assert event["phase"] == "test" and event["dur_s"] >= 0.002

    def test_executor_span_emission_ordering(self, tmp_path):
        path = str(tmp_path / "steps.jsonl")
        install_sink(JsonlSink(path))
        ex = Executor(name="spans", telemetry=True)
        from parameter_server_tpu.system.message import Task

        submitted = []
        submitted.append(ex.submit(lambda: np.ones(4)))
        # a dependent step: queue-wait spans the dependency's completion
        submitted.append(
            ex.submit(lambda: np.zeros(2), Task(wait_time=[submitted[0]]))
        )
        submitted.append(ex.submit(lambda: 42))
        ex.wait_all()
        ex.stop()
        close_sink()
        events = [json.loads(l) for l in open(path)]
        steps = [e for e in events if e["name"] == "executor.step"]
        assert {e["ts"] for e in steps} == set(submitted)
        for e in steps:
            assert e["executor"] == "spans"
            assert e["queue_wait_s"] >= 0
            assert e["run_s"] >= 0
            assert e["materialize_s"] >= 0
            # phase ordering invariant: queue-wait can never exceed the
            # submit->finished total
            assert e["queue_wait_s"] <= e["total_s"] + 1e-9

    def test_executor_histograms_populate_registry(self):
        ex = Executor(name="histcheck", telemetry=True)
        for _ in range(4):
            ex.submit(lambda: np.arange(8).sum())
        ex.wait_all()
        ex.stop()
        snap = default_registry().snapshot()
        key = "executor=histcheck"
        assert (
            snap["executor_steps_finished_total"]["values"][key] == 4
        )
        for name in (
            "executor_queue_wait_seconds",
            "executor_run_seconds",
            "executor_step_total_seconds",
        ):
            hv = snap[name]["values"][key]
            assert hv["count"] == 4
            assert hv["p50"] is not None


# ---------------------------------------------------------------------------
# teardown hermeticity + lint gate
# ---------------------------------------------------------------------------


def test_postoffice_reset_resets_telemetry(tmp_path):
    reg_before = default_registry()
    reg_before.counter("leftover_total").inc()
    install_sink(JsonlSink(str(tmp_path / "s.jsonl")))
    Postoffice.reset()
    reg_after = default_registry()
    assert reg_after is not reg_before
    assert reg_after.names() == []
    assert get_sink() is None  # sink closed and uninstalled
    # the new Postoffice instance hangs onto the fresh registry
    assert Postoffice.instance().metrics is reg_after


def test_metrics_lint_passes():
    """The Makefile metrics-lint target, run in-process as a tier-1 gate."""
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "script",
        "metrics_lint.py",
    )
    spec = importlib.util.spec_from_file_location("_metrics_lint", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.lint() == []


# ---------------------------------------------------------------------------
# overhead bound (acceptance: dispatch path within 10% with telemetry on)
# ---------------------------------------------------------------------------


def test_executor_telemetry_overhead_bounded():
    """Instrumented dispatch within 10% of uninstrumented.

    Steps carry realistic work (~100us of numpy) — the regime the bound
    protects; the per-step telemetry cost is a buffered record (one
    small lock + append, flushed outside the hot path).

    Measurement discipline (the ROADMAP bench invariant, same as
    benchmarks/components.host_ingest_ab): this host's effective CPU
    capacity flaps on a seconds timescale, so the quoted number is the
    MEDIAN of BACK-TO-BACK PAIRED reps — each pair runs the on/off arms
    adjacent in time (alternating order so drift cancels), and the
    per-PAIR ratio divides out whatever capacity that moment had. The
    old median(ons)/median(offs) compared medians of two *unpaired*
    samples, which a capacity flap spanning half an attempt could skew
    past the bound with both arms behaving — the flake this replaces.
    Three attempts still guard against a burst swallowing a whole
    attempt."""
    work = np.random.default_rng(0).random(262144)

    def one_chunk(ex, chunk=40):
        t0 = time.perf_counter()
        for _ in range(chunk):
            ex.submit(lambda: float(work.sum()))
        ex.wait_all()
        return time.perf_counter() - t0

    def attempt(tag):
        on = Executor(name=f"ovh_on_{tag}", telemetry=True)
        off = Executor(name=f"ovh_off_{tag}", telemetry=False)
        one_chunk(off, 10)
        one_chunk(on, 10)  # warm both paths
        pair_ratios = []
        for i in range(16):
            if i % 2 == 0:  # alternate order so drift cancels
                sec_off = one_chunk(off)
                sec_on = one_chunk(on)
            else:
                sec_on = one_chunk(on)
                sec_off = one_chunk(off)
            pair_ratios.append(sec_on / sec_off)
        off.stop()
        on.stop()
        return statistics.median(pair_ratios)

    ratios = []
    for i in range(3):
        ratios.append(attempt(i))
        if ratios[-1] <= 1.10:
            return
    pytest.fail(
        f"telemetry overhead above 10% in all attempts "
        f"(median of paired-rep ratios): {ratios}"
    )


# ---------------------------------------------------------------------------
# layer wiring: van accounting + parameter latency + heartbeat traffic
# ---------------------------------------------------------------------------


def _wire_message(sender: str, recver: str):
    from parameter_server_tpu.system.message import Message, Task

    msg = Message(task=Task(), sender=sender, recver=recver)
    msg.values = [np.ones(64, np.float32)]
    return msg


class TestVanAccounting:
    def test_recv_counted_at_receiver(self, mesh8):
        """Satellite: wire_recv_bytes counts where from_wire actually
        ran — a failing decode must not inflate the recv counter."""
        from parameter_server_tpu.system.remote_node import RemoteNode
        from parameter_server_tpu.system.van import Van

        van = Van(mesh8)
        a, b = RemoteNode("S0"), RemoteNode("W0")
        out = van.transfer(a, b, _wire_message("W0", "S0"))
        assert out.values  # round-tripped
        assert van.wire_sent_bytes == a.wire_sent_bytes > 0
        assert van.wire_recv_bytes == b.wire_recv_bytes > 0

        class Broken(RemoteNode):
            def from_wire(self, blob):
                raise RuntimeError("decode exploded")

        sent_before, recv_before = van.wire_sent_bytes, van.wire_recv_bytes
        with pytest.raises(RuntimeError):
            van.transfer(a, Broken("W0"), _wire_message("W0", "S0"))
        assert van.wire_sent_bytes > sent_before  # frame did leave
        assert van.wire_recv_bytes == recv_before  # nothing was received

    def test_transfer_feeds_heartbeat_info(self, mesh8):
        """Satellite: increase_in/out_bytes wired into the real transfer
        path, so dashboards report true traffic."""
        Postoffice.reset()
        po = Postoffice.instance()
        po.start(num_data=4, num_server=2)
        aux = po.start_aux()
        aux.register("W0")
        aux.register("S0")
        from parameter_server_tpu.system.remote_node import RemoteNode

        van = po.van
        van.transfer(
            RemoteNode("S0"), RemoteNode("W0"), _wire_message("W0", "S0")
        )
        w0, s0 = aux.info("W0"), aux.info("S0")
        assert w0.total_out_bytes > 0  # sender side
        assert s0.total_in_bytes > 0  # receiver side
        assert w0.total_out_bytes == s0.total_in_bytes
        # the registry mirrors agree with the van's own counters
        snap = po.metrics.snapshot()
        assert (
            snap["van_wire_sent_bytes_total"]["values"][""]
            == van.wire_sent_bytes
        )
        assert (
            snap["van_wire_recv_bytes_total"]["values"][""]
            == van.wire_recv_bytes
        )
        po.stop()


def test_parameter_push_pull_latency_per_channel(mesh8):
    from parameter_server_tpu.parameter.kv_vector import KVVector

    kv = KVVector(mesh=mesh8, k=1, num_slots=32, hashed=False, name="tel_kv")
    keys = np.array([1, 5, 9], dtype=np.int64)
    kv.set_keys(3, keys)
    kv.wait(
        kv.push(
            kv.request(channel=3), keys=keys, values=np.ones((3, 1), np.float32)
        )
    )
    np.testing.assert_allclose(kv.values(3, keys), np.ones((3, 1)))
    snap = default_registry().snapshot()
    key = "store=tel_kv,channel=3"
    assert snap["ps_push_keys_total"]["values"][key] == 3
    assert snap["ps_pull_keys_total"]["values"][key] >= 3
    assert snap["ps_push_latency_seconds"]["values"][key]["count"] == 1
    assert snap["ps_pull_latency_seconds"]["values"][key]["count"] >= 1
    kv.executor.stop()


# ---------------------------------------------------------------------------
# the acceptance run: one linear-app training on the CPU mesh
# ---------------------------------------------------------------------------


def test_linear_app_run_produces_full_telemetry(tmp_path, mesh8):
    from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker
    from parameter_server_tpu.apps.linear.config import (
        Config,
        LearningRateConfig,
        PenaltyConfig,
        SGDConfig,
    )
    from parameter_server_tpu.utils.sparse import random_sparse

    Postoffice.reset()
    trace_path = str(tmp_path / "run.jsonl")
    install_sink(JsonlSink(trace_path))
    po = Postoffice.instance()
    po.start(num_data=4, num_server=2)
    aux = po.start_aux()
    aux.register("W0")

    conf = Config()
    conf.penalty = PenaltyConfig(type="l1", lambda_=[0.01])
    conf.learning_rate = LearningRateConfig(type="decay", alpha=0.5, beta=1.0)
    conf.async_sgd = SGDConfig(
        algo="ftrl", minibatch=256, num_slots=512, max_delay=1
    )
    worker = AsyncSGDWorker(conf, mesh=po.mesh, name="accept_worker")
    rng = np.random.default_rng(0)
    w_true = (rng.normal(size=512) * (rng.random(512) < 0.2)).astype(np.float32)
    worker.train(
        random_sparse(256, 512, 8, seed=i, w_true=w_true) for i in range(6)
    )
    # exercise the van placement path + a host wire transfer
    po.van.put_table(np.zeros((64, 2), np.float32))
    from parameter_server_tpu.system.remote_node import RemoteNode

    po.van.transfer(RemoteNode("S0"), RemoteNode("W0"), _wire_message("W0", "S0"))
    aux.beat("W0")

    # 1) registry snapshot: non-zero executor step histograms + van bytes
    snap = po.metrics.snapshot()
    key = "executor=accept_worker"
    assert snap["executor_step_total_seconds"]["values"][key]["count"] > 0
    assert snap["executor_queue_wait_seconds"]["values"][key]["count"] > 0
    assert snap["van_placed_bytes_total"]["values"][""] > 0
    assert snap["van_wire_sent_bytes_total"]["values"][""] > 0
    assert snap["app_examples_total"]["values"][""] >= 6 * 256
    assert snap["heartbeat_reports_total"]["values"]["node=W0"] >= 1

    # 2) Prometheus exposition parses
    sample = re.compile(
        r"^[a-z_][a-z0-9_]*(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""
        r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})? [^ ]+$"
    )
    for line in po.metrics.render_text().splitlines():
        assert line.startswith("# ") or sample.match(line), line

    # 3) dashboard report carries the telemetry section
    report = aux.dashboard.report()
    assert "W0" in report
    assert "telemetry:" in report
    assert "executor_step_total_seconds" in report

    # 4) valid JSONL span file with executor step events
    close_sink()
    events = [json.loads(l) for l in open(trace_path)]
    steps = [
        e
        for e in events
        if e["name"] == "executor.step" and e["executor"] == "accept_worker"
    ]
    assert steps, "linear-app run must emit executor.step spans"
    for e in steps:
        assert e["queue_wait_s"] <= e["total_s"] + 1e-9
    worker.executor.stop()
    po.stop()
