"""pslint framework tests (script/pslint/, doc/STATIC_ANALYSIS.md).

Each pass is proven LIVE with a bad fixture it must flag and a good
fixture it must not; the engine's suppression contract (reason
mandatory) is exercised both ways; and the tier-1 acceptance test runs
the full suite against this repo and requires zero unsuppressed
findings — the checked-in concurrency annotations, thread owners,
jit purity, donation decisions and metric catalog all stay enforced.
"""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "script"))

from pslint.affinity import ThreadAffinityRule  # noqa: E402
from pslint.determinism import DeterminismRule  # noqa: E402
from pslint.donate_flow import UseAfterDonateRule  # noqa: E402
from pslint.engine import Engine, SourceFile, default_rules  # noqa: E402
from pslint.jitpure import JitPurityRule  # noqa: E402
from pslint.locks import LockDisciplineRule  # noqa: E402
from pslint.spans import SpanDisciplineRule  # noqa: E402
from pslint.threads import ThreadLifecycleRule  # noqa: E402


def write(tmp_path, rel, body):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(body))
    return rel


def run_rule(tmp_path, rule, rel):
    rule = type(rule)(scope=(rel,))
    findings, suppressed = Engine(str(tmp_path), [rule]).run()
    return findings, suppressed


class TestEngine:
    def test_findings_format_is_editor_clickable(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class C:
                def __init__(self):
                    self._x = 0  # guarded-by: _lock
                    self._lock = threading.Lock()

                def bad(self):
                    self._x = 1
            """,
        )
        findings, _ = run_rule(tmp_path, LockDisciplineRule(), rel)
        assert len(findings) == 1
        line = findings[0].format()
        # path:line rule message — splittable by the first two fields
        loc, rule, msg = line.split(" ", 2)
        assert loc == "m.py:10"
        assert rule == "guarded-access"
        assert "_x" in msg and "_lock" in msg

    def test_suppression_with_reason_silences(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class C:
                def __init__(self):
                    self._x = 0  # guarded-by: _lock
                    self._lock = threading.Lock()

                def stat(self):
                    # single writer: only the dispatch thread mutates it
                    return self._x  # pslint: disable=guarded-access — monotonic stat read, staleness is fine
            """,
        )
        findings, suppressed = run_rule(tmp_path, LockDisciplineRule(), rel)
        assert findings == []
        assert suppressed == 1

    def test_suppression_without_reason_rejected(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class C:
                def __init__(self):
                    self._x = 0  # guarded-by: _lock
                    self._lock = threading.Lock()

                def stat(self):
                    return self._x  # pslint: disable=guarded-access
            """,
        )
        findings, suppressed = run_rule(tmp_path, LockDisciplineRule(), rel)
        # the reasonless disable does NOT silence the guarded-access
        # finding, and is a finding of its own
        rules = sorted(f.rule for f in findings)
        assert rules == ["guarded-access", "suppression"]
        assert suppressed == 0

    def test_unknown_rule_name_rejected(self):
        with pytest.raises(ValueError, match="unknown rule"):
            default_rules(["no-such-pass"])


class TestLockDiscipline:
    def test_clean_class_passes(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class C:
                def __init__(self):
                    self._x = 0  # guarded-by: _lock
                    self._lock = threading.Lock()

                def inc(self):
                    with self._lock:
                        self._x += 1
            """,
        )
        findings, _ = run_rule(tmp_path, LockDisciplineRule(), rel)
        assert findings == []

    def test_unguarded_read_and_write_flagged(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class C:
                def __init__(self):
                    self._x = 0  # guarded-by: _lock
                    self._lock = threading.Lock()

                def bad_write(self):
                    self._x = 1

                def bad_read(self):
                    return self._x + 1
            """,
        )
        findings, _ = run_rule(tmp_path, LockDisciplineRule(), rel)
        assert [f.line for f in findings] == [10, 13]
        assert "written" in findings[0].message
        assert "read" in findings[1].message

    def test_holds_lock_annotation_honored(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class C:
                def __init__(self):
                    self._x = 0  # guarded-by: _lock
                    self._lock = threading.Lock()

                def _bump_locked(self):  # holds-lock: _lock
                    self._x += 1

                def bump(self):
                    with self._lock:
                        self._bump_locked()
            """,
        )
        findings, _ = run_rule(tmp_path, LockDisciplineRule(), rel)
        assert findings == []

    def test_nested_def_does_not_inherit_lock(self, tmp_path):
        """A def created under a with-lock may run on another thread
        (Thread targets!) — it must NOT count as holding the lock."""
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class C:
                def __init__(self):
                    self._x = 0  # guarded-by: _lock
                    self._lock = threading.Lock()

                def spawnish(self):
                    with self._lock:
                        def escapes():
                            self._x += 1
                        return escapes
            """,
        )
        findings, _ = run_rule(tmp_path, LockDisciplineRule(), rel)
        assert [f.rule for f in findings] == ["guarded-access"]

    def test_condition_wait_for_lambda_inherits_lock(self, tmp_path):
        """The WorkloadPool idiom: Condition(self._lock) shares the
        lock, and a wait_for predicate lambda runs with it held."""
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class C:
                def __init__(self):
                    self._n = 0  # guarded-by: _lock
                    self._lock = threading.Lock()
                    self._done = threading.Condition(self._lock)

                def wait(self):
                    with self._done:
                        self._done.wait_for(lambda: self._n > 0)
            """,
        )
        findings, _ = run_rule(tmp_path, LockDisciplineRule(), rel)
        assert findings == []

    def test_unknown_guard_lock_flagged(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class C:
                def __init__(self):
                    self._x = 0  # guarded-by: _mutex
                    self._lock = threading.Lock()
            """,
        )
        findings, _ = run_rule(tmp_path, LockDisciplineRule(), rel)
        assert [f.rule for f in findings] == ["unknown-lock"]

    def test_classlevel_guard_with_cls_lock(self, tmp_path):
        """The Postoffice singleton shape: class attribute guarded by a
        class-level lock, accessed via cls in classmethods."""
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class Single:
                _instance = None  # guarded-by: _lock
                _lock = threading.Lock()

                @classmethod
                def instance(cls):
                    with cls._lock:
                        if cls._instance is None:
                            cls._instance = cls()
                        return cls._instance

                @classmethod
                def bad_peek(cls):
                    return cls._instance
            """,
        )
        findings, _ = run_rule(tmp_path, LockDisciplineRule(), rel)
        assert [f.rule for f in findings] == ["guarded-access"]
        assert findings[0].line == 17

    def test_seeded_lock_order_cycle_detected(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def ab(self):
                    with self._a:
                        with self._b:
                            pass

                def ba(self):
                    with self._b:
                        with self._a:
                            pass
            """,
        )
        findings, _ = run_rule(tmp_path, LockDisciplineRule(), rel)
        assert [f.rule for f in findings] == ["lock-order"]
        assert "C._a" in findings[0].message and "C._b" in findings[0].message

    def test_cross_class_consistent_order_is_acyclic(self, tmp_path):
        """Holding A._l while calling a B method that takes B._l is an
        edge, not a cycle, while every path agrees on the order."""
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class B:
                def __init__(self):
                    self._l = threading.Lock()
                    self.peer = None

                def poke(self):
                    with self._l:
                        pass

                def crossed(self):
                    with self._l:
                        self.peer.poke()

            class A:
                def __init__(self):
                    self._l = threading.Lock()
                    self.b = B()

                def crossed(self):
                    with self._l:
                        self.b.crossed()
            """,
        )
        # consistent one-directional order (A._l -> B._l only): no cycle
        findings, _ = run_rule(tmp_path, LockDisciplineRule(), rel)
        assert findings == []

    def test_holds_lock_method_contributes_order_edges(self, tmp_path):
        """A lock acquired inside a `# holds-lock:` method is an edge
        from the annotated lock — the *_locked convention must not
        silence deadlock-cycle detection."""
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def inner_locked(self):  # holds-lock: _b
                    with self._a:
                        pass

                def ab(self):
                    with self._a:
                        with self._b:
                            pass
            """,
        )
        findings, _ = run_rule(tmp_path, LockDisciplineRule(), rel)
        assert [f.rule for f in findings] == ["lock-order"]
        assert "C._a" in findings[0].message and "C._b" in findings[0].message

    def test_multi_item_with_orders_locks(self, tmp_path):
        """``with self._a, self._b:`` acquires in item order — the
        intra-statement a→b edge must cycle against a reversed nested
        acquisition elsewhere."""
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def ab(self):
                    with self._a, self._b:
                        pass

                def ba(self):
                    with self._b:
                        with self._a:
                            pass
            """,
        )
        findings, _ = run_rule(tmp_path, LockDisciplineRule(), rel)
        assert [f.rule for f in findings] == ["lock-order"]

    def test_duplicate_class_names_both_checked(self, tmp_path):
        """Two scope files reusing a class name must BOTH stay under
        checking — a name-keyed model map silently dropped one."""
        body = """
            import threading

            class W:
                def __init__(self):
                    self._x = 0  # guarded-by: _lock
                    self._lock = threading.Lock()

                def bad(self):
                    self._x = 1
        """
        rel1 = write(tmp_path, "m1.py", body)
        rel2 = write(tmp_path, "m2.py", body)
        rule = LockDisciplineRule(scope=(rel1, rel2))
        findings, _ = Engine(str(tmp_path), [rule]).run()
        assert sorted(f.path for f in findings) == ["m1.py", "m2.py"]
        assert {f.rule for f in findings} == {"guarded-access"}

    def test_cycle_through_method_call_detected(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class A:
                def __init__(self):
                    self._l = threading.Lock()
                    self.b = B()

                def into_b(self):
                    with self._l:
                        self.b.into_a()

                def touch(self):
                    with self._l:
                        pass

            class B:
                def __init__(self):
                    self._l = threading.Lock()
                    self.a = A()

                def into_a(self):
                    with self._l:
                        self.a.touch()
            """,
        )
        findings, _ = run_rule(tmp_path, LockDisciplineRule(), rel)
        assert [f.rule for f in findings] == ["lock-order"]
        assert "A._l" in findings[0].message and "B._l" in findings[0].message


class TestThreadLifecycle:
    def test_joined_thread_passes(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class Owner:
                def start(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def stop(self):
                    self._t.join()
            """,
        )
        findings, _ = run_rule(tmp_path, ThreadLifecycleRule(), rel)
        assert findings == []

    def test_unjoined_thread_flagged(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            def fire_and_forget(fn):
                t = threading.Thread(target=fn, daemon=True)
                t.start()
            """,
        )
        findings, _ = run_rule(tmp_path, ThreadLifecycleRule(), rel)
        assert [f.rule for f in findings] == ["thread-join"]
        assert findings[0].line == 5

    def test_unjoined_thread_suppressible_with_reason(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            def fire_and_forget(fn):
                # pslint: disable=thread-join — interpreter-lifetime watcher, joined by no one by design
                t = threading.Thread(target=fn, daemon=True)
                t.start()
            """,
        )
        findings, suppressed = run_rule(tmp_path, ThreadLifecycleRule(), rel)
        assert findings == []
        assert suppressed == 1

    def test_str_join_does_not_satisfy_rule(self, tmp_path):
        """A ``", ".join(parts)`` in the owning class is not a thread
        join — classes with string formatting (Dashboard!) must not get
        a free pass for unjoined threads."""
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class Renderer:
                def start(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def render(self, parts):
                    return ", ".join(str(p) for p in parts)
            """,
        )
        findings, _ = run_rule(tmp_path, ThreadLifecycleRule(), rel)
        assert [f.rule for f in findings] == ["thread-join"]

    def test_function_level_join_owns_spawn(self, tmp_path):
        """The iter_on_thread shape: spawn + join in one function."""
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            def run_joined(fn):
                t = threading.Thread(target=fn)
                t.start()
                try:
                    yield
                finally:
                    t.join()
            """,
        )
        findings, _ = run_rule(tmp_path, ThreadLifecycleRule(), rel)
        assert findings == []


class TestSpansPass:
    def test_with_statement_span_passes(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            from parameter_server_tpu.telemetry import span, flow_scope

            def timed(fid):
                with flow_scope(fid), span("stage.prep", phase="e2e"):
                    return 1
            """,
        )
        findings, _ = run_rule(tmp_path, SpanDisciplineRule(), rel)
        assert findings == []

    def test_bare_span_call_flagged(self, tmp_path):
        """The PR-1 span-leak hazard: a bare span(...) builds a
        generator that never runs — untimed block, and a stored ctx can
        die with its owner and corrupt the timeline."""
        rel = write(
            tmp_path,
            "m.py",
            """
            from parameter_server_tpu.telemetry import span

            def leaky():
                span("stage.prep")
                return 1
            """,
        )
        findings, _ = run_rule(tmp_path, SpanDisciplineRule(), rel)
        assert [f.rule for f in findings] == ["span-with"]
        assert findings[0].line == 5

    def test_module_alias_span_flagged_and_with_passes(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            from parameter_server_tpu.telemetry import spans as telemetry_spans

            def bad():
                ctx = telemetry_spans.span("x")
                with ctx:
                    pass

            def good():
                with telemetry_spans.span("x"):
                    pass
            """,
        )
        findings, _ = run_rule(tmp_path, SpanDisciplineRule(), rel)
        assert [(f.rule, f.line) for f in findings] == [("span-with", 5)]

    def test_enter_context_owns_the_span(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import contextlib
            from parameter_server_tpu.telemetry import span

            def stacked():
                with contextlib.ExitStack() as stack:
                    stack.enter_context(span("stage.prep"))
            """,
        )
        findings, _ = run_rule(tmp_path, SpanDisciplineRule(), rel)
        assert findings == []

    def test_regex_match_span_not_flagged(self, tmp_path):
        """``re.Match.span()`` and other unrelated .span attributes must
        never trip the rule."""
        rel = write(
            tmp_path,
            "m.py",
            """
            import re

            def bounds(m: "re.Match"):
                return m.span(), m.span(1)
            """,
        )
        findings, _ = run_rule(tmp_path, SpanDisciplineRule(), rel)
        assert findings == []

    def test_suppressible_with_reason(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            from parameter_server_tpu.telemetry import span

            def deferred():
                # pslint: disable=span-with — handed to the reactor loop, which enters and closes it
                return span("stage.prep")
            """,
        )
        findings, suppressed = run_rule(tmp_path, SpanDisciplineRule(), rel)
        assert findings == []
        assert suppressed == 1


class TestJitPurity:
    def test_pure_jit_passes(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import functools
            import jax
            import jax.numpy as jnp
            import numpy as np

            @functools.partial(jax.jit, static_argnames=("k",))
            def pure(x, *, k):
                # np constants / shape math are trace-time legal
                scale = 1.0 / np.sqrt(x.shape[-1])
                return jnp.sum(x * np.float32(scale), axis=-1)[:k]
            """,
        )
        findings, _ = run_rule(tmp_path, JitPurityRule(), rel)
        assert findings == []

    def test_print_np_time_nonlocal_flagged(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import time
            import jax
            import numpy as np

            calls = []

            @jax.jit
            def impure(x):
                nonlocal_count = 0

                def bump():
                    nonlocal nonlocal_count
                    nonlocal_count += 1

                print("tracing", x.shape)
                t0 = time.perf_counter()
                host = np.asarray(x)
                bump()
                return x * host.size + t0
            """,
        )
        findings, _ = run_rule(tmp_path, JitPurityRule(), rel)
        kinds = sorted(f.message.split(" inside")[0] for f in findings)
        assert kinds == [
            "host numpy np.asarray()",
            "nonlocal mutation",
            "print()",
            "time.perf_counter() clock read",
        ]

    def test_telemetry_call_inside_jit_flagged(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import jax

            def _tel():
                return None

            @jax.jit
            def step(x):
                tel = _tel()
                tel["pushes"].inc()
                return x + 1
            """,
        )
        findings, _ = run_rule(tmp_path, JitPurityRule(), rel)
        assert [f.rule for f in findings] == ["jit-purity"]
        assert ".inc()" in findings[0].message

    def test_jit_by_reference_scanned(self, tmp_path):
        """kv_ops shape: partial(jax.jit, ...)(impl) marks impl."""
        rel = write(
            tmp_path,
            "m.py",
            """
            import functools
            import jax

            def _impl(x):
                print("boom")
                return x

            pull = functools.partial(jax.jit, static_argnames=())(_impl)
            """,
        )
        findings, _ = run_rule(tmp_path, JitPurityRule(), rel)
        assert [f.line for f in findings] == [6]


class TestDonationPass:
    def _fake_root(self, tmp_path, kv_ops_body):
        """A mini-repo exposing donation_lint's full scope."""
        from pslint.donation import _load_sibling

        scope = _load_sibling("donation_lint").SCOPE
        for rel in scope:
            write(tmp_path, rel, "")
        write(tmp_path, "parameter_server_tpu/ops/kv_ops.py", kv_ops_body)
        return tmp_path

    def test_undeclared_jit_site_flagged(self, tmp_path):
        from pslint.donation import DonationRule

        self._fake_root(
            tmp_path,
            """
            import jax

            def update(table, grads):
                return jax.jit(lambda t, g: t + g)(table, grads)
            """,
        )
        findings, _ = Engine(str(tmp_path), [DonationRule()]).run()
        assert [f.rule for f in findings] == ["donation"]
        assert findings[0].path == "parameter_server_tpu/ops/kv_ops.py"

    def test_no_donate_reason_passes(self, tmp_path):
        from pslint.donation import DonationRule

        self._fake_root(
            tmp_path,
            """
            import jax

            def pull(table, idx):
                # no-donate: pull reads the table; the store keeps it
                return jax.jit(lambda t, i: t[i])(table, idx)
            """,
        )
        findings, _ = Engine(str(tmp_path), [DonationRule()]).run()
        assert findings == []


class TestMetricsPass:
    def test_catalog_problems_become_findings(self, monkeypatch):
        from pslint import metrics as metrics_pass

        seen_roots = []

        class FakeLint:
            @staticmethod
            def lint(root=None):
                seen_roots.append(root)
                return ["counter 'x' should end in '_total'"]

        monkeypatch.setattr(metrics_pass, "_load_sibling", lambda name: FakeLint)
        findings = metrics_pass.MetricsRule().check({}, REPO)
        assert [f.rule for f in findings] == ["metrics"]
        assert findings[0].path.endswith("instruments.py")
        # --root must flow through to the catalog import (wrong-checkout
        # validation was a silent fail-open)
        assert seen_roots == [REPO]

    def test_live_catalog_is_clean(self):
        from pslint.metrics import MetricsRule

        assert MetricsRule().check({}, REPO) == []


class TestUseAfterDonate:
    DONATING_PRELUDE = """
            import functools
            import jax

            step = functools.partial(jax.jit, donate_argnums=(0,))(lambda t, g: t + g)

            def slow(t, g):
                return t + g
    """

    def test_read_after_donating_call_flagged(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            self.DONATING_PRELUDE
            + """
            def train(table, grads):
                out = step(table, grads)
                return table.sum()
            """,
        )
        findings, _ = run_rule(tmp_path, UseAfterDonateRule(), rel)
        assert [f.rule for f in findings] == ["use-after-donate"]
        assert "donated to step()" in findings[0].message

    def test_reassignment_kills_the_donation(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            self.DONATING_PRELUDE
            + """
            def train(table, grads):
                table = step(table, grads)
                return table.sum()
            """,
        )
        findings, _ = run_rule(tmp_path, UseAfterDonateRule(), rel)
        assert findings == []

    def test_donation_in_returning_branch_does_not_leak(self, tmp_path):
        """Regression: a donate inside an ``if`` arm that *returns* must
        not poison the fall-through sibling (the async_sgd selector
        idiom was a false positive until branch termination landed)."""
        rel = write(
            tmp_path,
            "m.py",
            self.DONATING_PRELUDE
            + """
            def train(table, grads, fast):
                if fast:
                    return step(table, grads)
                return slow(table, grads)
            """,
        )
        findings, _ = run_rule(tmp_path, UseAfterDonateRule(), rel)
        assert findings == []

    def test_one_wrapper_level_propagation(self, tmp_path):
        """A module function that forwards its arg into a donating
        callee is itself donating — callers one level up are caught."""
        rel = write(
            tmp_path,
            "m.py",
            self.DONATING_PRELUDE
            + """
            def apply(t, g):
                return step(t, g)

            def train(table, grads):
                apply(table, grads)
                return table.sum()
            """,
        )
        findings, _ = run_rule(tmp_path, UseAfterDonateRule(), rel)
        assert [f.rule for f in findings] == ["use-after-donate"]
        assert "donated to apply()" in findings[0].message

    def test_local_donating_name_does_not_poison_other_functions(
        self, tmp_path
    ):
        """Regression: a function-LOCAL ``fn = jit(..., donate_argnums=...)``
        must donate inside its own function only — a global name-keyed
        map flagged every unrelated call named ``fn``."""
        rel = write(
            tmp_path,
            "m.py",
            """
            import jax

            def donating_scope(table, grads):
                fn = jax.jit(lambda t, g: t + g, donate_argnums=(0,))
                fn(table, grads)
                return table.sum()

            def innocent_scope(x):
                fn = lambda v: v + 1
                fn(x)
                return x + 1
            """,
        )
        findings, _ = run_rule(tmp_path, UseAfterDonateRule(), rel)
        assert [(f.line, f.rule) for f in findings] == [
            (7, "use-after-donate")
        ]

    def test_donated_dead_escape_comment(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            self.DONATING_PRELUDE
            + """
            def train(table, grads):
                out = step(table, grads)
                return table  # donated-dead: error-path echo only, never dereferenced
            """,
        )
        findings, _ = run_rule(tmp_path, UseAfterDonateRule(), rel)
        assert findings == []

    def test_suppressible_with_reason(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            self.DONATING_PRELUDE
            + """
            def train(table, grads):
                out = step(table, grads)
                return table.sum()  # pslint: disable=use-after-donate — fixture: proving the disable path
            """,
        )
        findings, suppressed = run_rule(
            tmp_path, UseAfterDonateRule(), rel
        )
        assert findings == []
        assert suppressed == 1


class TestThreadAffinity:
    def test_two_entry_points_without_lock_flagged(self, tmp_path):
        """The seeded violation: an owner-thread method reachable from
        two distinct Thread entry points with no lock on the path."""
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class Pump:  # owner-thread: scheduler
                def __init__(self):
                    self.q = []
                    self._lock = threading.Lock()
                    self._t1 = threading.Thread(target=self._run_a, name="ingest")
                    self._t2 = threading.Thread(target=self._run_b, name="drain")

                def _run_a(self):
                    self.push(1)

                def _run_b(self):
                    self.push(2)

                def push(self, x):
                    self.q.append(x)
            """,
        )
        findings, _ = run_rule(tmp_path, ThreadAffinityRule(), rel)
        assert [f.rule for f in findings] == ["thread-affinity"]
        assert "Pump.push" in findings[0].message
        # entry names surface in the message for triage
        assert "ingest" in findings[0].message
        assert "drain" in findings[0].message

    def test_locked_method_is_exempt(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class Pump:  # owner-thread: scheduler
                def __init__(self):
                    self.q = []
                    self._lock = threading.Lock()
                    self._t1 = threading.Thread(target=self._run_a, name="ingest")
                    self._t2 = threading.Thread(target=self._run_b, name="drain")

                def _run_a(self):
                    self.push(1)

                def _run_b(self):
                    self.push(2)

                def push(self, x):
                    with self._lock:
                        self.q.append(x)
            """,
        )
        findings, _ = run_rule(tmp_path, ThreadAffinityRule(), rel)
        assert findings == []

    def test_single_entry_point_is_fine(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class Pump:  # owner-thread: scheduler
                def __init__(self):
                    self.q = []
                    self._t1 = threading.Thread(target=self._run_a, name="ingest")

                def _run_a(self):
                    self.push(1)

                def push(self, x):
                    self.q.append(x)
            """,
        )
        findings, _ = run_rule(tmp_path, ThreadAffinityRule(), rel)
        assert findings == []

    def test_owner_thread_any_exempts_a_method(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class Pump:  # owner-thread: scheduler
                def __init__(self):
                    self.q = []
                    self._t1 = threading.Thread(target=self._run_a, name="ingest")
                    self._t2 = threading.Thread(target=self._run_b, name="drain")

                def _run_a(self):
                    self.push(1)

                def _run_b(self):
                    self.push(2)

                def push(self, x):  # owner-thread: any
                    self.q.append(x)
            """,
        )
        findings, _ = run_rule(tmp_path, ThreadAffinityRule(), rel)
        assert findings == []

    def test_unannotated_class_not_checked(self, tmp_path):
        """No ``# owner-thread:`` declaration — the pass has no owner
        contract to enforce; the locks pass covers such classes."""
        rel = write(
            tmp_path,
            "m.py",
            """
            import threading

            class Pump:
                def __init__(self):
                    self.q = []
                    self._t1 = threading.Thread(target=self._run_a, name="ingest")
                    self._t2 = threading.Thread(target=self._run_b, name="drain")

                def _run_a(self):
                    self.push(1)

                def _run_b(self):
                    self.push(2)

                def push(self, x):
                    self.q.append(x)
            """,
        )
        findings, _ = run_rule(tmp_path, ThreadAffinityRule(), rel)
        assert findings == []


class TestDeterminism:
    def test_scoped_module_without_marker_flagged(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            '''
            """Module under the contract but missing its marker."""

            X = 1
            ''',
        )
        findings, _ = run_rule(tmp_path, DeterminismRule(), rel)
        assert [(f.line, f.rule) for f in findings] == [(1, "determinism")]
        assert "bit-identical" in findings[0].message

    def test_set_iteration_and_wall_clock_flagged(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            # bit-identical
            import time

            def pack(d):
                return [k for k in set(d)]

            def stamp():
                return time.time()
            """,
        )
        findings, _ = run_rule(tmp_path, DeterminismRule(), rel)
        assert [f.line for f in findings] == [6, 9]
        assert "order varies" in findings[0].message
        assert "wall-clock" in findings[1].message

    def test_sorted_set_and_perf_counter_pass(self, tmp_path):
        """sorted(...) launders set order; perf_counter is a sanctioned
        telemetry clock — neither is a finding."""
        rel = write(
            tmp_path,
            "m.py",
            """
            # bit-identical
            import time

            def pack(d):
                return sorted(set(d))

            def tick():
                return time.perf_counter()
            """,
        )
        findings, _ = run_rule(tmp_path, DeterminismRule(), rel)
        assert findings == []

    def test_unseeded_rng_and_unsorted_listdir_flagged(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            # bit-identical
            import os
            import random

            def sample():
                return random.random()

            def shards(path):
                return [p for p in os.listdir(path)]
            """,
        )
        findings, _ = run_rule(tmp_path, DeterminismRule(), rel)
        assert len(findings) == 2
        assert "unseeded" in findings[0].message or "RNG" in findings[0].message
        assert "sorted" in findings[1].message

    def test_suppressible_with_reason(self, tmp_path):
        rel = write(
            tmp_path,
            "m.py",
            """
            # bit-identical
            import time

            def stamp():
                # pslint: disable=determinism — telemetry birth timestamp, never replayed bytes
                return time.time()
            """,
        )
        findings, suppressed = run_rule(tmp_path, DeterminismRule(), rel)
        assert findings == []
        assert suppressed == 1


class TestCrossArtifact:
    """Each sub-check drives a mini-repo holding both sides of one
    artifact boundary, drifted on purpose."""

    def _mini_repo(self, tmp_path, **overrides):
        defaults = {
            "parameter_server_tpu/__init__.py": "",
            "parameter_server_tpu/system/__init__.py": "",
            "parameter_server_tpu/system/faults.py": """
                POINTS = ("push_drop", "pull_stall")
            """,
            "parameter_server_tpu/system/drill.py": """
                from . import faults

                def go():
                    faults.arm("pull_stall")
            """,
            "parameter_server_tpu/telemetry/__init__.py": "",
            "parameter_server_tpu/telemetry/instruments.py": """
                NAMES = ("ps_push_total", "ps_pull_latency")
            """,
            "parameter_server_tpu/benchmarks/__init__.py": "",
            "parameter_server_tpu/benchmarks/components.py": """
                def benchmark(name):
                    def deco(fn):
                        return fn
                    return deco

                @benchmark("decode")
                def bench_decode():
                    return {"recovery": 1}
            """,
            "Makefile": """
                bench:
                \tpython -m parameter_server_tpu.benchmarks decode
            """,
            "tests/test_benchmarks.py": 'KEYS = ["decode"]\n',
            "script/bench_diff.py": """
                METADATA_SECTIONS = frozenset({"recovery"})
            """,
            "bench.py": "",
        }
        defaults.update(overrides)
        for rel, body in defaults.items():
            write(tmp_path, rel, body)
        return tmp_path

    def _run(self, tmp_path):
        from pslint.artifacts import CrossArtifactRule

        return Engine(str(tmp_path), [CrossArtifactRule()]).run()

    def test_consistent_mini_repo_is_clean(self, tmp_path):
        self._mini_repo(tmp_path)
        findings, _ = self._run(tmp_path)
        assert findings == []

    def test_unknown_fault_point_flagged(self, tmp_path):
        self._mini_repo(
            tmp_path,
            **{
                "parameter_server_tpu/system/drill.py": """
                    from . import faults

                    def go():
                        faults.inject("push_dorp")
                """
            },
        )
        findings, _ = self._run(tmp_path)
        assert [f.rule for f in findings] == ["fault-point"]
        assert "push_dorp" in findings[0].message

    def test_unqualified_arm_call_not_matched(self, tmp_path):
        """``blackbox.arm()`` is a different arm — only ``faults.``-
        qualified calls are pinned to POINTS."""
        self._mini_repo(
            tmp_path,
            **{
                "parameter_server_tpu/system/drill.py": """
                    def go(blackbox):
                        blackbox.arm("not_a_point")
                """
            },
        )
        findings, _ = self._run(tmp_path)
        assert findings == []

    def test_alert_metric_drift_flagged(self, tmp_path):
        self._mini_repo(tmp_path)
        write(
            tmp_path,
            "configs/alerts/a.json",
            '{"rules": [{"metric": "ps_pull_latency", "den": "ps_gone_total"}]}\n',
        )
        findings, _ = self._run(tmp_path)
        assert [f.rule for f in findings] == ["alert-metric"]
        assert "ps_gone_total" in findings[0].message
        assert findings[0].path == "configs/alerts/a.json"

    def test_makefile_unregistered_benchmark_flagged(self, tmp_path):
        self._mini_repo(
            tmp_path,
            Makefile="""
                bench:
                \tpython -m parameter_server_tpu.benchmarks decode
                \tpython -m parameter_server_tpu.benchmarks deocde
            """,
        )
        findings, _ = self._run(tmp_path)
        assert [f.rule for f in findings] == ["bench-wiring"]
        assert "deocde" in findings[0].message
        assert findings[0].path == "Makefile"

    def test_unreferenced_registry_key_flagged(self, tmp_path):
        self._mini_repo(
            tmp_path,
            **{
                "parameter_server_tpu/benchmarks/components.py": """
                    def benchmark(name):
                        def deco(fn):
                            return fn
                        return deco

                    @benchmark("decode")
                    def bench_decode():
                        return {"recovery": 1}

                    @benchmark("ghost_bench_xyzzy")
                    def bench_ghost():
                        return {}
                """
            },
        )
        findings, _ = self._run(tmp_path)
        assert [f.rule for f in findings] == ["bench-wiring"]
        assert "ghost_bench_xyzzy" in findings[0].message
        assert "unreachable" in findings[0].message

    def test_stale_metadata_section_flagged(self, tmp_path):
        self._mini_repo(
            tmp_path,
            **{
                "script/bench_diff.py": """
                    METADATA_SECTIONS = frozenset({"recovery", "ghosts"})
                """
            },
        )
        findings, _ = self._run(tmp_path)
        assert [f.rule for f in findings] == ["metadata-section"]
        assert "ghosts" in findings[0].message


class TestIncrementalCache:
    """The content-hash cache contract: a warm run recomputes nothing,
    an edit recomputes exactly the edited file, and the cache can
    neither hide a fresh finding nor resurrect a fixed one."""

    def _engine(self, tmp_path, rels):
        return Engine(
            str(tmp_path),
            [DeterminismRule(scope=tuple(rels))],
            cache_path=str(tmp_path / "cache.json"),
        )

    def test_warm_run_is_fully_cached(self, tmp_path):
        rels = [
            write(tmp_path, "a.py", "# bit-identical\nX = 1\n"),
            write(tmp_path, "b.py", "# bit-identical\nY = 1\n"),
        ]
        e1 = self._engine(tmp_path, rels)
        assert e1.run() == ([], 0)
        assert e1.stats["determinism"] == {"analyzed": 2, "cached": 0}
        e2 = self._engine(tmp_path, rels)
        assert e2.run() == ([], 0)
        assert e2.stats["determinism"] == {"analyzed": 0, "cached": 2}

    def test_edit_recomputes_only_the_edited_file(self, tmp_path):
        rels = [
            write(tmp_path, "a.py", "# bit-identical\nX = 1\n"),
            write(tmp_path, "b.py", "# bit-identical\nY = 1\n"),
        ]
        self._engine(tmp_path, rels).run()
        # introduce a finding in b only: the stale cache entry must not
        # hide it, and a must stay served from cache
        (tmp_path / "b.py").write_text(
            "# bit-identical\nimport time\nT = time.time()\n"
        )
        e = self._engine(tmp_path, rels)
        findings, _ = e.run()
        assert e.stats["determinism"] == {"analyzed": 1, "cached": 1}
        assert [(f.path, f.line) for f in findings] == [("b.py", 3)]
        # revert: the finding disappears (the key is the content hash,
        # so the bad entry cannot be served for the fixed file); the
        # save-only-touched policy pruned the original entry, so b is
        # re-analyzed once while a stays a hit
        (tmp_path / "b.py").write_text("# bit-identical\nY = 1\n")
        e2 = self._engine(tmp_path, rels)
        assert e2.run() == ([], 0)
        assert e2.stats["determinism"] == {"analyzed": 1, "cached": 1}

    def test_cached_findings_still_pass_suppression_filter(self, tmp_path):
        """The cache stores PRE-suppression findings; the filter runs
        every time, so editing only a comment elsewhere cannot leak a
        suppressed finding."""
        rel = write(
            tmp_path,
            "c.py",
            "# bit-identical\nimport time\n"
            "T = time.time()  # pslint: disable=determinism — fixture timestamp\n",
        )
        e1 = self._engine(tmp_path, [rel])
        assert e1.run() == ([], 1)
        e2 = self._engine(tmp_path, [rel])
        assert e2.run() == ([], 1)
        assert e2.stats["determinism"] == {"analyzed": 0, "cached": 1}

    def test_rule_version_bump_invalidates(self, tmp_path):
        """The rule version is part of the cache key — a pass upgrade
        must never serve findings computed by its older self."""
        rel = write(tmp_path, "a.py", "# bit-identical\nX = 1\n")
        self._engine(tmp_path, [rel]).run()

        class Bumped(DeterminismRule):
            version = DeterminismRule.version + "-test"

        e = Engine(
            str(tmp_path),
            [Bumped(scope=(rel,))],
            cache_path=str(tmp_path / "cache.json"),
        )
        e.run()
        assert e.stats["determinism"] == {"analyzed": 1, "cached": 0}


class TestRepoIsClean:
    def test_full_suite_repo_clean(self):
        """Tier-1 acceptance: the repo lints clean under every pass —
        the concurrency annotations, thread owners, jitted data plane,
        donation decisions and metric catalog all hold."""
        findings, _ = Engine(REPO, default_rules()).run()
        assert findings == [], "\n".join(f.format() for f in findings)

    def test_every_suppression_carries_reason(self):
        """Engine-wide hygiene: scan every package + script file for
        pslint disables; each must parse with a reason (the engine
        enforces this for scoped files; this test sweeps everything)."""
        import re

        bad = []
        # (tests/ excluded: this file's fixture strings deliberately
        # contain a reasonless disable to prove the engine rejects it)
        for base in ("parameter_server_tpu", "script"):
            for dirpath, dirnames, filenames in os.walk(os.path.join(REPO, base)):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in filenames:
                    if not fn.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, fn)
                    with open(path, encoding="utf-8") as f:
                        for i, line in enumerate(f, 1):
                            m = re.search(r"#\s*pslint:\s*disable=(\S+)", line)
                            if m is None:
                                continue
                            if not re.search(r"(?:—|–|--| - )\s*\S", line[m.end():]):
                                bad.append(f"{path}:{i}")
        assert bad == [], f"reasonless pslint suppressions: {bad}"

    def test_cli_exit_codes(self):
        """The make target contract: exit 0 + OK line on this repo."""
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "script", "pslint", "cli.py")],
            capture_output=True,
            text=True,
            timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "pslint: OK" in proc.stdout

    def test_cli_rules_filter_and_list(self):
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "script", "pslint", "cli.py"),
                "--list",
            ],
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert proc.returncode == 0
        assert set(proc.stdout.split()) == {
            "locks", "threads", "jit-purity", "donation", "metrics",
            "spans", "use-after-donate", "thread-affinity",
            "determinism", "cross-artifact",
        }

    def test_cli_timings_and_budget(self, tmp_path):
        """--timings reports per-pass wall-clock; --budget turns a slow
        run into exit 2 (the make target keeps the suite honest)."""
        write(tmp_path, "parameter_server_tpu/__init__.py", "")
        write(tmp_path, "bench.py", "")
        cli = os.path.join(REPO, "script", "pslint", "cli.py")
        base = [
            sys.executable, cli, "--root", str(tmp_path),
            "--rules", "spans", "--no-cache",
        ]
        proc = subprocess.run(
            base + ["--timings"], capture_output=True, text=True, timeout=60
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "pslint: timing spans:" in proc.stderr
        assert "pslint: timing total:" in proc.stderr
        proc = subprocess.run(
            base + ["--budget", "0"], capture_output=True, text=True,
            timeout=60,
        )
        assert proc.returncode == 2
        assert "BUDGET EXCEEDED" in proc.stderr
