"""Chaos plane (doc/ROBUSTNESS.md): the deterministic fault-injection
registry (system/faults.py), the named fault points threaded through
Van/Executor/Heartbeat/Checkpoint/Ingest/serving, the retry/deadline
policy objects (utils/retry.py), the periodic consistent replica
backup, and degraded-mode serving. Every injected failure here is an
exercise of machinery that, before this plane existed, had only ever
been tested politely."""

import os
import threading
import time

import numpy as np
import pytest

from parameter_server_tpu.system import faults
from parameter_server_tpu.utils.retry import (
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    call_with_retry,
)


@pytest.fixture(autouse=True)
def _hermetic_faults():
    """Every test starts and ends with a disarmed default registry."""
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# the registry


class TestFaultRegistry:
    def test_disarmed_check_is_none_and_cheap(self):
        assert faults.check("van.transfer") is None
        assert faults.default_registry().n_armed == 0

    def test_unknown_point_rejected(self):
        with pytest.raises(ValueError, match="unknown fault point"):
            faults.arm("van.transfr")  # typo'd drills must not test nothing

    def test_after_n_calls_and_counters(self):
        faults.arm("executor.step", after_n_calls=2)
        assert faults.check("executor.step") is None
        assert faults.check("executor.step") is None
        assert faults.check("executor.step") is not None
        sp = faults.spec("executor.step")
        assert sp.calls == 3 and sp.fired == 1

    def test_once_disarms_after_first_fire(self):
        faults.arm("executor.step", once=True)
        assert faults.check("executor.step") is not None
        assert faults.check("executor.step") is None
        assert faults.default_registry().n_armed == 0

    def test_match_filters_and_does_not_count_mismatches(self):
        faults.arm("heartbeat.report", kind="silence", match="S0")
        assert faults.check("heartbeat.report", detail="W0") is None
        assert faults.check("heartbeat.report", detail="S0") is not None
        # only the matching call was counted
        faults.arm("heartbeat.report", kind="silence", match="S1",
                   after_n_calls=1)
        assert faults.check("heartbeat.report", detail="W0") is None
        assert faults.check("heartbeat.report", detail="S1") is None  # call 1
        assert faults.check("heartbeat.report", detail="S1") is not None

    def test_probability_deterministic_under_seed(self):
        def pattern(seed):
            reg = faults.FaultRegistry(seed=seed)
            reg.arm("van.transfer", kind="drop", probability=0.5)
            return [reg.check("van.transfer") is not None for _ in range(64)]

        a, b = pattern(123), pattern(123)
        assert a == b  # bit-identical firing pattern under one seed
        assert any(a) and not all(a)  # and it is actually probabilistic
        assert pattern(77) != a  # a different seed is a different drill

    def test_scoped_disarms_even_when_fault_propagates(self):
        with pytest.raises(faults.FaultError):
            with faults.scoped("executor.step", kind="raise"):
                faults.inject("executor.step")
        assert faults.spec("executor.step") is None

    def test_inject_sleeps_then_returns_spec_for_custom_kinds(self):
        faults.arm("serve.pull", kind="stall", delay_s=0.05)
        t0 = time.perf_counter()
        sp = faults.inject("serve.pull")
        assert sp is not None and sp.kind == "stall"
        assert time.perf_counter() - t0 >= 0.045


# ---------------------------------------------------------------------------
# retry / deadline policy


class TestRetryPolicy:
    def test_succeeds_after_transient_failures(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        slept = []
        assert call_with_retry(
            flaky, RetryPolicy(max_attempts=3, base_delay_s=0.01),
            sleep=slept.append,
        ) == "ok"
        assert len(calls) == 3 and len(slept) == 2
        assert slept[1] > slept[0] * 1.2  # exponential growth (jittered)

    def test_backoff_deterministic_under_seed(self):
        def delays(seed):
            out = []
            with pytest.raises(OSError):
                call_with_retry(
                    lambda: (_ for _ in ()).throw(OSError("x")),
                    RetryPolicy(max_attempts=4, base_delay_s=0.01),
                    seed=seed, sleep=out.append,
                )
            return out

        assert delays(5) == delays(5)

    def test_final_attempt_propagates_unwrapped(self):
        with pytest.raises(KeyError):
            call_with_retry(
                lambda: (_ for _ in ()).throw(KeyError("gone")),
                RetryPolicy(max_attempts=2, base_delay_s=0.0, jitter=0.0),
                sleep=lambda s: None,
            )

    def test_non_retryable_propagates_immediately(self):
        calls = []

        def boom():
            calls.append(1)
            raise KeyError("no")

        with pytest.raises(KeyError):
            call_with_retry(
                boom, RetryPolicy(max_attempts=5, retry_on=(OSError,)),
                sleep=lambda s: None,
            )
        assert len(calls) == 1

    def test_deadline_refuses_doomed_backoff(self):
        clock = [0.0]
        with pytest.raises(DeadlineExceeded) as ei:
            call_with_retry(
                lambda: (_ for _ in ()).throw(OSError("x")),
                RetryPolicy(
                    max_attempts=10, base_delay_s=5.0, deadline_s=1.0,
                    jitter=0.0,
                ),
                clock=lambda: clock[0], sleep=lambda s: None,
            )
        assert ei.value.deadline_s == 1.0
        assert isinstance(ei.value, TimeoutError)  # legacy callers fine

    def test_deadline_countdown(self):
        clock = [0.0]
        d = Deadline(2.0, clock=lambda: clock[0])
        assert not d.expired() and d.remaining() == 2.0
        clock[0] = 3.0
        assert d.expired()
        assert Deadline(None).remaining() is None


# ---------------------------------------------------------------------------
# executor fault point + diagnostic wait deadline


class TestExecutorFaults:
    def test_injected_raise_propagates_to_waiter(self):
        from parameter_server_tpu.system.executor import Executor

        ex = Executor(name="chaos")
        assert ex.wait(ex.submit(lambda: 1)) == 1
        with faults.scoped("executor.step", kind="raise", once=True):
            ts = ex.submit(lambda: 2)
            with pytest.raises(faults.FaultError):
                ex.wait(ts, timeout=10)
        # the executor survives the injected failure
        assert ex.wait(ex.submit(lambda: 3)) == 3
        ex.stop()

    def test_injected_stall_delays_dispatch(self):
        from parameter_server_tpu.system.executor import Executor

        ex = Executor(name="chaos_stall")
        with faults.scoped("executor.step", kind="stall", delay_s=0.1,
                           once=True):
            t0 = time.perf_counter()
            assert ex.wait(ex.submit(lambda: 4), timeout=10) == 4
            assert time.perf_counter() - t0 >= 0.09
        ex.stop()

    def test_wait_timeout_names_wedged_deps(self):
        from parameter_server_tpu.system.executor import Executor
        from parameter_server_tpu.system.message import Task

        ex = Executor(name="wedge")
        gate = threading.Event()
        dep = ex.submit(gate.wait)
        blocked = ex.submit(lambda: 9, Task(request=True, time=500,
                                            wait_time=[dep]))
        with pytest.raises(DeadlineExceeded) as ei:
            ex.wait(blocked, timeout=0.15)
        msg = str(ei.value)
        assert str(blocked) in msg and str(dep) in msg
        assert "unsatisfied wait_time deps" in msg
        gate.set()
        assert ex.wait(blocked, timeout=10) == 9  # still claimable after
        ex.stop()

    def test_wait_all_timeout_is_one_budget(self):
        from parameter_server_tpu.system.executor import Executor

        ex = Executor(name="drainwedge")
        gate = threading.Event()
        ex.submit(gate.wait)
        t0 = time.perf_counter()
        with pytest.raises(DeadlineExceeded):
            ex.wait_all(timeout=0.2)
        assert time.perf_counter() - t0 < 5
        gate.set()
        ex.wait_all(timeout=10)
        ex.stop()


# ---------------------------------------------------------------------------
# heartbeat silence + van wire faults


class TestTransportFaults:
    def test_heartbeat_silence_kills_exactly_the_matched_node(self):
        from parameter_server_tpu.system.heartbeat import (
            HeartbeatCollector,
            HeartbeatReport,
        )

        c = HeartbeatCollector(timeout=5.0)
        for nid in ("S0", "W0"):
            c.report(nid, HeartbeatReport(hostname=nid))
        t0 = time.time()
        faults.arm("heartbeat.report", kind="silence", match="S0")
        # both nodes keep "reporting"; only W0's reports arrive
        c.report("S0", HeartbeatReport())
        c.report("W0", HeartbeatReport())
        c._last_seen["W0"] = t0 + 10  # W0 heard from after the horizon
        assert c.dead_nodes(now=t0 + 6) == ["S0"]

    def test_van_drop_raises_and_never_counts_recv(self, mesh8):
        from parameter_server_tpu.system.remote_node import RemoteNode
        from parameter_server_tpu.system.van import Van
        from parameter_server_tpu.system.message import Message, Task

        van = Van(mesh8)
        a, b = RemoteNode("S0"), RemoteNode("W0")

        def msg():
            m = Message(task=Task(), sender="W0", recver="S0")
            m.values = [np.ones(32, np.float32)]
            return m

        van.transfer(a, b, msg())  # healthy round trip
        sent0, recv0 = van.wire_sent_bytes, van.wire_recv_bytes
        with faults.scoped("van.transfer", kind="drop", once=True):
            with pytest.raises(faults.FaultError):
                van.transfer(a, b, msg())
        assert van.wire_sent_bytes > sent0  # the frame left the sender
        assert van.wire_recv_bytes == recv0  # and never arrived

    def test_van_duplicate_delivers_twice(self, mesh8):
        from parameter_server_tpu.system.remote_node import RemoteNode
        from parameter_server_tpu.system.van import Van
        from parameter_server_tpu.system.message import Message, Task

        van = Van(mesh8)
        a, b = RemoteNode("S0"), RemoteNode("W0")

        def msg():
            m = Message(task=Task(), sender="W0", recver="S0")
            m.values = [np.ones(32, np.float32)]
            return m

        out = van.transfer(a, b, msg())
        single = van.wire_recv_bytes
        with faults.scoped("van.transfer", kind="duplicate", once=True):
            out = van.transfer(a, b, msg())
        assert out.values  # the (second) delivery still round-trips
        assert van.wire_recv_bytes == 3 * single  # frame decoded twice

    def test_van_delay_is_late_but_delivered(self, mesh8):
        from parameter_server_tpu.system.remote_node import RemoteNode
        from parameter_server_tpu.system.van import Van
        from parameter_server_tpu.system.message import Message, Task

        van = Van(mesh8)
        a, b = RemoteNode("S0"), RemoteNode("W0")
        m = Message(task=Task(), sender="W0", recver="S0")
        m.values = [np.ones(8, np.float32)]
        with faults.scoped("van.transfer", kind="delay", delay_s=0.08):
            t0 = time.perf_counter()
            out = van.transfer(a, b, m)
            assert time.perf_counter() - t0 >= 0.07
        assert out.values


# ---------------------------------------------------------------------------
# checkpoint crash consistency (die mid-write)


class TestCheckpointCrashConsistency:
    def _tree(self, v=1.0):
        return {"w": np.full((4, 2), v, np.float32),
                "step": np.array([v], np.float64)}

    def test_sync_die_mid_write_never_surfaces_torn_dir(self, tmp_path):
        from parameter_server_tpu.parameter.replica import CheckpointManager

        cm = CheckpointManager(str(tmp_path / "ck"), use_orbax=False)
        cm.save(1, self._tree(1.0))
        with faults.scoped("checkpoint.write", kind="die", once=True):
            with pytest.raises(faults.FaultError):
                cm.save(2, self._tree(2.0))
        # the crash window left a torn tmp dir — never a step dir
        names = os.listdir(cm.directory)
        assert any(n.endswith(".tmp") for n in names)
        assert cm.latest_step() == 1
        # a subsequent save HEALS: same step, fresh tmp, atomic rename
        cm.save(2, self._tree(2.0))
        assert cm.latest_step() == 2
        out = cm.restore(2, like=self._tree())
        np.testing.assert_array_equal(out["w"], self._tree(2.0)["w"])

    def test_async_die_reraises_from_wait_and_heals(self, tmp_path):
        from parameter_server_tpu.parameter.replica import CheckpointManager

        cm = CheckpointManager(str(tmp_path / "ck"), use_orbax=False)
        cm.save(5, self._tree(5.0))
        with faults.scoped("checkpoint.write", kind="die", once=True):
            cm.save_async(6, self._tree(6.0))
            with pytest.raises(RuntimeError, match="async checkpoint"):
                cm.wait()
        # the error was consumed by wait(); the torn step never lists
        assert cm.latest_step() == 5
        cm.save_async(6, self._tree(6.0))
        cm.wait()
        assert cm.latest_step() == 6

    def test_npz_fallback_template_mismatch_is_loud(self, tmp_path):
        from parameter_server_tpu.parameter.replica import CheckpointManager

        cm = CheckpointManager(str(tmp_path / "ck"), use_orbax=False)
        cm.save(1, self._tree(1.0))
        wrong = {"w": np.zeros((4, 2), np.float32),
                 "step": np.zeros(1),
                 "extra_moment": np.zeros(3)}
        with pytest.raises(ValueError, match="different model/optimizer"):
            cm.restore(1, like=wrong)


# ---------------------------------------------------------------------------
# ingest worker death


class TestIngestFaults:
    def test_prep_raise_forwards_at_position_and_joins(self):
        from parameter_server_tpu.learner.ingest import IngestPipeline

        before = threading.active_count()
        faults.arm("ingest.prep", kind="raise", after_n_calls=2, once=True)
        pipe = IngestPipeline(
            iter(range(6)), prep_fn=lambda x: x * 10, workers=2,
            name="chaos_ingest",
        ).start()
        got = []
        with pytest.raises(faults.FaultError):
            for item in pipe:
                got.append(item)
        assert got == [0, 10]  # batches before the dead one arrived
        pipe.close()
        deadline = time.time() + 10
        while threading.active_count() > before and time.time() < deadline:
            time.sleep(0.01)
        assert threading.active_count() <= before  # no leaked threads


# ---------------------------------------------------------------------------
# periodic consistent replica backup + barrier replay contract


class TestReplicaBackups:
    def _store(self, mesh8, name):
        from parameter_server_tpu.parameter.kv_vector import KVVector

        return KVVector(mesh=mesh8, k=2, num_slots=64, hashed=True,
                        name=name)

    def _push(self, kv, seed):
        rng = np.random.default_rng(seed)
        keys = rng.integers(0, 1 << 12, 16).astype(np.int64)
        vals = rng.normal(size=(16, 2)).astype(np.float32)
        ts = kv.push(kv.request(channel=0), keys=keys, values=vals)
        kv.executor.wait(ts, timeout=30)
        return ts, keys, vals

    def test_barrier_separates_snapshot_from_later_pushes(self, mesh8):
        from parameter_server_tpu.parameter.replica import ReplicaManager

        kv = self._store(mesh8, "bk_barrier")
        ts1, _, _ = self._push(kv, 1)
        rm = ReplicaManager()
        meta = rm.backup_consistent(kv)
        barrier = meta["barrier"][0]
        ts2, k2, v2 = self._push(kv, 2)
        assert ts1 < barrier < ts2
        after_two = np.array(kv.table(0, copy=True))
        # crash: wipe, recover from the snapshot, replay past the barrier
        kv.set_table(0, kv._zeros())
        assert rm.recover(kv, through_executor=True)
        kv.executor.wait(
            kv.push(kv.request(channel=0), keys=k2, values=v2), timeout=30
        )
        healed = np.array(kv.table(0, copy=True))
        assert healed.tobytes() == after_two.tobytes()  # bit-exact
        kv.executor.stop()

    def test_backup_consistent_untorn_under_live_pushes(self, mesh8):
        """The whole point of the submitted snapshot: a concurrent
        donated-push stream cannot tear the backup (each snapshot is
        SOME prefix of the push sequence, never a mix)."""
        from parameter_server_tpu.parameter.replica import ReplicaManager

        from parameter_server_tpu.parameter.kv_vector import KVVector

        # exact keys: one slot per key (a hashed directory's slot
        # collisions would double-count rows and fake a "torn" read)
        kv = KVVector(mesh=mesh8, k=2, num_slots=64, hashed=False,
                      name="bk_live")
        keys = np.arange(16, dtype=np.int64)
        kv.set_keys(0, keys)
        ones = np.ones((16, 2), np.float32)
        # one synchronous push first so channel 0 exists before the
        # first backup races the pusher's channel creation
        kv.executor.wait(
            kv.push(kv.request(channel=0), keys=keys, values=ones),
            timeout=30,
        )
        stop = threading.Event()
        err = []

        def pusher():
            try:
                while not stop.is_set():
                    kv.executor.wait(
                        kv.push(kv.request(channel=0), keys=keys,
                                values=ones),
                        timeout=30,
                    )
            except BaseException as e:
                err.append(e)

        t = threading.Thread(target=pusher)
        t.start()
        try:
            rm = ReplicaManager()
            for _ in range(5):
                rm.backup_consistent(kv)
                snap = rm._replicas[kv.name][0]
                rows = snap[kv.slots(0, keys)]
                # every pushed row shows the SAME number of pushes —
                # an integer multiple of ones, identical across rows
                counts = np.unique(rows)
                assert len(counts) == 1, counts
        finally:
            stop.set()
            t.join(timeout=30)
        assert not err
        kv.executor.stop()

    def test_periodic_loop_backs_up_and_joins(self, mesh8):
        from parameter_server_tpu.parameter.replica import ReplicaManager

        kv = self._store(mesh8, "bk_periodic")
        self._push(kv, 3)
        rm = ReplicaManager()
        rm.start_periodic(kv, interval_s=0.03)
        with pytest.raises(RuntimeError, match="already running"):
            rm.start_periodic(kv, interval_s=0.03)
        deadline = time.time() + 10
        while time.time() < deadline:
            meta = rm.meta(kv.name)
            if meta and meta["version"] >= 2:
                break
            time.sleep(0.01)
        rm.stop_periodic()
        meta = rm.meta(kv.name)
        assert meta and meta["version"] >= 2 and meta["consistent"]
        # the loop thread is gone; a second stop is a no-op
        rm.stop_periodic()
        assert rm.recover(kv)
        kv.executor.stop()


# ---------------------------------------------------------------------------
# recovery coordinator: retry + telemetry


class TestRecoveryRetryAndTelemetry:
    def _collector(self):
        from parameter_server_tpu.system.heartbeat import (
            HeartbeatCollector,
            HeartbeatReport,
        )

        c = HeartbeatCollector(timeout=5.0)
        c.report("S0", HeartbeatReport(hostname="S0"))
        return c

    def test_transient_handler_failure_retried_not_counted(self):
        from parameter_server_tpu.system.recovery import RecoveryCoordinator
        from parameter_server_tpu.telemetry.instruments import (
            recovery_instruments,
        )
        from parameter_server_tpu.telemetry.registry import default_registry

        reg = default_registry()
        recovery_instruments(reg)  # ensure the family exists to read
        fails_before = reg.get("ps_recovery_handler_failures_total").value()
        c = self._collector()
        rc = RecoveryCoordinator(
            c, handler_retry=RetryPolicy(max_attempts=3, base_delay_s=0.001)
        )
        attempts = []

        def flaky(nid):
            attempts.append(nid)
            if len(attempts) < 2:
                raise OSError("replacement shard mid-rebuild")

        rc.on_server_dead(flaky)
        assert rc.check(now=c._last_seen["S0"] + 6) == ["S0"]
        assert len(attempts) == 2  # retried once, then succeeded
        reg2 = default_registry()
        assert (
            reg2.get("ps_recovery_handler_failures_total").value()
            == fails_before
        )
        assert reg2.get("ps_recovery_deaths_total").value(role="server") >= 1

    def test_exhausted_handler_counts_failure(self):
        from parameter_server_tpu.system.recovery import RecoveryCoordinator
        from parameter_server_tpu.telemetry.instruments import (
            recovery_instruments,
        )
        from parameter_server_tpu.telemetry.registry import default_registry

        recovery_instruments(default_registry())
        before = default_registry().get(
            "ps_recovery_handler_failures_total"
        ).value()
        c = self._collector()
        rc = RecoveryCoordinator(
            c, handler_retry=RetryPolicy(max_attempts=2, base_delay_s=0.001)
        )
        rc.on_server_dead(
            lambda nid: (_ for _ in ()).throw(OSError("still dead"))
        )
        assert rc.check(now=c._last_seen["S0"] + 6) == ["S0"]
        assert default_registry().get(
            "ps_recovery_handler_failures_total"
        ).value() == before + 1


# ---------------------------------------------------------------------------
# degraded-mode serving (503 vs 429)


class TestDegradedServing:
    def _store(self, mesh8, name):
        from parameter_server_tpu.parameter.kv_vector import KVVector

        kv = KVVector(mesh=mesh8, k=1, num_slots=256, hashed=True, name=name)
        keys = np.arange(64, dtype=np.int64)
        vals = np.arange(64, dtype=np.float32).reshape(-1, 1) + 1.0
        kv.executor.wait(
            kv.push(kv.request(channel=0), keys=keys, values=vals),
            timeout=30,
        )
        return kv

    def _fe(self, kv, **cfg_kw):
        from parameter_server_tpu.serving import ServeConfig, ServeFrontend

        cfg = ServeConfig(workers=1, max_queue_depth=64, **cfg_kw)
        return ServeFrontend(kv, cfg).start()

    def test_fallback_mode_live_when_healthy(self, mesh8):
        from parameter_server_tpu.serving import PullRequest

        kv = self._store(mesh8, "deg_live")
        fe = self._fe(kv, replica="fallback")
        try:
            keys = np.array([1, 5, 9], np.int64)
            out = fe.submit(PullRequest(keys=keys)).result(30)
            np.testing.assert_allclose(out, kv.values(0, keys))
            assert fe.stats()["degraded_served"] == 0
        finally:
            fe.close()
        kv.executor.stop()

    def test_dead_store_degrades_to_stale_replica(self, mesh8):
        from parameter_server_tpu.serving import PullRequest

        kv = self._store(mesh8, "deg_stale")
        fe = self._fe(kv, replica="fallback", degraded_max_staleness_s=60.0)
        try:
            keys = np.array([2, 3], np.int64)
            fresh = fe.submit(PullRequest(keys=keys)).result(30)
            with faults.scoped("serve.pull", kind="raise"):
                stale = fe.submit(PullRequest(keys=keys)).result(30)
            np.testing.assert_array_equal(stale, fresh)
            assert fe.stats()["degraded_served"] == 1
        finally:
            fe.close()
        kv.executor.stop()

    def test_staleness_bound_turns_degraded_into_503(self, mesh8):
        from parameter_server_tpu.serving import DegradedError, PullRequest

        kv = self._store(mesh8, "deg_bound")
        fe = self._fe(kv, replica="fallback", degraded_max_staleness_s=0.0)
        try:
            time.sleep(0.02)  # replica age > 0 bound
            with faults.scoped("serve.pull", kind="raise"):
                with pytest.raises(DegradedError) as ei:
                    fe.submit(
                        PullRequest(keys=np.array([1], np.int64))
                    ).result(30)
            assert ei.value.reason == "stale"
        finally:
            fe.close()
        kv.executor.stop()

    def test_no_replica_is_503_not_429(self, mesh8):
        from parameter_server_tpu.serving import DegradedError, PullRequest

        kv = self._store(mesh8, "deg_noreplica")
        fe = self._fe(kv, replica="off")
        try:
            with faults.scoped("serve.pull", kind="raise"):
                with pytest.raises(DegradedError) as ei:
                    fe.submit(
                        PullRequest(keys=np.array([1], np.int64))
                    ).result(30)
            assert ei.value.reason == "no-replica"
        finally:
            fe.close()
        kv.executor.stop()

    def test_hot_replica_miss_with_dead_store_is_replica_miss(self, mesh8):
        from parameter_server_tpu.serving import DegradedError, PullRequest

        kv = self._store(mesh8, "deg_hotmiss")
        fe = self._fe(
            kv, replica="hot", hot_keys=np.arange(8, dtype=np.int64)
        )
        try:
            with faults.scoped("serve.pull", kind="raise"):
                # fully-hot requests still serve (replica-first path)
                out = fe.submit(
                    PullRequest(keys=np.array([1, 2], np.int64))
                ).result(30)
                assert out.shape == (2, 1)
                # a request with cold keys cannot be covered
                with pytest.raises(DegradedError) as ei:
                    fe.submit(
                        PullRequest(keys=np.array([1, 40], np.int64))
                    ).result(30)
            assert ei.value.reason == "replica-miss"
        finally:
            fe.close()
        kv.executor.stop()

    def test_shed_is_still_a_429_never_degraded(self, mesh8):
        """Overload and failure stay separately observable: a queue shed
        raises RejectedError even while the store path is dead."""
        from parameter_server_tpu.serving import (
            PullRequest,
            RejectedError,
            ServeConfig,
            ServeFrontend,
        )

        kv = self._store(mesh8, "deg_shed")
        fe = ServeFrontend(
            kv,
            ServeConfig(replica="fallback", workers=1, max_queue_depth=1,
                        coalesce_window_s=0.05),
        ).start()
        try:
            with faults.scoped("serve.pull", kind="stall", delay_s=0.2):
                first = fe.submit(PullRequest(keys=np.array([1], np.int64)))
                with pytest.raises(RejectedError) as ei:
                    for _ in range(8):  # the 1-deep lane must shed
                        fe.submit(PullRequest(keys=np.array([2], np.int64)))
                assert ei.value.reason == "queue"
                first.result(30)
        finally:
            fe.close()
        kv.executor.stop()

    def test_refresher_survives_refresh_faults(self, mesh8):
        """A dead shard's replica refresh fails; the background
        refresher keeps the last good snapshot and retries — it must
        not die and must recover once the store returns."""
        from parameter_server_tpu.serving import PullRequest

        kv = self._store(mesh8, "deg_refresh")
        fe = self._fe(kv, replica="fallback", replica_refresh_s=0.03)
        try:
            v0 = fe.replica.version
            faults.arm("serve.refresh", kind="raise")
            time.sleep(0.12)  # several failing refresh ticks
            faults.disarm("serve.refresh")
            deadline = time.time() + 10
            while fe.replica.version <= v0 and time.time() < deadline:
                time.sleep(0.01)
            assert fe.replica.version > v0  # refresher came back
            out = fe.submit(
                PullRequest(keys=np.array([7], np.int64))
            ).result(30)
            assert out.shape == (1, 1)
        finally:
            fe.close()
        kv.executor.stop()

    def test_ticket_deadline_is_diagnosable(self, mesh8):
        from parameter_server_tpu.serving import PullRequest

        kv = self._store(mesh8, "deg_ticket")
        fe = self._fe(kv, replica="fallback")
        try:
            with faults.scoped("serve.pull", kind="stall", delay_s=0.3):
                tk = fe.submit(PullRequest(keys=np.array([1], np.int64)))
                with pytest.raises(DeadlineExceeded):
                    tk.result(0.05)
                tk.result(30)  # the request itself still completes
        finally:
            fe.close()
        kv.executor.stop()


# ---------------------------------------------------------------------------
# the drill itself (smoke shape; the full run is `make chaos-bench`)


def test_recovery_drill_smoke():
    """Tier-1 acceptance: injected shard death under live train+serve
    load is detected and recovered with ZERO lost acknowledged updates
    — post-drill trajectory bit-identical to the undisturbed run."""
    from parameter_server_tpu.benchmarks.components import recovery_drill
    from parameter_server_tpu.system.postoffice import Postoffice

    try:
        out = recovery_drill(smoke=True)
    finally:
        Postoffice.reset()
    assert out["trajectory_bit_identical"] is True
    assert out["trainer_parked"] is True  # recovery ran AGAINST live
    # load (the trainer was parked mid-stream, not already finished)
    assert out["replayed_updates"] >= 1
    assert out["detection_ms"] > 0 and out["mttr_ms"] >= out["detection_ms"]
    assert out["serve"]["degraded_served"] >= 1
    assert out["serve"]["requests"] > 0
    assert out["backup_version_used"] >= 1
    assert out["disarmed_overhead"]["ratio_median"] > 0
