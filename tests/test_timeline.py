"""Timeline tracing + critical-path attribution (ISSUE 7 tentpole).

Covers: flow-id propagation across the real pipeline threads (feeder →
prep pool → consumer → executor step), the serve path's flow spans
(submit → execute → coalesced flush → reply), the abandoned-span
terminator from the pool's exception-forwarding path, the Chrome
trace-event export (schema invariants + a committed golden file), and
the attribution math on synthetic multi-thread traces with KNOWN
critical paths — upload-bound, compute-bound, and queue-bound runs must
each be attributed correctly.
"""

from __future__ import annotations

import json
import os
import sys
import threading

import numpy as np
import pytest

from parameter_server_tpu.system.executor import Executor
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.telemetry import (
    JsonlSink,
    close_sink,
    current_flow,
    flow_scope,
    install_sink,
    new_flow,
)
from parameter_server_tpu.telemetry import attribution, timeline
from parameter_server_tpu.telemetry import spans as telemetry_spans

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GOLDEN = os.path.join(os.path.dirname(__file__), "data", "timeline_golden.json")


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    Postoffice.reset()
    yield
    Postoffice.reset()


def _trace(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    install_sink(JsonlSink(path))
    return path


# ---------------------------------------------------------------------------
# flow primitives
# ---------------------------------------------------------------------------


class TestFlowScope:
    def test_ids_are_unique_and_scoped(self):
        a, b = new_flow(), new_flow()
        assert a != b
        assert current_flow() is None
        with flow_scope(a):
            assert current_flow() == a
            with flow_scope(b):
                assert current_flow() == b
            assert current_flow() == a
        assert current_flow() is None

    def test_none_scope_is_passthrough(self):
        with flow_scope(None):
            assert current_flow() is None

    def test_scope_is_thread_local(self):
        seen = {}

        def other():
            seen["flow"] = current_flow()

        with flow_scope(new_flow()):
            t = threading.Thread(target=other)
            t.start()
            t.join()
        assert seen["flow"] is None

    def test_span_attaches_active_flow(self, tmp_path):
        path = _trace(tmp_path)
        fid = new_flow()
        with flow_scope(fid):
            with telemetry_spans.span("unit.flowed"):
                pass
        with telemetry_spans.span("unit.unflowed"):
            pass
        close_sink()
        events = {e["name"]: e for e in timeline.load_events(path)}
        assert events["unit.flowed"]["flow"] == fid
        assert "flow" not in events["unit.unflowed"]
        # every event carries its emitting thread
        assert events["unit.flowed"]["thread"] == threading.current_thread().name

    def test_span_closes_with_error_attr_on_exception(self, tmp_path):
        path = _trace(tmp_path)
        with pytest.raises(ValueError):
            with telemetry_spans.span("unit.dies"):
                raise ValueError("boom")
        close_sink()
        (event,) = timeline.load_events(path)
        assert event["name"] == "unit.dies"
        assert event["error"] == "ValueError"
        assert event["dur_s"] >= 0.0


# ---------------------------------------------------------------------------
# cross-thread correlation through the real pipeline pieces
# ---------------------------------------------------------------------------


class TestPipelineFlows:
    def test_ingest_flow_rides_feeder_prep_and_executor(self, tmp_path):
        from parameter_server_tpu.learner.ingest import IngestPipeline

        path = _trace(tmp_path)
        pipe = IngestPipeline(
            range(5),
            filter_fn=lambda x: x,
            prep_fn=lambda x: x * 10,
            workers=2,
            name="flows",
        ).start()
        ex = Executor(name="flow_ex", telemetry=True)
        items = []
        for item in pipe:
            items.append(item)
            # the pipeline keeps the item's flow active on the consumer
            # thread, so a submit here correlates without plumbing
            ex.submit(lambda item=item: item + 1)
        ex.wait_all()
        ex.stop()
        close_sink()
        assert items == [0, 10, 20, 30, 40]  # bit-identical order kept
        by_flow = timeline.flows(timeline.load_events(path))
        chains = [
            [e["name"] for e in seq] for seq in by_flow.values()
        ]
        assert len(chains) == 5
        for chain in chains:
            assert chain == [
                "ingest.read", "ingest.filter", "ingest.prep",
                "executor.step",
            ]
        # the stages really ran on different threads
        threads_per_flow = [
            {e["thread"] for e in seq} for seq in by_flow.values()
        ]
        assert all(len(t) >= 2 for t in threads_per_flow)

    def test_ingest_without_sink_pays_nothing(self, monkeypatch):
        from parameter_server_tpu.learner import ingest as ingest_mod
        from parameter_server_tpu.learner.ingest import IngestPipeline

        # tracing off must mean span() is never even ENTERED — read,
        # filter and prep alike (the filter branch once paid the span
        # machinery unconditionally)
        def boom(*a, **k):
            raise AssertionError("span() entered with tracing off")

        monkeypatch.setattr(ingest_mod.telemetry_spans, "span", boom)
        pipe = IngestPipeline(
            range(4),
            filter_fn=lambda x: x,
            prep_fn=lambda x: x + 1,
            workers=2,
            name="off",
        ).start()
        assert list(pipe) == [1, 2, 3, 4]
        assert pipe._trace is False

    def test_device_uploader_hands_flow_to_consumer(self, tmp_path):
        from parameter_server_tpu.apps.linear.async_sgd import DeviceUploader

        path = _trace(tmp_path)

        class Prepped:
            num_examples = 4

        fids = [new_flow() for _ in range(3)]

        def source():
            for fid in fids:
                with flow_scope(fid):
                    yield Prepped(), 4

        up = DeviceUploader(source(), lambda p: p, depth=2)
        popped = []
        for _staged, n in up:
            assert n == 4
            popped.append(up.next_flow())
        up.close()
        close_sink()
        assert popped == fids  # FIFO with the item stream
        uploads = [
            e
            for e in timeline.load_events(path)
            if e["name"] == "ingest.upload"
        ]
        assert [e["flow"] for e in uploads] == fids

    def test_pool_worker_exception_emits_abandoned_terminator(self, tmp_path):
        from parameter_server_tpu.learner.ingest import IngestPipeline

        path = _trace(tmp_path)

        def prep(x):
            if x == 2:
                raise RuntimeError("poisoned batch")
            return x

        pipe = IngestPipeline(
            range(4), prep_fn=prep, workers=2, name="poison"
        ).start()
        got = []
        with pytest.raises(RuntimeError, match="poisoned batch"):
            for item in pipe:
                got.append(item)
        close_sink()
        assert got == [0, 1]  # exception at the position it occurred
        events = timeline.load_events(path)
        tombstones = [e for e in events if e.get("abandoned")]
        assert len(tombstones) == 1
        assert tombstones[0]["name"] == "poison.worker"
        assert tombstones[0]["reason"] == "RuntimeError"
        # the prep span itself closed WITH the error attr (the
        # context-managed-everywhere satellite: no open-ended spans)
        died = [e for e in events if e.get("error") == "RuntimeError"]
        assert any(e["name"] == "ingest.prep" for e in died)

    def test_executor_submit_captures_flow(self, tmp_path):
        path = _trace(tmp_path)
        ex = Executor(name="cap", telemetry=True)
        fid = new_flow()
        with flow_scope(fid):
            ts = ex.submit(lambda: 42)
        ex.wait(ts)
        ex.stop()
        close_sink()
        steps = [
            e
            for e in timeline.load_events(path)
            if e["name"] == "executor.step"
        ]
        assert steps and steps[0]["flow"] == fid


# ---------------------------------------------------------------------------
# serve-path flows: submit → execute → coalesced flush → reply
# ---------------------------------------------------------------------------


class _FakeStore:
    """Minimal pull protocol for the coalescer (no device, no mesh)."""

    def request(self, channel=0):
        return {"channel": channel}

    def pull(self, task, keys):
        self.last_keys = np.asarray(keys)
        return 7

    def wait_pull(self, ts):
        return np.stack([self.last_keys.astype(np.float32)] * 2, axis=1)


class TestServeFlows:
    def test_request_flow_spans_submit_to_reply(self, tmp_path):
        from parameter_server_tpu.serving.frontend import (
            PullRequest,
            ServeConfig,
            ServeFrontend,
        )

        path = _trace(tmp_path)
        fe = ServeFrontend(
            _FakeStore(),
            ServeConfig(replica="off", workers=1, coalesce_window_s=0.001),
        ).start()
        try:
            ticket = fe.submit(PullRequest(keys=np.array([3, 1, 2])))
            vals = ticket.result(timeout=10)
            np.testing.assert_allclose(vals[:, 0], [3, 1, 2])
            assert ticket.flow is not None
        finally:
            fe.close()
        close_sink()
        events = timeline.load_events(path)
        mine = [e for e in events if e.get("flow") == ticket.flow]
        names = [e["name"] for e in mine]
        assert names[0] == "serve.submit"
        assert "serve.execute" in names
        assert names[-1] == "serve.reply"
        # the coalescer's flush span names the request's flow as merged
        flush = [e for e in events if e["name"] == "serve.coalesce.flush"]
        assert flush and ticket.flow in flush[0]["flows"]
        # reply carries the measured latency
        reply = mine[-1]
        assert reply["latency_s"] >= 0.0

    def test_no_sink_means_no_flow_allocation(self, monkeypatch):
        from parameter_server_tpu.serving import frontend as frontend_mod
        from parameter_server_tpu.serving.frontend import (
            PullRequest,
            ServeConfig,
            ServeFrontend,
        )

        # the µs pull lane pays no span machinery when tracing is off:
        # a flow-less ticket must never enter span() on the worker
        def boom(*a, **k):
            raise AssertionError("span() entered on untraced request")

        monkeypatch.setattr(frontend_mod.telemetry_spans, "span", boom)
        fe = ServeFrontend(
            _FakeStore(),
            ServeConfig(replica="off", workers=1, coalesce_window_s=0.001),
        ).start()
        try:
            ticket = fe.submit(PullRequest(keys=np.array([1])))
            ticket.result(timeout=10)
            assert ticket.flow is None
        finally:
            fe.close()


# ---------------------------------------------------------------------------
# attribution: synthetic traces with KNOWN critical paths
# ---------------------------------------------------------------------------


def _span(name, t, dur, thread, flow=None, **attrs):
    ev = {
        "kind": "span", "name": name, "t_wall": t, "dur_s": dur,
        "thread": thread,
    }
    if flow is not None:
        ev["flow"] = flow
    ev.update(attrs)
    return ev


def _staged_run(prep_s, upload_s, device_s, launches=4):
    """Serialized launches: prep → upload → device back to back (the
    phase_breakdown shape), on three threads."""
    events, t = [], 100.0
    for i in range(launches):
        fid = 1000 + i
        events.append(_span("bench.prep", t, prep_s, "prep-thread", fid))
        t += prep_s
        events.append(_span("bench.upload", t, upload_s, "upload-thread", fid))
        t += upload_s
        events.append(_span("bench.device", t, device_s, "MainThread", fid))
        t += device_s
    return events


class TestAttribution:
    def test_upload_bound_run_is_attributed_to_upload(self):
        out = attribution.summarize(_staged_run(0.01, 0.10, 0.02))
        assert out["binding_resource"] == "upload"
        assert out["shares"]["upload"] == pytest.approx(
            0.10 / 0.13, abs=0.01
        )
        assert out["flows"]["dominant"] == "upload"
        assert out["binding_utilization"] == pytest.approx(
            0.10 / 0.13, abs=0.01
        )

    def test_compute_bound_run_is_attributed_to_device(self):
        out = attribution.summarize(_staged_run(0.01, 0.02, 0.10))
        assert out["binding_resource"] == "device_compute"
        assert out["flows"]["dominant"] == "device_compute"

    def test_host_bound_run_is_attributed_to_host_prep(self):
        out = attribution.summarize(_staged_run(0.10, 0.01, 0.02))
        assert out["binding_resource"] == "host_prep"

    def test_queue_bound_requests_dominated_by_queue_wait(self):
        # serve shape: submit marker, a long wait, a short execute, reply
        events = []
        for i in range(5):
            t = 10.0 + i * 0.3
            fid = 2000 + i
            events.append(_span("serve.submit", t, 0.0, "client", fid))
            events.append(
                _span("serve.execute", t + 0.2, 0.01, "serve-worker-0", fid)
            )
            events.append(
                _span("serve.reply", t + 0.211, 0.0, "serve-worker-0", fid)
            )
        out = attribution.summarize(events)
        assert out["flows"]["dominant"] == "queue_wait"
        shares = out["flows"]["critical_path_shares"]
        assert shares["queue_wait"] == pytest.approx(0.2 / 0.211, abs=0.02)

    def test_pull_execute_is_queue_wait_not_host_prep(self):
        # a pull's serve.execute blocks on the coalescer window + store
        # round trip inside PullTicket.result — billing it as host_prep
        # busy time would name the wrong binding resource under serve
        # load. predict execution is real host math and stays host_prep.
        pull = _span("serve.execute", 10.0, 0.05, "serve-worker-0", 1)
        pull["req"] = "pull"
        predict = _span("serve.execute", 10.0, 0.05, "serve-worker-1", 2)
        predict["req"] = "predict"
        assert attribution.categorize_event(pull) == "queue_wait"
        assert attribution.categorize_event(predict) == "host_prep"
        busy = attribution.busy_by_category([pull, predict])
        assert busy["queue_wait"] == pytest.approx(0.05)
        assert busy["host_prep"] == pytest.approx(0.05)

    def test_flush_flows_do_not_dilute_flow_view(self):
        # a coalescer flush flow's only duration-bearing span is the
        # uncategorized serve.coalesce.flush wrapper (executor phases
        # nest inside it), so its path has zero attributable time — it
        # must be excluded from the flow view instead of pushing every
        # category's median share toward zero
        events = []
        for i in range(3):  # request flows: mostly queue-wait
            t, fid = 10.0 + i, 100 + i
            events.append(_span("serve.submit", t, 0.0, "client", fid))
            ex = _span("serve.execute", t + 0.2, 0.01, "serve-worker-0", fid)
            ex["req"] = "pull"
            events.append(ex)
            events.append(_span("serve.reply", t + 0.211, 0.0, "serve-worker-0", fid))
        for i in range(3):  # flush flows: wrapper + nested executor step
            t, fid = 10.05 + i, 200 + i
            events.append(_span("serve.coalesce.flush", t, 0.1, "flusher", fid))
            events.append({
                "kind": "span", "name": "executor.step", "executor": "e",
                "ts": i, "t_wall": t + 0.09, "thread": "MainThread",
                "flow": fid, "queue_wait_s": 0.01, "run_s": 0.06,
                "materialize_s": 0.01, "total_s": 0.08,
            })
        out = attribution.attribute_flows(events)
        assert out["count"] == 3  # request flows only
        assert out["dominant"] == "queue_wait"
        assert out["critical_path_shares"]["queue_wait"] > 0.9

    def test_coalesce_flush_not_double_billed(self):
        # the flush span wraps the union merge + store pull whose work
        # the SAME flow's executor.step expansion already attributes —
        # the wrapper itself must stay uncategorized, not queue_wait
        flush = _span("serve.coalesce.flush", 10.0, 0.05, "flusher", 7)
        step = {
            "kind": "span", "name": "executor.step", "executor": "e",
            "ts": 1, "t_wall": 10.05, "thread": "MainThread", "flow": 7,
            "queue_wait_s": 0.01, "run_s": 0.03, "materialize_s": 0.01,
            "total_s": 0.05,
        }
        assert attribution.categorize_event(flush) is None
        busy = attribution.busy_by_category([flush, step])
        assert busy["queue_wait"] == pytest.approx(0.01)
        assert busy["device_compute"] == pytest.approx(0.04)

    def test_executor_step_expands_into_phases(self):
        events = [
            {
                "kind": "span", "name": "executor.step", "executor": "e",
                "ts": 3, "t_wall": 50.0, "thread": "MainThread", "flow": 9,
                "queue_wait_s": 0.4, "run_s": 0.1, "materialize_s": 0.1,
                "total_s": 0.6,
            }
        ]
        expanded = attribution.expand_executor_steps(events)
        names = [e["name"] for e in expanded]
        assert names == [
            "executor.queue_wait", "executor.run", "executor.materialize",
        ]
        assert all(e["flow"] == 9 for e in expanded)
        # phases tile [t_end - total, t_end] in order
        assert expanded[0]["t_wall"] == pytest.approx(49.4)
        assert expanded[-1]["t_wall"] + expanded[-1]["dur_s"] == pytest.approx(50.0)
        out = attribution.summarize(events)
        assert out["busy_s"]["queue_wait"] == pytest.approx(0.4)
        assert out["busy_s"]["device_compute"] == pytest.approx(0.2)

    def test_pipelined_overlap_not_double_counted_on_critical_path(self):
        # two flows whose device span overlaps the next flow's upload:
        # per-flow paths only count time past the cursor
        events = [
            _span("bench.upload", 0.0, 1.0, "up", 1),
            _span("bench.device", 0.5, 1.0, "main", 1),  # overlaps 0.5
        ]
        cp = attribution.flow_critical_path(events)
        assert cp["total_s"] == pytest.approx(1.5)
        assert cp["by_category"]["upload"] == pytest.approx(1.0)
        assert cp["by_category"]["device_compute"] == pytest.approx(0.5)

    def test_nested_encode_carved_out_of_host_prep(self):
        # wire.encode runs INSIDE the prep call on the prep thread
        # (worker.prep -> encode_exact), so its seconds bill to encode
        # alone — never doubly to host_prep
        events = [
            _span("bench.prep", 0.0, 1.0, "prep-thread", 1),
            _span("wire.encode", 0.3, 0.4, "prep-thread", 1, mode="exact"),
            _span("bench.device", 1.0, 0.5, "MainThread", 1),
        ]
        busy = attribution.busy_by_category(events)
        assert busy["host_prep"] == pytest.approx(0.6)
        assert busy["encode"] == pytest.approx(0.4)
        out = attribution.summarize(events)
        assert out["shares"]["host_prep"] == pytest.approx(0.6 / 1.5, abs=1e-4)
        assert out["shares"]["encode"] == pytest.approx(0.4 / 1.5, abs=1e-4)
        # an OVERLAPPING encode on another thread is parallel work, not
        # nesting — both resources really were busy; no carve-out
        parallel = [
            _span("bench.prep", 0.0, 1.0, "prep-thread", 1),
            _span("wire.encode", 0.3, 0.4, "other-thread", 2),
        ]
        busy2 = attribution.busy_by_category(parallel)
        assert busy2["host_prep"] == pytest.approx(1.0)
        assert busy2["encode"] == pytest.approx(0.4)

    def test_window_clips_busy_time(self):
        events = [_span("bench.upload", 0.0, 10.0, "up", 1)]
        out = attribution.summarize(events, window=(2.0, 4.0))
        assert out["busy_s"]["upload"] == pytest.approx(2.0)
        assert out["wall_s"] == pytest.approx(2.0)

    def test_flows_view_respects_window(self):
        # in-window flows are upload-bound; a later serialized
        # device-bound phase outside the window must stay out of the
        # per-flow median (bench.py's e2e section windows around the
        # timed stream, but the trace also holds breakdown-phase flows)
        timed = _staged_run(0.01, 0.10, 0.02)
        off = [
            dict(ev, t_wall=ev["t_wall"] + 500.0, flow=ev["flow"] + 100)
            for ev in _staged_run(0.01, 0.02, 0.30, launches=8)
        ]
        lo, hi = timeline.events_window(timed)
        out = attribution.summarize(timed + off, window=(lo, hi))
        assert out["flows"]["count"] == 4
        assert out["flows"]["dominant"] == "upload"
        # unwindowed, the off-phase flows swamp the median
        assert (
            attribution.summarize(timed + off)["flows"]["dominant"]
            == "device_compute"
        )

    def test_abandoned_spans_counted_not_attributed(self):
        events = _staged_run(0.01, 0.05, 0.01, launches=2)
        events.append(
            {
                "kind": "span", "name": "pool.worker", "t_wall": 101.0,
                "dur_s": 0.0, "thread": "w0", "abandoned": True,
                "reason": "RuntimeError",
            }
        )
        out = attribution.summarize(events)
        assert out["abandoned_spans"] == 1
        assert out["binding_resource"] == "upload"


# ---------------------------------------------------------------------------
# bench wiring: the attribution record section
# ---------------------------------------------------------------------------


class TestBenchAttribution:
    def test_attach_attribution_agrees_with_hand_breakdown(self, tmp_path):
        import bench

        path = str(tmp_path / "t.jsonl")
        with open(path, "w") as f:
            for ev in _staged_run(0.01, 0.10, 0.02):
                f.write(json.dumps({**ev, "phase": "breakdown"}) + "\n")
        rec = {
            "breakdown_fracs": {
                "host_prep": 0.077, "upload": 0.769, "device": 0.154,
            }
        }
        bench.attach_attribution(rec, path)
        att = rec["attribution"]
        assert att["binding_resource"] == "upload"
        assert att["shares"]["upload"] == pytest.approx(0.769, abs=0.1)
        assert att["agrees_with_hand_breakdown"] is True
        assert att["trace_jsonl"] == path

    def test_attach_attribution_flags_disagreement(self, tmp_path):
        import bench

        path = str(tmp_path / "t.jsonl")
        with open(path, "w") as f:
            for ev in _staged_run(0.01, 0.10, 0.02):
                f.write(json.dumps({**ev, "phase": "breakdown"}) + "\n")
        rec = {
            "breakdown_fracs": {
                "host_prep": 0.60, "upload": 0.20, "device": 0.20,
            }
        }
        bench.attach_attribution(rec, path)
        assert rec["attribution"]["agrees_with_hand_breakdown"] is False

    def test_attach_attribution_never_breaks_the_record(self):
        import bench

        rec = {}
        bench.attach_attribution(rec, "/nonexistent/path.jsonl")
        assert "attribution" not in rec
        assert "attribution_error" in rec
        bench.attach_attribution(rec, None)  # no sink: silent no-op

    def test_e2e_window_section(self, tmp_path):
        import bench

        path = str(tmp_path / "t.jsonl")
        events = _staged_run(0.01, 0.10, 0.02)
        with open(path, "w") as f:
            for ev in events:
                f.write(json.dumps({**ev, "phase": "e2e"}) + "\n")
        rec = {}
        lo, hi = timeline.events_window(events)
        bench.attach_attribution(rec, path, (lo, hi))
        e2e = rec["attribution"]["e2e"]
        assert e2e["binding_resource"] == "upload"
        assert e2e["wall_s"] == pytest.approx(hi - lo)


# ---------------------------------------------------------------------------
# Chrome trace export: schema + golden file
# ---------------------------------------------------------------------------


def _golden_events():
    """Fixed synthetic two-thread, two-flow timeline (stable across
    runs: hand-written wall times)."""
    return [
        _span("ingest.read", 1000.0, 0.010, "feeder", 11),
        _span("ingest.prep", 1000.012, 0.020, "pool-w0", 11),
        _span("ingest.read", 1000.011, 0.010, "feeder", 12),
        _span("ingest.prep", 1000.033, 0.020, "pool-w1", 12),
        _span(
            "serve.coalesce.flush", 1000.060, 0.005, "flusher", 13,
            merged=2, flows=[11, 12],
        ),
        {
            "kind": "span", "name": "poison.worker", "t_wall": 1000.070,
            "dur_s": 0.0, "thread": "pool-w0", "abandoned": True,
            "reason": "RuntimeError",
        },
    ]


class TestChromeExport:
    def test_schema_invariants(self):
        trace = timeline.to_chrome_trace(_golden_events())
        evs = trace["traceEvents"]
        phases = {e["ph"] for e in evs}
        assert phases == {"M", "X", "s", "f", "i"}
        # metadata names every thread track exactly once
        meta = [e for e in evs if e["ph"] == "M" and e["name"] == "thread_name"]
        assert len({m["tid"] for m in meta}) == len(meta) == 4
        # complete events carry µs ts + dur and echo their attrs
        xs = [e for e in evs if e["ph"] == "X"]
        assert all(e["dur"] >= 0 and "args" in e for e in xs)
        # flow arrows pair up: every start has a finish with the same id
        starts = [e for e in evs if e["ph"] == "s"]
        finishes = [e for e in evs if e["ph"] == "f"]
        assert sorted(e["id"] for e in starts) == sorted(
            e["id"] for e in finishes
        )
        # fan-in: both merged request flows arrow into the flush
        assert {e["id"] for e in starts} >= {11, 12}
        # abandoned tombstone is an instant event
        (inst,) = [e for e in evs if e["ph"] == "i"]
        assert "abandoned" in inst["name"]
        # valid JSON end to end
        json.dumps(trace)

    def test_matches_committed_golden(self):
        trace = timeline.to_chrome_trace(_golden_events())
        with open(GOLDEN) as f:
            golden = json.load(f)
        assert trace == golden, (
            "Chrome-trace export drifted from tests/data/timeline_golden"
            ".json — if the schema change is intentional, regenerate the "
            "golden (see its header note) and document it in "
            "doc/OBSERVABILITY.md"
        )

    def test_executor_step_renders_full_interval(self):
        # executor.step stamps t_wall at FINISH with no dur_s; the box
        # must span submit→finish, not sit as a 0-width sliver at the end
        events = [
            _span("ingest.read", 10.0, 0.1, "feeder", 1),
            {
                "kind": "span", "name": "executor.step", "t_wall": 10.8,
                "thread": "MainThread", "flow": 1, "total_s": 0.6,
                "queue_wait_s": 0.2, "run_s": 0.3, "materialize_s": 0.1,
            },
        ]
        trace = timeline.to_chrome_trace(events)
        (step,) = [
            e for e in trace["traceEvents"] if e.get("name") == "executor.step"
        ]
        assert step["dur"] == pytest.approx(0.6e6)
        assert step["ts"] == pytest.approx((10.2 - 10.0) * 1e6)

    def test_fan_in_arrow_anchors_before_flush(self):
        # the merged request's LAST span (serve.reply) postdates the
        # flush — the fan-in arrow must originate from the span
        # preceding the flush, clamped to flush start, never from the
        # future (backwards causality in Perfetto)
        events = [
            _span("serve.submit", 100.0, 0.0, "client", 21),
            _span("serve.execute", 100.010, 0.030, "serve-worker-0", 21),
            _span("serve.reply", 100.040, 0.0, "serve-worker-0", 21),
            _span(
                "serve.coalesce.flush", 100.020, 0.005, "flusher", 22,
                merged=1, flows=[21],
            ),
        ]
        trace = timeline.to_chrome_trace(events)
        flush_ts = next(
            e["ts"]
            for e in trace["traceEvents"]
            if e.get("name") == "serve.coalesce.flush"
        )
        arrows = [
            e for e in trace["traceEvents"]
            if e["ph"] in ("s", "f") and e["id"] == 21
        ]
        assert arrows
        assert all(e["ts"] <= flush_ts for e in arrows)
        assert any(e["ph"] == "f" and e["ts"] == flush_ts for e in arrows)

    def test_export_roundtrip_through_jsonl(self, tmp_path):
        jsonl = tmp_path / "t.jsonl"
        with open(jsonl, "w") as f:
            for ev in _golden_events():
                f.write(json.dumps(ev) + "\n")
            f.write("{half written")  # torn tail line must not break
        out = tmp_path / "t.json"
        trace = timeline.export_chrome_trace(str(jsonl), str(out))
        assert json.load(open(out)) == trace


def test_device_annotation_is_safe_everywhere():
    with timeline.device_annotation("unit.block"):
        pass
