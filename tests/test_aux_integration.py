"""Production heartbeat → recovery → dashboard integration (VERDICT r1 #4):
the aux subsystems must run inside an actual training loop, not only unit
tests. A worker dies mid-run on the 8-device mesh; the recovery
coordinator returns its workload to the pool and the surviving worker
finishes training every file."""

import threading
import time

import numpy as np
import pytest

from parameter_server_tpu.apps.linear.config import (
    Config,
    LearningRateConfig,
    LossConfig,
    PenaltyConfig,
    SGDConfig,
)
from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker
from parameter_server_tpu.learner.workload_pool import Workload, WorkloadPool
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.utils.sparse import random_sparse


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    yield
    Postoffice.reset()


def make_conf():
    conf = Config()
    conf.loss = LossConfig(type="logit")
    conf.penalty = PenaltyConfig(type="l2", lambda_=[0.1])
    conf.learning_rate = LearningRateConfig(alpha=0.5)
    conf.async_sgd = SGDConfig(algo="ftrl", num_slots=512, minibatch=64)
    return conf


def _batch_for(file_id: str, w_true):
    return random_sparse(128, 256, 6, seed=hash(file_id) % 1000, w_true=w_true)


def test_worker_death_mid_run_recovers_and_finishes(mesh8):
    po = Postoffice.instance()
    if not po.started:
        po.start()
    aux = po.start_aux(heartbeat_timeout=0.4)
    pool = WorkloadPool(Workload(files=[f"part-{i}" for i in range(6)]))
    aux.coordinator.on_worker_dead(pool.restore)
    aux.start(check_interval=0.05)

    rng = np.random.default_rng(0)
    w_true = (rng.normal(size=256) * (rng.random(256) < 0.2)).astype(np.float32)
    conf = make_conf()
    processed: dict[str, list] = {"W0": [], "W1": []}
    dead_evt = threading.Event()

    def worker_body(wid: str, die_after: int):
        worker = AsyncSGDWorker(conf, mesh=mesh8, name=wid)
        aux.register(wid)
        n = 0
        while True:
            load = pool.assign(wid)
            if load is None:
                # pool may refill when a dead peer's workload is restored
                if pool.wait_until_done(timeout=0.05):
                    return
                aux.beat(wid)
                continue
            for f in load.files:
                worker.train(iter([_batch_for(f, w_true)]))
                aux.beat(wid)
            n += 1
            if wid == "W1" and n >= die_after:
                # crash WITHOUT finishing the workload: it must come back
                # through the recovery path, not through pool bookkeeping
                dead_evt.set()
                return
            pool.finish(load.id)
            processed[wid].append(load.files)

    t1 = threading.Thread(target=worker_body, args=("W1", 1))
    t1.start()
    t1.join()
    assert dead_evt.is_set()

    # W1 is now silent; W0 keeps beating while the coordinator declares W1
    # dead and returns its unfinished file to the pool
    t0 = threading.Thread(target=worker_body, args=("W0", 10**9))
    t0.start()
    deadline = time.time() + 20
    while not pool.wait_until_done(timeout=0.2) and time.time() < deadline:
        pass
    t0.join(timeout=20)
    assert pool.wait_until_done(timeout=1), "training must finish after recovery"
    done_files = {f for loads in processed["W0"] for f in loads}
    assert len(done_files) == 6, "W0 must pick up W1's restored workload"
    # the dashboard saw both workers
    table = aux.dashboard.report()
    assert "W0" in table and "W1" in table
    aux.stop()


def test_dashboard_prints_on_interval(mesh8):
    po = Postoffice.instance()
    if not po.started:
        po.start()
    lines = []
    aux = po.start_aux(heartbeat_timeout=5.0, print_fn=lines.append)
    aux.register("W0")
    aux.start(check_interval=0.02, dashboard_interval=0.05)
    for _ in range(10):
        aux.beat("W0")
        time.sleep(0.02)
    aux.stop()
    assert lines and "W0" in lines[-1]


def test_beat_revives_recovered_node(mesh8):
    po = Postoffice.instance()
    if not po.started:
        po.start()
    aux = po.start_aux(heartbeat_timeout=0.1)
    seen = []
    aux.coordinator.on_worker_dead(seen.append)
    aux.register("W7")
    time.sleep(0.15)
    aux.coordinator.check()
    assert seen == ["W7"]
    aux.beat("W7")  # returned: future deaths must be detectable again
    time.sleep(0.15)
    aux.coordinator.check()
    assert seen == ["W7", "W7"]
