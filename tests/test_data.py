"""Data-plane tests: golden lines for every text format the reference's
ExampleParser handles (data/text_parser.cc: libsvm, criteo, adfea, terafea,
ps dense/sparse/sparse_binary), C++-vs-Python parser parity, and protobuf-text
config parsing of every shipped example conf (example/linear/*/*.conf)."""

import glob
import os

import numpy as np
import pytest

from parameter_server_tpu.apps.linear.config import parse_conf
from parameter_server_tpu.data.text_parser import (
    SLOT_SPACE,
    ExampleParser,
    parse_adfea,
    parse_criteo,
    parse_libsvm,
    parse_ps_dense,
    parse_ps_sparse,
    parse_ps_sparse_binary,
    parse_terafea,
)

CONF_DIR = os.path.join(os.path.dirname(__file__), "..", "configs")


class TestGoldenLines:
    def test_libsvm(self):
        b = parse_libsvm(["1 3:0.5 7:2", "-1 1:1", "0 2:4"])
        assert b.n == 3 and b.nnz == 4
        np.testing.assert_array_equal(b.y, [1, -1, -1])  # label>0 → +1 else -1
        np.testing.assert_array_equal(b.indices[:2], [3, 7])
        np.testing.assert_allclose(b.values[:3], [0.5, 2.0, 1.0])

    def test_libsvm_skips_garbage(self):
        b = parse_libsvm(["", "notalabel 1:2", "1 5:1"])
        assert b.n == 1 and b.indices[0] == 5

    def test_criteo(self):
        from parameter_server_tpu.data.text_parser import _CRITEO_STRIPE
        from parameter_server_tpu.utils.murmur import murmur3_x64_128

        line = "1\t" + "\t".join(str(i) for i in range(1, 14)) + "\t" + "\t".join(
            ["68fd1e64"] * 26
        )
        b = parse_criteo([line, line.replace("1\t", "0\t", 1)])
        assert b.n == 2 and b.nnz == 78 and b.binary
        np.testing.assert_array_equal(b.y, [1, -1])
        # reference key construction: integer slot i, count c -> binary key
        # kMaxKey/13*i + c (ParseCriteo, text_parser.cc)
        assert np.uint64(b.indices[0]) == np.uint64(1)  # i=0, cnt=1
        assert np.uint64(b.indices[12]) == np.uint64(
            (_CRITEO_STRIPE * 12 + 13) & ((1 << 64) - 1)
        )
        # categorical tokens: murmur3_x64_128 h0^h1, seed 512927377
        h0, h1 = murmur3_x64_128(b"68fd1e64", 512927377)
        assert np.uint64(b.indices[13]) == np.uint64(h0 ^ h1)

    def test_criteo_missing_fields(self):
        # EMPTY int fields parse as count 0 (strtoi32("") is a
        # successful no-conversion in the reference) -> key stripe*i+0;
        # short (<5 char) categorical tokens skipped; a line without
        # the 13 int tabs is dropped entirely
        ints = ["", "2"] + [""] * 11
        cats = ["abc"] + ["longtoken"] + [""] * 24
        b = parse_criteo(
            ["1\t" + "\t".join(ints) + "\t" + "\t".join(cats), "1\t2\t3"]
        )
        assert b.n == 1 and b.nnz == 14  # 13 int keys + 1 long cat
        from parameter_server_tpu.data.text_parser import _CRITEO_STRIPE

        # empty field 0 -> count 0; explicit "2" in slot i=1 -> count 2
        assert np.uint64(b.indices[0]) == np.uint64(0)
        assert np.uint64(b.indices[1]) == np.uint64(
            (_CRITEO_STRIPE * 1 + 2) & ((1 << 64) - 1)
        )

    def test_criteo_python_matches_native(self):
        from parameter_server_tpu.data.text_parser import _parse_native

        rng = np.random.default_rng(3)
        lines = []
        for _ in range(50):
            ints = [str(rng.integers(-2, 50)) if rng.random() > 0.2 else "" for _ in range(13)]
            cats = [f"{rng.integers(0, 1 << 32):08x}" if rng.random() > 0.3 else "ab" for _ in range(26)]
            lines.append(f"{rng.integers(0, 2)}\t" + "\t".join(ints) + "\t" + "\t".join(cats))
        py = parse_criteo(lines)
        cc = _parse_native(("\n".join(lines) + "\n").encode(), "ps_parse_criteo", 60)
        if cc is None:  # no native lib in this environment
            return
        np.testing.assert_array_equal(py.indices, cc.indices)
        np.testing.assert_array_equal(py.indptr, cc.indptr)
        np.testing.assert_array_equal(py.y, cc.y)

    def test_adfea(self):
        # ref ParseAdfea tokens (split on " :"): line_id, "1", label, then
        # key:slot pairs — text_parser.cc:90-121
        b = parse_adfea(["100 1 1 123:4 456:7", "101 1 0 789:2"])
        assert b.n == 2 and b.nnz == 3
        np.testing.assert_array_equal(b.y, [1, -1])
        assert b.indices[0] == 4 * SLOT_SPACE + 123
        assert b.indices[2] == 2 * SLOT_SPACE + 789
        assert b.binary

    def test_terafea(self):
        # ref ParseTerafea: "label line_id separator key key ..."; group id
        # rides in key >> 54, whole key is the feature id
        k1 = (3 << 54) | 123
        k2 = (3 << 54) | 456
        k3 = (9 << 54) | 123
        b = parse_terafea([f"1 1000 | {k1} {k2} {k3}", f"-1 1001 | {k1}"])
        assert b.n == 2 and b.nnz == 4
        np.testing.assert_array_equal(b.y, [1, -1])
        # whole-key identity: same key maps identically across rows,
        # different group bits keep same low bits distinct
        assert b.indices[0] == b.indices[3] == k1
        assert b.indices[2] == k3 != k1

    def test_ps_sparse(self):
        b = parse_ps_sparse(["1;2 3:0.5 4:1.5;7 9:2;", "-1;2 3:1;"])
        assert b.n == 2 and b.nnz == 4
        assert b.indices[0] == 2 * SLOT_SPACE + 3
        assert b.indices[2] == 7 * SLOT_SPACE + 9
        np.testing.assert_allclose(b.values[:3], [0.5, 1.5, 2.0])

    def test_ps_sparse_binary(self):
        b = parse_ps_sparse_binary(["1;2 3 4;7 9;", "0;2 3;"])
        assert b.n == 2 and b.nnz == 4 and b.binary
        np.testing.assert_array_equal(b.y, [1, -1])
        assert b.indices[0] == 2 * SLOT_SPACE + 3
        assert b.indices[2] == 7 * SLOT_SPACE + 9

    def test_ps_dense(self):
        b = parse_ps_dense(["1;2 0.5 1.5 2.5;", "-1;2 9;"])
        assert b.n == 2 and b.nnz == 4
        # positional keys within the group stripe
        np.testing.assert_array_equal(
            b.indices[:3] - 2 * SLOT_SPACE, [0, 1, 2]
        )
        np.testing.assert_allclose(b.values[:3], [0.5, 1.5, 2.5])


class TestNativeParity:
    """The C++ fast path must produce byte-identical CSR output to the
    Python fallback (ref: one parser, two deployments)."""

    @pytest.mark.parametrize("fmt,lines", [
        (
            "libsvm",
            ["1 3:0.5 7:2", "-1 1:1 2:0.25 9:4", "1 5:1"],
        ),
        (
            "criteo",
            [
                "1\t" + "\t".join(str(i) for i in range(1, 14))
                + "\t" + "\t".join(["68fd1e64", "80e26c9b"] * 13),
                # well-formed line with empty numeric/categorical fields
                # (the common Criteo missing-value shape)
                "0\t" + "\t".join(["", "2", ""] + [str(i) for i in range(3, 13)])
                + "\t" + "\t".join((["a1b2c3", ""] * 13)),
            ],
        ),
    ])
    def test_native_matches_python(self, fmt, lines):
        native = ExampleParser(fmt, use_native=True)
        python = ExampleParser(fmt, use_native=False)
        if not native.use_native:
            pytest.skip("native lib unavailable")
        a, b = native.parse_lines(lines), python.parse_lines(lines)
        np.testing.assert_array_equal(a.y, b.y)
        np.testing.assert_array_equal(a.indptr, b.indptr)
        np.testing.assert_array_equal(a.indices, b.indices)
        assert a.binary == b.binary
        if not a.binary:
            np.testing.assert_allclose(a.values, b.values)
        np.testing.assert_array_equal(a.slot_ids, b.slot_ids)


class TestParserFuzz:
    """Seeded mutation fuzz: the C++ fast paths must stay BIT-EXACT with
    the Python parsers on mangled input, not just on well-formed lines —
    truncations, garbage bytes, doubled separators, blank lines, and
    spliced fragments (the classes behind every past parity bug)."""

    def _mutate(self, rng, line: str) -> str:
        ops = rng.integers(0, 7)
        if ops == 0 and len(line) > 2:  # truncate anywhere
            return line[: rng.integers(1, len(line))]
        if ops == 5 and "\t" in line:  # empty out one criteo field
            f = line.split("\t")
            f[int(rng.integers(0, len(f)))] = ""
            return "\t".join(f)
        if ops == 6 and line:  # long leading-zero run before a digit
            # (strtoull/strtol accumulate magnitude — a digit-COUNT
            # overflow guard must not clamp '00…07' to ULLONG_MAX)
            i = rng.integers(0, len(line))
            return line[:i] + "0" * int(rng.integers(15, 30)) + line[i:]
        if ops == 1:  # inject a garbage byte
            i = rng.integers(0, len(line) + 1)
            ch = chr(rng.integers(33, 127))
            return line[:i] + ch + line[i:]
        if ops == 2 and line:  # double a separator
            i = rng.integers(0, len(line))
            return line[:i] + ("\t" if rng.random() < 0.5 else " ") + line[i:]
        if ops == 3:  # blank/whitespace-only line
            return " " * int(rng.integers(0, 4))
        if ops == 4 and len(line) > 4:  # splice two halves of itself
            i = rng.integers(1, len(line) - 1)
            return line[i:] + line[:i]
        return line

    def _wellformed(self, rng, fmt: str) -> str:
        if fmt == "criteo":
            ints = "\t".join(str(rng.integers(0, 100)) for _ in range(13))
            cats = "\t".join(
                f"{rng.integers(0, 1 << 32):08x}" for _ in range(26)
            )
            return f"{rng.integers(0, 2)}\t{ints}\t{cats}"
        # libsvm: ragged sparse rows, occasional explicit values;
        # indices SORTED — the strict parser drops unordered lines, and
        # unsorted generation would leave the value-parity path barely
        # exercised (mutations still cover the unordered-drop case)
        n = rng.integers(1, 6)
        idxs = np.sort(rng.integers(1, 1 << 20, size=n))
        feats = " ".join(
            f"{i}:{rng.integers(1, 5)}" if rng.random() < 0.5 else f"{i}:1"
            for i in idxs
        )
        return f"{(-1) ** rng.integers(0, 2)} {feats}"

    @pytest.mark.parametrize("fmt", ["libsvm", "criteo"])
    def test_mutated_lines_stay_bit_exact(self, fmt):
        native = ExampleParser(fmt, use_native=True)
        python = ExampleParser(fmt, use_native=False)
        if not native.use_native:
            pytest.skip("native lib unavailable")
        rng = np.random.default_rng(0)
        for trial in range(200):
            lines = []
            for _ in range(int(rng.integers(1, 8))):
                line = self._wellformed(rng, fmt)
                if rng.random() < 0.7:
                    line = self._mutate(rng, line)
                lines.append(line)
            a = native.parse_lines(lines)
            b = python.parse_lines(lines)
            ctx = f"trial {trial}: {lines!r}"
            np.testing.assert_array_equal(a.y, b.y, err_msg=ctx)
            np.testing.assert_array_equal(a.indptr, b.indptr, err_msg=ctx)
            np.testing.assert_array_equal(a.indices, b.indices, err_msg=ctx)
            assert a.binary == b.binary, ctx
            if not a.binary:
                # BIT-exact, not approximately equal — a 1-ulp strtod/
                # float() divergence is exactly what this test hunts
                np.testing.assert_array_equal(a.values, b.values, err_msg=ctx)
            np.testing.assert_array_equal(a.slot_ids, b.slot_ids, err_msg=ctx)

    def test_empty_tokens_parse_as_zero_like_reference(self):
        """strtonum.h treats strtoull("")/strtof("")/strtol("") as
        success with 0 (no conversion, end at the terminator). So
        ":5" is feature id 0, "7:" is value 0, an empty criteo label
        is class -1, and an EMPTY criteo int field emits key
        stripe*i+0 (that's how real criteo marks missing ints)."""
        for fmt in ("libsvm", "criteo"):
            python = ExampleParser(fmt, use_native=False)
            native = ExampleParser(fmt, use_native=True)
            if fmt == "libsvm":
                lines = ["1 :5 9:", "-1 :"]
                a = python.parse_lines(lines)
                assert a.y.tolist() == [1.0, -1.0]
                assert a.indices.tolist() == [0, 9, 0]
                assert a.values.tolist() == [5.0, 0.0, 0.0]
            else:
                ints = ["1"] * 13
                ints[3] = ""          # missing int -> key stripe*3 + 0
                cats = ["deadbeef"] * 26
                lines = ["\t".join([""] + ints + cats)]  # empty label
                a = python.parse_lines(lines)
                assert a.y.tolist() == [-1.0]  # label 0 -> negative
                from parameter_server_tpu.data.text_parser import (
                    _CRITEO_STRIPE,
                )
                assert (_CRITEO_STRIPE * 3) in (
                    np.asarray(a.indices, np.uint64).tolist()
                )
            if native.use_native:
                b = native.parse_lines(lines)
                np.testing.assert_array_equal(a.y, b.y)
                np.testing.assert_array_equal(a.indices, b.indices)
                np.testing.assert_array_equal(a.indptr, b.indptr)
                if not a.binary:
                    np.testing.assert_array_equal(a.values, b.values)

    @pytest.mark.parametrize("fmt,lines,want_indices", [
        # strtoull accumulates: 21 digits of mostly zeros is 7, not a
        # clamp to ULLONG_MAX (which would also drop the line as
        # unordered since ULLONG_MAX > 9 fails the sorted-ids check)
        ("libsvm", ["1 000000000000000000007:1 9:1"], [7, 9]),
        # criteo integer field: 20 zero-padded digits parse to key 5
        # in slot 6 (stripe 5), not strtol-ERANGE
        ("criteo", None, None),
    ])
    def test_leading_zero_runs_parse_by_magnitude(self, fmt, lines, want_indices):
        python = ExampleParser(fmt, use_native=False)
        native = ExampleParser(fmt, use_native=True)
        if fmt == "criteo":
            ints = ["1"] * 13
            ints[5] = "00000000000000000005"
            cats = ["00000000"] * 26
            lines = ["0\t" + "\t".join(ints + cats)]
        a = python.parse_lines(lines)
        if fmt == "libsvm":
            assert a.indices.tolist() == want_indices, a.indices
        else:
            from parameter_server_tpu.data.text_parser import _CRITEO_STRIPE
            assert (_CRITEO_STRIPE * 5 + 5) in a.indices.tolist()
        if native.use_native:
            b = native.parse_lines(lines)
            np.testing.assert_array_equal(a.indices, b.indices)
            np.testing.assert_array_equal(a.indptr, b.indptr)


class TestPythonOnlyParserRobustness:
    """adfea/terafea/ps_* have no native twin to diverge from, but they
    must never RAISE on mangled input and must always emit a consistent
    CSR (monotone indptr, matching array lengths)."""

    def _check_csr(self, b):
        assert b.indptr[0] == 0
        assert (np.diff(b.indptr) >= 0).all()
        assert b.indptr[-1] == len(b.indices)
        assert len(b.y) == len(b.indptr) - 1
        if b.values is not None:
            assert len(b.values) == len(b.indices)
        if b.slot_ids is not None:
            assert len(b.slot_ids) == len(b.indices)

    def test_mangled_lines_never_raise(self):
        from parameter_server_tpu.data.text_parser import (
            parse_ps_dense,
            parse_ps_sparse,
            parse_ps_sparse_binary,
        )

        parsers = {
            "adfea": parse_adfea,
            "terafea": parse_terafea,
            "ps_sparse": parse_ps_sparse,
            "ps_sparse_binary": parse_ps_sparse_binary,
            "ps_dense": parse_ps_dense,
        }
        seeds = {
            "adfea": "100 1 1 123:4 456:7",
            "terafea": "1 1000 | 123 456",
            "ps_sparse": "1;2 3:0.5 4:1.5;7 9:2;",
            "ps_sparse_binary": "1;2 3 4;7 9;",
            "ps_dense": "1;2 0.5 1.5 2.5;",
        }
        rng = np.random.default_rng(7)
        for name, fn in parsers.items():
            base = seeds[name]
            for trial in range(200):
                line = base
                for _ in range(int(rng.integers(1, 4))):
                    op = rng.integers(0, 5)
                    if op == 0 and len(line) > 2:
                        line = line[: rng.integers(1, len(line))]
                    elif op == 1:
                        i = rng.integers(0, len(line) + 1)
                        line = line[:i] + chr(rng.integers(33, 127)) + line[i:]
                    elif op == 2 and line:
                        i = rng.integers(0, len(line))
                        line = line[:i] + (";" if rng.random() < 0.5 else ":") + line[i:]
                    elif op == 3:
                        line = ""
                    elif op == 4 and len(line) > 4:
                        i = rng.integers(1, len(line) - 1)
                        line = line[i:] + line[:i]
                b = fn([line, base])  # mangled + a good line
                self._check_csr(b)
                assert b.n >= 1, (name, line)  # the good line always survives


class TestSlotIds:
    """Per-entry feature-group slots, matching the reference Example proto
    (text_parser.cc Slot.set_id: libsvm → 1; criteo int i → i+1, cat i →
    i+14; adfea/ps → group id; terafea → key >> 54)."""

    def test_criteo_slots(self):
        line = "1\t" + "\t".join(str(i) for i in range(1, 14)) + "\t" + "\t".join(
            ["68fd1e64"] * 26
        )
        b = parse_criteo([line])
        np.testing.assert_array_equal(b.slot_ids[:13], np.arange(1, 14))
        np.testing.assert_array_equal(b.slot_ids[13:], np.arange(14, 40))

    def test_criteo_truncated_cat_line_dropped(self):
        # ref ParseCriteo: a tab missing before the 25th categorical field
        # (i != 25) returns false — the whole line is dropped
        good = "1\t" + "\t".join(str(i) for i in range(1, 14)) + "\t" + "\t".join(
            ["68fd1e64"] * 26
        )
        truncated = "1\t" + "\t".join(str(i) for i in range(1, 14)) + "\t" + "\t".join(
            ["68fd1e64"] * 10
        )
        for use_native in (False, True):
            p = ExampleParser("criteo", use_native=use_native)
            if use_native and not p.use_native:
                continue
            b = p.parse_lines([good, truncated, good])
            assert b.n == 2, "truncated line must be dropped"

    def test_libsvm_slots(self):
        b = parse_libsvm(["1 3:0.5 7:2", "-1 1:1"])
        np.testing.assert_array_equal(b.slot_ids, [1, 1, 1])

    def test_adfea_slots(self):
        b = parse_adfea(["100 1 1 123:4 456:7", "101 1 0 789:2"])
        np.testing.assert_array_equal(b.slot_ids, [4, 7, 2])

    def test_terafea_slots(self):
        k1, k2 = (3 << 54) | 123, (9 << 54) | 456
        b = parse_terafea([f"1 1000 | {k1} {k2}"])
        np.testing.assert_array_equal(b.slot_ids, [3, 9])

    def test_ps_sparse_slots(self):
        b = parse_ps_sparse(["1;2 3:0.5 4:1.5;7 9:2;"])
        np.testing.assert_array_equal(b.slot_ids, [2, 2, 7])

    def test_record_roundtrip_keeps_slots(self):
        from parameter_server_tpu.data.example import batch_from_bytes, batch_to_bytes

        b = parse_criteo(
            [
                "1\t" + "\t".join(str(i) for i in range(1, 14)) + "\t"
                + "\t".join(["68fd1e64"] * 26)
            ]
        )
        rt = batch_from_bytes(batch_to_bytes(b))
        np.testing.assert_array_equal(rt.slot_ids, b.slot_ids)
        np.testing.assert_array_equal(rt.indices, b.indices)

    def test_slice_and_localize_keep_slots(self):
        from parameter_server_tpu.utils.localizer import remap

        b = parse_libsvm(["1 3:0.5 7:2", "-1 1:1", "1 9:2"])
        s = b.slice_rows(0, 2)
        np.testing.assert_array_equal(s.slot_ids, [1, 1, 1])
        kept = remap(b, np.array([1, 3, 9], dtype=np.int64))
        assert kept.slot_ids is not None and len(kept.slot_ids) == kept.nnz


class TestShippedConfigs:
    """Every conf under configs/ must parse (mirrors the reference's
    example/linear/* protobuf-text files driving main.cc)."""

    @pytest.mark.parametrize(
        "path",
        sorted(glob.glob(os.path.join(CONF_DIR, "*", "*.conf"))),
        ids=lambda p: "/".join(p.split(os.sep)[-2:]),
    )
    def test_parses(self, path):
        conf = parse_conf(open(path).read())
        assert conf.training_data or conf.validation_data
        if "batch" in os.path.basename(path) and "eval" not in os.path.basename(path):
            assert conf.darlin is not None
        if "online" in os.path.basename(path) and "eval" not in os.path.basename(path):
            assert conf.async_sgd is not None
        if "eval" in os.path.basename(path):
            assert conf.model_input is not None and conf.validation_data is not None


class TestFileMatching:
    def test_expand_globs_reference_regex(self, tmp_path):
        """Reference configs use basename REGEX patterns like "part.*"
        (data/common.cc searchFiles) — they must match part-0, part-1."""
        from parameter_server_tpu.utils import file as psfile

        d = tmp_path / "train"
        d.mkdir()
        for name in ("part-0", "part-1", "other.txt"):
            (d / name).write_text("x")
        hits = psfile.expand_globs([str(d / "part.*")])
        assert [os.path.basename(h) for h in hits] == ["part-0", "part-1"]
        # shell glob still works and wins when it matches
        hits = psfile.expand_globs([str(d / "*.txt")])
        assert [os.path.basename(h) for h in hits] == ["other.txt"]
        # regex is anchored: "art.*" must not match "part-0"
        assert psfile.expand_globs([str(d / "art.*")]) == []


class TestByteStreaming:
    """Chunked byte path (StreamReader.minibatches_bytes / parse_text):
    must yield exactly the same minibatches as the line path — chunk
    boundaries, thread-pool ordering and the tail batch included."""

    def _write_criteo(self, path, rows, seed=0):
        rng = np.random.default_rng(seed)
        with open(path, "w") as f:
            for i in range(rows):
                ints = "\t".join(str(v) for v in rng.integers(0, 50, 13))
                cats = "\t".join(
                    f"{v:08x}" for v in rng.integers(0, 1 << 24, 26)
                )
                f.write(f"{i % 2}\t{ints}\t{cats}\n")

    def test_matches_line_path(self, tmp_path):
        from parameter_server_tpu.data.stream_reader import StreamReader

        p = tmp_path / "part-0"
        self._write_criteo(str(p), rows=997)
        line_batches = list(StreamReader([str(p)], "criteo").minibatches(256))
        byte_batches = list(
            StreamReader([str(p)], "criteo").minibatches_bytes(
                256, chunk_bytes=1 << 14, threads=3
            )
        )
        assert len(line_batches) == len(byte_batches) == 4
        for a, b in zip(line_batches, byte_batches):
            np.testing.assert_array_equal(a.y, b.y)
            np.testing.assert_array_equal(a.indptr, b.indptr)
            np.testing.assert_array_equal(a.indices, b.indices)
            np.testing.assert_array_equal(a.slot_ids, b.slot_ids)

    def test_parse_text_equals_parse_lines(self):
        lines = ["1 3:1 7:2", "-1 1:4 9:1"]
        p = ExampleParser("libsvm")
        a = p.parse_lines(lines)
        b = p.parse_text(("\n".join(lines) + "\n").encode())
        np.testing.assert_array_equal(a.indices, b.indices)
        np.testing.assert_array_equal(a.y, b.y)


class TestLibsvmFastPaths:
    """Regression for the manual-parse fast paths (label, index, integer
    value): must stay bit-exact with the Python parser on floats,
    exponents, and values beyond double's exact-integer range."""

    def test_native_matches_python_on_edge_values(self):
        sample = (
            "+1 3:1 7:0.25 9:2\n"
            "-1 1:1e-3 2:1\n"
            "0 5:1\n"
            "2.5 4:9007199254740993\n"  # 2^53+1: must take the strtod path
        )
        p = ExampleParser("libsvm")
        a = p.parse_text(sample.encode())
        c = parse_libsvm(sample.splitlines())
        np.testing.assert_array_equal(a.y, c.y)
        np.testing.assert_array_equal(a.indptr, c.indptr)
        np.testing.assert_allclose(a.values, c.values, rtol=0)
        # an index beyond uint64 clamps (strtoull ERANGE semantics) in the
        # native parser — no wraparound key (the Python parser cannot even
        # represent it in int64, so no cross-check)
        big = p.parse_text(b"1 18446744073709551999:1\n")
        assert big.indices.view(np.uint64)[0] == np.uint64(2**64 - 1)

    def test_signed_index_empty_value_and_ws_lines(self):
        """Review scenarios: '+3:'/'-3:' signed indices (strtoull modulo
        semantics), empty value tokens defaulting to 1.0, and
        whitespace-only lines — native must match the Python parser."""
        sample = "+1 +3:1 -3:2\n1 3:\n1 3: 4:1\n \n1 5:2\n"
        p = ExampleParser("libsvm")
        a = p.parse_text(sample.encode())
        c = parse_libsvm(sample.splitlines())
        np.testing.assert_array_equal(a.y, c.y)
        np.testing.assert_array_equal(a.indptr, c.indptr)
        np.testing.assert_array_equal(a.indices, c.indices)
        np.testing.assert_allclose(a.values, c.values, rtol=0)

    def test_criteo_tabs_only_line_is_all_zero_row(self):
        """A tabs-only line parses as a valid ALL-MISSING row in the
        reference (strtofloat("")/strtoi32("") succeed with 0): label 0
        -> class -1, 13 zero-count int keys, no cats. The parse must
        still not let strtod cross the newline and steal the next
        line's label."""
        tabs_only = "\t" * 39 + "\n"
        good = (
            "1\t" + "\t".join("2" for _ in range(13)) + "\t"
            + "\t".join("LONGTOK%d" % i for i in range(26)) + "\n"
        )
        b = ExampleParser("criteo").parse_text((tabs_only + good).encode())
        assert b.n == 2
        assert b.y.tolist() == [-1.0, 1.0]  # "" label did NOT eat the 1
        assert b.indptr[1] - b.indptr[0] == 13  # 13 empty-int keys
