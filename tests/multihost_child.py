"""Child program for the multi-process integration test (run via
script/local.sh semantics: PS_* env set by the parent). Mirrors the
reference's `*_ps.cc` binaries that local.sh launches N times.

Each process preps ITS OWN minibatch (its file partition, per
DataAssigner semantics), the shards assemble into one global data-sharded
batch, and the SPMD step psums gradients across processes over DCN
(gloo on CPU test meshes). Prints PS_OK <global_examples> on success.
"""

import os
import sys

import numpy as np

from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker
from parameter_server_tpu.apps.linear.config import (
    Config,
    LearningRateConfig,
    PenaltyConfig,
    SGDConfig,
)
from parameter_server_tpu.parallel import distributed
from parameter_server_tpu.parallel import mesh as meshlib
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.utils.sparse import random_sparse

import jax


def main() -> int:
    po = Postoffice.instance().start(num_server=2)  # joins rendezvous
    assert distributed.is_multiprocess(), "expected a multi-process run"
    n_data = meshlib.num_workers(po.mesh)
    local = distributed.local_data_shards(po.mesh)

    conf = Config()
    conf.penalty = PenaltyConfig(type="l1", lambda_=[0.01])
    conf.learning_rate = LearningRateConfig(type="decay", alpha=0.5, beta=1.0)
    per_host_rows = 64 * local
    conf.async_sgd = SGDConfig(
        algo="ftrl",
        minibatch=per_host_rows,
        num_slots=1 << 12,
        max_delay=1,
        ell_lanes=8,
        wire="bits",
    )
    worker = AsyncSGDWorker(conf, mesh=po.mesh)

    # each host draws a DIFFERENT batch (its own partition)
    seed = 100 + jax.process_index()
    rng = np.random.default_rng(0)
    w_true = (rng.normal(size=1 << 12) * (rng.random(1 << 12) < 0.2)).astype(
        np.float32
    )
    for i in range(3):
        batch = random_sparse(
            per_host_rows, 1 << 12, 8, seed=seed + i, w_true=w_true, binary=True
        )
        prog = worker.collect(worker.process_minibatch(batch))
        # each step's num_ex is psum'd over the FULL data axis: all hosts
        assert prog.num_examples_processed == 64 * n_data, prog
    # scan superbatch across processes: each host stacks ITS 2 minibatches
    # [T=2, D_local, ...]; assembly shards dim 1 over the global data axis
    sup = [
        random_sparse(
            per_host_rows, 1 << 12, 8, seed=seed + 50 + i, w_true=w_true,
            binary=True,
        )
        for i in range(2)
    ]
    prog = worker.collect(worker.submit_superbatch(sup))
    assert prog.num_examples_processed == 2 * 64 * n_data, prog

    total = worker.progress.num_examples_processed
    expected = 64 * n_data * 5
    assert total == expected, f"examples {total} != {expected}"

    # -- control-plane frames through the per-peer filter chain over the
    # DCN transport (ref remote_node.cc: every send/recv runs the
    # chain; compressing = shared_array_inl.h snappy, key_caching =
    # key_caching.h signatures). Process 0 -> 1; byte reductions are
    # ASSERTED, not assumed. --
    if jax.process_index() in (0, 1):
        from parameter_server_tpu.system.message import (
            FilterSpec,
            Message,
            Task,
        )
        from parameter_server_tpu.system.remote_node import RemoteNode

        filters = [
            FilterSpec(type="key_caching"),
            FilterSpec(type="compressing"),
        ]
        keys = np.arange(0, 1 << 15, 2, dtype=np.int64)  # 32K keys
        vals = np.zeros(keys.size, np.float32)
        vals[::13] = 1.5  # sparse values: compression must win big
        raw_bytes = keys.nbytes + vals.nbytes
        if jax.process_index() == 0:
            rn = RemoteNode("host1")
            for seq in range(2):  # same keys twice: 2nd hits the key cache
                msg = Message(
                    task=Task(filters=list(filters)),
                    sender="host0", recver="host1",
                    key=keys.copy(), values=[vals.copy()],
                )
                distributed.post_bytes(f"ctl/0to1/{seq}", rn.to_wire(msg))
            # first frame: values compressed, keys present; second:
            # keys dropped by signature + compressed values
            assert rn.wire_sent_bytes < 2 * raw_bytes * 0.7
            distributed.post_bytes(
                "ctl/0to1/sent", str(rn.wire_sent_bytes).encode()
            )
        else:
            rn = RemoteNode("host0")
            sizes = []
            for seq in range(2):
                blob = distributed.fetch_bytes(f"ctl/0to1/{seq}")
                sizes.append(len(blob))
                m = rn.from_wire(blob)
                np.testing.assert_array_equal(m.key, keys)
                np.testing.assert_array_equal(m.values[0], vals)
            sent = int(distributed.fetch_bytes("ctl/0to1/sent"))
            assert sent == sum(sizes), (sent, sizes)
            # the cached-key resend must be much smaller than the first
            assert sizes[1] < sizes[0] * 0.5, sizes
            # and both beat the raw payload
            assert sizes[0] < raw_bytes, (sizes, raw_bytes)
            print(f"PS_FILTER_OK {sizes[0]} {sizes[1]} raw {raw_bytes}",
                  flush=True)

    # -- LM over DCN: the long-context stack on the SAME multi-process
    # mesh — sequence sharded over the global data axis (each host
    # feeds its local seq chunk), params FSDP-sharded over that axis,
    # ring-attention collectives and the gradient reduce-scatter riding
    # the cross-process transport. Loss is replicated output: every
    # process must print the identical value, and it must improve. --
    from jax.sharding import NamedSharding, PartitionSpec as P

    from parameter_server_tpu.models.transformer import (
        LMConfig,
        fsdp_shard_lm_params,
        init_lm,
        lm_loss,
    )

    cfg = LMConfig(
        vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        attention="ring", remat=True, rope=True,
    )
    # same PRNG on every host -> identical init; device_put then places
    # each host's addressable shards of the global (FSDP) layout
    lm_params = fsdp_shard_lm_params(
        init_lm(jax.random.PRNGKey(0), cfg), po.mesh, "data"
    )
    seq_sharding = NamedSharding(po.mesh, P(None, "data"))
    s_local = 32 * local  # seq positions owned by this host's rows
    s_global = 32 * n_data

    @jax.jit
    def lm_step(p, toks):
        loss, g = jax.value_and_grad(lm_loss)(p, toks, cfg, po.mesh, "data")
        return jax.tree.map(lambda a, b: a - 0.3 * b, p, g), loss

    # chunk ownership read off the MESH (not assumed): the data rows
    # whose devices this process owns, in row order
    dev_grid = np.asarray(po.mesh.devices)
    if dev_grid.ndim == 1:
        dev_grid = dev_grid[:, None]
    my_rows = [
        r for r in range(dev_grid.shape[0])
        if dev_grid[r].ravel()[0].process_index == jax.process_index()
    ]
    assert len(my_rows) == local, (my_rows, local)

    lm_rng = np.random.default_rng(9)  # same stream on all hosts; each
    # host slices ITS chunks of the same global batch so the data is
    # coherent, not per-host noise
    losses = []
    for _ in range(4):
        full = lm_rng.integers(0, 16, (2, s_global)).astype(np.int32)
        mine = np.concatenate(
            [full[:, r * 32 : (r + 1) * 32] for r in my_rows], axis=1
        )
        assert mine.shape == (2, s_local)
        toks = jax.make_array_from_process_local_data(
            seq_sharding, np.ascontiguousarray(mine), (2, s_global)
        )
        lm_params, l = lm_step(lm_params, toks)
        losses.append(float(l))
    assert losses[-1] < losses[0], losses
    print(f"PS_LM_OK {losses[-1]:.6f}", flush=True)

    print(f"PS_OK {total}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
