"""show_example inspection CLI (ref src/data/show_example.h)."""

import io

import numpy as np
import pytest

from parameter_server_tpu.data.show_example import (
    format_example,
    main,
    show_example,
)
from parameter_server_tpu.data.text2record import convert
from parameter_server_tpu.utils.sparse import SparseBatch


@pytest.fixture
def libsvm_file(tmp_path):
    p = tmp_path / "train.libsvm"
    p.write_text("1 3:0.5 7:1.25\n-1 1:2 9:0.125\n1 2:1\n1 4:1\n")
    return str(p)


def test_text_first_n(libsvm_file, capsys):
    shown = show_example(libsvm_file, "libsvm", 2)
    assert shown == 2
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 2
    # label slot id 0, features in slot id 1 (proto slot ids are 1-based)
    assert lines[0] == (
        "slot { id: 0 val: 1 } slot { id: 1 key: 3 key: 7 val: 0.5 val: 1.25 }"
    )
    assert "val: 2" in lines[1] and "key: 9" in lines[1]


def test_n_beyond_file(libsvm_file, capsys):
    assert show_example(libsvm_file, "libsvm", 100) == 4
    assert len(capsys.readouterr().out.strip().splitlines()) == 4


def test_recordio_roundtrip(libsvm_file, tmp_path, capsys):
    rec = str(tmp_path / "train.rec")
    convert([libsvm_file], "libsvm", rec)
    assert show_example(rec, "recordio", 3) == 3
    text_out = capsys.readouterr().out
    # record path shows the same parsed examples as the text path
    show_example(libsvm_file, "libsvm", 3)
    assert capsys.readouterr().out == text_out


def test_multislot_grouping():
    # criteo-style: slot_ids group entries into distinct slots
    batch = SparseBatch(
        y=np.array([1.0], np.float32),
        indptr=np.array([0, 3], np.int64),
        indices=np.array([10, 20, 30], np.int64),
        values=None,
        slot_ids=np.array([1, 1, 5], np.int32),
    )
    line = format_example(batch, 0)
    assert "slot { id: 1 key: 10 key: 20 }" in line
    assert "slot { id: 5 key: 30 }" in line
    assert "val:" not in line.split("}", 1)[1]  # binary: no feature vals


def test_cli_reference_flags(libsvm_file, capsys):
    # reference-style single-dash flags: -input -format -n
    rc = main(["-input", libsvm_file, "-format", "libsvm", "-n", "1"])
    assert rc == 0
    assert len(capsys.readouterr().out.strip().splitlines()) == 1


def test_cli_empty_input(tmp_path, capsys):
    p = tmp_path / "empty.libsvm"
    p.write_text("")
    assert main(["-input", str(p), "-format", "libsvm"]) == 1


def test_cli_bad_n(libsvm_file):
    with pytest.raises(SystemExit):
        main(["-input", libsvm_file, "-n", "0"])
