"""Self-driving consistency (ISSUE 20): the adaptive τ controller
(widen on stability, clamp on spikes, the full divergence reaction —
τ→0 + LR backoff + snapshot rollback), the in-jit KKT significance
filter with its off-is-bit-identical contract and suppressed-key
reconciliation, the host-side persistent drop, the live-τ breach
accounting, and the τ-sweep zero-recompile pin."""

import numpy as np
import pytest

from parameter_server_tpu.system import faults
from parameter_server_tpu.system.faults import FaultError
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.telemetry import learning as learning_mod


def _worker(po, tau=3, minibatch=64, num_slots=1 << 9,
            name="cons_worker", **sgd_kw):
    from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker
    from parameter_server_tpu.apps.linear.config import (
        Config,
        LearningRateConfig,
        PenaltyConfig,
        SGDConfig,
    )

    conf = Config()
    conf.penalty = PenaltyConfig(type="l1", lambda_=[0.1])
    conf.learning_rate = LearningRateConfig(
        type="decay", alpha=0.1, beta=1.0
    )
    conf.async_sgd = SGDConfig(
        algo="ftrl", minibatch=minibatch, num_slots=num_slots,
        max_delay=tau, **sgd_kw,
    )
    return AsyncSGDWorker(conf, mesh=po.mesh, name=name)


def _batches(n, minibatch=64, key_space=1 << 12, lanes=6, seed0=0):
    from parameter_server_tpu.utils.sparse import random_sparse

    out = []
    for i in range(n):
        b = random_sparse(
            minibatch, key_space, lanes, seed=seed0 + i, binary=True
        )
        b.y = np.where(
            np.arange(minibatch) % 3 == 0, 1.0, -1.0
        ).astype(np.float32)
        out.append(b)
    return out


def _state_leaves(worker):
    import jax

    return jax.tree.leaves(worker.state_host()["state"])


@pytest.fixture()
def po(mesh8):
    Postoffice.reset()
    faults.reset()
    po = Postoffice.instance().start(num_data=4, num_server=2)
    yield po
    faults.reset()
    po.stop()
    Postoffice.reset()


# ---------------------------------------------------------------------------
# adaptive τ: the controller policy
# ---------------------------------------------------------------------------


class TestAdaptiveTau:
    def test_widens_under_stability_and_stays_within_cap(self, po):
        worker = _worker(po, tau=4, name="cons_widen", tau_adaptive=True)
        ctl = worker._consistency.controller
        ctl.stable_steps = 2  # ramp scaled to the short test run
        try:
            worker.train(iter(_batches(12)))
        finally:
            worker.executor.stop()
        # started conservative, earned width, never past the cap
        assert ctl.tau_trace[0] == 1
        assert max(ctl.tau_trace) > 1
        assert max(ctl.tau_trace) <= 4
        st = learning_mod.get_plane("cons_widen").snapshot()["staleness"]
        assert st["live_tau"] == ctl.tau
        assert st["configured_tau"] == 4
        # the bounded-delay contract held against the LIVE τ at every
        # submission (the satellite-1 breach semantics)
        assert st["within_bound"]
        assert st["over_tau_max"] <= 0

    def test_soft_spike_clamps_tau_without_reaction(self, po):
        worker = _worker(po, tau=4, name="cons_spike", tau_adaptive=True)
        ctl = worker._consistency.controller
        try:
            ctl._set_tau(4, "widen")
            for _ in range(10):  # fill the spike window, all healthy
                ctl.on_metrics(0.5, 1.0, False)
            alpha_before = float(worker.lr.alpha)
            ctl.on_metrics(0.5, 50.0, False)  # 50x the window median
        finally:
            worker.executor.stop()
        assert ctl.tau == 2  # halved, not zeroed
        # a clamp is the cheap reversible move: no LR backoff, no
        # rollback episode
        assert float(worker.lr.alpha) == alpha_before
        assert ctl.episodes == []

    def test_react_backs_off_lr_and_rolls_back_state(self, po):
        worker = _worker(po, tau=3, name="cons_react", tau_adaptive=True)
        try:
            worker.train(iter(_batches(4)))
            snap_leaves = [
                np.asarray(x)
                for x in __import__("jax").tree.leaves(
                    worker._consistency.controller._snapshot["state"]
                )
            ]
            alpha_before = float(worker.lr.alpha)
            worker.train(iter(_batches(3, seed0=50)))  # move past it
            moved = _state_leaves(worker)
            assert any(
                not np.array_equal(np.asarray(a), b)
                for a, b in zip(moved, snap_leaves)
            )
            episode = worker._consistency.react("test")
            restored = _state_leaves(worker)
        finally:
            worker.executor.stop()
        assert episode["rolled_back"]
        assert episode["tau_after"] == 0
        assert float(worker.lr.alpha) == alpha_before * 0.5
        # bit-exact rollback to the controller's snapshot
        for a, b in zip(restored, snap_leaves):
            assert np.array_equal(np.asarray(a), b)

    def test_nonfinite_collect_runs_reaction_then_reconverges(self, po):
        worker = _worker(po, tau=3, name="cons_poison", tau_adaptive=True)
        try:
            worker.train(iter(_batches(4)))
            bad = _batches(1, seed0=90)[0]
            bad.y = np.full_like(bad.y, np.float32("inf"))
            worker.train(iter([bad]))
            ctl = worker._consistency.controller
            assert [e["reason"] for e in ctl.episodes] == ["nonfinite"]
            assert ctl.episodes[0]["rolled_back"]
            worker.train(iter(_batches(4, seed0=100)))
        finally:
            worker.executor.stop()
        traj = learning_mod.get_plane("cons_poison").snapshot()[
            "trajectory_tail"
        ]
        # post-rollback steps train on finite state again
        assert all(np.isfinite(p["loss"]) for p in traj[-3:])

    def test_rollback_fault_point_fires_before_any_state_change(self, po):
        worker = _worker(po, tau=3, name="cons_fault", tau_adaptive=True)
        try:
            worker.train(iter(_batches(2)))
            alpha_before = float(worker.lr.alpha)
            faults.arm("consistency.rollback", kind="raise")
            with pytest.raises(FaultError):
                worker._consistency.react("drill")
        finally:
            faults.disarm("consistency.rollback")
            worker.executor.stop()
        # the point fires BEFORE the reaction touches anything: a
        # failed reaction leaves LR, τ, and the episode log untouched
        assert float(worker.lr.alpha) == alpha_before
        assert worker._consistency.controller.episodes == []

    def test_effective_tau_clamped_to_configured_cap(self, po):
        worker = _worker(po, tau=3, name="cons_clamp")
        try:
            assert worker.set_effective_tau(99) == 3
            assert worker.set_effective_tau(-5) == 0
        finally:
            worker.executor.stop()


# ---------------------------------------------------------------------------
# satellite 2: τ moves never recompile
# ---------------------------------------------------------------------------


class TestTauNeverRecompiles:
    def test_tau_sweep_zero_recompiles_post_warmup(self, po):
        from parameter_server_tpu.telemetry import device as device_mod

        device_mod.reset()
        worker = _worker(
            po, tau=8, name="cons_sweep", update="sparse"
        )
        try:
            # warmup compiles every variant the sweep will touch:
            # τ=0 → snap_donate, τ=2 → snap + delay
            worker.set_effective_tau(0)
            worker.train(iter(_batches(2)))
            worker.set_effective_tau(2)
            worker.train(iter(_batches(4, seed0=10)))
            device_mod.mark_warmup()
            for tau in (0, 1, 3, 5, 8, 4, 0, 8):
                worker.set_effective_tau(tau)
                worker.train(iter(_batches(2, seed0=20 + tau)))
        finally:
            worker.executor.stop()
        snap = device_mod.snapshot()
        # the regression pin: τ is a host-side schedule, not a trace
        # constant — sweeping it re-specializes NOTHING
        assert snap["recompiles_post_warmup"] == 0


# ---------------------------------------------------------------------------
# KKT significance filter: contracts and accounting
# ---------------------------------------------------------------------------


class TestKKTFilter:
    def test_filter_off_two_runs_bit_identical(self, po):
        leaves = []
        for i in range(2):
            worker = _worker(
                po, tau=2, name=f"cons_off_{i}", update="sparse"
            )
            try:
                worker.train(iter(_batches(6)))
                leaves.append([np.asarray(x) for x in _state_leaves(worker)])
            finally:
                worker.executor.stop()
        for a, b in zip(*leaves):
            assert np.array_equal(a, b)

    def test_escape_one_filter_is_bit_identical_to_off(self, po):
        """The structural no-op configuration (every suppressed slot
        escapes): the filtered step must land bit-for-bit on the
        unfiltered trajectory — the contract that the mask composes
        without perturbing any update it keeps."""
        results = []
        for name, kw in (
            ("cons_id_off", {}),
            ("cons_id_noop", {"kkt_filter": True, "kkt_escape": 1.0}),
        ):
            worker = _worker(
                po, tau=2, name=name, update="sparse", **kw
            )
            try:
                worker.train(iter(_batches(6)))
                results.append(
                    [np.asarray(x) for x in _state_leaves(worker)]
                )
            finally:
                worker.executor.stop()
        for a, b in zip(*results):
            assert np.array_equal(a, b)

    def test_all_suppressed_leaves_state_bit_untouched(self, po):
        """A margin past every gradient with the escape hatch off:
        every at-zero slot is a provable no-op, so ONE filtered step
        must leave the whole table bit-identical to init."""
        worker = _worker(
            po, tau=0, name="cons_allsup", update="sparse",
            kkt_filter=True, kkt_margin=1e9, kkt_escape=0.0,
        )
        try:
            before = [np.asarray(x) for x in _state_leaves(worker)]
            worker.train(iter(_batches(2)))
            after = [np.asarray(x) for x in _state_leaves(worker)]
            tracker = worker._consistency.tracker
        finally:
            worker.executor.stop()
        assert tracker.candidates > 0
        assert tracker.suppressed == tracker.candidates
        assert tracker.pushed == 0
        for a, b in zip(before, after):
            assert np.array_equal(a, b)

    def test_two_filtered_runs_deterministic(self, po):
        summaries, leaves = [], []
        for i in range(2):
            worker = _worker(
                po, tau=2, name=f"cons_det_{i}", update="sparse",
                kkt_filter=True, kkt_drop_after=2, kkt_revisit_every=4,
                ingest_workers=1,
            )
            try:
                worker.train(iter(_batches(8)))
                summaries.append(worker._consistency.tracker.summary())
                leaves.append([np.asarray(x) for x in _state_leaves(worker)])
            finally:
                worker.executor.stop()
        assert summaries[0] == summaries[1]
        for a, b in zip(*leaves):
            assert np.array_equal(a, b)

    def test_suppression_reconciles_against_push_keys_counter(self, po):
        from parameter_server_tpu.telemetry import (
            registry as telemetry_registry,
        )
        from parameter_server_tpu.telemetry.instruments import (
            parameter_instruments,
        )

        if not telemetry_registry.enabled():
            pytest.skip("telemetry registry disabled")
        push = parameter_instruments(
            telemetry_registry.default_registry()
        )["push_keys"]
        before = push.value(store="cons_recon", channel=0)
        worker = _worker(
            po, tau=2, name="cons_recon", update="sparse",
            kkt_filter=True,
        )
        try:
            worker.train(iter(_batches(6)))
            summary = worker._consistency.tracker.summary()
        finally:
            worker.executor.stop()
        # the in-jit identity, metered host-side...
        assert summary["reconciled"]
        assert summary["pushed"] + summary["suppressed"] == (
            summary["candidates"]
        )
        # ...and credited to the worker's store label, so the bench
        # record's reduction claim reconciles against ps_push_keys_total
        after = push.value(store="cons_recon", channel=0)
        assert after - before == summary["pushed"]

    def test_host_drop_engages_and_revisits(self, po):
        worker = _worker(
            po, tau=1, name="cons_drop", update="sparse",
            kkt_filter=True, kkt_margin=1e9, kkt_escape=0.0,
            kkt_drop_after=2, kkt_revisit_every=5, ingest_workers=1,
        )
        try:
            # same batch repeatedly: every slot is suppressed every
            # sighting, so streaks cross drop_after deterministically
            b = _batches(1)[0]
            worker.train(iter([b] * 10))
            tracker = worker._consistency.tracker
            summary = tracker.summary()
        finally:
            worker.executor.stop()
        assert summary["dropped_slots"] > 0
        assert summary["dropped_entries"] > 0
        assert summary["filtered_batches"] > 0
        # the deterministic revisit cadence shipped unfiltered batches
        assert summary["revisit_batches"] == 2  # preps 5 and 10

    def test_config_validation(self, po):
        with pytest.raises(ValueError, match="sparse"):
            _worker(po, name="cons_bad1", kkt_filter=True, update="dense")
        with pytest.raises(ValueError, match="ingest_workers=1"):
            _worker(
                po, name="cons_bad2", update="sparse",
                kkt_filter=True, kkt_drop_after=2,
            )


# ---------------------------------------------------------------------------
# satellite 1: breach accounting tracks the LIVE τ
# ---------------------------------------------------------------------------


class TestLiveTauAccounting:
    def test_over_tau_margin_uses_tau_at_submit_time(self, po):
        worker = _worker(po, tau=4, name="cons_live")
        try:
            worker.train(iter(_batches(6)))
            plane = worker._learning
            st = plane.staleness_summary()
            assert st["within_bound"] and st["over_tau_max"] <= 0
            # a submission whose realized staleness exceeds the τ in
            # force AT SUBMIT TIME breaches, even under the configured
            # cap — the live-τ semantics the staleness_breach rule
            # now pages on
            plane.note_submit(3, tau=1)
            st = plane.staleness_summary()
        finally:
            worker.executor.stop()
        assert st["over_tau_max"] == 2
        assert not st["within_bound"]
        assert st["configured_tau"] == 4

    def test_live_tau_follows_set_effective_tau(self, po):
        worker = _worker(po, tau=4, name="cons_live2")
        try:
            worker.set_effective_tau(2)
            st = worker._learning.staleness_summary()
        finally:
            worker.executor.stop()
        assert st["live_tau"] == 2
        assert st["configured_tau"] == 4


# ---------------------------------------------------------------------------
# the whole episode in one flight-recorder bundle
# ---------------------------------------------------------------------------


class TestRollbackBundle:
    def test_reaction_captures_one_bundle_when_armed(self, po):
        from parameter_server_tpu.telemetry import blackbox

        prev = blackbox.set_min_interval(0.0)
        was_armed = blackbox.installed_recorder() is not None
        blackbox.arm()
        n0 = len(blackbox.bundles())
        worker = _worker(po, tau=3, name="cons_bundle", tau_adaptive=True)
        try:
            worker.train(iter(_batches(3)))
            bad = _batches(1, seed0=77)[0]
            bad.y = np.full_like(bad.y, np.float32("nan"))
            worker.train(iter([bad]))
        finally:
            worker.executor.stop()
            blackbox.set_min_interval(prev)
            if not was_armed:
                blackbox.disarm()
        new = blackbox.bundles()[n0:]
        triggers = [b["trigger"]["kind"] for b in new]
        assert "consistency_rollback" in triggers
        b = new[triggers.index("consistency_rollback")]
        assert b["trigger"]["detail"] == "nonfinite"
