"""Sparse-touched update formulation (SGDConfig.update='sparse').

The big-table mode: gather the batch's unique slot rows, run the SAME
per-row updater math, scatter the rows back — O(touched) HBM traffic
instead of the dense whole-shard sweep, no dense gradient temp (what
lets a 2^31-slot table fit one chip; reference parity: servers only run
entry Set on received keys, async_sgd.h:131-151).

Equivalence basis: the dense and sparse formulations aggregate
per-slot gradients identically (scatter-add vs host dedup + psum), so
FTRL/AdaGrad trajectories must match to fp-reassociation tolerance.
"""

import numpy as np
import pytest

from parameter_server_tpu.apps.linear.config import (
    Config,
    LearningRateConfig,
    PenaltyConfig,
    SGDConfig,
)
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.utils.sparse import random_sparse


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    yield
    Postoffice.reset()


def _conf(update: str, num_slots: int = 1 << 14, algo: str = "ftrl",
          state_dtype: str = "float32", ada_grad: bool = True) -> Config:
    conf = Config()
    conf.penalty = PenaltyConfig(type="l1", lambda_=[0.05])
    conf.learning_rate = LearningRateConfig(type="decay", alpha=0.5, beta=1.0)
    conf.async_sgd = SGDConfig(
        algo=algo, ada_grad=ada_grad, minibatch=256, num_slots=num_slots,
        max_delay=0, update=update, ftrl_state_dtype=state_dtype,
    )
    return conf


def _train_pair(mesh8, num_slots=1 << 14, algo="ftrl", n_batches=6,
                state_dtype="float32", ada_grad=True):
    from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker

    rng = np.random.default_rng(1)
    w_true = (rng.normal(size=512) * (rng.random(512) < 0.3)).astype(
        np.float32
    )
    batches = [
        random_sparse(256, 512, 8, seed=i, w_true=w_true)
        for i in range(n_batches)
    ]
    test = random_sparse(1000, 512, 8, seed=99, w_true=w_true)
    out = {}
    for update in ("dense", "sparse"):
        Postoffice.reset()
        worker = AsyncSGDWorker(
            _conf(update, num_slots, algo, state_dtype, ada_grad),
            mesh=mesh8,
        )
        assert worker._update_mode == update
        worker.train(iter(batches))
        out[update] = (worker.evaluate(test), worker.state)
    return out


class TestSparseDenseEquivalence:
    def test_ftrl_trajectory_matches_dense(self, mesh8):
        out = _train_pair(mesh8)
        ev_d, st_d = out["dense"]
        ev_s, st_s = out["sparse"]
        assert np.isfinite(ev_s["logloss"])
        np.testing.assert_allclose(
            ev_s["logloss"], ev_d["logloss"], rtol=1e-5
        )
        # state equality on the actual tables (z, sqrt_n), not just the
        # scalar objective: fp reassociation only
        for k in st_d:
            np.testing.assert_allclose(
                np.asarray(st_s[k], np.float32),
                np.asarray(st_d[k], np.float32),
                rtol=2e-5, atol=2e-6, err_msg=k,
            )

    def test_hash_collisions_aggregate_identically(self, mesh8):
        """num_slots far below the key count forces hash collisions;
        the sparse prep's slot-level re-unique must reproduce the
        dense scatter-add's implicit aggregation."""
        out = _train_pair(mesh8, num_slots=256)
        ev_d, st_d = out["dense"]
        ev_s, st_s = out["sparse"]
        for k in st_d:
            np.testing.assert_allclose(
                np.asarray(st_s[k], np.float32),
                np.asarray(st_d[k], np.float32),
                rtol=2e-5, atol=2e-6, err_msg=k,
            )

    def test_adagrad_trajectory_matches_dense(self, mesh8):
        out = _train_pair(mesh8, algo="standard", ada_grad=True)
        _, st_d = out["dense"]
        _, st_s = out["sparse"]
        for k in st_d:
            np.testing.assert_allclose(
                np.asarray(st_s[k], np.float32),
                np.asarray(st_d[k], np.float32),
                rtol=2e-5, atol=2e-6, err_msg=k,
            )

    def test_bf16_state_logloss_tracks_dense(self, mesh8):
        """bf16 sqrt_n: the two formulations draw different stochastic
        dither (position-hash over shard vs gathered rows), so only
        statistical agreement holds."""
        out = _train_pair(mesh8, state_dtype="bfloat16", n_batches=8)
        ev_d, _ = out["dense"]
        ev_s, _ = out["sparse"]
        assert abs(ev_s["logloss"] - ev_d["logloss"]) < 5e-3


class TestSparseSuperbatch:
    def test_scan_matches_per_step(self, mesh8):
        from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker

        rng = np.random.default_rng(2)
        w_true = (rng.normal(size=512) * (rng.random(512) < 0.3)).astype(
            np.float32
        )
        batches = [
            random_sparse(256, 512, 8, seed=i, w_true=w_true)
            for i in range(4)
        ]
        states = {}
        for fused in (False, True):
            Postoffice.reset()
            worker = AsyncSGDWorker(_conf("sparse"), mesh=mesh8)
            if fused:
                worker.executor.wait(worker.submit_superbatch(batches))
            else:
                for b in batches:
                    worker.executor.wait(worker.process_minibatch(b))
            worker.executor.wait_all()
            states[fused] = worker.state
        for k in states[False]:
            np.testing.assert_allclose(
                np.asarray(states[True][k], np.float32),
                np.asarray(states[False][k], np.float32),
                rtol=1e-6, atol=1e-7, err_msg=k,
            )


class TestSparseConfigGates:
    def test_explicit_sparse_with_filters_raises(self, mesh8):
        from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker

        conf = _conf("sparse")
        conf.async_sgd.push_filter = [
            {"type": "fixing_float", "num_bytes": 1}
        ]
        worker = AsyncSGDWorker(conf, mesh=mesh8)
        with pytest.raises(ValueError, match="sparse"):
            worker.process_minibatch(
                random_sparse(256, 512, 8, seed=0)
            )
            worker.executor.wait_all()

    def test_auto_resolution(self, mesh8, monkeypatch):
        from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker

        monkeypatch.setenv("PS_SPARSE_UPDATE_MIN_SLOTS", str(1 << 14))
        w = AsyncSGDWorker(_conf("auto", num_slots=1 << 15), mesh=mesh8)
        # per-server shard = 2^15/2 = 2^14 >= threshold -> sparse
        assert w._update_mode == "sparse"
        Postoffice.reset()
        w = AsyncSGDWorker(_conf("auto", num_slots=1 << 13), mesh=mesh8)
        assert w._update_mode == "dense"
        Postoffice.reset()
        # filters pin auto to dense (quietly)
        conf = _conf("auto", num_slots=1 << 15)
        conf.async_sgd.pull_filter = [
            {"type": "fixing_float", "num_bytes": 2}
        ]
        w = AsyncSGDWorker(conf, mesh=mesh8)
        assert w._update_mode == "dense"


class TestApplyStateRows:
    def test_matches_dense_apply_on_touched_rows(self):
        import jax.numpy as jnp

        from parameter_server_tpu.apps.linear.learning_rate import (
            LearningRate,
        )
        from parameter_server_tpu.apps.linear.penalty import ElasticNet
        from parameter_server_tpu.apps.linear.updaters import (
            FTRLUpdater,
            apply_state_rows,
        )

        lr = LearningRate("decay", alpha=0.5, beta=1.0)
        up = FTRLUpdater(lr, ElasticNet(0.05, 0.0))
        rng = np.random.default_rng(0)
        n = 1024
        state = {
            "z": jnp.asarray(rng.normal(size=n).astype(np.float32)),
            "sqrt_n": jnp.asarray(
                (rng.random(n) * 2).astype(np.float32)
            ),
        }
        rel = jnp.asarray([3, 100, 1023, 7, 0], jnp.int32)
        ok = jnp.asarray([True, True, True, False, True])
        g_u = jnp.asarray([0.5, -1.25, 0.01, 9.9, 0.3], jnp.float32)
        # dense oracle: scatter the ok gradients, dense apply
        g_dense = np.zeros(n, np.float32)
        for r, o, g in zip([3, 100, 1023, 7, 0], [1, 1, 1, 0, 1],
                           [0.5, -1.25, 0.01, 9.9, 0.3]):
            if o:
                g_dense[r] += g
        want = up.apply(state, jnp.asarray(g_dense), None)
        got = apply_state_rows(up, state, rel, ok, g_u)
        for k in want:
            np.testing.assert_allclose(
                np.asarray(got[k]), np.asarray(want[k]),
                rtol=1e-6, err_msg=k,
            )
        # the not-ok entry (row 7) must be untouched
        np.testing.assert_array_equal(
            np.asarray(got["z"])[7], np.asarray(state["z"])[7]
        )
