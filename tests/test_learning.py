"""Learning truth plane (PR 15): realized staleness vs the configured
τ, key heat & shard balance, in-jit convergence side outputs, the
shipped alert rules (divergence / staleness breach / shard imbalance),
the cluster scrape with node-labeled ``ps_learning_*``, and the monitor
path's redelivery hardening."""

import json
import urllib.request

import numpy as np
import pytest

from parameter_server_tpu.system import faults
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.telemetry import learning as learning_mod
from parameter_server_tpu.telemetry.registry import MetricsRegistry


def _worker(po, tau=3, minibatch=64, num_slots=1 << 10,
            name="lt_worker", **sgd_kw):
    from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker
    from parameter_server_tpu.apps.linear.config import (
        Config,
        LearningRateConfig,
        PenaltyConfig,
        SGDConfig,
    )

    conf = Config()
    conf.penalty = PenaltyConfig(type="l1", lambda_=[0.1])
    conf.learning_rate = LearningRateConfig(
        type="decay", alpha=0.1, beta=1.0
    )
    conf.async_sgd = SGDConfig(
        algo="ftrl", minibatch=minibatch, num_slots=num_slots,
        max_delay=tau, **sgd_kw,
    )
    return AsyncSGDWorker(conf, mesh=po.mesh, name=name)


def _batches(n, minibatch=64, key_space=1 << 14, lanes=6, seed0=0):
    from parameter_server_tpu.utils.sparse import random_sparse

    out = []
    for i in range(n):
        b = random_sparse(
            minibatch, key_space, lanes, seed=seed0 + i, binary=True
        )
        b.y = np.where(
            np.arange(minibatch) % 3 == 0, 1.0, -1.0
        ).astype(np.float32)
        out.append(b)
    return out


@pytest.fixture()
def po(mesh8):
    Postoffice.reset()
    faults.reset()
    po = Postoffice.instance().start(num_data=4, num_server=2)
    yield po
    faults.reset()
    po.stop()
    Postoffice.reset()


# ---------------------------------------------------------------------------
# realized staleness: the bounded-delay contract, measured
# ---------------------------------------------------------------------------


class TestRealizedStaleness:
    def test_observed_max_respects_configured_tau(self, po):
        tau = 3
        worker = _worker(po, tau=tau, name="lt_stale")
        try:
            worker.train(iter(_batches(12)))
        finally:
            worker.executor.stop()
        plane = learning_mod.get_plane("lt_stale")
        assert plane is not None
        st = plane.snapshot()["staleness"]
        assert st["configured_tau"] == tau
        assert st["submits"] == 12
        assert st["histogram"]["count"] == 12
        # the measured invariant: realized staleness never exceeds τ
        assert 0 < st["observed_max"] <= tau
        assert st["within_bound"]
        # executor logical-clock lag mirrors the ministep staleness on
        # a 1-ministep-per-submission run
        assert st["executor_clock_lag_max"] >= st["observed_max"]
        # the live gauge the staleness_breach rule watches is <= 0
        export = plane.export()
        over = export["ps_learning_staleness_over_tau"]["series"]
        assert all(s["value"] <= 0 for s in over)

    def test_tau_zero_is_always_fresh(self, po):
        worker = _worker(po, tau=0, name="lt_fresh")
        try:
            worker.train(iter(_batches(4)))
        finally:
            worker.executor.stop()
        st = learning_mod.get_plane("lt_fresh").snapshot()["staleness"]
        assert st["observed_max"] == 0
        assert st["within_bound"]


# ---------------------------------------------------------------------------
# key heat: windowed sketch vs exact, shard fold, decay, hot slots
# ---------------------------------------------------------------------------


class TestKeyHeat:
    def test_sketch_matches_exact_on_small_stream(self):
        heat = learning_mod.KeyHeat(num_slots=512, num_shards=2)
        rng = np.random.default_rng(3)
        exact = np.zeros(512, np.int64)
        for _ in range(16):
            slots = rng.integers(0, 512, 256)
            heat.note(slots)
            np.add.at(exact, slots, 1)
        uniq = np.flatnonzero(exact)
        est = heat.estimate(uniq)
        # CM is upper-biased; at 512 distinct slots in a 2^16 sketch
        # the estimates are exact
        assert (est >= exact[uniq]).all()
        assert float(np.mean(est == exact[uniq])) == 1.0

    def test_shard_fold_follows_assigner_ranges(self):
        # ranges come from the SAME NodeAssigner/Range.even_divide the
        # servers use; all traffic into the last shard's range reads as
        # num_shards x imbalance
        heat = learning_mod.KeyHeat(num_slots=100, num_shards=4)
        heat.note(np.arange(75, 100))  # the 4th shard's key range
        shares = heat.shares()
        assert shares["shares"][3] == 1.0
        assert shares["shares"][:3] == [0.0, 0.0, 0.0]
        assert shares["imbalance"] == 4.0

    def test_sentinel_and_out_of_range_slots_dropped(self):
        heat = learning_mod.KeyHeat(num_slots=64, num_shards=2)
        n = heat.note(np.array([1, 2, 64, 100, -1]))
        assert n == 2  # the sentinel (== num_slots) and beyond dropped

    def test_decay_window_halves_and_cools(self):
        heat = learning_mod.KeyHeat(num_slots=64, num_shards=2)
        heat.note(np.full(32, 7))
        assert heat.estimate(np.array([7]))[0] == 32
        heat.advance()
        assert heat.estimate(np.array([7]))[0] == 16
        total0 = heat.shares()["total_weight"]
        heat.advance()
        assert heat.shares()["total_weight"] == pytest.approx(total0 / 2)

    def test_top_slots_table_ranks_hot_first(self):
        heat = learning_mod.KeyHeat(num_slots=100, num_shards=4, top_k=4)
        heat.note(np.concatenate([np.full(50, 80), np.arange(10)]))
        top = heat.top_slots()
        assert top[0]["slot"] == 80
        assert top[0]["shard"] == 3
        assert top[0]["est"] >= 50


# ---------------------------------------------------------------------------
# convergence side outputs: in-jit scalars, metered host-side
# ---------------------------------------------------------------------------


class TestConvergenceSideOutputs:
    def test_dense_step_metrics_carry_norms(self, po):
        worker = _worker(po, tau=0, name="lt_conv")
        b = _batches(1)[0]
        try:
            ts = worker.process_minibatch(b)
            metrics = worker.executor.wait(ts)
        finally:
            worker.executor.stop()
        for key in ("grad_sq", "update_sq", "weight_sq"):
            assert key in metrics
            assert np.isfinite(float(metrics[key]))
        assert float(metrics["grad_sq"]) > 0
        # first step: the table is all zeros, so the consumed weights are
        assert float(metrics["weight_sq"]) == 0.0

    def test_sparse_update_metrics_carry_norms(self, po):
        worker = _worker(po, tau=0, name="lt_conv_sp", update="sparse")
        b = _batches(1)[0]
        try:
            ts = worker.process_minibatch(b)
            metrics = worker.executor.wait(ts)
        finally:
            worker.executor.stop()
        assert float(metrics["grad_sq"]) > 0
        assert np.isfinite(float(metrics["update_sq"]))

    def test_collect_feeds_plane_trajectory_and_examples(self, po):
        worker = _worker(po, tau=2, name="lt_traj")
        try:
            worker.train(iter(_batches(6)))
        finally:
            worker.executor.stop()
        snap = learning_mod.get_plane("lt_traj").snapshot()
        # device-confirmed example count, wired through collect()
        assert snap["examples"] == 6 * 64
        assert snap["collected_steps"] == 6
        tail = snap["trajectory_tail"]
        assert len(tail) == 6
        for pt in tail:
            assert isinstance(pt["loss"], float)
            assert pt["grad_norm"] > 0
        assert snap["divergence"] == {}


# ---------------------------------------------------------------------------
# shipped alert rules: inactive → pending → firing → resolved
# ---------------------------------------------------------------------------


class TestShippedLearningRules:
    def test_rules_ship_in_default_set(self):
        from parameter_server_tpu.telemetry.alerts import default_rules

        by_name = {r.name: r for r in default_rules()}
        assert by_name["loss_divergence"].kind == "counter_rate"
        assert (
            by_name["loss_divergence"].metric
            == "ps_learning_divergence_total"
        )
        assert by_name["staleness_breach"].kind == "gauge"
        assert (
            by_name["staleness_breach"].metric
            == "ps_learning_staleness_over_tau"
        )
        assert by_name["shard_imbalance"].kind == "gauge"
        assert (
            by_name["shard_imbalance"].metric
            == "ps_learning_shard_imbalance"
        )

    def test_staleness_breach_fires_and_resolves(self):
        """The SHIPPED staleness_breach rule driven through its whole
        lifecycle by a real plane breaching (then re-satisfying) the
        configured τ (PR 11 drill pattern)."""
        from parameter_server_tpu.telemetry.alerts import (
            AlertManager,
            default_rules,
        )

        rule = next(
            r for r in default_rules() if r.name == "staleness_breach"
        )
        reg = MetricsRegistry()
        clock = [0.0]
        mgr = AlertManager([rule], registry=reg, clock=lambda: clock[0])
        plane = learning_mod.LearningPlane(
            "W0", num_slots=256, num_shards=2, max_delay=2, registry=reg
        )
        mgr.evaluate()
        assert mgr.states()[rule.name].state_name == "inactive"
        plane.note_submit(5)  # realized staleness 5 > τ=2: breach
        clock[0] = 1.0
        mgr.evaluate()
        assert mgr.states()[rule.name].state_name == "firing"
        # a fresh plane (rebuilt worker) re-satisfies the bound
        learning_mod.LearningPlane(
            "W0", num_slots=256, num_shards=2, max_delay=2, registry=reg
        )
        clock[0] = 2.0
        mgr.evaluate()
        assert mgr.states()[rule.name].state_name == "resolved"
        clock[0] = 2.0 + rule.resolve_hold_s + 1.0
        mgr.evaluate()
        assert mgr.states()[rule.name].state_name == "inactive"

    def test_shard_imbalance_fires_and_resolves(self):
        from parameter_server_tpu.telemetry.alerts import (
            AlertManager,
            default_rules,
        )

        rule = next(
            r for r in default_rules() if r.name == "shard_imbalance"
        )
        reg = MetricsRegistry()
        clock = [0.0]
        mgr = AlertManager([rule], registry=reg, clock=lambda: clock[0])
        plane = learning_mod.LearningPlane(
            "W0", num_slots=640, num_shards=8, max_delay=0, registry=reg
        )
        mgr.evaluate()
        assert mgr.states()[rule.name].state_name == "inactive"
        # every key lands in one shard's range: imbalance 8 > 4
        plane.note_slots(np.arange(80))
        clock[0] = 1.0
        mgr.evaluate()
        assert mgr.states()[rule.name].state_name == "pending"
        clock[0] = 1.0 + rule.for_s + 1.0
        mgr.evaluate()
        assert mgr.states()[rule.name].state_name == "firing"
        # traffic spreads back out; the windowed view rebalances
        plane.note_slots(np.tile(np.arange(640), 3))
        clock[0] += 1.0
        mgr.evaluate()
        assert mgr.states()[rule.name].state_name == "resolved"

    def test_divergence_drill_fires_with_bundle(self, po):
        """Acceptance: a seeded LR blow-up drives the SHIPPED
        loss_divergence rule to firing, with a diagnostic bundle
        captured through the PR 13 alert trigger plane."""
        from parameter_server_tpu.benchmarks.components import (
            _divergence_drill,
        )

        out = _divergence_drill(po.mesh, smoke=True)
        assert out["divergence_counts"].get("nonfinite", 0) >= 1
        assert out["fired"]
        assert "firing" in out["states_seen"]
        assert out["resolved"]
        assert out["bundle_captured"]
        assert out["bundle_trigger"]["kind"] == "alert"
        assert out["bundle_trigger"]["detail"] == "loss_divergence"


# ---------------------------------------------------------------------------
# cluster view: ps_learning_* node-labeled on one scrape
# ---------------------------------------------------------------------------


class TestClusterLearningScrape:
    def _plane(self, node, reg):
        p = learning_mod.LearningPlane(
            node, num_slots=256, num_shards=2, max_delay=2, registry=reg
        )
        p.note_submit(1)
        p.note_step({
            "objective": 5.0, "num_ex": 10, "grad_sq": 4.0,
            "update_sq": 4.0, "weight_sq": 1.0,
        })
        p.note_slots(np.arange(64))
        return p

    def test_one_scrape_shows_node_labels_and_rollup(self, po):
        from parameter_server_tpu.telemetry.aggregate import (
            ClusterAggregator,
        )

        cluster = ClusterAggregator()
        master = learning_mod.ClusterFeedMaster(cluster)
        for node in ("W0", "W1"):
            plane = self._plane(node, MetricsRegistry())
            slaver = learning_mod.slaver_over_van(master, node, po.van)
            slaver.report(plane.export())
        text = cluster.render_text()
        # node-labeled series for both workers...
        assert 'ps_learning_loss{node="W0",worker="W0"}' in text
        assert 'ps_learning_loss{node="W1",worker="W1"}' in text
        # ...and the cluster rollup for counters
        assert 'ps_learning_examples_total{node="cluster"' in text
        # the staleness histogram merges bucket-wise into the rollup
        assert "ps_learning_staleness_ministeps_bucket" in text

    def test_duplicate_report_never_double_merges(self, po):
        """The van `duplicate` fault delivers one report frame twice;
        the master's seq guard must merge it once (satellite: a
        duplicated report never double-merges into cluster progress)."""
        from parameter_server_tpu.telemetry.aggregate import (
            ClusterAggregator,
        )

        cluster = ClusterAggregator()
        master = learning_mod.ClusterFeedMaster(cluster)
        plane = self._plane("W0", MetricsRegistry())
        slaver = learning_mod.slaver_over_van(master, "W0", po.van)
        faults.arm("van.transfer", kind="duplicate")
        slaver.report(plane.export())
        faults.reset()
        assert master.monitor.duplicates_dropped() == 1
        merged = cluster.merged()
        ex = [
            s for s in merged["ps_learning_examples_total"]["series"]
            if s["labels"]["node"] == "W0"
        ]
        assert len(ex) == 1 and ex[0]["value"] == 10.0


# ---------------------------------------------------------------------------
# monitor redelivery hardening (satellite): drop → retransmit,
# duplicate → exactly-once merge, on the ADDITIVE progress master
# ---------------------------------------------------------------------------


class TestMonitorRedelivery:
    def _master_slaver(self, po):
        from parameter_server_tpu.system.monitor import (
            MonitorMaster,
            MonitorSlaver,
        )

        master: MonitorMaster[list] = MonitorMaster()
        master.set_data_merger(lambda src, dst: dst.extend(src))
        return master, MonitorSlaver.over_van(master, "W0", po.van)

    def test_duplicate_frame_merges_exactly_once(self, po):
        master, slaver = self._master_slaver(po)
        faults.arm("van.transfer", kind="duplicate")
        slaver.report([1])
        faults.reset()
        slaver.report([2])
        # additive merge: a double-merged [1] would read [1, 1, 2]
        assert master.progress() == {"W0": [1, 2]}
        assert master.duplicates_dropped() == 1

    def test_dropped_frame_is_retransmitted(self, po):
        master, slaver = self._master_slaver(po)
        faults.arm("van.transfer", kind="drop", once=True)
        slaver.report([1])  # first attempt dropped; retry delivers
        faults.reset()
        assert master.progress() == {"W0": [1]}

    def test_exhausted_retries_surface_the_drop(self, po):
        master, slaver = self._master_slaver(po)
        faults.arm("van.transfer", kind="drop")
        with pytest.raises(faults.FaultError):
            slaver.report([1])
        faults.reset()
        assert master.progress() == {}

    def test_direct_path_unchanged(self):
        from parameter_server_tpu.system.monitor import (
            MonitorMaster,
            MonitorSlaver,
        )

        master: MonitorMaster[list] = MonitorMaster()
        master.set_data_merger(lambda src, dst: dst.extend(src))
        s = MonitorSlaver(master, "W0")
        s.report([1])
        s.report([2])  # no seq on the direct path: merge every call
        assert master.progress() == {"W0": [1, 2]}


# ---------------------------------------------------------------------------
# /debug/snapshot: the hot-slot table is served
# ---------------------------------------------------------------------------


class TestDebugSnapshotLearning:
    def test_snapshot_serves_learning_plane(self, po):
        from parameter_server_tpu.telemetry.exposition import (
            close_cluster,
            expose_cluster,
        )

        worker = _worker(po, tau=2, name="lt_snap")
        srv = None
        try:
            worker.train(iter(_batches(4)))
            srv = expose_cluster(po, port=0, metrics_interval=0.1)
            body = urllib.request.urlopen(
                f"{srv.url}/debug/snapshot", timeout=10
            ).read()
            snap = json.loads(body)
            lt = snap["learning"]["lt_snap"]
            assert lt["staleness"]["within_bound"]
            assert isinstance(lt["hot_slots"], list) and lt["hot_slots"]
            assert {"slot", "est", "shard"} <= set(lt["hot_slots"][0])
            # the same scrape point serves ps_learning_* series
            metrics = urllib.request.urlopen(
                f"{srv.url}/metrics", timeout=10
            ).read().decode()
            assert "ps_learning_staleness_ministeps" in metrics
        finally:
            close_cluster(srv)
            worker.executor.stop()
