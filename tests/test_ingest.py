"""Staged host-ingest pipeline (PR3): OrderedStagePool contracts,
ProducerConsumer exception/shutdown contracts, MinibatchReader
lifecycle, serial-vs-pipelined determinism parity on the libsvm
fixture (ELL i32 / u24 / bits encodings), and ingest telemetry."""

import os
import threading
import time

import numpy as np
import pytest

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "ingest_parity.libsvm")


def _settle_threads(before, timeout=5.0):
    """Wait for the thread count to drop back to ``before``."""
    t0 = time.time()
    while threading.active_count() > before and time.time() - t0 < timeout:
        time.sleep(0.02)
    return threading.active_count()


class TestOrderedStagePool:
    def test_in_order_emission_under_jitter(self):
        from parameter_server_tpu.utils.concurrent import OrderedStagePool

        def jittered(x):
            time.sleep(0.001 * ((x * 7) % 5))
            return x * x

        out = list(OrderedStagePool(jittered, range(50), num_workers=4))
        assert out == [x * x for x in range(50)]

    def test_fn_exception_forwarded_at_position(self):
        from parameter_server_tpu.utils.concurrent import OrderedStagePool

        def boom(x):
            if x == 3:
                raise ValueError("item three")
            return x

        it = iter(OrderedStagePool(boom, range(8), num_workers=3))
        assert [next(it) for _ in range(3)] == [0, 1, 2]
        with pytest.raises(ValueError, match="item three"):
            next(it)

    def test_source_exception_forwarded(self):
        from parameter_server_tpu.utils.concurrent import OrderedStagePool

        def poisoned():
            yield 1
            yield 2
            raise RuntimeError("source died")

        it = iter(OrderedStagePool(lambda x: x, poisoned(), num_workers=2))
        assert next(it) == 1
        assert next(it) == 2
        with pytest.raises(RuntimeError, match="source died"):
            next(it)

    def test_early_exit_leaks_no_threads(self):
        from parameter_server_tpu.utils.concurrent import OrderedStagePool

        before = threading.active_count()
        pool = OrderedStagePool(
            lambda x: x, range(1000), num_workers=3, capacity=2
        )
        it = iter(pool)
        assert next(it) == 0
        it.close()  # early abandon -> generator finally -> pool.close()
        assert _settle_threads(before) <= before

    def test_close_idempotent_and_joins(self):
        from parameter_server_tpu.utils.concurrent import OrderedStagePool

        before = threading.active_count()
        pool = OrderedStagePool(lambda x: x, range(100), num_workers=2)
        assert list(pool) == list(range(100))
        pool.close()
        pool.close()
        assert _settle_threads(before) <= before

    def test_close_wakes_cross_thread_consumer(self):
        """close() from another thread must wake a consumer blocked in
        the output-queue get (the DeviceUploader nesting), not strand
        it by draining the sentinel it was waiting for."""
        from parameter_server_tpu.utils.concurrent import OrderedStagePool

        def trickle():
            yield 0
            time.sleep(30)  # feeder wedged: consumer will block on item 2
            yield 1

        pool = OrderedStagePool(lambda x: x, trickle(), num_workers=2)
        got = []
        done = threading.Event()

        def consume():
            for x in pool:
                got.append(x)
            done.set()

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        t0 = time.time()
        while not got and time.time() - t0 < 5:
            time.sleep(0.01)
        assert got == [0]
        pool.close()  # consumer is blocked in out_q.get() right now
        assert done.wait(5), "consumer stayed blocked after close()"
        t.join(5)
        assert not t.is_alive()

    def test_backpressure_bounded_window(self):
        from parameter_server_tpu.utils.concurrent import OrderedStagePool

        started = []
        lock = threading.Lock()
        release = threading.Event()

        def slow(x):
            with lock:
                started.append(x)
            release.wait(5)
            return x

        pool = OrderedStagePool(slow, range(100), num_workers=2, capacity=3)
        it = iter(pool)
        time.sleep(0.3)  # let the feeder run as far as it can
        # in-flight window is bounded by capacity: the feeder cannot
        # race ahead of the consumer by more than the out-queue depth
        with lock:
            n_started = len(started)
        assert n_started <= 3 + 2, n_started
        release.set()
        assert next(it) == 0
        it.close()


class TestProducerConsumer:
    def test_producer_exception_forwarded(self):
        from parameter_server_tpu.utils.concurrent import ProducerConsumer

        state = {"n": 0}

        def produce():
            state["n"] += 1
            if state["n"] > 3:
                raise RuntimeError("producer died")
            return state["n"]

        pc = ProducerConsumer(capacity=4)
        pc.start_producer(produce)
        assert [pc.pop(), pc.pop(), pc.pop()] == [1, 2, 3]
        with pytest.raises(RuntimeError, match="producer died"):
            pc.pop()
        # poisoned stream stays poisoned (re-queued like the END marker)
        with pytest.raises(RuntimeError, match="producer died"):
            pc.pop()

    def test_close_leaks_no_threads_on_early_exit(self):
        from parameter_server_tpu.utils.concurrent import ProducerConsumer

        before = threading.active_count()
        pc = ProducerConsumer(capacity=2)
        pc.start_producer(lambda: 7)  # infinite producer, tiny queue
        assert pc.pop() == 7  # consumer exits early after one item
        pc.close()
        assert _settle_threads(before) <= before

    def test_end_of_stream_still_none(self):
        from parameter_server_tpu.utils.concurrent import ProducerConsumer

        it = iter([1, 2])
        pc = ProducerConsumer(capacity=4)
        pc.start_producer(lambda: next(it, None))
        assert [pc.pop(), pc.pop(), pc.pop(), pc.pop()] == [1, 2, None, None]
        pc.close()


class TestMinibatchReaderLifecycle:
    def _batches(self, n=4):
        from parameter_server_tpu.utils.sparse import SparseBatch

        rng = np.random.default_rng(0)
        for _ in range(n):
            idx = np.sort(rng.choice(1 << 20, 32, replace=False))
            yield SparseBatch(
                y=rng.choice((-1.0, 1.0), 8).astype(np.float32),
                indptr=np.arange(0, 33, 4, dtype=np.int64),
                indices=idx.astype(np.int64),
                values=np.ones(32, np.float32),
            )

    def test_read_before_start_raises(self):
        from parameter_server_tpu.learner.sgd import MinibatchReader

        reader = MinibatchReader(batches=self._batches())
        with pytest.raises(RuntimeError, match="before start"):
            reader.read()
        with pytest.raises(RuntimeError, match="before start"):
            next(iter(reader))

    def test_start_idempotent(self):
        from parameter_server_tpu.learner.sgd import MinibatchReader

        before = threading.active_count()
        reader = MinibatchReader(batches=self._batches(3))
        reader.start()
        first_pipe = reader._pipe
        reader.start()  # second call must be a no-op
        assert reader._pipe is first_pipe
        assert len(list(reader)) == 3
        reader.close()
        assert _settle_threads(before) <= before

    def test_close_joins_and_guards(self):
        from parameter_server_tpu.learner.sgd import MinibatchReader

        before = threading.active_count()
        reader = MinibatchReader(batches=self._batches(100))
        reader.start()
        assert reader.read() is not None
        reader.close()
        assert _settle_threads(before) <= before
        with pytest.raises(RuntimeError, match="after close"):
            reader.read()
        with pytest.raises(RuntimeError, match="after close"):
            reader.start()

    def test_context_manager(self):
        from parameter_server_tpu.learner.sgd import MinibatchReader

        before = threading.active_count()
        with MinibatchReader(batches=self._batches(2)) as reader:
            assert sum(1 for _ in reader) == 2
        assert _settle_threads(before) <= before

    def test_init_filter_after_start_raises(self):
        from parameter_server_tpu.learner.sgd import MinibatchReader

        reader = MinibatchReader(batches=self._batches(1))
        reader.start()
        with pytest.raises(RuntimeError, match="after start"):
            reader.init_filter(1 << 10, 2, 1)
        reader.close()

    def test_producer_exception_reaches_read(self):
        from parameter_server_tpu.learner.sgd import MinibatchReader

        def poisoned():
            yield from self._batches(2)
            raise OSError("disk gone")

        with MinibatchReader(batches=poisoned()) as reader:
            assert reader.read() is not None
            assert reader.read() is not None
            with pytest.raises(OSError, match="disk gone"):
                reader.read()


def _prep_fixture_batches(wire):
    """(source batches, prep_fn) for one encoding over the fixture.

    libsvm carries explicit ``:1`` values; the bits/ELL hot paths need
    BINARY batches, so both arms binarize identically (values are all
    ones — dropping them is lossless)."""
    from parameter_server_tpu.apps.linear.async_sgd import (
        prep_batch,
        prep_batch_ell,
        prep_batch_ell_bits,
    )
    from parameter_server_tpu.data.stream_reader import StreamReader
    from parameter_server_tpu.parameter.parameter import KeyDirectory
    from parameter_server_tpu.utils.sparse import SparseBatch

    rows, lanes, num_slots, shards = 128, 8, 4096, 2

    def source():
        for b in StreamReader([FIXTURE], "libsvm").minibatches(rows):
            assert b.values is not None and (b.values == 1).all()
            yield SparseBatch(
                y=b.y, indptr=b.indptr, indices=b.indices, values=None
            )

    directory = KeyDirectory(num_slots, hashed=True)

    if wire == "bits":
        def prep(b):
            out = prep_batch_ell_bits(
                b, directory, shards, rows // shards, lanes, num_slots
            )
            assert out is not None  # fixture is uniform/binary/±1
            return out
    elif wire in ("i32", "u24"):
        def prep(b):
            return prep_batch_ell(
                b, directory, shards, rows // shards, lanes, num_slots,
                pack=wire == "u24",
            )
    else:  # exact COO wire
        def prep(b):
            return prep_batch(
                b, directory, shards, rows // shards, b.nnz, b.nnz,
                num_slots,
            )
    return source, prep


class TestIngestParity:
    """Pipelined ingest must yield bit-identical (batch, uniq_keys)
    sequences to serial ingest on the fixed libsvm fixture — the
    determinism contract that lets the ordered pool replace the
    trainer-thread prep."""

    @pytest.mark.parametrize("wire", ["i32", "u24", "bits", "exact"])
    def test_bit_identical_streams(self, wire):
        import dataclasses

        from parameter_server_tpu.learner.ingest import IngestPipeline
        from parameter_server_tpu.utils.localizer import count_uniq_keys

        source, prep = _prep_fixture_batches(wire)

        def with_keys(b):
            keys, _ = count_uniq_keys(b)
            return prep(b), keys

        serial = [with_keys(b) for b in source()]
        assert len(serial) == 3  # 384 fixture rows / 128

        pipe = IngestPipeline(
            source(), prep_fn=with_keys, workers=3, capacity=2,
            name=f"parity_{wire}",
        ).start()
        pipelined = list(pipe)

        from parameter_server_tpu.apps.linear.async_sgd import ELLBitsBatch
        from parameter_server_tpu.utils.bitpack import slot_bits

        assert len(pipelined) == len(serial)
        for (sp, sk), (pp, pk) in zip(serial, pipelined):
            np.testing.assert_array_equal(sk, pk)
            assert type(sp) is type(pp)
            for f in dataclasses.fields(sp):
                sv, pv = getattr(sp, f.name), getattr(pp, f.name)
                if f.name == "slots_words" and isinstance(sp, ELLBitsBatch):
                    # the bitstream buffer is np.empty by design — only
                    # the live span per shard is meaningful (bits past
                    # it are masked off by the device unpacker)
                    bits = slot_bits(4096)
                    for d in range(sv.shape[0]):
                        live = (int(sp.counts[d]) * 8 * bits + 7) // 8
                        np.testing.assert_array_equal(
                            sv[d].view(np.uint8)[:live],
                            pv[d].view(np.uint8)[:live],
                            err_msg=f"slots_words shard {d}",
                        )
                    continue
                if sv is None:
                    assert pv is None
                elif isinstance(sv, np.ndarray):
                    np.testing.assert_array_equal(sv, pv, err_msg=f.name)
                else:
                    assert sv == pv, f.name

    def test_filtered_reader_parity(self):
        """MinibatchReader with the countmin tail-filter (stateful,
        feeder-serial) matches the inline serial filter application."""
        from parameter_server_tpu.data.stream_reader import StreamReader
        from parameter_server_tpu.filter.frequency import FrequencyFilter
        from parameter_server_tpu.learner.sgd import (
            MinibatchReader,
            apply_tail_filter,
        )

        filt = FrequencyFilter(1 << 14, 2)
        serial = [
            apply_tail_filter(b, filt, 2)
            for b in StreamReader([FIXTURE], "libsvm").minibatches(64)
        ]

        reader = MinibatchReader(files=[FIXTURE], minibatch_size=64)
        reader.init_filter(1 << 14, 2, 2)
        with reader:
            piped = list(reader)

        assert len(piped) == len(serial) == 6
        for s, p in zip(serial, piped):
            np.testing.assert_array_equal(s.indices, p.indices)
            np.testing.assert_array_equal(s.indptr, p.indptr)
            np.testing.assert_array_equal(s.y, p.y)


class TestLocalizerRemapParity:
    """The inverse-based Localizer.remap_index must stay bit-identical
    to the standalone remap() on both the full and filtered key sets
    (the prep hot-path shortcut)."""

    def _batch(self, seed=0, n=64, k=9):
        from parameter_server_tpu.utils.sparse import SparseBatch

        rng = np.random.default_rng(seed)
        idx = rng.integers(0, 1 << 24, n * k).astype(np.int64)
        return SparseBatch(
            y=rng.choice((-1.0, 1.0), n).astype(np.float32),
            indptr=np.arange(0, n * k + 1, k, dtype=np.int64),
            indices=idx,
            values=rng.normal(size=n * k).astype(np.float32),
        )

    def test_full_key_remap_matches(self):
        from parameter_server_tpu.utils.localizer import Localizer, remap

        b = self._batch()
        loc = Localizer()
        keys, _ = loc.count_uniq_index(b)
        fast = loc.remap_index(keys)
        slow = remap(b, keys)
        np.testing.assert_array_equal(fast.indices, slow.indices)
        np.testing.assert_array_equal(fast.indptr, slow.indptr)
        np.testing.assert_array_equal(fast.values, slow.values)
        assert fast.num_cols == slow.num_cols

    def test_filtered_remap_matches(self):
        from parameter_server_tpu.utils.localizer import Localizer, remap

        b = self._batch(seed=3)
        loc = Localizer()
        keys, _ = loc.count_uniq_index(b)
        keep = keys[::3]  # drop two thirds
        fast = loc.remap_index(keep)
        slow = remap(b, keep)
        np.testing.assert_array_equal(fast.indices, slow.indices)
        np.testing.assert_array_equal(fast.indptr, slow.indptr)
        np.testing.assert_array_equal(fast.values, slow.values)
        assert fast.num_cols == slow.num_cols


class TestIngestTelemetry:
    def test_stage_metrics_recorded(self):
        from parameter_server_tpu.learner.ingest import IngestPipeline
        from parameter_server_tpu.telemetry import registry as treg

        if not treg.enabled():
            pytest.skip("telemetry disabled")
        reg = treg.default_registry()
        base = reg.snapshot().get("ps_ingest_examples_total", {})
        base_n = base.get("values", {}).get("pipeline=tel_test", 0.0)

        source, _ = _prep_fixture_batches("i32")

        # no prep workers: batch-shaped items flow through and count
        pipe = IngestPipeline(source(), capacity=2, name="tel_test").start()
        n = sum(b.n for b in pipe)
        assert n == 384

        snap = reg.snapshot()
        total = snap["ps_ingest_examples_total"]["values"]["pipeline=tel_test"]
        assert total - base_n == 384
        stages = set(snap["ps_ingest_stage_seconds"]["values"])
        assert "stage=read" in stages
        assert "queue=tel_test" in snap["ps_ingest_queue_depth"]["values"]

    def test_instruments_in_catalog(self):
        """ps_ingest_* is part of install_all (metrics-lint surface)."""
        from parameter_server_tpu.telemetry.instruments import install_all
        from parameter_server_tpu.telemetry.registry import MetricsRegistry

        names = set(install_all(MetricsRegistry()))
        assert {
            "ps_ingest_stage_seconds",
            "ps_ingest_queue_depth",
            "ps_ingest_examples_total",
            "ps_ingest_batches_total",
            "ps_ingest_uploaded_bytes_total",
        } <= names


class TestDeviceUploader:
    def test_order_exceptions_and_bytes(self, mesh8):
        import jax

        from parameter_server_tpu.apps.linear.async_sgd import DeviceUploader
        from parameter_server_tpu.telemetry import registry as treg

        reg = treg.default_registry() if treg.enabled() else None
        if reg is not None:
            snap = reg.snapshot().get("ps_ingest_uploaded_bytes_total", {})
            before = snap.get("values", {}).get("", 0.0)

        from parameter_server_tpu.apps.linear.async_sgd import HashedBatch

        def mk(i):
            return HashedBatch(
                y=np.full((1, 4), float(i), np.float32),
                mask=np.ones((1, 4), np.float32),
                rows=np.zeros((1, 4), np.int32),
                slots=np.zeros((1, 4), np.int32),
                vals=np.ones((1, 4), np.float32),
            )

        items = [(mk(i), 1) for i in range(8)]
        per_nbytes = sum(
            leaf.nbytes for leaf in jax.tree.leaves(items[0][0])
        )
        up = DeviceUploader(iter(items), lambda h: jax.device_put(h.y))
        got = [(float(np.asarray(a)[0, 0]), n) for a, n in up]
        assert got == [(float(i), 1) for i in range(8)]
        up.close()

        if reg is not None:
            snap = reg.snapshot()["ps_ingest_uploaded_bytes_total"]
            after = snap["values"][""]
            assert after - before == 8 * per_nbytes

        def poisoned():
            yield items[0]
            raise RuntimeError("prep died")

        up = DeviceUploader(poisoned(), lambda h: jax.device_put(h.y))
        it = iter(up)
        next(it)
        with pytest.raises(RuntimeError, match="prep died"):
            next(it)
        up.close()


class TestHostIngestBench:
    def test_smoke_ab_runs_and_reports(self):
        """The components A/B returns the record bench.py embeds; a
        smoke run stays in tier-1 budget (seconds)."""
        from parameter_server_tpu.benchmarks.components import host_ingest_ab

        out = host_ingest_ab(smoke=True)
        assert out["examples"] > 0
        assert out["serial_examples_per_sec"] > 0
        assert out["pipelined_examples_per_sec"] > 0
        assert out["pipelined_speedup"] > 0
