"""Watcher scheduling logic (script/onchip.py): the device-lock
interplay that keeps the evidence watcher from colliding with a
concurrent bench — probe reports "busy" without touching the device,
run_task defers (returns None) instead of running, and an
"unsupported" lock is never misread as busy. All exercised with a
held flock and no device."""

import importlib.util
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def onchip(tmp_path, monkeypatch):
    """Import script/onchip.py fresh with an isolated lock path."""
    monkeypatch.setenv("PS_DEVICE_LOCK", str(tmp_path / "dev.lock"))
    monkeypatch.delenv("PS_DEVICE_LOCK_HELD", raising=False)
    spec = importlib.util.spec_from_file_location(
        "onchip_under_test", os.path.join(REPO, "script", "onchip.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # keep fabricated task records out of the REAL evidence/watch logs
    mod.LOG_MD = str(tmp_path / "log.md")
    mod.WATCH_LOG = str(tmp_path / "watch.log")
    mod.STATE = str(tmp_path / "state.json")
    return mod


def _hold_lock(path):
    """Hold the flock from this process (context manager)."""
    import contextlib
    import fcntl

    @contextlib.contextmanager
    def cm():
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o666)
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        try:
            yield
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    return cm()


def test_probe_reports_busy_under_held_lock(onchip, tmp_path):
    """A held lock means a live device user: probe must say busy
    WITHOUT spawning the (slow, device-touching) probe subprocess."""
    with _hold_lock(str(tmp_path / "dev.lock")):
        up, diag = onchip.probe(timeout_s=5)
    assert not up
    assert "busy" in diag, diag


def test_run_task_defers_under_held_lock(onchip, tmp_path, monkeypatch):
    """run_task returns None (deferred, no attempt burned) when the
    device is busy — it must not launch the child at all."""
    launched = []
    monkeypatch.setattr(
        onchip.subprocess, "run",
        lambda *a, **k: launched.append(a) or (_ for _ in ()).throw(
            AssertionError("child must not launch while device busy")
        ),
    )
    # shrink the internal wait so the test is fast: run_task polls the
    # lock with its own timeout; patch device_lock via the env knob
    import parameter_server_tpu.utils.device_lock as dl

    real = dl.device_lock
    monkeypatch.setattr(
        dl, "device_lock",
        lambda timeout_s=None, poll_s=5.0: real(timeout_s=0.2, poll_s=0.05),
    )
    with _hold_lock(str(tmp_path / "dev.lock")):
        out = onchip.run_task("link", None, timeout_s=5)
    assert out is None
    assert not launched


def test_run_task_runs_when_lock_free(onchip, tmp_path, monkeypatch):
    """With the lock free, run_task launches the child (stubbed) under
    PS_DEVICE_LOCK_HELD and records its JSON output."""
    seen_env = {}

    class R:
        stdout = '{"metric": "x", "value": 1}\n'
        returncode = 0
        stderr = ""

    def fake_run(argv, timeout, capture_output, text, cwd, env):
        seen_env.update(env)
        return R()

    monkeypatch.setattr(onchip.subprocess, "run", fake_run)
    monkeypatch.setattr(onchip, "LOG_MD", str(tmp_path / "log.md"))
    ok = onchip.run_task("link", None, timeout_s=5)
    assert ok is True
    assert seen_env.get("PS_DEVICE_LOCK_HELD") == "1"
    assert "metric" in open(tmp_path / "log.md").read()
