"""Watcher scheduling logic (script/onchip.py): the device-lock
interplay that keeps the evidence watcher from colliding with a
concurrent bench — probe reports "busy" without touching the device,
run_task defers (returns None) instead of running, and an
"unsupported" lock is never misread as busy. All exercised with a
held flock and no device."""

import importlib.util
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def onchip(tmp_path, monkeypatch):
    """Import script/onchip.py fresh with an isolated lock path."""
    monkeypatch.setenv("PS_DEVICE_LOCK", str(tmp_path / "dev.lock"))
    monkeypatch.delenv("PS_DEVICE_LOCK_HELD", raising=False)
    spec = importlib.util.spec_from_file_location(
        "onchip_under_test", os.path.join(REPO, "script", "onchip.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # keep fabricated task records out of the REAL evidence/watch logs
    mod.LOG_MD = str(tmp_path / "log.md")
    mod.WATCH_LOG = str(tmp_path / "watch.log")
    mod.STATE = str(tmp_path / "state.json")
    return mod


def _hold_lock(path):
    """Hold the flock from this process (context manager)."""
    import contextlib
    import fcntl

    @contextlib.contextmanager
    def cm():
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o666)
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        try:
            yield
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    return cm()


def test_probe_reports_busy_under_held_lock(onchip, tmp_path):
    """A held lock means a live device user: probe must say busy
    WITHOUT spawning the (slow, device-touching) probe subprocess."""
    with _hold_lock(str(tmp_path / "dev.lock")):
        up, diag = onchip.probe(timeout_s=5)
    assert not up
    assert "busy" in diag, diag


def test_run_task_defers_under_held_lock(onchip, tmp_path, monkeypatch):
    """run_task returns None (deferred, no attempt burned) when the
    device is busy — it must not launch the child at all."""
    launched = []
    monkeypatch.setattr(
        onchip.subprocess, "Popen",
        lambda *a, **k: launched.append(a) or (_ for _ in ()).throw(
            AssertionError("child must not launch while device busy")
        ),
    )
    # shrink the internal wait so the test is fast: run_task polls the
    # lock with its own timeout; patch device_lock via the env knob
    import parameter_server_tpu.utils.device_lock as dl

    real = dl.device_lock
    monkeypatch.setattr(
        dl, "device_lock",
        lambda timeout_s=None, poll_s=5.0: real(timeout_s=0.2, poll_s=0.05),
    )
    with _hold_lock(str(tmp_path / "dev.lock")):
        out = onchip.run_task("link", None, timeout_s=5)
    assert out is None
    assert not launched


def test_run_task_runs_when_lock_free(onchip, tmp_path, monkeypatch):
    """With the lock free, run_task launches the child (a real echo
    child) under PS_DEVICE_LOCK_HELD and records its JSON output."""
    child = (
        "import os, json; "
        "print(json.dumps({'metric': 'x', 'value': 1, "
        "'held': os.environ.get('PS_DEVICE_LOCK_HELD')}))"
    )
    ok = onchip.run_task("link", [sys.executable, "-c", child], timeout_s=30)
    assert ok is True
    logged = open(onchip.LOG_MD).read()
    assert '"metric": "x"' in logged
    assert '"held": "1"' in logged  # child saw the holder marker


def test_run_task_defers_on_fresh_foreign_request(onchip, tmp_path):
    """A fresh foreign priority marker defers the task BEFORE any
    child launch — the watcher stays off the device entirely while
    the driver's bench is trying to reach it."""
    import time as _t

    import parameter_server_tpu.utils.device_lock as dl

    with open(dl._request_path(), "w") as f:
        f.write(f"{os.getpid() + 1} {_t.time():.0f} bench\n")
    out = onchip.run_task("link", [sys.executable, "-c", "print()"],
                          timeout_s=5)
    assert out is None
    assert "yielding to priority request" in open(onchip.WATCH_LOG).read()


def test_run_task_preempts_running_child_on_request(onchip, tmp_path):
    """A priority request arriving MID-TASK kills the child and
    releases the lock within the 2s poll — the requester never waits
    out a multi-hour task hold. Partial JSON is still logged."""
    import threading
    import time as _t

    import parameter_server_tpu.utils.device_lock as dl

    sentinel = tmp_path / "child_printed"
    child = (
        "import json, pathlib, time; "
        "print(json.dumps({'metric': 'partial', 'value': 1}), flush=True); "
        f"pathlib.Path({str(sentinel)!r}).write_text('up'); "
        "time.sleep(120)"
    )

    def make_request():
        # fire the preemption only once the child has DEMONSTRABLY
        # printed: a fixed timer raced interpreter startup (~2.5s idle,
        # >6s under a loaded core) and killed the child pre-print
        deadline = _t.monotonic() + 60
        while not sentinel.exists() and _t.monotonic() < deadline:
            _t.sleep(0.2)
        if not sentinel.exists():
            # child never came up: let the test fail on its own
            # asserts rather than writing a request that (a) conflates
            # the failure cause and (b) could land under a LATER
            # test's lock dir from this unjoined thread
            return
        with open(dl._request_path(), "w") as f:
            f.write(f"{os.getpid() + 1} {_t.time():.0f} bench\n")

    threading.Thread(target=make_request, daemon=True).start()
    t0 = _t.monotonic()
    out = onchip.run_task("link", [sys.executable, "-c", child],
                          timeout_s=300)
    dt = _t.monotonic() - t0
    assert out is None  # deferred, not an attempt
    assert dt < 60, f"preemption took {dt:.0f}s"
    logged = open(onchip.LOG_MD).read()
    assert "preempted by priority request" in logged
    assert '"metric": "partial"' in logged  # partial output kept
    assert "PREEMPTED" in open(onchip.WATCH_LOG).read()


def test_session_stats_median_and_match(onchip, tmp_path):
    """Cross-session medians read prior SAME-CONFIG captures from the
    evidence log; mismatched device_kind/shape records are excluded."""
    with open(onchip.LOG_MD, "w") as f:
        f.write(
            '{"metric": "m", "value": 100.0, "device_kind": "TPU v5 lite"}\n'
            '{"metric": "m", "value": 300.0, "device_kind": "TPU v5 lite"}\n'
            '{"metric": "m", "value": 9.0, "device_kind": "cpu"}\n'
            '{"metric": "m", "value": 7.0}\n'  # missing key = excluded
            '{"metric": "other", "value": 1.0, "device_kind": "TPU v5 lite"}\n'
            '{"metric": "m", "value": 0, "device_kind": "TPU v5 lite"}\n'
            '{"metric": "m", "val'  # half-written tail must not break it
        )
    st = onchip.session_stats(
        "m", 200.0, {"device_kind": "TPU v5 lite"}
    )
    assert st["sessions"] == 3  # 100, 300 prior + this 200; cpu excluded
    assert st["median_across_sessions"] == 200.0
    assert st["session_spread"] == 1.0  # (300-100)/200
    # no log at all: this run is its own (only) session
    onchip.LOG_MD = str(tmp_path / "missing.md")
    st = onchip.session_stats("m", 50.0)
    assert st == {
        "sessions": 1,
        "median_across_sessions": 50.0,
        "session_spread": 0.0,
    }


def test_probe_yields_to_foreign_request(onchip, tmp_path):
    """probe() must not even spawn the device-touching child while a
    fresh foreign request exists (two tunnel clients wedge each
    other)."""
    import time as _t

    import parameter_server_tpu.utils.device_lock as dl

    with open(dl._request_path(), "w") as f:
        f.write(f"{os.getpid() + 1} {_t.time():.0f} bench\n")
    up, diag = onchip.probe(timeout_s=5)
    assert not up
    assert "yielding to priority request" in diag


def test_fresh_capture_resume_logic(onchip):
    """_fresh_capture: True only for a SUCCESSFUL metric line under a
    section header newer than the window — errors, zero values, stale
    sections, and absent metrics never count (a retry must redo them)."""
    import json
    import time

    now = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime())
    old = time.strftime(
        "%Y-%m-%d %H:%M:%S", time.localtime(time.time() - 2 * 86400)
    )
    kind = {"device_kind": "TPU v5 lite"}
    lines = [
        f"## {old} — lm (rc=0, 100s)",
        json.dumps({"metric": "lm_train_stale", "value": 123.0, **kind}),
        f"## {now} — lm (rc=0, 100s)",
        json.dumps({"metric": "lm_train_good", "value": 123.0, **kind}),
        json.dumps({"metric": "lm_train_err", "error": "boom", **kind}),
        json.dumps({"metric": "lm_train_zero", "value": 0, **kind}),
        # smoke lines and deflated conservative numbers never satisfy
        # a chip task's freshness check
        json.dumps({"metric": "lm_train_smoke", "value": 5.0,
                    "device_kind": "cpu"}),
        json.dumps({"metric": "lm_train_nokind", "value": 5.0}),
        json.dumps({"metric": "lm_decode_noisy", "value": 5.0,
                    "diff_noisy": True, **kind}),
        # a self-declared broken HBM derivation must be re-measured,
        # not treated as a fresh success (r4 advisor finding)
        json.dumps({"metric": "lm_decode_overpeak", "value": 5.0,
                    "exceeds_physical_peak": True, **kind}),
        # non-finite numeric anywhere = degenerate capture (a NaN
        # target_loss means the model diverged; its tok/s is not
        # evidence and must be re-measured, not skipped-as-fresh)
        json.dumps({"metric": "lm_spec_nan", "value": 5.0,
                    "target_loss": float("nan"), **kind}),
    ]
    with open(onchip.LOG_MD, "w") as f:
        f.write("\n".join(lines) + "\n")
    assert onchip._fresh_capture("lm_train_good")
    assert not onchip._fresh_capture("lm_train_stale")  # aged out
    assert not onchip._fresh_capture("lm_train_err")
    assert not onchip._fresh_capture("lm_train_zero")
    assert not onchip._fresh_capture("lm_train_absent")
    assert not onchip._fresh_capture("lm_train_smoke")
    assert not onchip._fresh_capture("lm_train_nokind")
    assert not onchip._fresh_capture("lm_decode_noisy")
    assert not onchip._fresh_capture("lm_decode_overpeak")
    assert not onchip._fresh_capture("lm_spec_nan")
    # a tighter window rejects even the fresh one
    assert not onchip._fresh_capture("lm_train_good", within_s=0.0)


def test_summarize_evidence_table(onchip, tmp_path, capsys, monkeypatch):
    """summarize_evidence: chip successes tabulated with cross-session
    medians; cpu/noisy records excluded by the shared _chip_success;
    a metric whose NEWEST record is an error is flagged even when an
    older success exists."""
    import importlib.util
    import json
    import os
    import sys
    import time

    now = time.time()

    def sec(ts):
        return "## " + time.strftime(
            "%Y-%m-%d %H:%M:%S", time.localtime(ts)
        ) + " — x (rc=0, 1s)"

    kind = {"device_kind": "TPU v5 lite", "unit": "u"}
    lines = [
        sec(now - 3000),
        json.dumps({"metric": "m_ok", "value": 100.0, **kind}),
        json.dumps({"metric": "m_stalefail", "value": 70.0, **kind}),
        json.dumps({"metric": "m_cpu", "value": 5.0,
                    "device_kind": "cpu"}),
        sec(now - 2000),
        json.dumps({"metric": "m_ok", "value": 120.0, **kind}),
        sec(now - 1000),
        json.dumps({"metric": "m_stalefail", "error": "wedge"}),
    ]
    with open(onchip.LOG_MD, "w") as f:
        f.write("\n".join(lines) + "\n")

    spec = importlib.util.spec_from_file_location(
        "summarize_under_test",
        os.path.join(REPO, "script", "summarize_evidence.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "_onchip", lambda: onchip)
    monkeypatch.setattr(sys, "argv", ["summarize_evidence.py"])
    assert mod.main() == 0
    out = capsys.readouterr().out
    # m_ok: 2 captures, median of [100, 120] -> 120 (upper median)
    ok_line = next(ln for ln in out.splitlines() if ln.startswith("m_ok"))
    assert "120.0" in ok_line and " 2 " in ok_line
    # cpu record excluded from the table
    assert "m_cpu" not in out.split("cpu-only")[0]
    # stale success + fresh error -> flagged as live failure
    assert "m_stalefail" in out
    assert "stale success above" in out


def test_state_stale_ages_out_prior_sessions(onchip):
    import time

    fresh = {"attempts": 3,
             "last_start": time.strftime("%Y-%m-%d %H:%M:%S")}
    old = {"attempts": 5, "status": "ok",
           "last_start": time.strftime(
               "%Y-%m-%d %H:%M:%S",
               time.localtime(time.time() - 2 * 86400))}
    assert not onchip._state_stale(fresh)
    assert onchip._state_stale(old)
    assert onchip._state_stale({})          # unparseable
    assert onchip._state_stale("bogus")     # wrong type
    assert onchip._state_stale({"last_start": None})  # null from a
    # hand-edited state file must read stale, not raise
