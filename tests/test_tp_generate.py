"""Serving x parallelism composition: KV-cached decode and speculative
decoding with TENSOR-PARALLEL (Megatron-placed) weights on the virtual
mesh — the obvious multi-chip serving mode. Parity bar: TP-sharded
generation must produce EXACTLY the tokens the replicated run produces
(greedy argmax; f32 compute keeps the psum reassociation below argmax
resolution at these scales).
"""

import dataclasses

import jax
import numpy as np
import pytest

from parameter_server_tpu.models.transformer import (
    LMConfig,
    init_lm,
    lm_generate,
    lm_generate_continue,
    shard_lm_params,
)

CFG = LMConfig(
    vocab=61, d_model=32, n_heads=4, n_layers=2, d_ff=64,
)


@pytest.fixture()
def setup(mesh8):
    params = init_lm(jax.random.PRNGKey(0), CFG)
    prompt = np.asarray(
        np.random.default_rng(1).integers(0, CFG.vocab, (2, 12)), np.int32
    )
    return params, jax.numpy.asarray(prompt)


def test_tp_generate_matches_replicated(setup, mesh8):
    params, prompt = setup
    plain = np.asarray(lm_generate(params, prompt, CFG, steps=9))
    tp = shard_lm_params(params, mesh8)
    # the projection weights really are split over the server axis
    assert "server" in str(
        jax.tree.leaves({k: v for k, v in tp.items() if k.endswith("/wq")})[
            0
        ].sharding.spec
    )
    sharded = np.asarray(lm_generate(tp, prompt, CFG, steps=9))
    np.testing.assert_array_equal(plain, sharded)


def test_tp_generate_gqa_int8_cache(setup, mesh8):
    """TP composes with the serving-side cache shrinkers (GQA + int8
    KV cache) — same exactness bar."""
    cfg = dataclasses.replace(CFG, n_kv_heads=2, kv_cache_dtype="int8")
    prompt = setup[1]
    params = init_lm(jax.random.PRNGKey(2), cfg)
    plain = np.asarray(lm_generate(params, prompt, cfg, steps=7))
    sharded = np.asarray(
        lm_generate(shard_lm_params(params, mesh8), prompt, cfg, steps=7)
    )
    np.testing.assert_array_equal(plain, sharded)


def test_tp_multiturn_continuation(setup, mesh8):
    """Multi-turn serving with TP weights: prefill-and-generate, then
    continue — equal to the replicated run at both turns."""
    params, prompt = setup
    out1, st = lm_generate(
        params, prompt, CFG, steps=5, return_state=True, max_len=40
    )
    turn2 = jax.numpy.asarray([[7, 8], [9, 10]], jax.numpy.int32)
    out2, _ = lm_generate_continue(
        params, st, CFG, steps=4, new_tokens=turn2
    )
    tp = shard_lm_params(params, mesh8)
    tout1, tst = lm_generate(
        tp, prompt, CFG, steps=5, return_state=True, max_len=40
    )
    tout2, _ = lm_generate_continue(
        tp, tst, CFG, steps=4, new_tokens=turn2
    )
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(tout1))
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(tout2))


def test_tp_speculative_decode(setup, mesh8):
    """Speculative decoding with a TP-sharded TARGET (the big model is
    the one worth sharding; the small draft stays replicated): output
    must equal plain greedy decode of the target — the speculative
    exactness contract, now under TP."""
    from parameter_server_tpu.models.speculative import speculative_generate

    params, prompt = setup
    dcfg = LMConfig(vocab=61, d_model=16, n_heads=2, n_layers=1, d_ff=32)
    dparams = init_lm(jax.random.PRNGKey(7), dcfg)
    plain = np.asarray(lm_generate(params, prompt, CFG, steps=8))
    tp = shard_lm_params(params, mesh8)
    out = speculative_generate(tp, CFG, dparams, dcfg, prompt, steps=8, gamma=3)
    np.testing.assert_array_equal(plain, np.asarray(out))
