"""Continuous batching (serving/batcher.py): concurrent decode
sessions sharing ONE running speculative-decode call.

The correctness contract pinned here is GREEDY TOKEN PARITY: every
session's output is token-for-token identical to its own sequential
``speculative_generate`` run — regardless of who shared the batch, when
they joined, or who retired mid-flight. Plus the serving-side edges:
the single-owner feeder rule, capacity validation before any slot is
consumed, EOS retiring a slot while the rest keep stepping, admission
shedding while the batch is full, and serve continuity through a
stalled ``rebalance.migrate``."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_tpu.models.speculative import speculative_generate
from parameter_server_tpu.models.transformer import LMConfig, init_lm
from parameter_server_tpu.parameter.kv_vector import KVVector
from parameter_server_tpu.serving import (
    BatcherConfig,
    ContinuousBatcher,
    DecodeRequest,
    RejectedError,
    ServeConfig,
    ServeFrontend,
)
from parameter_server_tpu.system import faults
from parameter_server_tpu.system.postoffice import Postoffice


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    faults.reset()
    yield
    faults.reset()
    Postoffice.reset()


TCFG = LMConfig(vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64)
DCFG = LMConfig(vocab=64, d_model=16, n_heads=2, n_layers=1, d_ff=32)
GAMMA = 2


@pytest.fixture(scope="module")
def models():
    tparams = init_lm(jax.random.PRNGKey(0), TCFG)
    dparams = init_lm(jax.random.PRNGKey(1), DCFG)
    return tparams, dparams


def _batcher(models, slots=4, max_prompt=8, max_new=16):
    tparams, dparams = models
    return ContinuousBatcher(
        tparams, TCFG, dparams, DCFG,
        BatcherConfig(slots=slots, max_prompt=max_prompt,
                      max_new=max_new, gamma=GAMMA),
    )


def _prompt(seed, b, p):
    return np.asarray(
        jax.random.randint(jax.random.PRNGKey(seed), (b, p), 0, TCFG.vocab),
        np.int32,
    )


def _sequential(models, req):
    """The per-session reference: this request decoded ALONE."""
    tparams, dparams = models
    kw = {}
    if req.prompt_lengths is not None:
        kw["prompt_lengths"] = jnp.asarray(req.prompt_lengths)
    if req.eos_id is not None:
        kw["eos_id"] = int(req.eos_id)
    return np.asarray(speculative_generate(
        tparams, TCFG, dparams, DCFG, jnp.asarray(req.prompt),
        int(req.steps), gamma=GAMMA, **kw,
    ))


def _drain(b, handles_done, max_rounds=500):
    for _ in range(max_rounds):
        if b.active_sessions() == 0:
            return
        handles_done.extend(b.step())
    raise AssertionError("batch failed to drain")


class TestTokenParity:
    def test_identity_under_join_leave_churn(self, models):
        """Six sessions with DIFFERENT lengths and budgets through a
        4-slot batch: late joiners enter as early finishers retire, and
        every output still equals its own solo run."""
        reqs = [
            DecodeRequest(prompt=_prompt(10 + i, 1, 3 + (i % 5)),
                          steps=4 + 3 * (i % 4))
            for i in range(6)
        ]
        b = _batcher(models)
        b.warmup()
        done, pending = [], list(reqs)
        admitted = []
        for _ in range(500):
            while pending and b.free_slots() >= pending[0].prompt.shape[0]:
                admitted.append(b.admit(pending.pop(0)))
            if not pending and b.active_sessions() == 0:
                break
            done.extend(b.step())
        assert len(done) == len(reqs)
        assert b.stats()["joins"] == 6 and b.stats()["retired"] == 6
        for h in admitted:
            np.testing.assert_array_equal(
                h.out, _sequential(models, h.req)
            )

    def test_wave_admit_and_block_step_identity(self, models):
        """The throughput path — admit_many joining mixed requests in
        one fused call (with its pow2 padding) and step_block fusing
        rounds per dispatch — commits exactly the same tokens as the
        one-by-one admit/step path pins above. Mixed per-request eos
        in a wave exercises the per-row eos vector; eos presence also
        forces the block back to single-round stepping."""
        reqs = [
            DecodeRequest(prompt=_prompt(40 + i, 1, 3 + (i % 4)),
                          steps=5 + 2 * (i % 3),
                          eos_id=(63 if i == 2 else None))
            for i in range(7)
        ]
        b = _batcher(models)
        b.warmup()
        done, pending = [], list(reqs)
        for _ in range(500):
            wave = []
            while pending and len(wave) < b.free_slots():
                wave.append((pending.pop(0), None))
            handles = b.admit_many(wave)
            assert len(handles) == len(wave)
            done.extend(b.step_block())
            if not pending and b.active_sessions() == 0:
                break
        assert len(done) == len(reqs)
        for h in done:
            np.testing.assert_array_equal(
                h.out, _sequential(models, h.req)
            )

    def test_block_step_fuses_rounds(self, models):
        """With no eos-armed session resident, step_block fuses
        exactly ceil(min_remaining/(gamma+1)) rounds into one dispatch
        — the bound is host-computable, so the fused count is
        deterministic regardless of acceptance luck."""
        b = _batcher(models)
        b.warmup()
        b.admit_many([
            (DecodeRequest(prompt=_prompt(50 + i, 1, 4), steps=12), None)
            for i in range(4)
        ])
        before = b.stats()["rounds"]
        b.step_block()
        # after join committed = len+1, so remaining = 11 and a round
        # commits at most gamma+1 = 3 tokens: ceil(11/3) = 4 rounds
        assert b.stats()["rounds"] - before == 4

    def test_wave_validation_never_leaks_slots(self, models):
        """One malformed request in a wave fails the whole admit_many
        BEFORE any slot is consumed — the frontend then isolates the
        bad one by re-admitting individually."""
        b = _batcher(models)
        good = DecodeRequest(prompt=_prompt(1, 1, 4), steps=4)
        bad = DecodeRequest(prompt=_prompt(2, 1, 4), steps=999)
        with pytest.raises(ValueError, match="steps"):
            b.admit_many([(good, None), (bad, None)])
        assert b.free_slots() == 4 and b.active_sessions() == 0

    def test_multi_row_ragged_request(self, models):
        """One request, three rows, ragged lengths: rows decode as
        independent sessions and reassemble in original row order."""
        prompt = _prompt(3, 3, 6)
        req = DecodeRequest(
            prompt=prompt, steps=5,
            prompt_lengths=np.array([6, 3, 4]),
        )
        b = _batcher(models)
        h = b.admit(req)
        done = []
        _drain(b, done)
        assert done == [h]
        np.testing.assert_array_equal(h.out, _sequential(models, req))

    def test_eos_retires_mid_batch_without_stalling_rest(self, models):
        """A session whose target commits EOS frees its slot EARLY
        while a longer session keeps decoding — and both still match
        their solo runs (EOS row: eos then zero-pads, the
        speculative_generate contract)."""
        short = DecodeRequest(prompt=_prompt(7, 1, 4), steps=12)
        # pick the eos from the short request's own solo continuation
        # so the batched run provably hits it mid-budget
        solo = _sequential(models, short)
        eos = int(solo[0, 4 + 2])  # the 3rd generated token
        short = DecodeRequest(prompt=short.prompt, steps=12, eos_id=eos)
        long = DecodeRequest(prompt=_prompt(8, 1, 4), steps=16)

        b = _batcher(models, slots=2)
        hs = b.admit(short)
        hl = b.admit(long)
        finished_order = []
        done = []
        for _ in range(500):
            if b.active_sessions() == 0:
                break
            for h in b.step():
                finished_order.append(h)
                done.append(h)
        assert finished_order[0] is hs  # eos retired first
        assert b.stats()["retired"] == 2
        np.testing.assert_array_equal(hs.out, _sequential(models, short))
        np.testing.assert_array_equal(hl.out, _sequential(models, long))
        # the eos actually cut the short session's output
        row = hs.out[0]
        assert eos in row[4:]
        cut = 4 + int(np.argmax(row[4:] == eos))
        assert (row[cut + 1:] == 0).all()


class TestSchedulerContract:
    def test_single_owner_enforced(self, models):
        b = _batcher(models)
        b.admit(DecodeRequest(prompt=_prompt(1, 1, 4), steps=3))
        errs = []

        def intruder():
            try:
                b.step()
            except RuntimeError as e:
                errs.append(e)

        t = threading.Thread(target=intruder)
        t.start()
        t.join(timeout=30)
        assert errs and "single-owner" in str(errs[0])
        done = []
        _drain(b, done)  # the owner thread still drives fine
        assert len(done) == 1

    def test_validate_rejects_before_consuming_slots(self, models):
        b = _batcher(models, slots=2, max_prompt=8, max_new=16)
        bad = [
            DecodeRequest(prompt=_prompt(1, 1, 9), steps=4),   # too wide
            DecodeRequest(prompt=_prompt(1, 3, 4), steps=4),   # B > slots
            DecodeRequest(prompt=_prompt(1, 1, 4), steps=17),  # > max_new
            DecodeRequest(prompt=_prompt(1, 1, 4), steps=0),
            DecodeRequest(prompt=_prompt(1, 1, 4), steps=4, eos_id=64),
            DecodeRequest(prompt=_prompt(1, 1, 4), steps=4,
                          prompt_lengths=np.array([5])),  # len > width
        ]
        for req in bad:
            with pytest.raises(ValueError):
                b.admit(req)
        assert b.free_slots() == 2  # nothing leaked

    def test_admit_past_capacity_raises(self, models):
        b = _batcher(models, slots=1)
        b.admit(DecodeRequest(prompt=_prompt(1, 1, 4), steps=8))
        with pytest.raises(RuntimeError, match="batch full"):
            b.admit(DecodeRequest(prompt=_prompt(2, 1, 4), steps=8))


# ---------------------------------------------------------------------------
# through the frontend: the decode worker as the batcher's scheduler
# ---------------------------------------------------------------------------


def _store(mesh, n_keys=128):
    kv = KVVector(mesh=mesh, k=1, num_slots=1 << 10, hashed=True,
                  name="batch_serve")
    rng = np.random.default_rng(0)
    keys = np.unique(rng.integers(0, 1 << 16, n_keys))
    kv.wait(kv.push(kv.request(channel=0), keys=keys,
                    values=np.ones((len(keys), 1), np.float32)))
    return kv, keys


class TestFrontendBatched:
    def test_concurrent_sessions_match_solo_runs(self, models, mesh8):
        """The tentpole end to end: concurrent DecodeRequests through
        ``ServeFrontend(batcher=...)`` — different prompts, budgets and
        arrival times sharing one running decode — each returning
        exactly its solo ``speculative_generate`` tokens."""
        kv, _ = _store(mesh8)
        fe = ServeFrontend(
            kv, ServeConfig(replica="off", workers=1),
            batcher=_batcher(models),
        ).start()
        try:
            reqs = [
                DecodeRequest(prompt=_prompt(20 + i, 1, 3 + (i % 5)),
                              steps=4 + 3 * (i % 4))
                for i in range(6)
            ]
            tickets = [fe.submit(r) for r in reqs]
            for r, tk in zip(reqs, tickets):
                np.testing.assert_array_equal(
                    tk.result(300), _sequential(models, r)
                )
            st = fe.stats()["batcher"]
            assert st["joins"] == 6 and st["retired"] == 6
            assert st["rounds"] >= 1
            snap = Postoffice.instance().metrics.snapshot()
            for m in ("ps_serve_batch_joins_total",
                      "ps_serve_batch_rounds_total",
                      "ps_serve_batch_retired_total"):
                assert sum(snap[m]["values"].values()) >= 1, m
        finally:
            fe.close()

    def test_admission_sheds_while_batch_full(self, models, mesh8):
        """The door still bounds the decode lane: with one slot pinned
        by a long session and the lane at its depth bound, the next
        decode sheds with the explicit 429 — it never queues unbounded
        behind the busy batch."""
        kv, _ = _store(mesh8)
        fe = ServeFrontend(
            kv, ServeConfig(replica="off", workers=1, max_queue_depth=2),
            batcher=_batcher(models, slots=1, max_new=16),
        ).start()
        try:
            t1 = fe.submit(DecodeRequest(prompt=_prompt(1, 1, 4), steps=16))
            t2 = fe.submit(DecodeRequest(prompt=_prompt(2, 1, 4), steps=16))
            with pytest.raises(RejectedError) as ei:
                fe.submit(DecodeRequest(prompt=_prompt(3, 1, 4), steps=4))
            assert ei.value.reason == "queue"
            assert ei.value.retry_after_s >= 0
            for tk in (t1, t2):  # the resident sessions still finish
                assert tk.result(300).shape == (1, 4 + 16)
        finally:
            fe.close()

    def test_serve_continuity_through_stalled_migration(self, models,
                                                        mesh8):
        """Batched decode touches only device model state — never the
        store — so a live ``rebalance.migrate`` stalling mid-move must
        not stall resident sessions (the pause-keeps-stepping
        semantics): decodes submitted before AND during the stall all
        complete with solo-run parity."""
        kv, keys = _store(mesh8)
        fe = ServeFrontend(
            kv, ServeConfig(replica="off", workers=1),
            batcher=_batcher(models),
        ).start()
        try:
            faults.arm("rebalance.migrate", kind="delay", delay_s=0.5,
                       once=True)
            mig = threading.Thread(
                target=lambda: kv.migrate(
                    np.random.default_rng(0).permutation(kv.num_slots)
                )
            )
            req0 = DecodeRequest(prompt=_prompt(30, 1, 4), steps=12)
            t0 = fe.submit(req0)
            mig.start()
            time.sleep(0.1)  # inside the stalled window
            reqs = [
                DecodeRequest(prompt=_prompt(31 + i, 1, 5), steps=8)
                for i in range(3)
            ]
            tickets = [fe.submit(r) for r in reqs]
            np.testing.assert_array_equal(
                t0.result(300), _sequential(models, req0)
            )
            for r, tk in zip(reqs, tickets):
                np.testing.assert_array_equal(
                    tk.result(300), _sequential(models, r)
                )
            mig.join(timeout=60)
            assert not mig.is_alive()
        finally:
            fe.close()

    def test_batcher_and_decode_fn_are_exclusive(self, models, mesh8):
        kv, _ = _store(mesh8)
        with pytest.raises(ValueError, match="decode_fn"):
            ServeFrontend(
                kv, ServeConfig(replica="off"),
                decode_fn=lambda req: req.prompt,
                batcher=_batcher(models),
            )
