"""Rotary position embeddings (LMConfig.rope): math vs a complex-number
reference, the relative-offset property, and parity of every schedule
(ring/flash/zigzag/a2a/GQA/decode) against the dense single-shard model
with rotation on. RoPE is the repo's positional scheme beyond NoPE; the
reference framework has no LM at all, so these are extension tests."""

import dataclasses

import jax
import numpy as np
import pytest

from parameter_server_tpu.models.transformer import (
    LMConfig,
    apply_rope,
    init_lm,
    lm_forward,
    lm_generate,
    shard_tokens,
)


class TestRopeMath:
    def test_matches_complex_rotation(self):
        """GPT-NeoX half-split RoPE is elementwise complex multiplication
        by e^(i * pos * theta^(-j/half)) on pairs (x[j], x[j+half])."""
        rng = np.random.default_rng(0)
        hd, s = 8, 16
        x = rng.normal(size=(s, hd)).astype(np.float32)
        pos = np.arange(s)
        got = np.asarray(apply_rope(x, pos))
        half = hd // 2
        inv = 10000.0 ** (-np.arange(half) / half)
        ang = pos[:, None] * inv[None, :]
        z = x[:, :half] + 1j * x[:, half:]
        rot = z * np.exp(1j * ang)
        want = np.concatenate([rot.real, rot.imag], -1).astype(np.float32)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_scores_depend_on_relative_offset_only(self):
        """(R_i q) . (R_j k) == (R_{i+c} q) . (R_{j+c} k): the whole point
        of rotary embeddings."""
        rng = np.random.default_rng(1)
        hd = 32
        q = rng.normal(size=(hd,)).astype(np.float32)
        k = rng.normal(size=(hd,)).astype(np.float32)

        def score(i, j):
            qi = np.asarray(apply_rope(q, np.int32(i)))
            kj = np.asarray(apply_rope(k, np.int32(j)))
            return float(qi @ kj)

        for i, j, c in [(3, 1, 40), (7, 7, 100), (12, 2, 1000)]:
            np.testing.assert_allclose(
                score(i, j), score(i + c, j + c), rtol=1e-4
            )

    def test_position_zero_is_identity(self):
        x = np.random.default_rng(2).normal(size=(4, 16)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(apply_rope(x, np.zeros(4, np.int32))), x, atol=1e-6
        )

    def test_odd_head_dim_rejected(self):
        with pytest.raises(ValueError, match="even"):
            LMConfig(vocab=8, d_model=6, n_heads=2, n_layers=1, d_ff=8,
                     rope=True)


@pytest.fixture(scope="module")
def rcfg():
    return LMConfig(vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                    rope=True)


@pytest.fixture(scope="module")
def rparams(rcfg):
    return init_lm(jax.random.PRNGKey(0), rcfg)


def _dense_ref(params, tokens, cfg):
    """Single-shard forward = the dense reference for every schedule."""
    from parameter_server_tpu.parallel import mesh as meshlib

    mesh1 = meshlib.make_mesh(num_data=1, num_server=1)
    return np.asarray(
        lm_forward(params, shard_tokens(tokens, mesh1), cfg, mesh1, "data")
    )


class TestRopeSchedules:
    def test_rope_changes_the_forward(self, mesh8, rcfg, rparams):
        """Guard against a silently-ignored flag. At the 0.02 init scale
        attention scores are ~1e-4 and near-uniform, so rotation barely
        moves the softmax; sharpen attention by scaling wq/wk."""
        sharp = {
            k: v * 50.0 if k.endswith(("wq", "wk")) else v
            for k, v in rparams.items()
        }
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, rcfg.vocab, (2, 64)).astype(np.int32)
        nope = dataclasses.replace(rcfg, rope=False)
        a = _dense_ref(sharp, tokens, rcfg)
        b = _dense_ref(sharp, tokens, nope)
        assert np.abs(a - b).max() > 1e-3

    @pytest.mark.parametrize("attention", ["ring", "ring_flash"])
    def test_sharded_matches_dense(self, mesh8, rcfg, rparams, attention):
        """Sequence sharding must not change rotated attention: the
        position iota partitions with the tokens under GSPMD."""
        cfg = dataclasses.replace(rcfg, attention=attention)
        rng = np.random.default_rng(3)
        tokens = rng.integers(0, cfg.vocab, (2, 64)).astype(np.int32)
        got = np.asarray(
            lm_forward(rparams, shard_tokens(tokens, mesh8), cfg, mesh8,
                       "data")
        )
        np.testing.assert_allclose(
            got, _dense_ref(rparams, tokens, cfg), atol=2e-4
        )

    def test_a2a_sharded_matches_dense(self, mesh8):
        """Ulysses reshards seq<->head; rope rotates before the a2a, so
        the head split must not disturb the rotation. Needs n_heads
        divisible by the data axis."""
        cfg = LMConfig(vocab=32, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                       rope=True, attention="a2a")
        params = init_lm(jax.random.PRNGKey(4), cfg)
        rng = np.random.default_rng(3)
        tokens = rng.integers(0, cfg.vocab, (2, 64)).astype(np.int32)
        got = np.asarray(
            lm_forward(params, shard_tokens(tokens, mesh8), cfg, mesh8,
                       "data")
        )
        np.testing.assert_allclose(
            got, _dense_ref(params, tokens, cfg), atol=2e-4
        )

    def test_zigzag_matches_dense_through_permutation(self, mesh8, rcfg,
                                                      rparams):
        """Zigzag layout: logits come back permuted but must equal the
        natural-order dense forward gathered through the permutation —
        proving the zigzag position ids are the permutation itself."""
        from parameter_server_tpu.models.attention import zigzag_permutation

        n = mesh8.shape["data"]
        cfg = dataclasses.replace(rcfg, attention="ring_zigzag")
        rng = np.random.default_rng(4)
        tokens = rng.integers(0, cfg.vocab, (2, 64)).astype(np.int32)
        perm = zigzag_permutation(64, n)
        got = np.asarray(
            lm_forward(rparams, shard_tokens(tokens[:, perm], mesh8), cfg,
                       mesh8, "data")
        )
        want = _dense_ref(rparams, tokens, dataclasses.replace(rcfg))
        np.testing.assert_allclose(got, want[:, perm], atol=2e-4)

    def test_gqa_rope_sharded_matches_dense(self, mesh8):
        cfg = LMConfig(vocab=32, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                       rope=True, n_kv_heads=2)
        params = init_lm(jax.random.PRNGKey(1), cfg)
        rng = np.random.default_rng(5)
        tokens = rng.integers(0, cfg.vocab, (2, 64)).astype(np.int32)
        got = np.asarray(
            lm_forward(params, shard_tokens(tokens, mesh8), cfg, mesh8,
                       "data")
        )
        np.testing.assert_allclose(
            got, _dense_ref(params, tokens, cfg), atol=2e-4
        )

    @pytest.mark.parametrize("kvh", [None, 1])
    def test_decode_matches_forward(self, rcfg, kvh):
        """KV-cached decode (rotate at the absolute slot, cache stores
        rotated k) must reproduce the training forward's logits."""
        cfg = dataclasses.replace(rcfg, n_kv_heads=kvh)
        params = init_lm(jax.random.PRNGKey(2), cfg)
        rng = np.random.default_rng(6)
        prompt = rng.integers(0, cfg.vocab, (2, 7)).astype(np.int32)
        steps = 5
        toks, logits = lm_generate(
            params, prompt, cfg, steps=steps, return_logits=True
        )
        # toks is [B, P+steps] (prompt included); logits covers every
        # position that predicts a next token: rows [0, P+steps-2]
        want = _dense_ref(params, np.asarray(toks), cfg)
        np.testing.assert_allclose(
            np.asarray(logits), want[:, :-1], atol=2e-4, rtol=1e-4
        )

    def test_remat_gradients_match_with_rope(self, mesh8, rcfg, rparams):
        """The hoisted cos/sin tables enter jax.checkpoint as inputs;
        remat must stay gradient-identical with rotation on."""
        from parameter_server_tpu.models.transformer import lm_loss

        rng = np.random.default_rng(7)
        tokens = shard_tokens(
            rng.integers(0, rcfg.vocab, (2, 64)).astype(np.int32), mesh8
        )
        g0 = jax.grad(lm_loss)(rparams, tokens, rcfg, mesh8, "data")
        g1 = jax.grad(lm_loss)(
            rparams, tokens, dataclasses.replace(rcfg, remat=True),
            mesh8, "data",
        )
        for k in g0:
            np.testing.assert_allclose(
                np.asarray(g0[k]), np.asarray(g1[k]), atol=1e-5,
                err_msg=k,
            )

    def test_rope_lm_learns_position_task(self, mesh8):
        """A genuinely position-dependent task: the period-4 pattern
        A B A C — the successor of A is B at even phase and C at odd
        phase, so a bigram (position-blind) predictor bottoms out at
        (2 ln 2)/4 ~ 0.347 nats/token. Driving the loss clearly below
        that floor requires using position, which RoPE provides."""
        import optax

        from parameter_server_tpu.models.transformer import lm_loss

        cfg = LMConfig(vocab=8, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                       rope=True)
        params = init_lm(jax.random.PRNGKey(3), cfg)
        tx = optax.adam(3e-3)
        opt = tx.init(params)

        @jax.jit
        def step(p, opt, toks):
            loss, g = jax.value_and_grad(lm_loss)(p, toks, cfg, mesh8, "data")
            up, opt = tx.update(g, opt, p)
            return optax.apply_updates(p, up), opt, loss

        tokens = np.tile(np.array([1, 2, 1, 3], np.int32), (4, 16))
        toks = shard_tokens(tokens, mesh8)
        loss = None
        for _ in range(200):
            params, opt, loss = step(params, opt, toks)
            loss.block_until_ready()  # throttle async dispatch
        bigram_floor = 2 * np.log(2) / 4  # ~0.347
        assert float(loss) < 0.6 * bigram_floor, (float(loss), bigram_floor)
