"""Watchdog: a mid-run tunnel wedge must yield the best-so-far JSON
record, not a hang (observed 2026-07-31: bench blocked 40 minutes in a
device wait, losing the already-measured phases).

Runs bench.Watchdog in a subprocess because it exits via os._exit.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

REPO_SNIPPET = """
import sys, time
sys.path.insert(0, {repo!r})
from bench import Watchdog
wd = Watchdog({metric!r}, stall_s=0.5, poll_s=0.1)
{body}
"""


def _run(body: str, metric: str = "criteo_sparse_lr_examples_per_sec"):
    return subprocess.run(
        [sys.executable, "-c",
         REPO_SNIPPET.format(repo=REPO, metric=metric, body=body)],
        capture_output=True, text=True, timeout=60,
    )


def test_wedge_after_headline_emits_partial_record_rc0():
    r = _run(
        "wd.beat('e2e', value=123456.0, vs_baseline=0.25, note='n')\n"
        "time.sleep(30)\n"
        "print('UNREACHED')\n"
    )
    assert r.returncode == 0
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["value"] == 123456.0
    assert rec["vs_baseline"] == 0.25
    assert "e2e" in rec["wedged"]
    assert "CUT SHORT" in rec["note"]
    assert "UNREACHED" not in r.stdout


def test_wedge_before_headline_emits_error_record_rc2():
    r = _run("wd.beat('warmup')\ntime.sleep(30)\n")
    assert r.returncode == 2
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["value"] == 0
    assert "warmup" in rec["error"]


def test_cancel_stops_the_watchdog():
    # sleep far past stall_s + several polls: only a WORKING cancel()
    # keeps the watchdog from firing during the wait
    r = _run(
        "wd.beat('e2e', value=1.0)\nwd.cancel()\ntime.sleep(2.0)\n"
        "print('SURVIVED')\n"
    )
    assert r.returncode == 0
    assert "SURVIVED" in r.stdout
    assert "wedged" not in r.stdout


def test_grace_defers_firing():
    # grace(10) pushes the idle clock past the whole 2s sleep (stall 0.5,
    # poll 0.1): a broken grace() would fire the error record mid-sleep
    r = _run("wd.grace(10)\ntime.sleep(2.0)\nwd.cancel()\nprint('HELD')\n")
    assert r.returncode == 0
    assert "HELD" in r.stdout
    assert "wedged" not in r.stdout


def test_beat_snaps_grace_back():
    # a beat after grace restores normal patience: the subsequent silence
    # must fire even though a 100s grace was granted earlier
    r = _run("wd.grace(100)\nwd.beat('late')\ntime.sleep(2.0)\n")
    assert r.returncode == 2
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert "late" in rec["error"]


def test_error_record_keeps_staged_diagnostics():
    # a wedge before the headline must still carry already-measured
    # fields (sweep_error, parity results), with value forced to 0
    r = _run(
        "wd.beat('e2e', sweep_error='boom', parity_ok=True)\n"
        "time.sleep(2.0)\n"
    )
    assert r.returncode == 2
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["value"] == 0 and rec["vs_baseline"] == 0
    assert rec["sweep_error"] == "boom"
    assert rec["parity_ok"] is True
    assert "wedged" in rec["error"]


def test_beats_keep_it_alive():
    # total wall time ~2s = many poll cycles past stall_s; only the
    # beats hold the idle clock below 0.5s
    r = _run(
        "for _ in range(10):\n"
        "    time.sleep(0.2)\n"
        "    wd.beat()\n"
        "wd.cancel()\nprint('ALIVE')\n"
    )
    assert r.returncode == 0
    assert "ALIVE" in r.stdout
    assert "wedged" not in r.stdout


def test_abort_after_headline_emits_partial_record_rc0():
    # a mid-run EXCEPTION (backend death raises instead of stalling —
    # observed: UNAVAILABLE from device_put 26 minutes into a healthy
    # run) must keep the already-landed headline, exit code 0
    r = _run(
        "wd.beat('e2e', value=42.0, vs_baseline=0.1, note='n')\n"
        "code = wd.abort('JaxRuntimeError: UNAVAILABLE')\n"
        "sys.exit(code)\n"
    )
    assert r.returncode == 0
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["value"] == 42.0
    assert "UNAVAILABLE" in rec["wedged"]
    assert "CUT SHORT" in rec["note"]


def test_abort_before_headline_emits_error_record_rc2():
    r = _run(
        "wd.beat('warmup', sweep_error='boom')\n"
        "code = wd.abort('JaxRuntimeError: UNAVAILABLE')\n"
        "sys.exit(code)\n"
    )
    assert r.returncode == 2
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["value"] == 0
    assert rec["sweep_error"] == "boom"
    assert "UNAVAILABLE" in rec["error"] and "warmup" in rec["error"]


def test_abort_after_finish_is_a_noop():
    # the exception handler may run after a final record already
    # printed: abort must not emit a second one
    r = _run(
        "import json\n"
        "wd.finish({'metric': 'm', 'value': 1.0})\n"
        "code = wd.abort('late')\n"
        "assert code == 0\n"
        "time.sleep(0.5)\n"
    )
    assert r.returncode == 0
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1


def test_finish_is_atomic_and_prints_once():
    r = _run(
        "import json\n"
        "wd.beat('e2e', value=7.0)\n"
        "wd.finish({'metric': 'm', 'value': 7.0})\n"
        "time.sleep(2.0)\n"
    )
    assert r.returncode == 0
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1
    assert json.loads(lines[0])["value"] == 7.0


def test_grace_is_monotone(monkeypatch):
    """A later, smaller grace must never shrink a pending larger one:
    _grace_for_compile(600) after _grace_for_transfer(big) would
    otherwise cut a legitimate slow upload's budget short and os._exit
    a healthy run (review finding, 2026-08-01)."""
    import bench

    wd = bench.Watchdog("m", stall_s=1e9)  # never fires on its own
    try:
        wd.grace(5000.0)
        big = wd._last
        wd.grace(10.0)
        assert wd._last == big  # smaller grace did not shrink
        wd.grace(9000.0)
        assert wd._last > big  # larger grace still extends
        wd.beat()
        assert wd._last < big  # beat snaps back to normal
    finally:
        wd.cancel()


def test_sigterm_flush_after_headline_keeps_measurement():
    # driver SIGTERM mid-run AFTER the headline landed: the staged
    # measurement must survive as the final record (r4 lost exactly
    # this: rc 124, parsed null)
    r = _run(
        "wd.beat('e2e', value=99.0, vs_baseline=0.2, note='n')\n"
        "wd.sigterm_flush('supervisor SIGTERM')\n"
        "time.sleep(0.5)\n"
    )
    assert r.returncode == 0
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["value"] == 99.0
    assert "SIGTERM" in rec["wedged"]


def test_sigterm_flush_before_headline_emits_error_record():
    r = _run(
        "wd.beat('warmup', sweep_error='x')\n"
        "wd.sigterm_flush('supervisor SIGTERM')\n"
    )
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["value"] == 0
    assert "SIGTERM" in rec["error"]
    assert rec["sweep_error"] == "x"


def test_sigterm_flush_after_finish_is_silent():
    # the handler may fire after a final record already printed: the
    # single-record guarantee must hold
    r = _run(
        "wd.finish({'metric': 'm', 'value': 3.0})\n"
        "wd.sigterm_flush('late SIGTERM')\n"
        "time.sleep(0.3)\n"
    )
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1
    assert json.loads(lines[0])["value"] == 3.0


def test_probe_budget_stays_under_driver_patience():
    """Total worst-case probe budget must stay well under the driver's
    observed ~30-min kill window: round 4's ~50-min budget meant the
    driver SIGTERM'd the bench mid-retry and recorded nothing."""
    import inspect

    import bench

    sig = inspect.signature(bench.probe_device)
    d = {k: v.default for k, v in sig.parameters.items()}
    total = d["attempts"] * d["timeout_s"] + (d["attempts"] - 1) * d["retry_wait_s"]
    assert total <= 900, f"probe budget {total}s exceeds the 15-min cap"


def test_probe_retries_refresh_the_provisional_record(monkeypatch):
    import subprocess as sp

    import bench
    from parameter_server_tpu.utils import device_lock, subproc

    def _always_hangs(cmd, timeout_s):
        raise sp.TimeoutExpired(cmd, timeout_s)

    monkeypatch.setattr(subproc, "run_graceful", _always_hangs)
    # keep the test off the real watcher's priority-marker files
    monkeypatch.setattr(device_lock, "request_priority", lambda *a, **k: None)
    calls = []
    diag = bench.probe_device(
        timeout_s=0.1, attempts=3, retry_wait_s=0.0,
        on_retry=lambda a, d: calls.append((a, d)),
    )
    assert diag is not None and "did not complete" in diag
    assert [a for a, _ in calls] == [1, 2]
    assert all("did not complete" in d for _, d in calls)


def test_bench_main_sigterm_during_probe_leaves_record():
    """End-to-end kill test: SIGTERM while the probe hangs must leave a
    parseable failure record on stdout (the exact r4 silent death)."""
    snippet = """
import contextlib, os, signal, sys, threading, time
# the test NEEDS main() to take the probe path: an ambient
# JAX_PLATFORMS=cpu (the tier-1 harness exports it) flips main's
# cpu_run shortcut and skips the probe entirely — clear it; the probe
# is mocked below so no device is ever touched either way
os.environ.pop("JAX_PLATFORMS", None)
sys.path.insert(0, {repo!r})
import bench
import parameter_server_tpu.utils.device_lock as dl
# no real device work in this test: neutralize the machine-wide lock
# and priority markers so a live watcher on this host is undisturbed
dl.device_lock = lambda **kw: contextlib.nullcontext(True)
dl.clear_priority = lambda: None
bench.probe_device = lambda **kw: time.sleep(600)
threading.Timer(
    3.0, lambda: os.kill(os.getpid(), signal.SIGTERM)
).start()
sys.argv = ["bench.py"]
sys.exit(bench.main())
""".format(repo=REPO)
    r = subprocess.run(
        [sys.executable, "-c", snippet],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 143
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert lines, f"no JSON on stdout; stderr: {r.stderr[-500:]}"
    rec = json.loads(lines[-1])
    assert rec["value"] == 0 and rec["vs_baseline"] == 0
    assert "SIGTERM'd by its supervisor" in rec["error"]
    # the provisional printed BEFORE the kill too (belt for SIGKILL)
    first = json.loads(lines[0])
    assert first["value"] == 0 and "provisional" in first["error"]


def test_build_device_error_skips_provisional_lines(tmp_path, monkeypatch):
    """The watcher copies EVERY JSON line of a bench run into
    BENCH_ONCHIP.md — including the new zero-value provisional printed
    before the probe. A zero line must not consume the section's
    attribution stamp, or the real capture behind it is never seen."""
    import bench

    (tmp_path / "BENCH_ONCHIP.md").write_text(
        "## 2026-08-02 09:00:00 — bench (rc=0, 300s)\n"
        "```\n"
        '{"metric": "criteo_sparse_lr_examples_per_sec", "value": 0, '
        '"vs_baseline": 0, "error": "provisional record: ..."}\n'
        '{"metric": "criteo_sparse_lr_examples_per_sec", "value": 650000.0, '
        '"unit": "examples/sec", "vs_baseline": 1.3}\n'
        "```\n"
    )
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
    rec = bench.build_device_error("tunnel down")
    cap = rec["last_onchip_capture"]
    assert cap["value"] == 650000.0
    assert cap["captured_at"].startswith("2026-08-02")


def test_build_device_error_metric_threads_through(tmp_path, monkeypatch):
    import bench

    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
    rec = bench.build_device_error(
        "x", metric="criteo_real_examples_per_sec"
    )
    assert rec["metric"] == "criteo_real_examples_per_sec"


class TestUploadPipeline:
    def _patch(self, monkeypatch):
        import jax

        import bench

        class FakeSB:
            def __init__(self, parts):
                self.num_examples = sum(p.num_examples for p in parts)

        monkeypatch.setattr(
            bench, "stack_supersteps", lambda parts, T: FakeSB(parts)
        )
        monkeypatch.setattr(bench, "tree_host_nbytes", lambda sb: 7)
        monkeypatch.setattr(jax, "device_put", lambda sb: sb)
        return bench

    def test_groups_of_T_and_tail_skip(self, monkeypatch):
        bench = self._patch(monkeypatch)

        class P:
            num_examples = 2

        pipe = bench.UploadPipeline(iter([P() for _ in range(7)]), T=3)
        got = list(pipe)
        assert [(n, nb) for _sb, n, nb, _fid in got] == [(6, 7), (6, 7)]
        # no span sink installed -> no flow ids allocated
        assert all(fid is None for _sb, _n, _nb, fid in got)
        # the 7th part is a trailing partial group: skipped + disclosed
        assert pipe.skipped_examples == 2

    def test_producer_exception_propagates(self, monkeypatch):
        bench = self._patch(monkeypatch)

        def boom():
            class P:
                num_examples = 1

            yield P()
            raise RuntimeError("parse died")

        pipe = bench.UploadPipeline(boom(), T=1)
        it = iter(pipe)
        next(it)  # first group arrives
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="parse died"):
            for _ in it:
                pass


def test_operation_blocks_firing_despite_foreign_beats():
    # an in-budget operation on one thread must hold the watchdog's
    # fire even though another thread beats (which would cancel a
    # plain grace); stall 0.5s, op budget 3s, sleep 1.5s with beats
    r = _run(
        "import threading\n"
        "stop = []\n"
        "def beater():\n"
        "    while not stop:\n"
        "        wd.beat()\n"
        "        time.sleep(0.05)\n"
        "threading.Thread(target=beater, daemon=True).start()\n"
        "with wd.operation(3.0):\n"
        "    stop_t = time.monotonic() + 1.5\n"
        "    while time.monotonic() < stop_t:\n"
        "        time.sleep(0.1)\n"
        "stop.append(1)\n"
        "wd.cancel()\nprint('OP_HELD')\n"
    )
    assert r.returncode == 0
    assert "OP_HELD" in r.stdout
    assert "wedged" not in r.stdout


def test_operation_exit_restores_sensitivity():
    # after the op exits, plain stall detection resumes immediately
    r = _run(
        "with wd.operation(100.0):\n"
        "    pass\n"
        "time.sleep(2.0)\n"
    )
    assert r.returncode == 2
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert "wedged" in rec["error"]


def test_expired_operation_budget_fires():
    # a WEDGED transfer outlives its byte-derived budget: the watchdog
    # must fire once the budget expires instead of waiting forever
    r = _run(
        "import threading\n"
        "def stuck():\n"
        "    with wd.operation(0.2):\n"
        "        time.sleep(60)\n"
        "threading.Thread(target=stuck, daemon=True).start()\n"
        "time.sleep(30)\n"
    )
    assert r.returncode == 2


def test_sigterm_handler_clears_priority_marker():
    """A SIGTERM during the device-lock WAIT must not leave a priority
    marker behind: the watcher honors fresh markers from dead pids for
    up to 30 minutes (observed ~11 idle minutes from two killed test
    benches, 2026-08-01)."""
    import os

    import pytest

    import bench
    from parameter_server_tpu.utils import device_lock as dl

    dl.request_priority("test-kill")
    assert os.path.exists(dl._request_path())
    with pytest.raises(SystemExit):
        bench._sigterm_handler(15, None)
    assert not os.path.exists(dl._request_path())
