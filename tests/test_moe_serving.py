"""MoE serving: the generate family now runs mixture-of-experts
models (dropless per-token routing — transformer._moe_ffn_dropless).

Exactness bar: with a training capacity that never binds
(capacity_factor >= n_experts), serving logits/tokens match the
training forward exactly — capacity drops are a whole-batch decision
incremental decoding cannot reproduce, so serving routes droplessly
and the equality holds precisely when nothing was dropped."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_tpu.models.transformer import (
    LMConfig,
    init_lm,
    lm_forward,
    lm_generate,
    lm_generate_continue,
    shard_tokens,
)

# Promoted to the slow tier (PR 2, per the PR-1 ROADMAP note): the
# shard_map-shim unlock made the full 'not slow' suite overrun the
# 870s tier-1 budget on a 2-core host. Run via `pytest -m slow`.
pytestmark = pytest.mark.slow

# layer 2 is MoE; capacity_factor >= n_experts => training never drops
MOE = LMConfig(
    vocab=61, d_model=32, n_heads=4, n_layers=2, d_ff=64,
    moe_every=2, n_experts=4, capacity_factor=8.0,
)


@pytest.fixture()
def params():
    return init_lm(jax.random.PRNGKey(0), MOE)


def _mesh1():
    from parameter_server_tpu.parallel import mesh as meshlib

    return meshlib.make_mesh(num_data=1, num_server=1)


def test_moe_prefill_logits_match_forward(mesh8, params):
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 61, (2, 16)).astype(np.int32)
    _, dec = lm_generate(params, tokens, MOE, steps=0, return_logits=True)
    mesh1 = _mesh1()
    full = lm_forward(params, shard_tokens(tokens, mesh1), MOE, mesh1, "data")
    np.testing.assert_allclose(
        np.asarray(dec), np.asarray(full)[:, :-1], atol=2e-4, rtol=1e-4
    )


def test_moe_greedy_decode_matches_forward_argmax(mesh8, params):
    """Full circle: greedy-generate, then re-run the TRAINING forward
    over the produced sequence — its argmax must reproduce every
    generated token (covers _decode_step's MoE path, not just
    prefill)."""
    rng = np.random.default_rng(2)
    prompt = jnp.asarray(rng.integers(0, 61, (2, 9)), np.int32)
    out = lm_generate(params, prompt, MOE, steps=7)
    mesh1 = _mesh1()
    full = np.asarray(
        lm_forward(params, shard_tokens(np.asarray(out), mesh1), MOE,
                   mesh1, "data")
    )
    pred = full.argmax(-1)
    np.testing.assert_array_equal(
        pred[:, 8:-1], np.asarray(out)[:, 9:]
    )


def test_moe_ragged_rows_equal_single_row(mesh8, params):
    rng = np.random.default_rng(3)
    rows = [rng.integers(1, 61, w).astype(np.int32) for w in (4, 10)]
    padded = np.zeros((2, 10), np.int32)
    for i, r in enumerate(rows):
        padded[i, : r.size] = r
    out = np.asarray(
        lm_generate(
            params, jnp.asarray(padded), MOE, steps=5,
            prompt_lengths=np.asarray([4, 10], np.int32),
        )
    )
    for i, r in enumerate(rows):
        solo = np.asarray(
            lm_generate(params, jnp.asarray(r[None, :]), MOE, steps=5)
        )[0]
        np.testing.assert_array_equal(out[i, : r.size + 5], solo)


def test_moe_multiturn_continuation(mesh8, params):
    rng = np.random.default_rng(4)
    p1 = jnp.asarray(rng.integers(0, 61, (2, 6)), np.int32)
    turn2 = jnp.asarray(rng.integers(0, 61, (2, 3)), np.int32)
    out1, st = lm_generate(
        params, p1, MOE, steps=4, return_state=True, max_len=24
    )
    out2, _ = lm_generate_continue(
        params, st, MOE, steps=4, new_tokens=turn2
    )
    # single-shot over the concatenated history
    hist = jnp.concatenate([jnp.asarray(out1), turn2], axis=1)
    single = np.asarray(lm_generate(params, hist, MOE, steps=4))
    np.testing.assert_array_equal(
        np.asarray(out2)[:, -4:], single[:, -4:]
    )


def test_moe_speculative_target(mesh8, params):
    from parameter_server_tpu.models.speculative import speculative_generate

    rng = np.random.default_rng(5)
    prompt = jnp.asarray(rng.integers(0, 61, (2, 7)), np.int32)
    dcfg = LMConfig(vocab=61, d_model=16, n_heads=2, n_layers=1, d_ff=32)
    dparams = init_lm(jax.random.PRNGKey(6), dcfg)
    plain = np.asarray(lm_generate(params, prompt, MOE, steps=6))
    spec = np.asarray(
        speculative_generate(params, MOE, dparams, dcfg, prompt, 6, gamma=2)
    )
    np.testing.assert_array_equal(plain, spec)


def test_moe_under_tensor_parallelism(mesh8, params):
    """MoE decode with TP-sharded dense weights (the expert tables
    stay replicated under shard_lm_params): tokens equal the
    replicated run exactly."""
    from parameter_server_tpu.models.transformer import shard_lm_params

    rng = np.random.default_rng(11)
    prompt = jnp.asarray(rng.integers(0, 61, (2, 7)), np.int32)
    rep = np.asarray(lm_generate(params, prompt, MOE, steps=5))
    tp = np.asarray(
        lm_generate(shard_lm_params(params, mesh8), prompt, MOE, steps=5)
    )
    np.testing.assert_array_equal(rep, tp)


def test_moe_sampled_generation_runs(mesh8, params):
    rng = np.random.default_rng(7)
    prompt = jnp.asarray(rng.integers(0, 61, (2, 5)), np.int32)
    out = np.asarray(
        lm_generate(
            params, prompt, MOE, steps=4, temperature=0.9, top_k=8,
            key=jax.random.PRNGKey(8),
        )
    )
    assert out.shape == (2, 9)


def test_capacity_binding_breaks_parity_documented(mesh8, params):
    """The documented caveat is real: with a SMALL training capacity
    (drops likely), the training forward and the dropless serving
    prefill legitimately diverge — this pins that the equality above
    is doing work, not holding vacuously."""
    tight = dataclasses.replace(MOE, capacity_factor=0.25)
    rng = np.random.default_rng(9)
    # enough tokens that a 0.25 capacity factor MUST drop some
    tokens = rng.integers(0, 61, (2, 32)).astype(np.int32)
    _, dec = lm_generate(params, tokens, tight, steps=0, return_logits=True)
    mesh1 = _mesh1()
    full = lm_forward(
        params, shard_tokens(tokens, mesh1), tight, mesh1, "data"
    )
    diff = np.abs(np.asarray(dec) - np.asarray(full)[:, :-1]).max()
    assert diff > 1e-3, (
        "expected divergence under binding capacity; got none — is the "
        "dropless-vs-capacity distinction still real?"
    )
