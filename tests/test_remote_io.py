"""Remote filesystem plumbing (ref util/hdfs.h + file.cc hadoopFS):
pluggable scheme registry, gzip streaming over remote reads, and the
hadoop-CLI adapter exercised against a local fake `hadoop` executable."""

import gzip
import os
import stat

import pytest

from parameter_server_tpu.data.stream_reader import StreamReader
from parameter_server_tpu.utils import file as psfile


class LocalFakeFS(psfile.RemoteFS):
    """mock:// filesystem backed by a local directory."""

    def __init__(self, root):
        self.root = str(root)

    def _local(self, path):
        return os.path.join(self.root, path.split("://", 1)[1])

    def open_read(self, path):
        return open(self._local(path), "rb")

    def open_write(self, path):
        local = self._local(path)
        os.makedirs(os.path.dirname(local), exist_ok=True)
        return open(local, "wb")

    def list(self, pattern):
        import glob

        hits = glob.glob(self._local(pattern))
        return sorted(
            "mock://" + os.path.relpath(h, self.root) for h in hits
        )


@pytest.fixture
def mockfs(tmp_path):
    fs = LocalFakeFS(tmp_path / "remote")
    psfile.register_filesystem("mock", fs)
    yield fs
    psfile.register_filesystem("mock", None)


def test_unregistered_scheme_still_gated():
    with pytest.raises(NotImplementedError, match="register"):
        psfile.open_read("hdfs://nn/some/file.txt")
    with pytest.raises(NotImplementedError):
        psfile.open_write("s3://bucket/key")


def test_roundtrip_text_through_registered_fs(mockfs):
    with psfile.open_write("mock://a/b.txt") as f:
        f.write("hello\nworld\n")
    assert list(psfile.read_lines("mock://a/b.txt")) == ["hello", "world"]


def test_gzip_streaming_over_remote(mockfs, tmp_path):
    local = tmp_path / "remote" / "z.gz"
    os.makedirs(local.parent, exist_ok=True)
    with gzip.open(local, "wt") as f:
        f.write("1 1:0.5\n-1 2:1.5\n")
    lines = list(psfile.read_lines("mock://z.gz"))
    assert lines == ["1 1:0.5", "-1 2:1.5"]


def test_expand_globs_lists_remote(mockfs, tmp_path):
    root = tmp_path / "remote" / "train"
    os.makedirs(root)
    for i in range(3):
        (root / f"part-{i}").write_text("1 1:1\n")
    hits = psfile.expand_globs(["mock://train/part-*"])
    assert hits == [f"mock://train/part-{i}" for i in range(3)]


def test_stream_reader_over_remote(mockfs, tmp_path):
    root = tmp_path / "remote" / "d"
    os.makedirs(root)
    (root / "p0").write_text("1 1:0.5\n-1 3:2\n")
    (root / "p1").write_text("1 2:1\n")
    batch = StreamReader(["mock://d/p*"], "libsvm").read_all()
    assert batch is not None and batch.n == 3 and batch.nnz == 3


FAKE_HADOOP = """#!/bin/sh
# tiny `hadoop fs` stand-in: maps hdfs://fake/<p> to $FAKE_HDFS_ROOT/<p>
shift  # drop "fs"
while [ "$1" = "-D" ]; do shift 2; done
op="$1"; shift
strip() { echo "$1" | sed 's|hdfs://fake/||'; }
case "$op" in
  -cat) cat "$FAKE_HDFS_ROOT/$(strip "$1")" ;;
  -put) src="$1"; dst="$FAKE_HDFS_ROOT/$(strip "$2")"
        mkdir -p "$(dirname "$dst")"; cat > "$dst" ;;
  -ls)  for f in "$FAKE_HDFS_ROOT"/$(strip "$1"); do
          [ -e "$f" ] || exit 1
          echo "-rw-r--r-- 1 u g 0 2026-01-01 00:00 hdfs://fake/$(basename "$f")"
        done ;;
  *) exit 2 ;;
esac
"""


@pytest.fixture
def hadoop_cli(tmp_path, monkeypatch):
    binary = tmp_path / "hadoop"
    binary.write_text(FAKE_HADOOP)
    binary.chmod(binary.stat().st_mode | stat.S_IEXEC)
    root = tmp_path / "hdfs_root"
    os.makedirs(root)
    monkeypatch.setenv("FAKE_HDFS_ROOT", str(root))
    fs = psfile.HadoopCliFS(binary=str(binary), namenode="hdfs://fake")
    psfile.register_filesystem("hdfs", fs)
    yield root
    psfile.register_filesystem("hdfs", None)


def test_hadoop_cli_read_write_roundtrip(hadoop_cli):
    with psfile.open_write("hdfs://fake/out/data.txt") as f:
        f.write("alpha\nbeta\n")
    assert (hadoop_cli / "out" / "data.txt").read_text() == "alpha\nbeta\n"
    assert list(psfile.read_lines("hdfs://fake/out/data.txt")) == ["alpha", "beta"]


def test_hadoop_cli_ls(hadoop_cli):
    for i in range(2):
        (hadoop_cli / f"part-{i}").write_text("x\n")
    hits = psfile.expand_globs(["hdfs://fake/part-*"])
    assert hits == ["hdfs://fake/part-0", "hdfs://fake/part-1"]


def test_hadoop_cli_missing_file_raises(hadoop_cli):
    f = psfile.open_read("hdfs://fake/nope.txt")
    with pytest.raises(IOError):
        f.read()
        f.close()
