"""NN-through-KVLayer training, ring collectives, and ring attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from parameter_server_tpu.models.attention import dense_attention, ring_attention
from parameter_server_tpu.models.convnet import MLP, ConvNet
from parameter_server_tpu.parallel.ring import (
    ring_allgather,
    ring_allreduce,
    ring_scan,
)
from parameter_server_tpu.system.postoffice import Postoffice


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    yield
    Postoffice.reset()


def synth_classification(n, d, classes, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(classes, d)) * 3
    y = rng.integers(0, classes, n)
    x = centers[y] + rng.normal(size=(n, d))
    return x.astype(np.float32), y.astype(np.int32)


class TestNNTrainer:
    def test_mlp_learns_blobs(self, mesh8):
        from parameter_server_tpu.apps.nn.trainer import NNTrainer

        x, y = synth_classification(512, 16, 4, seed=0)
        trainer = NNTrainer(MLP(num_classes=4), input_shape=(16,), mesh=mesh8)
        first = None
        for i in range(30):
            m = trainer.train_step(x, y)
            if first is None:
                first = m["loss"]
        ev = trainer.evaluate(x, y)
        assert ev["accuracy"] > 0.9
        assert m["loss"] < first * 0.5

    def test_convnet_shapes_and_step(self, mesh8):
        from parameter_server_tpu.apps.nn.trainer import NNTrainer

        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 16, 16, 3)).astype(np.float32)
        y = rng.integers(0, 10, 16).astype(np.int32)
        trainer = NNTrainer(ConvNet(num_classes=10, width=8), input_shape=(16, 16, 3), mesh=mesh8)
        m1 = trainer.train_step(x, y)
        m2 = trainer.train_step(x, y)
        assert np.isfinite(m1["loss"]) and m2["loss"] <= m1["loss"] * 1.5

    def test_checkpoint_restore_roundtrip(self, mesh8, tmp_path):
        """A fresh trainer (different seed) restores exactly — params,
        optimizer momentum, and step count — and keeps training."""
        from parameter_server_tpu.apps.nn.trainer import NNTrainer
        from parameter_server_tpu.parameter.replica import CheckpointManager

        x, y = synth_classification(256, 16, 4, seed=0)
        t1 = NNTrainer(MLP(num_classes=4), input_shape=(16,), mesh=mesh8)
        for _ in range(10):
            t1.train_step(x, y)
        mgr = CheckpointManager(str(tmp_path / "ck"))
        t1.checkpoint(mgr, step=10)
        want = t1.evaluate(x, y)

        t2 = NNTrainer(
            MLP(num_classes=4), input_shape=(16,), mesh=mesh8, seed=99
        )
        assert t2.restore(mgr) == 10
        assert t2.steps_done == 10
        got = t2.evaluate(x, y)
        assert got["loss"] == want["loss"], (got, want)
        # momentum came back too: the next steps match the original run
        m1 = t1.train_step(x, y)
        m2 = t2.train_step(x, y)
        np.testing.assert_allclose(m1["loss"], m2["loss"], rtol=1e-6)

    def test_params_live_in_kv_layer(self, mesh8):
        from parameter_server_tpu.apps.nn.trainer import NNTrainer

        trainer = NNTrainer(MLP(num_classes=2), input_shape=(8,), mesh=mesh8)
        assert len(trainer.kv.layers) == 4  # 2 dense layers x (kernel, bias)
        snap = trainer.kv.get_replica()
        assert all(isinstance(v, np.ndarray) for v in snap.values())


class TestRing:
    def test_ring_allreduce_matches_psum(self, mesh8):
        x = np.arange(32, dtype=np.float32).reshape(8, 4)

        def local(v):
            return ring_allreduce(v[0], "data")[None]

        out = shard_map(
            local, mesh=mesh8, in_specs=(P("data", None),), out_specs=P("data", None),
            check_vma=False,
        )(x.reshape(4, 2, 4))
        expect = x.reshape(4, 2, 4).sum(axis=0)
        for shard in np.asarray(out):
            np.testing.assert_allclose(shard, expect)

    def test_ring_allgather_order(self, mesh8):
        x = np.arange(4, dtype=np.float32)

        def local(v):
            return ring_allgather(v[0], "data")[None]

        out = shard_map(
            local, mesh=mesh8, in_specs=(P("data"),), out_specs=P("data"),
            check_vma=False,
        )(x.reshape(4, 1))
        # every device must see [x0, x1, x2, x3] in device order
        res = np.asarray(out).reshape(4, 4)
        for row in res:
            np.testing.assert_allclose(row, x)

    def test_ring_scan_visits_all_blocks(self, mesh8):
        x = np.arange(4, dtype=np.float32)

        def local(v):
            acc = ring_scan(
                v[0], "data", lambda a, blk, step: a + blk, jnp.zeros_like(v[0])
            )
            return acc[None]

        out = shard_map(
            local, mesh=mesh8, in_specs=(P("data"),), out_specs=P("data"),
            check_vma=False,
        )(x.reshape(4, 1))
        np.testing.assert_allclose(np.asarray(out).ravel(), [6, 6, 6, 6])


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, mesh8, causal):
        rng = np.random.default_rng(0)
        b, s, h = 2, 32, 16  # s sharded 4-way -> 8 per device
        q = rng.normal(size=(b, s, h)).astype(np.float32)
        k = rng.normal(size=(b, s, h)).astype(np.float32)
        v = rng.normal(size=(b, s, h)).astype(np.float32)
        out = ring_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            mesh=mesh8, axis="data", causal=causal,
        )
        expect = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect), atol=2e-5)

    def test_long_sequence_memory_shape(self, mesh8):
        # just exercises a longer sharded sequence end to end
        rng = np.random.default_rng(1)
        q = rng.normal(size=(1, 256, 8)).astype(np.float32)
        out = ring_attention(
            jnp.asarray(q), jnp.asarray(q), jnp.asarray(q), mesh=mesh8, axis="data",
            causal=True,
        )
        assert out.shape == (1, 256, 8)
        assert np.isfinite(np.asarray(out)).all()


class TestRingAttentionGrad:
    def test_gradient_matches_dense(self, mesh8):
        """Autodiff through the ppermute ring: training long-context
        models over a seq-sharded mesh needs exact gradients, not just the
        forward pass."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from parameter_server_tpu.models.attention import (
            dense_attention,
            ring_attention,
        )

        rng = np.random.default_rng(0)
        b, s, h = 2, 32, 8
        q, k, v = (rng.normal(size=(b, s, h)).astype(np.float32) for _ in range(3))
        shard = NamedSharding(mesh8, P(None, "data", None))

        def loss_ring(q, k, v):
            out = ring_attention(q, k, v, mesh=mesh8, axis="data", causal=True)
            return jnp.sum(out * out)

        def loss_dense(q, k, v):
            out = dense_attention(q, k, v, causal=True)
            return jnp.sum(out * out)

        qd, kd, vd = (jax.device_put(x, shard) for x in (q, k, v))
        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(qd, kd, vd)
        g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for gr, gd in zip(g_ring, g_dense):
            np.testing.assert_allclose(np.asarray(gr), np.asarray(gd), atol=2e-4)


class TestUlyssesAttention:
    """All-to-all sequence parallelism (models/attention.ulysses_attention):
    the a2a complement of ring attention — re-shard sequence->heads, dense
    attention per local head, re-shard back."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_mha(self, mesh8, causal):
        from parameter_server_tpu.models.attention import (
            dense_mha,
            ulysses_attention,
        )

        rng = np.random.default_rng(0)
        b, s, h, nh = 2, 32, 32, 8
        q, k, v = (rng.normal(size=(b, s, h)).astype(np.float32) for _ in range(3))
        out = ulysses_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            mesh=mesh8, axis="data", n_heads=nh, causal=causal,
        )
        want = dense_mha(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), nh, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5)

    def test_gradient_matches_dense(self, mesh8):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from parameter_server_tpu.models.attention import (
            dense_mha,
            ulysses_attention,
        )

        rng = np.random.default_rng(1)
        b, s, h, nh = 2, 32, 16, 4
        q, k, v = (rng.normal(size=(b, s, h)).astype(np.float32) for _ in range(3))
        shard = NamedSharding(mesh8, P(None, "data", None))

        def loss_u(q, k, v):
            o = ulysses_attention(q, k, v, mesh=mesh8, axis="data",
                                  n_heads=nh, causal=True)
            return jnp.sum(o * o)

        def loss_d(q, k, v):
            return jnp.sum(dense_mha(q, k, v, nh, causal=True) ** 2)

        qd, kd, vd = (jax.device_put(x, shard) for x in (q, k, v))
        gu = jax.grad(loss_u, argnums=(0, 1, 2))(qd, kd, vd)
        gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(gu, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=2e-4)


class TestMoEExpertParallel:
    """Expert parallelism (models/moe.py): switch-routed MoE FFN with
    experts sharded over the mesh axis and a2a token dispatch."""

    def _setup(self, d=16, ff=32, e=8):
        from parameter_server_tpu.models.moe import init_moe

        return init_moe(jax.random.PRNGKey(0), d, ff, e)

    def test_matches_dense_reference(self, mesh8):
        from parameter_server_tpu.models.moe import moe_ffn, moe_ffn_dense

        params = self._setup()
        rng = np.random.default_rng(1)
        x = rng.normal(size=(2, 32, 16)).astype(np.float32)
        out = moe_ffn(params, jnp.asarray(x), mesh=mesh8, axis="data")
        want = moe_ffn_dense(params, jnp.asarray(x), n_shards=4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-6)

    def test_gradient_matches_dense(self, mesh8):
        import jax as _jax

        from parameter_server_tpu.models.moe import moe_ffn, moe_ffn_dense

        params = self._setup()
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.normal(size=(2, 32, 16)).astype(np.float32))

        gs = _jax.grad(lambda p: jnp.sum(moe_ffn(p, x, mesh=mesh8, axis="data") ** 2))(params)
        gd = _jax.grad(lambda p: jnp.sum(moe_ffn_dense(p, x, n_shards=4) ** 2))(params)
        for k in gs:
            np.testing.assert_allclose(
                np.asarray(gs[k]), np.asarray(gd[k]), atol=2e-4,
                err_msg=k,
            )

    def test_capacity_drops_overflow_tokens(self, mesh8):
        from parameter_server_tpu.models.moe import moe_ffn

        params = self._setup()
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(size=(1, 32, 16)).astype(np.float32))

        def zero_frac(cf):
            out = moe_ffn(params, x, mesh=mesh8, axis="data",
                          capacity_factor=cf)
            flat = np.asarray(out).reshape(-1, 16)
            return (np.abs(flat).sum(axis=1) == 0).mean()

        # ample capacity: every token served; tight capacity: overflow
        # tokens emit exactly 0 (Switch residual-path semantics)
        assert zero_frac(8.0) == 0.0
        assert zero_frac(0.5) > zero_frac(8.0)


class TestPipelineParallel:
    """GPipe fill-drain pipeline (models/pipeline.py): stage-sharded
    layers, microbatches streamed over the ppermute ring — forward and
    gradients must equal sequential layer application."""

    def _stage_fn(self):
        def stage_fn(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])

        return stage_fn

    def _params(self, n, d, seed=0):
        rng = np.random.default_rng(seed)
        return {
            "w": jnp.asarray(rng.normal(size=(n, d, d)).astype(np.float32) * 0.3),
            "b": jnp.asarray(rng.normal(size=(n, d)).astype(np.float32) * 0.1),
        }

    def test_forward_matches_sequential(self, mesh8):
        from parameter_server_tpu.models.pipeline import (
            pipeline_apply,
            sequential_apply,
        )

        n, d = 4, 8  # mesh8 data axis = 4 stages
        params = self._params(n, d)
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(6, 5, d)).astype(np.float32))
        out = pipeline_apply(self._stage_fn(), params, x, mesh=mesh8, axis="data")
        want = sequential_apply(self._stage_fn(), params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)

    def test_gradients_match_sequential(self, mesh8):
        import jax as _jax

        from parameter_server_tpu.models.pipeline import (
            pipeline_apply,
            sequential_apply,
        )

        n, d = 4, 8
        params = self._params(n, d, seed=2)
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(size=(5, 4, d)).astype(np.float32))
        fn = self._stage_fn()
        gp = _jax.grad(
            lambda p: jnp.sum(pipeline_apply(fn, p, x, mesh=mesh8, axis="data") ** 2)
        )(params)
        gs = _jax.grad(lambda p: jnp.sum(sequential_apply(fn, p, x) ** 2))(params)
        for k in gp:
            np.testing.assert_allclose(
                np.asarray(gp[k]), np.asarray(gs[k]), atol=1e-4, err_msg=k
            )

    @pytest.mark.parametrize("k", [2, 3])
    def test_multiple_stages_per_device(self, mesh8, k):
        """n_stages = k * axis: each device chains its k-stage block per
        tick — deep stacks without more devices; fwd + grads exact."""
        import jax as _jax

        from parameter_server_tpu.models.pipeline import (
            pipeline_apply,
            sequential_apply,
        )

        n, d = 4 * k, 8  # mesh8 data axis = 4 devices
        params = self._params(n, d, seed=6)
        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.normal(size=(5, 3, d)).astype(np.float32))
        fn = self._stage_fn()
        out = pipeline_apply(fn, params, x, mesh=mesh8, axis="data")
        want = sequential_apply(fn, params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)
        gp = _jax.grad(
            lambda p: jnp.sum(pipeline_apply(fn, p, x, mesh=mesh8, axis="data") ** 2)
        )(params)
        gs = _jax.grad(lambda p: jnp.sum(sequential_apply(fn, p, x) ** 2))(params)
        for key in gp:
            np.testing.assert_allclose(
                np.asarray(gp[key]), np.asarray(gs[key]), atol=1e-4,
                err_msg=key,
            )

    def test_non_multiple_stage_count_rejected(self, mesh8):
        from parameter_server_tpu.models.pipeline import pipeline_apply

        params = self._params(5, 8)  # 5 stages on a 4-device axis
        x = jnp.zeros((2, 3, 8), jnp.float32)
        with pytest.raises(ValueError, match="MULTIPLE"):
            pipeline_apply(self._stage_fn(), params, x, mesh=mesh8, axis="data")

    def test_single_microbatch(self, mesh8):
        from parameter_server_tpu.models.pipeline import (
            pipeline_apply,
            sequential_apply,
        )

        params = self._params(4, 8, seed=4)
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.normal(size=(1, 3, 8)).astype(np.float32))
        out = pipeline_apply(self._stage_fn(), params, x, mesh=mesh8, axis="data")
        want = sequential_apply(self._stage_fn(), params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)
