"""Linear-method CLI (apps/linear/main.py): end-to-end conf-driven run
on the virtual mesh — the reference's `main.cc + ps.sh` surface. Also
covers --profile device-trace capture and Checkpointable.checkpoint_async."""

import numpy as np
import pytest

from parameter_server_tpu.apps.linear.main import main

# Promoted to the slow tier (PR 2, per the PR-1 ROADMAP note): the
# shard_map-shim unlock made the full 'not slow' suite overrun the
# 870s tier-1 budget on a 2-core host. Run via `pytest -m slow`.
pytestmark = pytest.mark.slow


@pytest.fixture(autouse=True)
def fresh_po():
    """Exception-safe singleton teardown (repo pattern, test_darlin.py)."""
    from parameter_server_tpu.system.postoffice import Postoffice

    Postoffice.reset()
    yield
    Postoffice.reset()


@pytest.fixture()
def svm_conf(tmp_path):
    rng = np.random.default_rng(0)
    lines = []
    for _ in range(400):
        y = rng.integers(0, 2)
        idx = np.sort(rng.choice(200, size=8, replace=False))
        feats = " ".join(f"{i + 1}:1" for i in idx)
        lines.append(f"{y} {feats}\n")
    data = tmp_path / "part.train"
    data.write_text("".join(lines))
    conf = tmp_path / "run.conf"
    conf.write_text(
        f"""
training_data {{
  format: TEXT
  text: LIBSVM
  file: "{data}"
}}
loss {{ type: LOGIT }}
penalty {{ type: L1 lambda: 0.1 }}
learning_rate {{ type: DECAY alpha: 1 beta: 1 }}
async_sgd {{
  algo: FTRL
  minibatch: 100
}}
"""
    )
    return conf


def test_linear_cli_runs_conf(mesh8, svm_conf, capsys):
    rc = main([str(svm_conf)])
    assert rc == 0
    out = capsys.readouterr().out
    # the scheduler's merged progress table (ref ShowProgress header)
    assert "examples" in out, out


def test_linear_cli_bf16_state_conf(mesh8, svm_conf, capsys):
    """ftrl_state_dtype is .conf-reachable end to end: the run trains
    with a bf16 sqrt_n table through the full CLI path."""
    from parameter_server_tpu.apps.linear.config import parse_conf

    text = svm_conf.read_text().replace(
        "algo: FTRL", 'algo: FTRL\n  ftrl_state_dtype: "bfloat16"'
    )
    # the injection must have taken effect (a fixture wording change
    # would otherwise silently turn this into a duplicate f32 test)
    assert parse_conf(text).async_sgd.ftrl_state_dtype == "bfloat16"
    svm_conf.write_text(text)
    rc = main([str(svm_conf)])
    assert rc == 0
    assert "examples" in capsys.readouterr().out

    with pytest.raises(ValueError, match="ftrl_state_dtype"):
        parse_conf(text.replace('"bfloat16"', '"bf16"'))


def test_linear_cli_profile_trace(mesh8, svm_conf, tmp_path, capsys):
    prof = tmp_path / "trace"
    rc = main([str(svm_conf), "--profile", str(prof)])
    assert rc == 0
    assert [p for p in prof.rglob("*") if p.is_file()], (
        "no trace artifacts written"
    )


def test_checkpoint_async_mixin(tmp_path):
    """Checkpointable.checkpoint_async snapshots before returning and
    the write lands durably after wait()."""
    from parameter_server_tpu.parameter.replica import (
        CheckpointManager,
        Checkpointable,
    )

    class Toy(Checkpointable):
        def __init__(self):
            self.w = np.arange(6.0)

        def state_host(self):
            return {"w": self.w}

        def load_state_host(self, snap):
            self.w = np.asarray(snap["w"])

    t = Toy()
    mgr = CheckpointManager(str(tmp_path / "ck"), use_orbax=False)
    t.checkpoint_async(mgr, step=2)
    t.w += 50.0  # mutate immediately: the saved snapshot must be owned
    mgr.wait()
    t2 = Toy()
    assert t2.restore(mgr) == 2
    np.testing.assert_array_equal(t2.w, np.arange(6.0))
