"""Cluster metrics plane (PR 10): node-labeled aggregation over the
message plane, the HTTP exposition endpoint, and live SLO alerting.

The contracts pinned here are the ones doc/OBSERVABILITY.md "Cluster
metrics plane" sells:

- typed merges are EXACT (counters sum, gauges stay per-node,
  histograms merge bucket-wise — unit-verified against hand-merged
  fixtures), under a ``node`` label whose values survive Prometheus
  text-format escaping even for hostile hostnames;
- per-node metric reports ride the real Van transfer path (serialized
  frames, restricted unpickler, byte accounting, fault points) on a
  timer, with the direct-call path kept for single-process tests;
- a heartbeat-silenced node shows up STALE in /metrics and flips
  /healthz non-200 within the configured window, then recovers cleanly
  when reports resume (the PR 9 ``heartbeat.report`` fault point);
- serve overload past the SLO rule walks ``ps_alert_state`` through
  pending→firing→resolved, with the firing event visible in
  ``Dashboard.report()`` and ``/debug/snapshot``;
- the endpoint starts on an ephemeral port, scrapes during a LIVE
  linear-app run, and joins its server thread without leaks (tier-1);
- every ps_* name the endpoint serves exists in the instruments.py
  canonical catalog (the metrics-lint orphan sweep, plus a live-scrape
  assertion here).
"""

import json
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from parameter_server_tpu.system import faults
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.telemetry import alerts as alerts_mod
from parameter_server_tpu.telemetry.aggregate import (
    CLUSTER_NODE,
    ClusterAggregator,
)
from parameter_server_tpu.telemetry.alerts import AlertManager, AlertRule
from parameter_server_tpu.telemetry.exposition import (
    ExpositionServer,
    close_cluster,
    expose_cluster,
    serve_registry,
)
from parameter_server_tpu.telemetry.registry import MetricsRegistry


@pytest.fixture(autouse=True)
def hermetic():
    Postoffice.reset()
    faults.reset()
    before = set(threading.enumerate())
    yield
    faults.reset()
    Postoffice.reset()
    # no test here may leak a thread (exposition servers, aux loops,
    # alert evaluators all join on close)
    deadline = time.time() + 5
    while time.time() < deadline:
        leaked = [
            t for t in set(threading.enumerate()) - before if t.is_alive()
        ]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, f"leaked threads: {leaked}"


def _get(url, timeout=10):
    return urllib.request.urlopen(url, timeout=timeout)


# ---------------------------------------------------------------------------
# registry export_state: the serializable unit of the message plane
# ---------------------------------------------------------------------------


class TestExportState:
    def test_counter_gauge_series(self):
        reg = MetricsRegistry()
        c = reg.counter("ps_x_total", "help x", labelnames=("k",))
        c.labels(k="a").inc(2)
        c.labels(k="b").inc(3)
        reg.gauge("ps_g", "gauge").set(7)
        ex = reg.export_state()
        assert ex["ps_x_total"]["type"] == "counter"
        assert ex["ps_x_total"]["series"] == [
            {"labels": {"k": "a"}, "value": 2.0},
            {"labels": {"k": "b"}, "value": 3.0},
        ]
        assert ex["ps_g"]["series"] == [{"labels": {}, "value": 7.0}]

    def test_histogram_keeps_raw_buckets(self):
        reg = MetricsRegistry()
        h = reg.histogram("ps_h_seconds", "h", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        ex = reg.export_state()["ps_h_seconds"]
        (s,) = ex["series"]
        assert ex["buckets"] == [0.1, 1.0, 10.0]
        assert s["buckets"] == [1, 1, 1]  # 50.0 lives above the last bound
        assert s["count"] == 4 and s["min"] == 0.05 and s["max"] == 50.0

    def test_json_roundtrip(self):
        reg = MetricsRegistry()
        reg.counter("ps_a_total").inc()
        reg.histogram("ps_b_seconds").observe(0.1)
        ex = reg.export_state()
        assert json.loads(json.dumps(ex)) == ex


# ---------------------------------------------------------------------------
# typed merge semantics, verified against hand-merged fixtures
# ---------------------------------------------------------------------------


def _node_export(counter=0.0, gauge=None, hist=(), buckets=(0.1, 1.0)):
    reg = MetricsRegistry()
    if counter:
        reg.counter("ps_c_total", "c", labelnames=("k",)).labels(
            k="a"
        ).inc(counter)
    if gauge is not None:
        reg.gauge("ps_g", "g").set(gauge)
    h = reg.histogram("ps_h_seconds", "h", buckets=buckets)
    for v in hist:
        h.observe(v)
    return reg.export_state()


class TestClusterMerge:
    def test_counters_sum_per_label_set(self):
        agg = ClusterAggregator()
        agg.update("W0", _node_export(counter=2.0))
        agg.update("W1", _node_export(counter=5.0))
        m = agg.merged()["ps_c_total"]
        assert m["labelnames"] == ["node", "k"]
        by_node = {s["labels"]["node"]: s["value"] for s in m["series"]}
        # hand-merged: per-node series kept, cluster rollup = 2 + 5
        assert by_node == {"W0": 2.0, "W1": 5.0, CLUSTER_NODE: 7.0}

    def test_gauges_stay_per_node(self):
        agg = ClusterAggregator()
        agg.update("W0", _node_export(gauge=1.0))
        agg.update("W1", _node_export(gauge=9.0))
        m = agg.merged()["ps_g"]
        nodes = [s["labels"]["node"] for s in m["series"]]
        assert CLUSTER_NODE not in nodes  # a summed gauge means nothing
        assert sorted(nodes) == ["W0", "W1"]

    def test_histograms_merge_bucket_wise(self):
        # hand-merged fixture: W0 observes {0.05, 0.5}, W1 {0.05, 5.0}
        #   bucket counts (bounds 0.1, 1.0): W0=[1,1], W1=[1,0]
        #   cluster = [2,1]; count 4; sum 5.6; min 0.05; max 5.0
        agg = ClusterAggregator()
        agg.update("W0", _node_export(hist=(0.05, 0.5)))
        agg.update("W1", _node_export(hist=(0.05, 5.0)))
        m = agg.merged()["ps_h_seconds"]
        cl = next(
            s for s in m["series"] if s["labels"]["node"] == CLUSTER_NODE
        )
        assert cl["buckets"] == [2, 1]
        assert cl["count"] == 4
        assert cl["sum"] == pytest.approx(5.6)
        assert cl["min"] == 0.05 and cl["max"] == 5.0

    def test_bucket_conflict_counted_not_mismerged(self):
        agg = ClusterAggregator()
        agg.update("W0", _node_export(hist=(0.5,)))
        agg.update("W1", _node_export(hist=(0.5,), buckets=(0.2, 2.0)))
        m = agg.merged()["ps_h_seconds"]
        nodes = [s["labels"]["node"] for s in m["series"]]
        assert "W1" not in nodes  # conflicting layout never merges
        assert agg.conflicts >= 1

    def test_cluster_node_id_reserved(self):
        agg = ClusterAggregator()
        with pytest.raises(ValueError):
            agg.update(CLUSTER_NODE, _node_export(counter=1.0))

    def test_staleness_marking_and_forget(self):
        t = [0.0]
        agg = ClusterAggregator(stale_after_s=1.0, clock=lambda: t[0])
        agg.update("W0", _node_export(counter=1.0))
        t[0] = 0.5
        agg.update("W1", _node_export(counter=1.0))
        t[0] = 1.8  # W0 age 1.8 > 1.0; W1 age 1.3 > 1.0? yes both...
        assert agg.stale_nodes() == ["W0", "W1"]
        t[0] = 1.2  # W0 stale (1.2), W1 fresh (0.7)
        assert agg.stale_nodes() == ["W0"]
        txt = agg.render_text()
        assert 'ps_cluster_node_up{node="W0"} 0' in txt
        assert 'ps_cluster_node_up{node="W1"} 1' in txt
        # the stale node's series still render — marked, not hidden
        assert 'ps_c_total{node="W0",k="a"}' in txt
        agg.forget("W0")
        assert agg.stale_nodes() == []
        assert "W0" not in agg.render_text()


# ---------------------------------------------------------------------------
# Prometheus text-format escaping compliance (hostile label values)
# ---------------------------------------------------------------------------

_SERIES_RE = re.compile(
    r'^(?P<name>[a-z_][a-z0-9_]*)'
    r'(\{(?P<labels>(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*",?)*)\})?'
    r' (?P<value>\S+)$'
)


def _unescape(v: str) -> str:
    return (
        v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )


def _parse_label_values(labels: str) -> list:
    # values are quoted; inside them only \\, \" and \n escapes exist
    return [
        _unescape(m) for m in re.findall(r'="((?:[^"\\\n]|\\["\\n])*)"', labels)
    ]


HOSTILE = 'node-7.cluster "eu-west"\nslash\\end'


class TestEscapingCompliance:
    def test_registry_renderer_escapes_hostile_label_values(self):
        reg = MetricsRegistry()
        c = reg.counter("ps_e_total", "e", labelnames=("host",))
        c.labels(host=HOSTILE).inc()
        lines = [
            l for l in reg.render_text().splitlines()
            if l and not l.startswith("#")
        ]
        assert len(lines) == 1  # raw newline would have split the line
        m = _SERIES_RE.match(lines[0])
        assert m, lines[0]
        assert _parse_label_values(m.group("labels")) == [HOSTILE]

    def test_aggregator_renderer_escapes_hostile_node_names(self):
        agg = ClusterAggregator()
        agg.update(HOSTILE, _node_export(counter=1.0, hist=(0.5,)))
        for line in agg.render_text().splitlines():
            if not line or line.startswith("#"):
                continue
            m = _SERIES_RE.match(line)
            assert m, f"unparseable exposition line: {line!r}"
            for v in _parse_label_values(m.group("labels") or ""):
                assert "\n" not in v or v == HOSTILE
        # the hostile node round-trips exactly through escape/unescape
        up = [
            l for l in agg.render_text().splitlines()
            if l.startswith("ps_cluster_node_up")
        ]
        (vals,) = [
            _parse_label_values(_SERIES_RE.match(l).group("labels"))
            for l in up
        ]
        assert vals == [HOSTILE]

    def test_help_text_escaping(self):
        reg = MetricsRegistry()
        reg.counter("ps_e_total", "line one\nline \\two").inc()
        (help_line,) = [
            l for l in reg.render_text().splitlines()
            if l.startswith("# HELP")
        ]
        assert "\n" not in help_line
        assert help_line == "# HELP ps_e_total line one\\nline \\\\two"


# ---------------------------------------------------------------------------
# the message plane: reports ride real Van transfers
# ---------------------------------------------------------------------------


class TestMessagePlane:
    def test_report_rides_the_van_wire(self, mesh8):
        po = Postoffice.instance().start(num_data=4, num_server=2)
        aux = po.start_aux()
        aux.register("W0")
        sent0 = po.van.wire_sent_bytes
        assert aux.report_node("W0") is True
        assert po.van.wire_sent_bytes > sent0, (
            "metric report must cross the serialized wire path"
        )
        ages = aux.cluster.node_ages()
        assert "W0" in ages
        # the merged view carries the node's ps_node_* family
        txt = aux.cluster.render_text()
        assert 'ps_node_heartbeats_total{node="W0"}' in txt
        po.stop()

    def test_direct_path_without_van(self):
        # single-process test path: no Postoffice.start, wire falls back
        from parameter_server_tpu.system.aux_runtime import AuxRuntime

        aux = AuxRuntime()
        aux.register("W0")
        assert aux.report_node("W0", wire=False)
        assert "W0" in aux.cluster.node_ages()

    def test_report_all_includes_process_registry(self, mesh8):
        po = Postoffice.instance().start(num_data=4, num_server=2)
        po.metrics.counter("probe_total", "probe").inc(3)
        aux = po.start_aux()
        aux.register("W0")
        aux.report_all()
        merged = aux.cluster.merged()
        # the process registry reports under the process node id (H0)
        assert aux.node_id == "H0"
        probe = merged["probe_total"]["series"]
        assert {"labels": {"node": "H0"}, "value": 3.0} in probe
        po.stop()

    def test_dropped_frame_loses_report_not_process(self, mesh8):
        po = Postoffice.instance().start(num_data=4, num_server=2)
        aux = po.start_aux()
        aux.register("W0")
        faults.arm("van.transfer", kind="drop")
        assert aux.report_node("W0") is False  # lost, not raised
        faults.reset()
        assert aux.report_node("W0") is True
        po.stop()

    def test_monitor_progress_over_messages(self, mesh8):
        from parameter_server_tpu.system.monitor import (
            MonitorMaster,
            MonitorSlaver,
        )

        po = Postoffice.instance().start(num_data=4, num_server=2)
        master = MonitorMaster()
        master.set_data_merger(lambda src, dst: dst.extend(src))
        s = MonitorSlaver.over_van(master, "W0", po.van)
        sent0 = po.van.wire_sent_bytes
        s.report([1, 2])
        s.report([3])
        assert master.progress() == {"W0": [1, 2, 3]}
        assert po.van.wire_sent_bytes > sent0
        po.stop()

    def test_monitor_periodic_timer(self):
        from parameter_server_tpu.system.monitor import (
            MonitorMaster,
            MonitorSlaver,
        )

        master = MonitorMaster()
        s = MonitorSlaver(master, "W0")
        n = [0]

        def progress():
            n[0] += 1
            return n[0]

        s.start_periodic(progress, interval=0.02)
        deadline = time.time() + 5
        while not master.progress() and time.time() < deadline:
            time.sleep(0.01)
        s.stop()
        assert master.progress().get("W0", 0) >= 1


class TestMonitorPrintRace:
    def test_concurrent_reports_print_once_per_window(self):
        """Regression (PR 10 satellite): _last_print was read and
        written OUTSIDE _lock, so N reporter threads racing the
        interval check could all pass it and print the same window N
        times. With check-and-claim atomic, exactly one print happens
        per interval no matter how many reporters collide."""
        from parameter_server_tpu.system.monitor import MonitorMaster

        master = MonitorMaster()
        prints = []
        master.set_printer(lambda t, snap: prints.append(t), interval=60.0)
        n_threads = 16
        barrier = threading.Barrier(n_threads)

        def hammer(i):
            barrier.wait()
            for j in range(50):
                master.report(f"W{i}", j)

        threads = [
            threading.Thread(target=hammer, args=(i,))
            for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(prints) == 1, (
            f"{len(prints)} prints for one 60s window — the "
            "check-and-claim is not atomic"
        )


# ---------------------------------------------------------------------------
# staleness: a heartbeat-silenced node (PR 9 faults point) goes stale,
# /healthz flips non-200, and recovery is clean when reports resume
# ---------------------------------------------------------------------------


class TestStaleness:
    def test_silenced_node_stale_then_recovers(self, mesh8):
        po = Postoffice.instance().start(num_data=4, num_server=2)
        srv = expose_cluster(
            po, port=0, metrics_interval=0.05, check_interval=0.05,
            stale_after_s=0.4, heartbeat_timeout=0.5,
        )
        try:
            ok, _ = srv.aux.health()
            assert ok
            faults.arm("heartbeat.report", kind="silence", match="S0")
            deadline = time.time() + 10
            stale = False
            while time.time() < deadline and not stale:
                time.sleep(0.1)
                try:
                    _get(f"{srv.url}/healthz")
                except urllib.error.HTTPError as e:
                    assert e.code == 503
                    detail = json.load(e)
                    stale = "S0" in detail["stale_nodes"]
            assert stale, "healthz never flipped 503 with S0 stale"
            txt = _get(f"{srv.url}/metrics").read().decode()
            assert 'ps_cluster_node_up{node="S0"} 0' in txt
            # other nodes stay up — one silenced shard, not an outage
            assert 'ps_cluster_node_up{node="W0"} 1' in txt

            faults.reset()
            deadline = time.time() + 10
            status = None
            while time.time() < deadline and status != 200:
                time.sleep(0.1)
                try:
                    status = _get(f"{srv.url}/healthz").status
                except urllib.error.HTTPError as e:
                    status = e.code
            assert status == 200, "healthz never recovered after resume"
            txt = _get(f"{srv.url}/metrics").read().decode()
            assert 'ps_cluster_node_up{node="S0"} 1' in txt
        finally:
            close_cluster(srv)
            po.stop()


# ---------------------------------------------------------------------------
# alerting: serve overload past the SLO rule → pending→firing→resolved
# ---------------------------------------------------------------------------


class TestAlertRules:
    def test_rule_validation(self):
        with pytest.raises(ValueError):
            AlertRule(name="x", kind="nope", metric="m", threshold=1)
        with pytest.raises(ValueError):
            AlertRule(name="x", kind="ratio", metric="m", threshold=1)
        with pytest.raises(ValueError):
            AlertRule(name="x", kind="burn_rate", metric="m", den=["d"],
                      threshold=1)  # budget missing
        with pytest.raises(ValueError):
            AlertRule(name="x", kind="gauge", metric="m", threshold=1,
                      op="!=")

    def test_default_rule_file_loads(self):
        rules = alerts_mod.default_rules()
        names = {r.name for r in rules}
        assert {"serve_p99_slo", "serve_degraded_rate", "serve_shed_burn",
                "serve_queue_depth", "recovery_mttr"} <= names
        # every referenced metric exists in the canonical catalog
        from parameter_server_tpu.telemetry.instruments import install_all

        catalog = set(install_all(MetricsRegistry()))
        for r in rules:
            assert r.metric in catalog, r.metric
            for d in r.den:
                assert d in catalog, d

    def test_unknown_rule_field_rejected(self, tmp_path):
        p = tmp_path / "rules.json"
        p.write_text(json.dumps({
            "version": 1,
            "rules": [{"name": "x", "kind": "gauge", "metric": "m",
                       "threshold": 1, "thresold_typo": 2}],
        }))
        with pytest.raises(ValueError, match="unknown fields"):
            alerts_mod.load_rules(str(p))

    def test_counter_rate_and_reset_handling(self):
        reg = MetricsRegistry()
        c = reg.counter("ps_r_total", "r")
        t = [0.0]
        m = AlertManager(
            [AlertRule(name="r", kind="counter_rate", metric="ps_r_total",
                       threshold=5.0, window_s=10)],
            registry=reg, clock=lambda: t[0],
        )
        m.evaluate()
        t[0] = 1.0
        c.inc(20)
        m.evaluate()
        assert m.states()["r"].value == pytest.approx(20.0)


class TestServeOverloadAlert:
    def test_slo_breach_pending_firing_resolved(self, mesh8):
        """Drive real serve traffic past the p99 SLO rule and watch the
        full state walk, with the firing event in Dashboard.report()
        and /debug/snapshot (acceptance criterion)."""
        from parameter_server_tpu.serving import (
            PullRequest,
            ServeConfig,
            ServeFrontend,
        )
        from parameter_server_tpu.parameter.kv_vector import KVVector

        po = Postoffice.instance().start(num_data=4, num_server=2)
        kv = KVVector(mesh=po.mesh, k=1, num_slots=1 << 10, hashed=True,
                      name="alert_store")
        rng = np.random.default_rng(0)
        keys = np.unique(rng.integers(0, 1 << 16, 256))
        kv.wait(kv.push(kv.request(channel=0), keys=keys,
                        values=np.ones((len(keys), 1), np.float32)))
        fe = ServeFrontend(
            kv, ServeConfig(max_queue_depth=256, workers=1, replica="off"),
        ).start()

        t = [0.0]
        rule = AlertRule(
            name="serve_p99_slo", kind="quantile",
            metric="ps_serve_latency_seconds", q=0.99,
            threshold=1e-7,  # any real CPU-store latency breaches it
            window_s=10.0, for_s=1.0, resolve_hold_s=5.0,
        )
        mgr = AlertManager([rule], clock=lambda: t[0])
        aux = po.start_aux()
        aux.set_alerts(mgr)

        srv = expose_cluster(po, port=0, alerts=mgr, metrics_interval=0.2)
        try:
            mgr.evaluate()  # t=0 baseline, no traffic: inactive
            assert mgr.states()["serve_p99_slo"].state_name == "inactive"

            # overload: a burst of real pulls, all slower than 100ns
            tickets = [fe.submit(PullRequest(keys=keys[:32]))
                       for _ in range(20)]
            for tk in tickets:
                tk.result(30)
            t[0] = 1.0
            evs = mgr.evaluate()
            assert mgr.states()["serve_p99_slo"].state_name == "pending"
            t[0] = 2.5  # for_s=1 elapsed, condition still true in window
            evs += mgr.evaluate()
            assert mgr.states()["serve_p99_slo"].state_name == "firing"
            assert any(e.to == "firing" for e in evs)

            # the firing event is visible to humans: dashboard + debug
            report = aux.dashboard.report()
            assert "alert serve_p99_slo: pending->firing" in report
            assert "serve_p99_slo firing" in report
            snap = json.load(_get(f"{srv.url}/debug/snapshot"))
            assert snap["alerts"]["states"]["serve_p99_slo"]["state_name"] \
                == "firing"
            assert any(
                e["to"] == "firing"
                for e in snap["alerts"]["recent_events"]
            )
            # and as a scraped series: ps_alert_state == 2
            txt = _get(f"{srv.url}/metrics").read().decode()
            assert re.search(
                r'ps_alert_state\{.*rule="serve_p99_slo".*\} 2', txt
            ), txt.split("ps_alert_state", 1)[-1][:200]

            # traffic stops → window drains → resolved → inactive
            t[0] = 13.0
            mgr.evaluate()
            assert mgr.states()["serve_p99_slo"].state_name == "resolved"
            t[0] = 19.0
            mgr.evaluate()
            assert mgr.states()["serve_p99_slo"].state_name == "inactive"
        finally:
            fe.close()
            close_cluster(srv)
            kv.executor.stop()
            po.stop()


# ---------------------------------------------------------------------------
# exposition endpoint mechanics
# ---------------------------------------------------------------------------


class TestExpositionServer:
    def test_ephemeral_port_and_routes(self):
        reg = MetricsRegistry()
        reg.counter("ps_t_total", "t").inc(4)
        srv = serve_registry(reg)
        try:
            assert srv.port > 0
            resp = _get(f"{srv.url}/metrics")
            assert resp.headers["Content-Type"].startswith("text/plain")
            assert "ps_t_total 4" in resp.read().decode()
            assert _get(f"{srv.url}/healthz").status == 200
            assert "metrics" in json.load(_get(f"{srv.url}/debug/snapshot"))
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{srv.url}/nope")
            assert ei.value.code == 404
        finally:
            srv.close()

    def test_broken_renderer_answers_500(self):
        def boom():
            raise RuntimeError("render broke")

        srv = ExpositionServer(boom).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{srv.url}/metrics")
            assert ei.value.code == 500
        finally:
            srv.close()

    def test_close_is_idempotent(self):
        srv = serve_registry(MetricsRegistry())
        srv.close()
        srv.close()


# ---------------------------------------------------------------------------
# tier-1 smoke: scrape a LIVE linear-app run, join without leaks
# ---------------------------------------------------------------------------


def test_live_linear_run_scrape_smoke(mesh8):
    """The satellite acceptance: endpoint on an ephemeral port, scraped
    during a live linear-app training run — node-labeled series from
    >= 2 nodes, every served ps_* family in the canonical catalog,
    healthz 200, clean thread join (the autouse fixture asserts no
    leaks)."""
    from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker
    from parameter_server_tpu.apps.linear.config import (
        Config,
        LearningRateConfig,
        PenaltyConfig,
        SGDConfig,
    )
    from parameter_server_tpu.telemetry.instruments import install_all
    from parameter_server_tpu.utils.sparse import random_sparse

    po = Postoffice.instance().start(num_data=4, num_server=2)
    srv = expose_cluster(po, port=0, metrics_interval=0.1,
                         check_interval=0.05)
    # scrape-time refresh normally floors at scrape_refresh_min_s (a
    # tight scrape loop must not re-drive the message plane per GET);
    # this test asserts on state from the training that JUST finished,
    # so force every scrape fresh instead of racing the timer sweep
    srv.aux.scrape_refresh_min_s = 0.0

    conf = Config()
    conf.penalty = PenaltyConfig(type="l1", lambda_=[0.01])
    conf.learning_rate = LearningRateConfig(type="decay", alpha=0.5, beta=1.0)
    conf.async_sgd = SGDConfig(
        algo="ftrl", minibatch=256, num_slots=512, max_delay=1
    )
    worker = AsyncSGDWorker(conf, mesh=po.mesh, name="scrape_worker")
    rng = np.random.default_rng(0)
    w_true = (rng.normal(size=512) * (rng.random(512) < 0.2)).astype(
        np.float32
    )
    try:
        worker.train(
            random_sparse(256, 512, 8, seed=i, w_true=w_true)
            for i in range(4)
        )
        txt = _get(f"{srv.url}/metrics").read().decode()
        nodes = {
            line.split('node="', 1)[1].split('"', 1)[0]
            for line in txt.splitlines()
            if line.startswith("ps_cluster_node_up{")
        }
        assert len(nodes) >= 2, nodes
        # the process registry's training series ride under H0
        assert 'executor_steps_finished_total{node="H0"' in txt
        # cluster rollup of a counter family exists
        assert f'node="cluster"' in txt
        # every ps_* family served is in the canonical catalog
        catalog = set(install_all(MetricsRegistry()))
        served = {
            re.match(r"([a-z0-9_]+)", line).group(1)
            for line in txt.splitlines()
            if line.startswith("ps_")
        }
        base = {
            re.sub(r"_(bucket|sum|count)$", "", name) for name in served
        }
        orphans = {
            n for n in served | base
            if n.startswith("ps_") and n not in catalog
            and re.sub(r"_(bucket|sum|count)$", "", n) not in catalog
        }
        assert not orphans, f"served ps_* outside the catalog: {orphans}"
        ok = _get(f"{srv.url}/healthz")
        assert ok.status == 200
        snap = json.load(_get(f"{srv.url}/debug/snapshot"))
        assert snap["health"]["ok"] is True
        assert "cluster" in snap and "alerts" in snap
    finally:
        worker.executor.stop()
        close_cluster(srv)
        po.stop()


# ---------------------------------------------------------------------------
# metrics-lint orphan sweep (CI satellite)
# ---------------------------------------------------------------------------


def _load_metrics_lint():
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "script", "metrics_lint.py",
    )
    spec = importlib.util.spec_from_file_location("_metrics_lint_cm", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestOrphanLint:
    def test_orphan_registration_flagged(self, tmp_path):
        lint = _load_metrics_lint()
        pkg = tmp_path / "parameter_server_tpu" / "rogue"
        pkg.mkdir(parents=True)
        (pkg / "mod.py").write_text(
            "def f(reg):\n"
            "    reg.ensure_counter('ps_bogus_total', 'rogue series')\n"
            "    reg.ensure_counter('app_fine_total')  # non-ps_: ignored\n"
        )
        problems = lint.orphan_problems(str(tmp_path), {"ps_ok_total"})
        assert len(problems) == 1
        assert "ps_bogus_total" in problems[0]
        assert "mod.py:2" in problems[0]

    def test_catalog_names_pass(self, tmp_path):
        lint = _load_metrics_lint()
        pkg = tmp_path / "parameter_server_tpu"
        pkg.mkdir()
        (pkg / "mod.py").write_text(
            "def f(reg):\n"
            "    reg.ensure_counter('ps_ok_total')\n"
        )
        assert lint.orphan_problems(str(tmp_path), {"ps_ok_total"}) == []

    def test_repo_is_orphan_clean(self):
        # the full lint (incl. the sweep over the real tree) is green —
        # also exercised by make metrics-lint / pslint
        assert _load_metrics_lint().lint() == []
