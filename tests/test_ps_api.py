"""ps.h-façade tests: hello-world app parity (ref src/test/hello_ps.cc) and
the node-identity helpers from src/ps.h."""

from __future__ import annotations

import threading

import pytest

import parameter_server_tpu as pst
from parameter_server_tpu import ps
from parameter_server_tpu.system.message import Task
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.utils.range import Range


@pytest.fixture(autouse=True)
def _fresh_system():
    Postoffice.reset()
    yield
    ps.stop_system()


def test_hello_world_roundtrip():
    """Port of hello_ps.cc: workers Submit two tasks to the server group,
    Wait on each, then a third with a completion callback reading
    last_response."""
    log = []
    log_lock = threading.Lock()

    def record(line):
        with log_lock:
            log.append(line)

    class Server(ps.App):
        def process_request(self, req):
            record((ps.my_node_id(), "req", req.task.time, req.sender))

    class Worker(ps.App):
        def process_response(self, res):
            record((ps.my_node_id(), "res", res.task.time, res.sender))

        def run(self):
            ts = ps.submit(self, Task(), ps.NodeGroups.SERVER_GROUP)
            self.wait(ts)
            ts = ps.submit(self, Task(), ps.NodeGroups.SERVER_GROUP)
            self.wait(ts)

            done = threading.Event()

            def on_done():
                assert self.last_response() is not None
                record((ps.my_node_id(), "cb", self.last_response().task.time))
                done.set()

            self.wait(ps.submit(self, Task(), callback=on_done))
            assert done.is_set()

    def create_app():
        if ps.is_worker():
            return Worker()
        if ps.is_server():
            return Server()
        return ps.App()

    apps = ps.run_system(create_app, num_workers=2, num_servers=2)
    assert len(apps) == 5  # H0 + 2 servers + 2 workers

    reqs = [e for e in log if e[1] == "req"]
    ress = [e for e in log if e[1] == "res"]
    cbs = [e for e in log if e[1] == "cb"]
    # each of 2 workers sent 3 requests, each fanned out to 2 servers
    assert len(reqs) == 2 * 3 * 2
    assert len(ress) == 2 * 3 * 2
    assert len(cbs) == 2
    assert {e[0] for e in reqs} == {"S0", "S1"}
    assert {e[0] for e in ress} == {"W0", "W1"}
    # every request AND response crossed the Van's wire path (ref van.cc
    # process-level send/recv counters). Cross-check the van totals
    # against the per-peer RemoteNode counters — a path that bypassed
    # the van (or dropped the response direction) breaks these.
    van = apps[0].po.van
    rn_sent = sum(
        rn.wire_sent_bytes for a in apps for rn in a.remote_nodes.nodes()
    )
    rn_recv = sum(
        rn.wire_recv_bytes for a in apps for rn in a.remote_nodes.nodes()
    )
    assert van.wire_sent_bytes == rn_sent > 0
    assert van.wire_recv_bytes == rn_recv > 0
    # responses really crossed: each WORKER decoded frames from servers
    for w in (a for a in apps if a.node.id.startswith("W")):
        assert any(rn.wire_recv_bytes > 0 for rn in w.remote_nodes.nodes())


def test_node_identity_helpers():
    seen = {}

    class Probe(ps.App):
        def __init__(self):
            super().__init__()
            seen[ps.my_node_id()] = (
                ps.is_scheduler(),
                ps.is_server(),
                ps.is_worker(),
                ps.my_rank(),
                ps.rank_size(),
                ps.my_key_range(),
            )

    ps.run_system(Probe, num_workers=3, num_servers=2, key_space=Range(0, 100))

    assert seen["H0"][:3] == (True, False, False)
    assert seen["S0"][:3] == (False, True, False)
    assert seen["W2"][:3] == (False, False, True)
    assert seen["W1"][3:5] == (1, 3)
    assert seen["S1"][3:5] == (1, 2)
    # server key ranges evenly divide the key space (ref Range::EvenDivide)
    assert seen["S0"][5] == Range(0, 50)
    assert seen["S1"][5] == Range(50, 100)
    # workers span the whole key space
    assert seen["W0"][5] == Range.all()


def test_ready_barriers_and_scheduler_id():
    ps.start_system(num_workers=1, num_servers=1)
    ps.wait_servers_ready()
    ps.wait_workers_ready()
    assert ps.scheduler_id() == "H0"
    assert ps.next_customer_id() >= 1
    ps.stop_system()
    with pytest.raises(RuntimeError):
        ps.wait_servers_ready()


def test_package_exports():
    assert pst.__version__
    assert pst.KVVector is not None and pst.KVMap is not None
    assert pst.ps.App is ps.App


def test_worker_exception_propagates():
    """A crashed worker run() must fail run_system, not vanish (ref: the
    worker process's exit code propagates through local.sh)."""

    class Crasher(ps.App):
        def run(self):
            if ps.is_worker():
                raise RuntimeError("worker died")

    with pytest.raises(RuntimeError, match="worker died"):
        ps.run_system(Crasher, num_workers=2, num_servers=1)


def test_group_broadcast_delivers_to_self():
    """Groups include the sender's own node when its role matches (ref
    executor.cc AddNode: every node joins kLiveGroup + its role group)."""
    got = []

    class Echo(ps.App):
        def process_request(self, msg):
            got.append((msg.sender, ps.my_node_id()))

        def run(self):
            if ps.my_node_id() == "W0":
                self.wait(ps.submit(self, Task(), ps.NodeGroups.LIVE_GROUP))

    ps.run_system(Echo, num_workers=2, num_servers=1)
    receivers = {r for s, r in got if s == "W0"}
    assert "W0" in receivers  # self-delivery via loopback
    assert receivers == {"H0", "S0", "W0", "W1"}


def test_reentrant_submit_from_process_request():
    """process_request may relay a broadcast to a group containing its own
    node — the receive lock must be re-entrant, not deadlock."""
    relayed = []

    class Relay(ps.App):
        def process_request(self, msg):
            if msg.task.cmd == 1 and ps.is_scheduler():
                ps.submit(self, Task(cmd=2), ps.NodeGroups.LIVE_GROUP)
            elif msg.task.cmd == 2:
                relayed.append(ps.my_node_id())

        def run(self):
            if ps.my_node_id() == "W0":
                self.wait(ps.submit(self, Task(cmd=1), ps.scheduler_id()))

    ps.run_system(Relay, num_workers=1, num_servers=1)
    assert set(relayed) == {"H0", "S0", "W0"}
